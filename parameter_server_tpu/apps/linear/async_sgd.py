"""Asynchronous SGD for linear methods — the flagship pipeline.

Counterpart of ``src/app/linear_method/async_sgd.h``. The reference splits
into scheduler (workload dispatch), workers (minibatch gradient: pull w →
Xw → loss grad → push g) and servers (FTRL/AdaGrad entry updates). Here the
worker+server roles fuse into ONE jitted SPMD step over the (data, server)
mesh — the push/pull messages become the collectives inside it:

    pull:  gather (z, √n) at the batch's unique slots from server shards,
           psum over the *server* axis assembles rows; weights derived
           lazily (FTRL w is a function of state, as in FTRLEntry).
    work:  Xw, per-row loss gradient, X^T g — segment-sums over the
           padded-COO batch (ops/spmv), on-shard, MXU/VPU-friendly.
    push:  scatter per-unique gradients densely into the owned server
           shard, psum over the *data* axis aggregates workers, then the
           updater (FTRL/AdaGrad) applies the touched-masked dense update.

Bounded-delay consistency (SGDConfig.max_delay = τ): gradients are computed
against a weight snapshot refreshed every τ steps while updates land on the
live state — the same staleness the reference's message clocks permit —
and the host executor additionally pipelines up to τ+1 steps in flight.
"""

from __future__ import annotations

import dataclasses
import functools
import os
import time
from typing import Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ...utils import file as psfile

from ...utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ...learner.sgd import ISGDCompNode, ISGDScheduler, SGDProgress
from ...ops.kv_ops import localize, slot_sentinel, valid_slots
from ...ops.wire_codec import decode_u24
from ...parallel import mesh as meshlib
from ...parallel import partition as partlib
from ...parallel.mesh import DATA_AXIS, SERVER_AXIS
from ...system.message import Task
from ...utils import evaluation
from ...utils.bitpack import (
    hash_slots_packed,
    packed_nwords,
    slot_bits,
    unpack_bits,
    unpack_sign_bits,
)
from ...utils.localizer import Localizer
from ...utils.sparse import SparseBatch
from .config import Config, SGDConfig
from .learning_rate import LearningRate
from .loss import create_loss
from .penalty import create_penalty
from .updaters import create_updater


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PreppedBatch:
    """Static-shape localized minibatch, per data shard (leading dim D)."""

    y: np.ndarray  # [D, R]
    mask: np.ndarray  # [D, R]
    rows: np.ndarray  # [D, NZ] int32
    ucols: np.ndarray  # [D, NZ] int32 — index into uslots
    vals: np.ndarray  # [D, NZ] float32
    uslots: np.ndarray  # [D, U] int32 slot ids (sentinel = num_slots)
    umask: np.ndarray  # [D, U] float32

    @property
    def num_examples(self) -> int:
        return int(self.mask.sum())


def prep_batch(
    batch: SparseBatch,
    directory,
    num_shards: int,
    rows_pad: int,
    nnz_pad: int,
    uniq_pad: int,
    num_slots: int,
) -> PreppedBatch:
    """Host-side localize+pad: the MinibatchReader::Read tail (sgd.h:117-135)
    — unique keys, remap to batch-local ids, map keys to table slots."""
    shards = []
    per = -(-batch.n // num_shards)
    for d in range(num_shards):
        sub = batch.slice_rows(min(d * per, batch.n), min((d + 1) * per, batch.n))
        loc = Localizer()
        keys, _ = loc.count_uniq_index(sub)
        local = loc.remap_index(keys)
        if local.nnz > nnz_pad or len(keys) > uniq_pad or local.n > rows_pad:
            raise ValueError(
                f"batch exceeds padding: nnz {local.nnz}>{nnz_pad} or "
                f"uniq {len(keys)}>{uniq_pad} or rows {local.n}>{rows_pad}"
            )
        y = np.zeros(rows_pad, np.float32)
        y[: local.n] = local.y
        mask = np.zeros(rows_pad, np.float32)
        mask[: local.n] = 1.0
        rows = np.zeros(nnz_pad, np.int32)
        ucols = np.zeros(nnz_pad, np.int32)
        vals = np.zeros(nnz_pad, np.float32)
        rows[: local.nnz] = local.row_ids()
        ucols[: local.nnz] = local.indices
        vals[: local.nnz] = local.value_array()
        uslots = np.full(uniq_pad, slot_sentinel(num_slots), np.int32)
        umask = np.zeros(uniq_pad, np.float32)
        uslots[: len(keys)] = directory.slots(keys)
        umask[: len(keys)] = 1.0
        shards.append((y, mask, rows, ucols, vals, uslots, umask))
    stack = [np.stack(x) for x in zip(*shards)]
    return PreppedBatch(*stack)


def prep_batch_shared(
    batch: SparseBatch,
    directory,
    num_shards: int,
    rows_pad: int,
    nnz_pad: int,
    uniq_pad: int,
    num_slots: int,
) -> PreppedBatch:
    """Globally-deduped prep for the sparse-update formulation: ONE
    slot-unique table for the whole minibatch, replicated to every data
    shard (identical ``uslots``/``umask`` rows), so the device step can
    aggregate per-slot gradients with an elementwise data-axis psum and
    scatter state rows back without cross-shard duplicates.

    Dedup happens at SLOT level (after the directory hash), not key
    level: two keys hash-colliding into one slot must have their
    gradients summed before the nonlinear entry update — the same
    aggregation the dense scatter-add performs implicitly. Vectorized
    (unique + searchsorted), no per-shard Localizer sort."""
    keys_all = np.unique(np.asarray(batch.indices))
    slots_of_key = directory.slots(keys_all)
    uniq_slots, key_to_ucol = np.unique(slots_of_key, return_inverse=True)
    u = len(uniq_slots)
    if u > uniq_pad:
        raise ValueError(f"batch exceeds padding: uniq {u}>{uniq_pad}")
    uslots = np.full(uniq_pad, slot_sentinel(num_slots), np.int32)
    uslots[:u] = uniq_slots
    umask = np.zeros(uniq_pad, np.float32)
    umask[:u] = 1.0
    key_to_ucol = key_to_ucol.astype(np.int32)

    shards = []
    per = -(-batch.n // num_shards)
    for d in range(num_shards):
        lo_r = min(d * per, batch.n)
        hi_r = min((d + 1) * per, batch.n)
        lo, hi = batch.indptr[lo_r], batch.indptr[hi_r]
        nsub, nnz = hi_r - lo_r, hi - lo
        if nnz > nnz_pad or nsub > rows_pad:
            raise ValueError(
                f"batch exceeds padding: nnz {nnz}>{nnz_pad} or "
                f"rows {nsub}>{rows_pad}"
            )
        y = np.zeros(rows_pad, np.float32)
        y[:nsub] = batch.y[lo_r:hi_r]
        mask = np.zeros(rows_pad, np.float32)
        mask[:nsub] = 1.0
        counts = np.diff(batch.indptr[lo_r : hi_r + 1])
        rows = np.zeros(nnz_pad, np.int32)
        rows[:nnz] = np.repeat(np.arange(nsub, dtype=np.int32), counts)
        ucols = np.zeros(nnz_pad, np.int32)
        ucols[:nnz] = key_to_ucol[
            np.searchsorted(keys_all, batch.indices[lo:hi])
        ]
        vals = np.zeros(nnz_pad, np.float32)
        vals[:nnz] = batch.values[lo:hi] if not batch.binary else 1.0
        shards.append((y, mask, rows, ucols, vals, uslots, umask))
    stack = [np.stack(x) for x in zip(*shards)]
    return PreppedBatch(*stack)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PreppedSuperBatch:
    """T stacked PreppedBatches — the exact wire's scan superbatch
    (fields [T, D, ...]; one device launch scans T sequential
    ministeps, the ELLBitsSuperBatch twin for the dedup wire)."""

    y: np.ndarray
    mask: np.ndarray
    rows: np.ndarray
    ucols: np.ndarray
    vals: np.ndarray
    uslots: np.ndarray
    umask: np.ndarray

    @property
    def steps(self) -> int:
        return int(self.y.shape[0])

    @property
    def num_examples(self) -> int:
        return int(self.mask.sum())


def stack_prepped_batches(batches: "List[PreppedBatch]") -> PreppedSuperBatch:
    """Stack T localized exact-wire minibatches along a new leading T
    axis for one scan-fused launch."""
    if not batches:
        raise ValueError("empty superbatch")
    return PreppedSuperBatch(
        *(
            np.stack([getattr(b, f.name) for b in batches])
            for f in dataclasses.fields(PreppedBatch)
        )
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class HashedBatch:
    """Fast-path batch for hashed directories: per-entry slot ids, no
    uniquification. Duplicate slots aggregate correctly in the push
    scatter-add, so the host needn't sort/unique at all — the whole prep is
    a vectorized hash + pad, which is what makes the TPU pipeline
    host-bound-free (the reference pays a per-minibatch Localizer sort,
    sgd.h:121-134; we only need that for exact-key directories)."""

    y: np.ndarray  # [D, R]
    mask: np.ndarray  # [D, R]
    rows: np.ndarray  # [D, NZ] int32
    slots: np.ndarray  # [D, NZ] int32 (sentinel = num_slots for padding)
    vals: np.ndarray  # [D, NZ] float32

    @property
    def num_examples(self) -> int:
        return int(self.mask.sum())


def prep_batch_hashed(
    batch: SparseBatch,
    directory,
    num_shards: int,
    rows_pad: int,
    nnz_pad: int,
    num_slots: int,
) -> HashedBatch:
    """Vectorized hash+pad prep (no sort): ~20x cheaper than prep_batch."""
    shards = []
    per = -(-batch.n // num_shards)
    for d in range(num_shards):
        lo_r, hi_r = min(d * per, batch.n), min((d + 1) * per, batch.n)
        lo, hi = batch.indptr[lo_r], batch.indptr[hi_r]
        nsub = hi_r - lo_r
        nnz = hi - lo
        if nnz > nnz_pad or nsub > rows_pad:
            raise ValueError(f"batch exceeds padding: {nnz}>{nnz_pad} or {nsub}>{rows_pad}")
        y = np.zeros(rows_pad, np.float32)
        y[:nsub] = batch.y[lo_r:hi_r]
        mask = np.zeros(rows_pad, np.float32)
        mask[:nsub] = 1.0
        counts = np.diff(batch.indptr[lo_r : hi_r + 1])
        rows = np.zeros(nnz_pad, np.int32)
        rows[:nnz] = np.repeat(np.arange(nsub, dtype=np.int32), counts)
        slots = np.full(nnz_pad, slot_sentinel(num_slots), np.int32)
        slots[:nnz] = directory.slots(batch.indices[lo:hi])
        vals = np.zeros(nnz_pad, np.float32)
        vals[:nnz] = (
            batch.values[lo:hi] if not batch.binary else 1.0
        )
        shards.append((y, mask, rows, slots, vals))
    stack = [np.stack(x) for x in zip(*shards)]
    return HashedBatch(*stack)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ELLBatch:
    """ELL-packed batch: the TPU-native row-block format.

    Each example owns exactly K feature lanes — ``slots[r, k]`` (sentinel
    ``num_slots`` for missing) and optional ``vals`` (None ⇒ binary
    features, the common CTR case; ref sparse_matrix.h ``binary()``).
    Row ids are *implicit* in the layout, Xw is a lane-sum (no scatter),
    and the wire/PCIe payload drops to 4 bytes per feature. This is the
    "HBM-resident row-block" encoding the design targets: dense [R, K]
    tiles that XLA vectorizes directly.
    """

    y: np.ndarray  # [D, R]
    mask: np.ndarray  # [D, R] float32
    slots: np.ndarray  # [D, R, K] int32
    vals: Optional[np.ndarray]  # [D, R, K] float32 or None (binary)

    @property
    def num_examples(self) -> int:
        return int(self.mask.sum())


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ELLPackedBatch:
    """ELLBatch with slot ids packed to 3 bytes on the wire.

    The host→device link (PCIe, or an RPC tunnel in disaggregated setups)
    is the pipeline's scarce resource — the device step is ~100x faster
    than the transfer. Slot ids address ``num_slots`` < 2^24 entries, so
    int32 wastes a byte per feature; we ship little-endian u24 and
    reassemble with three cheap VPU ops inside the jitted step. This is the
    same byte-economy instinct as the reference's fixing_float filter
    (filter/fixing_float.h) applied to the key stream instead of values.
    """

    y: np.ndarray  # [D, R] float32
    mask: np.ndarray  # [D, R] uint8
    slots_u24: np.ndarray  # [D, R, K, 3] uint8, little-endian
    vals: Optional[np.ndarray]  # [D, R, K] float32 or None (binary)

    @property
    def num_examples(self) -> int:
        return int(self.mask.sum())


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ELLBitsBatch:
    """ELLBatch on the minimal wire: ceil(log2 S)-bit slot ids, 1-bit
    labels, row counts instead of a mask.

    Only produced for the CTR hot path (hashed directory, binary features,
    uniform rows): no sentinel is needed, so a 4M-slot table ships 22
    bits/feature — 31% fewer bytes than int32, 8% fewer than u24 — plus
    2KB of label bits per 16K rows instead of 64KB of float32. On a
    transfer-bound single-core host this is a direct throughput win; see
    utils/bitpack.py for the stream layout.
    """

    y_bits: np.ndarray  # [D, ceil(R/8)] uint8 little-endian sign bits
    counts: np.ndarray  # [D] int32 live-row count per data shard
    slots_words: np.ndarray  # [D, W] uint32 bitstream words
    # static row padding (R): y_bits rounds R to bytes, so the true row
    # count must ride along for the consumer's step builder
    rows: int = dataclasses.field(metadata=dict(static=True), default=0)

    @property
    def num_examples(self) -> int:
        return int(self.counts.sum())


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ELLBitsSuperBatch:
    """T minibatches of ELLBits wire stacked on a leading scan axis.

    The device steps through all T minibatches in ONE launch
    (``lax.scan`` inside the jitted step): on a tunneled/remote TPU the
    per-launch round trip costs as much as several device steps, so
    batching launches is the single biggest throughput lever — and it is
    the idiomatic XLA shape for a sequential optimizer loop anyway.
    Within a superbatch the weights advance every ministep (staleness 0);
    the configured ``max_delay`` bound still governs the snapshot taken
    across superbatch submissions, so the delay bound is never exceeded.
    """

    y_bits: np.ndarray  # [T, D, ceil(R/8)] uint8
    counts: np.ndarray  # [T, D] int32
    slots_words: np.ndarray  # [T, D, W] uint32
    rows: int = dataclasses.field(metadata=dict(static=True), default=0)

    @property
    def steps(self) -> int:
        return len(self.counts)

    @property
    def num_examples(self) -> int:
        return int(self.counts.sum())


def stack_bits_batches(parts: List[ELLBitsBatch]) -> ELLBitsSuperBatch:
    """Stack T prepped ELLBitsBatch minibatches into one scan superbatch."""
    rows = parts[0].rows
    assert all(p.rows == rows for p in parts), "superbatch needs uniform rows"
    return ELLBitsSuperBatch(
        y_bits=np.stack([p.y_bits for p in parts]),
        counts=np.stack([p.counts for p in parts]),
        slots_words=np.stack([p.slots_words for p in parts]),
        rows=rows,
    )


def pack_u24(idx: np.ndarray) -> np.ndarray:
    """int32 [..] → uint8 [.., 3] little-endian (values must be < 2^24)."""
    flat = np.ascontiguousarray(idx, dtype="<u4")
    return flat.view(np.uint8).reshape(*idx.shape, 4)[..., :3].copy()


# jit-side inverse of pack_u24 — the canonical implementation lives in
# ops/wire_codec (decode_u24, with the rest of the wire decode ops);
# re-exported under the historical name for the ELLPackedBatch step
unpack_u24 = decode_u24


def prep_batch_ell(
    batch: SparseBatch,
    directory,
    num_shards: int,
    rows_pad: int,
    lanes: int,
    num_slots: int,
    pack: bool = False,
) -> ELLBatch:
    """Pack a CSR batch into ELL lanes.

    A row with more than ``lanes`` features cannot be represented — the
    reference never drops data, so neither do we: raises ValueError with
    the dropped-entry count (``prep`` pre-checks and falls back to the
    hashed COO path instead of calling in)."""
    max_row = int(np.diff(batch.indptr).max()) if batch.n else 0
    if max_row > lanes:
        dropped = int(
            np.maximum(np.diff(batch.indptr) - lanes, 0).sum()
        )
        raise ValueError(
            f"ELL lane budget {lanes} < widest row {max_row}: packing would "
            f"silently drop {dropped} features; raise ell_lanes or use the "
            "hashed COO path"
        )
    shards = []
    per = -(-batch.n // num_shards)
    binary = batch.binary
    for d in range(num_shards):
        lo_r, hi_r = min(d * per, batch.n), min((d + 1) * per, batch.n)
        nsub = hi_r - lo_r
        y = np.zeros(rows_pad, np.float32)
        y[:nsub] = batch.y[lo_r:hi_r]
        mask = np.zeros(rows_pad, np.float32)
        mask[:nsub] = 1.0
        counts = np.diff(batch.indptr[lo_r : hi_r + 1]).astype(np.int64)
        seg = slice(batch.indptr[lo_r], batch.indptr[hi_r])
        slot_ids = directory.slots(batch.indices[seg])
        uniform = bool(nsub) and bool((counts == lanes).all())
        if uniform and nsub == rows_pad:
            # full uniform batch (the CTR hot path): the freshly-hashed ids
            # ARE the ELL array — reshape in place, no fill, no copy
            slots = slot_ids.reshape(nsub, lanes)
            vals = (
                None
                if binary
                else batch.values[seg].astype(np.float32, copy=False).reshape(nsub, lanes)
            )
            shards.append((y, mask, slots, vals))
            continue
        slots = np.full((rows_pad, lanes), slot_sentinel(num_slots), np.int32)
        vals = None if binary else np.zeros((rows_pad, lanes), np.float32)
        if uniform:
            # uniform rows (fixed-width data): ELL packing is a reshape
            slots[:nsub] = slot_ids.reshape(nsub, lanes)
            if not binary:
                vals[:nsub] = batch.values[seg].reshape(nsub, lanes)
        else:
            lane_idx = _lane_positions(counts, lanes)
            keep = lane_idx >= 0
            flat_rows = np.repeat(np.arange(nsub), counts)[keep]
            flat_lanes = lane_idx[keep]
            slots[flat_rows, flat_lanes] = slot_ids[keep]
            if not binary:
                vals[flat_rows, flat_lanes] = batch.values[seg][keep]
        shards.append((y, mask, slots, vals))
    ys, masks, slotss, valss = zip(*shards)
    if num_shards == 1:
        # single data shard: add the leading axis as a view, not a stack copy
        stack = lambda xs: xs[0][None]  # noqa: E731
    else:
        stack = np.stack
    if pack:
        assert num_slots < (1 << 24), "u24 wire format needs num_slots < 2^24"
        out = ELLPackedBatch(
            y=stack(ys),
            mask=stack(masks).astype(np.uint8),
            slots_u24=pack_u24(stack(slotss)),
            vals=None if binary else stack(valss),
        )
    else:
        out = ELLBatch(
            y=stack(ys),
            mask=stack(masks),
            slots=stack(slotss),
            vals=None if binary else stack(valss),
        )
    return out


def prep_batch_ell_bits(
    batch: SparseBatch,
    directory,
    num_shards: int,
    rows_pad: int,
    lanes: int,
    num_slots: int,
) -> Optional[ELLBitsBatch]:
    """Minimal-wire ELL prep: fused hash→slot→bitstream (one C++ pass per
    shard), labels as sign bits, mask as a row count. Applies only to the
    hashed/binary/uniform-row case — returns None otherwise so the caller
    falls back to the u24 format (which carries sentinels and values).
    Returns host arrays; device placement goes through the worker's
    ``upload`` (which handles multi-process assembly)."""
    if not (batch.binary and directory.hashed):
        return None
    counts_all = np.diff(batch.indptr)
    if not (counts_all == lanes).all():
        return None
    # labels travel as sign bits — lossless only for ±1 classification
    # labels (what the parsers emit); regression targets must keep a fat
    # wire or they'd silently collapse to their sign
    if not (np.abs(batch.y) == 1).all():
        return None
    bits = slot_bits(num_slots)
    per = -(-batch.n // num_shards)
    nwords = packed_nwords(rows_pad * lanes, bits)
    y_nbytes = (rows_pad + 7) // 8
    # np.empty, not zeros: the hash→pack pass overwrites every payload
    # byte in place, and bits past each value's own span are masked off by
    # the device unpacker — zeroing 2MB/batch would just burn host cycles.
    # Bits belonging to PADDING rows decode to garbage slots, which is
    # fine: their gradients, touched-flags and metrics are all gated on
    # the row mask inside the step.
    slots_words = np.empty((num_shards, nwords), "<u4")
    y_bits = np.zeros((num_shards, y_nbytes), np.uint8)
    counts = np.zeros((num_shards,), np.int32)
    for d in range(num_shards):
        lo_r, hi_r = min(d * per, batch.n), min((d + 1) * per, batch.n)
        nsub = hi_r - lo_r
        if nsub > rows_pad:
            raise ValueError(f"batch exceeds padding: {nsub}>{rows_pad}")
        seg = slice(batch.indptr[lo_r], batch.indptr[hi_r])
        nbytes = (nsub * lanes * bits + 7) // 8
        hash_slots_packed(
            batch.indices[seg],
            # hash modulus = the directory's CONFIGURED slot count — the
            # same map as every other path (and stable across elastic
            # resizes); bit width / storage sizing stays padded
            directory.num_slots,
            bits,
            out=slots_words[d].view(np.uint8)[:nbytes],
        )
        yb = np.packbits(batch.y[lo_r:hi_r] > 0, bitorder="little")
        y_bits[d, : yb.size] = yb
        counts[d] = nsub
    return ELLBitsBatch(
        y_bits=y_bits, counts=counts, slots_words=slots_words, rows=rows_pad
    )


def prep_batch_ell_stream(
    batch: SparseBatch,
    directory,
    num_shards: int,
    rows_pad: int,
    lanes: int,
    num_slots: int,
    statics,
):
    """Stream-once lane-dictionary wire prep: the fused
    hash→unique→remap→bit-pack pass (one native C ABI call per shard,
    learner/wire.encode_stream_shard; NumPy fallback bit-identical).
    Small-vocabulary lanes ship per-lane uslot tables + packed ucols,
    high-vocabulary lanes keep the raw bit stream — the cache-free
    encoding for single-epoch data, where the UploadCache never hits.

    Applies to the same domain as the bits wire (hashed directory,
    binary features, uniform rows, ±1 labels) AND only while every
    shard fits the pinned ``statics`` — returns None otherwise so the
    caller falls back to the raw bits wire (never wrong bytes, only
    fat ones). STATELESS given ``statics`` (pool-able prep stage)."""
    from ...learner.wire import (
        EncodedEllStreamBatch,
        encode_stream_shard,
        tree_nbytes,
        wire_instruments,
    )

    tel = wire_instruments()

    def fallback(reason: str):
        if tel is not None:
            tel["fallbacks"].labels(reason=reason).inc()
        return None

    if statics is None or not (batch.binary and directory.hashed):
        return fallback("domain")
    if statics.lanes != lanes:
        return fallback("domain")
    counts_all = np.diff(batch.indptr)
    if not (counts_all == lanes).all():
        return fallback("ragged")
    if not (np.abs(batch.y) == 1).all():
        return fallback("labels")
    t0 = time.perf_counter()
    per = -(-batch.n // num_shards)
    n_dict = len(statics.dict_lanes)
    y_nbytes = (rows_pad + 7) // 8
    y_bits = np.zeros((num_shards, y_nbytes), np.uint8)
    counts = np.zeros((num_shards,), np.int32)
    raw_ws, code_ws, table_ws = [], [], []
    lane_starts = np.zeros((num_shards, n_dict), np.int32)
    n_uniq = np.zeros((num_shards,), np.int32)
    for d in range(num_shards):
        lo_r, hi_r = min(d * per, batch.n), min((d + 1) * per, batch.n)
        nsub = hi_r - lo_r
        if nsub > rows_pad:
            raise ValueError(f"batch exceeds padding: {nsub}>{rows_pad}")
        seg = slice(batch.indptr[lo_r], batch.indptr[hi_r])
        got = encode_stream_shard(
            batch.indices[seg], nsub, rows_pad,
            # hash modulus = the directory's CONFIGURED slot count (the
            # same map as every other path, stable across elastic
            # resizes); raw_bits sizing uses the padded table
            directory.num_slots,
            statics,
        )
        if got is None:
            # a shard overflowed the pinned statics (vocabulary drift
            # past the padded code space / table capacity)
            return fallback("statics_overflow")
        raw_w, code_w, table_w, starts, total = got
        raw_ws.append(raw_w)
        code_ws.append(code_w)
        table_ws.append(table_w)
        lane_starts[d] = starts
        n_uniq[d] = total
        yb = np.packbits(batch.y[lo_r:hi_r] > 0, bitorder="little")
        y_bits[d, : yb.size] = yb
        counts[d] = nsub
    out = EncodedEllStreamBatch(
        y_bits=y_bits,
        counts=counts,
        raw_words=np.stack(raw_ws),
        code_words=np.stack(code_ws),
        table_words=np.stack(table_ws),
        lane_starts=lane_starts,
        n_uniq=n_uniq,
        rows=rows_pad,
        lanes=lanes,
        dict_lanes=statics.dict_lanes,
        code_bits=statics.code_bits,
        dict_pad=statics.dict_pad,
        raw_bits=statics.raw_bits,
    )
    if tel is not None:
        enc_b = tree_nbytes(out)
        # the raw alternative these bytes displace: the bits wire at
        # the same shape (what prep_batch_ell_bits would have shipped)
        bits_b = num_shards * (
            packed_nwords(rows_pad * lanes, statics.raw_bits) * 4
            + y_nbytes + 4
        )
        tel["encode_seconds"].observe(time.perf_counter() - t0)
        tel["bytes"].labels(encoding="stream").inc(enc_b)
        tel["saved_bytes"].labels(reason="encoding").inc(
            max(0, bits_b - enc_b)
        )
    return out


def _lane_positions(counts: np.ndarray, lanes: int) -> np.ndarray:
    """Per-entry lane index within its row; -1 when beyond the lane budget."""
    total = int(counts.sum())
    if total == 0:
        return np.zeros(0, np.int64)
    starts = np.zeros(len(counts), np.int64)
    np.cumsum(counts[:-1], out=starts[1:])
    pos = np.arange(total, dtype=np.int64) - np.repeat(starts, counts)
    return np.where(pos < lanes, pos, -1)


def _make_perturb(noise, salt: int):
    """ADD_NOISE wire op: N(mean, std) on nonzero entries, or None when
    disabled. A mean-only filter (std=0, mean!=0) still applies — the
    reference's normal_distribution(mean, 0) degenerates to adding the
    constant. The key folds BOTH mesh coordinates so every shard of every
    worker draws its own iid stream."""
    if noise is None:
        return None
    mean, std = float(noise[0]), float(noise[1])
    if mean == 0.0 and std <= 0.0:
        return None

    def perturb(g, seed):
        key = jax.random.fold_in(jax.random.PRNGKey(salt), seed)
        key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
        key = jax.random.fold_in(key, jax.lax.axis_index(SERVER_AXIS))
        n = mean + std * jax.random.normal(key, g.shape, g.dtype)
        return jnp.where(g != 0, g + n, g)

    return perturb


def make_push_reduce(push_quant: int, noise=None):
    """Cross-worker gradient reduction, optionally through the quantized
    wire: the device-side realization of the reference's FIXING_FLOAT
    push filter (src/filter/fixing_float.h) — each worker stochastically
    rounds its shard gradient to ``push_quant``-byte fixed point with its
    OWN [min, max] scale (the reference's per-message scale, reusing
    filter/fixing_float.quantize_jax) and the decoded values are summed.
    Zero entries are masked back to exactly zero so slots a worker never
    touched contribute nothing — the sparse_filter ∘ fixing_float chain
    of the reference's confs (absent keys get no quantization noise).

    ``noise=(mean, std)`` applies the ADD_NOISE filter device-side:
    N(mean, std) on each worker's own contribution (only where it is
    nonzero — absent keys get no noise), before quantization and
    aggregation, exactly the wire position of src/filter/add_noise.h."""
    perturb = _make_perturb(noise, 0xA015E)

    if not push_quant:
        if perturb is None:
            return lambda g, seed: jax.lax.psum(g, DATA_AXIS)
        return lambda g, seed: jax.lax.psum(perturb(g, seed), DATA_AXIS)
    from ...filter.fixing_float import dequantize_jax, quantize_jax
    from ...ops import quantize as qops

    use_pallas = qops.use_pallas()

    def reduce(g, seed):
        if perturb is not None:
            g = perturb(g, seed)  # ADD_NOISE rides the wire before quantize
        if use_pallas:
            # fused Pallas normalize+noise+floor (measured ~4% faster than
            # the XLA chain on v5e for 2M-slot shards; BENCH_r2 notes)
            s = seed.astype(jnp.int32) * jnp.int32(1000003) + jax.lax.axis_index(
                DATA_AXIS
            ).astype(jnp.int32)
            q, lo, hi = qops.quantize_traced(g, s, num_bytes=push_quant)
        else:
            key = jax.random.fold_in(jax.random.PRNGKey(0x5EED), seed)
            key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
            q, lo, hi = quantize_jax(g, push_quant, key)
        dec = dequantize_jax(q, lo, hi, push_quant)
        dec = jnp.where(g != 0, dec, 0.0)
        return jax.lax.psum(dec, DATA_AXIS)

    return reduce


def make_push_touched(push_quant: int, noise=None):
    """(g_shard, seed) -> (reduced g, touched membership mask).

    touched gates ``updater.apply`` (untouched slots pass through, ref
    per-entry Set on received keys only). Without quantization the
    reduced gradient's support IS membership — up to exact float
    cancellation across contributions, which is a no-op update for FTRL
    and a skipped proximal shrink for AdaGrad/SGD on that measure-zero
    event (the price of dropping a second 640k-index scatter, ~8ms/step
    on v5e). Under a quantized push that shortcut would be wrong —
    fixed-point rounding deterministically zeroes small gradients — so
    membership is collected PRE-quantization with a psum of the support
    mask (a cheap dense collective, still no scatter)."""
    push_reduce = make_push_reduce(push_quant, noise=noise)
    if not push_quant:

        def run(g_shard, seed):
            # touched=None: membership IS the reduced gradient's
            # support; updaters derive it on the fly (the FTRL kernel
            # in-block), so no table-sized mask array ever
            # materializes — 4 GB of the 2^30-table OOM budget
            return push_reduce(g_shard, seed), None

    else:

        def run(g_shard, seed):
            touched = (
                jax.lax.psum((g_shard != 0).astype(jnp.float32), DATA_AXIS) > 0
            )
            return push_reduce(g_shard, seed), touched

    return run


def make_pull_lookup(updater, pull_quant: int, noise=None,
                     narrow: "bool | None" = None):
    """Server-side weight derivation + per-slot lookup for the pull
    path, optionally through the quantized wire (FIXING_FLOAT
    pull_filter): each server shard derives its dense weight vector
    from its live state — the reference's servers send WEIGHTS, not raw
    state — and, when ``pull_quant`` is set, stochastically rounds it
    to n-byte fixed point (per-shard scale) before workers gather it.
    Exact zeros (L1-pruned coordinates) stay exactly zero, as under the
    sparse_filter chain. ``noise`` applies ADD_NOISE to the sent
    weights (pull_filter), the server→worker direction of
    src/filter/add_noise.h.

    Returns ``(derive, lookup)``:

    - ``derive(pulled, seed)`` — once per shard per step: the
      representation workers gather from.
    - ``lookup(rep, rel, ok)`` — flat f32 weights at gather indices
      ``rel``, zero where ``ok`` is False.

    ``narrow`` gathers the quantized CODES plus a 1-byte zero-mask
    and dequantizes AFTER the gather, instead of materializing and
    gathering a dense f32 shard — the byte-economy instinct behind
    the reference's production 1-byte fixing_float pull
    (example/linear/ctr/online_l1lr.conf). MEASURED NEGATIVE on TPU
    (BENCH_ONCHIP 08-02: u8+mask gather 23.6 ms vs f32 18.0 ms at
    640k indices; bench `_q1` 585k vs 632k ex/s): v5e gathers are
    row-granularity-bound, not byte-bound, so two narrow gathers lose
    to one wide one. ``narrow=None`` therefore resolves to the WIDE
    path for every width; narrow stays selectable
    (``pull_gather: "narrow"``) for parts where bytes do bind.
    Exactness-preserving either way: dequantize is elementwise with
    per-shard scalar lo/hi, so dequantize(gather(q)) ==
    gather(dequantize(q)) bit-for-bit, and the gathered zero-mask
    reproduces the exact-zero rule."""
    perturb = _make_perturb(noise, 0xA015F)

    def wide_lookup(w, rel, ok):
        return jnp.where(ok, w[rel], 0.0)

    if not pull_quant:
        def derive_plain(pulled, seed):
            w = updater.weights(pulled)
            return w if perturb is None else perturb(w, seed)

        return derive_plain, wide_lookup

    if narrow is None:
        narrow = False  # wide wins on TPU at every width (docstring)
    from ...filter.fixing_float import dequantize_jax, quantize_jax
    from ...ops import quantize as qops

    use_pallas = qops.use_pallas()

    def quantized(pulled, seed):
        w = updater.weights(pulled)
        if perturb is not None:
            w = perturb(w, seed)
        if use_pallas:
            s = seed.astype(jnp.int32) * jnp.int32(999983) + jax.lax.axis_index(
                SERVER_AXIS
            ).astype(jnp.int32)
            q, lo, hi = qops.quantize_traced(w, s, num_bytes=pull_quant)
        else:
            key = jax.random.fold_in(jax.random.PRNGKey(0xF00D), seed)
            key = jax.random.fold_in(key, jax.lax.axis_index(SERVER_AXIS))
            q, lo, hi = quantize_jax(w, pull_quant, key)
        return w, q, lo, hi

    if narrow:
        def derive_narrow(pulled, seed):
            w, q, lo, hi = quantized(pulled, seed)
            return q, w != 0, lo, hi

        def narrow_lookup(rep, rel, ok):
            q, nz, lo, hi = rep
            dec = dequantize_jax(q[rel], lo, hi, pull_quant)
            return jnp.where(ok & nz[rel], dec, 0.0)

        return derive_narrow, narrow_lookup

    def derive_wide(pulled, seed):
        w, q, lo, hi = quantized(pulled, seed)
        dec = dequantize_jax(q, lo, hi, pull_quant)
        return jnp.where(w != 0, dec, 0.0)

    return derive_wide, wide_lookup


def _convergence_metrics(metrics, g_push, update, w_used,
                         final_is_global: bool = False):
    """Cheap in-jit convergence side outputs for the learning truth
    plane (telemetry/learning.py): squared L2 norms of the per-worker
    gradient actually pushed (summed over workers), the aggregated
    post-filter update handed to the updater, and the weights the step
    consumed (per-occurrence touched weights — a blow-up detector and
    trend line, NOT the global table norm). Trace-pure raw scalars on
    the metrics dict, metered host-side in ``ISGDCompNode.collect``
    (the PR 8 jit-purity pattern); donation-safe — every input predates
    the state update.

    Replication contract (metrics ride out_specs P()): in the dense
    formulations ``g_push`` is the ownership-masked (``ok``) gradient —
    each real entry lives on exactly ONE server shard — so the grad
    fold sums over BOTH axes; ``update`` is a per-server shard vector
    replicated over data (server fold only); ``w_used`` is the
    server-assembled weights replicated over server (data fold only).
    ``final_is_global``: the sparse formulation's psum'd per-unique
    update and its gathered weights are already identical on every
    shard — only the per-worker gradient still folds over data."""
    grad = jnp.sum(jnp.square(g_push))
    upd = jnp.sum(jnp.square(update))
    w = jnp.sum(jnp.square(w_used))
    if final_is_global:
        metrics["grad_sq"] = jax.lax.psum(grad, DATA_AXIS)
        metrics["update_sq"] = upd
        metrics["weight_sq"] = w
    else:
        metrics["grad_sq"] = jax.lax.psum(
            jax.lax.psum(grad, SERVER_AXIS), DATA_AXIS
        )
        metrics["update_sq"] = jax.lax.psum(upd, SERVER_AXIS)
        metrics["weight_sq"] = jax.lax.psum(w, DATA_AXIS)
    return metrics


def _progress_metrics(loss, y, xw, mask, with_aux: bool):
    """SGDProgress scalars (padding rows masked out of the objective); the
    per-example xw/y/mask aux — needed only for host-side AUC — costs three
    all_gathers + a device→host minibatch transfer, so it's optional."""
    metrics = {
        "objective": jax.lax.psum(jnp.sum(loss.row_loss(y, xw) * mask), DATA_AXIS),
        "num_ex": jax.lax.psum(jnp.sum(mask), DATA_AXIS),
        "correct": jax.lax.psum(jnp.sum(((xw > 0) == (y > 0)) * mask), DATA_AXIS),
    }
    if with_aux:
        metrics["xw"] = jax.lax.all_gather(xw, DATA_AXIS)
        metrics["y"] = jax.lax.all_gather(y, DATA_AXIS)
        metrics["mask"] = jax.lax.all_gather(mask, DATA_AXIS)
    return metrics


def _donation_variants(step_impl, name: str = "train_step"):
    """Wrap a traced ``(live, pull, batch, seed) -> (new_state, metrics)``
    step with input-buffer donation where it is legal.

    Donating the live table lets XLA alias input->output: the update
    writes every slot anyway, and aliasing removes the extra whole-table
    output buffer — at 2^28+ slots that buffer is the difference between
    a table fitting on one chip or not. Legality depends on aliasing at
    CALL time (donating a buffer also passed as another argument is a
    runtime error — ``f(donate(a), a)``):

    - ``pull is live`` (a snapshot step) and the caller says the snapshot
      never outlives the call (``donate_ok``, i.e. max_delay == 0): a
      single-argument donated program.
    - ``pull is live`` otherwise: a single-argument non-donated program —
      the snapshot buffer must survive for future delayed steps.
    - distinct buffers (delayed step): donate live, pull is safe.

    Each jitted variant is wrapped into the device inventory
    (telemetry/device.py) under ``<name>.<variant>``: per-step-builder
    cost/memory analysis lands in the bench record's ``device``
    section, new-aval recompiles are counted (zero post-warmup on a
    healthy run), and the donated variants' input→output aliasing is
    runtime-verified (a fallback means the step silently paid a
    whole-table copy).
    """
    from ...telemetry import device as device_tel

    step_delay = device_tel.instrument(
        f"{name}.delay",
        functools.partial(jax.jit, donate_argnums=(0,))(step_impl),
        donate_argnums=(0,),
    )

    def snap_impl(live_state, batch, seed):
        return step_impl(live_state, live_state, batch, seed)

    # no-donate: the snapshot buffer must survive for future delayed
    # steps (max_delay > 0); the donate_ok path below covers delay 0
    step_snap = device_tel.instrument(f"{name}.snap", jax.jit(snap_impl))
    step_snap_donate = device_tel.instrument(
        f"{name}.snap_donate",
        functools.partial(jax.jit, donate_argnums=(0,))(snap_impl),
        donate_argnums=(0,),
    )

    def step(live_state, pull_state, batch, seed=np.uint32(0),
             donate_ok: bool = False):
        if pull_state is live_state:
            fn = step_snap_donate if donate_ok else step_snap
            return fn(live_state, batch, seed)
        return step_delay(live_state, pull_state, batch, seed)

    return step


def make_train_step_ell(
    updater,
    loss,
    mesh,
    num_slots: int,
    binary: bool,
    with_aux: bool = True,
    packed: bool = False,
    push_quant: int = 0,
    pull_quant: int = 0,
    push_noise=None,
    pull_noise=None,
    pull_narrow: "bool | None" = None,
):
    """Fused SPMD step over ELL batches: Xw is a lane reduction (no row
    scatter); only the push keeps a scatter-add. ``packed`` accepts the
    u24-wire ELLPackedBatch and unpacks indices on device."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    push_touched = make_push_touched(push_quant, noise=push_noise)
    pull_derive, pull_lookup = make_pull_lookup(
        updater, pull_quant, noise=pull_noise, narrow=pull_narrow
    )

    def local_step(live, pulled, seed, y, mask, slots, vals):
        y, mask, slots = y[0], mask[0], slots[0]
        vals = None if binary else vals[0]
        if packed:
            mask = mask.astype(jnp.float32)
            slots = unpack_u24(slots)
        flat = slots.reshape(-1)
        rel, ok = localize(flat, shard)

        # pull: each server derives (and optionally quantizes) its
        # representation once, workers gather entries + assemble via psum
        w_rep = pull_derive(pulled, seed)
        w_e = jax.lax.psum(
            pull_lookup(w_rep, rel, ok), SERVER_AXIS
        ).reshape(slots.shape)  # [R, K]
        x = w_e if binary else w_e * vals
        xw = x.sum(axis=1)

        gr = loss.row_grad(y, xw) * mask  # [R]
        g_e = gr[:, None] if binary else gr[:, None] * vals  # [R, K]
        valid = valid_slots(slots, num_slots) if binary else (vals != 0)
        g_flat = jnp.where(valid, g_e, 0.0).reshape(-1)

        g_push = jnp.where(ok, g_flat, 0.0)
        g_shard = jnp.zeros((shard,), jnp.float32).at[rel].add(g_push)
        g_shard, touched = push_touched(g_shard, seed)
        new_state = updater.apply(live, g_shard, touched, seed=seed)

        metrics = _progress_metrics(loss, y, xw, mask, with_aux)
        _convergence_metrics(metrics, g_push, g_shard, w_e * mask[:, None])
        return new_state, metrics

    def state_spec(state):
        # declared in parallel/partition.py — one spec rule for every
        # updater-state leaf, fitted to rank (scalars replicate)
        return partlib.state_partition_spec(state)

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = state_spec(live_state)
        slots = batch.slots_u24 if packed else batch.slots
        # binary batches carry no vals; pass slots as an unused placeholder
        vals = slots if binary else batch.vals
        batch_specs = tuple(P(DATA_AXIS) for _ in range(4))
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), *batch_specs),
            out_specs=(specs, P()),
            check_vma=False,
        )(live_state, pull_state, seed, batch.y, batch.mask, slots, vals)

    return _donation_variants(step_impl, name="step_ell")


def _make_uniform_ell_mini_step(
    updater, loss, shard, decode_fn, with_aux, push_quant,
    pull_quant, push_noise=None, pull_noise=None, pull_narrow=None,
):
    """Shared single-minibatch body for the uniform-row binary ELL wire
    step builders (bits + stream): ``decode_fn(*wire_operands)`` →
    ``(y, mask, slots[R, K])`` inside the jit, then the one pull →
    lane-sum → push → update body both wires share."""
    push_touched = make_push_touched(push_quant, noise=push_noise)
    pull_derive, pull_lookup = make_pull_lookup(
        updater, pull_quant, noise=pull_noise, narrow=pull_narrow
    )

    def mini_step(live, pulled, seed, *wire_operands):
        # named_scope phases: HLO op metadata carries these, so a
        # --profile trace buckets step time into wire-decode / pull /
        # compute / push / update (utils/profiling.summarize_trace)
        with jax.named_scope("ps_decode"):
            y, mask, slots = decode_fn(*wire_operands)
            # slot-localization arithmetic belongs to decode: it turns
            # wire slots into shard-relative gather indices
            flat = slots.reshape(-1)
            rel, ok = localize(flat, shard)

        with jax.named_scope("ps_pull"):
            w_rep = pull_derive(pulled, seed)
            w_e = jax.lax.psum(
                pull_lookup(w_rep, rel, ok), SERVER_AXIS
            ).reshape(slots.shape)  # [R, K]
        with jax.named_scope("ps_compute"):
            xw = w_e.sum(axis=1)

            gr = loss.row_grad(y, xw) * mask  # [R]
            # uniform rows: every lane of a live row is a real feature,
            # and padding rows are killed by the mask folded into gr
            g_flat = jnp.broadcast_to(gr[:, None], slots.shape).reshape(-1)

        with jax.named_scope("ps_push"):
            g_push = jnp.where(ok, g_flat, 0.0)
            g_shard = jnp.zeros((shard,), jnp.float32).at[rel].add(g_push)
            g_shard, touched = push_touched(g_shard, seed)
        with jax.named_scope("ps_update"):
            new_state = updater.apply(live, g_shard, touched, seed=seed)

        with jax.named_scope("ps_metrics"):
            metrics = _progress_metrics(loss, y, xw, mask, with_aux)
            # padding rows' garbage-decoded slots gather real weights;
            # the mask gates them out of the consumed-weight norm just
            # like it gates their gradients
            _convergence_metrics(
                metrics, g_push, g_shard, w_e * mask[:, None]
            )
        return new_state, metrics

    return mini_step


def _make_bits_mini_step(
    updater, loss, num_slots, shard, rows, lanes, with_aux, push_quant,
    pull_quant, push_noise=None, pull_noise=None, pull_narrow=None,
):
    """Single-minibatch body for the bits-wire step builders:
    (live, pulled, seed, per-device y_bits/count/words) -> (state, metrics)."""
    bits = slot_bits(num_slots)

    def decode_fn(y_bits, count, words):
        y = unpack_sign_bits(y_bits, rows)
        mask = (jnp.arange(rows) < count).astype(jnp.float32)
        slots = unpack_bits(words, rows * lanes, bits).reshape(rows, lanes)
        return y, mask, slots

    return _make_uniform_ell_mini_step(
        updater, loss, shard, decode_fn, with_aux, push_quant,
        pull_quant, push_noise, pull_noise, pull_narrow,
    )


def _make_stream_mini_step(
    updater, loss, shard, static_key, with_aux, push_quant,
    pull_quant, push_noise=None, pull_noise=None, pull_narrow=None,
):
    """Single-minibatch body for the stream-wire (lane-dictionary) step
    builders: (live, pulled, seed, per-device y_bits/count/raw_words/
    code_words/table_words/lane_starts) -> (state, metrics). The lane
    split, code width and table capacity are static (they pin the
    decode program — one jit per ``static_key``)."""
    from ...ops.wire_codec import decode_stream_slots

    rows, lanes, dict_lanes, code_bits, dict_pad, raw_bits = static_key

    def decode_fn(y_bits, count, raw_words, code_words, table_words,
                  lane_starts):
        y = unpack_sign_bits(y_bits, rows)
        mask = (jnp.arange(rows) < count).astype(jnp.float32)
        slots = decode_stream_slots(
            raw_words, code_words, table_words, lane_starts,
            rows=rows, lanes=lanes, dict_lanes=dict_lanes,
            code_bits=code_bits, dict_pad=dict_pad, raw_bits=raw_bits,
        )
        return y, mask, slots

    return _make_uniform_ell_mini_step(
        updater, loss, shard, decode_fn, with_aux, push_quant,
        pull_quant, push_noise, pull_noise, pull_narrow,
    )


def _bits_state_spec(state):
    # declared in parallel/partition.py (same rule as state_spec)
    return partlib.state_partition_spec(state)


def make_train_step_ell_bits(
    updater,
    loss,
    mesh,
    num_slots: int,
    rows: int,
    lanes: int,
    with_aux: bool = True,
    push_quant: int = 0,
    pull_quant: int = 0,
    push_noise=None,
    pull_noise=None,
    pull_narrow: "bool | None" = None,
):
    """Fused SPMD step over the minimal-wire ELLBitsBatch (binary,
    uniform-row): slot ids unpack from the bitstream, labels from sign
    bits, the mask from the row count — all inside the jitted step, so the
    host ships ~bits/8 bytes per feature and nothing else."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    mini_step = _make_bits_mini_step(
        updater, loss, num_slots, shard, rows, lanes, with_aux,
        push_quant, pull_quant, push_noise, pull_noise, pull_narrow,
    )

    def local_step(live, pulled, seed, y_bits, counts, words):
        return mini_step(live, pulled, seed, y_bits[0], counts[0], words[0])

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = _bits_state_spec(live_state)
        batch_specs = tuple(P(DATA_AXIS) for _ in range(3))
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), *batch_specs),
            out_specs=(specs, P()),
            check_vma=False,
        )(live_state, pull_state, seed, batch.y_bits, batch.counts,
          batch.slots_words)

    return _donation_variants(step_impl, name="step_ell_bits")


def make_train_step_ell_bits_scan(
    updater,
    loss,
    mesh,
    num_slots: int,
    rows: int,
    lanes: int,
    with_aux: bool = True,
    push_quant: int = 0,
    pull_quant: int = 0,
    push_noise=None,
    pull_noise=None,
    pull_narrow: "bool | None" = None,
):
    """Scan-fused superstep: T bits-wire minibatches per launch.

    ``lax.scan`` drives the shared mini-step over the leading T axis
    inside ONE jitted program — the weights advance every ministep (the
    sequential-optimizer semantics), while the host pays a single
    dispatch/transfer round trip for T steps. Metrics come back summed
    over the superbatch (stacked per-ministep when ``with_aux``)."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    mini_step = _make_bits_mini_step(
        updater, loss, num_slots, shard, rows, lanes, with_aux,
        push_quant, pull_quant, push_noise, pull_noise, pull_narrow,
    )

    def local_step(live, pulled, seed, y_bits, counts, words):
        del pulled  # staleness 0 inside the superstep (≤ any delay bound)
        t_steps = y_bits.shape[0]

        def body(carry, xs):
            state, i = carry
            yb, cc, ww = xs
            new_state, metrics = mini_step(
                state, state, seed + i, yb[0], cc[0], ww[0]
            )
            return (new_state, i + np.uint32(1)), metrics

        (new_state, _), metrics = jax.lax.scan(
            body, (live, np.uint32(0)), (y_bits, counts, words),
            length=t_steps,
        )
        if not with_aux:
            metrics = jax.tree.map(lambda m: m.sum(axis=0), metrics)
        else:
            # scalars fold; per-example aux stays stacked per ministep
            metrics = {
                k: (v.sum(axis=0) if v.ndim == 1 else v)
                for k, v in metrics.items()
            }
        return new_state, metrics

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = _bits_state_spec(live_state)
        batch_specs = tuple(P(None, DATA_AXIS) for _ in range(3))
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), *batch_specs),
            out_specs=(specs, P()),
            check_vma=False,
        )(live_state, pull_state, seed, batch.y_bits, batch.counts,
          batch.slots_words)

    return _donation_variants(step_impl, name="step_ell_bits_scan")


_STREAM_FIELDS = (
    "y_bits", "counts", "raw_words", "code_words", "table_words",
    "lane_starts",
)


def make_train_step_ell_stream(
    updater,
    loss,
    mesh,
    num_slots: int,
    static_key: tuple,
    with_aux: bool = True,
    push_quant: int = 0,
    pull_quant: int = 0,
    push_noise=None,
    pull_noise=None,
    pull_narrow: "bool | None" = None,
):
    """Fused SPMD step over the stream-once lane-dictionary wire
    (EncodedEllStreamBatch): dictionary lanes decode as
    ``uslots[lane_start + ucol]`` gathers, raw lanes unpack from the
    bit stream — all inside the jitted step, so only the encoded bytes
    cross the host→device link."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    mini_step = _make_stream_mini_step(
        updater, loss, shard, static_key, with_aux,
        push_quant, pull_quant, push_noise, pull_noise, pull_narrow,
    )

    def local_step(live, pulled, seed, *wire):
        return mini_step(live, pulled, seed, *(w[0] for w in wire))

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = _bits_state_spec(live_state)
        batch_specs = tuple(P(DATA_AXIS) for _ in _STREAM_FIELDS)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), *batch_specs),
            out_specs=(specs, P()),
            check_vma=False,
        )(live_state, pull_state, seed,
          *(getattr(batch, f) for f in _STREAM_FIELDS))

    return _donation_variants(step_impl, name="step_ell_stream")


def make_train_step_ell_stream_scan(
    updater,
    loss,
    mesh,
    num_slots: int,
    static_key: tuple,
    with_aux: bool = True,
    push_quant: int = 0,
    pull_quant: int = 0,
    push_noise=None,
    pull_noise=None,
    pull_narrow: "bool | None" = None,
):
    """Scan-fused superstep over T stream-wire minibatches per launch
    (the make_train_step_ell_bits_scan twin — see its semantics note:
    weights advance every ministep, one dispatch per T steps)."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    mini_step = _make_stream_mini_step(
        updater, loss, shard, static_key, with_aux,
        push_quant, pull_quant, push_noise, pull_noise, pull_narrow,
    )

    def local_step(live, pulled, seed, *wire):
        del pulled  # staleness 0 inside the superstep (≤ any delay bound)
        t_steps = wire[0].shape[0]

        def body(carry, xs):
            state, i = carry
            new_state, metrics = mini_step(
                state, state, seed + i, *(w[0] for w in xs)
            )
            return (new_state, i + np.uint32(1)), metrics

        (new_state, _), metrics = jax.lax.scan(
            body, (live, np.uint32(0)), wire, length=t_steps,
        )
        if not with_aux:
            metrics = jax.tree.map(lambda m: m.sum(axis=0), metrics)
        else:
            metrics = {
                k: (v.sum(axis=0) if v.ndim == 1 else v)
                for k, v in metrics.items()
            }
        return new_state, metrics

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = _bits_state_spec(live_state)
        batch_specs = tuple(P(None, DATA_AXIS) for _ in _STREAM_FIELDS)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), *batch_specs),
            out_specs=(specs, P()),
            check_vma=False,
        )(live_state, pull_state, seed,
          *(getattr(batch, f) for f in _STREAM_FIELDS))

    return _donation_variants(step_impl, name="step_ell_stream_scan")


def make_train_step_hashed(
    updater, loss, mesh, num_slots: int, with_aux: bool = True,
    push_quant: int = 0, pull_quant: int = 0, push_noise=None,
    pull_noise=None, pull_narrow: "bool | None" = None,
):
    """Per-entry fused SPMD step (hashed fast path): gather state at each
    nnz slot, segment-sum Xw by row, scatter per-entry gradients densely —
    duplicates fold in the scatter, so no uniquification anywhere."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    push_touched = make_push_touched(push_quant, noise=push_noise)
    pull_derive, pull_lookup = make_pull_lookup(
        updater, pull_quant, noise=pull_noise, narrow=pull_narrow
    )

    def local_step(live, pulled, seed, y, mask, rows, slots, vals):
        y, mask, rows, slots, vals = y[0], mask[0], rows[0], slots[0], vals[0]
        rel, ok = localize(slots, shard)

        # sentinel/padding slots are owned by no shard -> gathered weight 0,
        # and their vals are 0, so they vanish from Xw and g
        w_rep = pull_derive(pulled, seed)
        w_e = jax.lax.psum(pull_lookup(w_rep, rel, ok), SERVER_AXIS)

        xw = jax.ops.segment_sum(vals * w_e, rows, num_segments=y.shape[0])
        gr = loss.row_grad(y, xw) * mask
        g_e = vals * gr[rows]

        g_push = jnp.where(ok, g_e, 0.0)
        g_shard = jnp.zeros((shard,), jnp.float32).at[rel].add(g_push)
        g_shard, touched = push_touched(g_shard, seed)
        new_state = updater.apply(live, g_shard, touched, seed=seed)

        metrics = _progress_metrics(loss, y, xw, mask, with_aux)
        _convergence_metrics(metrics, g_push, g_shard, w_e)
        return new_state, metrics

    def state_spec(state):
        # declared in parallel/partition.py — one spec rule for every
        # updater-state leaf, fitted to rank (scalars replicate)
        return partlib.state_partition_spec(state)

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = state_spec(live_state)
        batch_specs = tuple(P(DATA_AXIS) for _ in range(5))
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), *batch_specs),
            out_specs=(specs, P()),
            check_vma=False,
        )(
            live_state,
            pull_state,
            seed,
            batch.y,
            batch.mask,
            batch.rows,
            batch.slots,
            batch.vals,
        )

    return _donation_variants(step_impl, name="step_hashed")


def sparse_update_min_slots() -> int:
    """``SGDConfig.update="auto"`` flip point, in PER-SERVER shard
    slots: below it the dense sweep wins (the whole-shard Pallas pass
    is cheap — 2^28 trains at 446k ex/s); at and above it the row
    formulation wins — and 2^31 REQUIRES it (the dense gradient temp
    alone is 8.6 GB). The current 2^30 default was derived from the
    XLA rows path (~130 ms sweep at 2^30 vs ~80 ms for four 640k-row
    gathers/scatters, BENCH_ONCHIP component medians). The fused
    sparse kernel (ops/ftrl_sparse.py) moves the row side of that
    comparison: once an on-chip ``ftrl_sparse`` A/B capture lands
    (``make ftrl-bench`` / every bench record), re-derive as the
    smallest shard where ``ftrl_sparse.fused_ms`` (at the training
    uniq width) beats the dense sweep's per-ministep cost
    (``step_phase_ftrl_update_ms`` at that shard) — the kernel only
    LOWERS this threshold, it never raises it, so 2^30 stays a safe
    default until the capture re-judges it (doc/PERFORMANCE.md, "FTRL
    roofline"). Env ``PS_SPARSE_UPDATE_MIN_SLOTS`` overrides while
    on-chip captures refine the default."""
    try:
        return int(os.environ.get("PS_SPARSE_UPDATE_MIN_SLOTS", 1 << 30))
    except ValueError:
        return 1 << 30


def _make_exact_mini_step(
    updater, loss, shard, with_aux, update, push_quant, pull_quant,
    push_noise, pull_noise, pull_narrow, significance=None,
):
    """Shared single-minibatch body for the exact (host-dedup) wire:
    (live, pulled, seed, per-device y/mask/rows/ucols/vals/uslots/umask)
    -> (state, metrics). Two update formulations:

    - ``"dense"``: scatter per-unique gradients into a dense shard
      vector, psum over the data axis (inside push_reduce), run the
      updater over the WHOLE shard with a touched mask. O(shard) HBM
      traffic per ministep — wins while the table sweep is cheap.
    - ``"sparse"``: psum the per-unique-slot gradients directly (prep
      guarantees every data shard carries the SAME globally-deduped
      ``uslots``, so the psum is elementwise-aligned), then
      gather→apply→scatter only the touched rows
      (updaters.apply_state_rows). O(unique) traffic — the 2^30+/2^31
      formulation, and the only one that fits 2^31 on one chip (no
      dense gradient temp). The reference's servers likewise only run
      entry ``Set`` on received keys (async_sgd.h:131-151).

    The sparse form composes with the EXACT wire only: quantized/noisy
    push/pull filters are defined on dense shard vectors (per-shard
    scale factors), so they stay with ``"dense"``.

    ``significance`` (ops/significance.SignificanceSpec, sparse-only):
    the in-jit KKT filter — slots whose aggregated update provably
    leaves the FTRL proximal weight at zero are masked out of the
    update entirely (their rows are scatter-dropped, bit-untouched).
    ``None`` traces the literal pre-filter program (the off =
    bit-identical contract).
    """
    if significance is not None and update != "sparse":
        raise ValueError(
            "the KKT significance filter composes with update='sparse' "
            "only (its mask is defined on the globally-deduped unique-"
            "slot vectors)"
        )
    if update == "sparse":
        if push_quant or pull_quant or push_noise or pull_noise:
            raise ValueError(
                "update='sparse' composes with the exact (unfiltered) "
                "wire only; quantized/noisy filters need update='dense'"
            )
        # pull_narrow only modifies a QUANTIZED pull (gather codes+mask
        # instead of dequantized weights); with pull_quant rejected
        # above it has nothing to modify, and the row-gather below
        # ignores it entirely. Fail loudly on an explicit 'narrow'
        # rather than silently dropping it, so a future
        # narrow-without-quant mode cannot diverge here unnoticed
        # (ADVICE round 5). `None` ("auto") stays fine.
        if pull_narrow:
            raise ValueError(
                "update='sparse' does not implement pull_gather="
                "'narrow' (narrow modifies the quantized pull, which "
                "sparse mode rejects); use pull_gather='auto'/'wide'"
            )
        from .updaters import apply_state_rows

        def mini_step_sparse(live, pulled, seed, y, mask, rows, ucols,
                             vals, uslots, umask):
            rel, ok = localize(uslots, shard)
            with jax.named_scope("ps_pull"):
                # derive weights from the GATHERED rows of the pull
                # state — no whole-table weight derivation. Exact:
                # updater.weights is elementwise, so gather∘derive ==
                # derive∘gather bit-for-bit.
                pulled_u = jax.tree.map(
                    lambda a: a[rel] if a.ndim >= 1 else a, pulled
                )
                w_own = jnp.where(ok, updater.weights(pulled_u), 0.0)
                w_u = jax.lax.psum(w_own, SERVER_AXIS) * umask
            with jax.named_scope("ps_compute"):
                xw = jax.ops.segment_sum(
                    vals * w_u[ucols], rows, num_segments=y.shape[0]
                )
                gr = loss.row_grad(y, xw) * mask
                g_u = jax.ops.segment_sum(
                    vals * gr[rows], ucols, num_segments=uslots.shape[0]
                )
                g_u = g_u * umask
            with jax.named_scope("ps_push"):
                # workers share one global uslots table, so gradient
                # aggregation is an elementwise psum of the U-vector —
                # no dense scatter, no shard-sized temp
                g_local = g_u
                g_u = jax.lax.psum(g_u, DATA_AXIS)
            ok_upd = ok
            if significance is not None:
                with jax.named_scope("ps_kkt"):
                    from ...ops.significance import kkt_mask

                    # assemble the global z accumulator the same way
                    # w_u was (one extra U-vector collective, disclosed
                    # in doc/PERFORMANCE.md): the KKT test needs the
                    # slot's z, owned by exactly one server shard
                    z_own = jnp.where(ok, pulled_u["z"], 0.0)
                    z_u = jax.lax.psum(z_own, SERVER_AXIS) * umask
                    keep, n_suppressed = kkt_mask(
                        z_u, g_u, w_u, umask, seed, spec=significance
                    )
                    # suppressed slots leave the push entirely: their
                    # aggregated gradient zeroes AND their rows are
                    # scatter-dropped below — state bit-untouched
                    g_u = jnp.where(keep, g_u, 0.0)
                    ok_upd = ok & keep
            with jax.named_scope("ps_update"):
                new_state = apply_state_rows(
                    updater, live, rel, ok_upd, g_u, seed=seed
                )
            with jax.named_scope("ps_metrics"):
                metrics = _progress_metrics(loss, y, xw, mask, with_aux)
                # g_u / w_u are the GLOBAL unique vectors (identical on
                # every shard after their psums) — no further fold
                _convergence_metrics(
                    metrics, g_local, g_u, w_u, final_is_global=True
                )
                if significance is not None:
                    # suppressed-key accounting, metered host-side in
                    # collect (learner/consistency.py reconciles these
                    # against ps_push_keys_total in-record)
                    metrics["kkt_slots"] = jnp.sum(
                        (umask > 0).astype(jnp.float32)
                    )
                    metrics["kkt_suppressed"] = n_suppressed
                    if significance.feedback:
                        # per-slot keep/ids for the host drop tracker —
                        # global vectors, identical on every shard
                        metrics["kkt_keep"] = keep
                        metrics["kkt_uslots"] = uslots
            return new_state, metrics

        return mini_step_sparse

    if update != "dense":
        raise ValueError(f"unknown update mode {update!r}")
    push_touched = make_push_touched(push_quant, noise=push_noise)
    pull_derive, pull_lookup = make_pull_lookup(
        updater, pull_quant, noise=pull_noise, narrow=pull_narrow
    )

    def mini_step(live, pulled, seed, y, mask, rows, ucols, vals,
                  uslots, umask):
        rel, ok = localize(uslots, shard)

        # named_scope: phase names reach HLO op metadata, so a
        # --profile trace (utils/profiling.summarize_trace) can bucket
        # device time by pull/compute/push/update instead of opaque
        # fusion numbers — the r3 verdict's "where do the step's 96%
        # of roofline go" question needs this attribution
        # -- pull (server-side weight derivation, gather + psum assembly) --
        with jax.named_scope("ps_pull"):
            w_rep = pull_derive(pulled, seed)
            w_u = (
                jax.lax.psum(pull_lookup(w_rep, rel, ok), SERVER_AXIS)
                * umask
            )

        # -- worker compute (Xw, row grad, X^T g) --
        with jax.named_scope("ps_compute"):
            xw = jax.ops.segment_sum(
                vals * w_u[ucols], rows, num_segments=y.shape[0]
            )
            gr = loss.row_grad(y, xw) * mask
            g_u = jax.ops.segment_sum(
                vals * gr[rows], ucols, num_segments=uslots.shape[0]
            )
            g_u = g_u * umask

        # -- push (dense scatter into owned shard + psum over data axis) --
        with jax.named_scope("ps_push"):
            g_push = jnp.where(ok, g_u, 0.0)
            g_shard = jnp.zeros((shard,), jnp.float32).at[rel].add(g_push)
            g_shard, touched = push_touched(g_shard, seed)

        with jax.named_scope("ps_update"):
            new_state = updater.apply(live, g_shard, touched, seed=seed)

        # -- progress (ref SGDProgress fields) --
        with jax.named_scope("ps_metrics"):
            metrics = _progress_metrics(loss, y, xw, mask, with_aux)
            _convergence_metrics(metrics, g_push, g_shard, w_u)
        return new_state, metrics

    return mini_step


def make_train_step_scan(
    updater, loss, mesh, num_slots: int, with_aux: bool = True,
    push_quant: int = 0, pull_quant: int = 0, push_noise=None,
    pull_noise=None, pull_narrow: "bool | None" = None,
    update: str = "dense", significance=None,
):
    """Scan-fused superstep over the exact wire: T host-dedup'd
    minibatches per launch (the PreppedSuperBatch twin of
    make_train_step_ell_bits_scan — one dispatch/transfer round trip
    for T sequential ministeps, weights advancing every ministep)."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    # feedback vectors are per-ministep; the scan metric fold would sum
    # them into garbage — scan supersteps keep the mask, drop the echo
    if significance is not None:
        significance = significance.without_feedback()
    mini_step = _make_exact_mini_step(
        updater, loss, shard, with_aux, update, push_quant, pull_quant,
        push_noise, pull_noise, pull_narrow, significance=significance,
    )

    def local_step(live, pulled, seed, y, mask, rows, ucols, vals,
                   uslots, umask):
        del pulled  # staleness 0 inside the superstep (≤ any delay bound)
        t_steps = y.shape[0]

        def body(carry, xs):
            state, i = carry
            yb, mb, rb, ub, vb, usb, umb = xs
            new_state, metrics = mini_step(
                state, state, seed + i, yb[0], mb[0], rb[0], ub[0],
                vb[0], usb[0], umb[0],
            )
            return (new_state, i + np.uint32(1)), metrics

        (new_state, _), metrics = jax.lax.scan(
            body, (live, np.uint32(0)),
            (y, mask, rows, ucols, vals, uslots, umask),
            length=t_steps,
        )
        if not with_aux:
            metrics = jax.tree.map(lambda m: m.sum(axis=0), metrics)
        else:
            # scalars fold; per-example aux stays stacked per ministep
            metrics = {
                k: (v.sum(axis=0) if v.ndim == 1 else v)
                for k, v in metrics.items()
            }
        return new_state, metrics

    def state_spec(state):
        # declared in parallel/partition.py — one spec rule for every
        # updater-state leaf, fitted to rank (scalars replicate)
        return partlib.state_partition_spec(state)

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = state_spec(live_state)
        batch_specs = tuple(P(None, DATA_AXIS) for _ in range(7))
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), *batch_specs),
            out_specs=(specs, P()),
            check_vma=False,
        )(
            live_state,
            pull_state,
            seed,
            batch.y,
            batch.mask,
            batch.rows,
            batch.ucols,
            batch.vals,
            batch.uslots,
            batch.umask,
        )

    return _donation_variants(step_impl, name="step_exact_scan")


def _encoded_shard_decoder(num_slots: int):
    """Per-shard decode closure for the compact wire (ops/wire_codec via
    learner.wire.decode_exact_shard): EncodedExactBatch leaves with a
    leading local-shard dim of 1 → the raw per-shard exact-wire arrays.
    The static encoding parameters ride on the batch object itself (the
    batch and superbatch classes both carry them)."""
    from ...learner.wire import decode_exact_shard

    def decode(eb):
        leaves = (
            eb.y[0], eb.counts[0], eb.row_counts[0], eb.nnz[0],
            eb.ucols_words[0], eb.uslots[0], eb.n_uniq[0],
            None if eb.vals is None else eb.vals[0],
            None if eb.vals_lo is None else eb.vals_lo[0],
            None if eb.vals_hi is None else eb.vals_hi[0],
        )
        # named_scope: wire decode shows up as its own phase in the
        # --profile trace (utils/profiling.summarize_trace), so the
        # bytes-for-VPU-cycles trade stays measurable
        with jax.named_scope("ps_wire_decode"):
            return decode_exact_shard(eb, num_slots, _leaves=leaves)

    return decode


def make_train_step_encoded(
    updater, loss, mesh, num_slots: int, with_aux: bool = True,
    push_quant: int = 0, pull_quant: int = 0, push_noise=None,
    pull_noise=None, pull_narrow: "bool | None" = None,
    update: str = "dense", significance=None,
):
    """Fused SPMD step over the compact wire's EncodedExactBatch: only
    the encoded buffers cross the host→device link; the jit decodes
    them per shard (ops/wire_codec, trace-pure) and runs the SAME exact
    mini-step as make_train_step — exact-mode parity is bit-for-bit
    (tests/test_wire.py)."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    mini_step = _make_exact_mini_step(
        updater, loss, shard, with_aux, update, push_quant, pull_quant,
        push_noise, pull_noise, pull_narrow, significance=significance,
    )
    decode = _encoded_shard_decoder(num_slots)

    def local_step(live, pulled, seed, eb):
        y, mask, rows, ucols, vals, uslots, umask = decode(eb)
        return mini_step(
            live, pulled, seed, y, mask, rows, ucols, vals, uslots, umask
        )

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = _bits_state_spec(live_state)
        bspec = jax.tree.map(lambda _: P(DATA_AXIS), batch)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), bspec),
            out_specs=(specs, P()),
            check_vma=False,
        )(live_state, pull_state, seed, batch)

    return _donation_variants(step_impl, name="step_encoded")


def make_train_step_encoded_scan(
    updater, loss, mesh, num_slots: int, with_aux: bool = True,
    push_quant: int = 0, pull_quant: int = 0, push_noise=None,
    pull_noise=None, pull_narrow: "bool | None" = None,
    update: str = "dense", significance=None,
):
    """Scan-fused superstep over the compact wire: T encoded minibatches
    per launch (the EncodedExactSuperBatch twin of make_train_step_scan
    — decode AND ministep both live inside the one jitted program)."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    if significance is not None:  # scan fold: mask yes, echo no
        significance = significance.without_feedback()
    mini_step = _make_exact_mini_step(
        updater, loss, shard, with_aux, update, push_quant, pull_quant,
        push_noise, pull_noise, pull_narrow, significance=significance,
    )
    decode = _encoded_shard_decoder(num_slots)

    def local_step(live, pulled, seed, eb):
        del pulled  # staleness 0 inside the superstep (≤ any delay bound)
        t_steps = eb.counts.shape[0]

        def body(carry, xs):
            state, i = carry
            y, mask, rows, ucols, vals, uslots, umask = decode(xs)
            new_state, metrics = mini_step(
                state, state, seed + i, y, mask, rows, ucols, vals,
                uslots, umask,
            )
            return (new_state, i + np.uint32(1)), metrics

        (new_state, _), metrics = jax.lax.scan(
            body, (live, np.uint32(0)), eb, length=t_steps
        )
        if not with_aux:
            metrics = jax.tree.map(lambda m: m.sum(axis=0), metrics)
        else:
            metrics = {
                k: (v.sum(axis=0) if v.ndim == 1 else v)
                for k, v in metrics.items()
            }
        return new_state, metrics

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = _bits_state_spec(live_state)
        bspec = jax.tree.map(lambda _: P(None, DATA_AXIS), batch)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), bspec),
            out_specs=(specs, P()),
            check_vma=False,
        )(live_state, pull_state, seed, batch)

    return _donation_variants(step_impl, name="step_encoded_scan")


def make_train_step(
    updater, loss, mesh, num_slots: int, with_aux: bool = True,
    push_quant: int = 0, pull_quant: int = 0, push_noise=None,
    pull_noise=None, pull_narrow: "bool | None" = None,
    update: str = "dense", significance=None,
):
    """Build the fused SPMD train step. Returns jitted
    ``step(live_state, pull_state, batch_arrays) -> (new_state, metrics)``.

    ``update="sparse"`` swaps the dense scatter+whole-shard sweep for
    the gather→apply→scatter row formulation (see
    updaters.apply_state_rows) — the big-table mode the scale captures
    flip to above ``sparse_update_min_slots``.
    """
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server
    mini_step = _make_exact_mini_step(
        updater, loss, shard, with_aux, update, push_quant, pull_quant,
        push_noise, pull_noise, pull_narrow, significance=significance,
    )

    def local_step(live, pulled, seed, y, mask, rows, ucols, vals, uslots, umask):
        # squeeze the per-shard leading dim added by stacking
        return mini_step(
            live, pulled, seed, y[0], mask[0], rows[0], ucols[0],
            vals[0], uslots[0], umask[0],
        )

    def state_spec(state):
        # declared in parallel/partition.py — one spec rule for every
        # updater-state leaf, fitted to rank (scalars replicate)
        return partlib.state_partition_spec(state)

    def step_impl(live_state, pull_state, batch, seed=np.uint32(0)):
        specs = state_spec(live_state)
        batch_specs = tuple(P(DATA_AXIS) for _ in range(7))
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, specs, P(), *batch_specs),
            out_specs=(specs, P()),
            check_vma=False,
        )(
            live_state,
            pull_state,
            seed,
            batch.y,
            batch.mask,
            batch.rows,
            batch.ucols,
            batch.vals,
            batch.uslots,
            batch.umask,
        )

    return _donation_variants(step_impl, name="step_exact")


_SUPPORTED_FILTERS = (
    "fixing_float", "key_caching", "sparse", "compressing", "add_noise",
)


def _add_noise_params(filters):
    """(mean, std) of an ADD_NOISE entry in a conf filter list, or None.
    Applied device-side to each worker's gradient contribution before
    aggregation — the wire position of the reference's filter
    (src/filter/add_noise.h encodes worker->server messages)."""
    for f in filters or ():
        if isinstance(f, dict):
            ftype = str(f.get("type", "")).lower()
            mean, std = f.get("mean", 0.0), f.get("std", 0.0)
        else:
            ftype = str(getattr(f, "type", "")).lower()
            mean, std = getattr(f, "mean", 0.0), getattr(f, "std", 0.0)
        if ftype == "add_noise":
            return float(mean or 0.0), float(std or 0.0)
    return None


def _fixing_float_bytes(filters, where: str) -> int:
    """num_bytes of a FIXING_FLOAT entry in a conf filter list (0 = none),
    validated; accepts dicts (conf parse) or FilterSpec-likes."""
    import logging

    nb = 0
    for f in filters or ():
        if isinstance(f, dict):
            ftype, fnb = f.get("type"), f.get("num_bytes", 1)
        else:
            ftype, fnb = getattr(f, "type", None), getattr(f, "num_bytes", 1)
        ftype = str(ftype).lower() if ftype is not None else ""
        if ftype == "fixing_float":
            nb = int(fnb or 1)
            if nb not in (1, 2):
                raise ValueError(
                    f"{where} FIXING_FLOAT num_bytes must be 1 or 2, got {nb}"
                )
        elif ftype not in _SUPPORTED_FILTERS:
            logging.getLogger(__name__).warning(
                "%s filter %r is not applied by the fused async-SGD step",
                where, ftype,
            )
    return nb


def _wire_encoding_name(prepped) -> str:
    """Telemetry label for the wire a prepped batch rides
    (``ps_wire_bytes_total{encoding="<name>+lz"}`` on the staging leg)."""
    from ...learner.wire import (
        EncodedEllStreamBatch,
        EncodedEllStreamSuperBatch,
        EncodedExactBatch,
        EncodedExactSuperBatch,
    )

    if isinstance(
        prepped, (EncodedEllStreamBatch, EncodedEllStreamSuperBatch)
    ):
        return "stream"
    if isinstance(prepped, (EncodedExactBatch, EncodedExactSuperBatch)):
        return "exact"
    if isinstance(prepped, (ELLBitsBatch, ELLBitsSuperBatch)):
        return "bits"
    return "raw"


# owner-thread: consumer
class DeviceUploader:
    """Double-buffered host→device stage of the ingest pipeline.

    Issues ``upload_fn`` (a ``jax.device_put`` under the hood) for
    batch t+1 on its own thread while the consumer runs step t, so the
    host→device transfer — the pipeline's scarce resource — overlaps
    device compute instead of serializing in front of it. ``depth``
    bounds the staged-ahead window (default 2: the classic double
    buffer — one batch on the wire while one is being consumed), which
    also bounds the extra device memory pinned by staged batches.

    Donation-safety: batch buffers are only ever INPUTS to the jitted
    steps (never donated — only the table state is, via
    ``donate_argnums=(0,)``), and submission stays on the consumer
    thread under the executor's ``max_in_flight`` bound, so staging
    ahead can never alias a donated buffer.

    Exceptions from the upload thread forward to the consumer;
    ``close()`` stops and joins the thread (also called when iteration
    ends)."""

    def __init__(self, source, upload_fn, depth: int = 2):
        import collections

        from ...learner.ingest import pipeline_instruments
        from ...telemetry import spans as telemetry_spans
        from ...utils.concurrent import iter_on_thread

        tel = pipeline_instruments()
        # timeline flow hand-off: the uploader thread records each
        # staged batch's flow id (set by the ingest pipeline while this
        # thread pulled the item) in FIFO order; the consumer pops one
        # per item (iter_on_thread preserves order) so the trainer-step
        # submit can run under the SAME flow — feeder → prep pool →
        # uploader → trainer step all correlate. deque append/popleft
        # are atomic (no lock needed; single producer, single consumer).
        self._flows: "collections.deque" = collections.deque()

        def uploaded():
            from ...learner.wire import maybe_decompress

            for prepped, n in source:
                t0 = time.perf_counter()
                # staging-leg frames (wire_compress) decode HERE, on
                # the single uploader thread, immediately before the
                # device_put — the feeder half of the stateless-or-
                # feeder rule; everything below sees plain arrays and
                # uploaded_bytes stays the REALIZED link traffic
                prepped = maybe_decompress(prepped)
                fid = telemetry_spans.current_flow()
                if tel is not None:
                    tel["batches"].labels(pipeline="device_uploader").inc()
                    tel["examples"].labels(pipeline="device_uploader").inc(
                        int(prepped.num_examples)
                    )
                # sample BEFORE the upload: when upload_fn is a caching
                # uploader (learner/wire.UploadCache), leaves served
                # from the device-resident cache never cross the link —
                # uploaded_bytes must stay the REALIZED link traffic
                # (doc/OBSERVABILITY.md), so hit bytes are subtracted
                saved0 = int(getattr(upload_fn, "saved_bytes", 0))
                if telemetry_spans.get_sink() is not None:
                    # span (not a hand-built emit): an upload_fn failure
                    # still closes the event with an `error` attr, so
                    # the traced flow shows WHERE it died instead of
                    # silently ending at ingest.prep
                    with telemetry_spans.flow_scope(fid):
                        with telemetry_spans.span(
                            "ingest.upload", pipeline="device_uploader"
                        ):
                            staged = upload_fn(prepped)
                else:
                    staged = upload_fn(prepped)
                if tel is not None:
                    hit_bytes = (
                        int(getattr(upload_fn, "saved_bytes", 0)) - saved0
                    )
                    tel["uploaded_bytes"].inc(
                        max(
                            0,
                            sum(
                                int(getattr(leaf, "nbytes", 0))
                                for leaf in jax.tree.leaves(prepped)
                            )
                            - hit_bytes,
                        )
                    )
                    tel["stage_seconds"].labels(stage="upload").observe(
                        time.perf_counter() - t0
                    )
                self._flows.append(fid)
                yield staged, n

        # maxsize = depth - 1 staged in the queue + 1 held by the
        # consumer = `depth` device-staged batches in flight.
        # No locks here (pslint lock-pass scope, nothing guarded):
        # iter_on_thread owns the cross-thread queue + join contract,
        # and _it is only touched from the consumer thread.
        self._it = iter_on_thread(uploaded(), maxsize=max(1, depth - 1))

    def next_flow(self):
        """The flow id of the next yielded batch (FIFO with the item
        stream; None when tracing is off). Consumer thread only."""
        try:
            return self._flows.popleft()
        except IndexError:
            return None

    def __iter__(self):
        return self._it

    def close(self) -> None:
        self._it.close()


class AsyncSGDWorker(ISGDCompNode):
    """Fused worker+server node (ref AsyncSGDWorker + AsyncSGDServer).

    Consumes minibatches, runs the SPMD step, reports SGDProgress to the
    scheduler's monitor. max_delay>0 computes gradients on a τ-stale weight
    snapshot and keeps τ+1 steps in flight (bounded-delay consistency).
    """

    def __init__(self, conf: Config, mesh=None, name: str = "async_sgd_worker"):
        super().__init__(name=name)
        self.conf = conf
        sgd = conf.async_sgd or SGDConfig()
        self.sgd = sgd
        if mesh is None:
            mesh = self.po.mesh
        assert mesh is not None, "Postoffice.start() first"
        self.mesh = mesh
        self.loss = create_loss(conf.loss.type)
        self.penalty = create_penalty(conf.penalty.type, conf.penalty.lambda_)
        self.lr = LearningRate(
            conf.learning_rate.type, conf.learning_rate.alpha, conf.learning_rate.beta
        )
        self.updater = create_updater(
            sgd.algo, sgd.ada_grad, self.lr, self.penalty,
            ftrl_state_dtype=sgd.ftrl_state_dtype,
        )

        from ...parameter.parameter import KeyDirectory, pad_slots

        if sgd.wire not in ("", "i32", "u24", "bits", "stream"):
            raise ValueError(
                f"unknown SGDConfig.wire {sgd.wire!r}; expected "
                "'i32', 'u24', 'bits', 'stream', or '' (legacy "
                "wire_u24 flag)"
            )
        if sgd.wire_compress not in ("", "lz"):
            raise ValueError(
                f"unknown SGDConfig.wire_compress {sgd.wire_compress!r}; "
                "expected '' or 'lz'"
            )
        from ...learner.wire import WIRE_ENCODE_MODES

        if sgd.wire_encode not in WIRE_ENCODE_MODES:
            raise ValueError(
                f"unknown SGDConfig.wire_encode {sgd.wire_encode!r}; "
                f"expected one of {WIRE_ENCODE_MODES}"
            )
        if sgd.wire_cache_mb < 0:
            raise ValueError(
                f"SGDConfig.wire_cache_mb must be >= 0, got {sgd.wire_cache_mb}"
            )
        # FIXING_FLOAT push/pull filters → n-byte quantized wire inside the
        # fused step (KEY_CACHING needs no device work here — streaming
        # minibatches never repeat key sets, and darlin keeps its blocks
        # device-resident outright; SPARSE's zero-masking is folded into
        # the quantized paths)
        self._push_quant = _fixing_float_bytes(sgd.push_filter, "push_filter")
        self._pull_quant = _fixing_float_bytes(sgd.pull_filter, "pull_filter")
        # ADD_NOISE push filter -> device-side per-worker gradient noise
        self._push_noise = _add_noise_params(sgd.push_filter)
        self._pull_noise = _add_noise_params(sgd.pull_filter)
        try:
            self._pull_narrow = {
                "auto": None, "narrow": True, "wide": False
            }[sgd.pull_gather]
        except KeyError:
            raise ValueError(
                f"unknown SGDConfig.pull_gather {sgd.pull_gather!r}; "
                "expected 'auto', 'narrow', or 'wide'"
            ) from None
        self._seed_counter = 0
        self._warned_ell_overflow = False
        self._warned_scan_fallback = False
        self._warned_stream_multiproc = False
        # stream-wire statics: derived ONCE from the first batch on the
        # feeder/trainer thread and pinned (the `_padding` pattern), so
        # every pool worker encodes against the same decode program.
        # None after derivation = no lane-dictionary split wins on this
        # data → the run stays on the plain bits wire.
        self._stream_statics = None
        self._stream_statics_set = False
        self.num_slots = pad_slots(sgd.num_slots, meshlib.num_servers(mesh))
        self._update_mode = self._resolve_update_mode(sgd)
        # the hash modulus is the CONFIGURED slot count, not the padded
        # table size: padding depends on the server count, and keys must
        # keep their slots across elastic resizes (the reference's key
        # space is likewise fixed while server key ranges move,
        # manager.cc NodeAdd / Range::EvenDivide). Padded tail slots are
        # storage only — never addressed.
        self.directory = KeyDirectory(sgd.num_slots, hashed=True)
        # direct-to-sharded init (no transient whole-array copy — the
        # 2^30-table OOM lesson; rationale at meshlib.init_sharded)
        self.state = meshlib.init_sharded(
            lambda: self.updater.init(self.num_slots), mesh
        )
        # step functions cached per (encoding, binary, with_aux)
        self._steps: Dict[Tuple[str, bool, bool], object] = {}
        # no-donate: weights_dense derives FROM the live state, which
        # keeps training afterwards
        self._weights_fn = jax.jit(self.updater.weights)
        # max_delay=0 still bounds in-flight work to one step ahead — 0 here
        # would mean *unbounded* (executor semantics), pinning every metrics
        # future in memory
        self.executor.max_in_flight = max(0, sgd.max_delay) + 1
        self._pull_state = self.state
        self._steps_since_snapshot = 0
        # ongoing replication (ref Parameter::SetReplica, executor.cc
        # num_replicas_): every replica_every steps the whole table rolls
        # one shard right, so shard s's segment is mirrored in shard s+1's
        # HBM — a dead shard loses ≤ replica_every steps
        self._replica_state = None
        self._steps_since_replica = 0
        if sgd.num_replicas > 0:
            per = self.num_slots // meshlib.num_servers(mesh)

            def _roll(state):
                return jax.tree.map(
                    lambda x: jnp.roll(x, per, axis=0) if x.ndim >= 1 else x,
                    state,
                )

            self._replicate_fn = jax.jit(_roll, donate_argnums=())
        else:
            self._replicate_fn = None
        self._pads: Optional[Tuple[int, int, int]] = None
        self._num_shards_cache: Optional[int] = None
        self.progress = SGDProgress()
        # learning truth plane (telemetry/learning.py): realized
        # staleness per submission, key heat folded by server key
        # range, convergence metering in collect(). Created fresh per
        # worker so it binds the CURRENT default registry.
        from ...telemetry import registry as telemetry_registry

        if telemetry_registry.enabled():
            from ...telemetry import learning as learning_mod

            self._learning = learning_mod.plane(
                self.name,
                num_slots=self.num_slots,
                num_shards=meshlib.num_servers(mesh),
                max_delay=max(0, sgd.max_delay),
            )
        self._heat_counter = 0  # feeder/trainer thread only
        self._snapshot_ts: Optional[int] = None  # submit thread only
        # -- self-driving consistency (learner/consistency.py) --
        # live effective τ: SGDConfig.max_delay is the CAP; the
        # adaptive controller moves this between submissions. Plain
        # int, single-writer (the collect thread via set_effective_tau)
        # / read by the submit thread — int rebinding is atomic and
        # the value is advisory scheduling state, never a shape.
        self._effective_tau = max(0, sgd.max_delay)
        self._tau_adaptive = bool(sgd.tau_adaptive)
        self._significance = None
        if sgd.kkt_filter:
            if self._update_mode != "sparse":
                raise ValueError(
                    "SGDConfig.kkt_filter requires update='sparse' (the "
                    "mask is defined on the globally-deduped unique-slot "
                    f"vectors); resolved update mode is "
                    f"{self._update_mode!r}"
                )
            if sgd.algo != "ftrl" or getattr(
                self.penalty, "lambda1", 0.0
            ) <= 0.0:
                raise ValueError(
                    "SGDConfig.kkt_filter derives its threshold from the "
                    "FTRL proximal dead zone: algo='ftrl' and an L1 "
                    "penalty (lambda1 > 0) are required"
                )
            if sgd.kkt_drop_after > 0 and sgd.ingest_workers != 1:
                # the drop set evolves in collect order; a concurrent
                # prep pool would apply it in racy, nondeterministic
                # order (the stateless-or-feeder rule). ingest_workers
                # defaults to 0 ("auto", multi-worker) — require the
                # explicit serial setting.
                raise ValueError(
                    "SGDConfig.kkt_drop_after > 0 (host-side key drop) "
                    "requires the serial prep path: set ingest_workers=1"
                )
            from ...ops.significance import SignificanceSpec

            self._significance = SignificanceSpec(
                l1=float(self.penalty.lambda1),
                margin=float(sgd.kkt_margin),
                escape=float(sgd.kkt_escape),
                feedback=sgd.kkt_drop_after > 0,
            )
        if sgd.tau_adaptive or sgd.kkt_filter:
            from ...learner.consistency import ConsistencyRuntime

            self._consistency = ConsistencyRuntime.from_config(self, sgd)

    def set_effective_tau(self, tau: int) -> int:
        """Move the live bounded-delay τ (between submissions; the
        adaptive controller's actuator). Clamped to [0, max_delay] —
        the configured value stays the contract CAP, so realized
        staleness under any live τ also satisfies the configured bound.
        Never recompiles: τ only schedules snapshot refreshes (a host
        counter), and adaptive mode pins one step executable."""
        tau = int(min(max(0, self.sgd.max_delay), max(0, int(tau))))
        self._effective_tau = tau
        if self._learning is not None:
            self._learning.set_tau(tau)
        return tau

    def _resolve_update_mode(self, sgd: SGDConfig) -> str:
        """``SGDConfig.update`` → concrete formulation. "auto" flips to
        sparse at big per-server shards (sparse_update_min_slots)
        unless push/pull filters are configured — those are defined on
        dense shard vectors, so auto quietly stays dense; an EXPLICIT
        "sparse" + filters is a config error (raised in the builder)."""
        mode = sgd.update or "auto"
        if mode not in ("auto", "dense", "sparse"):
            raise ValueError(
                f"unknown SGDConfig.update {mode!r}; expected "
                "'auto', 'dense', or 'sparse'"
            )
        filtered = bool(
            self._push_quant or self._pull_quant
            or self._push_noise or self._pull_noise
        )
        from ...parallel import distributed

        multi = distributed.is_multiprocess()
        if mode == "auto":
            shard = self.num_slots // meshlib.num_servers(self.mesh)
            if (
                shard >= sparse_update_min_slots()
                and not filtered
                and not multi
            ):
                return "sparse"
            return "dense"
        if mode == "sparse" and multi:
            # each host preps its own data partition, so hosts would
            # build DIFFERENT global-unique slot tables and the
            # elementwise gradient psum would misalign
            raise ValueError(
                "update='sparse' is single-process for now; multi-host "
                "big tables shard the dense update over servers instead"
            )
        return mode

    def _ingest_workers(self) -> int:
        """Prep-pool width for the pipelined train path.
        ``SGDConfig.ingest_workers`` wins when set; the default scales
        to the host: cores-1 (capped at 4) so the feeder thread (parse
        + filter) and the trainer keep a core to breathe on — on a
        2-core host that is ONE prep worker, which still moves all
        localize/pack work off this thread (doc/PERFORMANCE.md,
        "Host-ingest pipeline")."""
        if self.sgd.ingest_workers > 0:
            return self.sgd.ingest_workers
        return max(1, min(4, (os.cpu_count() or 2) - 1))

    def _num_shards(self) -> int:
        """Data shards THIS process preps. Single-process: the whole data
        axis. Multi-process: only the rows this host's devices own — each
        host localizes its own file partition (ref DataAssigner) and the
        shards assemble into one global batch in :meth:`upload`.
        Cached: the mesh is fixed for the worker's lifetime and the walk
        is O(mesh size), too slow for the per-minibatch prep path."""
        if self._num_shards_cache is None:
            from ...parallel import distributed

            if distributed.is_multiprocess():
                self._num_shards_cache = distributed.local_data_shards(self.mesh)
            else:
                self._num_shards_cache = meshlib.num_workers(self.mesh)
        return self._num_shards_cache

    def _padding(self, batch: SparseBatch) -> Tuple[int, int, int]:
        if self._pads is None:
            from ...parallel import distributed

            d = self._num_shards()
            if distributed.is_multiprocess():
                # every process must jit the SAME shapes or the collectives
                # mismatch: derive padding from config (identical on all
                # hosts), never from this host's first batch
                rows = self.sgd.rows_pad or -(-self.sgd.minibatch // d)
                if self.sgd.ell_lanes > 0:
                    nnz = self.sgd.nnz_pad or rows * self.sgd.ell_lanes
                elif self.sgd.nnz_pad:
                    nnz = self.sgd.nnz_pad
                else:
                    raise ValueError(
                        "multi-process runs need SGDConfig.nnz_pad set "
                        "explicitly (auto-sizing from the first local batch "
                        "would give each host different compiled shapes)"
                    )
                self._pads = (rows, nnz, nnz)
                return self._pads
            rows = self.sgd.rows_pad or -(-batch.n // d)
            per_nnz = -(-batch.nnz // d)
            # tight padding: 25% headroom rounded to 4k — transfer bytes are
            # the pipeline's scarce resource, not compile-shape variety
            nnz = self.sgd.nnz_pad or max(4096, -(-int(per_nnz * 1.25) // 4096) * 4096)
            self._pads = (rows, nnz, nnz)
        return self._pads

    def _note_heat(self, batch: SparseBatch) -> None:
        """Key-heat feed (learning truth plane): hash this batch's keys
        to table slots and fold them into the worker's windowed count
        sketch + per-shard load shares. Called ONLY from the
        feeder/trainer thread (the sketch is stateful — the
        stateless-or-feeder ingest rule), sampled every
        ``plane.heat_every`` batches so the feeder never stalls on it;
        the hash is the same vectorized murmur the prep pays."""
        lp = self._learning
        if lp is None or not batch.n:
            return
        self._heat_counter += 1
        if self._heat_counter % lp.heat_every:
            return
        lp.note_slots(self.directory.slots(np.asarray(batch.indices)))

    def process_minibatch(self, batch: SparseBatch, report: bool = True) -> int:
        """Pull → gradient → push, one async step (ref UpdateModel inner loop
        + ComputeGradient)."""
        self._note_heat(batch)
        return self._submit_prepped(self.prep(batch, device_put=False))

    def upload(self, prepped):
        """Host-prepped shards → device arrays. Multi-process: assemble
        this host's shards into the global data-sharded batch (the data
        axis sits at dim 1 for scan superbatches, after the T axis).
        Staging-leg frames (wire_compress) decode here, immediately
        before device placement — the uploader half of the
        stateless-or-feeder rule."""
        from ...learner.wire import (
            EncodedEllStreamSuperBatch,
            EncodedExactSuperBatch,
            maybe_decompress,
        )
        from ...parallel import distributed

        prepped = maybe_decompress(prepped)
        axis_dim = (
            1
            if isinstance(
                prepped,
                (
                    ELLBitsSuperBatch,
                    PreppedSuperBatch,
                    EncodedExactSuperBatch,
                    EncodedEllStreamSuperBatch,
                ),
            )
            else 0
        )
        return distributed.global_from_local(self.mesh, prepped, axis_dim=axis_dim)

    def _maybe_encode(self, out):
        """Compact-wire encode for exact-wire (PreppedBatch) preps —
        STATELESS (pool-safe prep stage, the PR-3 ingest rule); falls
        back to the raw wire when the batch lies outside a verified
        encoding domain, so the wire is never wrong, only fat."""
        if not self.sgd.wire_encode:
            return out
        from ...learner.wire import encode_exact

        enc = encode_exact(out, self.num_slots, mode=self.sgd.wire_encode)
        return out if enc is None else enc

    def _get_stream_statics(self, batch: SparseBatch):
        """Pinned stream-wire statics, derived from the FIRST eligible
        batch (like ``_padding``: pinned on the feeder/trainer thread
        before parallel preps could race to different lane splits).
        None = the lane-dictionary wire never wins on this data — the
        run stays on the bits wire."""
        if not self._stream_statics_set:
            from ...learner.wire import derive_stream_statics

            counts = np.diff(batch.indptr)
            if (
                batch.binary
                and batch.n
                and (counts == self.sgd.ell_lanes).all()
            ):
                self._stream_statics = derive_stream_statics(
                    batch.indices,
                    self.sgd.ell_lanes,
                    self.directory.num_slots,
                    self.num_slots,
                )
                self._stream_statics_set = True
        return self._stream_statics

    def prep(self, batch: SparseBatch, device_put: bool = True):
        """Localize+pad a batch for this worker (producer-thread safe)."""
        if self._consistency is not None:
            # host-side significance drop (learner/consistency.py):
            # persistently-suppressed slots leave the batch BEFORE
            # dedup/padding, so they never cost upload keys or bytes.
            # A no-op unless kkt_drop_after > 0 (serial prep enforced
            # at init — the drop set evolves in collect order).
            batch = self._consistency.filter_batch(batch, self.directory)
        rows_pad, nnz_pad, uniq_pad = self._padding(batch)
        num_shards = self._num_shards()
        if self._update_mode == "sparse":
            # the sparse row-update needs globally slot-unique batches
            # (scatter-set correctness) — one shared dedup table for
            # all data shards, regardless of wire/ELL settings. Padded
            # to a (8,128)-tileable length so the row-apply can take
            # the Pallas kernel.
            uniq = min(nnz_pad * num_shards, self.num_slots)
            uniq = -(-uniq // 1024) * 1024
            out = self._maybe_encode(prep_batch_shared(
                batch, self.directory, num_shards, rows_pad, nnz_pad,
                uniq, self.num_slots,
            ))
            return self.upload(out) if device_put else out
        out = None
        use_ell = self.sgd.ell_lanes > 0 and self.directory.hashed
        if use_ell and batch.n:
            # ELL truncation guard (the reference never drops features): a
            # row wider than the lane budget falls back to the hashed COO
            # path — except multiprocess, where a per-host program change
            # would desync the collectives, so fail loudly instead
            max_row = int(np.diff(batch.indptr).max())
            if max_row > self.sgd.ell_lanes:
                from ...parallel import distributed

                if distributed.is_multiprocess():
                    raise ValueError(
                        f"row with {max_row} features exceeds ell_lanes="
                        f"{self.sgd.ell_lanes}; raise ell_lanes (the wire "
                        "format must be identical on every host)"
                    )
                if not self._warned_ell_overflow:
                    import logging

                    logging.getLogger(__name__).warning(
                        "batch has a %d-feature row > ell_lanes=%d; "
                        "falling back to the hashed COO path (no features "
                        "dropped, ELL fast path disabled for such batches)",
                        max_row, self.sgd.ell_lanes,
                    )
                    self._warned_ell_overflow = True
                use_ell = False
        if use_ell:
            wire = self.sgd.wire or ("u24" if self.sgd.wire_u24 else "i32")
            if wire == "stream":
                from ...parallel import distributed

                if distributed.is_multiprocess():
                    # statics are DATA-derived (which lanes take the
                    # dictionary) — per-host derivation could compile
                    # different programs and desync the collectives, so
                    # multi-process runs keep the uniform bits wire
                    if not self._warned_stream_multiproc:
                        import logging

                        logging.getLogger(__name__).warning(
                            "wire='stream' is single-process (its lane "
                            "split is derived from data); multi-process "
                            "runs use the bits wire"
                        )
                        self._warned_stream_multiproc = True
                    wire = "bits"
                else:
                    out = prep_batch_ell_stream(
                        batch,
                        self.directory,
                        num_shards,
                        rows_pad,
                        self.sgd.ell_lanes,
                        self.num_slots,
                        self._get_stream_statics(batch),
                    )
                    if out is None:
                        wire = "bits"  # raw fallback: never wrong bytes
            if out is None and wire == "bits":
                out = prep_batch_ell_bits(
                    batch,
                    self.directory,
                    num_shards,
                    rows_pad,
                    self.sgd.ell_lanes,
                    self.num_slots,
                )
                if out is None:
                    from ...parallel import distributed

                    if distributed.is_multiprocess():
                        # a silent per-host fallback would jit DIFFERENT
                        # step programs on different hosts -> collective
                        # mismatch/hang; the wire must be uniform
                        raise ValueError(
                            "wire='bits' needs binary features, uniform "
                            f"{self.sgd.ell_lanes}-lane rows and ±1 labels "
                            "on every host; this host's batch does not "
                            "qualify — use wire='u24' for this data"
                        )
                    wire = "u24"  # non-uniform/valued batch: sentinel wire
            if out is None:
                out = prep_batch_ell(
                    batch,
                    self.directory,
                    num_shards,
                    rows_pad,
                    self.sgd.ell_lanes,
                    self.num_slots,
                    pack=wire == "u24" and self.num_slots < (1 << 24),
                )
        elif self.directory.hashed:
            out = prep_batch_hashed(
                batch,
                self.directory,
                num_shards,
                rows_pad,
                nnz_pad,
                self.num_slots,
            )
        else:
            out = self._maybe_encode(prep_batch(
                batch,
                self.directory,
                num_shards,
                rows_pad,
                nnz_pad,
                uniq_pad,
                self.num_slots,
            ))
        return self.upload(out) if device_put else out

    def _get_step(self, prepped, with_aux: bool):
        from ...learner.wire import (
            EncodedEllStreamBatch,
            EncodedEllStreamSuperBatch,
            EncodedExactBatch,
            EncodedExactSuperBatch,
        )

        if isinstance(prepped, EncodedEllStreamSuperBatch):
            key = ("ell_stream_scan", (prepped.steps, prepped.static_key()),
                   with_aux)
            builder = lambda: make_train_step_ell_stream_scan(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                static_key=prepped.static_key(), with_aux=with_aux,
                push_quant=self._push_quant, pull_quant=self._pull_quant,
                push_noise=self._push_noise, pull_noise=self._pull_noise,
                pull_narrow=self._pull_narrow,
            )
        elif isinstance(prepped, EncodedEllStreamBatch):
            key = ("ell_stream", prepped.static_key(), with_aux)
            builder = lambda: make_train_step_ell_stream(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                static_key=prepped.static_key(), with_aux=with_aux,
                push_quant=self._push_quant, pull_quant=self._pull_quant,
                push_noise=self._push_noise, pull_noise=self._pull_noise,
                pull_narrow=self._pull_narrow,
            )
        elif isinstance(prepped, EncodedExactSuperBatch):
            key = (
                "exact_enc_scan",
                (prepped.steps, prepped.static_key(), self._update_mode),
                with_aux,
            )
            builder = lambda: make_train_step_encoded_scan(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                with_aux=with_aux, push_quant=self._push_quant,
                pull_quant=self._pull_quant, push_noise=self._push_noise,
                pull_noise=self._pull_noise, pull_narrow=self._pull_narrow,
                update=self._update_mode, significance=self._significance,
            )
        elif isinstance(prepped, EncodedExactBatch):
            key = (
                "exact_enc",
                (prepped.static_key(), self._update_mode),
                with_aux,
            )
            builder = lambda: make_train_step_encoded(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                with_aux=with_aux, push_quant=self._push_quant,
                pull_quant=self._pull_quant, push_noise=self._push_noise,
                pull_noise=self._pull_noise, pull_narrow=self._pull_narrow,
                update=self._update_mode, significance=self._significance,
            )
        elif isinstance(prepped, PreppedSuperBatch):
            key = ("exact_scan", (prepped.steps, self._update_mode), with_aux)
            builder = lambda: make_train_step_scan(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                with_aux=with_aux, push_quant=self._push_quant,
                pull_quant=self._pull_quant, push_noise=self._push_noise,
                pull_noise=self._pull_noise, pull_narrow=self._pull_narrow,
                update=self._update_mode, significance=self._significance,
            )
        elif isinstance(prepped, ELLBitsSuperBatch):
            key = ("ell_bits_scan", (prepped.rows, prepped.steps), with_aux)
            builder = lambda: make_train_step_ell_bits_scan(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                rows=prepped.rows, lanes=self.sgd.ell_lanes, with_aux=with_aux,
                push_quant=self._push_quant, pull_quant=self._pull_quant,
                push_noise=self._push_noise, pull_noise=self._pull_noise,
                pull_narrow=self._pull_narrow,
            )
        elif isinstance(prepped, ELLBitsBatch):
            key = ("ell_bits", prepped.rows, with_aux)
            builder = lambda: make_train_step_ell_bits(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                rows=prepped.rows, lanes=self.sgd.ell_lanes, with_aux=with_aux,
                push_quant=self._push_quant, pull_quant=self._pull_quant,
                push_noise=self._push_noise, pull_noise=self._pull_noise,
                pull_narrow=self._pull_narrow,
            )
        elif isinstance(prepped, (ELLBatch, ELLPackedBatch)):
            packed = isinstance(prepped, ELLPackedBatch)
            key = ("ell_packed" if packed else "ell", prepped.vals is None, with_aux)
            builder = lambda: make_train_step_ell(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                binary=prepped.vals is None, with_aux=with_aux, packed=packed,
                push_quant=self._push_quant, pull_quant=self._pull_quant,
                push_noise=self._push_noise, pull_noise=self._pull_noise,
                pull_narrow=self._pull_narrow,
            )
        elif isinstance(prepped, HashedBatch):
            key = ("hashed", False, with_aux)
            builder = lambda: make_train_step_hashed(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                with_aux=with_aux, push_quant=self._push_quant,
                pull_quant=self._pull_quant, push_noise=self._push_noise,
                pull_noise=self._pull_noise,
                pull_narrow=self._pull_narrow,
            )
        else:
            key = ("exact", self._update_mode, with_aux)
            builder = lambda: make_train_step(  # noqa: E731
                self.updater, self.loss, self.mesh, self.num_slots,
                with_aux=with_aux, push_quant=self._push_quant,
                pull_quant=self._pull_quant, push_noise=self._push_noise,
                pull_noise=self._pull_noise,
                pull_narrow=self._pull_narrow,
                update=self._update_mode,
                significance=self._significance,
            )
        if key not in self._steps:
            self._steps[key] = builder()
        return self._steps[key]

    def _submit_prepped(self, prepped, with_aux: bool = True) -> int:
        """Dispatch one SPMD step on an already-localized batch.

        ``with_aux=False`` skips the per-example xw/y/mask outputs (host AUC)
        — the cheap mode for throughput-critical loops.
        """
        from ...parallel import distributed

        if distributed.is_multiprocess() and any(
            isinstance(leaf, np.ndarray) for leaf in jax.tree.leaves(prepped)
        ):
            # host shards can't be auto-sharded across processes by jit;
            # assemble the global batch explicitly
            prepped = self.upload(prepped)
        from ...learner.wire import (
            EncodedEllStreamSuperBatch,
            EncodedExactSuperBatch,
        )

        # the LIVE bounded-delay τ (== SGDConfig.max_delay unless the
        # adaptive controller moved it; always <= the configured cap)
        tau = self._effective_tau
        # a scan superbatch advances the weights n_steps times in one
        # submission (staleness 0 inside it — within any delay bound)
        n_steps = (
            prepped.steps
            if isinstance(
                prepped,
                (
                    ELLBitsSuperBatch,
                    PreppedSuperBatch,
                    EncodedExactSuperBatch,
                    EncodedEllStreamSuperBatch,
                ),
            )
            else 1
        )
        # snapshot *scheduling* happens at submit time (deterministic in
        # submission order), but the snapshot itself must be taken when the
        # step RUNS on the executor's dispatch thread — self.state is only
        # advanced there, and steps execute in submission order
        do_snapshot = tau <= 0 or self._steps_since_snapshot >= tau
        # realized staleness of THIS submission, in ministeps: how far
        # its weight snapshot lags the apply clock. A snapshot-taking
        # step applies against the snapshot it itself pulls (staleness
        # 0); otherwise the snapshot is _steps_since_snapshot ministeps
        # old. Steps run in submission order (no deps → the executor's
        # ready heap dispatches by timestamp), so the submit-time value
        # IS the realized one.
        staleness = 0 if do_snapshot else self._steps_since_snapshot
        if do_snapshot:
            self._steps_since_snapshot = 0
        step_fn = self._get_step(prepped, with_aux)
        self._seed_counter += n_steps
        seed = np.uint32(self._seed_counter - (n_steps - 1))

        def step():
            if do_snapshot:
                self._pull_state = self.state
            # donate_ok: with max_delay == 0 every step snapshots, so the
            # pull snapshot never outlives this call and the live table
            # can be donated (halves table HBM footprint). Adaptive τ
            # pins the NON-donated variant even at τ=0: the donated and
            # non-donated programs are different executables, and a
            # controller clamping τ to 0 mid-run must never buy the
            # donation with a recompile (the τ-sweep zero-recompile
            # regression pin, tests/test_consistency.py)
            donated = tau <= 0 and not self._tau_adaptive
            new_state, metrics = step_fn(
                self.state, self._pull_state, prepped, seed,
                donate_ok=donated,
            )
            self.state = new_state
            if donated:
                # the donated call consumed the buffer _pull_state points
                # at; re-anchor the snapshot on the newest state so a
                # LATER max_delay change never reads a deleted buffer
                # (staleness 0 satisfies any future bound)
                self._pull_state = new_state
            if self._replicate_fn is not None:
                self._steps_since_replica += n_steps
                if (
                    self._replica_state is None
                    or self._steps_since_replica >= self.sgd.replica_every
                ):
                    self._steps_since_replica = 0
                    self._replica_state = self._replicate_fn(self.state)
            return metrics

        self._steps_since_snapshot += n_steps
        self._note_ftrl_dispatch(prepped, n_steps)
        ts = self.submit(step, Task())
        if self._learning is not None:
            # logical-clock stamp: the executor timestamp of the
            # snapshot-taking submission vs this one (the Executor
            # timestamps the contract is defined over)
            if do_snapshot or self._snapshot_ts is None:
                self._snapshot_ts = ts
            self._learning.note_submit(
                staleness, n_steps=n_steps,
                clock_lag=ts - self._snapshot_ts,
                tau=tau,
            )
        return ts

    def _note_ftrl_dispatch(self, prepped, n_steps: int) -> None:
        """Host-side FTRL update-path accounting (ps_ftrl_rows_total /
        ps_ftrl_update_path_total): the path is STATIC per compiled
        step (trace-time predicate), so the submit thread names it via
        the same pure predicates the trace uses — an in-jit counter
        would fire once at trace time and never again (pslint
        jit-purity). No-op for non-FTRL/non-decay updaters and while
        telemetry is off."""
        from ...ops.ftrl import _use_pallas
        from ...ops.ftrl_sparse import resolve_update_path
        from ...telemetry.instruments import cached_ftrl_instruments
        from .updaters import FTRLUpdater

        tel = cached_ftrl_instruments()
        if tel is None:
            return
        if not (
            isinstance(self.updater, FTRLUpdater)
            and self.updater.lr.type == LearningRate.DECAY
        ):
            return
        shard = self.num_slots // meshlib.num_servers(self.mesh)
        u = 0
        if self._update_mode == "sparse":
            u = int(
                getattr(prepped, "uniq_pad", 0)
                or getattr(prepped, "uslots", np.empty((0, 0))).shape[-1]
            )
        path = resolve_update_path(
            self._update_mode, on_tpu=_use_pallas(), shard=shard, u=u,
            bf16_n=self.updater.sqrt_n_dtype == jnp.bfloat16,
            has_seed=True,  # _submit_prepped always threads a seed
        )
        rows = u if self._update_mode == "sparse" else shard
        tel["path"].labels(path=path).inc(n_steps)
        tel["rows"].inc(rows * n_steps)

    def _submit_fused(self, prepped: List[ELLBitsBatch], with_aux: bool) -> int:
        """The one fused-submit path both grouping APIs share."""
        return self._submit_prepped(
            self.upload(stack_bits_batches(prepped)), with_aux=with_aux
        )

    def submit_superbatch(
        self, batches: List[SparseBatch], with_aux: bool = False
    ) -> int:
        """Prep + stack T minibatches and run them as ONE scan-fused
        device launch (see ELLBitsSuperBatch). Requires the bits wire —
        raises on ineligible batches (the training loop's submit_group is
        the tolerant variant)."""
        from ...learner.wire import (
            EncodedEllStreamBatch,
            EncodedExactBatch,
            stack_encoded_batches,
            stack_stream_batches,
        )

        prepped = [self.prep(b, device_put=False) for b in batches]
        if all(isinstance(p, ELLBitsBatch) for p in prepped):
            return self._submit_fused(prepped, with_aux)
        if all(isinstance(p, EncodedEllStreamBatch) for p in prepped) and (
            len({p.static_key() for p in prepped}) == 1
        ):
            return self._submit_prepped(
                self.upload(stack_stream_batches(prepped)),
                with_aux=with_aux,
            )
        # exact-wire (raw or compact-encoded) scan fusion is SPARSE-
        # update only, same gate and rationale as _prep_group: the scan
        # runs ministeps on the live state (staleness 0), which is
        # sparse mode's contract but would silently drop dense mode's
        # snapshot-pull / per-ministep filter semantics (ADVICE r5)
        if self._update_mode == "sparse":
            if all(isinstance(p, PreppedBatch) for p in prepped):
                return self._submit_prepped(
                    self.upload(stack_prepped_batches(prepped)),
                    with_aux=with_aux,
                )
            if all(isinstance(p, EncodedExactBatch) for p in prepped) and (
                len({p.static_key() for p in prepped}) == 1
            ):
                # compact-wire superbatch: decode rides inside the scan
                return self._submit_prepped(
                    self.upload(stack_encoded_batches(prepped)),
                    with_aux=with_aux,
                )
        raise ValueError(
            "superbatch needs the bits wire (hashed directory, binary "
            "uniform-row batches) or the exact wire in sparse-update "
            "mode (dense-mode exact groups run per-minibatch: the scan "
            "would bypass snapshot/filter semantics); got a "
            "mixed/fallback encoding or a dense-mode exact group"
        )

    def _prep_group(self, batches: List[SparseBatch]):
        """Host side of tolerant grouping (prep + stack, no device
        work ordering constraints — safe to run on a pipeline thread):
        one scan superbatch when every batch takes the bits wire, else
        per-minibatch parts. Returns ``[(host_prepped, n_ministeps)]``.
        With ``wire_compress`` set, every emitted part's leaves are
        framed through the staging-leg codec here — ON the pool
        (stateless), decoded on the uploader thread by ``upload``."""
        from ...learner.wire import (
            EncodedEllStreamBatch,
            EncodedExactBatch,
            stack_encoded_batches,
            stack_stream_batches,
        )

        prepped = [self.prep(b, device_put=False) for b in batches]
        if len(prepped) > 1 and all(
            isinstance(p, EncodedEllStreamBatch) for p in prepped
        ) and len({p.static_key() for p in prepped}) == 1:
            return self._maybe_compress(
                [(stack_stream_batches(prepped), len(prepped))]
            )
        if len(prepped) > 1 and all(
            isinstance(p, ELLBitsBatch) for p in prepped
        ):
            return self._maybe_compress(
                [(stack_bits_batches(prepped), len(prepped))]
            )
        # exact-wire (raw or compact-encoded) scan fusion is gated on
        # SPARSE update mode: make_train_step_scan runs every ministep
        # against the LIVE state (`del pulled`, staleness 0), which is
        # sparse mode's documented contract but would silently change
        # dense-mode semantics (snapshot pulls every max_delay steps,
        # push/pull filters per ministep) — dense exact-wire groups
        # stay per-minibatch (ADVICE round 5).
        if len(prepped) > 1 and self._update_mode == "sparse":
            if all(isinstance(p, PreppedBatch) for p in prepped):
                return self._maybe_compress(
                    [(stack_prepped_batches(prepped), len(prepped))]
                )
            if all(isinstance(p, EncodedExactBatch) for p in prepped) and (
                len({p.static_key() for p in prepped}) == 1
            ):
                return self._maybe_compress(
                    [(stack_encoded_batches(prepped), len(prepped))]
                )
        if len(prepped) > 1 and not self._warned_scan_fallback:
            import logging

            logging.getLogger(__name__).info(
                "steps_per_launch=%d requested but the batch group is not "
                "bits-wire eligible (needs hashed directory + binary "
                "uniform rows); running per-minibatch steps",
                self.sgd.steps_per_launch,
            )
            self._warned_scan_fallback = True
        return self._maybe_compress([(p, 1) for p in prepped])

    def _maybe_compress(self, parts):
        """Staging-leg codec for emitted prep parts (``wire_compress``):
        stateless frame encode on the pool; ``upload`` decodes on the
        uploader thread right before device placement. Off = identity."""
        if not self.sgd.wire_compress:
            return parts
        from ...learner.wire import compress_batch

        return [
            (compress_batch(p, encoding=_wire_encoding_name(p)), n)
            for p, n in parts
        ]

    def submit_group(self, batches: List[SparseBatch], with_aux: bool = True):
        """Tolerant grouping for the training loop: scan-fuse when every
        batch takes the bits wire, fall back to per-minibatch steps
        otherwise (ragged rows, valued features, ...). Returns
        ``[(timestamp, n_ministeps), ...]`` so callers can bound
        in-flight work in MINISTEPS, not launches."""
        return [
            (self._submit_prepped(self.upload(p), with_aux=with_aux), n)
            for p, n in self._prep_group(batches)
        ]

    # collect: inherited from ISGDCompNode (shared worker plumbing, incl.
    # the scan-superstep per-ministep AUC layout)

    def train(
        self,
        batches: Iterator[SparseBatch],
        pipelined: "bool | None" = None,
    ) -> SGDProgress:
        """Drive a pass over an iterator of minibatches.

        With ``steps_per_launch > 1`` (and the bits wire) minibatches are
        grouped into scan-fused supersteps — one device launch per T
        steps; a trailing group smaller than T still runs (its own scan
        length). Weights advance every ministep either way.

        ``pipelined`` (default: on when T > 1) moves prep + stack +
        device staging onto a daemon thread behind a bounded queue, so
        localization CPU time and the host→device wire overlap the
        device steps this thread is collecting — the same three-stage
        split bench.py's timed loops use, and the TPU twin of the
        reference's MinibatchReader producer/consumer overlap
        (src/learner/sgd.h:60-143). Submission still happens HERE, in
        order, so seeds, snapshot scheduling (max_delay), and therefore
        the entire trajectory are bit-identical to the unpipelined
        path (asserted in tests)."""
        T = max(1, self.sgd.steps_per_launch)
        if pipelined is None:
            pipelined = T > 1
        try:
            return self._train_impl(batches, T, pipelined)
        except BaseException:
            # a poisoned reader or mid-run failure must not leave
            # in-flight device steps behind: interpreter teardown would
            # kill the executor thread inside a C++ device wait
            # ('terminate called / FATAL: exception not rethrown')
            import contextlib

            with contextlib.suppress(Exception):
                self.executor.wait_all(pop=False)
            raise

    def _train_impl(
        self, batches: Iterator[SparseBatch], T: int, pipelined: bool
    ) -> SGDProgress:
        pending: List[Tuple[int, int]] = []  # (ts, n_ministeps)
        # backpressure in MINISTEPS (aux memory scales with them), while
        # always allowing at least one full launch in flight
        bound = max(T, self.sgd.max_delay + 1)

        if pipelined:
            # staged ingest (learner/ingest.py): grouping runs on the
            # pipeline's feeder thread, localize/pack fans out over the
            # ordered prep pool, and the double-buffered DeviceUploader
            # issues the device_put for batch t+1 while step t runs —
            # prep_batch work leaves this thread entirely. No
            # submission off-thread: ordered device dispatch (seeds,
            # snapshot schedule) stays HERE, so the trajectory is
            # bit-identical to the serial path.
            from ...learner.ingest import IngestPipeline

            def grouped():
                group: List[SparseBatch] = []
                for batch in batches:
                    # padding is derived from the FIRST batch exactly as
                    # on the serial path — pin it before parallel preps
                    # could race to different pads
                    if self._pads is None:
                        self._padding(batch)
                    # same for the stream wire's lane-split statics:
                    # pinned here on the feeder, before the pool forks
                    if (
                        self.sgd.wire == "stream"
                        and not self._stream_statics_set
                    ):
                        self._get_stream_statics(batch)
                    # key heat rides the FEEDER thread (this generator
                    # runs on the ingest pipeline's feeder) — the
                    # stateless-or-feeder home for the stateful sketch
                    self._note_heat(batch)
                    group.append(batch)
                    if len(group) >= T:
                        yield group
                        group = []
                if group:
                    yield group

            workers = self._ingest_workers()
            pipe = IngestPipeline(
                grouped(),
                prep_fn=self._prep_group,
                workers=workers,
                # the in-flight window must scale with the pool or the
                # extra workers idle (the pool admits at most `capacity`
                # groups); each staged group holds T prepped host
                # batches, so this is also the host-memory bound
                capacity=2 * workers,
                name="train_ingest",
            ).start()

            def flattened():
                for parts in pipe:
                    yield from parts

            # upload key caching (learner/wire.UploadCache): stateful,
            # so it lives on the uploader's serial thread (the PR-3
            # stateless-or-feeder ingest rule), never in the prep pool.
            # Multi-process keeps the plain path — global batch
            # assembly owns placement there.
            upload_fn = self.upload
            if self.sgd.wire_cache_mb > 0:
                from ...parallel import distributed

                if not distributed.is_multiprocess():
                    from ...learner.wire import UploadCache

                    upload_fn = UploadCache(
                        max_bytes=self.sgd.wire_cache_mb << 20
                    )
            uploader = DeviceUploader(flattened(), upload_fn, depth=2)
            try:
                from ...telemetry import spans as telemetry_spans

                for staged_batch, n in uploader:
                    # submit under the batch's flow id (popped FIFO from
                    # the uploader) so the executor.step span correlates
                    # back through upload → prep → read in the timeline
                    with telemetry_spans.flow_scope(uploader.next_flow()):
                        pending.append(
                            (self._submit_prepped(
                                staged_batch, with_aux=True),
                             n)
                        )
                    while sum(n for _, n in pending) > bound:
                        self.collect(pending.pop(0)[0])
            finally:
                # close BEFORE the exception propagates out of this
                # frame: the traceback would otherwise pin the
                # generators (and their pipeline threads) alive past
                # train()'s cleanup, letting teardown kill a thread
                # mid-device-call
                uploader.close()
                pipe.close()
            for ts, _ in pending:
                self.collect(ts)
            return self.progress

        group: List[SparseBatch] = []

        def flush_group():
            if not group:
                return
            pending.extend(self.submit_group(list(group), with_aux=True))
            group.clear()

        for batch in batches:
            self._note_heat(batch)
            group.append(batch)
            if len(group) >= T:
                flush_group()
            # collect finished steps opportunistically to keep memory flat
            while sum(n for _, n in pending) > bound:
                self.collect(pending.pop(0)[0])
        flush_group()
        for ts, _ in pending:
            self.collect(ts)
        return self.progress

    def weights_dense(self) -> np.ndarray:
        # drain in-flight steps (state advances on the executor thread)
        # WITHOUT popping: metrics stay claimable by a later collect()
        self.executor.wait_all(pop=False)
        return np.asarray(self._weights_fn(self.state))

    def recover_server_shard(self, shard: int) -> bool:
        """Rebuild a dead server shard's slot segment from the live
        neighbor replica (ref Parameter::Recover pulling the dead node's
        key segment from kReplicaGroup). The restored segment is at most
        ``replica_every`` steps stale. Submitted through the executor so
        it is ordered with in-flight training steps."""
        if self._replica_state is None:
            return False
        n_servers = meshlib.num_servers(self.mesh)
        per = self.num_slots // n_servers

        def do_recover():
            seg = (jnp.arange(self.num_slots) // per) == shard

            def fix(prim, rep):
                if getattr(prim, "ndim", 0) < 1:
                    return prim
                recovered = jnp.roll(rep, -per, axis=0)
                m = seg.reshape((-1,) + (1,) * (prim.ndim - 1))
                return jnp.where(m, recovered, prim)

            self.state = jax.tree.map(fix, self.state, self._replica_state)
            self._pull_state = self.state
            return True

        ts = self.submit(do_recover)
        return bool(self.executor.wait(ts))

    def wipe_server_shard(self, shard: int) -> None:
        """Test/chaos helper: zero a shard's slot segment, simulating a
        replacement server that boots empty (ref recovery tests)."""
        n_servers = meshlib.num_servers(self.mesh)
        per = self.num_slots // n_servers

        def do_wipe():
            seg = (jnp.arange(self.num_slots) // per) == shard

            def z(prim):
                if getattr(prim, "ndim", 0) < 1:
                    return prim
                m = seg.reshape((-1,) + (1,) * (prim.ndim - 1))
                return jnp.where(m, jnp.zeros_like(prim), prim)

            self.state = jax.tree.map(z, self.state)
            self._pull_state = self.state

        self.executor.wait(self.submit(do_wipe))

    def evaluate(self, batch: SparseBatch) -> Dict[str, float]:
        """Validation metrics on a batch (ref COMPUTE_VALIDATION_AUC)."""
        w = self.weights_dense()
        slots = self.directory.slots(batch.indices)
        vals = batch.value_array()
        xw = np.zeros(batch.n, np.float32)
        contrib = np.where(slots < self.num_slots, w[np.minimum(slots, self.num_slots - 1)], 0.0)
        np.add.at(xw, batch.row_ids(), vals * contrib)
        return {
            "auc": evaluation.auc(batch.y, xw),
            "accuracy": evaluation.accuracy(batch.y, xw),
            "logloss": evaluation.logloss(batch.y, xw),
        }

    def save_model(self, path: str) -> List[str]:
        """Nonzero weights as key\\tvalue text, one file per server shard
        named ``{path}_S{k}`` (ref AsyncSGDServer::SaveModel writes
        ``file + "_" + MyNodeID()`` — example eval configs match
        ``model_S.*``). Shard k holds its owned slot range, exactly the
        device sharding of the table.

        With a hashed directory the original keys are unrecoverable, so the
        keys written are table slots and a ``#hashed <num_slots>`` header
        tells consumers (ModelEvaluation) to route lookups through the same
        hash. Exact directories write true global keys.
        """
        w = self.weights_dense()
        nz = np.flatnonzero(w)
        keys = self.directory.keys
        n_server = meshlib.num_servers(self.mesh)
        shard_size = self.num_slots // n_server
        written = []
        for s in range(n_server):
            spath = f"{path}_S{s}"
            sel = nz[(nz >= s * shard_size) & (nz < (s + 1) * shard_size)]
            with psfile.open_write(spath) as f:
                if self.directory.hashed:
                    # header modulus = the directory's CONFIGURED count
                    # (what evaluation must hash with), not the padded
                    # table size — they differ on non-divisible tables
                    f.write(f"#hashed\t{self.directory.num_slots}\n")
                    for i in sel:
                        f.write(f"{i}\t{float(w[i])!r}\n")
                else:
                    for i in sel:
                        if i < len(keys):
                            f.write(f"{keys[i]}\t{float(w[i])!r}\n")
            written.append(spath)
        return written

    # -- full-state checkpoint/resume (ref save_model_every_n_iter +
    #    Parameter::Recover: the durable analog of server replicas) --

    def state_host(self) -> dict:
        """Snapshot the full optimizer state to host memory (device->host,
        no files) — the live-migration path for elastic resizes (ref
        Parameter::GetReplica feeding manager.cc NodeAdd key-range moves)."""
        # pop=False: a mid-training snapshot must not swallow in-flight
        # steps' metrics — collect(ts) afterwards still accounts them
        self.executor.wait_all(pop=False)
        return {
            "state": jax.tree.map(np.asarray, self.state),
            "seed_counter": np.int64(self._seed_counter),
        }

    def load_state_host(self, snap: dict) -> None:
        """Install a host snapshot onto THIS worker's mesh — the receiving
        half of a live migration. The table may be padded differently
        under a different server count: the configured slots always carry
        over; only dead padding is trimmed or zero-extended."""
        def fit(leaf):
            leaf = np.asarray(leaf)
            if leaf.ndim >= 1 and leaf.shape[0] != self.num_slots:
                if leaf.shape[0] > self.num_slots:
                    leaf = leaf[: self.num_slots]
                else:
                    pad = np.zeros(
                        (self.num_slots - leaf.shape[0],) + leaf.shape[1:],
                        leaf.dtype,
                    )
                    leaf = np.concatenate([leaf, pad])
            spec = partlib.state_partition_spec(leaf)
            return jax.device_put(leaf, NamedSharding(self.mesh, spec))

        self.state = jax.tree.map(fit, snap["state"])
        self._pull_state = self.state
        self._steps_since_snapshot = 0
        self._replica_state = None
        self._seed_counter = int(snap["seed_counter"])

    # checkpoint: inherited from Checkpointable — state_host already
    # drains (pop=False) and carries the seed counter

    def restore(self, manager, step: Optional[int] = None) -> int:
        """Restore state from the latest (or given) checkpoint and return
        its step. Training resumed from here replays bit-identically:
        the seed counter (quantization noise stream) comes back too."""
        if step is None:
            step = manager.latest_step()
            assert step is not None, "no checkpoint found"
        like = {"state": self.state, "seed_counter": np.int64(0)}
        tree = manager.restore(step, like=like)
        self.state = jax.tree.map(
            lambda leaf: jax.device_put(
                np.asarray(leaf),
                NamedSharding(
                    self.mesh,
                    partlib.state_partition_spec(np.asarray(leaf)),
                ),
            ),
            tree["state"],
        )
        self._pull_state = self.state
        self._steps_since_snapshot = 0
        self._seed_counter = int(tree["seed_counter"])
        return step


class AsyncSGDScheduler(ISGDScheduler):
    """Workload dispatch + progress display (ref AsyncSGDScheduler)."""

    def __init__(self, conf: Config, name: str = "async_sgd_scheduler"):
        from ...learner.workload_pool import Workload, WorkloadPool

        sgd = conf.async_sgd or SGDConfig()
        load = Workload(
            files=list(conf.training_data.file),
            replica=sgd.num_data_pass,
            shuffle=True,
        )
        super().__init__(workload_pool=WorkloadPool(load), name=name)
        self.conf = conf
