"""Server-side updaters: FTRL, AdaGrad, SGD.

Counterparts of the per-key entry structs in
``src/app/linear_method/async_sgd.h`` (FTRLEntry, AdaGradEntry, SGDEntry)
— vectorized over slots. Each updater defines:

- ``init(num_slots)``: struct-of-arrays state,
- ``weights(state_u)``: model weights from (gathered) state — FTRL derives
  w from (z, √n) exactly like FTRLEntry which "not necessary to store w",
- ``apply(state, grad, touched)``: the entry ``Set`` step, fused dense over
  a server shard with a touched mask (untouched slots pass through).

The same objects plug into KVMap as entries (parameter/kv_map.py protocol)
and into the fused SPMD train step (async_sgd.py).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .learning_rate import LearningRate
from .penalty import ElasticNet


class FTRLUpdater:
    """FTRL-proximal (ref FTRLEntry::Set, async_sgd.h:131-151):

        n' = sqrt(n² + g²); σ = (n' − n)/α; z += g − σ w; n = n'
        w = prox(−z·η, η),  η = lr.eval(n') = α/(n' + β)

    ``sqrt_n_dtype="bfloat16"`` stores the gradient-magnitude
    accumulator at half width (state 16 B/slot -> 12 B/slot; the
    single-chip slot ceiling grows ~1.33x). All MATH stays f32 —
    sqrt_n is widened at read and narrowed at write — and the narrow
    is STOCHASTICALLY rounded when the caller passes a ``seed``
    (the fused SPMD step does): deterministic truncation would stall
    the accumulator by absorption once n >> per-update increment,
    freezing the per-coordinate learning-rate decay for hot features
    (see ops/ftrl.py stochastic_round_bf16 / the kernel's on-core
    PRNG). Without a seed (the KVMap entry protocol) the narrow
    truncates deterministically — fine for short-lived tables,
    disclosed here. z, the model accumulator, is always f32.
    """

    def __init__(self, lr: LearningRate, penalty: ElasticNet,
                 sqrt_n_dtype=jnp.float32):
        self.lr = lr
        self.penalty = penalty
        self.sqrt_n_dtype = jnp.dtype(sqrt_n_dtype)

    def init(self, num_slots: int) -> Dict[str, jnp.ndarray]:
        return {
            "z": jnp.zeros(num_slots, jnp.float32),
            "sqrt_n": jnp.zeros(num_slots, self.sqrt_n_dtype),
        }

    def weights(self, state):
        eta = self.lr.eval(state["sqrt_n"].astype(jnp.float32))
        return self.penalty.proximal(-state["z"] * eta, eta)

    def apply(self, state, grad, touched, seed=None):
        z = state["z"]
        if self.lr.type == LearningRate.DECAY and z.ndim == 1:
            # fused op (ops/ftrl.py): Pallas single-HBM-pass kernel on
            # TPU (f32 AND bf16-sqrt_n variants — the bf16 kernel
            # stochastically rounds with the on-core PRNG), jnp
            # reference path elsewhere; the op owns every fallback
            from ...ops.ftrl import ftrl_update

            z_new, n_new = ftrl_update(
                z, state["sqrt_n"], grad, touched,
                alpha=self.lr.alpha, beta=self.lr.beta,
                l1=self.penalty.lambda1, l2=self.penalty.lambda2,
                seed=seed,
            )
            return {"z": z_new, "sqrt_n": n_new}
        if touched is None:  # unquantized push: membership == support
            touched = grad != 0
        sqrt_n = state["sqrt_n"].astype(jnp.float32)
        w = self.weights(state)
        sqrt_n_new = jnp.sqrt(sqrt_n * sqrt_n + grad * grad)
        sigma = (sqrt_n_new - sqrt_n) / self.lr.alpha
        z_new = z + grad - sigma * w
        masked_n = jnp.where(touched, sqrt_n_new, sqrt_n)
        if self.sqrt_n_dtype == jnp.bfloat16 and seed is not None:
            from ...ops.ftrl import stochastic_round_bf16

            # untouched slots round-trip exactly (their f32 value IS a
            # bf16 value), so the dither cannot drift idle slots
            masked_n = stochastic_round_bf16(masked_n, seed)
        return {
            "z": jnp.where(touched, z_new, z),
            "sqrt_n": masked_n.astype(self.sqrt_n_dtype),
        }


class AdaGradUpdater:
    """AdaGrad (ref AdaGradEntry::Set): sum_sq += g²;
    w = prox(w − η g, η), η = lr.eval(√sum_sq)."""

    def __init__(self, lr: LearningRate, penalty: ElasticNet):
        self.lr = lr
        self.penalty = penalty

    def init(self, num_slots: int) -> Dict[str, jnp.ndarray]:
        return {
            "w": jnp.zeros(num_slots, jnp.float32),
            "sum_sq": jnp.zeros(num_slots, jnp.float32),
        }

    def weights(self, state):
        return state["w"]

    def apply(self, state, grad, touched, seed=None):
        if touched is None:  # unquantized push: membership == support
            touched = grad != 0
        sum_sq = state["sum_sq"] + grad * grad
        eta = self.lr.eval(jnp.sqrt(sum_sq))
        w = self.penalty.proximal(state["w"] - eta * grad, eta)
        return {
            "w": jnp.where(touched, w, state["w"]),
            "sum_sq": jnp.where(touched, sum_sq, state["sum_sq"]),
        }


class SGDUpdater:
    """Plain (proximal) SGD with a global step count — the reference's
    commented-out SGDEntry, completed: w = prox(w − η g, η), η = lr.eval(√t)."""

    def __init__(self, lr: LearningRate, penalty: ElasticNet):
        self.lr = lr
        self.penalty = penalty

    def init(self, num_slots: int) -> Dict[str, jnp.ndarray]:
        return {
            "w": jnp.zeros(num_slots, jnp.float32),
            "t": jnp.zeros((), jnp.float32),
        }

    def weights(self, state):
        return state["w"]

    def apply(self, state, grad, touched, seed=None):
        if touched is None:  # unquantized push: membership == support
            touched = grad != 0
        t = state["t"] + 1.0
        eta = self.lr.eval(jnp.sqrt(t))
        w = self.penalty.proximal(state["w"] - eta * grad, eta)
        return {"w": jnp.where(touched, w, state["w"]), "t": t}


def apply_state_rows(updater, state, rel, ok, g_u, seed=None, *,
                     force_pallas=False, interpret=False):
    """Sparse-touched update: run ``updater.apply`` on just the gathered
    rows ``rel`` of a server shard and scatter the results back.

    The big-table formulation — the reference's servers only ever run
    the per-key entry ``Set`` on RECEIVED keys (async_sgd.h:131-151,
    kv_map's per-message loop); the dense whole-shard sweep is the
    TPU-friendly variant that wins at small tables, but per ministep it
    moves O(shard) HBM traffic and needs a dense gradient temp — at
    2^30 slots that sweep is ~130 ms and at 2^31 the f32 temp alone
    (8.6 GB) pushes the table off-chip. This form moves
    O(unique-touched) state instead: gather the touched rows, update
    them with the SAME per-row math (so every updater and the Pallas
    FTRL kernel apply unchanged), scatter the new rows back.

    ``rel`` must be unique among ``ok`` entries — host prep dedups at
    slot level (hash collisions included) because the update is
    nonlinear in the summed gradient. Non-owned/padding entries
    (``ok`` False) are routed to the one-past-the-end row in UNSIGNED
    index space and dropped by the scatter (``mode='drop'``): a signed
    -1 would WRAP to the shard's real last row and scatter-set a stale
    value over its genuine update (observed: the last slot of every
    shard losing its step), and uint32 both never wraps and still
    represents one-past-end for the maximal 2^31-row shard. Their
    gradient is zeroed so the rows they DO gather (clipped indices)
    can't perturb anything. Scalar state leaves (e.g. SGDUpdater's
    step count) take the updated value directly — there is nothing to
    scatter.

    FTRL/decay takes the FUSED path when the shapes allow it
    (ops/ftrl_sparse.py — one Pallas gather→update→scatter pass over
    the touched 128-lane rows instead of four XLA dispatches, in-place
    via input_output_aliases); ``use_sparse_kernel`` is the testable
    path predicate and every fallback is bit-identical to the generic
    gather/apply/scatter below. ``force_pallas``/``interpret`` pin the
    kernel for parity tests and A/B sweeps (never onto a shape it
    cannot tile).
    """
    # the duplicate-free contract, asserted where it CAN be (concrete
    # host arrays — direct calls and tests; traced production inputs
    # are guaranteed by prep's slot-level np.unique): the update is
    # nonlinear in the summed gradient, so a duplicated ok row would
    # silently double-apply in BOTH formulations
    if isinstance(rel, np.ndarray) and isinstance(ok, np.ndarray):
        r = rel[np.asarray(ok, bool)]
        assert len(np.unique(r)) == len(r), (
            "apply_state_rows: rel must be duplicate-free among ok "
            "entries (host prep dedups at slot level)"
        )
    from .learning_rate import LearningRate

    if (
        isinstance(updater, FTRLUpdater)
        and updater.lr.type == LearningRate.DECAY
        and state["z"].ndim == 1
    ):
        from ...ops import ftrl_sparse

        if ftrl_sparse.use_sparse_kernel(
            state["z"].shape[0], rel.shape[0],
            updater.sqrt_n_dtype == jnp.bfloat16, seed is not None,
            force_pallas,
        ):
            z_new, n_new = ftrl_sparse.ftrl_sparse_update(
                state["z"], state["sqrt_n"], rel, ok, g_u,
                alpha=updater.lr.alpha, beta=updater.lr.beta,
                l1=updater.penalty.lambda1, l2=updater.penalty.lambda2,
                seed=seed, force_pallas=force_pallas,
                interpret=interpret,
            )
            return {"z": z_new, "sqrt_n": n_new}
    state_u = jax.tree.map(lambda a: a[rel] if a.ndim >= 1 else a, state)
    new_u = updater.apply(state_u, jnp.where(ok, g_u, 0.0), None, seed=seed)
    rel_u32 = rel.astype(jnp.uint32)

    def _scatter(full, new_leaf):
        if full.ndim < 1:
            return new_leaf
        oob = jnp.where(ok, rel_u32, jnp.uint32(full.shape[0]))
        return full.at[oob].set(new_leaf.astype(full.dtype), mode="drop")

    return jax.tree.map(_scatter, state, new_u)


def create_updater(algo: str, ada_grad: bool, lr: LearningRate,
                   penalty: ElasticNet, ftrl_state_dtype: str = "float32"):
    """ref AsyncSGDServer ctor dispatch (async_sgd.h:46-58)."""
    a = algo.lower()
    if a == "ftrl":
        return FTRLUpdater(lr, penalty, sqrt_n_dtype=ftrl_state_dtype)
    if a == "standard":
        return AdaGradUpdater(lr, penalty) if ada_grad else SGDUpdater(lr, penalty)
    raise ValueError(f"unknown sgd algo: {algo}")
