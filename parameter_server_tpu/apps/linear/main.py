"""Linear-method CLI (ref main.cc + script/ps.sh):

    python -m parameter_server_tpu.apps.linear.main <config.conf> \\
        [--num-servers N] [--num-workers M] [--verbose]

Reads a reference-style protobuf-text config, boots the postoffice mesh and
runs the selected app end to end (async SGD, darlin, or model evaluation).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("conf", help="path to a protobuf-text .conf file")
    ap.add_argument("--num-servers", type=int, default=1)
    ap.add_argument("--num-workers", type=int, default=0, help="0 = rest of devices")
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument(
        "--report-interval", type=float, default=0.0,
        help="print the node dashboard every N seconds (0 = at end only; "
        "ref dashboard.cc / FLAGS_report_interval)",
    )
    ap.add_argument(
        "--heartbeat-timeout", type=float, default=10.0,
        help="seconds without a heartbeat before a node is declared dead "
        "(ref manager.cc dead-node flow)",
    )
    ap.add_argument(
        "--profile", metavar="DIR", default=None,
        help="capture a jax.profiler device trace of the app run into "
        "DIR (TensorBoard profile / Perfetto format)",
    )
    args = ap.parse_args(argv)

    from ...system.postoffice import Postoffice
    from .config import parse_conf

    with open(args.conf) as f:
        conf = parse_conf(f.read())

    po = Postoffice.instance().start(
        num_data=args.num_workers or None, num_server=args.num_servers
    )
    # heartbeat → dashboard → recovery, running for every app (the
    # reference boots these with the postoffice on every node)
    aux = po.start_aux(heartbeat_timeout=args.heartbeat_timeout)
    aux.start(
        check_interval=max(0.2, args.heartbeat_timeout / 5),
        dashboard_interval=args.report_interval,
    )

    from ...utils.profiling import device_trace

    with device_trace(args.profile):
        rc = _run_app(conf, aux, args)
    if rc:
        return rc
    if args.verbose or args.report_interval > 0:
        print(aux.dashboard.report())
    po.stop()
    return 0


def _run_app(conf, aux, args) -> int:
    from ...learner.sgd import MinibatchReader

    if conf.darlin is not None:
        from .darlin import DarlinScheduler

        sched = DarlinScheduler(conf)
        aux.register(sched.name)
        td = conf.training_data
        sched.load_data(td.file, td.text if td.format == "text" else td.format)
        sched.run_loaded(verbose=True)
        if conf.model_output is not None and conf.model_output.file:
            files = sched.save_model(conf.model_output.file[0])
            print(f"model written to {', '.join(files)}")
        print(sched.show_progress(max(sched.g_progress) if sched.g_progress else 0))
    elif conf.async_sgd is not None:
        from .async_sgd import AsyncSGDScheduler, AsyncSGDWorker

        sched = AsyncSGDScheduler(conf)
        sched.run()
        worker = AsyncSGDWorker(conf)
        worker.attach_monitor(sched)
        aux.register(worker.name)
        # dead worker → its file workloads go back to the pool; dead
        # server shard → checkpoint restore (manager.cc dead-node flow)
        aux.coordinator.on_worker_dead(sched.workload_pool.restore)
        sgd = conf.async_sgd
        while True:
            load = sched.workload_pool.assign(worker.name)
            if load is None:
                break
            td = conf.training_data
            reader = MinibatchReader(
                files=load.files,
                minibatch_size=sgd.minibatch,
                data_format=td.text if td.format == "text" else td.format,
            )
            if sgd.tail_feature_freq > 0:
                reader.init_filter(
                    sgd.countmin_n, sgd.countmin_k, sgd.tail_feature_freq
                )
            with reader:  # start() the producer thread; close() joins it
                worker.train(iter(reader))
            sched.workload_pool.finish(load.id)
        sched.monitor.maybe_print(force=True)
        if conf.model_output is not None and conf.model_output.file:
            files = worker.save_model(conf.model_output.file[0])
            print(f"model written to {', '.join(files)}")
        if conf.validation_data is not None and conf.validation_data.file:
            from ...data.stream_reader import StreamReader

            vd = conf.validation_data
            r = StreamReader(vd.file, vd.text if vd.format == "text" else vd.format)
            allb = r.read_all()
            if allb is not None:
                ev = worker.evaluate(allb)
                print(
                    f"validation auc: {ev['auc']:.6f}, accuracy: {ev['accuracy']:.6f}, "
                    f"logloss: {ev['logloss']:.6f}"
                )
    elif conf.validation_data is not None:
        from .model_evaluation import ModelEvaluation

        ModelEvaluation(conf).run()
    else:
        print("config selects no app", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
