"""Learning-rate schedules (ref ``src/app/linear_method/learning_rate.h``):

CONSTANT: η = α;  DECAY: η(x) = α / (x + β), where x is the per-coordinate
scale (√n in FTRL/AdaGrad). jnp-traceable.
"""

from __future__ import annotations

import jax.numpy as jnp


class LearningRate:
    CONSTANT = "constant"
    DECAY = "decay"

    def __init__(self, type_: str = DECAY, alpha: float = 0.1, beta: float = 1.0):
        assert alpha > 0 and beta >= 0
        self.type = type_.lower()
        self.alpha = float(alpha)
        self.beta = float(beta)

    def eval(self, x=0.0):
        if self.type == self.CONSTANT:
            return jnp.asarray(self.alpha)
        return self.alpha / (x + self.beta)
