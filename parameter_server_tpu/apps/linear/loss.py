"""Scalar losses for linear methods.

Counterpart of ``src/app/linear_method/loss.h``: logit and square hinge
(plus square for regression), each exposing objective value, per-row
gradient dL/d(Xw), and a per-row diagonal-Hessian (curvature) weight — the
pieces the reference's ``ScalarLoss::compute`` assembles into X^T(...)
products, which here happen in ops/spmv.

Note: the reference's SquareHingeLoss gradient uses the indicator
``y*Xw > 1`` (active side inverted, src loss.h:110); we implement the
standard subgradient ``-2 y max(0, 1 - y·Xw)``.
"""

from __future__ import annotations

import jax.numpy as jnp


class LogitLoss:
    """L(y, Xw) = sum log(1 + exp(-y Xw)), y ∈ {-1, +1}."""

    def row_loss(self, y, xw):
        return jnp.logaddexp(0.0, -y * xw)

    def evaluate(self, y, xw):
        return jnp.sum(self.row_loss(y, xw))

    def row_grad(self, y, xw):
        tau = 1.0 / (1.0 + jnp.exp(y * xw))
        return -y * tau

    def row_hess(self, y, xw):
        tau = 1.0 / (1.0 + jnp.exp(y * xw))
        return tau * (1.0 - tau)


class SquareHingeLoss:
    """L = sum max(0, 1 - y Xw)^2."""

    def row_loss(self, y, xw):
        return jnp.maximum(0.0, 1.0 - y * xw) ** 2

    def evaluate(self, y, xw):
        return jnp.sum(self.row_loss(y, xw))

    def row_grad(self, y, xw):
        return -2.0 * y * jnp.maximum(0.0, 1.0 - y * xw)

    def row_hess(self, y, xw):
        return jnp.where(y * xw < 1.0, 2.0, 0.0)


class SquareLoss:
    """L = 0.5 sum (Xw - y)^2 (regression)."""

    def row_loss(self, y, xw):
        return 0.5 * (xw - y) ** 2

    def evaluate(self, y, xw):
        return jnp.sum(self.row_loss(y, xw))

    def row_grad(self, y, xw):
        return xw - y

    def row_hess(self, y, xw):
        return jnp.ones_like(y)


def create_loss(type_: str):
    """Factory (ref loss.h createLoss)."""
    t = type_.lower()
    if t == "logit":
        return LogitLoss()
    if t in ("square_hinge", "squarehinge"):
        return SquareHingeLoss()
    if t == "square":
        return SquareLoss()
    raise ValueError(f"unknown loss type: {type_}")
