"""Wide & Deep CTR model on the parameter-server pipeline (TPU-first).

Beyond-parity extension, one rung up from :mod:`fm`: the classic deep-CTR
architecture — a WIDE linear term over hashed sparse features plus a DEEP
MLP over concatenated per-lane embeddings — on the exact same ELL/mesh
machinery as the linear and FM apps, so the sparse side still rides the
sharded parameter-server tables:

    f(x) = b + sum_i w_i  +  MLP([e_1 | e_2 | ... | e_K])      e_i = V[slot_i]

with ``w`` ([slots]) and ``V`` ([slots, k]) key-range-sharded over the
server mesh axis (pull = masked gather + psum, push = scatter-add into
the owning shard + psum over the data axis — KVVector semantics, ref
``parameter/kv_vector.h``), and the dense MLP replicated like a small
KVLayer (below the partition threshold, ref ``parameter/kv_layer.h``).
The deep gradients come from ``jax.vjp`` of the fused forward instead of
hand-derived chain rule — the functional-transform dividend of the
TPU-first design. Everything updates with AdaGrad (+ proximal L1 on the
wide table only; ref AdaGradEntry::Set, async_sgd.h).

The wire is the ELL row-block format from async_sgd (``prep_batch_ell``):
uniform lanes, hashed directory, binary features — for criteo each of the
39 lanes IS a feature slot, so the concatenated embedding layout matches
the per-slot embedding-bag structure of production CTR models.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ...learner.sgd import ISGDCompNode, SGDProgress
from ...ops.kv_ops import localize, valid_slots
from ...parallel import mesh as meshlib
from ...parallel.mesh import DATA_AXIS, SERVER_AXIS
from ...parameter.parameter import KeyDirectory, pad_slots
from ...system.message import Task
from ...utils import evaluation
from ...utils.sparse import SparseBatch
from .async_sgd import _progress_metrics
from .config import Config
from .learning_rate import LearningRate
from .loss import create_loss
from .penalty import create_penalty


def _mlp_forward(h, mlp):
    """ReLU MLP over [R, lanes*k] -> [R] (jnp; mirrored in numpy below)."""
    n_layers = len(mlp) // 2
    for i in range(n_layers - 1):
        h = jax.nn.relu(h @ mlp[2 * i] + mlp[2 * i + 1])
    return (h @ mlp[-2] + mlp[-1])[:, 0]


def make_deep_ctr_step(
    mesh,
    num_slots: int,
    k: int,
    lanes: int,
    loss,
    penalty,
    lr: LearningRate,
    with_aux: bool = True,
):
    """Fused SPMD wide&deep step over an ELLBatch (binary): pull w and V
    at the batch's slots, forward wide+deep, vjp the deep part, scatter
    per-slot gradients, AdaGrad-update tables + MLP + bias."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server

    def local_step(state, y, mask, slots):
        y, mask, slots = y[0], mask[0], slots[0]  # [R], [R], [R, K]
        flat = slots.reshape(-1)
        rel, ok = localize(flat, shard)

        # -- pull: gather w and V entries from the owning shard --
        w_e = jax.lax.psum(
            jnp.where(ok, state["table"]["w"][rel], 0.0), SERVER_AXIS
        ).reshape(slots.shape)  # [R, K]
        v_e = jax.lax.psum(
            jnp.where(ok[:, None], state["table"]["v"][rel], 0.0), SERVER_AXIS
        ).reshape(slots.shape + (k,))  # [R, K, k]
        live = valid_slots(slots, num_slots).astype(jnp.float32)  # sentinels -> 0
        mlp = state["mlp"]

        def fwd(v_e, mlp):
            # live-mask INSIDE the differentiated fn so sentinel-lane
            # embedding gradients vanish through the vjp
            e = (v_e * live[..., None]).reshape(v_e.shape[0], lanes * k)
            return state["b"] + (w_e * live).sum(axis=1) + _mlp_forward(e, mlp)

        xw, pullback = jax.vjp(fwd, v_e, mlp)
        gr = loss.row_grad(y, xw) * mask  # [R]
        g_ve, g_mlp = pullback(gr)

        # -- push: wide grads per entry; deep grads from the vjp --
        gw_flat = (jnp.broadcast_to(gr[:, None], slots.shape) * live).reshape(-1)
        gv_flat = g_ve.reshape(-1, k)
        g_w = jnp.zeros((shard,), jnp.float32).at[rel].add(
            jnp.where(ok, gw_flat, 0.0)
        )
        g_v = jnp.zeros((shard, k), jnp.float32).at[rel].add(
            jnp.where(ok[:, None], gv_flat, 0.0)
        )
        g_w = jax.lax.psum(g_w, DATA_AXIS)
        g_v = jax.lax.psum(g_v, DATA_AXIS)
        g_mlp = jax.lax.psum(g_mlp, DATA_AXIS)
        g_b = jax.lax.psum(jnp.sum(gr), DATA_AXIS)
        touched = (g_w != 0) | (jnp.abs(g_v).sum(axis=1) != 0)

        # -- AdaGrad updates (proximal L1 on the wide table only) --
        w_ss = state["table"]["w_ss"] + g_w * g_w
        eta_w = lr.eval(jnp.sqrt(w_ss))
        w_new = penalty.proximal(state["table"]["w"] - eta_w * g_w, eta_w)
        v_ss = state["table"]["v_ss"] + g_v * g_v
        v_new = state["table"]["v"] - lr.eval(jnp.sqrt(v_ss)) * g_v
        mlp_ss = [s + g * g for s, g in zip(state["mlp_ss"], g_mlp)]
        mlp_new = [
            p - lr.eval(jnp.sqrt(s)) * g
            for p, s, g in zip(mlp, mlp_ss, g_mlp)
        ]
        b_ss = state["b_ss"] + g_b * g_b
        b_new = state["b"] - lr.eval(jnp.sqrt(b_ss)) * g_b

        new_state = {
            "table": {
                "w": jnp.where(touched, w_new, state["table"]["w"]),
                "w_ss": jnp.where(touched, w_ss, state["table"]["w_ss"]),
                "v": jnp.where(touched[:, None], v_new, state["table"]["v"]),
                "v_ss": jnp.where(
                    touched[:, None], v_ss, state["table"]["v_ss"]
                ),
            },
            "mlp": mlp_new,
            "mlp_ss": mlp_ss,
            "b": b_new,
            "b_ss": b_ss,
        }
        return new_state, _progress_metrics(loss, y, xw, mask, with_aux)

    def state_spec(state):
        return {
            "table": jax.tree.map(
                lambda leaf: P(SERVER_AXIS) if leaf.ndim >= 1 else P(),
                state["table"],
            ),
            "mlp": jax.tree.map(lambda _: P(), state["mlp"]),
            "mlp_ss": jax.tree.map(lambda _: P(), state["mlp_ss"]),
            "b": P(),
            "b_ss": P(),
        }

    # donate the sharded tables: the update writes them anyway and
    # the worker always rebinds (self.state = new_state); aliasing
    # input->output halves the table HBM footprint (as in async_sgd)
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, batch_y, batch_mask, batch_slots):
        specs = state_spec(state)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(specs, P()),
            check_vma=False,
        )(state, batch_y, batch_mask, batch_slots)

    return step


class DeepCTRWorker(ISGDCompNode):
    """Async wide&deep trainer on the data x server mesh.

    Same consumption API as AsyncSGDWorker/FMWorker (``process_minibatch``
    / ``collect`` / ``train`` / ``evaluate`` / ``state_host``); the table
    is hashed with the configured modulus (elastic-resize stable) and the
    batch wire is the ELL row-block format."""

    def __init__(
        self,
        conf: Config,
        k: int = 8,
        hidden: Sequence[int] = (64, 32),
        mesh=None,
        v_init_std: float = 0.01,
        seed: int = 0,
        name: str = "deep_ctr_worker",
    ):
        super().__init__(name=name)
        sgd = conf.async_sgd
        assert sgd is not None and sgd.ell_lanes > 0, (
            "deep CTR needs async_sgd conf with ell_lanes (uniform ELL rows)"
        )
        if mesh is None:
            mesh = self.po.mesh
        self.mesh = mesh
        self.sgd = sgd
        self.k = int(k)
        self.lanes = int(sgd.ell_lanes)
        self.hidden = tuple(int(h) for h in hidden)
        self.loss = create_loss(conf.loss.type)
        self.penalty = create_penalty(conf.penalty.type, conf.penalty.lambda_)
        self.lr = LearningRate(
            conf.learning_rate.type, conf.learning_rate.alpha,
            conf.learning_rate.beta,
        )
        self.num_slots = pad_slots(sgd.num_slots, meshlib.num_servers(mesh))
        self.directory = KeyDirectory(sgd.num_slots, hashed=True)
        rng = np.random.default_rng(seed)
        dims = (self.lanes * self.k,) + self.hidden + (1,)
        mlp = []
        for d_in, d_out in zip(dims[:-1], dims[1:]):
            # the MLP is small and replicated: host init is fine (and
            # keeps He-init reproducibility with numpy rng)
            mlp.append(
                jnp.asarray(
                    rng.normal(0.0, np.sqrt(2.0 / d_in), (d_in, d_out)),
                    jnp.float32,
                )
            )
            mlp.append(jnp.zeros((d_out,), jnp.float32))

        # the server-sharded TABLE (the scale-bearing state) goes
        # direct-to-sharded (rationale at meshlib.init_sharded)
        def _init_table():
            n, k = self.num_slots, self.k
            return {
                "w": jnp.zeros((n,), jnp.float32),
                "w_ss": jnp.zeros((n,), jnp.float32),
                "v": v_init_std * jax.random.normal(
                    jax.random.PRNGKey(seed), (n, k), jnp.float32
                ),
                "v_ss": jnp.zeros((n, k), jnp.float32),
            }

        table = meshlib.init_sharded(_init_table, mesh)
        self.state = {
            "table": table,
            "mlp": mlp,
            "mlp_ss": [jnp.zeros_like(p) for p in mlp],
            "b": jnp.zeros((), jnp.float32),
            "b_ss": jnp.zeros((), jnp.float32),
        }
        self._step = make_deep_ctr_step(
            mesh, self.num_slots, self.k, self.lanes, self.loss,
            self.penalty, self.lr,
        )
        self._rows_pad: Optional[int] = None
        self.progress = SGDProgress()

    def process_minibatch(self, batch: SparseBatch) -> int:
        prepped = self._prep_ell(batch)  # shared base prep (ISGDCompNode)

        def run():
            new_state, metrics = self._step(
                self.state, prepped.y, prepped.mask, prepped.slots
            )
            self.state = new_state
            return metrics

        return self.submit(run, Task())

    def wipe_server_shard(self, shard: int) -> None:
        """Zero a dead server shard's TABLE segment (the replicated MLP
        survives a server death by construction — every rank holds it)."""
        n_server = meshlib.num_servers(self.mesh)
        per = self.num_slots // n_server
        lo, hi = shard * per, (shard + 1) * per
        self.executor.wait_all(pop=False)
        self.state["table"] = jax.tree.map(
            lambda leaf: leaf.at[lo:hi].set(0.0), self.state["table"]
        )

    def recover_server_shard(self, shard: int) -> bool:
        """No ongoing replica (configure checkpoints for durability):
        report failure so the elastic coordinator reshards around it."""
        del shard
        return False

    # collect/train: inherited from ISGDCompNode (shared worker plumbing)

    def state_host(self) -> dict:
        """Host snapshot for live migration (ElasticCoordinator.resize)."""
        self.executor.wait_all(pop=False)
        return {"state": jax.tree.map(np.asarray, self.state)}

    def load_state_host(self, snap: dict) -> None:
        def fit_table(leaf):
            leaf = np.asarray(leaf)
            if leaf.shape[0] != self.num_slots:
                if leaf.shape[0] > self.num_slots:
                    leaf = leaf[: self.num_slots]
                else:
                    pad = np.zeros(
                        (self.num_slots - leaf.shape[0],) + leaf.shape[1:],
                        leaf.dtype,
                    )
                    leaf = np.concatenate([leaf, pad])
            return jax.device_put(
                leaf,
                NamedSharding(
                    self.mesh, P(SERVER_AXIS, *([None] * (leaf.ndim - 1)))
                ),
            )

        st = snap["state"]
        self.state = {
            "table": jax.tree.map(fit_table, st["table"]),
            "mlp": [jnp.asarray(p) for p in st["mlp"]],
            "mlp_ss": [jnp.asarray(p) for p in st["mlp_ss"]],
            "b": jnp.asarray(st["b"]),
            "b_ss": jnp.asarray(st["b_ss"]),
        }

    def predict_margin(self, batch: SparseBatch) -> np.ndarray:
        """Host-side vectorized forward (evaluation path): the SAME
        lanes-layout as the device step — short rows pad with sentinel
        (zero) embeddings; rows WIDER than the lane budget are rejected
        exactly like the training path (never silently drop features)."""
        # settle in-flight steps (state swaps on the executor thread) so
        # the margin reads ONE consistent state version, not a mix
        self.executor.wait_all(pop=False)
        w = np.asarray(self.state["table"]["w"]).astype(np.float64)
        v = np.asarray(self.state["table"]["v"]).astype(np.float64)
        mlp = [np.asarray(p).astype(np.float64) for p in self.state["mlp"]]
        b = float(self.state["b"])
        if batch.n == 0:
            return np.zeros(0, np.float32)
        lanes, kk = self.lanes, self.k
        counts = np.diff(batch.indptr)
        if counts.max(initial=0) > lanes:
            raise ValueError(
                f"row with {int(counts.max())} features exceeds the ELL "
                f"lane budget ({lanes}); predict_margin refuses to drop "
                "features (same contract as the training path)"
            )
        slots = self.directory.slots(batch.indices)
        # scatter the CSR stream into a dense [n, lanes] lane matrix
        mat = np.zeros((batch.n, lanes), np.int64)
        ok = np.arange(lanes)[None, :] < counts[:, None]
        rows_idx = np.repeat(np.arange(batch.n), counts)
        lane_idx = np.arange(batch.nnz) - np.repeat(
            batch.indptr[:-1].astype(np.int64), counts
        )
        mat[rows_idx, lane_idx] = slots
        e = v[mat] * ok[..., None]  # [n, lanes, k]
        wide = (w[mat] * ok).sum(axis=1)
        h = e.reshape(batch.n, lanes * kk)
        for i in range(len(mlp) // 2 - 1):
            h = np.maximum(h @ mlp[2 * i] + mlp[2 * i + 1], 0.0)
        deep = (h @ mlp[-2] + mlp[-1])[:, 0]
        return (b + wide + deep).astype(np.float32)

    def evaluate(self, batch: SparseBatch) -> Dict[str, float]:
        xw = self.predict_margin(batch)
        y = batch.y
        ll = float(np.mean(np.logaddexp(0.0, -y * xw)))
        return {"auc": evaluation.auc(y, xw), "logloss": ll}
