"""Linear-method configuration.

Dataclass mirror of ``src/app/linear_method/proto/linear.proto`` (Config,
SGDConfig, LossConfig, PenaltyConfig, LearningRateConfig) plus the BCD
extension fields used by darlin (``delta_init_value``, ``delta_max_value``,
``kkt_filter_threshold_ratio``) and ``src/learner/proto/bcd.proto``'s
BCDConfig. Parsed from the reference's protobuf-text ``.conf`` files by
``parse_conf`` so the example configs keep working.
"""

from __future__ import annotations

import dataclasses
import re
from typing import List, Optional


@dataclasses.dataclass
class DataConfig:
    """ref data/proto/data.proto DataConfig."""

    format: str = "text"  # text | record | bin
    text: str = "libsvm"  # libsvm | criteo | adfea | terafea | ps (TextFormat)
    file: List[str] = dataclasses.field(default_factory=list)
    ignore_feature_group: bool = False
    range_begin: int = 0  # example range restriction (ref DataConfig.range)
    range_end: int = 0


@dataclasses.dataclass
class LossConfig:
    type: str = "logit"  # square | logit | hinge | square_hinge


@dataclasses.dataclass
class PenaltyConfig:
    type: str = "l1"  # l1 | l2
    lambda_: List[float] = dataclasses.field(default_factory=lambda: [0.1])


@dataclasses.dataclass
class LearningRateConfig:
    type: str = "decay"  # constant | decay
    alpha: float = 0.1
    beta: float = 1.0


@dataclasses.dataclass
class SGDConfig:
    """ref learner/proto/sgd.proto SGDConfig."""

    algo: str = "ftrl"  # standard | ftrl
    minibatch: int = 1000
    data_buf: int = 1000  # prefetch budget, MB
    ada_grad: bool = True  # for algo=standard
    max_delay: int = 0  # bounded-delay window (in-flight steps)
    num_data_pass: int = 1
    report_interval: float = 1.0
    tail_feature_freq: int = 0
    countmin_n: int = 100_000_000
    countmin_k: int = 2
    push_filter: list = dataclasses.field(default_factory=list)
    pull_filter: list = dataclasses.field(default_factory=list)
    # TPU extensions
    # pull-gather formulation for quantized pulls: "auto" (resolves
    # to wide — the on-chip A/B measured TPU gathers as row-
    # granularity-bound, so the narrow codes+mask gather is SLOWER;
    # BENCH_ONCHIP 08-02), or an explicit "narrow"/"wide". Narrow
    # gathers the quantized codes + zero-mask and dequantizes
    # post-gather; exactness-equal to wide, worth forcing only on
    # parts where gathered bytes, not rows, bind.
    pull_gather: str = "auto"
    num_slots: int = 1 << 22  # hashed weight table size
    rows_pad: int = 0  # 0 = minibatch size
    nnz_pad: int = 0  # 0 = auto from first batch
    ell_lanes: int = 0  # >0: ELL row-block packing with K feature lanes
    # pack ELL slot ids to 3 bytes on the wire. Off by default: the numpy
    # byte-slice pack costs ~3.5ms/16k-batch on the critical path, which
    # only pays off on links where raw bytes (not host cycles) dominate.
    wire_u24: bool = False
    # wire format for ELL batches: "" (legacy: honor wire_u24), "i32",
    # "u24", "bits" (ceil(log2 num_slots)-bit slot stream + 1-bit
    # labels; needs the hashed/binary/uniform-row hot path, falls back to
    # u24 otherwise — cheapest bytes AND cheapest host cycles via the
    # fused C++ hash→pack pass), or "stream" (the stream-once
    # lane-dictionary wire, learner/wire.py: small-vocabulary lanes —
    # criteo's integer count fields — ship per-lane sorted unique-slot
    # tables + bit-packed table indices, high-vocabulary lanes keep the
    # raw bit stream; the cache-free encoding for single-epoch data,
    # ~96 B/example vs the bits wire's 126.9 at the criteo-law 2^26
    # shape, bit-identical decode on device, falls back to "bits" when
    # no lane split wins or a batch leaves the pinned lane statics)
    wire: str = ""
    # staging-leg byte codec (learner/wire.compress_batch): "" = off,
    # "lz" = prep-pool workers frame each emitted batch's leaves
    # through the native LZ codec (utils/codec.py; incompressible
    # leaves ride raw) and the uploader thread decodes them right
    # before device_put. Shrinks the host↔host STAGING leg (the
    # disaggregated feeder→trainer hand-off), NOT the PJRT
    # host→device link itself — see doc/PERFORMANCE.md "Wire format"
    # for which legs compression does and does not shrink.
    wire_compress: str = ""
    # compact wire for the EXACT (host-dedup) batch path
    # (learner/wire.py): "" = raw buffers (today's stream), "exact" =
    # lossless encode — bit-packed ucols, delta/bit-packed sorted
    # uslots, sign-bit labels, count-coded mask/rows, binary values
    # elided; decode happens inside the jitted step (ops/wire_codec),
    # so only encoded bytes cross the host→device link and the decoded
    # stream is BIT-IDENTICAL to the raw wire (parity-tested).
    # "int8"/"u16"/"bf16" additionally narrow the value stream of
    # valued batches (stochastic fixed-point / bfloat16) — lossy,
    # gated behind the logloss-parity bound in tests/test_wire.py.
    wire_encode: str = ""
    # upload key cache (learner/wire.UploadCache): >0 enables crc32c-
    # signature key caching on the host→device leg with this many MB of
    # retained host copies — a repeated batch array (multi-epoch
    # passes, eval/replay loops) re-uses its device-resident buffer
    # instead of re-crossing the link. Exact-verified (the signature
    # routes, a byte compare decides), so it composes with wire_encode
    # losslessly. Costs host RAM for the retained copies and HBM for
    # the pinned device buffers; size it to the repeated working set.
    wire_cache_mb: int = 0
    # ongoing server replication (ref FLAGS_num_replicas + Parameter::
    # SetReplica): >0 mirrors each server shard's segment onto its
    # neighbor shard every `replica_every` steps, so a dead server loses
    # at most that many steps instead of everything since the last
    # checkpoint
    num_replicas: int = 0
    replica_every: int = 1
    # scan-fused supersteps: >1 runs that many minibatches per device
    # launch (lax.scan inside one jitted program; needs wire="bits") —
    # the dominant throughput lever on high-latency host<->device links
    steps_per_launch: int = 1
    # prep-pool width for the pipelined ingest path (learner/ingest.py):
    # 0 = auto (cores-1, capped at 4 — leaves the feeder thread and the
    # trainer a core each on small hosts); batch order and therefore
    # the training trajectory are identical at any width (ordered pool)
    ingest_workers: int = 0
    # FTRL sqrt_n storage dtype: "float32" (default, bit-exact vs the
    # reference) or "bfloat16" — halves that half of the table state
    # (16 B/slot -> 12 B/slot), raising the single-chip slot ceiling
    # ~1.33x; sqrt_n is a gradient-magnitude accumulator whose mantissa
    # loss perturbs only the per-coordinate learning-rate schedule, so
    # convergence holds to ~1e-3 logloss (tested) while z — the actual
    # model accumulator — stays f32
    ftrl_state_dtype: str = "float32"
    # server-update formulation: "dense" (scatter + whole-shard sweep,
    # wins at small tables), "sparse" (gather→apply→scatter only the
    # batch's unique slots — O(touched) HBM traffic instead of
    # O(shard); the 2^30+ mode, and the only one whose 2^31 table fits
    # one chip), or "auto" (sparse iff the per-server shard is ≥
    # PS_SPARSE_UPDATE_MIN_SLOTS, default 2^30 — set from the on-chip
    # dense-sweep vs gather/scatter measurements). Sparse runs on the
    # exact wire (host-dedup'd slots) and composes with unfiltered
    # push/pull only.
    update: str = "auto"
    # -- self-driving consistency (learner/consistency.py) --
    # adaptive bounded-delay τ: max_delay becomes the CAP and the live
    # effective τ moves in [0, max_delay] with gradient geometry —
    # widening while grad norms hold steady (more async throughput),
    # clamping toward 0 on divergence leading indicators, with
    # automatic LR backoff + snapshot rollback on a divergence. Pins
    # the non-donated step variant so τ moves never recompile.
    tau_adaptive: bool = False
    # in-jit KKT-style significance filter (ops/significance.py):
    # suppress slots whose pending update provably leaves the FTRL
    # proximal weight at zero (|z + g| <= lambda1 * kkt_margin at
    # w == 0) — requires algo="ftrl", an L1 penalty, and the sparse
    # update formulation. Lossy by design (a suppressed slot skips its
    # z accumulation); the seeded kkt_escape fraction ships anyway so
    # persistent sub-threshold features still learn. False =
    # bit-identical to the unfiltered path (contract-tested).
    kkt_filter: bool = False
    kkt_margin: float = 1.0
    kkt_escape: float = 1.0 / 64.0
    # host-side key drop: a slot suppressed on kkt_drop_after
    # consecutive collected steps stops being uploaded at all (prep
    # removes it from the batch — forward-exact while its weight is
    # zero) until the every-kkt_revisit_every-th prepped batch ships
    # unfiltered to re-measure. 0 disables the host drop (in-jit
    # filter only). Serial-prep path only (the drop set evolves in
    # collect order; a concurrent ingest pool would make it racy).
    kkt_drop_after: int = 0
    kkt_revisit_every: int = 64


@dataclasses.dataclass
class BCDConfig:
    """ref learner/proto/bcd.proto + darlin extensions in linear.proto."""

    num_data_pass: int = 10  # max_pass_of_data
    feature_block_ratio: float = 4.0
    random_feature_block_order: bool = True
    max_block_delay: int = 0
    epsilon: float = 1e-4
    save_model_every_n_iter: int = 0
    load_local_data: bool = False
    comm_filter: list = dataclasses.field(default_factory=list)
    # darlin trust-region extension fields
    delta_init_value: float = 1.0
    delta_max_value: float = 5.0
    kkt_filter_threshold_ratio: float = 10.0


@dataclasses.dataclass
class Config:
    training_data: DataConfig = dataclasses.field(default_factory=DataConfig)
    validation_data: Optional[DataConfig] = None
    model_output: Optional[DataConfig] = None
    model_input: Optional[DataConfig] = None
    loss: LossConfig = dataclasses.field(default_factory=LossConfig)
    penalty: PenaltyConfig = dataclasses.field(default_factory=PenaltyConfig)
    learning_rate: LearningRateConfig = dataclasses.field(
        default_factory=LearningRateConfig
    )
    async_sgd: Optional[SGDConfig] = None
    darlin: Optional[BCDConfig] = None


_ENUMS = {
    "LOGIT": "logit", "SQUARE": "square", "HINGE": "hinge",
    "SQUARE_HINGE": "square_hinge", "L1": "l1", "L2": "l2",
    "CONSTANT": "constant", "DECAY": "decay", "FTRL": "ftrl",
    "STANDARD": "standard", "TEXT": "text", "LIBSVM": "libsvm",
    "CRITEO": "criteo", "ADFEA": "adfea", "TERAFEA": "terafea",
    # a reference .conf declaring PROTO means the REFERENCE's binary
    # format (protobuf Example recordio, data/ref_interop.py) — that is
    # what its readers consume as DataConfig.PROTO; this repo's own
    # crc-framed batches keep the separate "record" format name
    "BIN": "bin", "PROTO": "ref_record",
    "SPARSE": "ps_sparse", "SPARSE_BINARY": "ps_sparse_binary",
    "DENSE": "ps_dense", "KEY_CACHING": "key_caching",
    "COMPRESSING": "compressing", "FIXING_FLOAT": "fixing_float",
}


def _ftrl_state_dtype(val) -> str:
    """Validated ftrl_state_dtype: only the two supported storage
    dtypes. Anything else — f16 (absorption-stalls WITHOUT the bf16
    stochastic-rounding path, plus overflow range), f64, or a typo
    like "bf16" — must fail AT PARSE TIME with the accepted values,
    not as an obscure dtype error deep in server construction."""
    v = str(val).lower()
    if v not in ("float32", "bfloat16"):
        raise ValueError(
            f"ftrl_state_dtype must be 'float32' or 'bfloat16', got {val!r}"
        )
    return v


def parse_conf_dict(text: str) -> dict:
    """Parse protobuf text format into nested dicts (repeated -> lists)."""
    text = re.sub(r"#[^\n]*", "", text)

    def parse_block(pos: int):
        out: dict = {}
        while pos < len(text):
            while pos < len(text) and text[pos] in " \t\r\n;":
                pos += 1
            if pos >= len(text) or text[pos] == "}":
                return out, pos + 1
            m = re.match(r"([A-Za-z_][A-Za-z0-9_]*)\s*", text[pos:])
            if not m:
                raise ValueError(f"parse error at {text[pos:pos+40]!r}")
            key = m.group(1)
            pos += m.end()
            if pos < len(text) and text[pos] == "{":
                val, pos = parse_block(pos + 1)
            else:
                if text[pos] == ":":
                    pos += 1
                while pos < len(text) and text[pos] in " \t":
                    pos += 1
                if text[pos] == "{":
                    val, pos = parse_block(pos + 1)
                elif text[pos] == '"':
                    end = text.index('"', pos + 1)
                    val = text[pos + 1 : end]
                    pos = end + 1
                else:
                    m2 = re.match(r"[^\s{}]+", text[pos:])
                    raw = m2.group(0)
                    pos += m2.end()
                    val = _coerce(raw)
            if key in out:
                if not isinstance(out[key], list):
                    out[key] = [out[key]]
                out[key].append(val)
            else:
                out[key] = val
        return out, pos

    d, _ = parse_block(0)
    return d


def _coerce(raw: str):
    if raw in _ENUMS:
        return _ENUMS[raw]
    if raw.lower() in ("true", "false"):
        return raw.lower() == "true"
    try:
        return int(raw)
    except ValueError:
        pass
    try:
        return float(raw)
    except ValueError:
        return raw


def _filter_list(v) -> list:
    """Normalize repeated ``push_filter { ... }`` blocks to a list of
    dicts (ref FilterConfig in proto/filter.proto)."""
    if v is None:
        return []
    return list(v) if isinstance(v, list) else [v]


def _data_config(d: dict) -> DataConfig:
    files = d.get("file", [])
    if not isinstance(files, list):
        files = [files]
    return DataConfig(
        format=str(d.get("format", "text")).lower(),
        text=str(d.get("text", "libsvm")).lower(),
        file=[str(f) for f in files],
        ignore_feature_group=bool(d.get("ignore_feature_group", False)),
    )


def parse_conf(text: str) -> Config:
    """Parse a reference-style .conf (protobuf text) into Config."""
    d = parse_conf_dict(text)
    cfg = Config()
    if "training_data" in d:
        cfg.training_data = _data_config(d["training_data"])
    if "validation_data" in d:
        cfg.validation_data = _data_config(d["validation_data"])
    if "model_output" in d:
        cfg.model_output = _data_config(d["model_output"])
    if "model_input" in d:
        cfg.model_input = _data_config(d["model_input"])
    if "loss" in d:
        cfg.loss = LossConfig(type=str(d["loss"].get("type", "logit")))
    if "penalty" in d:
        lam = d["penalty"].get("lambda", [0.1])
        if not isinstance(lam, list):
            lam = [lam]
        cfg.penalty = PenaltyConfig(
            type=str(d["penalty"].get("type", "l1")), lambda_=[float(x) for x in lam]
        )
    if "learning_rate" in d:
        lr = d["learning_rate"]
        cfg.learning_rate = LearningRateConfig(
            type=str(lr.get("type", "decay")),
            alpha=float(lr.get("alpha", 0.1)),
            beta=float(lr.get("beta", 1.0)),
        )
    if "async_sgd" in d:
        s = d["async_sgd"]
        cfg.async_sgd = SGDConfig(
            algo=str(s.get("algo", "ftrl")),
            minibatch=int(s.get("minibatch", 1000)),
            data_buf=int(s.get("data_buf", 1000)),
            ada_grad=bool(s.get("ada_grad", True)),
            max_delay=int(s.get("max_delay", 0)),
            num_data_pass=int(s.get("num_data_pass", 1)),
            report_interval=float(s.get("report_interval", 1.0)),
            tail_feature_freq=int(s.get("tail_feature_freq", 0)),
            countmin_n=int(float(s.get("countmin_n", 1e8))),
            countmin_k=int(s.get("countmin_k", 2)),
            num_slots=int(s.get("num_slots", 1 << 22)),
            rows_pad=int(s.get("rows_pad", 0)),
            nnz_pad=int(s.get("nnz_pad", 0)),
            ell_lanes=int(s.get("ell_lanes", 0)),
            wire_u24=bool(s.get("wire_u24", False)),
            wire=str(s.get("wire", "")),
            num_replicas=int(s.get("num_replicas", 0)),
            replica_every=int(s.get("replica_every", 1)),
            steps_per_launch=int(s.get("steps_per_launch", 1)),
            ftrl_state_dtype=_ftrl_state_dtype(
                s.get("ftrl_state_dtype", "float32")
            ),
            push_filter=_filter_list(s.get("push_filter")),
            pull_filter=_filter_list(s.get("pull_filter")),
            pull_gather=str(s.get("pull_gather", "auto")),
        )
    if "darlin" in d:
        b = d["darlin"]
        cfg.darlin = BCDConfig(
            num_data_pass=int(b.get("max_pass_of_data", b.get("num_data_pass", 10))),
            feature_block_ratio=float(b.get("feature_block_ratio", 4.0)),
            random_feature_block_order=bool(b.get("random_feature_block_order", True)),
            max_block_delay=int(b.get("max_block_delay", 0)),
            epsilon=float(b.get("epsilon", 1e-4)),
            save_model_every_n_iter=int(b.get("save_model_every_n_iter", 0)),
            load_local_data=bool(b.get("load_local_data", False)),
            delta_init_value=float(b.get("delta_init_value", 1.0)),
            delta_max_value=float(b.get("delta_max_value", 5.0)),
            kkt_filter_threshold_ratio=float(b.get("kkt_filter_threshold_ratio", 10.0)),
            comm_filter=_filter_list(b.get("comm_filter")),
        )
    return cfg
