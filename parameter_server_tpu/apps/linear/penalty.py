"""Penalties and proximal operators.

Counterpart of ``src/app/linear_method/penalty.h``: elastic net
``λ1 |x| + λ2 x²`` with the proximal step
``prox(z, η) = soft(z, λ1 η) / (1 + λ2 η)`` — exactly the reference's
``ElasticNet::proximal``. jnp-traced; used inside FTRL/AdaGrad updaters and
darlin's shrink step.
"""

from __future__ import annotations

import jax.numpy as jnp


class ElasticNet:
    def __init__(self, lambda1: float = 0.0, lambda2: float = 0.0):
        assert lambda1 >= 0 and lambda2 >= 0
        self.lambda1 = float(lambda1)
        self.lambda2 = float(lambda2)

    def eval(self, w) -> jnp.ndarray:
        return self.lambda1 * jnp.sum(jnp.abs(w)) + self.lambda2 * jnp.sum(w * w)

    def proximal(self, z, eta):
        """argmin_x 0.5/η (x-z)² + h(x) (ref penalty.h:proximal)."""
        leta = self.lambda1 * eta
        shrunk = jnp.sign(z) * jnp.maximum(jnp.abs(z) - leta, 0.0)
        return shrunk / (1.0 + self.lambda2 * eta)


def create_penalty(type_: str, lambdas) -> ElasticNet:
    """Factory (ref penalty.h createPenalty): L1 -> (λ1[, λ2]), L2 -> (0, λ)."""
    t = type_.lower()
    lambdas = list(lambdas)
    if t == "l1":
        l1 = lambdas[0]
        l2 = lambdas[1] if len(lambdas) > 1 else 0.0
        return ElasticNet(l1, l2)
    if t == "l2":
        return ElasticNet(0.0, lambdas[0])
    raise ValueError(f"unknown penalty type: {type_}")
