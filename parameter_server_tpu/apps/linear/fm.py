"""Factorization machine on the parameter-server pipeline (TPU-first).

Beyond-parity extension: the reference's linear_method family covers
linear models (its sibling project DiFacto adds FM); this module brings
second-order feature interactions to the same ELL/mesh machinery so a
CTR user of the framework gets FM without leaving it.

Model (binary features, the CTR case):

    f(x) = b + sum_i w_i + 0.5 * (||sum_i v_i||^2 - sum_i ||v_i||^2)

over the active slots i of a row — the O(nnz * k) identity for the
pairwise term. Embeddings live in a ``[slots, k]`` table sharded over the
server mesh axis exactly like the linear table (key-range sharding);
gradients scatter-add per shard and psum across the data axis, and every
parameter updates with AdaGrad + proximal elastic-net (ref
AdaGradEntry::Set semantics, async_sgd.h).

The wire is the ELL row-block format from async_sgd (``prep_batch_ell``):
uniform lanes, hashed directory, binary features.
"""

from __future__ import annotations

from typing import Dict, Optional

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ...utils.compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ...learner.sgd import ISGDCompNode, SGDProgress
from ...ops.kv_ops import localize, valid_slots
from ...parallel import mesh as meshlib
from ...parallel.mesh import DATA_AXIS, SERVER_AXIS
from ...parameter.parameter import KeyDirectory, pad_slots
from ...system.message import Task
from ...utils import evaluation
from ...utils.sparse import SparseBatch
from .async_sgd import _progress_metrics
from .config import Config
from .learning_rate import LearningRate
from .loss import create_loss
from .penalty import create_penalty


def make_fm_step(
    mesh,
    num_slots: int,
    k: int,
    loss,
    penalty,
    lr: LearningRate,
    v_lr_scale: float,
    with_aux: bool = True,
):
    """Fused SPMD FM step over an ELLBatch (binary): pull w and V at the
    batch's slots, forward with the O(nnz*k) pairwise identity, scatter
    per-slot gradients, AdaGrad-update both tables + the global bias."""
    n_server = meshlib.num_servers(mesh)
    shard = num_slots // n_server

    def local_step(state, y, mask, slots):
        y, mask, slots = y[0], mask[0], slots[0]  # [R], [R], [R, K]
        flat = slots.reshape(-1)
        rel, ok = localize(flat, shard)

        # -- pull: gather w and V entries from the owning shard --
        w_e = jax.lax.psum(
            jnp.where(ok, state["w"][rel], 0.0), SERVER_AXIS
        ).reshape(slots.shape)  # [R, K]
        v_e = jax.lax.psum(
            jnp.where(ok[:, None], state["v"][rel], 0.0), SERVER_AXIS
        ).reshape(slots.shape + (k,))  # [R, K, k]
        live = valid_slots(slots, num_slots).astype(jnp.float32)  # sentinels -> 0
        w_e = w_e * live
        v_e = v_e * live[..., None]

        # -- forward: linear + O(nnz*k) pairwise identity --
        s = v_e.sum(axis=1)  # [R, k]
        pair = 0.5 * (
            jnp.sum(s * s, axis=1) - jnp.sum(v_e * v_e, axis=(1, 2))
        )  # [R]
        xw = state["b"] + w_e.sum(axis=1) + pair

        gr = loss.row_grad(y, xw) * mask  # [R]

        # -- push: per-entry grads, scatter-add into the owned shard --
        gw_flat = jnp.broadcast_to(gr[:, None], slots.shape).reshape(-1)
        gv = gr[:, None, None] * (s[:, None, :] - v_e)  # [R, K, k]
        gv_flat = gv.reshape(-1, k)
        lanes_live = (live.reshape(-1) > 0) & ok
        g_w = jnp.zeros((shard,), jnp.float32).at[rel].add(
            jnp.where(lanes_live, gw_flat, 0.0)
        )
        g_v = jnp.zeros((shard, k), jnp.float32).at[rel].add(
            jnp.where(lanes_live[:, None], gv_flat, 0.0)
        )
        g_w = jax.lax.psum(g_w, DATA_AXIS)
        g_v = jax.lax.psum(g_v, DATA_AXIS)
        g_b = jax.lax.psum(jnp.sum(gr), DATA_AXIS)
        touched = g_w != 0  # FM embeddings ride the linear support

        # -- AdaGrad + proximal update (ref AdaGradEntry::Set) --
        w_ss = state["w_ss"] + g_w * g_w
        eta_w = lr.eval(jnp.sqrt(w_ss))
        w_new = penalty.proximal(state["w"] - eta_w * g_w, eta_w)
        v_ss = state["v_ss"] + g_v * g_v
        eta_v = v_lr_scale * lr.eval(jnp.sqrt(v_ss))
        v_new = state["v"] - eta_v * g_v  # embeddings: no L1 (dense factors)
        b_ss = state["b_ss"] + g_b * g_b
        b_new = state["b"] - lr.eval(jnp.sqrt(b_ss)) * g_b

        new_state = {
            "w": jnp.where(touched, w_new, state["w"]),
            "w_ss": jnp.where(touched, w_ss, state["w_ss"]),
            "v": jnp.where(touched[:, None], v_new, state["v"]),
            "v_ss": jnp.where(touched[:, None], v_ss, state["v_ss"]),
            "b": b_new,
            "b_ss": b_ss,
        }
        return new_state, _progress_metrics(loss, y, xw, mask, with_aux)

    def state_spec(state):
        return jax.tree.map(
            lambda leaf: P(SERVER_AXIS) if leaf.ndim >= 1 else P(), state
        )

    # donate the sharded tables: the update writes them anyway and
    # the worker always rebinds (self.state = new_state); aliasing
    # input->output halves the table HBM footprint (as in async_sgd)
    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(state, batch_y, batch_mask, batch_slots):
        specs = state_spec(state)
        return shard_map(
            local_step,
            mesh=mesh,
            in_specs=(specs, P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
            out_specs=(specs, P()),
            check_vma=False,
        )(state, batch_y, batch_mask, batch_slots)

    return step


class FMWorker(ISGDCompNode):
    """Async FM trainer on the data x server mesh.

    Same consumption API as AsyncSGDWorker (``process_minibatch`` /
    ``collect`` / ``train`` / ``evaluate``); the table is hashed with the
    configured modulus (elastic-resize stable) and the batch wire is the
    ELL row-block format."""

    def __init__(
        self,
        conf: Config,
        k: int = 8,
        mesh=None,
        v_init_std: float = 0.01,
        v_lr_scale: float = 1.0,
        seed: int = 0,
        name: str = "fm_worker",
    ):
        super().__init__(name=name)
        sgd = conf.async_sgd
        assert sgd is not None and sgd.ell_lanes > 0, (
            "FM needs async_sgd conf with ell_lanes (uniform ELL rows)"
        )
        if mesh is None:
            mesh = self.po.mesh
        self.mesh = mesh
        self.sgd = sgd
        self.k = int(k)
        self.loss = create_loss(conf.loss.type)
        self.penalty = create_penalty(conf.penalty.type, conf.penalty.lambda_)
        self.lr = LearningRate(
            conf.learning_rate.type, conf.learning_rate.alpha,
            conf.learning_rate.beta,
        )
        self.num_slots = pad_slots(sgd.num_slots, meshlib.num_servers(mesh))
        self.directory = KeyDirectory(sgd.num_slots, hashed=True)
        # direct-to-sharded init (rationale at meshlib.init_sharded);
        # v uses on-device PRNG so the table never crosses the host link
        def _init():
            n, k = self.num_slots, self.k
            return {
                "w": jnp.zeros((n,), jnp.float32),
                "w_ss": jnp.zeros((n,), jnp.float32),
                "v": v_init_std * jax.random.normal(
                    jax.random.PRNGKey(seed), (n, k), jnp.float32
                ),
                "v_ss": jnp.zeros((n, k), jnp.float32),
                "b": jnp.zeros((), jnp.float32),
                "b_ss": jnp.zeros((), jnp.float32),
            }

        self.state = meshlib.init_sharded(_init, mesh)
        self._step = make_fm_step(
            mesh, self.num_slots, self.k, self.loss, self.penalty, self.lr,
            v_lr_scale,
        )
        self._rows_pad: Optional[int] = None
        self.progress = SGDProgress()

    def process_minibatch(self, batch: SparseBatch) -> int:
        prepped = self._prep_ell(batch)  # shared base prep (ISGDCompNode)

        def run():
            new_state, metrics = self._step(
                self.state, prepped.y, prepped.mask, prepped.slots
            )
            self.state = new_state
            return metrics

        return self.submit(run, Task())

    def wipe_server_shard(self, shard: int) -> None:
        """Simulate/acknowledge a dead server shard: zero its segment
        (same contract as AsyncSGDWorker.wipe_server_shard)."""
        n_server = meshlib.num_servers(self.mesh)
        per = self.num_slots // n_server
        lo, hi = shard * per, (shard + 1) * per

        def z(leaf):
            if np.ndim(leaf) >= 1:
                return leaf.at[lo:hi].set(0.0)
            return leaf

        self.executor.wait_all(pop=False)
        self.state = jax.tree.map(z, self.state)

    def recover_server_shard(self, shard: int) -> bool:
        """FM keeps no ongoing replica (configure checkpoints for
        durability): crash recovery reports failure so the elastic
        coordinator shrinks around the dead range instead."""
        del shard
        return False

    # collect/train: inherited from ISGDCompNode (shared worker plumbing)

    def state_host(self) -> dict:
        """Host snapshot for live migration (same contract as
        AsyncSGDWorker.state_host — ElasticCoordinator.resize uses it)."""
        self.executor.wait_all(pop=False)
        return {"state": jax.tree.map(np.asarray, self.state)}

    def load_state_host(self, snap: dict) -> None:
        def fit(leaf):
            leaf = np.asarray(leaf)
            if leaf.ndim >= 1 and leaf.shape[0] != self.num_slots:
                if leaf.shape[0] > self.num_slots:
                    leaf = leaf[: self.num_slots]
                else:
                    pad = np.zeros(
                        (self.num_slots - leaf.shape[0],) + leaf.shape[1:],
                        leaf.dtype,
                    )
                    leaf = np.concatenate([leaf, pad])
            return jax.device_put(
                leaf,
                NamedSharding(
                    self.mesh, P(SERVER_AXIS, *([None] * (np.ndim(leaf) - 1)))
                    if np.ndim(leaf) >= 1 else P()
                ),
            )

        self.state = jax.tree.map(fit, snap["state"])

    def predict_margin(self, batch: SparseBatch) -> np.ndarray:
        """Host-side vectorized forward pass (evaluation path): per-row
        segment sums via ``np.add.reduceat`` — O(nnz*k), no Python loop."""
        # settle in-flight steps (state swaps on the executor thread) so
        # the margin reads ONE consistent state version, not a mix
        self.executor.wait_all(pop=False)
        w = np.asarray(self.state["w"]).astype(np.float64)
        v = np.asarray(self.state["v"]).astype(np.float64)
        b = float(self.state["b"])
        if batch.n == 0:
            return np.zeros(0, np.float32)
        slots = self.directory.slots(batch.indices)
        counts = np.diff(batch.indptr)
        seg = batch.indptr[:-1].astype(np.int64)
        # reduceat misbehaves on empty segments (repeated offsets) — mask
        # those rows to the bias afterwards
        safe_seg = np.minimum(seg, max(batch.nnz - 1, 0))
        vs = v[slots]  # [nnz, k]
        sum_w = np.add.reduceat(w[slots], safe_seg) if batch.nnz else np.zeros(batch.n)
        sum_v = np.add.reduceat(vs, safe_seg, axis=0) if batch.nnz else np.zeros((batch.n, v.shape[1]))
        sum_v2 = (
            np.add.reduceat((vs * vs).sum(axis=1), safe_seg)
            if batch.nnz
            else np.zeros(batch.n)
        )
        out = b + sum_w + 0.5 * ((sum_v * sum_v).sum(axis=1) - sum_v2)
        out = np.where(counts > 0, out, b)
        return out.astype(np.float32)

    def evaluate(self, batch: SparseBatch) -> Dict[str, float]:
        xw = self.predict_margin(batch)
        y = batch.y
        ll = float(np.mean(np.logaddexp(0.0, -y * xw)))
        return {"auc": evaluation.auc(y, xw), "logloss": ll}
