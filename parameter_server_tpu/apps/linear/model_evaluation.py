"""Model evaluation app (ref ``src/app/linear_method/model_evaluation.h``):
load a saved text model (key\\tweight per line, possibly several shard
files), stream validation data, compute AUC/accuracy/logloss."""

from __future__ import annotations

from typing import Dict

import numpy as np

from ...data.stream_reader import StreamReader
from ...system.customer import App
from ...utils import evaluation
from ...utils import file as psfile
from .config import Config


class ModelEvaluation(App):
    def __init__(self, conf: Config, name: str = "model_evaluation"):
        super().__init__(name=name)
        self.conf = conf
        self.metrics: Dict[str, float] = {}

    def load_model(self) -> Dict[int, float]:
        """Parse key\\tvalue model files (ref Run() model load loop).

        A ``#hashed <num_slots>`` header (async SGD hashed-directory export)
        sets ``self.hashed_slots`` so validation keys are routed through the
        same hash before lookup.
        """
        assert self.conf.model_input is not None, "model_input required"
        weight: Dict[int, float] = {}
        self.hashed_slots = 0
        for path in psfile.expand_globs(self.conf.model_input.file):
            with psfile.open_read(path) as f:
                for line in f:
                    parts = line.split()
                    if not parts:
                        continue
                    if parts[0] == "#hashed":
                        self.hashed_slots = int(parts[1])
                        continue
                    if len(parts) >= 2:
                        weight[int(parts[0])] = float(parts[1])
        return weight

    def run(self) -> Dict[str, float]:
        weight = self.load_model()
        keys = np.fromiter(weight.keys(), dtype=np.int64, count=len(weight))
        vals = np.fromiter(weight.values(), dtype=np.float32, count=len(weight))
        order = np.argsort(keys)
        keys, vals = keys[order], vals[order]

        vd = self.conf.validation_data
        assert vd is not None, "validation_data required"
        reader = StreamReader(vd.file, vd.text if vd.format == "text" else vd.format)
        ys, xws = [], []
        hashed_dir = None
        if getattr(self, "hashed_slots", 0):
            from ...parameter.parameter import KeyDirectory

            hashed_dir = KeyDirectory(self.hashed_slots, hashed=True)
        for batch in reader.minibatches(1 << 14):
            xw = np.zeros(batch.n, np.float32)
            if len(keys):
                lookup = (
                    hashed_dir.slots(batch.indices).astype(np.int64)
                    if hashed_dir is not None
                    else batch.indices
                )
                pos = np.searchsorted(keys, lookup)
                posc = np.minimum(pos, len(keys) - 1)
                hit = (pos < len(keys)) & (keys[posc] == lookup)
                w_e = np.where(hit, vals[posc], 0.0).astype(np.float32)
                np.add.at(xw, batch.row_ids(), batch.value_array() * w_e)
            ys.append(batch.y)
            xws.append(xw)
        y = np.concatenate(ys) if ys else np.zeros(0, np.float32)
        xw = np.concatenate(xws) if xws else np.zeros(0, np.float32)
        self.metrics = {
            "num_examples": float(len(y)),
            "auc": evaluation.auc(y, xw),
            "accuracy": evaluation.accuracy(y, xw),
            "logloss": evaluation.logloss(y, xw),
        }
        # ref prints "auc: %f, accuracy: %f"
        print(
            f"auc: {self.metrics['auc']:.6f}, accuracy: {self.metrics['accuracy']:.6f}, "
            f"logloss: {self.metrics['logloss']:.6f} ({int(self.metrics['num_examples'])} examples)"
        )
        return self.metrics
