"""SGD learner framework (ref ``src/learner/sgd.{h,cc}``).

- ``SGDProgress``: the progress record (ref learner/proto/sgd.proto).
- ``ISGDScheduler``: workload pool + monitor + progress table printing
  (ref ISGDScheduler::Run / ShowProgress / MergeProgress).
- ``ISGDCompNode``: computation node base with a reporter slaver.
- ``MinibatchReader``: prefetching minibatch source with countmin
  tail-feature filtering and key localization (ref MinibatchReader<V>),
  running read + filter on an ``learner.ingest.IngestPipeline`` feeder
  thread (the staged-parallel host-ingest plane).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional

import numpy as np

from ..data.stream_reader import StreamReader
from ..filter.frequency import FrequencyFilter
from ..parameter.replica import Checkpointable
from ..system.customer import App
from ..system.monitor import MonitorMaster, MonitorSlaver
from ..utils.localizer import Localizer
from ..utils.sparse import SparseBatch
from .workload_pool import WorkloadPool


@dataclasses.dataclass
class SGDProgress:
    """ref sgd.proto SGDProgress."""

    objective: List[float] = dataclasses.field(default_factory=list)
    num_examples_processed: int = 0
    accuracy: List[float] = dataclasses.field(default_factory=list)
    auc: List[float] = dataclasses.field(default_factory=list)
    nnz: int = 0
    weight_sum: float = 0.0
    delta_sum: float = 0.0

    def merge(self, other: "SGDProgress") -> None:
        """ref ISGDScheduler::MergeProgress."""
        self.objective.extend(other.objective)
        self.accuracy.extend(other.accuracy)
        self.auc.extend(other.auc)
        self.num_examples_processed += other.num_examples_processed
        self.nnz = other.nnz or self.nnz
        self.weight_sum += other.weight_sum
        self.delta_sum += other.delta_sum


class ISGDScheduler(App):
    """Scheduler: hands workloads to comp nodes, merges progress, prints the
    live table (ref ISGDScheduler::Run + ShowProgress)."""

    def __init__(self, workload_pool: Optional[WorkloadPool] = None, name: str = "sgd_scheduler"):
        super().__init__(name=name)
        self.workload_pool = workload_pool or WorkloadPool()
        self.monitor: MonitorMaster[SGDProgress] = MonitorMaster()
        self.monitor.set_data_merger(lambda src, dst: dst.merge(src))
        self._show_prog_head = True
        self.num_ex_processed = 0

    def show_progress(self, elapsed: float, progress: Dict[str, SGDProgress]) -> None:
        """ref ISGDScheduler::ShowProgress — one merged line per interval."""
        total = SGDProgress()
        for p in progress.values():
            total.merge(p)
        if not total.objective:
            return
        if self._show_prog_head:
            print(" sec  examples    loss      auc   accuracy")
            self._show_prog_head = False
        self.num_ex_processed += total.num_examples_processed
        # objective entries are per-minibatch sums; display per-example loss
        per_ex = sum(total.objective) / max(1, total.num_examples_processed)
        print(
            f"{elapsed:4.0f}  {self.num_ex_processed:.2e}  "
            f"{per_ex:.5f}  {np.mean(total.auc or [0]):.4f}  "
            f"{np.mean(total.accuracy or [0]):.4f}"
        )
        for p in progress.values():  # reset accumulation window
            p.objective.clear()
            p.auc.clear()
            p.accuracy.clear()
            p.num_examples_processed = 0

    def run(self) -> None:
        self.monitor.set_printer(self.show_progress, interval=1.0)


class ISGDCompNode(App, Checkpointable):
    """ref ISGDCompNode: has a reporter to the scheduler's monitor.

    Also the single home of the worker-side progress plumbing shared by
    every SGD-family worker (AsyncSGDWorker, FMWorker, DeepCTRWorker):
    ``collect`` (wait on a step, fold metrics into ``self.progress``,
    heartbeat + dashboard timers, per-minibatch AUC incl. the scan-
    superstep layout) and the default ``train`` loop. Subclasses provide
    ``self.progress`` (an SGDProgress) and ``process_minibatch``."""

    def __init__(self, name: str = "sgd_comp", monitor: Optional[MonitorMaster] = None):
        super().__init__(name=name)
        self.reporter: MonitorSlaver[SGDProgress] = MonitorSlaver(monitor, name)
        # app-layer telemetry (doc/OBSERVABILITY.md): device-confirmed
        # training volume, counted in collect() where the step's metrics
        # land — a cold path shared by every SGD-family worker
        self._examples_counter = None
        # learning truth plane (telemetry/learning.py): workers that
        # know their table geometry install one (AsyncSGDWorker does);
        # collect() folds the step's device-confirmed example count and
        # the in-jit convergence side outputs into it
        self._learning = None
        # self-driving consistency (learner/consistency.py): installed
        # by workers running the adaptive τ controller and/or the KKT
        # significance filter; collect() hands it each step's metrics
        # AFTER the learning plane folds them (the controller reads the
        # plane's judgments, it never re-derives them)
        self._consistency = None
        from ..telemetry import registry as telemetry_registry

        if telemetry_registry.enabled():
            from ..telemetry.instruments import app_instruments

            self._examples_counter = app_instruments(
                telemetry_registry.default_registry()
            )["examples"]

    def attach_monitor(self, scheduler: ISGDScheduler) -> None:
        self.reporter = MonitorSlaver(scheduler.monitor, self.name)

    def collect(self, ts: int) -> SGDProgress:
        """Wait for a step and fold its metrics into progress (the
        worker's reporter_.Report path)."""
        from ..utils import evaluation

        self.po.beat(self.name)  # liveness signal (ref heartbeat thread)
        hb = self.po.aux.info(self.name) if self.po.aux is not None else None
        if hb is not None:
            hb.start_timer()  # dashboard busy-time (ref heartbeat_info.h)
        metrics = self.executor.wait(ts)
        if hb is not None:
            hb.stop_timer()
        if metrics is None:
            return self.progress
        if self._examples_counter is not None:
            self._examples_counter.inc(int(metrics["num_ex"]))
        if self._learning is not None:
            # the progress plane's device-confirmed side: the step's
            # own num_ex output plus the in-jit loss/grad/update/weight
            # side outputs, metered host-side (PR 8 jit-purity pattern)
            self._learning.note_step(metrics)
        if self._consistency is not None:
            # adaptive τ / KKT accounting / divergence reaction — may
            # back off LR, clamp τ, and roll state back to the last
            # healthy snapshot (the exceptional path; collect-thread
            # only, like everything else in this method)
            self._consistency.on_collect(metrics)
        prog = SGDProgress(
            objective=[float(metrics["objective"])],
            num_examples_processed=int(metrics["num_ex"]),
            accuracy=[
                float(metrics["correct"]) / max(1.0, float(metrics["num_ex"]))
            ],
        )
        if "xw" in metrics:  # aux present: per-minibatch AUC (prog.add_auc)
            y = np.asarray(metrics["y"])
            xw = np.asarray(metrics["xw"])
            mask = np.asarray(metrics["mask"])
            if xw.ndim >= 3:
                # scan superstep: leading ministep axis — one AUC per
                # ministep (each scored against its own weight version),
                # preserving the per-minibatch monitoring granularity
                prog.auc = [
                    evaluation.auc(
                        y[t].ravel()[mask[t].ravel() > 0],
                        xw[t].ravel()[mask[t].ravel() > 0],
                    )
                    for t in range(xw.shape[0])
                ]
            else:
                m = mask.ravel() > 0
                prog.auc = [evaluation.auc(y.ravel()[m], xw.ravel()[m])]
        self.progress.merge(prog)
        self.reporter.report(prog)
        return prog

    def train(self, batches) -> SGDProgress:
        """Default minibatch loop: keep a small in-flight window so the
        device pipeline stays fed while metrics drain."""
        pending = []
        for b in batches:
            pending.append(self.process_minibatch(b))
            if len(pending) > 2:
                self.collect(pending.pop(0))
        for ts in pending:
            self.collect(ts)
        return self.progress

    # checkpoint/restore: inherited from replica.Checkpointable via the
    # state_host/load_state_host hooks (state_host drains with
    # pop=False, so metrics of in-flight steps remain collectable)

    def _prep_ell(self, batch):
        """Shared ELL prep for the embedding-table workers (FM, DeepCTR):
        ceil-divide rows over the data shards, size the row padding from
        the conf or the first batch, refuse batches that outgrow the
        compiled padding. Requires ``self.sgd/.directory/.num_slots`` and
        a ``self._rows_pad`` slot (None until first use)."""
        from ..apps.linear.async_sgd import prep_batch_ell  # lazy: apps import us
        from ..parallel import mesh as meshlib

        d = meshlib.num_workers(self.mesh)
        if self._rows_pad is None:
            self._rows_pad = self.sgd.rows_pad or -(-batch.n // d)
        if -(-batch.n // d) > self._rows_pad:
            raise ValueError(
                f"batch of {batch.n} rows exceeds the compiled padding "
                f"({self._rows_pad} rows/shard x {d} shards); set "
                "SGDConfig.rows_pad to the largest minibatch up front"
            )
        return prep_batch_ell(
            batch, self.directory, d, self._rows_pad, self.sgd.ell_lanes,
            self.num_slots,
        )


def apply_tail_filter(
    batch: SparseBatch, filter_: FrequencyFilter, freq: int
) -> SparseBatch:
    """One batch through the countmin tail-feature filter: insert this
    batch's unique keys, drop entries whose estimated frequency is
    below ``freq`` (ref MinibatchReader::Read, sgd.h:117-135). STATEFUL
    — batches must pass through in stream order for a deterministic
    result, which is why the ingest pipeline keeps this stage serial on
    the feeder thread."""
    loc = Localizer()
    # one unique pass serves both the sketch update and the remap
    # (count_uniq_index == count_uniq_keys + the retained inverse)
    keys, cnt = loc.count_uniq_index(batch)
    filter_.insert_keys(keys, cnt)
    keep = filter_.query_keys(keys, freq)
    local = loc.remap_index(keep)
    # restore global key ids so downstream sees a normal batch
    local.indices = keep[local.indices]
    local.num_cols = batch.num_cols
    return local


class MinibatchReader:
    """Prefetching minibatch reader (ref MinibatchReader<V>, sgd.h:60-143).

    Streams SparseBatches from files and filters tail features with a
    countmin sketch, both OFF the trainer thread: reading and filtering
    run on an :class:`~..learner.ingest.IngestPipeline` feeder thread
    behind a bounded queue, so the consumer only pays a queue pop. Keys
    stay global — the worker's ``prep_batch`` does the final remap to
    table slots.

    Lifecycle (enforced): call :meth:`start` before reading (``start``
    is idempotent), and :meth:`close` when done — it stops and joins
    the producer thread. Usable as a context manager.
    """

    def __init__(
        self,
        files: Optional[List[str]] = None,
        minibatch_size: int = 1000,
        data_format: str = "libsvm",
        capacity: int = 16,
        batches: Optional[Iterator[SparseBatch]] = None,
    ):
        self._source: Optional[Iterator[SparseBatch]] = batches
        if self._source is None:
            reader = StreamReader(files or [], data_format)
            # chunked byte parse: raw line-aligned chunks go straight
            # into the GIL-releasing native parser on a small pool
            # (falls back to the line path for formats without one);
            # bit-identical to minibatches() — tests/test_data.py
            # TestByteStreaming
            self._source = reader.minibatches_bytes(
                minibatch_size, threads=2
            )
        self._filter: Optional[FrequencyFilter] = None
        self._freq = 0
        self._capacity = capacity
        self._pipe: Optional["IngestPipeline"] = None
        self._it: Optional[Iterator[SparseBatch]] = None
        self._closed = False

    def init_filter(self, n: int, k: int, freq: int) -> None:
        """Countmin tail-feature filter (ref InitFilter); set before
        :meth:`start`."""
        if self._pipe is not None:
            raise RuntimeError("init_filter() after start()")
        self._filter = FrequencyFilter(n, k)
        self._freq = freq

    def start(self) -> "MinibatchReader":
        """Start the producer thread. Idempotent: a second call is a
        no-op (the reference's _started flag, now enforced)."""
        if self._closed:
            raise RuntimeError("MinibatchReader.start() after close()")
        if self._pipe is not None:
            return self
        from .ingest import IngestPipeline

        filter_fn = None
        if self._filter is not None and self._freq > 0:
            filt, freq = self._filter, self._freq
            filter_fn = lambda b: apply_tail_filter(b, filt, freq)  # noqa: E731
        self._pipe = IngestPipeline(
            self._source,
            filter_fn=filter_fn,
            capacity=self._capacity,
            name="minibatch_reader",
        ).start()
        self._it = iter(self._pipe)
        return self

    def read(self) -> Optional[SparseBatch]:
        """Next minibatch with tail features dropped (ref Read), or
        None at end of stream. Raises if the reader was never started
        or already closed, and re-raises producer exceptions."""
        if self._pipe is None or self._it is None:
            raise RuntimeError(
                "MinibatchReader.read() before start(): call start() "
                "first, or use the reader as a context manager"
            )
        if self._closed:
            raise RuntimeError("MinibatchReader.read() after close()")
        return next(self._it, None)

    def close(self) -> None:
        """Stop the pipeline and join the producer thread; idempotent."""
        self._closed = True
        if self._pipe is not None:
            self._pipe.close()

    def __enter__(self) -> "MinibatchReader":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def __iter__(self) -> Iterator[SparseBatch]:
        while True:
            b = self.read()
            if b is None:
                return
            yield b
