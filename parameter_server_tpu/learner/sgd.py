"""SGD learner framework (ref ``src/learner/sgd.{h,cc}``).

- ``SGDProgress``: the progress record (ref learner/proto/sgd.proto).
- ``ISGDScheduler``: workload pool + monitor + progress table printing
  (ref ISGDScheduler::Run / ShowProgress / MergeProgress).
- ``ISGDCompNode``: computation node base with a reporter slaver.
- ``MinibatchReader``: prefetching minibatch source with countmin
  tail-feature filtering and key localization (ref MinibatchReader<V>).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional

import numpy as np

from ..data.stream_reader import StreamReader
from ..filter.frequency import FrequencyFilter
from ..system.customer import App
from ..system.monitor import MonitorMaster, MonitorSlaver
from ..utils.concurrent import ProducerConsumer
from ..utils.localizer import Localizer, count_uniq_keys
from ..utils.sparse import SparseBatch
from .workload_pool import WorkloadPool


@dataclasses.dataclass
class SGDProgress:
    """ref sgd.proto SGDProgress."""

    objective: List[float] = dataclasses.field(default_factory=list)
    num_examples_processed: int = 0
    accuracy: List[float] = dataclasses.field(default_factory=list)
    auc: List[float] = dataclasses.field(default_factory=list)
    nnz: int = 0
    weight_sum: float = 0.0
    delta_sum: float = 0.0

    def merge(self, other: "SGDProgress") -> None:
        """ref ISGDScheduler::MergeProgress."""
        self.objective.extend(other.objective)
        self.accuracy.extend(other.accuracy)
        self.auc.extend(other.auc)
        self.num_examples_processed += other.num_examples_processed
        self.nnz = other.nnz or self.nnz
        self.weight_sum += other.weight_sum
        self.delta_sum += other.delta_sum


class ISGDScheduler(App):
    """Scheduler: hands workloads to comp nodes, merges progress, prints the
    live table (ref ISGDScheduler::Run + ShowProgress)."""

    def __init__(self, workload_pool: Optional[WorkloadPool] = None, name: str = "sgd_scheduler"):
        super().__init__(name=name)
        self.workload_pool = workload_pool or WorkloadPool()
        self.monitor: MonitorMaster[SGDProgress] = MonitorMaster()
        self.monitor.set_data_merger(lambda src, dst: dst.merge(src))
        self._show_prog_head = True
        self.num_ex_processed = 0

    def show_progress(self, elapsed: float, progress: Dict[str, SGDProgress]) -> None:
        """ref ISGDScheduler::ShowProgress — one merged line per interval."""
        total = SGDProgress()
        for p in progress.values():
            total.merge(p)
        if not total.objective:
            return
        if self._show_prog_head:
            print(" sec  examples    loss      auc   accuracy")
            self._show_prog_head = False
        self.num_ex_processed += total.num_examples_processed
        # objective entries are per-minibatch sums; display per-example loss
        per_ex = sum(total.objective) / max(1, total.num_examples_processed)
        print(
            f"{elapsed:4.0f}  {self.num_ex_processed:.2e}  "
            f"{per_ex:.5f}  {np.mean(total.auc or [0]):.4f}  "
            f"{np.mean(total.accuracy or [0]):.4f}"
        )
        for p in progress.values():  # reset accumulation window
            p.objective.clear()
            p.auc.clear()
            p.accuracy.clear()
            p.num_examples_processed = 0

    def run(self) -> None:
        self.monitor.set_printer(self.show_progress, interval=1.0)


class ISGDCompNode(App):
    """ref ISGDCompNode: has a reporter to the scheduler's monitor."""

    def __init__(self, name: str = "sgd_comp", monitor: Optional[MonitorMaster] = None):
        super().__init__(name=name)
        self.reporter: MonitorSlaver[SGDProgress] = MonitorSlaver(monitor, name)

    def attach_monitor(self, scheduler: ISGDScheduler) -> None:
        self.reporter = MonitorSlaver(scheduler.monitor, self.name)


class MinibatchReader:
    """Prefetching minibatch reader (ref MinibatchReader<V>, sgd.h:60-143).

    Streams SparseBatches from files, filters tail features with a countmin
    sketch, and yields (batch, uniq_keys) with keys still global — the
    worker's ``prep_batch`` does the final remap to table slots.
    """

    def __init__(
        self,
        files: Optional[List[str]] = None,
        minibatch_size: int = 1000,
        data_format: str = "libsvm",
        capacity: int = 16,
        batches: Optional[Iterator[SparseBatch]] = None,
    ):
        self._source: Optional[Iterator[SparseBatch]] = batches
        if self._source is None:
            reader = StreamReader(files or [], data_format)
            self._source = reader.minibatches(minibatch_size)
        self._filter: Optional[FrequencyFilter] = None
        self._freq = 0
        self._pc: ProducerConsumer[SparseBatch] = ProducerConsumer(capacity)
        self._started = False

    def init_filter(self, n: int, k: int, freq: int) -> None:
        """Countmin tail-feature filter (ref InitFilter)."""
        self._filter = FrequencyFilter(n, k)
        self._freq = freq

    def start(self) -> None:
        src = self._source

        def produce() -> Optional[SparseBatch]:
            return next(src, None)

        self._pc.start_producer(produce)
        self._started = True

    def read(self) -> Optional[SparseBatch]:
        """Next minibatch with tail features dropped (ref Read)."""
        if not self._started:
            self.start()
        batch = self._pc.pop()
        if batch is None:
            return None
        if self._filter is not None and self._freq > 0:
            keys, cnt = count_uniq_keys(batch)
            self._filter.insert_keys(keys, cnt)
            keep = self._filter.query_keys(keys, self._freq)
            loc = Localizer()
            loc.count_uniq_index(batch)
            local = loc.remap_index(keep)
            # restore global key ids so downstream sees a normal batch
            local.indices = keep[local.indices]
            local.num_cols = batch.num_cols
            return local
        return batch

    def __iter__(self) -> Iterator[SparseBatch]:
        while True:
            b = self.read()
            if b is None:
                return
            yield b
