"""Self-driving consistency: adaptive τ + KKT significance accounting.

PR 15 made the bounded-delay contract *measured*; this module makes it
*driven*. The OSDI'14 parameter server exposes flexible consistency as
a first-class dial (Li et al., OSDI'14 §3.4) and the NIPS'14 companion
proves convergence degrades gracefully with delay (Li et al., NIPS'14)
— which together say τ should be earned from gradient geometry, not
hand-picked: wide while the trajectory is stable (async throughput),
clamped the moment divergence leading indicators move. The same papers'
KKT filter says most keys should never ship at all. Both live here, on
the telemetry plane the reference never had:

- :class:`AdaptiveTauController` — moves the worker's *effective* τ
  between submissions (``AsyncSGDWorker.set_effective_tau``; the
  configured ``max_delay`` stays the contract CAP). Policy: widen one
  ministep after every ``stable_steps`` healthy collects; halve on a
  soft grad-norm spike (its own window median, a gentler factor than
  the learning plane's divergence judge — the controller reacts BEFORE
  the alert would); and on a hard divergence signal (non-finite
  loss/gradient, or the plane's spike judgment) run the full reaction:
  τ→0, automatic LR backoff (step cache re-jit — the exceptional
  recompile path, disclosed), and rollback to the controller's last
  healthy in-memory snapshot through the same ``state_host`` /
  ``load_state_host`` surface the PR 9 recovery machinery replays
  through. The ``consistency.rollback`` fault point fires first, so
  drills can fail the reaction itself.
- :class:`SignificanceTracker` — the host half of the in-jit KKT mask
  (``ops/significance.py``). Meters the mask's per-step suppressed /
  candidate counts into the ``ps_consistency_*`` family AND credits
  the actually-shipped keys to ``ps_push_keys_total`` (store = worker
  name), so the reduction reconciles in-record:
  ``pushed + suppressed == candidates``. With ``kkt_drop_after > 0``
  it also consumes the mask's per-slot feedback to build a
  persistent-drop set: a slot suppressed ``drop_after`` consecutive
  sightings leaves future batches HOST-SIDE (``filter_batch``, called
  from ``prep`` before dedup/padding — those keys never cost upload
  bytes either), with every ``kkt_revisit_every``-th batch shipped
  unfiltered so dropped slots are deterministically revisited and can
  re-earn their place.

Threading (the stateless-or-feeder rule): ``on_collect`` runs on the
collect thread only; ``filter_batch`` runs on the prep thread — serial
by construction, ``kkt_drop_after > 0`` requires ``ingest_workers=1``
(enforced at worker init) because the drop set evolves in collect
order and a concurrent pool would apply it nondeterministically. The
shared drop-set handoff is the one cross-thread edge and is guarded by
a lock.

Determinism: the in-jit mask is seeded (the step's own seed stream),
collects arrive in submission order, and the revisit cadence is a
counter — two runs with the same data, seed, and config make identical
suppression, drop, and τ decisions.
"""

# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import collections
import threading
from typing import Any, Dict, List, Mapping, Optional

import numpy as np

#: healthy collects between τ widenings (one ministep each): stability
#: must be re-earned per notch, so a noisy run climbs slowly
STABLE_STEPS = 8

#: soft-spike factor vs the controller's own grad-norm window median —
#: deliberately far below the learning plane's divergence judge (100x):
#: the controller CLAMPS early so the alert never needs to fire
SOFT_SPIKE_FACTOR = 4.0

#: grad-norm window for the soft-spike median
SPIKE_WINDOW = 32

#: healthy collects before the soft-spike judge activates
SPIKE_MIN_WINDOW = 8

#: healthy collects between rollback snapshots (state_host drains the
#: executor, so this is the knob trading snapshot cost against the
#: rollback blast radius the snapshot_age gauge reports)
SNAPSHOT_EVERY = 16

#: LR multiplier the divergence reaction applies
BACKOFF_FACTOR = 0.5

#: episode records kept for the bench/debug snapshot
EPISODE_CAP = 64


class AdaptiveTauController:
    """Moves one worker's effective τ from its convergence telemetry.

    Collect-thread only (no lock needed on its own state; the runtime
    serializes). Holds the rollback snapshot — plain host arrays from
    ``worker.state_host()`` — and the reaction logic.
    """

    def __init__(
        self,
        worker,
        *,
        stable_steps: int = STABLE_STEPS,
        spike_factor: float = SOFT_SPIKE_FACTOR,
        snapshot_every: int = SNAPSHOT_EVERY,
        backoff_factor: float = BACKOFF_FACTOR,
        tel: Optional[Dict[str, object]] = None,
    ):
        self.worker = worker
        self.tau_max = max(0, int(worker.sgd.max_delay))
        self.stable_steps = max(1, int(stable_steps))
        self.spike_factor = float(spike_factor)
        self.snapshot_every = max(1, int(snapshot_every))
        self.backoff_factor = float(backoff_factor)
        self._tel = tel
        # conservative start: one ministep of slack, widened as
        # stability is earned (τ=0 would serialize warmup for nothing;
        # τ=max would gamble the whole cap on an untested trajectory)
        self.tau = worker.set_effective_tau(min(1, self.tau_max))
        self._stable = 0
        self._grad_window: collections.deque = collections.deque(
            maxlen=SPIKE_WINDOW
        )
        self._snapshot: Optional[dict] = None
        self._snapshot_age = 0
        self._healthy = 0
        self.episodes: List[Dict[str, Any]] = []
        self.tau_trace: List[int] = [self.tau]

    # -- per-collect policy --

    def on_metrics(
        self, loss: float, grad_norm: Optional[float], nonfinite: bool
    ) -> None:
        if nonfinite:
            self.react("nonfinite")
            return
        spike = False
        if grad_norm is not None:
            if len(self._grad_window) >= SPIKE_MIN_WINDOW:
                med = float(np.median(self._grad_window))
                spike = med > 0 and grad_norm > self.spike_factor * med
            self._grad_window.append(grad_norm)
        if spike:
            # leading indicator, not yet divergence: clamp τ hard
            # (halve) but keep LR and state — cheap, reversible, and
            # re-widened within stable_steps collects if it was noise
            self._set_tau(self.tau // 2, "clamp")
            self._stable = 0
            return
        self._healthy += 1
        self._stable += 1
        if self._stable >= self.stable_steps and self.tau < self.tau_max:
            self._set_tau(self.tau + 1, "widen")
            self._stable = 0
        # rollback snapshot on the healthy cadence (first healthy
        # collect included: a reaction before the first cadence tick
        # must still have somewhere to roll back to)
        self._snapshot_age += 1
        if self._snapshot is None or self._healthy % self.snapshot_every == 0:
            self._take_snapshot()
        if self._tel is not None:
            self._tel["snapshot_age"].labels(
                worker=self.worker.name
            ).set(self._snapshot_age)

    def _take_snapshot(self) -> None:
        # state_host drains the executor (pop=False — in-flight
        # metrics stay collectable), so the snapshot is consistent
        self._snapshot = self.worker.state_host()
        self._snapshot_age = 0

    def _set_tau(self, tau: int, direction: str) -> None:
        tau = self.worker.set_effective_tau(tau)
        if tau != self.tau:
            self.tau = tau
            self.tau_trace.append(tau)
            if self._tel is not None:
                self._tel["tau_changes"].labels(
                    worker=self.worker.name, direction=direction
                ).inc()

    # -- the divergence reaction --

    def react(self, reason: str) -> Dict[str, Any]:
        """τ→0 + LR backoff + snapshot rollback. Collect thread only.

        Also the ``loss_divergence`` alert hook: an alert listener can
        call this directly (reason="alert") — it is idempotent per
        episode in effect, since post-rollback state re-earns τ and LR
        stays backed off.
        """
        from ..system import faults

        # the drill point fires BEFORE any state is touched: a drill
        # injecting a raise here proves the caller survives the
        # reaction itself failing (collect propagates the FaultError)
        faults.inject("consistency.rollback", detail=reason)
        worker = self.worker
        self._set_tau(0, "reset")
        self._stable = 0
        self._grad_window.clear()
        # automatic LR backoff. lr.alpha is a trace-time constant
        # closed over by the compiled steps, so the step cache and the
        # weights fn re-jit — the ONE sanctioned recompile path, paid
        # only on the exceptional divergence reaction (the τ sweep
        # stays at recompiles_post_warmup == 0).
        import jax

        worker.lr.alpha = float(worker.lr.alpha) * self.backoff_factor
        worker._steps.clear()
        worker._weights_fn = jax.jit(worker.updater.weights)
        rolled_back = False
        if self._snapshot is not None:
            # drain in-flight steps before installing old state:
            # load_state_host does not drain (its migration caller
            # already has), and a poisoned in-flight step must not
            # land on top of the restored table
            worker.executor.wait_all(pop=False)
            worker.load_state_host(self._snapshot)
            rolled_back = True
        self._snapshot_age = 0
        episode = {
            "reason": reason,
            "healthy_collects": self._healthy,
            "alpha_after": float(worker.lr.alpha),
            "tau_after": self.tau,
            "rolled_back": rolled_back,
        }
        self.episodes.append(episode)
        del self.episodes[:-EPISODE_CAP]
        if self._tel is not None:
            self._tel["backoff"].labels(worker=worker.name).inc()
            if rolled_back:
                self._tel["rollback"].labels(
                    worker=worker.name, reason=reason
                ).inc()
        from ..telemetry import blackbox

        if blackbox.installed_recorder() is not None:
            # armed flight recorder: the whole episode (pre-divergence
            # evidence still in the rings + this reaction) lands in
            # one bundle, keyed to the trigger plane like alert
            # firings are
            blackbox.trigger_bundle("consistency_rollback", detail=reason)
        return episode


class SignificanceTracker:
    """Host accounting + persistent-drop set for the in-jit KKT mask.

    ``note_metrics`` runs on the collect thread; ``filter_batch`` on
    the (serial) prep thread. ``_lock`` guards the handoff.
    """

    def __init__(
        self,
        worker,
        *,
        drop_after: int,
        revisit_every: int,
        tel: Optional[Dict[str, object]] = None,
    ):
        self.worker = worker
        self.num_slots = int(worker.num_slots)
        self.drop_after = int(drop_after)
        self.revisit_every = max(1, int(revisit_every))
        self._tel = tel
        self._push_keys = None
        if tel is not None:
            from ..telemetry import registry as telemetry_registry
            from ..telemetry.instruments import parameter_instruments

            # the worker-side analog of the KV stores' pushed-key
            # accounting: what the filtered sparse step actually
            # shipped, under this worker's store label — the number
            # the suppression counters reconcile against
            self._push_keys = parameter_instruments(
                telemetry_registry.default_registry()
            )["push_keys"].labels(store=worker.name, channel=0)
        self._streaks: Dict[int, int] = {}  # collect thread only
        self._dropped: set = set()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._preps = 0  # prep thread only
        # running totals (collect/prep threads as noted; read via
        # summary() from anywhere — ints, torn reads acceptable)
        self.candidates = 0
        self.suppressed = 0
        self.pushed = 0
        self.dropped_entries = 0
        self.filtered_batches = 0
        self.revisit_batches = 0

    # -- collect side: mask accounting + streaks --

    def note_metrics(self, metrics: Mapping[str, Any]) -> None:
        if "kkt_slots" not in metrics:
            return
        cand = int(round(float(metrics["kkt_slots"])))
        sup = int(round(float(metrics["kkt_suppressed"])))
        self.candidates += cand
        self.suppressed += sup
        self.pushed += cand - sup
        if self._tel is not None:
            w = self.worker.name
            self._tel["candidates"].labels(worker=w).inc(cand)
            self._tel["suppressed"].labels(worker=w).inc(sup)
        if self._push_keys is not None:
            self._push_keys.inc(cand - sup)
        if self.drop_after > 0 and "kkt_keep" in metrics:
            self._note_feedback(
                np.asarray(metrics["kkt_uslots"]),
                np.asarray(metrics["kkt_keep"]),
            )

    def _note_feedback(self, uslots: np.ndarray, keep: np.ndarray) -> None:
        uslots = uslots.reshape(-1)
        keep = keep.reshape(-1).astype(bool)
        real = (uslots >= 0) & (uslots < self.num_slots)
        sup = uslots[real & ~keep]
        kept = uslots[real & keep]
        undropped = []
        for s in kept.tolist():
            self._streaks.pop(s, None)
            undropped.append(s)
        newly: List[int] = []
        for s in sup.tolist():
            streak = self._streaks.get(s, 0) + 1
            if streak >= self.drop_after:
                self._streaks.pop(s, None)
                newly.append(s)
            else:
                self._streaks[s] = streak
        if newly or undropped:
            with self._lock:
                # a kept sighting (a revisit batch, or the escape
                # hatch shipping it) re-earns the slot its place
                self._dropped.difference_update(undropped)
                self._dropped.update(newly)

    # -- prep side: the host drop --

    def filter_batch(self, batch, directory):
        """Drop persistently-suppressed slots from one batch before
        prep (CSR rebuild). Every ``revisit_every``-th batch ships
        unfiltered — the deterministic revisit cadence."""
        self._preps += 1
        if self._preps % self.revisit_every == 0:
            self.revisit_batches += 1
            return batch
        with self._lock:
            if not self._dropped:
                return batch
            dropped = np.fromiter(self._dropped, dtype=np.int64)
        slots = directory.slots(batch.indices)
        keep = ~np.isin(slots, dropped)
        n_drop = int(batch.nnz - keep.sum())
        if n_drop == 0:
            return batch
        rows = batch.row_ids()[keep]
        counts = np.zeros(batch.n, dtype=np.int64)
        np.add.at(counts, rows, 1)
        indptr = np.zeros(batch.n + 1, dtype=batch.indptr.dtype)
        np.cumsum(counts, out=indptr[1:])
        from ..utils.sparse import SparseBatch

        out = SparseBatch(
            y=batch.y,
            indptr=indptr,
            indices=batch.indices[keep],
            values=None if batch.values is None else batch.values[keep],
            num_cols=batch.num_cols,
            slot_ids=None if batch.slot_ids is None else batch.slot_ids[keep],
        )
        self.dropped_entries += n_drop
        self.filtered_batches += 1
        if self._tel is not None:
            self._tel["dropped"].labels(worker=self.worker.name).inc(n_drop)
        return out

    def dropped_slots(self) -> int:
        with self._lock:
            return len(self._dropped)

    def summary(self) -> Dict[str, Any]:
        """Record-embeddable accounting, with the reconciliation
        identity stated in-place (bench records assert it)."""
        return {
            "candidates": self.candidates,
            "suppressed": self.suppressed,
            "pushed": self.pushed,
            "reconciled": self.pushed + self.suppressed == self.candidates,
            "dropped_slots": self.dropped_slots(),
            "dropped_entries": self.dropped_entries,
            "filtered_batches": self.filtered_batches,
            "revisit_batches": self.revisit_batches,
        }


class ConsistencyRuntime:
    """One worker's consistency plane: controller + tracker + hooks.

    Installed by ``AsyncSGDWorker.__init__`` when ``tau_adaptive`` or
    ``kkt_filter`` is set; ``ISGDCompNode.collect`` calls
    :meth:`on_collect`, ``prep`` calls :meth:`filter_batch`.
    """

    def __init__(self, worker, controller, tracker):
        self.worker = worker
        self.controller: Optional[AdaptiveTauController] = controller
        self.tracker: Optional[SignificanceTracker] = tracker

    @classmethod
    def from_config(cls, worker, sgd, **kw) -> "ConsistencyRuntime":
        from ..telemetry import registry as telemetry_registry

        tel = None
        if telemetry_registry.enabled():
            from ..telemetry.instruments import consistency_instruments

            tel = consistency_instruments(
                telemetry_registry.default_registry()
            )
        controller = None
        if sgd.tau_adaptive:
            controller = AdaptiveTauController(worker, tel=tel, **kw)
        tracker = None
        if sgd.kkt_filter:
            tracker = SignificanceTracker(
                worker,
                drop_after=sgd.kkt_drop_after,
                revisit_every=sgd.kkt_revisit_every,
                tel=tel,
            )
        return cls(worker, controller, tracker)

    # -- hooks --

    def on_collect(self, metrics: Mapping[str, Any]) -> None:
        """Collect-thread hook: fold one step's host-materialized
        metrics into the tracker, then run the controller policy."""
        if self.tracker is not None:
            self.tracker.note_metrics(metrics)
        if self.controller is not None:
            import math

            objective = float(metrics.get("objective", 0.0))
            num_ex = int(metrics.get("num_ex", 0))
            loss = objective / max(1, num_ex)
            grad_sq = metrics.get("grad_sq")
            grad_norm = None
            if grad_sq is not None:
                g = float(grad_sq)
                grad_norm = math.sqrt(g) if math.isfinite(g) and g >= 0 else g
            nonfinite = not math.isfinite(loss) or (
                grad_norm is not None and not math.isfinite(grad_norm)
            )
            self.controller.on_metrics(loss, grad_norm, nonfinite)

    def filter_batch(self, batch, directory):
        if self.tracker is None:
            return batch
        return self.tracker.filter_batch(batch, directory)

    def react(self, reason: str = "alert") -> Optional[Dict[str, Any]]:
        """External reaction entry (the loss_divergence alert listener
        path); no-op without a controller."""
        if self.controller is None:
            return None
        return self.controller.react(reason)

    def snapshot(self) -> Dict[str, Any]:
        """The record-embeddable consistency view (bench `consistency`
        section + /debug/snapshot)."""
        out: Dict[str, Any] = {"worker": self.worker.name}
        if self.controller is not None:
            c = self.controller
            out["tau"] = {
                "live": c.tau,
                "cap": c.tau_max,
                "trace": list(c.tau_trace[-64:]),
                "healthy_collects": c._healthy,
                "snapshot_age": c._snapshot_age,
            }
            out["episodes"] = list(c.episodes)
        if self.tracker is not None:
            out["significance"] = self.tracker.summary()
        return out
