"""Compact host→device wire: encoded batch buffers for the upload path.

The on-chip bench records put the device-only rate at 0.6-1.3M
examples/sec while e2e through the host→device link collapses to
68-342k — the link-bound ceiling (bytes/example × link MB/s) IS the
throughput knob. This module is the host half of the compact wire: the
ingest pipeline's prep stage emits *encoded* batch buffers, the jitted
train step decodes them on device (ops/wire_codec.py), and decoded
batches never cross the link.

It is the upload-path realization of the reference's wire filter stack
(src/filter/): each encoding below names the filter whose byte-economy
it transplants from the server wire onto the host→device leg —

- **bit-packed indices** (``ucols``/``uslots`` at ceil(log2 S) bits,
  utils/bitpack.py): the key-stream analog of fixing_float's
  fixed-width values.
- **delta-coded sorted slot arrays** (``uslots`` is np.unique output —
  strictly increasing — so gaps fit u16 and the device reconstructs
  with one exact int32 cumsum): the compressing filter's instinct,
  restricted to a transform XLA can invert.
- **structure elision** (mask → live-row count, COO row ids → per-row
  feature counts, binary values → nothing, ±1 labels → sign bits): the
  sparse filter's drop-what-reconstructs rule.
- **fixed-point / bf16 values** (``wire_encode='int8'|'u16'|'bf16'``,
  filter/fixing_float quantize): the FIXING_FLOAT filter verbatim —
  lossy, stochastic-rounded, gated behind config with a logloss-parity
  bound (tests/test_wire.py).
- **key caching** (:class:`UploadCache`): a repeated array uploads only
  its crc32c signature — filter/key_caching.py semantics (signature
  routes, exact verify against a retained copy decides, same
  ``MAX_SIG_LEN`` prefix budget) with the device-resident buffer as the
  receiver's cache. Multi-epoch passes and eval/replay loops re-ship
  ~nothing.

The default ``exact`` mode is **lossless and bit-identical**: every
encoder VERIFIES its domain assumptions on the actual batch (and
returns None so the caller falls back to the raw wire when they fail),
so decode-on-device reproduces the unencoded stream bit-for-bit —
parity-tested like PR 3's ingest contract.

Concurrency contract (the PR-3 ingest determinism rule): ``encode_*``
are STATELESS and deterministic — pool-able prep stages.
:class:`UploadCache` is STATEFUL and single-owner: it must live on the
(serial) uploader thread, never in the prep pool; it asserts its owner
thread at every call.

``MessageWireCodec`` drives the actual host-side FilterChain
(filter/base.py: compressing → key_caching → fixing_float, decode in
reverse) over batch payloads for the host↔host legs (multi-host ingest
hand-off, replay spill) and for chain round-trip tests — on the
host→device leg the chain's transforms are realized by the jit-side
decode ops instead, which is what keeps the decode inside the step.
"""
# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import List, Optional, Tuple

import jax
import numpy as np

from ..filter.fixing_float import quantize
from ..system.message import FilterSpec, Message, Task
from ..utils import crc32c
from ..utils.bitpack import pack_bits, slot_bits, stream_to_words

# the key-caching filter's signature prefix budget — one constant,
# shared semantics (filter/key_caching.py, parameter.KeyDirectory)
MAX_SIG_LEN = 2048

#: value-stream encodings: mode -> (code dtype, fixing_float num_bytes)
_QUANT_MODES = {"int8": (np.uint8, 1), "u16": (np.uint16, 2)}
WIRE_ENCODE_MODES = ("", "exact", "int8", "u16", "bf16")


def wire_instruments():
    """ps_wire_* instruments against the process registry, or None while
    telemetry is disabled. Cached per registry (the encode runs once
    per batch on every prep-pool worker — telemetry.instruments owns
    the one hot-path cache, same shape as cached_kvops_instruments)."""
    from ..telemetry.instruments import cached_wire_instruments

    return cached_wire_instruments()


def tree_nbytes(tree) -> int:
    """Host bytes of a (possibly encoded) batch tree — what would cross
    the link if uploaded as-is."""
    return int(
        sum(getattr(leaf, "nbytes", 0) for leaf in jax.tree.leaves(tree))
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EncodedExactBatch:
    """PreppedBatch on the compact wire (fields [D, ...] per data shard).

    Static fields pin the decode program (jit keys on them); array
    fields are exactly what crosses the link. ``y`` is sign bits
    (uint8 [D, ceil(R/8)]) when ``y_sign`` else raw float32 [D, R];
    ``uslots`` is a u16 gap stream when ``uslots_delta`` else a
    ceil(log2 S+1)-bit word stream; ``vals`` is absent for binary
    batches, float32 for exact valued ones, u8/u16 codes (+ per-shard
    ``vals_lo``/``vals_hi``) for fixed-point, bfloat16 for bf16."""

    y: np.ndarray
    counts: np.ndarray  # [D] int32 live rows
    row_counts: np.ndarray  # [D, R] u8/u16 features per row
    nnz: np.ndarray  # [D] int32 live COO entries
    ucols_words: np.ndarray  # [D, W] uint32 bit-packed ucols
    uslots: np.ndarray  # [D, U] u16 deltas | [D, W2] uint32 words
    n_uniq: np.ndarray  # [D] int32 live unique slots
    vals: Optional[np.ndarray]
    vals_lo: Optional[np.ndarray]  # [D] float32 (fixed-point modes)
    vals_hi: Optional[np.ndarray]
    rows_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    nnz_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    uniq_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    ucols_bits: int = dataclasses.field(metadata=dict(static=True), default=0)
    uslots_bits: int = dataclasses.field(metadata=dict(static=True), default=0)
    y_sign: bool = dataclasses.field(metadata=dict(static=True), default=False)
    uslots_delta: bool = dataclasses.field(
        metadata=dict(static=True), default=True
    )
    vals_mode: str = dataclasses.field(
        metadata=dict(static=True), default="binary"
    )

    @property
    def num_examples(self) -> int:
        return int(np.asarray(self.counts).sum())

    def static_key(self) -> tuple:
        """The decode-program cache key (everything jit specializes on)."""
        return (
            self.rows_pad, self.nnz_pad, self.uniq_pad, self.ucols_bits,
            self.uslots_bits, self.y_sign, self.uslots_delta, self.vals_mode,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EncodedExactSuperBatch:
    """T stacked EncodedExactBatches (fields [T, D, ...]) — the compact
    wire's scan superbatch: one launch decodes and runs T sequential
    ministeps (the PreppedSuperBatch twin)."""

    y: np.ndarray
    counts: np.ndarray
    row_counts: np.ndarray
    nnz: np.ndarray
    ucols_words: np.ndarray
    uslots: np.ndarray
    n_uniq: np.ndarray
    vals: Optional[np.ndarray]
    vals_lo: Optional[np.ndarray]
    vals_hi: Optional[np.ndarray]
    rows_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    nnz_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    uniq_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    ucols_bits: int = dataclasses.field(metadata=dict(static=True), default=0)
    uslots_bits: int = dataclasses.field(metadata=dict(static=True), default=0)
    y_sign: bool = dataclasses.field(metadata=dict(static=True), default=False)
    uslots_delta: bool = dataclasses.field(
        metadata=dict(static=True), default=True
    )
    vals_mode: str = dataclasses.field(
        metadata=dict(static=True), default="binary"
    )

    @property
    def steps(self) -> int:
        return int(self.counts.shape[0])

    @property
    def num_examples(self) -> int:
        return int(np.asarray(self.counts).sum())

    def static_key(self) -> tuple:
        return (
            self.rows_pad, self.nnz_pad, self.uniq_pad, self.ucols_bits,
            self.uslots_bits, self.y_sign, self.uslots_delta, self.vals_mode,
        )


def stack_encoded_batches(
    parts: List[EncodedExactBatch],
) -> EncodedExactSuperBatch:
    """Stack T encoded minibatches into one scan superbatch. Statics
    must agree across T (they pin ONE decode program)."""
    if not parts:
        raise ValueError("empty superbatch")
    key = parts[0].static_key()
    assert all(p.static_key() == key for p in parts), (
        "encoded superbatch needs uniform static encoding parameters"
    )
    opt = lambda name: (  # noqa: E731
        None
        if getattr(parts[0], name) is None
        else np.stack([getattr(p, name) for p in parts])
    )
    return EncodedExactSuperBatch(
        y=np.stack([p.y for p in parts]),
        counts=np.stack([p.counts for p in parts]),
        row_counts=np.stack([p.row_counts for p in parts]),
        nnz=np.stack([p.nnz for p in parts]),
        ucols_words=np.stack([p.ucols_words for p in parts]),
        uslots=np.stack([p.uslots for p in parts]),
        n_uniq=np.stack([p.n_uniq for p in parts]),
        vals=opt("vals"),
        vals_lo=opt("vals_lo"),
        vals_hi=opt("vals_hi"),
        rows_pad=parts[0].rows_pad,
        nnz_pad=parts[0].nnz_pad,
        uniq_pad=parts[0].uniq_pad,
        ucols_bits=parts[0].ucols_bits,
        uslots_bits=parts[0].uslots_bits,
        y_sign=parts[0].y_sign,
        uslots_delta=parts[0].uslots_delta,
        vals_mode=parts[0].vals_mode,
    )


def _derived_nnz(p) -> np.ndarray:
    """Live COO entries per shard: the index past the last entry where
    anything is nonzero. Entries beyond the true nnz are all-zero by
    construction (prep zero-pads rows/ucols/vals), and an interior
    all-zero entry reconstructs to the same zeros either way, so this
    bound is exact for bit-identical decode."""
    live = (
        (np.asarray(p.rows) != 0)
        | (np.asarray(p.ucols) != 0)
        | (np.asarray(p.vals) != 0)
    )
    nz = p.rows.shape[1]
    rev = live[:, ::-1]
    any_live = rev.any(axis=1)
    return np.where(any_live, nz - rev.argmax(axis=1), 0).astype(np.int32)


def _quantize_vals(
    vals: np.ndarray, nnz: np.ndarray, mode: str
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-shard fixed-point encode with a DETERMINISTIC, content-keyed
    rounding stream: the prep pool may encode batches in any order, and
    the ingest contract requires the emitted stream to be independent
    of worker interleaving — so the stochastic-rounding rng is seeded
    from the shard's own bytes, never from shared mutable state.

    Only the LIVE entries (``[:nnz]``) are quantized — the [lo, hi]
    scale must not be widened (and resolution wasted) by the zero
    padding, and padding codes are meaningless anyway: the device
    decode masks everything past ``nnz`` back to the raw wire's exact
    0.0 (a dequantized zero is 0±step noise that would otherwise
    scatter-add a padding-sized bias into row 0 / uslots[0])."""
    dt, num_bytes = _QUANT_MODES[mode]
    q = np.zeros(vals.shape, dtype=dt)
    lo = np.zeros(vals.shape[0], np.float32)
    hi = np.ones(vals.shape[0], np.float32)
    for d in range(vals.shape[0]):
        n = int(nnz[d])
        if n == 0:
            continue
        rng = np.random.default_rng(crc32c.value(vals[d, :n].tobytes()))
        q[d, :n], lo[d], hi[d] = quantize(vals[d, :n], num_bytes, rng)
    return q, lo, hi


def encode_exact(
    prepped,
    num_slots: int,
    mode: str = "exact",
) -> Optional[EncodedExactBatch]:
    """Encode a PreppedBatch for the compact wire, or None when the
    batch falls outside an encoding's verified domain (caller ships the
    raw wire — never wrong bytes).

    STATELESS + deterministic (pool-able prep stage). ``mode``:
    ``"exact"`` is lossless/bit-identical; ``"int8"``/``"u16"``/
    ``"bf16"`` additionally narrow the value stream (lossy — config-
    gated behind a logloss-parity bound; binary batches have no value
    stream, so every mode is exact for them).

    With a span sink installed, the encode emits one ``wire.encode``
    timeline span carrying the active flow id — the ``encode`` category
    of the critical-path attribution (telemetry/attribution.py)."""
    from ..telemetry import spans as telemetry_spans

    if telemetry_spans.get_sink() is None:
        return _encode_exact_impl(prepped, num_slots, mode)
    with telemetry_spans.span("wire.encode", mode=mode):
        return _encode_exact_impl(prepped, num_slots, mode)


def _encode_exact_impl(
    prepped,
    num_slots: int,
    mode: str = "exact",
) -> Optional[EncodedExactBatch]:
    from ..apps.linear.async_sgd import PreppedBatch
    from ..ops.kv_ops import slot_sentinel

    if not isinstance(prepped, PreppedBatch):
        return None
    if mode not in WIRE_ENCODE_MODES or mode == "":
        raise ValueError(
            f"unknown wire_encode mode {mode!r}; expected one of "
            f"{WIRE_ENCODE_MODES[1:]}"
        )
    tel = wire_instruments()
    t0 = time.perf_counter()
    y = np.asarray(prepped.y)
    mask = np.asarray(prepped.mask)
    rows = np.asarray(prepped.rows)
    ucols = np.asarray(prepped.ucols)
    vals = np.asarray(prepped.vals)
    uslots = np.asarray(prepped.uslots)
    umask = np.asarray(prepped.umask)
    d_shards, rows_pad = y.shape
    nnz_pad = rows.shape[1]
    uniq_pad = uslots.shape[1]
    sentinel = slot_sentinel(num_slots)

    # -- verified structure elisions (each check is the exact domain of
    # its decode op; any failure → raw wire) --
    counts = mask.sum(axis=1).astype(np.int32)
    if not (mask == (np.arange(rows_pad) < counts[:, None])).all():
        return None
    n_uniq = umask.sum(axis=1).astype(np.int32)
    if not (umask == (np.arange(uniq_pad) < n_uniq[:, None])).all():
        return None
    nnz = _derived_nnz(prepped)
    live = np.arange(nnz_pad) < nnz[:, None]
    # rows must be the repeat(arange, counts) form — verified exactly,
    # per shard, below (bincount then reconstruct-and-compare)
    row_counts = np.zeros((d_shards, rows_pad), np.int64)
    for d in range(d_shards):
        if nnz[d] and rows[d, : nnz[d]].min() < 0:
            return None
        rc = np.bincount(rows[d, : nnz[d]], minlength=rows_pad)
        if rc.size > rows_pad:
            return None
        row_counts[d, : rc.size] = rc
        if not (
            rows[d, : nnz[d]]
            == np.repeat(np.arange(rows_pad), row_counts[d])
        ).all():
            return None
    rc_dtype = np.uint8 if row_counts.max(initial=0) < 256 else np.uint16
    if row_counts.max(initial=0) >= (1 << 16):
        return None

    # -- ucols: bit-packed at ceil(log2 uniq_pad) bits --
    ucols_bits = slot_bits(uniq_pad)
    if (ucols < 0).any() or (ucols >= uniq_pad).any():
        return None
    if (~live & (ucols != 0)).any():
        return None
    ucols_words = np.stack(
        [
            stream_to_words(pack_bits(ucols[d], ucols_bits), nnz_pad, ucols_bits)
            for d in range(d_shards)
        ]
    )

    # -- uslots: sorted unique slots (prep_batch_shared's np.unique
    # output) → u16 gap stream with the sentinel tail elided; unsorted
    # (prep_batch hashes sorted KEYS, so its slots arrive shuffled) or
    # wide-gapped arrays → ceil(log2 S+1)-bit packed words instead --
    if sentinel < 0 or num_slots >= (1 << 31):
        return None  # 2^31 tables use the -1 sentinel; keep the raw wire
    uslots_bits = slot_bits(num_slots, sentinel=True)
    ok_sorted = True
    deltas = np.zeros((d_shards, uniq_pad), np.int64)
    for d in range(d_shards):
        u = n_uniq[d]
        seg = uslots[d, :u].astype(np.int64)
        if (uslots[d, u:] != sentinel).any():
            return None
        if (seg < 0).any() or (seg >= num_slots).any():
            return None
        if u and ok_sorted:
            dd = np.diff(seg, prepend=0)
            if (dd[1:] <= 0).any() or dd.max(initial=0) >= (1 << 16):
                ok_sorted = False
            else:
                deltas[d, :u] = dd
    if ok_sorted:
        uslots_enc = deltas.astype(np.uint16)
        uslots_delta = True
    else:
        uslots_enc = np.stack(
            [
                stream_to_words(
                    pack_bits(uslots[d], uslots_bits), uniq_pad, uslots_bits
                )
                for d in range(d_shards)
            ]
        )
        uslots_delta = False

    # -- labels: sign bits when exactly ±1 on live rows, 0 on padding --
    y_sign = bool((np.abs(y) == mask).all())
    if y_sign:
        y_enc = np.stack(
            [np.packbits(y[d] > 0, bitorder="little") for d in range(d_shards)]
        )
    else:
        y_enc = y

    # -- values: elide (binary), narrow (quant modes), or ship f32 --
    vals_lo = vals_hi = None
    binary = bool((vals == live.astype(np.float32)).all())
    if binary:
        vals_enc, vals_mode = None, "binary"
    elif mode == "exact":
        vals_enc, vals_mode = vals, "f32"
    elif mode == "bf16":
        import ml_dtypes

        vals_enc, vals_mode = vals.astype(ml_dtypes.bfloat16), "bf16"
    else:
        vals_enc, vals_lo, vals_hi = _quantize_vals(vals, nnz, mode)
        vals_mode = mode

    out = EncodedExactBatch(
        y=y_enc,
        counts=counts,
        row_counts=row_counts.astype(rc_dtype),
        nnz=nnz,
        ucols_words=ucols_words,
        uslots=uslots_enc,
        n_uniq=n_uniq,
        vals=vals_enc,
        vals_lo=vals_lo,
        vals_hi=vals_hi,
        rows_pad=rows_pad,
        nnz_pad=nnz_pad,
        uniq_pad=uniq_pad,
        ucols_bits=ucols_bits,
        uslots_bits=uslots_bits,
        y_sign=y_sign,
        uslots_delta=uslots_delta,
        vals_mode=vals_mode,
    )
    if tel is not None:
        enc_b, raw_b = tree_nbytes(out), tree_nbytes(prepped)
        tel["encode_seconds"].observe(time.perf_counter() - t0)
        tel["bytes"].labels(encoding=mode).inc(enc_b)
        tel["saved_bytes"].labels(reason="encoding").inc(max(0, raw_b - enc_b))
    return out


def decode_exact_shard(enc, num_slots: int, d: int = None, *, _leaves=None):
    """Decode ONE data shard of an EncodedExactBatch with the REAL
    jit-side ops (ops/wire_codec) — the shared body the device step
    builders trace and the host parity oracle runs on CPU.

    Returns ``(y, mask, rows, ucols, vals, uslots, umask)`` shaped like
    one shard of the raw PreppedBatch. ``_leaves`` lets a traced caller
    pass already-sliced per-shard operands (inside shard_map the slicing
    happened outside); the host path slices shard ``d`` itself."""
    import jax.numpy as jnp

    from ..ops import wire_codec as wc
    from ..ops.kv_ops import slot_sentinel

    if _leaves is not None:
        y_e, count, row_counts, nnz, ucw, usl, n_uniq, vals, vlo, vhi = _leaves
    else:
        y_e, count, row_counts, nnz, ucw, usl, n_uniq = (
            enc.y[d], enc.counts[d], enc.row_counts[d], enc.nnz[d],
            enc.ucols_words[d], enc.uslots[d], enc.n_uniq[d],
        )
        vals = None if enc.vals is None else enc.vals[d]
        vlo = None if enc.vals_lo is None else enc.vals_lo[d]
        vhi = None if enc.vals_hi is None else enc.vals_hi[d]

    if enc.y_sign:
        y = wc.decode_sign_labels(y_e, count, enc.rows_pad)
    else:
        y = y_e
    mask = wc.decode_mask(count, enc.rows_pad)
    rows = wc.decode_row_ids(row_counts, nnz, enc.nnz_pad)
    ucols = wc.decode_bitstream(ucw, enc.nnz_pad, enc.ucols_bits)
    # the raw wire zero-pads ucols past nnz; the packed stream's tail
    # bits are zero too, but mask explicitly so the contract is local
    ucols = jnp.where(jnp.arange(enc.nnz_pad) < nnz, ucols, 0)
    if enc.uslots_delta:
        uslots = wc.decode_sorted_deltas(usl, n_uniq, slot_sentinel(num_slots))
    else:
        uslots = wc.decode_bitstream(usl, enc.uniq_pad, enc.uslots_bits)
    umask = wc.decode_mask(n_uniq, enc.uniq_pad)
    if enc.vals_mode == "binary":
        v = wc.decode_binary_vals(nnz, enc.nnz_pad)
    elif enc.vals_mode == "f32":
        v = vals
    elif enc.vals_mode == "bf16":
        v = wc.decode_bf16(vals)
    else:
        # mask the dequantized stream back to the raw wire's exact 0.0
        # past nnz: a dequantized zero code is 0±step noise, and every
        # padding entry carries rows=0/ucols=0 — unmasked they would
        # scatter-add a padding-sized bias into row 0 and uslots[0]
        # (f32/bf16/binary are safe: 0.0 round-trips exactly there)
        v = jnp.where(
            jnp.arange(enc.nnz_pad) < nnz,
            wc.decode_fixed_point(
                vals, vlo, vhi, _QUANT_MODES[enc.vals_mode][1]
            ),
            0.0,
        )
    return y, mask, rows, ucols, v, uslots, umask


def decode_exact_host(enc: EncodedExactBatch, num_slots: int) -> tuple:
    """Host parity oracle: decode every shard on CPU and stack — shaped
    exactly like the raw PreppedBatch fields
    ``(y, mask, rows, ucols, vals, uslots, umask)``."""
    if isinstance(enc, EncodedExactSuperBatch):
        raise ValueError("host oracle decodes per-minibatch; index T first")
    parts = [
        tuple(
            np.asarray(x)
            for x in decode_exact_shard(enc, num_slots, d)
        )
        for d in range(enc.counts.shape[0])
    ]
    return tuple(np.stack(x) for x in zip(*parts))


# ---------------------------------------------------------------------------
# Stream-once lane-dictionary wire (the cache-free encoding).
#
# The UploadCache amortizes repeated traffic, but the production CTR
# shape — stream a multi-GB criteo file ONCE — repeats nothing, so the
# bits wire's ceil(log2 S) bits/feature stood as the recorded
# 126.9 B/example upload bound. The exploitable structure that survives
# the hash is per-FIELD: a lane whose per-batch vocabulary is small
# (criteo's 13 integer count fields hash to ~90 distinct slots per 16k
# batch) ships a per-lane sorted unique-slot table (``uslots``) plus
# bit-packed per-row table indices (``ucols``) at ~7 bits instead of
# 26, while high-vocabulary lanes (hashed categorical tokens, ~98%
# unique per batch — incompressible past the hash; delta-coding the
# global unique-slot set was measured and LOSES at ≥60% unique) keep
# the raw bit stream. Measured on the criteo-law shape: 96.4 B/example
# at 2^26 slots vs the 126.9 raw-bits baseline, no cache anywhere.
#
# Statics (which lanes take the dictionary, the shared code width, the
# table capacity) are derived once from the worker's first batch
# (`derive_stream_statics`, the `_padding` pattern) and pinned: encode
# itself stays STATELESS (pool-able — the PR-3 ingest rule) and
# VERIFIES each batch fits the pinned statics, returning None so the
# caller ships the raw bits wire when it doesn't — never wrong bytes.
# ---------------------------------------------------------------------------


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EncodedEllStreamBatch:
    """ELL batch on the stream-once lane-dictionary wire (fields
    [D, ...] per data shard). ``raw_words`` is the row-major bit stream
    of the raw lanes at ``raw_bits`` each; ``code_words`` the row-major
    dictionary codes (``ucols``) of the dict lanes at ``code_bits``;
    ``table_words`` the concatenated per-lane sorted unique slots
    (``uslots``) at ``raw_bits``, ``lane_starts`` their start offsets.
    Bits past each live prefix are zero; garbage decodes on padding
    rows are gated by the row mask exactly like the bits wire."""

    y_bits: np.ndarray  # [D, ceil(R/8)] uint8 little-endian sign bits
    counts: np.ndarray  # [D] int32 live rows
    raw_words: np.ndarray  # [D, Wr] uint32
    code_words: np.ndarray  # [D, Wc] uint32
    table_words: np.ndarray  # [D, Wt] uint32
    lane_starts: np.ndarray  # [D, n_dict] int32
    n_uniq: np.ndarray  # [D] int32 live table entries
    rows: int = dataclasses.field(metadata=dict(static=True), default=0)
    lanes: int = dataclasses.field(metadata=dict(static=True), default=0)
    dict_lanes: tuple = dataclasses.field(
        metadata=dict(static=True), default=()
    )
    code_bits: int = dataclasses.field(metadata=dict(static=True), default=0)
    dict_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    raw_bits: int = dataclasses.field(metadata=dict(static=True), default=0)

    @property
    def num_examples(self) -> int:
        return int(np.asarray(self.counts).sum())

    def static_key(self) -> tuple:
        return (
            self.rows, self.lanes, self.dict_lanes, self.code_bits,
            self.dict_pad, self.raw_bits,
        )


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class EncodedEllStreamSuperBatch:
    """T stacked EncodedEllStreamBatches (fields [T, D, ...]) — one
    scan launch decodes and runs T sequential ministeps."""

    y_bits: np.ndarray
    counts: np.ndarray
    raw_words: np.ndarray
    code_words: np.ndarray
    table_words: np.ndarray
    lane_starts: np.ndarray
    n_uniq: np.ndarray
    rows: int = dataclasses.field(metadata=dict(static=True), default=0)
    lanes: int = dataclasses.field(metadata=dict(static=True), default=0)
    dict_lanes: tuple = dataclasses.field(
        metadata=dict(static=True), default=()
    )
    code_bits: int = dataclasses.field(metadata=dict(static=True), default=0)
    dict_pad: int = dataclasses.field(metadata=dict(static=True), default=0)
    raw_bits: int = dataclasses.field(metadata=dict(static=True), default=0)

    @property
    def steps(self) -> int:
        return int(self.counts.shape[0])

    @property
    def num_examples(self) -> int:
        return int(np.asarray(self.counts).sum())

    def static_key(self) -> tuple:
        return (
            self.rows, self.lanes, self.dict_lanes, self.code_bits,
            self.dict_pad, self.raw_bits,
        )


def stack_stream_batches(
    parts: List[EncodedEllStreamBatch],
) -> EncodedEllStreamSuperBatch:
    """Stack T stream-wire minibatches into one scan superbatch.
    Statics must agree across T (they pin ONE decode program)."""
    if not parts:
        raise ValueError("empty superbatch")
    key = parts[0].static_key()
    assert all(p.static_key() == key for p in parts), (
        "stream superbatch needs uniform static encoding parameters"
    )
    arrays = (
        "y_bits", "counts", "raw_words", "code_words", "table_words",
        "lane_starts", "n_uniq",
    )
    return EncodedEllStreamSuperBatch(
        **{f: np.stack([getattr(p, f) for p in parts]) for f in arrays},
        rows=parts[0].rows,
        lanes=parts[0].lanes,
        dict_lanes=parts[0].dict_lanes,
        code_bits=parts[0].code_bits,
        dict_pad=parts[0].dict_pad,
        raw_bits=parts[0].raw_bits,
    )


@dataclasses.dataclass(frozen=True)
class StreamStatics:
    """Pinned static parameters of the stream wire (one decode
    program). Derived from the worker's first batch, then every encode
    verifies against them — the `_padding` pattern."""

    lanes: int
    dict_lanes: tuple
    code_bits: int
    dict_pad: int
    raw_bits: int


def _pow2ceil(n: int) -> int:
    return 1 << max(0, int(max(1, n)) - 1).bit_length()


def _lane_code_bits(n_uniq: int) -> int:
    """Bit width of a lane's dictionary codes, with 25% headroom padded
    to a power of two so small vocabulary drift between batches cannot
    flip the static width (each flip would compile a new decode
    program)."""
    return max(1, (_pow2ceil(n_uniq + (n_uniq >> 2)) - 1).bit_length())


def derive_stream_statics(
    keys: np.ndarray, lanes: int, hash_num_slots: int, num_slots: int
) -> Optional[StreamStatics]:
    """Derive the pinned stream-wire statics from one batch's key
    stream (uniform ``lanes``-wide rows, row-major). Returns None when
    no lane-dictionary split wins over the plain bits wire — the
    caller then stays on the bits wire for the run.

    The lane rule is the lane's own net win: shipping codes at the
    lane's padded code width instead of raw bits must save more row
    bits than the lane's padded uslot table costs to ship. That keeps
    high-vocabulary lanes raw automatically — an all-unique lane
    (hashed categorical tokens, ~98% unique per batch) pays a
    rows-sized table for zero code savings, and past the hash those
    streams are ~incompressible anyway (measured: delta-coding the
    global unique slot set loses at the criteo-law ~65% unique
    fraction). A final combined check re-verifies the win at the
    SHARED code width (the widest chosen lane's) before pinning."""
    from ..utils.bitpack import slot_bits as _slot_bits
    from ..utils.murmur import hash_slots

    k = np.ascontiguousarray(keys, dtype=np.uint64).ravel()
    if lanes <= 0 or k.size == 0 or k.size % lanes:
        return None
    raw_bits = _slot_bits(num_slots)
    cols = hash_slots(k, hash_num_slots).reshape(-1, lanes)
    n_rows = cols.shape[0]
    lane_u = [int(len(np.unique(cols[:, j]))) for j in range(lanes)]
    dict_lanes = tuple(
        j
        for j in range(lanes)
        if n_rows * (raw_bits - _lane_code_bits(lane_u[j]))
        > _pow2ceil(lane_u[j] + (lane_u[j] >> 2)) * raw_bits
    )
    if not dict_lanes:
        return None
    code_bits = max(_lane_code_bits(lane_u[j]) for j in dict_lanes)
    total = sum(lane_u[j] for j in dict_lanes)
    dict_pad = _pow2ceil(total + (total >> 2))
    # net-win check against the plain bits wire at THIS batch's shape:
    # per-row code savings must beat the shipped table + offsets
    rows = cols.shape[0]
    saved_bits = rows * len(dict_lanes) * (raw_bits - code_bits)
    table_bits = dict_pad * raw_bits + 32 * len(dict_lanes)
    if saved_bits <= table_bits:
        return None
    return StreamStatics(
        lanes=lanes, dict_lanes=dict_lanes, code_bits=code_bits,
        dict_pad=dict_pad, raw_bits=raw_bits,
    )


def _encode_stream_shard_py(
    slots: np.ndarray, nsub: int, rows_pad: int, st: StreamStatics
):
    """NumPy reference encode of ONE shard's hashed slot matrix —
    bit-identical to the native fused pass (parity tier-1 tested).
    Returns (raw_words, code_words, table_words, lane_starts, n_uniq)
    or None when the batch falls outside the pinned statics."""
    from ..utils.bitpack import pack_bits

    n_dict = len(st.dict_lanes)
    n_raw = st.lanes - n_dict
    cols = slots.reshape(nsub, st.lanes)
    dict_set = frozenset(st.dict_lanes)
    raw_lanes = [j for j in range(st.lanes) if j not in dict_set]
    tables = []
    lane_starts = np.zeros(n_dict, np.int32)
    codes = np.empty((nsub, n_dict), np.int32)
    total = 0
    for i, j in enumerate(st.dict_lanes):
        u, inv = np.unique(cols[:, j], return_inverse=True)
        if len(u) > (1 << st.code_bits) or total + len(u) > st.dict_pad:
            return None
        lane_starts[i] = total
        total += len(u)
        tables.append(u.astype(np.int32, copy=False))
        codes[:, i] = inv
    raw_vals = (
        cols[:, raw_lanes].reshape(-1) if n_raw else np.zeros(0, np.int32)
    )
    table_vals = np.concatenate(tables) if tables else np.zeros(0, np.int32)
    raw_words = stream_to_words(
        pack_bits(raw_vals, st.raw_bits), rows_pad * n_raw, st.raw_bits
    )
    code_words = stream_to_words(
        pack_bits(codes.reshape(-1), st.code_bits),
        rows_pad * n_dict,
        st.code_bits,
    )
    table_words = stream_to_words(
        pack_bits(table_vals, st.raw_bits), st.dict_pad, st.raw_bits
    )
    return raw_words, code_words, table_words, lane_starts, np.int32(total)


def encode_stream_shard(
    keys: np.ndarray,
    nsub: int,
    rows_pad: int,
    hash_num_slots: int,
    st: StreamStatics,
    seed: int = 0,
):
    """Fused hash→unique→remap→bit-pack over ONE shard's key stream
    (the Localizer-prep host stage, fused): native one-pass C ABI call
    when ``libpsnative`` is loaded, bit-identical NumPy fallback
    otherwise. STATELESS + deterministic (pool-able prep stage).
    Returns (raw_words, code_words, table_words, lane_starts, n_uniq)
    or None when the shard falls outside the pinned statics (caller
    ships the raw bits wire)."""
    import ctypes

    from ..cpp import native
    from ..utils.bitpack import packed_nwords
    from ..utils.murmur import hash_slots

    k = np.ascontiguousarray(keys, dtype=np.uint64).ravel()
    assert k.size == nsub * st.lanes, (k.size, nsub, st.lanes)
    lib = native()
    if (
        lib is None
        or getattr(lib, "ps_stream_encode", None) is None
        or k.size < 4096
    ):
        return _encode_stream_shard_py(
            hash_slots(k, hash_num_slots, seed), nsub, rows_pad, st
        )
    n_dict = len(st.dict_lanes)
    n_raw = st.lanes - n_dict
    dict_mask = np.zeros(st.lanes, np.uint8)
    dict_mask[list(st.dict_lanes)] = 1
    # zeroed full-capacity buffers: the native packers write only the
    # live prefix; the zero tail is part of the wire bytes (parity)
    raw_buf = np.zeros(
        packed_nwords(rows_pad * n_raw, st.raw_bits) * 4, np.uint8
    )
    code_buf = np.zeros(
        packed_nwords(rows_pad * n_dict, st.code_bits) * 4, np.uint8
    )
    table_buf = np.zeros(
        packed_nwords(st.dict_pad, st.raw_bits) * 4, np.uint8
    )
    starts = np.zeros(n_dict + 1, np.int32)
    u8p = ctypes.POINTER(ctypes.c_uint8)
    got = lib.ps_stream_encode(
        k.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        ctypes.c_int64(nsub),
        ctypes.c_int32(st.lanes),
        ctypes.c_uint64(seed),
        ctypes.c_uint64(hash_num_slots),
        dict_mask.ctypes.data_as(u8p),
        ctypes.c_uint32(st.raw_bits),
        ctypes.c_uint32(st.code_bits),
        ctypes.c_int32(st.dict_pad),
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        raw_buf.ctypes.data_as(u8p),
        code_buf.ctypes.data_as(u8p),
        table_buf.ctypes.data_as(u8p),
    )
    if got < 0:
        return None
    return (
        raw_buf.view("<u4"),
        code_buf.view("<u4"),
        table_buf.view("<u4"),
        starts[:n_dict].copy(),
        np.int32(got),
    )


def decode_stream_shard(enc: EncodedEllStreamBatch, d: int):
    """Decode ONE data shard of an EncodedEllStreamBatch with the REAL
    jit-side ops (ops/wire_codec) — the shared body the device step
    traces and the host parity oracle runs on CPU. Returns
    ``(y, mask, slots)`` with ``slots`` int32 [rows, lanes]."""
    from ..ops import wire_codec as wc

    y = wc.decode_sign_labels(enc.y_bits[d], enc.counts[d], enc.rows)
    mask = wc.decode_mask(enc.counts[d], enc.rows)
    slots = wc.decode_stream_slots(
        enc.raw_words[d],
        enc.code_words[d],
        enc.table_words[d],
        enc.lane_starts[d],
        rows=enc.rows,
        lanes=enc.lanes,
        dict_lanes=enc.dict_lanes,
        code_bits=enc.code_bits,
        dict_pad=enc.dict_pad,
        raw_bits=enc.raw_bits,
    )
    return y, mask, slots


class UploadCache:
    """Key caching on the host→device leg: a repeated array re-uses its
    device-resident buffer, identified by crc32c signature and VERIFIED
    by exact comparison against a retained host copy (the signature
    routes, it never decides — filter/key_caching.py +
    KeyDirectory-slot-cache semantics, so a collision can never serve
    wrong bytes).

    STATEFUL, single-owner: lives on the serial uploader thread (the
    PR-3 ingest rule — stateless stages pool, stateful stages stay
    serial); the owner-thread assert makes a violation loud instead of
    racy. LRU-evicts by retained host bytes (``max_bytes``); leaves
    smaller than ``min_leaf_bytes`` upload directly (signature overhead
    would exceed the win)."""

    def __init__(
        self,
        upload_leaf=None,
        max_bytes: int = 64 << 20,
        min_leaf_bytes: int = 4096,
    ):
        self._upload_leaf = upload_leaf or jax.device_put
        self._max_bytes = int(max_bytes)
        self._min_leaf_bytes = int(min_leaf_bytes)
        # sig -> [host_copy, device_buf]; MRU at the end. Single-owner
        # by contract (asserted) — no lock on purpose.
        self._cache: "OrderedDict[tuple, list]" = OrderedDict()
        self._bytes = 0
        self._owner: Optional[int] = None
        self.hits = 0
        self.misses = 0
        self.saved_bytes = 0
        self._tel = wire_instruments()

    def _assert_owner(self) -> None:
        me = threading.get_ident()
        if self._owner is None:
            self._owner = me
        elif self._owner != me:
            raise RuntimeError(
                "UploadCache is single-owner (stateful upload stages run "
                "serially on the uploader thread — doc/PERFORMANCE.md "
                f"'Wire format'); owned by thread {self._owner}, called "
                f"from {me}"
            )

    def _sig(self, arr: np.ndarray) -> tuple:
        return (
            crc32c.array_signature(arr, MAX_SIG_LEN),
            arr.shape,
            arr.dtype.str,
        )

    def _put_leaf(self, leaf):
        arr = np.asarray(leaf)
        if arr.nbytes < self._min_leaf_bytes:
            return self._upload_leaf(leaf)
        sig = self._sig(arr)
        entry = self._cache.get(sig)
        if entry is not None and np.array_equal(entry[0], arr):
            self._cache.move_to_end(sig)
            self.hits += 1
            self.saved_bytes += arr.nbytes
            if self._tel is not None:
                self._tel["cache_hits"].inc()
                self._tel["saved_bytes"].labels(reason="cache_hit").inc(
                    arr.nbytes
                )
            return entry[1]
        self.misses += 1
        if self._tel is not None:
            self._tel["cache_misses"].inc()
        dev = self._upload_leaf(leaf)
        if entry is not None:
            # signature collision overwrite: release the displaced
            # entry's accounting or phantom bytes accumulate until the
            # eviction loop permanently thrashes the cache
            self._bytes -= entry[0].nbytes
        self._cache[sig] = [arr.copy(), dev]
        self._bytes += arr.nbytes
        while self._bytes > self._max_bytes and len(self._cache) > 1:
            _, (old, _dev) = self._cache.popitem(last=False)
            self._bytes -= old.nbytes
        return dev

    def __call__(self, prepped):
        """Upload a batch tree, reusing device buffers for leaves whose
        bytes the device already holds."""
        self._assert_owner()
        return jax.tree.map(self._put_leaf, prepped)


def wire_filter_specs(num_bytes: int = 0) -> List[FilterSpec]:
    """The upload wire's host-side filter chain in the reference's
    WORKING order (example/linear/ctr confs → Van::Send applies in
    list order, Recv in reverse): key_caching, then fixing_float
    (``num_bytes`` 0 disables quantization), then compressing — values
    must quantize BEFORE the byte codec sees them (the codec emits
    uint8 frames, which fixing_float would skip), and the round-trip
    property itself holds under ANY ordering (tests/test_filters.py
    pins both this order and the swapped one)."""
    return [
        FilterSpec(type="key_caching"),
        FilterSpec(type="fixing_float", num_bytes=num_bytes),
        FilterSpec(type="compressing"),
    ]


class MessageWireCodec:
    """Drive the host-side FilterChain over batch payloads — the
    host↔host legs of the upload path (multi-host ingest hand-off,
    replay spill) and the chain round-trip contract tests.

    One stateful chain per peer per direction (ref RemoteNode): the
    key-caching filter's per-(channel, range) cache lives in the chain,
    so a repeated key array crosses as its signature only."""

    def __init__(self, num_bytes: int = 0, channel: int = 0):
        from ..filter.base import FilterChain

        self._encode_chain = FilterChain()
        self._decode_chain = FilterChain()
        self._num_bytes = num_bytes
        self._channel = channel

    def encode(self, key: Optional[np.ndarray], values: List[np.ndarray]) -> Message:
        msg = Message(task=Task(key_channel=self._channel))
        msg.task.filters = wire_filter_specs(self._num_bytes)
        msg.key = key
        msg.values = list(values)
        return self._encode_chain.encode(msg)

    def decode(self, msg: Message) -> Tuple[Optional[np.ndarray], List[np.ndarray]]:
        out = self._decode_chain.decode(msg)
        return out.key, list(out.values)


# ---------------------------------------------------------------------------
# LZ on the host→device STAGING leg (the reference's compressing filter,
# upload edition).
#
# The reference compresses every filtered message's value arrays on the
# wire (src/filter/compressing.h, snappy). Our upload path's analog is
# the STAGING leg: prep-pool workers compress each encoded batch's
# leaves into self-describing codec frames (utils/codec.py — native LZ,
# zlib fallback, incompressible payloads ride raw), and the serial
# uploader thread decompresses them immediately before ``device_put``.
# That split honors the stateless-or-feeder rule (compress is stateless
# → pool; decompress rides the single uploader thread) and mirrors the
# reference's chain order: quantize/encode first, byte-codec last.
#
# Byte accounting: ``ps_wire_bytes_total{encoding="<mode>+lz"}`` and
# ``ps_wire_saved_bytes_total{reason="compression"}`` record the staged
# (compressed) bytes — the modeled disaggregated feeder→device-host
# leg — while ``ps_ingest_uploaded_bytes_total`` stays the REALIZED
# PJRT link traffic (arrays decompress BEFORE device_put, so the
# tunnel itself ships decoded wire bytes; doc/PERFORMANCE.md "Wire
# format" spells out which legs compression does and does not shrink).
# ---------------------------------------------------------------------------


class CompressedBatch:
    """A host-prepped batch tree with its array leaves compressed into
    codec frames — the staging-leg container handed from the prep pool
    to the uploader. NOT a jax pytree: it never reaches a jitted step;
    ``decompress_batch`` restores the original tree bit-identically
    (np.frombuffer of the decoded frame, dtype/shape from the retained
    meta)."""

    __slots__ = (
        "frames", "meta", "treedef", "n", "raw_nbytes", "wire_nbytes",
        "encoding",
    )

    def __init__(self, frames, meta, treedef, n, raw_nbytes, wire_nbytes,
                 encoding):
        self.frames = frames  # List[bytes] codec frames, leaf order
        self.meta = meta  # List[(dtype str, shape)] per leaf
        self.treedef = treedef
        self.n = n  # example count (uploader telemetry)
        self.raw_nbytes = raw_nbytes
        self.wire_nbytes = wire_nbytes  # staged bytes, net of compression
        self.encoding = encoding

    @property
    def num_examples(self) -> int:
        return int(self.n)


def compress_batch(prepped, encoding: str = "") -> CompressedBatch:
    """Compress a host-prepped batch tree's leaves for the staging leg
    (STATELESS — pool-able prep stage). Incompressible leaves ride raw
    inside their self-describing frame (utils/codec.compress), so the
    worst case is one header byte per leaf."""
    from ..utils import codec

    leaves, treedef = jax.tree.flatten(prepped)
    frames, meta = [], []
    raw_nbytes = wire_nbytes = 0
    for leaf in leaves:
        arr = np.ascontiguousarray(leaf)
        frame = codec.compress(arr.tobytes())
        frames.append(frame)
        meta.append((arr.dtype.str, arr.shape))
        raw_nbytes += arr.nbytes
        wire_nbytes += len(frame)
    n = getattr(prepped, "num_examples", 0)
    out = CompressedBatch(
        frames, meta, treedef, n, raw_nbytes, wire_nbytes, encoding
    )
    tel = wire_instruments()
    if tel is not None:
        if encoding:
            tel["bytes"].labels(encoding=f"{encoding}+lz").inc(wire_nbytes)
        tel["saved_bytes"].labels(reason="compression").inc(
            max(0, raw_nbytes - wire_nbytes)
        )
    return out


def decompress_batch(cb: CompressedBatch):
    """Uploader-side inverse of :func:`compress_batch`: restore the
    original batch tree bit-for-bit before ``device_put``. Runs on the
    single uploader/staging thread (the feeder half of the
    stateless-or-feeder rule)."""
    from ..utils import codec

    leaves = []
    for frame, (dtype, shape) in zip(cb.frames, cb.meta):
        dt = np.dtype(dtype)
        expected = dt.itemsize * int(np.prod(shape, dtype=np.int64))
        raw = codec.decompress(frame, expected_size=expected)
        leaves.append(np.frombuffer(raw, dtype=dt).reshape(shape))
    return jax.tree.unflatten(cb.treedef, leaves)


def maybe_decompress(item):
    """Identity for plain batch trees; frame decode for CompressedBatch
    (the uploader calls this on every staged item so compression stays
    a config choice, not a code path fork)."""
    return decompress_batch(item) if isinstance(item, CompressedBatch) else item
