"""Block coordinate descent framework (ref ``src/learner/bcd.{h,cc}``).

``BCDScheduler::Run`` = LoadData → PreprocessData → DivideFeatureBlocks,
then apps drive per-block UPDATE_MODEL/EVALUATE_PROGRESS tasks. Here:

- ``load_data``: stream all training files into one SparseBatch per worker
  shard (the reference assigns file slices via DataAssigner).
- ``preprocess``: global key localization — the reference's workers send
  unique keys to servers to build the model key arrays (bcd.h
  PreprocessData); we build the global sorted key union + remapped columns.
- ``divide_feature_blocks``: partition features into ~ratio×groups blocks,
  mirroring fea_blk_ pairs (group, key range).

``BCDProgress`` mirrors learner/proto/bcd.proto.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..data.stream_reader import StreamReader
from ..system.customer import App
from ..utils.localizer import Localizer
from ..utils.range import Range
from ..utils.sparse import SparseBatch


@dataclasses.dataclass
class BCDProgress:
    """ref learner/proto/bcd.proto BCDProgress."""

    objective: float = 0.0
    relative_obj: float = 0.0
    violation: float = 0.0
    nnz_w: int = 0
    nnz_active_set: int = 0
    busy_time: float = 0.0
    total_time: float = 0.0

    def merge(self, other: "BCDProgress") -> None:
        self.objective += other.objective
        self.violation = max(self.violation, other.violation)
        self.nnz_w += other.nnz_w
        self.nnz_active_set += other.nnz_active_set
        self.busy_time += other.busy_time


@dataclasses.dataclass
class FeatureBlock:
    """One update unit: (group id, local column range) — ref fea_blk_."""

    group: int
    col_range: Range


class BCDScheduler(App):
    def __init__(self, bcd_conf, name: str = "bcd_scheduler"):
        super().__init__(name=name)
        self.bcd_conf = bcd_conf
        self.g_progress: Dict[int, BCDProgress] = {}
        self.fea_blk: List[FeatureBlock] = []
        self.blk_order: List[int] = []
        self.global_keys: Optional[np.ndarray] = None
        self.data: Optional[SparseBatch] = None  # localized, cols = len(global_keys)

    # -- Run() stages (ref bcd.cc) --

    def load_data(self, files: List[str], data_format: str = "libsvm") -> SparseBatch:
        reader = StreamReader(files, data_format)
        batch = reader.read_all()
        if batch is None:
            raise ValueError(f"no data in {files}")
        return self.set_data(batch)

    def set_data(self, batch: SparseBatch) -> SparseBatch:
        """Preprocess: global localization (ref PreprocessData key union)."""
        loc = Localizer()
        keys, _ = loc.count_uniq_index(batch)
        self.global_keys = keys
        self.data = loc.remap_index(keys)
        return self.data

    def divide_feature_blocks(self, num_groups: int = 1) -> List[FeatureBlock]:
        """ref BCDScheduler::DivideFeatureBlocks: ~ratio blocks per group."""
        assert self.data is not None, "load data first"
        f = self.data.cols
        ratio = max(self.bcd_conf.feature_block_ratio, 0)
        nblk = max(1, int(round(ratio * num_groups))) if ratio > 0 else 1
        nblk = min(nblk, max(1, f))
        full = Range(0, f)
        self.fea_blk = [
            FeatureBlock(group=0, col_range=full.even_divide(nblk, i))
            for i in range(nblk)
        ]
        self.blk_order = list(range(nblk))
        return self.fea_blk

    def merge_progress(self, iteration: int, prog: BCDProgress) -> None:
        cur = self.g_progress.get(iteration)
        if cur is None:
            self.g_progress[iteration] = prog
        else:
            cur.merge(prog)

    def show_progress(self, iteration: int) -> str:
        """ref ShowTime/ShowObjective line."""
        p = self.g_progress.get(iteration, BCDProgress())
        return (
            f"iter {iteration:3d}: objv {p.objective:.6e} "
            f"rel {p.relative_obj:.2e} |w|0 {p.nnz_w} "
            f"active {p.nnz_active_set} vio {p.violation:.2e}"
        )
