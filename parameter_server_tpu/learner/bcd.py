"""Block coordinate descent framework (ref ``src/learner/bcd.{h,cc}``).

``BCDScheduler::Run`` = LoadData → PreprocessData → DivideFeatureBlocks,
then apps drive per-block UPDATE_MODEL/EVALUATE_PROGRESS tasks. Here:

- ``load_data``: stream all training files into one SparseBatch per worker
  shard (the reference assigns file slices via DataAssigner); the
  SlotReader path caches per-slot column partitions like the reference's
  compressed slot cache.
- ``preprocess``: key localization — the reference's workers send unique
  keys to servers to build the model key arrays (bcd.h PreprocessData); we
  build the key union per feature group and lay columns out slot-major, so
  every feature group owns a contiguous column range.
- ``divide_feature_blocks``: reference semantics (bcd.cc
  DivideFeatureBlocks): per feature group, ``ceil(nnz_per_row * ratio)``
  blocks when the group's features are correlated (nnz_per_row > 1), one
  block otherwise; blocks even-divide the group's column range.

``BCDProgress`` mirrors learner/proto/bcd.proto.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from ..data.stream_reader import StreamReader
from ..system.customer import App
from ..utils.localizer import Localizer
from ..utils.range import Range
from ..utils.sparse import SparseBatch


@dataclasses.dataclass
class BCDProgress:
    """ref learner/proto/bcd.proto BCDProgress."""

    objective: float = 0.0
    relative_obj: float = 0.0
    violation: float = 0.0
    nnz_w: int = 0
    nnz_active_set: int = 0
    busy_time: float = 0.0
    total_time: float = 0.0

    def merge(self, other: "BCDProgress") -> None:
        self.objective += other.objective
        self.violation = max(self.violation, other.violation)
        self.nnz_w += other.nnz_w
        self.nnz_active_set += other.nnz_active_set
        self.busy_time += other.busy_time


@dataclasses.dataclass
class FeatureBlock:
    """One update unit: (group id, local column range) — ref fea_blk_."""

    group: int
    col_range: Range


class BCDScheduler(App):
    def __init__(self, bcd_conf, name: str = "bcd_scheduler"):
        super().__init__(name=name)
        self.bcd_conf = bcd_conf
        self.g_progress: Dict[int, BCDProgress] = {}
        self.fea_blk: List[FeatureBlock] = []
        self.blk_order: List[int] = []
        self.global_keys: Optional[np.ndarray] = None
        self.data: Optional[SparseBatch] = None  # localized, cols = len(global_keys)
        # slot-major layout: per-column group id + per-group column range
        self.col_slots: Optional[np.ndarray] = None  # [cols] int32
        self.slot_ranges: Dict[int, Range] = {}
        self.info = None  # ExampleInfo (per-group nnz stats)

    # -- Run() stages (ref bcd.cc) --

    def load_data(
        self,
        files: List[str],
        data_format: str = "libsvm",
        cache_dir: Optional[str] = None,
    ) -> SparseBatch:
        """LoadData stage. With ``cache_dir`` the SlotReader path is used
        (per-slot column partitions cached on disk, ref slot_reader.cc);
        otherwise a plain streaming read."""
        if cache_dir is not None:
            return self.load_via_slot_reader(files, data_format, cache_dir)
        reader = StreamReader(files, data_format)
        batch = reader.read_all()
        if batch is None:
            raise ValueError(f"no data in {files}")
        return self.set_data(batch)

    def load_via_slot_reader(
        self, files: List[str], data_format: str, cache_dir: Optional[str] = None
    ) -> SparseBatch:
        """LoadData through SlotReader (ref BCDWorker data loading): read
        once, split per feature group, then localize each group into its own
        contiguous column segment (slot-major layout)."""
        from ..data.slot_reader import SlotReader

        self._reset_slot_state()
        sr = SlotReader(files, data_format, cache_dir=cache_dir)
        self.info = sr.read()
        labels = sr.labels
        if labels is None:
            raise ValueError(f"no data in {files}")
        n = len(labels)
        col_off = 0
        keys_parts, slot_parts = [], []
        rows_parts, cols_parts, vals_parts = [], [], []
        for s in self.info.slot:
            sub = sr.slot(s.id)
            if sub is None or sub.nnz == 0:
                continue
            uniq = np.unique(sub.indices)
            local = np.searchsorted(uniq, sub.indices)
            keys_parts.append(uniq)
            slot_parts.append(np.full(len(uniq), s.id, np.int32))
            rows_parts.append(sub.row_ids())
            cols_parts.append(local.astype(np.int64) + col_off)
            vals_parts.append(sub.value_array())
            self.slot_ranges[s.id] = Range(col_off, col_off + len(uniq))
            col_off += len(uniq)
            sr.clear(s.id)
        self.global_keys = (
            np.concatenate(keys_parts) if keys_parts else np.zeros(0, np.int64)
        )
        self.col_slots = (
            np.concatenate(slot_parts) if slot_parts else np.zeros(0, np.int32)
        )
        rows = np.concatenate(rows_parts) if rows_parts else np.zeros(0, np.int32)
        cols = np.concatenate(cols_parts) if cols_parts else np.zeros(0, np.int64)
        vals = np.concatenate(vals_parts) if vals_parts else np.zeros(0, np.float32)
        order = np.argsort(rows, kind="stable")
        counts = np.bincount(rows, minlength=n).astype(np.int64)
        indptr = np.zeros(n + 1, np.int64)
        np.cumsum(counts, out=indptr[1:])
        self.data = SparseBatch(
            y=np.asarray(labels, np.float32),
            indptr=indptr,
            indices=cols[order],
            values=vals[order],
            num_cols=col_off,
            slot_ids=None,  # encoded structurally via col_slots now
        )
        return self.data

    def set_data(self, batch: SparseBatch) -> SparseBatch:
        """Preprocess: key localization (ref PreprocessData key union). When
        the batch carries per-entry slot ids, columns are permuted to
        slot-major order so each feature group is a contiguous range."""
        self._reset_slot_state()
        loc = Localizer()
        keys, _ = loc.count_uniq_index(batch)
        localized = loc.remap_index(keys)
        if batch.slot_ids is not None and batch.nnz:
            col_slot = np.zeros(len(keys), np.int32)
            col_slot[localized.indices] = batch.slot_ids
            order = np.argsort(col_slot, kind="stable")  # keys stay sorted per slot
            inv = np.empty_like(order)
            inv[order] = np.arange(len(order))
            localized = SparseBatch(
                y=localized.y,
                indptr=localized.indptr,
                indices=inv[localized.indices],
                values=localized.values,
                num_cols=localized.num_cols,
            )
            self.global_keys = keys[order]
            self.col_slots = col_slot[order]
            self._fill_slot_ranges()
            from ..data.info import info_from_batch

            self.info = info_from_batch(batch)
        else:
            self.global_keys = keys
            self.col_slots = None
        self.data = localized
        return self.data

    def _reset_slot_state(self) -> None:
        """Loading new data must not inherit the previous dataset's slot
        layout (stale ranges would mis-divide the new feature blocks)."""
        self.slot_ranges = {}
        self.col_slots = None
        self.info = None

    def _fill_slot_ranges(self) -> None:
        self.slot_ranges = {}
        if self.col_slots is None or not len(self.col_slots):
            return
        bounds = np.flatnonzero(np.diff(self.col_slots)) + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [len(self.col_slots)]])
        for lo, hi in zip(starts, ends):
            self.slot_ranges[int(self.col_slots[lo])] = Range(int(lo), int(hi))

    def divide_feature_blocks(self, num_groups: int = 1) -> List[FeatureBlock]:
        """ref BCDScheduler::DivideFeatureBlocks. With per-group slot info:
        a group whose features are correlated (nnz_per_row > 1) is split
        into ``ceil(nnz_per_row * feature_block_ratio)`` blocks over its
        column range; uncorrelated groups get one block (bcd.cc:70-89).
        Without slot structure, falls back to ~ratio×num_groups even blocks
        over all columns."""
        assert self.data is not None, "load data first"
        ratio = max(self.bcd_conf.feature_block_ratio, 0)
        self.fea_blk = []
        if self.info is not None and self.slot_ranges:
            # NOTE: the reference skips slot 0 here because its Example proto
            # stores the label in slot 0 (bcd.cc:75). Our parsers never put
            # labels in slots (they live in SparseBatch.y), so every slot in
            # slot_ranges is a genuine feature group — including group id 0,
            # which terafea (key >> 54 == 0) and adfea/ps files can emit.
            by_id = {s.id: s for s in self.info.slot}
            for sid in sorted(self.slot_ranges):
                crange = self.slot_ranges[sid]
                s = by_id.get(sid)
                nblk = 1
                if s is not None and s.nnz_ex > 0:
                    nnz_per_row = s.nnz_ele / s.nnz_ex
                    if nnz_per_row > 1 + 1e-6 and ratio > 0:
                        nblk = max(1, int(np.ceil(nnz_per_row * ratio)))
                nblk = min(nblk, max(1, crange.size()))
                for i in range(nblk):
                    blk = crange.even_divide(nblk, i)
                    if blk.size() > 0:
                        self.fea_blk.append(FeatureBlock(group=sid, col_range=blk))
        else:
            f = self.data.cols
            nblk = max(1, int(round(ratio * num_groups))) if ratio > 0 else 1
            nblk = min(nblk, max(1, f))
            full = Range(0, f)
            self.fea_blk = [
                FeatureBlock(group=0, col_range=full.even_divide(nblk, i))
                for i in range(nblk)
            ]
        self.blk_order = list(range(len(self.fea_blk)))
        return self.fea_blk

    def merge_progress(self, iteration: int, prog: BCDProgress) -> None:
        cur = self.g_progress.get(iteration)
        if cur is None:
            self.g_progress[iteration] = prog
        else:
            cur.merge(prog)

    def show_progress(self, iteration: int) -> str:
        """ref ShowTime/ShowObjective line."""
        p = self.g_progress.get(iteration, BCDProgress())
        return (
            f"iter {iteration:3d}: objv {p.objective:.6e} "
            f"rel {p.relative_obj:.2e} |w|0 {p.nnz_w} "
            f"active {p.nnz_active_set} vio {p.violation:.2e}"
        )
