"""Workload pool (ref ``src/learner/workload_pool.{h,cc}``).

Thread-safe assignment of file workloads to computation nodes: ``assign``
hands out the next unfinished piece, ``restore`` re-queues a dead node's
pieces, ``finish`` marks done, ``wait_until_done`` blocks. ``replica`` runs
each piece N times (num_data_pass) and ``shuffle`` randomizes order, like
the reference's Workload proto fields.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import List, Optional


@dataclasses.dataclass
class Workload:
    """ref learner/proto/workload.proto."""

    files: List[str] = dataclasses.field(default_factory=list)
    id: int = -1
    replica: int = 1
    shuffle: bool = False
    finished: List[int] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class _Info:
    node: str = ""
    load: Optional[Workload] = None
    assigned: bool = False
    finished: bool = False


class WorkloadPool:
    def __init__(self, load: Optional[Workload] = None):
        self._loads: List[_Info] = []  # guarded-by: _lock
        self._num_finished = 0  # guarded-by: _lock
        self._lock = threading.Lock()
        # _done shares _lock, so `with self._done:` guards the same state
        self._done = threading.Condition(self._lock)
        if load is not None:
            self.set(load)

    def set(self, load: Workload) -> None:
        pieces = []
        for _ in range(max(1, load.replica)):
            files = list(load.files)
            if load.shuffle:
                random.shuffle(files)
            pieces.extend(files)
        with self._lock:
            self._loads = [
                _Info(load=Workload(files=[f], id=i)) for i, f in enumerate(pieces)
            ]
            self._num_finished = 0

    def assign(self, node_id: str) -> Optional[Workload]:
        """Next unassigned piece, or None if all assigned/finished."""
        with self._lock:
            for info in self._loads:
                if not info.assigned and not info.finished:
                    info.assigned = True
                    info.node = node_id
                    return info.load
        return None

    def restore(self, node_id: str) -> None:
        """Re-queue unfinished pieces of a dead node (failure recovery)."""
        with self._lock:
            for info in self._loads:
                if info.node == node_id and info.assigned and not info.finished:
                    info.assigned = False
                    info.node = ""

    def finish(self, load_id: int) -> None:
        with self._lock:
            for info in self._loads:
                if info.load is not None and info.load.id == load_id and not info.finished:
                    info.finished = True
                    self._num_finished += 1
                    self._done.notify_all()
                    return

    def num_pending(self) -> int:
        with self._lock:
            return len(self._loads) - self._num_finished

    def wait_until_done(self, timeout: Optional[float] = None) -> bool:
        with self._lock:
            return self._done.wait_for(
                lambda: self._num_finished == len(self._loads), timeout=timeout
            )
