"""Staged, parallel host-ingest pipeline.

The OSDI'14 parameter server's core throughput lesson is to overlap
data movement with computation via producer/consumer pipelines; on TPU
the host→device link is the scarce resource (the device step is ~100x
faster than the transfer), so every host second spent parsing,
filtering, or packing ON the trainer thread is a second the link sits
idle. This module splits ingest into stages and pins each to the right
concurrency:

    read ──> filter ──> prep (xN workers, ordered) ──> consumer
    (feeder thread,     (OrderedStagePool)             (trainer, or a
     serial, in order)                                  DeviceUploader)

- **read**: pull batches from the source iterator (chunked parse lives
  inside StreamReader — the native parser releases the GIL, so this
  stage runs in true parallel with prep).
- **filter**: the countmin tail-feature filter is STATEFUL (insert
  then query), so it runs serially on the feeder thread in batch order
  — parallelizing it would change which keys pass the frequency
  threshold and break determinism.
- **prep**: localize/remap/ELL-pack/bitpack is stateless per batch —
  it fans out over ``workers`` pool threads, and the pool re-emits
  results IN SOURCE ORDER, so the consumer sees a batch stream
  bit-identical to the serial path (tier-1 parity test in
  tests/test_ingest.py). The stream wire's fused native prep
  (``learner/wire.encode_stream_shard``, one C ABI call per shard) and
  the staging-leg frame encode (``wire_compress``) both run INSIDE
  this stage — stateless, so the pool parallelism applies to them for
  free; the matching frame DECODE belongs to the single uploader
  thread (DeviceUploader), never here.

Exceptions from any stage forward to the consumer at the position they
occurred; ``close()`` joins every thread (early consumer exit leaks
nothing). Telemetry (``ps_ingest_*``, doc/OBSERVABILITY.md) records
per-stage latency histograms, queue-depth gauges, and volume counters.
"""
# bit-identical: this module is under the replay bit-identity contract (pslint determinism pass)

from __future__ import annotations

import time
from typing import Callable, Iterator, Optional

from ..system import faults
from ..telemetry import spans as telemetry_spans
from ..utils.concurrent import OrderedStagePool, iter_on_thread


def pipeline_instruments():
    """ps_ingest_* instruments against the process registry, or None
    while telemetry is disabled."""
    from ..telemetry import registry as telemetry_registry

    if not telemetry_registry.enabled():
        return None
    from ..telemetry.instruments import ingest_instruments

    return ingest_instruments(telemetry_registry.default_registry())


class IngestPipeline:
    """Multi-stage ingest: serial read+filter on a feeder thread, prep
    on an ordered worker pool, deterministic batch order throughout.

    ``filter_fn`` (optional) runs serially in batch order on the feeder
    thread; ``prep_fn`` (optional) runs on ``workers`` pool threads
    with in-order emission. With no prep_fn (or ``workers == 0``) the
    pipeline degenerates to a single prefetching producer thread —
    the classic MinibatchReader shape (ref sgd.h:60-143).

    Lifecycle: ``start()`` is idempotent; iteration before ``start()``
    raises; ``close()`` stops and joins every pipeline thread and is
    also called automatically when iteration completes. Usable as a
    context manager.
    """

    def __init__(
        self,
        source,
        *,
        filter_fn: Optional[Callable] = None,
        prep_fn: Optional[Callable] = None,
        workers: int = 0,
        capacity: int = 4,
        name: str = "ingest",
    ):
        self._source = iter(source)
        self._filter_fn = filter_fn
        self._prep_fn = prep_fn
        self._workers = max(0, int(workers))
        self._capacity = max(1, int(capacity))
        self._name = name
        self._tel = pipeline_instruments()
        # Single-consumer lifecycle state — deliberately lock-free (in
        # pslint's lock-pass scope, nothing guarded): start()/__iter__/
        # close() all run on the consumer thread; the pool and thread
        # iterator own their cross-thread synchronization internally.
        self._pool: Optional[OrderedStagePool] = None
        self._thread_it = None
        self._it: Optional[Iterator] = None
        self._closed = False
        # timeline tracing (telemetry/timeline.py): decided once at
        # start() — when a span sink is installed, every batch gets a
        # flow id on the feeder and rides it through filter → prep →
        # the consumer (items travel internally as (flow, batch) pairs;
        # the consumer-facing iterator unwraps). Off = zero overhead.
        self._trace = False

    # -- stage bodies --------------------------------------------------

    def _observe(self, stage: str, seconds: float) -> None:
        if self._tel is not None:
            self._tel["stage_seconds"].labels(stage=stage).observe(seconds)

    def _produced(self) -> Iterator:
        """Feeder-side serial stages: read (source next) + filter.
        When tracing, each batch is born here with a flow id and every
        stage span carries it — items flow on as (flow, batch)."""
        src = self._source
        while True:
            # pslint: disable=determinism — trace/telemetry birth timestamp only; it rides span metadata, never the encoded batch bytes the replay contract covers
            t_wall = time.time()
            t0 = time.perf_counter()
            try:
                batch = next(src)
            except StopIteration:
                return
            read_s = time.perf_counter() - t0
            self._observe("read", read_s)
            fid = None
            if self._trace:
                fid = telemetry_spans.new_flow()
                telemetry_spans.emit(
                    {
                        "kind": "span",
                        "name": "ingest.read",
                        "pipeline": self._name,
                        "t_wall": t_wall,
                        "dur_s": read_s,
                        "flow": fid,
                    }
                )
            if self._filter_fn is not None:
                if self._trace:
                    with telemetry_spans.flow_scope(fid):
                        with telemetry_spans.span(
                            "ingest.filter", pipeline=self._name
                        ):
                            t0 = time.perf_counter()
                            batch = self._filter_fn(batch)
                            self._observe(
                                "filter", time.perf_counter() - t0
                            )
                else:
                    t0 = time.perf_counter()
                    batch = self._filter_fn(batch)
                    self._observe("filter", time.perf_counter() - t0)
            yield (fid, batch) if self._trace else batch

    def _prep(self, item):
        # fault point (doc/ROBUSTNESS.md): an armed raise dies mid-batch
        # on a POOL WORKER thread — exercising the pool's contract that
        # worker exceptions forward to the consumer at the position they
        # occurred and close() still joins every thread
        faults.inject("ingest.prep", detail=self._name)
        if self._trace:
            fid, batch = item
            with telemetry_spans.flow_scope(fid):
                with telemetry_spans.span(
                    "ingest.prep", pipeline=self._name
                ):
                    t0 = time.perf_counter()
                    out = self._prep_fn(batch)
                    self._observe("prep", time.perf_counter() - t0)
            return fid, out
        t0 = time.perf_counter()
        out = self._prep_fn(item)
        self._observe("prep", time.perf_counter() - t0)
        return out

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "IngestPipeline":
        """Idempotent: build and start the pipeline threads once."""
        if self._closed:
            raise RuntimeError(f"{self._name}: start() after close()")
        if self._it is not None:
            return self
        self._trace = telemetry_spans.get_sink() is not None
        if self._prep_fn is not None and self._workers > 0:
            self._pool = OrderedStagePool(
                self._prep,
                self._produced(),
                num_workers=self._workers,
                capacity=self._capacity,
                name=self._name,
            ).start()
            self._it = iter(self._pool)
        else:
            # single producer thread: read + filter (+ prep, serially)
            src = (
                map(self._prep, self._produced())
                if self._prep_fn is not None
                else self._produced()
            )
            self._thread_it = iter_on_thread(src, maxsize=self._capacity)
            self._it = self._thread_it
        return self

    @property
    def started(self) -> bool:
        return self._it is not None

    def qsize(self) -> int:
        """Batches staged ahead of the consumer (0 before start)."""
        return self._pool.qsize() if self._pool is not None else 0

    def __iter__(self) -> Iterator:
        if self._it is None:
            raise RuntimeError(
                f"{self._name}: iterated before start() — call start() "
                "first (or use the pipeline as a context manager)"
            )
        tel = self._tel
        try:
            for item in self._it:
                # tracing wraps items as (flow, batch) internally; the
                # consumer sees the bare batch, with the batch's flow
                # active on its thread until it advances to the next
                # item (so a downstream stage's spans correlate)
                fid = None
                if self._trace:
                    fid, item = item
                if tel is not None:
                    tel["queue_depth"].labels(queue=self._name).set(
                        self.qsize()
                    )
                    # volume counters only for batch-shaped items; a
                    # pipeline emitting groups/parts leaves counting to
                    # the downstream stage (DeviceUploader) so batches
                    # are never double-counted
                    n = getattr(item, "n", None) or getattr(
                        item, "num_examples", None
                    )
                    if n:
                        tel["batches"].labels(pipeline=self._name).inc()
                        tel["examples"].labels(pipeline=self._name).inc(
                            int(n)
                        )
                with telemetry_spans.flow_scope(fid):
                    yield item
        finally:
            self.close()

    def close(self) -> None:
        """Stop and join every pipeline thread; safe to call twice."""
        self._closed = True
        if self._pool is not None:
            self._pool.close()
        if self._thread_it is not None:
            self._thread_it.close()

    def __enter__(self) -> "IngestPipeline":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()
