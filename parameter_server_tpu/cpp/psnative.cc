// Native host runtime for parameter_server_tpu.
//
// Plays the role of the reference's C++ data plane (src/util/crc32c.cc,
// murmurhash3.cc, src/data/text_parser.cc): checksums, hashing and text
// parsing are host-CPU bound, so they live here; the TPU compute path stays
// in JAX/XLA. Exposed with a plain C ABI and loaded via ctypes.
//
// Build: make -C parameter_server_tpu/cpp   (g++ -O3 -shared -fPIC)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cstdlib>
#include <cstdio>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, poly 0x82F63B78), slicing-by-8.
// Same polynomial/masking as the reference's util/crc32c.{h,cc} so
// signatures agree with the Python fallback.
// ---------------------------------------------------------------------------

static uint32_t kCrcTable[8][256];
static bool crc_init_done = false;

static void crc_init() {
  if (crc_init_done) return;
  for (int i = 0; i < 256; ++i) {
    uint32_t c = (uint32_t)i;
    for (int k = 0; k < 8; ++k) c = (c >> 1) ^ ((c & 1) ? 0x82F63B78u : 0);
    kCrcTable[0][i] = c;
  }
  for (int t = 1; t < 8; ++t) {
    for (int i = 0; i < 256; ++i) {
      uint32_t c = kCrcTable[t - 1][i];
      kCrcTable[t][i] = (c >> 8) ^ kCrcTable[0][c & 0xFF];
    }
  }
  crc_init_done = true;
}

uint32_t ps_crc32c(const uint8_t* data, uint64_t n) {
  crc_init();
  uint32_t crc = 0xFFFFFFFFu;
  uint64_t i = 0;
  while (i + 8 <= n) {
    uint64_t word;
    memcpy(&word, data + i, 8);
    word ^= (uint64_t)crc;
    crc = kCrcTable[7][word & 0xFF] ^ kCrcTable[6][(word >> 8) & 0xFF] ^
          kCrcTable[5][(word >> 16) & 0xFF] ^ kCrcTable[4][(word >> 24) & 0xFF] ^
          kCrcTable[3][(word >> 32) & 0xFF] ^ kCrcTable[2][(word >> 40) & 0xFF] ^
          kCrcTable[1][(word >> 48) & 0xFF] ^ kCrcTable[0][(word >> 56) & 0xFF];
    i += 8;
  }
  for (; i < n; ++i) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ data[i]) & 0xFF];
  return crc ^ 0xFFFFFFFFu;
}

// ---------------------------------------------------------------------------
// 64-bit mixing hash — must match utils/murmur.py (splitmix64 finalizer).
// ---------------------------------------------------------------------------

// The one definition of the mix — static inline so the hot loops below
// inline (and auto-vectorize) it while every entry point stays bit-exact
// with the others and with utils/murmur.py.
static inline uint64_t mix64(uint64_t z, uint64_t seed) {
  z += seed + 0x9E3779B97F4A7C15ull;
  z = (z ^ (z >> 30)) * 0xBF58476D1CE4E5B9ull;
  z = (z ^ (z >> 27)) * 0x94D049BB133111EBull;
  return z ^ (z >> 31);
}

uint64_t ps_mix64(uint64_t z, uint64_t seed) { return mix64(z, seed); }

void ps_mix64_array(const uint64_t* keys, uint64_t n, uint64_t seed,
                    uint64_t* out) {
  for (uint64_t i = 0; i < n; ++i) out[i] = ps_mix64(keys[i], seed);
}

// Fused key→slot mapping for hashed directories (KeyDirectory.slots): hash
// and reduce into [0, num_slots) in one pass, int32 out — saves the numpy
// uint64 temporaries and the second masking pass on the prep critical path.
void ps_hash_slots(const uint64_t* keys, uint64_t n, uint64_t seed,
                   uint64_t num_slots, int32_t* out) {
  if ((num_slots & (num_slots - 1)) == 0) {
    const uint64_t mask = num_slots - 1;
    for (uint64_t i = 0; i < n; ++i)  // inlined mix: auto-vectorizes
      out[i] = (int32_t)(mix64(keys[i], seed) & mask);
  } else {
    for (uint64_t i = 0; i < n; ++i)
      out[i] = (int32_t)(mix64(keys[i], seed) % num_slots);
  }
}

// ---------------------------------------------------------------------------
// MurmurHash3 x64 128-bit (Austin Appleby's public-domain algorithm; the
// reference's util/murmurhash3.cc uses the same function — criteo
// categorical tokens are keyed by h[0]^h[1] with seed 512927377, so this
// must be the real thing, bit-for-bit).
// ---------------------------------------------------------------------------

static inline uint64_t rotl64(uint64_t x, int8_t r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t fmix64(uint64_t k) {
  k ^= k >> 33;
  k *= 0xff51afd7ed558ccdull;
  k ^= k >> 33;
  k *= 0xc4ceb9fe1a85ec53ull;
  k ^= k >> 33;
  return k;
}

void ps_murmur3_x64_128(const uint8_t* data, uint64_t len, uint32_t seed,
                        uint64_t* out) {
  const uint64_t nblocks = len / 16;
  uint64_t h1 = seed, h2 = seed;
  const uint64_t c1 = 0x87c37b91114253d5ull;
  const uint64_t c2 = 0x4cf5ad432745937full;

  for (uint64_t i = 0; i < nblocks; ++i) {
    uint64_t k1, k2;
    memcpy(&k1, data + i * 16, 8);
    memcpy(&k2, data + i * 16 + 8, 8);
    k1 *= c1; k1 = rotl64(k1, 31); k1 *= c2; h1 ^= k1;
    h1 = rotl64(h1, 27); h1 += h2; h1 = h1 * 5 + 0x52dce729ull;
    k2 *= c2; k2 = rotl64(k2, 33); k2 *= c1; h2 ^= k2;
    h2 = rotl64(h2, 31); h2 += h1; h2 = h2 * 5 + 0x38495ab5ull;
  }

  const uint8_t* tail = data + nblocks * 16;
  uint64_t k1 = 0, k2 = 0;
  switch (len & 15) {
    case 15: k2 ^= (uint64_t)tail[14] << 48;  // fallthrough
    case 14: k2 ^= (uint64_t)tail[13] << 40;  // fallthrough
    case 13: k2 ^= (uint64_t)tail[12] << 32;  // fallthrough
    case 12: k2 ^= (uint64_t)tail[11] << 24;  // fallthrough
    case 11: k2 ^= (uint64_t)tail[10] << 16;  // fallthrough
    case 10: k2 ^= (uint64_t)tail[9] << 8;    // fallthrough
    case 9:
      k2 ^= (uint64_t)tail[8];
      k2 *= c2; k2 = rotl64(k2, 33); k2 *= c1; h2 ^= k2;
      // fallthrough
    case 8: k1 ^= (uint64_t)tail[7] << 56;  // fallthrough
    case 7: k1 ^= (uint64_t)tail[6] << 48;  // fallthrough
    case 6: k1 ^= (uint64_t)tail[5] << 40;  // fallthrough
    case 5: k1 ^= (uint64_t)tail[4] << 32;  // fallthrough
    case 4: k1 ^= (uint64_t)tail[3] << 24;  // fallthrough
    case 3: k1 ^= (uint64_t)tail[2] << 16;  // fallthrough
    case 2: k1 ^= (uint64_t)tail[1] << 8;   // fallthrough
    case 1:
      k1 ^= (uint64_t)tail[0];
      k1 *= c1; k1 = rotl64(k1, 31); k1 *= c2; h1 ^= k1;
  }

  h1 ^= len; h2 ^= len;
  h1 += h2; h2 += h1;
  h1 = fmix64(h1); h2 = fmix64(h2);
  h1 += h2; h2 += h1;
  out[0] = h1;
  out[1] = h2;
}

// ---------------------------------------------------------------------------
// Bit-packed wire format for slot-id streams. The host→device link is the
// pipeline's scarce resource; slot ids for a table of S entries need only
// ceil(log2 S) bits each, so we ship a little-endian bitstream instead of
// int32 (e.g. 22 bits/feature for a 4M-slot table = 31% fewer bytes than
// int32, 8% fewer than u24). Same byte-economy instinct as the reference's
// fixing_float filter (src/filter/fixing_float.h), applied to keys.
// ---------------------------------------------------------------------------

// Flush whole 32-bit words from the accumulator (single unaligned store
// instead of a per-byte loop — the packer's inner loop is on the prep
// critical path), then drain the <32-bit tail bytewise.
static inline uint8_t* flush32(uint8_t* w, uint64_t* acc, uint32_t* accbits) {
  if (*accbits >= 32) {
    uint32_t lo = (uint32_t)*acc;
    memcpy(w, &lo, 4);
    w += 4;
    *acc >>= 32;
    *accbits -= 32;
  }
  return w;
}

static inline uint8_t* drain_tail(uint8_t* w, uint64_t acc, uint32_t accbits) {
  while (accbits > 0) {
    *w++ = (uint8_t)acc;
    acc >>= 8;
    accbits = accbits >= 8 ? accbits - 8 : 0;
  }
  return w;
}

// Pack n b-bit values (b <= 31) into a little-endian bitstream. out must
// hold ceil(n*b/8) bytes.
void ps_pack_bits(const int32_t* vals, uint64_t n, uint32_t bits,
                  uint8_t* out) {
  const uint64_t vmask = (1ull << bits) - 1;  // truncate like pack_bits_np
  uint64_t acc = 0;
  uint32_t accbits = 0;
  uint8_t* w = out;
  for (uint64_t i = 0; i < n; ++i) {
    acc |= ((uint64_t)(uint32_t)vals[i] & vmask) << accbits;
    accbits += bits;
    w = flush32(w, &acc, &accbits);
  }
  drain_tail(w, acc, accbits);
}

// Fused hash → slot → bit-pack, tiled: the hash tile below is a plain
// elementwise loop with no loop-carried state, so -march=native
// vectorizes it (8-lane vpmullq on AVX-512DQ); the sequential pack
// accumulator then drains the cache-hot tile. One pass over the key
// stream, no full-size int32 temporary — the localization hot path for
// hashed directories (prep_batch_ell_bits).
void ps_hash_slots_packbits(const uint64_t* keys, uint64_t n, uint64_t seed,
                            uint64_t num_slots, uint32_t bits, uint8_t* out) {
  const int pow2 = (num_slots & (num_slots - 1)) == 0;
  const uint64_t mask = num_slots - 1;
  enum { TILE = 2048 };
  uint32_t tile[TILE];
  uint64_t acc = 0;
  uint32_t accbits = 0;
  uint8_t* w = out;
  for (uint64_t start = 0; start < n; start += TILE) {
    const uint64_t m = n - start < TILE ? n - start : TILE;
    const uint64_t* k = keys + start;
    if (pow2) {
      for (uint64_t j = 0; j < m; ++j)  // inlined mix: auto-vectorized
        tile[j] = (uint32_t)(mix64(k[j], seed) & mask);
    } else {
      for (uint64_t j = 0; j < m; ++j)
        tile[j] = (uint32_t)(mix64(k[j], seed) % num_slots);
    }
    for (uint64_t j = 0; j < m; ++j) {
      acc |= ((uint64_t)tile[j]) << accbits;
      accbits += bits;
      w = flush32(w, &acc, &accbits);
    }
  }
  drain_tail(w, acc, accbits);
}

// ---------------------------------------------------------------------------
// Fused stream-once wire prep: hash → per-lane unique → remap → bit-pack in
// ONE pass over a parsed shard (the "Localizer prep" host stage, fused).
//
// The stream-once (single-epoch) wire cannot win through the upload key
// cache — nothing repeats — so it wins through per-FIELD structure instead:
// a lane whose per-batch vocabulary is small (criteo's 13 integer count
// fields hash to ~90 distinct slots per 16k batch) ships a per-lane sorted
// unique-slot table ("uslots") plus per-row table indices ("ucols") at
// code_bits ≈ ceil(log2 vocab) bits, while high-vocabulary lanes (hashed
// categorical tokens, ~98% unique — incompressible past the hash) keep the
// raw ceil(log2 S)-bit stream. The caller pins the static widths
// (dict_mask/code_bits/dict_pad) from its first batch; this call verifies
// the batch fits them and returns -1 so the caller falls back to the raw
// bits wire (never wrong bytes, only fat ones).
//
// Output layout (must stay bit-identical to the NumPy fallback in
// learner/wire.py — parity is tier-1 tested):
//   raw_stream:   row-major (row, raw lanes in lane order), raw_bits each
//   code_stream:  row-major (row, dict lanes in lane order), code_bits each
//   table_stream: concatenated per-lane sorted unique slots, raw_bits each
//   lane_starts:  [n_dict + 1] table start offsets (last = total entries)
// All three byte buffers must arrive ZEROED at full capacity: the packers
// write only the live prefix and the zero tail is part of the wire bytes.
// ---------------------------------------------------------------------------

int64_t ps_stream_encode(const uint64_t* keys, int64_t nsub, int32_t lanes,
                         uint64_t seed, uint64_t num_slots,
                         const uint8_t* dict_mask, uint32_t raw_bits,
                         uint32_t code_bits, int32_t dict_pad,
                         int32_t* lane_starts, uint8_t* raw_stream,
                         uint8_t* code_stream, uint8_t* table_stream) {
  const int64_t n = nsub * (int64_t)lanes;
  const int pow2 = (num_slots & (num_slots - 1)) == 0;
  const uint64_t mask = num_slots - 1;
  int32_t* slots = new int32_t[n > 0 ? n : 1];
  if (pow2) {
    for (int64_t i = 0; i < n; ++i) slots[i] = (int32_t)(mix64(keys[i], seed) & mask);
  } else {
    for (int64_t i = 0; i < n; ++i) slots[i] = (int32_t)(mix64(keys[i], seed) % num_slots);
  }

  int32_t n_dict = 0;
  for (int32_t j = 0; j < lanes; ++j) n_dict += dict_mask[j] ? 1 : 0;

  // per-lane unique + remap via LSD radix sort over (slot << 32 | row)
  // composite keys: one linear walk over the sorted pairs assigns each
  // row its sorted-unique position — semantically np.unique +
  // return_inverse, but with no per-entry binary search (the
  // lower_bound variant measured ~2x SLOWER than the NumPy path; this
  // one beats it). Only ceil(raw_bits/8) counting passes run, since
  // the row half never needs ordering.
  int32_t* table = new int32_t[dict_pad > 0 ? dict_pad : 1];
  int32_t* codes = new int32_t[nsub * (int64_t)(n_dict ? n_dict : 1)];
  uint64_t* pairs = new uint64_t[nsub > 0 ? nsub : 1];
  uint64_t* aux = new uint64_t[nsub > 0 ? nsub : 1];
  int32_t total = 0;
  int32_t di = 0;
  int64_t rc = 0;
  const int64_t code_cap = 1ll << code_bits;
  const int slot_passes = (int)((raw_bits + 7) / 8);
  for (int32_t j = 0; j < lanes && rc == 0; ++j) {
    if (!dict_mask[j]) continue;
    for (int64_t r = 0; r < nsub; ++r)
      pairs[r] = ((uint64_t)(uint32_t)slots[r * lanes + j] << 32) |
                 (uint32_t)r;
    uint64_t* src = pairs;
    uint64_t* dst = aux;
    for (int p = 0; p < slot_passes; ++p) {
      const int shift = 32 + 8 * p;
      int64_t count[256] = {0};
      for (int64_t r = 0; r < nsub; ++r)
        ++count[(src[r] >> shift) & 0xFF];
      int64_t pos = 0;
      for (int b = 0; b < 256; ++b) {
        int64_t c = count[b];
        count[b] = pos;
        pos += c;
      }
      for (int64_t r = 0; r < nsub; ++r)
        dst[count[(src[r] >> shift) & 0xFF]++] = src[r];
      uint64_t* t = src;
      src = dst;
      dst = t;
    }
    lane_starts[di] = total;
    int32_t u = 0;
    uint32_t prev = 0;
    for (int64_t r = 0; r < nsub; ++r) {
      const uint32_t slot = (uint32_t)(src[r] >> 32);
      if (r == 0 || slot != prev) {
        if (total + u >= dict_pad || u >= code_cap) { rc = -1; break; }
        table[total + u] = (int32_t)slot;
        ++u;
        prev = slot;
      }
      codes[(int64_t)(uint32_t)src[r] * n_dict + di] = u - 1;
    }
    if (rc != 0) break;
    total += u;
    ++di;
  }
  if (rc == 0) {
    lane_starts[n_dict] = total;
    // raw lanes, row-major, packed sequentially at raw_bits
    {
      uint64_t acc = 0;
      uint32_t accbits = 0;
      uint8_t* w = raw_stream;
      const uint64_t vmask = (1ull << raw_bits) - 1;
      for (int64_t r = 0; r < nsub; ++r) {
        for (int32_t j = 0; j < lanes; ++j) {
          if (dict_mask[j]) continue;
          acc |= ((uint64_t)(uint32_t)slots[r * lanes + j] & vmask) << accbits;
          accbits += raw_bits;
          w = flush32(w, &acc, &accbits);
        }
      }
      drain_tail(w, acc, accbits);
    }
    // dict codes, row-major, packed at code_bits
    {
      uint64_t acc = 0;
      uint32_t accbits = 0;
      uint8_t* w = code_stream;
      const uint64_t vmask = (1ull << code_bits) - 1;
      for (int64_t i = 0; i < nsub * (int64_t)n_dict; ++i) {
        acc |= ((uint64_t)(uint32_t)codes[i] & vmask) << accbits;
        accbits += code_bits;
        w = flush32(w, &acc, &accbits);
      }
      drain_tail(w, acc, accbits);
    }
    ps_pack_bits(table, (uint64_t)total, raw_bits, table_stream);
    rc = total;
  }
  delete[] aux;
  delete[] pairs;
  delete[] codes;
  delete[] table;
  delete[] slots;
  return rc;
}

// ---------------------------------------------------------------------------
// Text parsers (libsvm / criteo). Parse a buffer of newline-separated
// examples into CSR arrays. Caller supplies output buffers sized by
// ps_parse_* return contract: returns #examples parsed (NEGATED minus one,
// i.e. -(rows+1), when the value-capacity budget was hit mid-stream so the
// caller can retry with a bigger buffer), fills nnz via out_nnz (rolled
// back to the last complete row on a capacity stop). `slots` (nullable)
// receives the per-entry feature-group id, matching the reference Example
// proto's Slot.id (data/text_parser.cc: libsvm features live in slot 1;
// criteo int feature i → slot i+1, categorical i → slot i+14).
// ---------------------------------------------------------------------------

static inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// libsvm: "label idx:val idx:val ..." (ref data/text_parser.cc ParseLibsvm
// + util/strtonum.h). Reference-STRICT: the label and every value must be
// a full decimal-float token, every feature token needs ':', indices use
// strtou64 semantics (sign wraps modulo 2^64, clamp at ULLONG_MAX) and
// must be non-decreasing in uint64 order, and ANY malformed token drops
// the WHOLE line (the reference returns false — no partial rows). An
// empty value ("idx:") is 0.0 (strtof("") succeeds with 0). Deliberate
// narrowing vs strtof, mirrored by the Python parser: hex floats / inf /
// nan are rejected (a decimal-only grammar both paths implement
// identically — real libsvm data never contains the exotic forms).

// validate [s, e) as [+-]?(digits[.digits*]? | .digits)([eE][+-]?digits)?
static int is_decfloat(const char* s, const char* e) {
  if (s >= e) return 0;
  if (*s == '+' || *s == '-') ++s;
  int mant = 0;
  while (s < e && *s >= '0' && *s <= '9') { ++s; mant = 1; }
  if (s < e && *s == '.') {
    ++s;
    while (s < e && *s >= '0' && *s <= '9') { ++s; mant = 1; }
  }
  if (!mant) return 0;
  if (s < e && (*s == 'e' || *s == 'E')) {
    ++s;
    if (s < e && (*s == '+' || *s == '-')) ++s;
    int ex = 0;
    while (s < e && *s >= '0' && *s <= '9') { ++s; ex = 1; }
    if (!ex) return 0;
  }
  return s == e;
}

// parse a VALIDATED decimal-float token (bounded copy so strtod never
// reads past the caller's buffer; tokens longer than the scratch are
// treated as malformed — no real data has 63-char numbers)
static int parse_decfloat(const char* s, const char* e, double* out) {
  // fast path: plain short integers (the binary-feature ":1" case and
  // small counts) — exact in double, no strtod call
  if (e - s >= 1 && e - s <= 15) {
    uint64_t acc = 0;
    const char* q = s;
    while (q < e && *q >= '0' && *q <= '9') acc = acc * 10 + (uint64_t)(*q++ - '0');
    if (q == e) { *out = (double)acc; return 1; }
  }
  char tmp[64];
  size_t n = (size_t)(e - s);
  if (n == 0 || n >= sizeof(tmp) || !is_decfloat(s, e)) return 0;
  memcpy(tmp, s, n);
  tmp[n] = 0;
  *out = strtod(tmp, NULL);
  return 1;
}

// strtou64 semantics over [s, e): optional sign (negation wraps modulo
// 2^64), clamp at ULLONG_MAX, all bytes must be consumed. An EMPTY
// range succeeds with 0 — strtoull("") performs no conversion and
// leaves end at the terminator, which strtonum.h counts as success
// (so ":val" is feature id 0). A bare sign still fails (end != NUL).
static int parse_u64_tok(const char* s, const char* e, uint64_t* out) {
  if (s == e) { *out = 0; return 1; }
  int neg = 0;
  if (s < e && (*s == '+' || *s == '-')) { neg = (*s == '-'); ++s; }
  if (s >= e) return 0;
  uint64_t v = 0;
  int clamped = 0;
  while (s < e) {
    if (*s < '0' || *s > '9') return 0;
    unsigned d = (unsigned)(*s++ - '0');
    if (v > (0xFFFFFFFFFFFFFFFFull - d) / 10) clamped = 1;
    v = v * 10 + d;
  }
  if (clamped) v = 0xFFFFFFFFFFFFFFFFull;
  *out = neg ? (0ull - v) : v;
  return 1;
}

static inline const char* tok_end(const char* p, const char* line_end) {
  while (p < line_end && *p != ' ' && *p != '\t' && *p != '\r') ++p;
  return p;
}

int64_t ps_parse_libsvm(const char* buf, int64_t len,
                        float* y, int64_t* indptr, uint64_t* indices,
                        float* values, int32_t* slots, int64_t max_rows,
                        int64_t max_nnz, int64_t* out_nnz) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t row = 0, nnz = 0;
  indptr[0] = 0;
  while (p < end && row < max_rows) {
    const char* line_end = (const char*)memchr(p, '\n', end - p);
    if (!line_end) line_end = end;
    const char* next = line_end + 1;
    p = skip_ws(p, line_end);
    if (p >= line_end) { p = next; continue; }
    // label: strict full token (fast path for the ubiquitous one-digit
    // labels, identical grammar)
    const char* te = tok_end(p, line_end);
    double label;
    if (te - p == 1 && *p >= '0' && *p <= '9') {
      label = (double)(*p - '0');
    } else if (te - p == 2 && (*p == '+' || *p == '-') &&
               p[1] >= '0' && p[1] <= '9') {
      label = (*p == '-') ? -(double)(p[1] - '0') : (double)(p[1] - '0');
    } else if (!parse_decfloat(p, te, &label)) {
      p = next;  // ref: strtofloat(label) false -> drop line
      continue;
    }
    p = te;
    int64_t row_start = nnz;
    uint64_t last_idx = 0;
    int ok = 1;
    while (1) {
      p = skip_ws(p, line_end);
      if (p >= line_end) break;
      te = tok_end(p, line_end);
      const char* colon = p;
      while (colon < te && *colon != ':') ++colon;
      uint64_t idx;
      if (colon >= te ||                       // no ':' in token
          !parse_u64_tok(p, colon, &idx) ||    // bad index
          last_idx > idx) {                    // unordered (uint64)
        ok = 0;
        break;
      }
      last_idx = idx;
      double val;
      if (colon + 1 == te) {
        val = 0.0;  // ref: strtofloat("") succeeds with 0
      } else if (!parse_decfloat(colon + 1, te, &val)) {
        ok = 0;
        break;
      }
      if (nnz >= max_nnz) { *out_nnz = indptr[row]; return -(row + 1); }
      indices[nnz] = idx;
      values[nnz] = (float)val;
      if (slots) slots[nnz] = 1;
      ++nnz;
      p = te;
    }
    if (!ok) { nnz = row_start; p = next; continue; }  // drop the WHOLE line
    y[row] = (float)(label <= 0 ? -1.0 : 1.0);
    indptr[++row] = nnz;
    p = next;
  }
  *out_nnz = nnz;
  return row;
}

// criteo tsv: "label \t i1..i13 ints \t c14..c39 categorical tokens".
// Reference semantics (data/text_parser.cc ParseCriteo): ALL features are
// BINARY keys — integer slot i with count c becomes key kMaxKey/13*i + c
// (one-hot by count), and a categorical token longer than 4 chars hashes
// through MurmurHash3_x64_128(seed 512927377) to h[0]^h[1]. Lines missing
// the integer-field tabs are dropped, as the reference returns false; a
// tab missing before the 25th categorical field likewise drops the line
// (ParseCriteo: `if (pp == NULL) { if (i != 25) return false; }`).
// criteo fields are a handful of bytes: an inline scan beats memchr's
// call + SIMD-setup overhead at these lengths (~40 fields/row), and a
// manual digit loop beats locale-aware strtol. Together ~1.8x parse
// throughput on the single-core host (the real-data pipeline is
// parse-bound there).
static inline const char* find_tab(const char* p, const char* line_end) {
  while (p < line_end && *p != '\t') ++p;
  return p < line_end ? p : NULL;
}

int64_t ps_parse_criteo(const char* buf, int64_t len,
                        float* y, int64_t* indptr, uint64_t* indices,
                        float* values, int32_t* slots, int64_t max_rows,
                        int64_t max_nnz, int64_t* out_nnz) {
  const char* p = buf;
  const char* end = buf + len;
  int64_t row = 0, nnz = 0;
  indptr[0] = 0;
  const uint64_t kStripe = 0xFFFFFFFFFFFFFFFFull / 13;  // kMaxKey / 13
  while (p < end && row < max_rows) {
    const char* line_end = (const char*)memchr(p, '\n', end - p);
    if (!line_end) line_end = end;
    if (p >= line_end) { p = line_end + 1; continue; }
    int64_t row_nnz_start = nnz;
    double label;
    const char* f = find_tab(p, line_end);
    if (!f) { p = line_end + 1; continue; }
    if (f == p + 1 && (p[0] == '0' || p[0] == '1')) {
      // the overwhelmingly common criteo case: a bare 0/1 label
      label = p[0] - '0';
    } else if (f == p) {
      // empty label field: strtofloat("") is a successful
      // no-conversion in the reference -> label 0 (negative class)
      label = 0.0;
    } else {
      // ref strtofloat: leading spaces, then a full decimal-float
      // field (same strict grammar as the libsvm paths)
      const char* ls = p;
      while (ls < f && *ls == ' ') ++ls;
      if (!parse_decfloat(ls, f, &label)) { p = line_end + 1; continue; }
    }
    p = f + 1;
    int ok = 1;
    for (int i = 0; i < 13; ++i) {  // integer count features
      f = find_tab(p, line_end);
      if (!f) { ok = 0; break; }  // ref: missing int tab drops the line
      if (f == p) {
        // EMPTY int field (how real criteo marks a missing value):
        // strtoi32("") succeeds with 0 in the reference, so it emits
        // key stripe*i + 0 — an empty field is NOT a skip
        if (nnz >= max_nnz) { *out_nnz = indptr[row]; return -(row + 1); }
        indices[nnz] = kStripe * (uint64_t)i;
        values[nnz] = 1.0f;
        if (slots) slots[nnz] = i + 1;
        ++nnz;
      } else {
        // ref strtoi32 (strtonum.h): strtol must consume the WHOLE field
        // (leading spaces ok, then sign + digits, nothing after — a
        // partial parse like "4bb3f55c" SKIPS the field), the long
        // clamps at +/-2^63-ish on overflow, and the int32 assignment
        // truncates mod 2^32
        const char* e = p;
        while (e < f && *e == ' ') ++e;
        int neg = 0;
        if (e < f && (*e == '-' || *e == '+')) { neg = (*e == '-'); ++e; }
        unsigned long long acc = 0;
        int clamped = 0;
        const char* digits_start = e;
        while (e < f && *e >= '0' && *e <= '9') {
          unsigned d = (unsigned)(*e++ - '0');
          if (acc > (0x7FFFFFFFFFFFFFFFull - d) / 10) { clamped = 1; }
          acc = acc * 10 + d;
        }
        if (e != digits_start && e == f) {
          int64_t cnt64;
          if (clamped) cnt64 = neg ? (-0x7FFFFFFFFFFFFFFFll - 1) : 0x7FFFFFFFFFFFFFFFll;
          else cnt64 = neg ? -(int64_t)acc : (int64_t)acc;
          int64_t cnt = (int64_t)(int32_t)(uint32_t)(uint64_t)cnt64;
          if (nnz >= max_nnz) { *out_nnz = indptr[row]; return -(row + 1); }
          indices[nnz] = kStripe * (uint64_t)i + (uint64_t)cnt;
          values[nnz] = 1.0f;
          if (slots) slots[nnz] = i + 1;
          ++nnz;
        }
      }
      p = f + 1;
    }
    if (!ok) { nnz = row_nnz_start; p = line_end + 1; continue; }
    for (int i = 0; i < 26; ++i) {  // categorical tokens
      f = (p <= line_end) ? find_tab(p, line_end) : NULL;
      if (!f && i != 25) { ok = 0; break; }  // ref: missing cat tab drops line
      const char* tok_end = f ? f : line_end;
      int64_t n = tok_end - p;
      if (n > 4) {  // ref: short/empty tokens are skipped
        if (nnz >= max_nnz) { *out_nnz = indptr[row]; return -(row + 1); }
        uint64_t h[2];
        ps_murmur3_x64_128((const uint8_t*)p, (uint64_t)n, 512927377u, h);
        indices[nnz] = h[0] ^ h[1];
        values[nnz] = 1.0f;
        if (slots) slots[nnz] = i + 14;
        ++nnz;
      }
      p = tok_end + 1;
    }
    if (!ok) { nnz = row_nnz_start; p = line_end + 1; continue; }
    y[row] = label > 0 ? 1.0f : -1.0f;
    indptr[++row] = nnz;
    p = line_end + 1;
  }
  *out_nnz = nnz;
  return row;
}

// ---------------------------------------------------------------------------
// Fast byte-level LZ wire codec — the role of the reference's snappy
// message compression (src/util/shared_array_inl.h:245 CompressTo /
// UncompressFrom, used by src/filter/compressing.h on every filtered
// message). snappy/LZ4 aren't in this environment, so this is an
// LZ4-style block codec of our own: greedy 4-byte-hash matcher, 16-bit
// offsets, token = (literal_len:4 | match_len-4:4) with 255-run length
// extensions, stream ends with a literals-only tail. Both ends are this
// library, so the format only needs to be self-consistent + safe: the
// decompressor bounds-checks every read/write and rejects malformed
// input with -1 (wire payloads are untrusted); -2 means the output
// buffer is too small (retry with a bigger one — distinct from -1 so
// callers never grow buffers for garbage input).

static inline uint32_t lz_hash32(uint32_t v) {
  return (v * 2654435761u) >> 19;  // 13-bit table index
}

uint64_t ps_lz_max_compressed(uint64_t n) {
  // worst case: pure literals = n + one length-extension byte per 255
  // literals + token + terminator slack
  return n + n / 255 + 16;
}

int64_t ps_lz_compress(const uint8_t* src, uint64_t n,
                       uint8_t* dst, uint64_t cap) {
  const uint8_t* ip = src;
  const uint8_t* iend = src + n;
  const uint8_t* anchor = src;
  // matches must leave >= 5 bytes of tail literals and stop match
  // extension 5 bytes early (mirrors LZ4's endgame margins; keeps the
  // decoder's overlap copy away from buffer ends)
  const uint8_t* mflimit = (n > 12) ? iend - 12 : src;
  const uint8_t* matchlimit = iend - 5;
  uint8_t* op = dst;
  uint8_t* oend = dst + cap;
  uint32_t table[1u << 13];  // position+1 into src; 0 = empty
  memset(table, 0, sizeof(table));

  if (n > 12) {
    // skip acceleration (the LZ4 trick): on incompressible stretches
    // the step between probes grows, so pure-noise input costs ~1
    // probe per 2 bytes instead of per byte
    uint32_t miss = 0;
    while (ip < mflimit) {
      uint32_t seq;
      memcpy(&seq, ip, 4);
      uint32_t h = lz_hash32(seq);
      uint32_t prev = table[h];
      table[h] = (uint32_t)(ip - src) + 1;
      uint32_t cand4;
      if (prev && (uint64_t)(ip - src) + 1 - prev <= 0xFFFF &&
          (memcpy(&cand4, src + prev - 1, 4), cand4 == seq)) {
        miss = 0;
        const uint8_t* match = src + prev - 1;
        const uint8_t* q = ip + 4;
        const uint8_t* m = match + 4;
        while (q < matchlimit && *q == *m) { ++q; ++m; }
        uint64_t mlen = (uint64_t)(q - ip) - 4;  // stored as len-4
        uint64_t lit = (uint64_t)(ip - anchor);
        // token + worst-case length extensions + literals + offset
        if ((uint64_t)(oend - op) < 1 + lit + lit / 255 + 1 + 2 + mlen / 255 + 1)
          return -1;
        uint8_t* tok = op++;
        if (lit >= 15) {
          *tok = (uint8_t)(15u << 4);
          uint64_t rest = lit - 15;
          while (rest >= 255) { *op++ = 255; rest -= 255; }
          *op++ = (uint8_t)rest;
        } else {
          *tok = (uint8_t)(lit << 4);
        }
        memcpy(op, anchor, lit);
        op += lit;
        uint32_t off = (uint32_t)(ip - match);
        *op++ = (uint8_t)(off & 0xFF);
        *op++ = (uint8_t)(off >> 8);
        if (mlen >= 15) {
          *tok |= 15;
          uint64_t rest = mlen - 15;
          while (rest >= 255) { *op++ = 255; rest -= 255; }
          *op++ = (uint8_t)rest;
        } else {
          *tok |= (uint8_t)mlen;
        }
        ip += mlen + 4;
        anchor = ip;
      } else {
        ip += 1 + (miss++ >> 6);
      }
    }
  }
  // literals-only tail
  {
    uint64_t lit = (uint64_t)(iend - anchor);
    if ((uint64_t)(oend - op) < 1 + lit + lit / 255 + 1) return -1;
    uint8_t* tok = op++;
    if (lit >= 15) {
      *tok = (uint8_t)(15u << 4);
      uint64_t rest = lit - 15;
      while (rest >= 255) { *op++ = 255; rest -= 255; }
      *op++ = (uint8_t)rest;
    } else {
      *tok = (uint8_t)(lit << 4);
    }
    memcpy(op, anchor, lit);
    op += lit;
  }
  return (int64_t)(op - dst);
}

int64_t ps_lz_decompress(const uint8_t* src, uint64_t n,
                         uint8_t* dst, uint64_t cap) {
  const uint8_t* ip = src;
  const uint8_t* iend = src + n;
  uint8_t* op = dst;
  uint8_t* oend = dst + cap;
  while (ip < iend) {
    uint8_t tok = *ip++;
    uint64_t lit = tok >> 4;
    if (lit == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        lit += b;
      } while (b == 255);
    }
    if (lit > (uint64_t)(iend - ip)) return -1;
    if (lit > (uint64_t)(oend - op)) return -2;
    memcpy(op, ip, lit);
    op += lit;
    ip += lit;
    if (ip >= iend) {
      // literals-only tail: a match-nibble here would be malformed
      if ((tok & 15) != 0) return -1;
      break;
    }
    if ((uint64_t)(iend - ip) < 2) return -1;
    uint32_t off = (uint32_t)ip[0] | ((uint32_t)ip[1] << 8);
    ip += 2;
    uint64_t mlen = (uint64_t)(tok & 15);
    if (mlen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        mlen += b;
      } while (b == 255);
    }
    mlen += 4;
    if (off == 0 || off > (uint64_t)(op - dst)) return -1;
    if (mlen > (uint64_t)(oend - op)) return -2;
    const uint8_t* m = op - off;
    if (off >= mlen) {
      memcpy(op, m, mlen);  // disjoint
    } else if (off >= 8 && mlen + 8 <= (uint64_t)(oend - op)) {
      // overlapping but period >= 8: 8-byte strided copies are safe
      // (each copies bytes written >= 8 positions back); may write up
      // to 7 bytes past mlen, bounded above
      for (uint64_t i = 0; i < mlen; i += 8) memcpy(op + i, m + i, 8);
    } else {
      // short period (e.g. RLE, off=1): byte-wise is required
      for (uint64_t i = 0; i < mlen; ++i) op[i] = m[i];
    }
    op += mlen;
  }
  return (int64_t)(op - dst);
}


}  // extern "C"
