"""ctypes loader for the native host library (``libpsnative.so``).

Builds lazily with ``make`` on first use if g++ is available; all callers
must handle ``native() is None`` and fall back to NumPy paths. This mirrors
the reference's split: C++ for the host data plane, accelerator code
elsewhere.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libpsnative.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _configure(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    f32p = ctypes.POINTER(ctypes.c_float)
    lib.ps_crc32c.argtypes = [u8p, ctypes.c_uint64]
    lib.ps_crc32c.restype = ctypes.c_uint32
    lib.ps_mix64.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
    lib.ps_mix64.restype = ctypes.c_uint64
    lib.ps_mix64_array.argtypes = [u64p, ctypes.c_uint64, ctypes.c_uint64, u64p]
    lib.ps_mix64_array.restype = None
    i32p = ctypes.POINTER(ctypes.c_int32)
    lib.ps_hash_slots.argtypes = [
        u64p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64, i32p,
    ]
    lib.ps_hash_slots.restype = None
    lib.ps_pack_bits.argtypes = [i32p, ctypes.c_uint64, ctypes.c_uint32, u8p]
    lib.ps_pack_bits.restype = None
    lib.ps_hash_slots_packbits.argtypes = [
        u64p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_uint32, u8p,
    ]
    lib.ps_hash_slots_packbits.restype = None
    lib.ps_stream_encode.argtypes = [
        u64p, ctypes.c_int64, ctypes.c_int32,      # keys, nsub, lanes
        ctypes.c_uint64, ctypes.c_uint64,          # seed, num_slots
        u8p, ctypes.c_uint32, ctypes.c_uint32,     # dict_mask, raw/code bits
        ctypes.c_int32,                            # dict_pad
        i32p, u8p, u8p, u8p,                       # lane_starts + 3 streams
    ]
    lib.ps_stream_encode.restype = ctypes.c_int64
    lib.ps_lz_max_compressed.argtypes = [ctypes.c_uint64]
    lib.ps_lz_max_compressed.restype = ctypes.c_uint64
    lib.ps_lz_compress.argtypes = [u8p, ctypes.c_uint64, u8p, ctypes.c_uint64]
    lib.ps_lz_compress.restype = ctypes.c_int64
    lib.ps_lz_decompress.argtypes = [u8p, ctypes.c_uint64, u8p, ctypes.c_uint64]
    lib.ps_lz_decompress.restype = ctypes.c_int64
    lib.ps_murmur3_x64_128.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32, u64p,
    ]
    lib.ps_murmur3_x64_128.restype = None
    for name in ("ps_parse_libsvm", "ps_parse_criteo"):
        fn = getattr(lib, name)
        fn.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            f32p, i64p, u64p, f32p, i32p,
            ctypes.c_int64, ctypes.c_int64, i64p,
        ]
        fn.restype = ctypes.c_int64
    return lib


def native() -> Optional[ctypes.CDLL]:
    """The loaded native library, building it if needed; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        src = os.path.join(_DIR, "psnative.cc")
        stale = os.path.exists(_LIB_PATH) and os.path.exists(src) and (
            os.path.getmtime(src) > os.path.getmtime(_LIB_PATH)
        )
        if not os.path.exists(_LIB_PATH) or stale:
            try:
                subprocess.run(
                    ["make", "-C", _DIR],
                    capture_output=True,
                    timeout=120,
                    check=True,
                )
            except (OSError, subprocess.SubprocessError):
                return None
        try:
            _lib = _configure(ctypes.CDLL(_LIB_PATH))
        except (OSError, AttributeError):
            # AttributeError: a stale .so missing newer symbols that slipped
            # past the mtime check — honor the None contract, don't raise
            _lib = None
        return _lib
