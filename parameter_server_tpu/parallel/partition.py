"""Declarative partitioning: regex rules → PartitionSpecs, resolved once.

GSPMD (Xu et al., arXiv:2105.04663) showed that partition placement
should be *declared* — specs over named mesh axes — not constructed
ad-hoc at every callsite. Before this module, `NamedSharding(mesh,
P(SERVER_AXIS, None))` was hand-built in parallel/mesh.py, ops/kv_ops.py,
apps/linear/async_sgd.py and parameter/kv_layer.py; each was one more
place a layout decision could silently drift. Now the canonical specs
live HERE, a rule table maps parameter-tree paths to specs the way the
reference's ``Range<Key>::EvenDivide`` mapped key ranges to servers, and
every layer resolves its layout through one :class:`Partitioner` per
mesh (cached — "resolved once per model/table").

The second half closes the loop PR 15 opened: the learning truth plane
measures per-shard key heat and an imbalance ratio, and the OSDI'14
parameter server made range repartitioning over measured load a core
server capability. :class:`RebalanceController` listens for the shipped
``shard_imbalance`` alert, recomputes the slot assignment from the
measured hot-slot / load-share tables (:func:`plan_rebalance`), and
migrates rows online through ``KVVector.migrate`` — the PR 9
consistent-snapshot machinery (per-channel barrier timestamps bound
exactly which pushes are in the snapshot; journaled pushes past the
barrier replay in order). See doc/PERFORMANCE.md "Declarative
partitioning" and doc/ROBUSTNESS.md "The backup barrier".
"""

from __future__ import annotations

import abc
import dataclasses
import logging
import re
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, SERVER_AXIS

_LOG = logging.getLogger(__name__)

#: the canonical declared specs — the ad-hoc per-callsite ``P(...)``
#: constructions these replace (mesh.table_sharding, kv_ops shard_map
#: in_specs, async_sgd state_spec, kv_layer._sharding)
TABLE_SPEC = P(SERVER_AXIS, None)  # [P, k] tables: rows by server key range
BATCH_SPEC = P(DATA_AXIS)          # example batches over the worker axis
REPLICATED_SPEC = P()

#: default rule table: (path regex, spec). First match wins; specs are
#: fitted to each leaf's rank (:func:`fit_spec`), so one rule covers a
#: [P] state vector and a [P, k] table alike. The catch-all row-shards
#: every array leaf — the updater-state convention every step builder
#: used inline before this table existed.
DEFAULT_RULES: Tuple[Tuple[str, P], ...] = (
    # example-batch leaves ride the data axis
    (r"(^|/)(batch|examples?|y|mask|slots|vals)($|/)", BATCH_SPEC),
    # scalar hyperparams / step counters stay replicated
    (r"(^|/)(lr|step|count|beta|alpha|lambda)($|/)", REPLICATED_SPEC),
    # parameter tables and updater state: rows by server key range
    (r".*", TABLE_SPEC),
)


def tree_path_to_string(path: Tuple, sep: str = "/") -> str:
    """Render a jax tree path as a ``/``-joined name string."""
    keys = []
    for key in path:
        if hasattr(key, "key"):
            keys.append(str(key.key))
        elif hasattr(key, "idx"):
            keys.append(str(key.idx))
        elif hasattr(key, "name"):
            keys.append(str(key.name))
        else:
            keys.append(str(key))
    return sep.join(keys)


def named_tree_map(f: Callable, tree: Any, *rest, sep: str = "/",
                   is_leaf=None) -> Any:
    """``jax.tree.map`` variant whose mapped function receives the
    leaf's ``/``-joined path name as its first argument."""
    return jax.tree_util.tree_map_with_path(
        lambda path, x, *r: f(tree_path_to_string(path, sep=sep), x, *r),
        tree,
        *rest,
        is_leaf=is_leaf,
    )


def fit_spec(spec: P, ndim: int) -> P:
    """Fit a declared spec to a leaf's rank: scalars are replicated,
    shorter specs get trailing ``None`` dims, longer ones truncate (a
    row-sharding rule applies to any table rank)."""
    if ndim == 0:
        return P()
    parts = tuple(spec)[:ndim]
    return P(*(parts + (None,) * (ndim - len(parts))))


def match_partition_rules(rules: Sequence[Tuple[str, P]], tree: Any) -> Any:
    """Resolve a pytree of PartitionSpecs from ``(regex, spec)`` rules:
    each leaf's path name is matched against the rules in order; the
    first hit's spec — fitted to the leaf's rank — wins. No match is an
    error (a silent default is a layout bug waiting to ship)."""

    def match(name: str, leaf: Any) -> P:
        ndim = getattr(leaf, "ndim", np.ndim(leaf))
        for pattern, spec in rules:
            if re.search(pattern, name):
                return fit_spec(spec, ndim)
        raise ValueError(
            f"no partition rule matched {name!r} — add a rule (or a "
            "catch-all) to the table"
        )

    return named_tree_map(match, tree)


def state_partition_spec(state: Any) -> Any:
    """The updater-state spec tree: every array leaf row-sharded over
    the server key ranges, scalars replicated — the ONE declaration the
    step builders (async_sgd), KVMap push specs and init_sharded all
    resolve instead of re-deriving inline."""
    return match_partition_rules(((r".*", TABLE_SPEC),), state)


class Partitioner(abc.ABC):
    """Resolve declared partition specs against one mesh.

    The shard/gather/local_data surface mirrors the exemplar
    partitioner ABCs: ``partition`` resolves specs for a tree,
    ``shard`` places a host tree onto the mesh under those specs,
    ``gather`` pulls a sharded tree back to host, ``local_data`` slices
    a global batch down to this process's data-axis rows.
    """

    @property
    @abc.abstractmethod
    def mesh(self) -> Mesh: ...

    @abc.abstractmethod
    def partition(self, tree: Any) -> Any:
        """Pytree of fitted PartitionSpecs for ``tree``'s leaves."""

    def sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def shard(self, tree: Any, specs: Any = None) -> Any:
        specs = self.partition(tree) if specs is None else specs
        return jax.tree.map(
            lambda x, s: jax.device_put(x, self.sharding(s)), tree, specs
        )

    def gather(self, tree: Any) -> Any:
        return jax.tree.map(lambda x: np.asarray(x), tree)

    def local_data(self, x: np.ndarray) -> np.ndarray:
        """This process's slice of a data-axis-sharded global batch."""
        n_proc = jax.process_count()
        if n_proc == 1:
            return x
        per = len(x) // n_proc
        i = jax.process_index()
        return x[i * per:(i + 1) * per]


class MeshPartitioner(Partitioner):
    """The rule-table partitioner every layer resolves through.

    One instance per mesh (see :func:`for_mesh`); the canonical
    table/batch/replicated NamedShardings are resolved once at
    construction — callsites that used to build ``NamedSharding(mesh,
    P(SERVER_AXIS, None))`` inline now read :meth:`table_sharding`.
    """

    def __init__(self, mesh: Mesh,
                 rules: Sequence[Tuple[str, P]] = DEFAULT_RULES):
        self._mesh = mesh
        self.rules = tuple(rules)
        # resolved once per mesh — the whole point of declaring them
        self._table = NamedSharding(mesh, TABLE_SPEC)
        self._batch = NamedSharding(mesh, BATCH_SPEC)
        self._replicated = NamedSharding(mesh, REPLICATED_SPEC)

    @property
    def mesh(self) -> Mesh:
        return self._mesh

    def partition(self, tree: Any) -> Any:
        return match_partition_rules(self.rules, tree)

    # -- the canonical resolved shardings --

    def table_sharding(self) -> NamedSharding:
        """[P, k] parameter tables: rows by server key range."""
        return self._table

    def batch_sharding(self) -> NamedSharding:
        """Example batches: rows over the data (worker) axis."""
        return self._batch

    def replicated(self) -> NamedSharding:
        return self._replicated

    def state_specs(self, state: Any) -> Any:
        """Updater-state spec tree (the KVMap/async_sgd shard_map
        in/out specs)."""
        return state_partition_spec(state)

    def layer_sharding(self, shape, partition_thr: int) -> NamedSharding:
        """KVLayer's placement rule as a declared policy: layers of
        ``partition_thr``+ elements shard their first server-divisible
        dim; small layers replicate (ref kv_layer.h partition_thr)."""
        size = int(np.prod(shape)) if len(shape) else 1
        n_server = self._mesh.shape[SERVER_AXIS]
        if size >= partition_thr:
            for dim, d in enumerate(shape):
                if d % n_server == 0:
                    spec = [None] * len(shape)
                    spec[dim] = SERVER_AXIS
                    return NamedSharding(self._mesh, P(*spec))
        return self._replicated

    def init_sharded(self, init_fn: Callable[[], Any]) -> Any:
        """Materialize ``init_fn()`` directly into its resolved layout
        (jit + out_shardings — no transient unsharded copy; the path
        that lets a table bigger than one chip's HBM initialize at all,
        see mesh.init_sharded's sizing note)."""
        shapes = jax.eval_shape(init_fn)
        specs = self.partition(shapes)
        shardings = jax.tree.map(lambda s: self.sharding(s), specs)
        with self._mesh:
            return jax.jit(init_fn, out_shardings=shardings)()


_partitioners: Dict[Mesh, MeshPartitioner] = {}  # guarded-by: _partitioners_lock
_partitioners_lock = threading.Lock()


def for_mesh(mesh: Mesh) -> MeshPartitioner:
    """The (cached) partitioner for a mesh — specs resolve once, every
    layer shares the instance."""
    with _partitioners_lock:
        p = _partitioners.get(mesh)
        if p is None:
            p = _partitioners[mesh] = MeshPartitioner(mesh)
        return p


# -- heat-driven repartitioning ---------------------------------------------


@dataclasses.dataclass
class RebalancePlan:
    """A slot permutation recomputed from measured load.

    ``perm`` is a bijection over the table's padded slot capacity in
    CURRENT-layout slot ids: row ``j`` moves to ``perm[j]``. ``moves``
    lists the hot slots relocated (slot, est weight, from → to shard);
    the matching cold slots travel the other way (a swap keeps every
    shard's row count static — shapes never change, only ownership).
    """

    perm: np.ndarray
    moves: List[Dict[str, Any]]
    imbalance_before: Optional[float]
    predicted_imbalance: Optional[float]

    @property
    def rows_moved(self) -> int:
        return int(np.count_nonzero(self.perm != np.arange(len(self.perm))))


def plan_rebalance(heat, num_slots: int, num_shards: int,
                   max_moves: int = 64) -> Optional[RebalancePlan]:
    """Recompute slot ownership from the measured hot-slot / load-share
    tables (telemetry/learning.KeyHeat — the PR 15 inputs).

    Greedy, deterministic: hottest slots first, each moved from its
    (over-mean) shard to the currently least-loaded shard by swapping
    with a cold slot there. Counts are adjusted per move so later moves
    see the earlier ones; the predicted imbalance is disclosed in the
    plan and metered as ``ps_partition_post_imbalance`` until real
    post-rebalance traffic replaces it.
    """
    if num_shards < 2:
        return None
    shares = heat.shares()
    imbalance = shares.get("imbalance")
    total = float(shares.get("total_weight") or 0.0)
    if imbalance is None or total <= 0:
        return None
    counts = np.asarray(shares["shares"], np.float64) * total
    hot = heat.top_slots()
    if not hot:
        return None
    per = num_slots // num_shards
    hot_set = {h["slot"] for h in hot}
    used_cold: set = set()
    perm = np.arange(num_slots, dtype=np.int64)
    moves: List[Dict[str, Any]] = []

    def cold_slot(shard: int) -> Optional[int]:
        # deterministic: scan the shard's range from the top — padding
        # rows and never-hot slots live there
        for s in range(per * (shard + 1) - 1, per * shard - 1, -1):
            if s not in hot_set and s not in used_cold:
                return s
        return None

    for h in sorted(hot, key=lambda d: -d["est"]):
        if len(moves) >= max_moves:
            break
        src = int(h["shard"])
        if counts[src] <= counts.mean():
            continue  # its shard is not the problem
        dst = int(np.argmin(counts))
        if dst == src:
            continue
        cold = cold_slot(dst)
        if cold is None:
            continue
        slot = int(h["slot"])
        used_cold.add(cold)
        used_cold.add(slot)  # a slot moves at most once per plan
        perm[slot], perm[cold] = perm[cold], perm[slot]
        w = float(h["est"])
        counts[src] -= w
        counts[dst] += w
        moves.append({
            "slot": slot, "est": w, "from_shard": src, "to_shard": dst,
            "cold_slot": cold,
        })
    if not moves:
        return None
    predicted = (
        float(counts.max() / counts.mean()) if counts.mean() > 0 else None
    )
    return RebalancePlan(
        perm=perm,
        moves=moves,
        imbalance_before=float(imbalance),
        predicted_imbalance=predicted,
    )


def _rule_threshold(default: float = 4.0) -> float:
    """The shipped ``shard_imbalance`` rule's threshold — the
    controller triggers at the same level the alert pages at."""
    try:
        from ..telemetry import alerts as alerts_mod

        for rule in alerts_mod.default_rules():
            if rule.name == "shard_imbalance":
                return float(rule.threshold)
    except Exception:
        pass
    return default


class RebalanceController:
    """Heat-driven live repartitioning: ``shard_imbalance`` firing →
    :func:`plan_rebalance` over the measured tables → one online
    ``KVVector.migrate`` through the PR 9 snapshot/barrier/replay
    machinery — serving degrades (never errors) during the move, and
    the post-migration table is bit-identical to an undisturbed run
    (tests/test_rebalance.py pins both).

    Thread-safety: ``execute`` may be called from the alert manager's
    evaluation thread (via :meth:`attach`) and from drills/operators
    concurrently — one lock serializes rebalances and guards the
    history.
    """

    def __init__(self, store, heat, channel: int = 0,
                 threshold: Optional[float] = None,
                 max_moves: int = 64):
        self.store = store
        self.heat = heat
        self.channel = int(channel)
        self.threshold = (
            _rule_threshold() if threshold is None else float(threshold)
        )
        self.max_moves = int(max_moves)
        self._history: List[dict] = []  # guarded-by: _lock
        self._lock = threading.Lock()

    def _tel(self):
        from ..telemetry.instruments import cached_partition_instruments

        return cached_partition_instruments()

    def should_rebalance(self) -> bool:
        imb = self.heat.shares().get("imbalance")
        return imb is not None and imb > self.threshold

    def plan(self) -> Optional[RebalancePlan]:
        return plan_rebalance(
            self.heat, self.store.num_slots, self.heat.num_shards,
            max_moves=self.max_moves,
        )

    def execute(self, force: bool = False) -> Optional[dict]:
        """Plan + migrate once, if over threshold (or ``force``).
        Returns the rebalance record (also kept on :meth:`history`), or
        None when balance is already acceptable / no useful plan."""
        with self._lock:
            imb = self.heat.shares().get("imbalance")
            if not force and (imb is None or imb <= self.threshold):
                return None
            plan = self.plan()
            if plan is None or plan.rows_moved == 0:
                return None
            t0 = time.perf_counter()
            mig = self.store.migrate(plan.perm, ch=self.channel)
            dt = time.perf_counter() - t0
            # fresh measurement window: the old window's shard counts
            # describe the OLD layout — post-rebalance imbalance must
            # be re-measured, not inherited (hot-slot ids translate)
            self.heat.rebase(plan.perm)
            tel = self._tel()
            if tel is not None:
                tel["rebalances"].inc()
                tel["rows_moved"].inc(plan.rows_moved)
                tel["migration_seconds"].observe(dt)
                if plan.predicted_imbalance is not None:
                    tel["post_imbalance"].set(plan.predicted_imbalance)
            record = {
                "rows_moved": plan.rows_moved,
                "moves": len(plan.moves),
                "migration_seconds": round(dt, 4),
                "imbalance_before": plan.imbalance_before,
                "predicted_imbalance": plan.predicted_imbalance,
                "barrier_ts": mig.get("barrier_ts"),
                "install_ts": mig.get("install_ts"),
                "replayed_pushes": mig.get("replayed"),
                "journaled_pushes": mig.get("journaled"),
                "attempts": mig.get("attempts"),
            }
            self._history.append(record)
            return record

    def refresh_post_imbalance(self) -> Optional[float]:
        """Read the re-measured (post-rebase) imbalance and publish it
        as ``ps_partition_post_imbalance`` — the drill calls this after
        post-rebalance traffic has flowed."""
        imb = self.heat.shares().get("imbalance")
        tel = self._tel()
        if imb is not None and tel is not None:
            tel["post_imbalance"].set(imb)
        return imb

    def history(self) -> List[dict]:
        with self._lock:
            return list(self._history)

    def attach(self, alerts, rule: str = "shard_imbalance") -> Callable:
        """Wire the controller to an AlertManager: the ``rule``'s
        transition INTO firing executes one rebalance on the evaluation
        thread (rebalances serialize on the controller lock; a failed
        migrate logs and leaves the alert to re-fire)."""

        def on_event(event) -> None:
            if event.rule != rule or event.to != "firing":
                return
            try:
                self.execute()
            except Exception:
                _LOG.exception(
                    "alert-triggered rebalance failed; table layout "
                    "unchanged — the %s alert will keep firing", rule
                )

        alerts.add_listener(on_event)
        return on_event
