"""Ring collectives over mesh axes (ppermute-based).

The reference's scale story is ZMQ point-to-point between nodes; the TPU
equivalent is neighbor exchange over the ICI ring. These helpers express
bandwidth-optimal ring schedules explicitly — useful when XLA's built-in
collectives aren't the shape you want (e.g. ring attention streaming K/V
blocks, or overlapping reduce with compute).

All functions must be called inside ``shard_map`` over the named axis.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def ring_next(x: jax.Array, axis: str, shift: int = 1) -> jax.Array:
    """Send to the next device on the ring; receive from the previous."""
    n = jax.lax.axis_size(axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return jax.lax.ppermute(x, axis, perm)


def ring_allreduce(x: jax.Array, axis: str) -> jax.Array:
    """Allreduce as an explicit ring schedule: rotate-and-add n−1 hops.

    Semantically ``psum`` (XLA lowers psum to the bandwidth-optimal
    reduce-scatter+all-gather ring on TPU already); this explicit form exists
    so schedules can interleave compute between hops (see ring_scan), and as
    the reference point tests check psum against.
    """
    n = jax.lax.axis_size(axis)
    total = x
    rotated = x
    for _ in range(n - 1):
        rotated = ring_next(rotated, axis)
        total = total + rotated
    return total


def ring_allgather(x: jax.Array, axis: str) -> jax.Array:
    """All-gather via n-1 neighbor hops; returns [n, *x.shape]."""
    n = jax.lax.axis_size(axis)
    pieces = [x]
    cur = x
    for _ in range(n - 1):
        cur = ring_next(cur, axis)
        pieces.append(cur)
    idx = jax.lax.axis_index(axis)
    stacked = jnp.stack(pieces)  # hop t holds device (idx - t)'s shard
    positions = (idx - jnp.arange(n)) % n
    return jnp.zeros_like(stacked).at[positions].set(stacked)


def ring_scan(
    x: jax.Array,
    axis: str,
    fn: Callable[[jax.Array, jax.Array, jax.Array], jax.Array],
    init: jax.Array,
) -> jax.Array:
    """Stream every device's shard past a local accumulator:
    ``acc = fn(acc, block, step)`` for each of the n ring steps — the
    skeleton under ring attention (block = a remote K/V shard)."""
    n = jax.lax.axis_size(axis)
    acc = init
    block = x
    for step in range(n):
        acc = fn(acc, block, jnp.int32(step))
        if step + 1 < n:
            block = ring_next(block, axis)
    return acc
