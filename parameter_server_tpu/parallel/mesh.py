"""Device mesh construction — the TPU replacement for node groups.

The reference organizes nodes into groups (``src/system/executor.h``:
kServerGroup/kWorkerGroup/kCompGroup) connected by ZMQ. Here those roles are
axes of a ``jax.sharding.Mesh``:

- ``data`` axis ≙ kWorkerGroup — examples are sharded along it; gradient
  aggregation is a psum/reduce_scatter across it (rides ICI).
- ``server`` axis ≙ kServerGroup — parameter tables are sharded along it by
  contiguous key range, like the reference's server key ranges
  (``Range<Key>::EvenDivide`` in manager.cc).

A chip may sit on both axes (2-D mesh): that's the common TPU layout where
every chip holds a parameter shard *and* computes gradients, unlike the
reference where workers and servers are disjoint processes.
"""

from __future__ import annotations

import logging
import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SERVER_AXIS = "server"


def honor_jax_platforms() -> None:
    """Apply the JAX_PLATFORMS env var via jax.config BEFORE backend
    init: an accelerator plugin's programmatic platform selection beats
    the env var alone, so ``JAX_PLATFORMS=cpu`` silently loses without
    this. The single home of the dance (Postoffice.start, benchmarks
    CLI, and bench.py's device probe all call it)."""
    import os

    if os.environ.get("JAX_PLATFORMS"):
        try:
            jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
        except RuntimeError:
            logging.getLogger(__name__).warning(
                "JAX backend already initialized; JAX_PLATFORMS=%s NOT "
                "applied — call honor_jax_platforms() before any jax use",
                os.environ["JAX_PLATFORMS"],
            )


def make_mesh(
    num_data: Optional[int] = None,
    num_server: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a ``(data, server)`` mesh over available devices.

    Defaults to all devices on the data axis (pure data parallel with
    replicated-then-sharded tables handled by NamedSharding specs).
    """
    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    log = logging.getLogger(__name__)
    if num_data is None:
        # auto-shape must factor the FULL device count: the old
        # ``n // num_server`` rounding made num_server=3 on 8 devices a
        # 2x3 mesh with 2 chips idle. When the requested server count
        # does not divide n, step it down to the largest divisor of n
        # that still fits — 8 devices never run 6-wide.
        num_server = max(1, min(int(num_server), n))
        if n % num_server != 0:
            adjusted = next(
                d for d in range(num_server, 0, -1) if n % d == 0
            )
            log.warning(
                "auto-shape: %d server shards do not divide %d devices; "
                "using %d server shards (largest divisor <= requested) "
                "so no chip idles",
                num_server, n, adjusted,
            )
            num_server = adjusted
        num_data = n // num_server
        log.info(
            "auto-shaped mesh %dx%d (data x server) over %d devices, 0 idle",
            num_data, num_server, n,
        )
    need = num_data * num_server
    if need > n:
        raise ValueError(f"mesh {num_data}x{num_server} needs {need} > {n} devices")
    if need < n:
        log.warning(
            "mesh %dx%d leaves %d of %d devices idle",
            num_data, num_server, n - need, n,
        )
    # fewer nodes than devices is fine (ref script/local.sh runs any N/M on
    # one box): take a prefix of the device list
    arr = np.asarray(devs[:need]).reshape(num_data, num_server)
    return Mesh(arr, (DATA_AXIS, SERVER_AXIS))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """Parameter tables: sharded by key range over the server axis,
    replicated over data. Resolved through the mesh's (cached)
    declarative partitioner — parallel/partition.py owns the spec."""
    from . import partition  # deferred: partition imports our axis names

    return partition.for_mesh(mesh).table_sharding()


def init_sharded(init_fn, mesh: Mesh, axis: str = SERVER_AXIS):
    """Materialize ``init_fn()``'s pytree DIRECTLY into its sharded
    layout: every leaf with rank >= 1 is row-sharded over ``axis``
    (trailing dims replicated), scalars replicated.

    The point is peak memory and the host link: building a leaf whole
    on the default device and then device_put-resharding transiently
    doubles its HBM footprint (that pushed a 2^30-slot, 8.6 GB FTRL
    table into RESOURCE_EXHAUSTED on a 16 GB chip), and a host-side
    init would push the whole table through the host<->device link
    (~23 MB/s through the tunnel). jit + out_shardings writes zeros/
    random values straight into the sharded buffers; on-device PRNG
    (jax.random.*) inside ``init_fn`` stays device-resident too."""
    from . import partition

    shapes = jax.eval_shape(init_fn)
    shardings = jax.tree.map(
        lambda s: NamedSharding(
            mesh, partition.fit_spec(P(axis), len(s.shape))
        ),
        shapes,
    )
    with mesh:
        return jax.jit(init_fn, out_shardings=shardings)()


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Example batches: sharded over the data axis, replicated over
    server (spec owned by parallel/partition.py)."""
    from . import partition

    return partition.for_mesh(mesh).batch_sharding()


def replicated(mesh: Mesh) -> NamedSharding:
    from . import partition

    return partition.for_mesh(mesh).replicated()


def num_servers(mesh: Mesh) -> int:
    return mesh.shape[SERVER_AXIS]


def num_workers(mesh: Mesh) -> int:
    return mesh.shape[DATA_AXIS]


def force_host_mesh(n: int = 8) -> None:
    """Test helper: must run before jax initializes. Forces an n-device CPU
    platform so multi-chip sharding logic is exercised without TPUs."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
