"""Multi-host (multi-process) support — the DCN side of the fabric.

The reference scales across machines with ZMQ sockets bootstrapped by a
scheduler node (``src/system/van.cc Van::Connect``; launched by
``script/local.sh`` / ``mpi_node.sh``). The TPU-native equivalent is one
JAX process per host joined through ``jax.distributed`` (gRPC coordination
service = the scheduler rendezvous), after which every process sees the
GLOBAL device list and a single ``Mesh`` spans all hosts — collectives
ride ICI within a slice and DCN across slices, chosen by XLA from the mesh
axis layout.

What this module adds on top of ``jax.distributed.initialize``:

- :func:`initialize` — env-driven bootstrap (PS_COORDINATOR_ADDRESS /
  PS_NUM_PROCESSES / PS_PROCESS_ID, the analog of the reference's
  scheduler node string in ``env.cc``), with the CPU cross-process
  collective backend (gloo) configured and clear errors for the
  backend-already-initialized trap.
- :func:`global_from_local` — assemble a process-local batch pytree into
  global device arrays sharded over the mesh's data axis
  (``jax.make_array_from_process_local_data``): each host feeds its own
  examples, the SPMD step sees one global batch. This is the reference's
  "every worker reads its own file partition" (DataAssigner) made
  explicit.
- :func:`local_data_shards` — how many data-axis rows this process owns
  (its share of the worker group).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import mesh as meshlib

_initialized = False


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-process rendezvous. Returns True when running
    multi-process, False for plain single-process use.

    Args default from the environment (set by ``script/local.sh`` or the
    cluster launcher): ``PS_COORDINATOR_ADDRESS`` (host:port of process
    0's coordination service — the reference's scheduler node),
    ``PS_NUM_PROCESSES``, ``PS_PROCESS_ID``.

    Must run before the first JAX computation. If another component
    already initialized the backend (e.g. an accelerator plugin loaded at
    interpreter start), joining is impossible — we raise with the fix
    rather than silently degrading to process_count()==1.
    """
    global _initialized
    addr = coordinator_address or os.environ.get("PS_COORDINATOR_ADDRESS")
    if not addr:
        return False
    if _initialized:
        return True
    n = int(num_processes or os.environ.get("PS_NUM_PROCESSES", "1"))
    pid = int(process_id if process_id is not None else os.environ.get("PS_PROCESS_ID", "0"))
    if n <= 1:
        return False
    # CPU hosts talk gloo for cross-process collectives; set before the
    # backend spins up or psum silently stays process-local.
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older jax: option absent
            pass
    jax.distributed.initialize(
        coordinator_address=addr, num_processes=n, process_id=pid
    )
    if jax.process_count() != n:
        raise RuntimeError(
            f"jax.distributed joined {jax.process_count()} processes, expected "
            f"{n}. A backend was initialized before the rendezvous — on this "
            "image the axon TPU plugin registers at interpreter start; launch "
            "with PALLAS_AXON_POOL_IPS unset (and JAX_PLATFORMS=cpu) for "
            "multi-process CPU runs, or initialize before any jax use."
        )
    _initialized = True
    return True


def is_multiprocess() -> bool:
    return jax.process_count() > 1


# -- control-plane byte transport (ref van.cc ZMQ send/recv over DCN) --
#
# Host-to-host Messages ride the jax.distributed coordination service's
# key-value store (the same gRPC channel that bootstrapped the cluster —
# the reference reuses its scheduler connection for control traffic the
# same way). This is for CONTROL-plane frames: workload grants, progress
# reports, filtered parameter messages in tests; bulk tensor traffic
# belongs to XLA collectives over ICI/DCN, never here.


def _kv_client():
    from jax._src import distributed as _dist

    c = _dist.global_state.client
    if c is None:
        raise RuntimeError(
            "no jax.distributed client — control-plane messaging needs a "
            "multi-process rendezvous (PS_COORDINATOR_ADDRESS et al.)"
        )
    return c


def post_bytes(tag: str, blob: bytes) -> None:
    """Publish one control-plane frame under a UNIQUE tag (the store is
    write-once per key: include sender/seq in the tag, e.g. "w0/3")."""
    _kv_client().key_value_set_bytes(f"psmsg/{tag}", blob)


def fetch_bytes(tag: str, timeout_ms: int = 120_000) -> bytes:
    """Block until the frame tagged ``tag`` is published, return it."""
    return _kv_client().blocking_key_value_get_bytes(
        f"psmsg/{tag}", timeout_ms
    )


def local_data_shards(mesh: Mesh) -> int:
    """Number of data-axis rows whose devices belong to this process.

    A data row must be WHOLLY owned by one process: the batch is sharded
    P(data) and replicated over the server axis, and
    ``make_array_from_process_local_data`` has no way to check that two
    processes feeding the same row agree — split ownership would let
    divergent per-host batches masquerade as one global row (silent
    corruption). We raise instead; pick num_server / devices-per-host so
    each host owns whole rows (e.g. num_server ≤ local device count and
    divides it).
    """
    this = jax.process_index()
    rows = 0
    axes = dict(zip(mesh.axis_names, range(len(mesh.axis_names))))
    arr = np.asarray(mesh.devices)
    if arr.ndim == 1:
        arr = arr[:, None]
    data_dim = axes.get(meshlib.DATA_AXIS, 0)
    for r in range(arr.shape[data_dim]):
        row = arr[r] if data_dim == 0 else arr[:, r]
        owners = {d.process_index for d in np.ravel(row)}
        if this in owners:
            if len(owners) > 1:
                raise ValueError(
                    f"data row {r} spans processes {sorted(owners)}; each "
                    "data-axis row must be wholly owned by one process — "
                    "choose num_server to divide the per-host device count"
                )
            rows += 1
    if rows == 0:
        raise ValueError(
            f"process {this} owns no data-axis rows of mesh {dict(mesh.shape)} "
            "(its devices were left idle by the mesh layout); every process "
            "must own at least one row — grow num_data or shrink the job"
        )
    return rows


def global_from_local(mesh: Mesh, tree, axis_name: str = None, axis_dim: int = 0):
    """Assemble per-process host arrays into global jax.Arrays sharded
    over the data axis. Single-process: plain device_put.

    ``axis_dim`` selects which leaf dimension carries the data shards —
    0 for per-minibatch trees ([D_local, ...]), 1 for scan superbatches
    ([T, D_local, ...]); that dim grows from this process's local shard
    count to the full data axis.
    """
    axis = axis_name or meshlib.DATA_AXIS
    if not is_multiprocess():
        return jax.device_put(tree)
    d_global = mesh.shape[axis]

    def put(leaf):
        if leaf is None:
            return None
        leaf = np.asarray(leaf)
        spec = [None] * leaf.ndim
        spec[axis_dim] = axis
        sharding = NamedSharding(mesh, P(*spec))
        global_shape = tuple(
            d_global if i == axis_dim else s for i, s in enumerate(leaf.shape)
        )
        return jax.make_array_from_process_local_data(sharding, leaf, global_shape)

    return jax.tree.map(put, tree, is_leaf=lambda x: x is None)
