"""Manager: customer registry and node lifecycle.

Counterpart of ``src/system/manager.{h,cc}``: tracks customers by id,
assigns fresh customer ids (ref ``NextCustomerID``), records node roles and
key ranges, and coordinates orderly shutdown. Node join/leave on TPU is mesh
(re)construction — elastic resize hooks re-shard tables via
``parameter.replica`` checkpoints rather than live key-range migration.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..utils.range import Range


class Node:
    """A logical node (ref proto/node.proto): role + key range."""

    SCHEDULER, SERVER, WORKER = "scheduler", "server", "worker"

    def __init__(self, role: str, rank: int, key_range: Optional[Range] = None):
        self.role = role
        self.rank = rank
        self.key_range = key_range if key_range is not None else Range.all()
        # H=scheduler(head), S=server, W=worker — distinct prefixes (the
        # reference's van.cc uses "H" for the scheduler node id too)
        prefix = {"scheduler": "H", "server": "S", "worker": "W"}[role]
        self.id = f"{prefix}{rank}"

    def __repr__(self) -> str:
        return f"Node({self.id}, keys={self.key_range})"


class Manager:
    def __init__(self) -> None:
        self._customers: Dict[int, object] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self.nodes: List[Node] = []

    def next_customer_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def add_customer(self, customer) -> None:
        with self._lock:
            if customer.id in self._customers:
                raise ValueError(f"customer id {customer.id} already exists")
            self._customers[customer.id] = customer

    def remove_customer(self, cid: int) -> None:
        with self._lock:
            self._customers.pop(cid, None)

    def get_customer(self, cid: int):
        with self._lock:
            return self._customers.get(cid)

    def find_customer_by_name(self, name: str):
        with self._lock:
            for c in self._customers.values():
                if getattr(c, "name", None) == name:
                    return c
        return None

    def init_nodes(self, num_servers: int, num_workers: int, key_space: Range) -> None:
        """Assign server key ranges by even division (ref manager.cc
        NodeIDGenerator / Range::EvenDivide over servers)."""
        self.nodes = [Node(Node.SCHEDULER, 0)]
        for i in range(num_servers):
            self.nodes.append(Node(Node.SERVER, i, key_space.even_divide(num_servers, i)))
        for i in range(num_workers):
            self.nodes.append(Node(Node.WORKER, i))

    def stop(self) -> None:
        with self._lock:
            self._customers.clear()
