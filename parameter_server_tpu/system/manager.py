"""Manager: customer registry and node lifecycle.

Counterpart of ``src/system/manager.{h,cc}``: tracks customers by id,
assigns fresh customer ids (ref ``NextCustomerID``), records node roles and
key ranges, broadcasts node add/remove events to subscribers (ref
``AddNode``'s NodeChange broadcast / ``NodeDisconnected``), and coordinates
orderly shutdown. Node join/leave on TPU is mesh (re)construction: the
``system.elastic.ElasticCoordinator`` performs the live key-range
migration (device->host->device reshard, no checkpoint files) and drives
this registry's events.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..utils.range import Range


class Node:
    """A logical node (ref proto/node.proto): role + key range."""

    SCHEDULER, SERVER, WORKER = "scheduler", "server", "worker"

    def __init__(self, role: str, rank: int, key_range: Optional[Range] = None):
        self.role = role
        self.rank = rank
        self.key_range = key_range if key_range is not None else Range.all()
        # H=scheduler(head), S=server, W=worker — distinct prefixes (the
        # reference's van.cc uses "H" for the scheduler node id too)
        prefix = {"scheduler": "H", "server": "S", "worker": "W"}[role]
        self.id = f"{prefix}{rank}"

    def __repr__(self) -> str:
        return f"Node({self.id}, keys={self.key_range})"


class Manager:
    def __init__(self) -> None:
        self._customers: Dict[int, object] = {}
        self._next_id = 0
        self._lock = threading.Lock()
        self.nodes: List[Node] = []
        # (event, node) listeners; event in {"add", "remove"} (ref
        # manager.cc NodeChange broadcast to every connected node)
        self._node_listeners: List = []

    def subscribe_nodes(self, cb) -> None:
        """Register a callback for node add/remove events (idempotent —
        elastic resizes re-subscribe surviving listeners)."""
        if cb not in self._node_listeners:
            self._node_listeners.append(cb)

    def broadcast(self, event: str, node: Node) -> None:
        """Fan a membership event out to subscribers (ref manager.cc
        NodeChange broadcast). ``event`` in {"add", "remove"}."""
        for cb in list(self._node_listeners):
            cb(event, node)

    def add_node(self, node: Node) -> None:
        """Record a joined node and broadcast (ref manager.cc AddNode)."""
        with self._lock:
            self.nodes.append(node)
        self.broadcast("add", node)

    def remove_node(self, node_id: str) -> Optional[Node]:
        """Drop a node and broadcast (ref manager.cc NodeDisconnected)."""
        with self._lock:
            for i, n in enumerate(self.nodes):
                if n.id == node_id:
                    dead = self.nodes.pop(i)
                    break
            else:
                return None
        self.broadcast("remove", dead)
        return dead

    def next_customer_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    def add_customer(self, customer) -> None:
        with self._lock:
            if customer.id in self._customers:
                raise ValueError(f"customer id {customer.id} already exists")
            self._customers[customer.id] = customer

    def remove_customer(self, cid: int) -> None:
        with self._lock:
            self._customers.pop(cid, None)

    def get_customer(self, cid: int):
        with self._lock:
            return self._customers.get(cid)

    def find_customer_by_name(self, name: str):
        with self._lock:
            for c in self._customers.values():
                if getattr(c, "name", None) == name:
                    return c
        return None

    def init_nodes(self, num_servers: int, num_workers: int, key_space: Range) -> None:
        """Assign server key ranges by even division (ref manager.cc
        NodeIDGenerator / Range::EvenDivide over servers)."""
        self.nodes = [Node(Node.SCHEDULER, 0)]
        for i in range(num_servers):
            self.nodes.append(Node(Node.SERVER, i, key_space.even_divide(num_servers, i)))
        for i in range(num_workers):
            self.nodes.append(Node(Node.WORKER, i))

    def stop(self) -> None:
        with self._lock:
            self._customers.clear()
