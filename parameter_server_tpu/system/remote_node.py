"""Per-peer state: filter chains and traffic counters.

Counterpart of ``src/system/remote_node.{h,cc}``: the reference keeps one
RemoteNode per (customer, peer) holding the stateful filter instances
(key caches, fixed-point ranges) and byte counters; Van::Send/Recv look the
chain up per peer so caches don't leak across peers. Same structure here
for the host control plane.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

from ..filter.base import FilterChain
from .message import FilterSpec, Message


class RemoteNode:
    def __init__(self, node_id: str):
        self.node_id = node_id
        self.chain = FilterChain()
        self.sent_bytes = 0
        self.recv_bytes = 0
        # serialized frame sizes — the actual on-the-wire counters the
        # reference keeps per peer (remote_node.cc sent_bytes_)
        self.wire_sent_bytes = 0
        self.wire_recv_bytes = 0

    def encode(self, msg: Message, specs: Optional[Sequence[FilterSpec]] = None) -> Message:
        out = self.chain.encode(msg, specs)
        self.sent_bytes += sum(v.nbytes for v in out.values)
        return out

    def decode(self, msg: Message, specs: Optional[Sequence[FilterSpec]] = None) -> Message:
        self.recv_bytes += sum(v.nbytes for v in msg.values)
        return self.chain.decode(msg, specs)

    def to_wire(self, msg: Message, specs: Optional[Sequence[FilterSpec]] = None) -> bytes:
        """Filter-encode then serialize — the full per-peer send path
        (ref van.cc Send: RemoteNode filters, then the ZMQ frame)."""
        blob = self.encode(msg, specs).to_bytes()
        self.wire_sent_bytes += len(blob)
        return blob

    def from_wire(self, blob: bytes) -> Message:
        """Deserialize then filter-decode (ref van.cc Recv)."""
        self.wire_recv_bytes += len(blob)
        return self.decode(Message.from_bytes(blob))


class RemoteNodeTable:
    """node_id → RemoteNode (ref Executor's nodes_ map)."""

    def __init__(self) -> None:
        self._nodes: Dict[str, RemoteNode] = {}

    def get(self, node_id: str) -> RemoteNode:
        if node_id not in self._nodes:
            self._nodes[node_id] = RemoteNode(node_id)
        return self._nodes[node_id]

    def nodes(self) -> list:
        """All per-peer endpoints (for counter aggregation/diagnostics)."""
        return list(self._nodes.values())

    def remove(self, node_id: str) -> None:
        self._nodes.pop(node_id, None)

    def __len__(self) -> int:
        return len(self._nodes)
