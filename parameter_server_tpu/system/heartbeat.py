"""Heartbeats and failure detection.

Counterpart of ``src/system/heartbeat_info.{h,cc}``: each node periodically
reports host metrics (cpu, memory, traffic, busy time); the scheduler's
collector marks nodes dead when reports stop arriving — that's the failure
detection signal the manager uses to trigger workload restore
(WorkloadPool.restore) and replica recovery.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from ..utils import resource_usage
from . import faults


@dataclasses.dataclass
class HeartbeatReport:
    """ref proto/heartbeat.proto HeartbeatReport fields we can source."""

    hostname: str = ""
    seconds_since_epoch: float = 0.0
    total_time_milli: float = 0.0
    busy_time_milli: float = 0.0
    net_in_mb: float = 0.0
    net_out_mb: float = 0.0
    process_rss_mb: float = 0.0
    process_virt_mb: float = 0.0
    process_cpu_usage: float = 0.0
    host_cpu_usage: float = 0.0


class HeartbeatInfo:
    """Per-node metrics sampler (busy timer + /proc counters)."""

    def __init__(self, hostname: str = "localhost"):
        self.hostname = hostname
        self._busy_ms = 0.0  # guarded-by: _lock
        self._busy_start: Optional[float] = None  # guarded-by: _lock
        self._start = time.time()
        self._in_bytes = 0  # guarded-by: _lock
        self._out_bytes = 0  # guarded-by: _lock
        # lifetime totals: ``get()`` drains the per-report deltas above
        # (the dashboard's in(MB)/out(MB) are per-interval), so tests and
        # telemetry snapshots need a counter that never resets
        self._total_in_bytes = 0  # guarded-by: _lock
        self._total_out_bytes = 0  # guarded-by: _lock
        self._total_busy_ms = 0.0  # guarded-by: _lock
        self._last = resource_usage.sample()  # guarded-by: _lock
        self._lock = threading.Lock()

    def start_timer(self) -> None:
        with self._lock:
            self._busy_start = time.perf_counter()

    def stop_timer(self) -> None:
        with self._lock:
            if self._busy_start is not None:
                delta = (time.perf_counter() - self._busy_start) * 1e3
                self._busy_ms += delta
                self._total_busy_ms += delta
                self._busy_start = None

    def increase_in_bytes(self, delta: int) -> None:
        with self._lock:
            self._in_bytes += delta
            self._total_in_bytes += delta

    def increase_out_bytes(self, delta: int) -> None:
        with self._lock:
            self._out_bytes += delta
            self._total_out_bytes += delta

    @property
    def total_in_bytes(self) -> int:
        with self._lock:
            return self._total_in_bytes

    @property
    def total_out_bytes(self) -> int:
        with self._lock:
            return self._total_out_bytes

    @property
    def total_busy_ms(self) -> float:
        """Lifetime busy-timer milliseconds — ``get()`` drains the
        per-report delta, so the cluster metrics plane's monotone
        ps_node_busy_seconds_total counter needs this."""
        with self._lock:
            return self._total_busy_ms

    @property
    def uptime_s(self) -> float:
        return time.time() - self._start

    def get(self) -> HeartbeatReport:
        # The whole sample-and-diff runs under the lock (pslint
        # guarded-access): ``_last`` was previously read and replaced
        # OUTSIDE it, so two reporter threads could rate the same
        # window twice — or write an OLDER sample over a newer one,
        # making the next dt negative and the cpu rates garbage.
        # Sampling inside the lock serializes reporters, so successive
        # reports tile the timeline exactly once. sample() is two tiny
        # /proc reads; heartbeat cadence is seconds — contention is nil.
        with self._lock:
            cur = resource_usage.sample()
            busy = self._busy_ms
            self._busy_ms = 0.0
            in_b, self._in_bytes = self._in_bytes, 0
            out_b, self._out_bytes = self._out_bytes, 0
            last, self._last = self._last, cur
        dt = max(1e-9, cur.timestamp - last.timestamp)
        proc_cpu = (cur.cpu_seconds - last.cpu_seconds) / dt
        host_cpu = (
            (cur.host_total_cpu_seconds - last.host_total_cpu_seconds) / dt
        )
        return HeartbeatReport(
            hostname=self.hostname,
            seconds_since_epoch=cur.timestamp,
            total_time_milli=(cur.timestamp - self._start) * 1e3,
            busy_time_milli=busy,
            net_in_mb=in_b / 1e6,
            net_out_mb=out_b / 1e6,
            process_rss_mb=cur.rss_mb,
            process_virt_mb=cur.vm_mb,
            process_cpu_usage=proc_cpu,
            host_cpu_usage=host_cpu,
        )


class HeartbeatCollector:
    """Scheduler-side liveness tracking (manager.cc heartbeat handling)."""

    def __init__(self, timeout: float = 10.0):
        self.timeout = timeout
        self._reports: Dict[str, HeartbeatReport] = {}  # guarded-by: _lock
        self._last_seen: Dict[str, float] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def report(self, node_id: str, hb: HeartbeatReport) -> None:
        # fault point (doc/ROBUSTNESS.md): an armed "silence" (matched
        # on the node id) drops the report BEFORE it refreshes
        # last-seen — to the collector the node simply stops reporting,
        # which is exactly what a crashed shard looks like. The node
        # itself keeps running; the recovery drill kills shards this way.
        if faults.check("heartbeat.report", detail=node_id) is not None:
            return
        with self._lock:
            self._reports[node_id] = hb
            self._last_seen[node_id] = time.time()

    def dead_nodes(self, now: Optional[float] = None) -> List[str]:
        """Nodes whose last report is older than the timeout."""
        now = now if now is not None else time.time()
        with self._lock:
            return [
                nid
                for nid, seen in self._last_seen.items()
                if now - seen > self.timeout
            ]

    def last_seen(self, node_id: str) -> Optional[float]:
        """Wall time of the node's newest *landed* report, or None.

        The metrics plane uses the before/after delta of this to learn
        whether a report it just submitted actually landed — an armed
        ``heartbeat.report`` silence drops reports inside
        :meth:`report`, and the caller must not then feed the cluster
        aggregator on the silenced node's behalf (a crashed node stops
        reporting *everything*)."""
        with self._lock:
            return self._last_seen.get(node_id)

    def forget(self, node_id: str) -> None:
        """Drop a decommissioned node from liveness tracking (elastic
        shrink: a node removed on purpose must not later 'die')."""
        with self._lock:
            self._reports.pop(node_id, None)
            self._last_seen.pop(node_id, None)

    def touch_all(self) -> None:
        """Refresh every tracked node's last-seen time: a deliberate
        cluster-wide pause (elastic resize, checkpoint restore) is not a
        death — without this, a pause longer than the timeout would make
        the next check declare every survivor dead at once."""
        now = time.time()
        with self._lock:
            for nid in self._last_seen:
                self._last_seen[nid] = now

    def reports(self) -> Dict[str, HeartbeatReport]:
        with self._lock:
            return dict(self._reports)
