"""Heartbeats and failure detection.

Counterpart of ``src/system/heartbeat_info.{h,cc}``: each node periodically
reports host metrics (cpu, memory, traffic, busy time); the scheduler's
collector marks nodes dead when reports stop arriving — that's the failure
detection signal the manager uses to trigger workload restore
(WorkloadPool.restore) and replica recovery.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from ..utils import resource_usage
from . import faults


@dataclasses.dataclass
class HeartbeatReport:
    """ref proto/heartbeat.proto HeartbeatReport fields we can source."""

    hostname: str = ""
    seconds_since_epoch: float = 0.0
    total_time_milli: float = 0.0
    busy_time_milli: float = 0.0
    net_in_mb: float = 0.0
    net_out_mb: float = 0.0
    process_rss_mb: float = 0.0
    process_virt_mb: float = 0.0
    process_cpu_usage: float = 0.0
    host_cpu_usage: float = 0.0


class HeartbeatInfo:
    """Per-node metrics sampler (busy timer + /proc counters)."""

    def __init__(self, hostname: str = "localhost"):
        self.hostname = hostname
        self._busy_ms = 0.0  # guarded-by: _lock
        self._busy_start: Optional[float] = None  # guarded-by: _lock
        self._start = time.time()
        self._in_bytes = 0  # guarded-by: _lock
        self._out_bytes = 0  # guarded-by: _lock
        # lifetime totals: ``get()`` drains the per-report deltas above
        # (the dashboard's in(MB)/out(MB) are per-interval), so tests and
        # telemetry snapshots need a counter that never resets
        self._total_in_bytes = 0  # guarded-by: _lock
        self._total_out_bytes = 0  # guarded-by: _lock
        self._total_busy_ms = 0.0  # guarded-by: _lock
        self._last = resource_usage.sample()  # guarded-by: _lock
        self._lock = threading.Lock()

    def start_timer(self) -> None:
        with self._lock:
            self._busy_start = time.perf_counter()

    def stop_timer(self) -> None:
        with self._lock:
            if self._busy_start is not None:
                delta = (time.perf_counter() - self._busy_start) * 1e3
                self._busy_ms += delta
                self._total_busy_ms += delta
                self._busy_start = None

    def increase_in_bytes(self, delta: int) -> None:
        with self._lock:
            self._in_bytes += delta
            self._total_in_bytes += delta

    def increase_out_bytes(self, delta: int) -> None:
        with self._lock:
            self._out_bytes += delta
            self._total_out_bytes += delta

    @property
    def total_in_bytes(self) -> int:
        with self._lock:
            return self._total_in_bytes

    @property
    def total_out_bytes(self) -> int:
        with self._lock:
            return self._total_out_bytes

    @property
    def total_busy_ms(self) -> float:
        """Lifetime busy-timer milliseconds — ``get()`` drains the
        per-report delta, so the cluster metrics plane's monotone
        ps_node_busy_seconds_total counter needs this."""
        with self._lock:
            return self._total_busy_ms

    @property
    def uptime_s(self) -> float:
        return time.time() - self._start

    def get(self) -> HeartbeatReport:
        # The whole sample-and-diff runs under the lock (pslint
        # guarded-access): ``_last`` was previously read and replaced
        # OUTSIDE it, so two reporter threads could rate the same
        # window twice — or write an OLDER sample over a newer one,
        # making the next dt negative and the cpu rates garbage.
        # Sampling inside the lock serializes reporters, so successive
        # reports tile the timeline exactly once. sample() is two tiny
        # /proc reads; heartbeat cadence is seconds — contention is nil.
        with self._lock:
            cur = resource_usage.sample()
            busy = self._busy_ms
            self._busy_ms = 0.0
            in_b, self._in_bytes = self._in_bytes, 0
            out_b, self._out_bytes = self._out_bytes, 0
            last, self._last = self._last, cur
        dt = max(1e-9, cur.timestamp - last.timestamp)
        proc_cpu = (cur.cpu_seconds - last.cpu_seconds) / dt
        host_cpu = (
            (cur.host_total_cpu_seconds - last.host_total_cpu_seconds) / dt
        )
        return HeartbeatReport(
            hostname=self.hostname,
            seconds_since_epoch=cur.timestamp,
            total_time_milli=(cur.timestamp - self._start) * 1e3,
            busy_time_milli=busy,
            net_in_mb=in_b / 1e6,
            net_out_mb=out_b / 1e6,
            process_rss_mb=cur.rss_mb,
            process_virt_mb=cur.vm_mb,
            process_cpu_usage=proc_cpu,
            host_cpu_usage=host_cpu,
        )


class ClockSync:
    """Per-peer clock-offset estimation from metric-report exchanges.

    A merged multi-node timeline (telemetry/timeline.merge_node_events)
    is only readable if every node's wall clocks are aligned; real
    hosts drift. Each metric report that crosses the Van carries its
    send wall time (``Task.trace["t_send"]``, the sender's clock), the
    observer records its own receive time, and the CALLER supplies its
    best estimate of the one-way delivery delay between the two stamps
    (``delay_s``: the measured transfer duration for the in-process
    loopback leg — the whole measured window IS the delivery — or
    rtt/2 for a genuine request/response round trip). The sample is::

        offset = t_recv - delay_s - t_send    # node clock + offset
                                              #   ≈ observer clock

    and the retained estimate per peer is the sample with the SMALLEST
    observed delay (queueing inflates delay; the min-delay exchange
    bounds the error by that delay — the Cristian bound, disclosed
    alongside the estimate). In today's single-process runs offsets
    measure ~0 EVEN under injected ``van.transfer`` delay faults —
    the delay is measured, not assumed — which is the machinery's
    sanity check.
    """

    def __init__(self, keep_best: bool = True):
        self.keep_best = keep_best
        # node -> (offset_s, delay_s, n_samples)
        self._est: Dict[str, tuple] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def observe(
        self, node_id: str, t_send: float, t_recv: float, delay_s: float
    ) -> None:
        """Fold one exchange in. Nonsensical samples (negative delay —
        a clock step mid-exchange) are dropped."""
        if delay_s < 0.0:
            return
        offset = t_recv - delay_s - t_send
        with self._lock:
            cur = self._est.get(node_id)
            n = (cur[2] + 1) if cur else 1
            if cur is None or not self.keep_best or delay_s < cur[1]:
                self._est[node_id] = (offset, delay_s, n)
            else:
                self._est[node_id] = (cur[0], cur[1], n)

    def offset(self, node_id: str) -> Optional[float]:
        """Seconds to ADD to ``node_id``'s clock to land on this
        process's clock, or None before any exchange."""
        with self._lock:
            cur = self._est.get(node_id)
            return cur[0] if cur else None

    def offsets(self) -> Dict[str, float]:
        """node id -> best offset estimate (merge_node_events shape)."""
        with self._lock:
            return {n: est[0] for n, est in self._est.items()}

    def snapshot(self) -> Dict[str, dict]:
        """Diagnostic view: offset + the delivery delay that produced
        it + sample count per peer (the delay IS the error bound)."""
        with self._lock:
            return {
                n: {
                    "offset_s": round(est[0], 6),
                    "delay_s": round(est[1], 6),
                    "error_bound_s": round(est[1], 6),
                    "samples": est[2],
                }
                for n, est in sorted(self._est.items())
            }


class HeartbeatCollector:
    """Scheduler-side liveness tracking (manager.cc heartbeat handling)."""

    def __init__(self, timeout: float = 10.0):
        self.timeout = timeout
        self._reports: Dict[str, HeartbeatReport] = {}  # guarded-by: _lock
        self._last_seen: Dict[str, float] = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def report(self, node_id: str, hb: HeartbeatReport) -> None:
        # fault point (doc/ROBUSTNESS.md): an armed "silence" (matched
        # on the node id) drops the report BEFORE it refreshes
        # last-seen — to the collector the node simply stops reporting,
        # which is exactly what a crashed shard looks like. The node
        # itself keeps running; the recovery drill kills shards this way.
        if faults.check("heartbeat.report", detail=node_id) is not None:
            return
        with self._lock:
            self._reports[node_id] = hb
            self._last_seen[node_id] = time.time()

    def dead_nodes(self, now: Optional[float] = None) -> List[str]:
        """Nodes whose last report is older than the timeout."""
        now = now if now is not None else time.time()
        with self._lock:
            return [
                nid
                for nid, seen in self._last_seen.items()
                if now - seen > self.timeout
            ]

    def last_seen(self, node_id: str) -> Optional[float]:
        """Wall time of the node's newest *landed* report, or None.

        The metrics plane uses the before/after delta of this to learn
        whether a report it just submitted actually landed — an armed
        ``heartbeat.report`` silence drops reports inside
        :meth:`report`, and the caller must not then feed the cluster
        aggregator on the silenced node's behalf (a crashed node stops
        reporting *everything*)."""
        with self._lock:
            return self._last_seen.get(node_id)

    def forget(self, node_id: str) -> None:
        """Drop a decommissioned node from liveness tracking (elastic
        shrink: a node removed on purpose must not later 'die')."""
        with self._lock:
            self._reports.pop(node_id, None)
            self._last_seen.pop(node_id, None)

    def touch_all(self) -> None:
        """Refresh every tracked node's last-seen time: a deliberate
        cluster-wide pause (elastic resize, checkpoint restore) is not a
        death — without this, a pause longer than the timeout would make
        the next check declare every survivor dead at once."""
        now = time.time()
        with self._lock:
            for nid in self._last_seen:
                self._last_seen[nid] = now

    def reports(self) -> Dict[str, HeartbeatReport]:
        with self._lock:
            return dict(self._reports)
