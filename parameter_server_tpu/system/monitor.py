"""Progress monitoring (ref ``src/system/monitor.h``).

MonitorMaster collects typed progress reports from slavers and merges
them per node; MonitorSlaver pushes reports. The reference moves these
over messages on a timer (``monitor.h`` MonitorSlaver sends a
``Command::UPDATE`` task every second; the master merges on receipt);
this port keeps the direct-call path for single-process tests AND
offers the message-plane path: a slaver constructed ``over_van`` wraps
each report in a :class:`~parameter_server_tpu.system.message.Message`
(``Command.EVALUATE_PROGRESS``) and ships it through the Van's real
transfer path — filter chains, serialization, byte accounting and the
``van.transfer`` fault point included — and ``start_periodic`` is the
reference's reporting timer.

Progress payloads on the message path must be plain data (dicts /
lists / numbers / numpy arrays): the wire header rides the restricted
unpickler (``message._restricted_loads``), which rejects arbitrary
classes by design.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Generic, Optional, TypeVar

from .message import Command, Message, Task

_LOG = logging.getLogger(__name__)

P = TypeVar("P")


class MonitorMaster(Generic[P]):
    def __init__(self, merger: Optional[Callable[[P, P], None]] = None):
        self._progress: Dict[str, P] = {}  # guarded-by: _lock
        self._merger = merger
        self._printer: Optional[Callable[[float, Dict[str, P]], None]] = None
        self._interval = 1.0
        self._lock = threading.Lock()
        self._start = time.time()
        # guarded-by: _lock — maybe_print used to read-then-write this
        # OUTSIDE the lock: two reporter threads racing the check could
        # both pass it and print the same window twice (pslint
        # guarded-access; regression test in tests/test_system_aux.py)
        self._last_print = 0.0
        # guarded-by: _lock — redelivery idempotence: highest report
        # seq merged per node. The over_van path is at-least-once (a
        # dropped frame is retransmitted; a van `duplicate` fault
        # delivers one frame twice), and a re-merged progress delta
        # would double-count into cluster progress — so a seq at or
        # below the high-water mark is dropped, not merged.
        self._seq: Dict[str, int] = {}
        self._dup_dropped = 0  # guarded-by: _lock

    def set_data_merger(self, fn: Optional[Callable[[P, P], None]]) -> None:
        self._merger = fn

    def set_printer(self, fn: Callable[[float, Dict[str, P]], None], interval: float = 1.0) -> None:
        self._printer = fn
        self._interval = interval

    def report(
        self, node_id: str, progress: P, seq: Optional[int] = None
    ) -> bool:
        """Merge one report; returns False when the seq guard rejected
        it as a redelivery (``seq`` <= the node's high-water mark).
        Direct in-process callers pass no seq and merge unconditionally
        — exactly-once is their call discipline, not the wire's."""
        with self._lock:
            if seq is not None:
                if seq <= self._seq.get(node_id, -1):
                    self._dup_dropped += 1
                    return False
                self._seq[node_id] = seq
            cur = self._progress.get(node_id)
            if cur is None or self._merger is None:
                self._progress[node_id] = progress
            else:
                self._merger(progress, cur)
        self.maybe_print()
        return True

    def duplicates_dropped(self) -> int:
        """Reports the seq guard rejected (redelivery accounting)."""
        with self._lock:
            return self._dup_dropped

    def handle_message(self, msg: Message) -> bool:
        """Receiver side of the message-plane path: unwrap one slaver
        report (``task.payload = {"node": id, "progress": P, "seq":
        n}``) and merge it like a direct call — through the seq guard,
        because this path really does redeliver (van `duplicate`)."""
        payload = msg.task.payload or {}
        return self.report(
            payload["node"], payload["progress"], seq=payload.get("seq")
        )

    def maybe_print(self, force: bool = False) -> None:
        if self._printer is None:
            return
        now = time.time()
        # check-and-claim the print window atomically: the snapshot is
        # taken in the same critical section, the (potentially slow)
        # printer runs outside it
        with self._lock:
            if not force and now - self._last_print < self._interval:
                return
            self._last_print = now
            snapshot = dict(self._progress)
        self._printer(now - self._start, snapshot)

    def progress(self) -> Dict[str, P]:
        with self._lock:
            return dict(self._progress)


class MonitorSlaver(Generic[P]):
    """Node-side reporter.

    ``wire`` is the transport: None (default) calls the master
    directly — the single-process test path; a callable ships the
    wrapped Message (see :meth:`over_van`). ``start_periodic`` reports
    ``progress_fn()`` on a timer like the reference's monitor thread.
    """

    def __init__(
        self,
        master: Optional[MonitorMaster[P]],
        node_id: str,
        wire: Optional[Callable[[Message], None]] = None,
    ):
        self.master = master
        self.node_id = node_id
        self.wire = wire
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # guarded-by: _seq_lock — wire-path report sequence (the
        # master's redelivery guard keys on it); the periodic timer and
        # a manual report() may race, so the stamp is lock-claimed
        self._seq = 0
        self._seq_lock = threading.Lock()

    @classmethod
    def over_van(
        cls,
        master: MonitorMaster[P],
        node_id: str,
        van,
        master_id: str = "H0",
        max_attempts: int = 3,
    ) -> "MonitorSlaver[P]":
        """A slaver whose reports ride ``van.transfer`` between a fresh
        RemoteNode endpoint pair (node → scheduler), landing in
        ``master.handle_message`` — the reference's report-over-message
        flow inside one process.

        At-least-once hardening (PR 15): delivery happens at the
        receiving endpoint's DECODE (``from_wire``), so a van
        ``duplicate`` fault — one frame decoded twice — really does
        redeliver into the master (whose seq guard dedupes it), and a
        ``drop`` fault (FaultError before decode) is retransmitted up
        to ``max_attempts`` times with the SAME seq instead of losing
        the report. Exactly the failure semantics a real wire has, with
        the master idempotent under them (tests/test_system_aux.py).
        """
        from .remote_node import RemoteNode

        class _Delivering(RemoteNode):
            """Receiver endpoint that delivers each decoded report
            frame to the master — at-least-once means delivery count
            == decode count, not transfer-return count."""

            def from_wire(self, blob: bytes) -> Message:
                out = super().from_wire(blob)
                if out.task.cmd == Command.EVALUATE_PROGRESS:
                    master.handle_message(out)
                return out

        tx, rx = RemoteNode(master_id), _Delivering(node_id)

        def wire(msg: Message) -> None:
            from . import faults as faults_mod

            last: Optional[BaseException] = None
            for _ in range(max(1, max_attempts)):
                try:
                    van.transfer(tx, rx, msg)
                    return
                except faults_mod.FaultError as e:
                    # injected drop: the frame died before decode —
                    # retransmit the SAME message (same seq; a
                    # successful earlier delivery is impossible here,
                    # and a duplicated retransmit dedupes at the master)
                    last = e
            if last is not None:
                raise last

        return cls(master, node_id, wire=wire)

    def report(self, progress: P) -> None:
        if self.wire is not None:
            with self._seq_lock:
                self._seq += 1
                seq = self._seq
            self.wire(Message(
                task=Task(
                    cmd=Command.EVALUATE_PROGRESS,
                    payload={
                        "node": self.node_id,
                        "progress": progress,
                        "seq": seq,
                    },
                ),
                sender=self.node_id,
                recver="H0",
            ))
        elif self.master is not None:
            self.master.report(self.node_id, progress)

    # -- the reporting timer (ref monitor.h: slaver reports every sec) --

    def start_periodic(
        self, progress_fn: Callable[[], P], interval: float = 1.0
    ) -> None:
        if self._thread is not None:
            return
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(interval):
                try:
                    self.report(progress_fn())
                except Exception:  # noqa: BLE001 — a dropped frame (the
                    # van.transfer fault point) or transient wire error
                    # loses ONE report; the timer must survive to send
                    # the next, else the master's view silently freezes
                    _LOG.exception(
                        "monitor report from %s failed", self.node_id
                    )

        self._thread = threading.Thread(
            target=loop, daemon=True, name=f"monitor-{self.node_id}"
        )
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
