"""Progress monitoring (ref ``src/system/monitor.h``).

MonitorMaster collects typed progress reports from slavers and merges them
per node; MonitorSlaver pushes reports. The reference moves these over
messages on a timer; here slavers call the master directly (same process —
the scheduler is host-side), preserving the merge semantics and the
periodic display hook.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Generic, Optional, TypeVar

P = TypeVar("P")


class MonitorMaster(Generic[P]):
    def __init__(self, merger: Optional[Callable[[P, P], None]] = None):
        self._progress: Dict[str, P] = {}  # guarded-by: _lock
        self._merger = merger
        self._printer: Optional[Callable[[float, Dict[str, P]], None]] = None
        self._interval = 1.0
        self._lock = threading.Lock()
        self._start = time.time()
        self._last_print = 0.0

    def set_data_merger(self, fn: Callable[[P, P], None]) -> None:
        self._merger = fn

    def set_printer(self, fn: Callable[[float, Dict[str, P]], None], interval: float = 1.0) -> None:
        self._printer = fn
        self._interval = interval

    def report(self, node_id: str, progress: P) -> None:
        with self._lock:
            cur = self._progress.get(node_id)
            if cur is None or self._merger is None:
                self._progress[node_id] = progress
            else:
                self._merger(progress, cur)
        self.maybe_print()

    def maybe_print(self, force: bool = False) -> None:
        if self._printer is None:
            return
        now = time.time()
        if force or now - self._last_print >= self._interval:
            self._last_print = now
            with self._lock:
                snapshot = dict(self._progress)
            self._printer(now - self._start, snapshot)

    def progress(self) -> Dict[str, P]:
        with self._lock:
            return dict(self._progress)


class MonitorSlaver(Generic[P]):
    def __init__(self, master: Optional[MonitorMaster[P]], node_id: str):
        self.master = master
        self.node_id = node_id

    def report(self, progress: P) -> None:
        if self.master is not None:
            self.master.report(self.node_id, progress)
