"""Customer: base class of every shared object (apps, parameters).

Counterpart of ``src/system/customer.h``. A customer owns an executor
(timestamps + dependency tracking) and registers with the postoffice under a
unique id, exactly like the reference's ``Customer(id)`` +
``Postoffice::instance().manager().AddCustomer(this)``.
"""

from __future__ import annotations

import dataclasses

from typing import Any, Callable, Optional

from .executor import Executor
from .message import Message, Task


class Customer:
    def __init__(self, id: Optional[int] = None, name: str = ""):
        from .postoffice import Postoffice

        self.po = Postoffice.instance()
        self.id = self.po.manager.next_customer_id() if id is None else id
        self.name = name or f"customer_{self.id}"
        self.executor = Executor(name=self.name)
        self._last_response: Optional[Message] = None
        # per-peer filter chains + wire byte counters (ref executor.h
        # nodes_: every customer keeps its own RemoteNode per peer)
        from .remote_node import RemoteNodeTable

        self.remote_nodes = RemoteNodeTable()
        self.po.manager.add_customer(self)

    # -- communication (ref customer.h Submit/Wait/Reply) --

    def submit(
        self,
        step: Callable[[], Any],
        task: Optional[Task] = None,
        callback: Optional[Callable[[], None]] = None,
    ) -> int:
        return self.executor.submit(step, task, callback)

    def wait(self, timestamp: int) -> Any:
        return self.executor.wait(timestamp)

    def reply(self, request: Message, response: Optional[Message] = None) -> None:
        """Mark a request processed and deliver the response to its sender
        (host-side: invoke the paired customer's ProcessResponse)."""
        if response is None:
            response = Message()
        response.task.request = False
        response.task.time = request.task.time
        response.sender, response.recver = request.recver, request.sender
        request.replied = True  # ref executor.cc: system acks once per request
        self.executor.tracker.finish(request.task.time)
        target = self.po.manager.find_customer_by_name(request.sender)
        if target is not None:
            # responses ride the same per-peer filter chains and wire
            # framing as requests (ref remote_node.cc: filters apply on
            # every send AND recv — pull responses are the dominant
            # server->worker traffic). Peer keys mirror the request path,
            # so one RemoteNode per peer carries both directions. Encode
            # a copy: the chain mutates the message in place (values ->
            # compressed blobs, key stripped) and the caller keeps its
            # response object.
            wire_msg = dataclasses.replace(
                response,
                task=response.task.fresh_copy(),
                values=list(response.values),
                callback=None,
            )
            response = self.po.van.transfer(
                self.remote_nodes.get(response.recver),
                target.remote_nodes.get(response.sender),
                wire_msg,
            )
            target._last_response = response  # ref customer.h LastResponse()
            target.process_response(response)
        if request.callback is not None:
            request.callback()

    def last_response(self) -> Optional[Message]:
        """The most recent response delivered to me (ref customer.h
        LastResponse — valid inside a response callback)."""
        return self._last_response

    # -- user hooks (ref ProcessRequest/ProcessResponse) --

    def process_request(self, request: Message) -> None:
        pass

    def process_response(self, response: Message) -> None:
        pass

    def remove(self) -> None:
        self.po.manager.remove_customer(self.id)


class App(Customer):
    """Base application (ref customer.h App): ``run`` is executed by the
    main thread after construction."""

    def run(self) -> None:
        pass

    @staticmethod
    def create(conf: Any) -> "App":
        """Factory from a config object (ref App::Create in main.cc dispatch);
        apps register via apps/registry."""
        from ..apps.registry import create_app

        return create_app(conf)
