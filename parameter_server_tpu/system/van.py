"""Van: the transport layer, rebuilt on XLA collectives.

Counterpart of ``src/system/van.{h,cc}``. The reference moves bytes between
nodes with ZMQ sockets; on TPU the equivalent "wire" is the ICI/DCN fabric
driven by XLA collectives inside jitted programs. The Van therefore exposes:

- device placement (``put``) with the right NamedSharding — the analog of
  addressing a message to a node group;
- the collective primitives push/pull compile down to (psum, all_gather,
  reduce_scatter, ppermute) bound to mesh axes;
- host-side filter-chain encode/decode for control-plane messages (the
  reference applies filters in Van::Send/Recv via RemoteNode).

Multi-host bootstrap (the reference's scheduler rendezvous in
``Van::Connect``) maps to ``jax.distributed.initialize``; gated here because
this environment is single-host.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import mesh as meshlib
from ..telemetry import registry as telemetry_registry
from ..telemetry import spans as telemetry_spans
from . import faults
from .message import Message


class Van:
    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.placed_bytes = 0  # device placement volume (put_* below)
        # serialized host frames through transfer() — kept separate from
        # placement bytes so each counter means ONE thing (ref van.cc
        # send_bytes_/recv_bytes_ count wire frames)
        self.wire_sent_bytes = 0
        self.wire_recv_bytes = 0
        # registry mirrors of the counters above (telemetry spine): one
        # process-wide series each, shared with dashboard/bench snapshots
        self._tel = None
        if telemetry_registry.enabled():
            from ..telemetry.instruments import van_instruments

            self._tel = van_instruments(telemetry_registry.default_registry())
        # ident -> node id, for heartbeat traffic attribution: app names
        # resolve through the manager's customer table (a linear scan) —
        # cache positive resolutions so chatty RPC traffic pays it once
        # per peer, not per frame
        self._ident_nodes: dict = {}

    # -- placement (addressing) --

    def _count_placed(self, nbytes: int) -> None:
        self.placed_bytes += nbytes
        if self._tel is not None:
            self._tel["placed_bytes"].inc(nbytes)

    def put_table(self, arr) -> jax.Array:
        """Place a parameter table sharded by key range over servers."""
        out = jax.device_put(arr, meshlib.table_sharding(self.mesh))
        self._count_placed(arr.nbytes)
        return out

    def put_batch(self, arr) -> jax.Array:
        """Place a batch sharded over the data (worker) axis."""
        out = jax.device_put(arr, meshlib.batch_sharding(self.mesh))
        self._count_placed(arr.nbytes)
        return out

    def put_replicated(self, arr) -> jax.Array:
        out = jax.device_put(arr, meshlib.replicated(self.mesh))
        self._count_placed(arr.nbytes)
        return out

    # -- host wire (control plane) --

    def transfer(self, sender, recver, msg: Message) -> Message:
        """The full host wire path between two per-peer endpoints (ref
        van.cc Send then Recv): the sender's RemoteNode filter-encodes
        and serializes, the frame crosses the "wire" (loopback within a
        process, the jax.distributed KV transport across hosts), and the
        receiver's RemoteNode deserializes and decodes. Van keeps the
        process-level byte counters (ref Van send_bytes_/recv_bytes_);
        the per-peer counters live on the RemoteNodes.

        Every ps.py group RPC — request AND response — crosses here.

        Byte accounting is side-correct: sent bytes are counted at
        serialization, recv bytes only after ``from_wire`` actually ran
        on the receiving endpoint (measured as that endpoint's counter
        delta) — a decode failure, or a multi-host split where the
        receiving process does its own ``from_wire``, never inflates
        this process's recv counter with sender-side frame lengths.
        Both directions also feed the nodes' HeartbeatInfo so the
        dashboard reports true traffic.

        Trace context: the sending thread's active flow id (plus this
        process's node id and the send wall time) is stamped onto
        ``Task.trace`` before serialization — flow ids used to die
        right here, making a multi-node timeline unstitchable. The
        receiving side re-activates it (``spans.activate_trace``) so
        one batch/request is ONE flow across processes, and the leg
        itself is a ``van.transfer`` span (the ``network`` resource in
        telemetry/attribution.py). An explicitly pre-set trace is
        respected (re-sends keep their origin)."""
        if getattr(msg.task, "trace", None) is None:
            msg.task.trace = telemetry_spans.trace_context()
        blob = sender.to_wire(msg)
        sent = len(blob)
        with telemetry_spans.span(
            "van.transfer", sender=msg.sender, recver=msg.recver,
            bytes=sent,
        ):
            self.wire_sent_bytes += sent
            self._account(msg.sender, out_bytes=sent)
            # fault point (doc/ROBUSTNESS.md) — the wire between
            # serialize and deliver, where real networks fail. Placed
            # AFTER the send accounting so a dropped frame costs sender
            # bytes but never receiver bytes (the side-correct counting
            # contract above):
            #   drop      → FaultError; the RPC layer sees a lost frame
            #   delay     → the frame arrives late (delay_s)
            #   duplicate → at-least-once delivery: from_wire runs
            #               twice, probing receiver idempotence under
            #               redelivery
            fault = faults.check(
                "van.transfer", detail=f"{msg.sender}->{msg.recver}"
            )
            duplicate = False
            if fault is not None:
                if fault.delay_s:
                    import time as _time

                    _time.sleep(fault.delay_s)
                if fault.kind == "drop":
                    raise fault.make_error(
                        f"frame {msg.sender}->{msg.recver} dropped"
                    )
                duplicate = fault.kind == "duplicate"
            recv_before = recver.wire_recv_bytes
            if duplicate:
                recver.from_wire(blob)
            out = recver.from_wire(blob)
            recv = recver.wire_recv_bytes - recv_before
            self.wire_recv_bytes += recv
            self._account(msg.recver, in_bytes=recv)
            if self._tel is not None:
                self._tel["wire_sent_bytes"].inc(sent)
                self._tel["wire_recv_bytes"].inc(recv)
                self._tel["transfers"].inc()
        return out

    def _account(self, ident: str, in_bytes: int = 0, out_bytes: int = 0) -> None:
        """Feed a transfer's bytes into the node's HeartbeatInfo (ref
        heartbeat_info.cc: Van::Send/Recv bump the traffic counters the
        dashboard's in(MB)/out(MB) columns report). ``ident`` may be a
        node id ("W0") or a customer/app name — resolved best-effort;
        silently skipped before start_aux or for unregistered nodes."""
        if not ident or (not in_bytes and not out_bytes):
            return
        from .postoffice import Postoffice

        po = Postoffice._instance  # never create the singleton from here
        if po is None or po.aux is None:
            return
        info = po.aux.info(ident)
        if info is None:
            # app names differ from node ids (ps.py submits under the
            # customer name); map through the registered customer's node
            node_id = self._ident_nodes.get(ident)
            if node_id is None:
                cust = po.manager.find_customer_by_name(ident)
                node = getattr(cust, "node", None)
                if node is None:
                    return  # unresolved now; may register later — no
                    # negative caching
                node_id = self._ident_nodes[ident] = node.id
            info = po.aux.info(node_id)
            if info is None:
                return
        if in_bytes:
            info.increase_in_bytes(in_bytes)
        if out_bytes:
            info.increase_out_bytes(out_bytes)

    def send(self, msg: Message, filters: Optional[Sequence] = None) -> Message:
        from ..filter.base import encode_chain

        return encode_chain(msg, filters or msg.task.filters)

    def recv(self, msg: Message, filters: Optional[Sequence] = None) -> Message:
        from ..filter.base import decode_chain

        return decode_chain(msg, filters or msg.task.filters)


def init_distributed() -> None:
    """Multi-host bootstrap (ref Van::Connect scheduler rendezvous).

    Joins jax.distributed when coordinator env vars are present
    (PS_COORDINATOR_ADDRESS / PS_NUM_PROCESSES / PS_PROCESS_ID — the
    reference's scheduler host:port + node ids in env.cc); no-op on a
    single host. Full logic in parallel/distributed.py.
    """
    from ..parallel import distributed

    distributed.initialize()
