"""Van: the transport layer, rebuilt on XLA collectives.

Counterpart of ``src/system/van.{h,cc}``. The reference moves bytes between
nodes with ZMQ sockets; on TPU the equivalent "wire" is the ICI/DCN fabric
driven by XLA collectives inside jitted programs. The Van therefore exposes:

- device placement (``put``) with the right NamedSharding — the analog of
  addressing a message to a node group;
- the collective primitives push/pull compile down to (psum, all_gather,
  reduce_scatter, ppermute) bound to mesh axes;
- host-side filter-chain encode/decode for control-plane messages (the
  reference applies filters in Van::Send/Recv via RemoteNode).

Multi-host bootstrap (the reference's scheduler rendezvous in
``Van::Connect``) maps to ``jax.distributed.initialize``; gated here because
this environment is single-host.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import mesh as meshlib
from .message import Message


class Van:
    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.placed_bytes = 0  # device placement volume (put_* below)
        # serialized host frames through transfer() — kept separate from
        # placement bytes so each counter means ONE thing (ref van.cc
        # send_bytes_/recv_bytes_ count wire frames)
        self.wire_sent_bytes = 0
        self.wire_recv_bytes = 0

    # -- placement (addressing) --

    def put_table(self, arr) -> jax.Array:
        """Place a parameter table sharded by key range over servers."""
        out = jax.device_put(arr, meshlib.table_sharding(self.mesh))
        self.placed_bytes += arr.nbytes
        return out

    def put_batch(self, arr) -> jax.Array:
        """Place a batch sharded over the data (worker) axis."""
        out = jax.device_put(arr, meshlib.batch_sharding(self.mesh))
        self.placed_bytes += arr.nbytes
        return out

    def put_replicated(self, arr) -> jax.Array:
        out = jax.device_put(arr, meshlib.replicated(self.mesh))
        self.placed_bytes += arr.nbytes
        return out

    # -- host wire (control plane) --

    def transfer(self, sender, recver, msg: Message) -> Message:
        """The full host wire path between two per-peer endpoints (ref
        van.cc Send then Recv): the sender's RemoteNode filter-encodes
        and serializes, the frame crosses the "wire" (loopback within a
        process, the jax.distributed KV transport across hosts), and the
        receiver's RemoteNode deserializes and decodes. Van keeps the
        process-level byte counters (ref Van send_bytes_/recv_bytes_);
        the per-peer counters live on the RemoteNodes.

        Every ps.py group RPC — request AND response — crosses here."""
        blob = sender.to_wire(msg)
        self.wire_sent_bytes += len(blob)
        self.wire_recv_bytes += len(blob)
        return recver.from_wire(blob)

    def send(self, msg: Message, filters: Optional[Sequence] = None) -> Message:
        from ..filter.base import encode_chain

        return encode_chain(msg, filters or msg.task.filters)

    def recv(self, msg: Message, filters: Optional[Sequence] = None) -> Message:
        from ..filter.base import decode_chain

        return decode_chain(msg, filters or msg.task.filters)


def init_distributed() -> None:
    """Multi-host bootstrap (ref Van::Connect scheduler rendezvous).

    Joins jax.distributed when coordinator env vars are present
    (PS_COORDINATOR_ADDRESS / PS_NUM_PROCESSES / PS_PROCESS_ID — the
    reference's scheduler host:port + node ids in env.cc); no-op on a
    single host. Full logic in parallel/distributed.py.
    """
    from ..parallel import distributed

    distributed.initialize()
