"""Tasks and messages — the host control plane.

Counterpart of ``src/system/message.{h,cc}`` + ``proto/task.proto``. In the
reference every RPC is a ``Task`` protobuf (request flag, logical time,
wait_time dependencies, key_range, filters, typed payloads) carried in a
``Message`` with key/value byte arrays over ZMQ. Here the data plane is XLA
collectives, so Message carries host array references and Task keeps the
same scheduling metadata (time/wait_time/key_range/channel/filters) used by
the executor to order jitted steps.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

from ..utils.range import Range

INVALID_TIME = -1

# builtins reachable via pickle's find_class that are data, not code
_SAFE_BUILTINS = {"complex", "range", "slice", "frozenset", "set", "bytearray"}
# exact (module, name) pairs for the numpy machinery array/scalar pickles
# actually use — NOT a module prefix: numpy also exports file writers
# (numpy.save), dlopen helpers (ctypeslib.load_library) and classes with
# side-effectful constructors (numpy.memmap)
_SAFE_NUMPY = {
    ("numpy", "ndarray"),
    ("numpy", "dtype"),
    ("numpy.core.multiarray", "_reconstruct"),
    ("numpy._core.multiarray", "_reconstruct"),
    ("numpy.core.multiarray", "scalar"),
    ("numpy._core.multiarray", "scalar"),
}
# the closed set of package types that legitimately ride a wire header
# (the Task graph). NOT "any package class": instantiating e.g. Customer
# registers phantom customers with the receiver's postoffice — package
# constructors can carry side effects even when no stdlib code is
# reachable. Extend this set when a new type genuinely joins the wire.
_SAFE_PACKAGE = {
    ("parameter_server_tpu.system.message", "Task"),
    ("parameter_server_tpu.system.message", "FilterSpec"),
    ("parameter_server_tpu.system.message", "Command"),
    ("parameter_server_tpu.utils.range", "Range"),
    # heartbeat/metrics reports ride the message plane (aux_runtime
    # metric reports, monitor progress): a plain dataclass of floats
    # and one hostname string, no side effects on construction
    ("parameter_server_tpu.system.heartbeat", "HeartbeatReport"),
}


def _restricted_loads(blob: bytes):
    """Unpickle a wire header allowing only this package's classes, the
    exact numpy reconstruction machinery, and plain-data builtins.

    Defenses (each closes a demonstrated bypass class):
    - names containing '.' are rejected outright — protocol-4
      STACK_GLOBAL resolves dotted names by attribute traversal, so an
      allowed module would otherwise reach e.g. ``cpp.subprocess.run``;
    - the package allowance is the closed ``_SAFE_PACKAGE`` set of wire
      dataclasses, not "any package class" — constructors like
      ``Customer()`` mutate receiver state (postoffice registration);
    - numpy is a closed (module, name) set, not a prefix;
    - numpy dtype classes (numpy 2 pickles dtypes as
      ``numpy.dtypes.Float64DType``) are allowed as types only.
    """
    import io
    import pickle

    class _Unpickler(pickle.Unpickler):
        def find_class(self, module: str, name: str):
            def deny() -> None:
                raise pickle.UnpicklingError(
                    f"wire frame names forbidden global {module}.{name}"
                )

            if "." in name:  # STACK_GLOBAL attribute traversal
                deny()
            if (module, name) in _SAFE_PACKAGE:
                return super().find_class(module, name)
            if module.startswith("parameter_server_tpu."):
                deny()
            if (module, name) in _SAFE_NUMPY:
                return super().find_class(module, name)
            if module == "numpy.dtypes":
                obj = super().find_class(module, name)
                if not isinstance(obj, type):
                    deny()
                return obj
            if module == "collections" and name == "OrderedDict":
                return super().find_class(module, name)
            if module == "builtins" and name in _SAFE_BUILTINS:
                return super().find_class(module, name)
            deny()

    return _Unpickler(io.BytesIO(blob)).load()


class Command(enum.Enum):
    """Control commands (ref task.proto Control/ManageNode + sgd.proto
    SGDCall + bcd.proto BCDCall command enums, collapsed)."""

    TERMINATE = "terminate"
    REQUEST_WORKLOAD = "request_workload"
    UPDATE_MODEL = "update_model"
    PREPROCESS_DATA = "preprocess_data"
    EVALUATE_PROGRESS = "evaluate_progress"
    SAVE_MODEL = "save_model"
    RECOVER = "recover"
    HEARTBEAT = "heartbeat"
    # flight-recorder ring fetch (telemetry/blackbox.py): a node ships
    # its bounded event ring to the scheduler for a diagnostic bundle
    DUMP_BLACKBOX = "dump_blackbox"


#: the closed key set a wire trace context may carry (Task.trace)
_TRACE_KEYS = {"flow", "node", "t_send"}


def _validate_trace(trace: Any) -> Optional[dict]:
    """Validate a decoded header's trace context (Task.trace).

    The field rides the restricted unpickler like the rest of the Task,
    but the unpickler only bounds WHICH types can be named — a hostile
    peer could still smuggle an arbitrarily nested container or a
    numpy payload into the slot the receiver later re-activates as a
    flow scope. This narrows it to the closed shape
    :func:`telemetry.spans.trace_context` emits: a flat dict of at
    most {flow: int, node: short str, t_send: finite float}. Anything
    else raises ``ValueError`` loudly (the from_bytes malformed-frame
    contract); absent/None decodes as None (legacy peers, tracing off).
    """
    if trace is None:
        return None
    if type(trace) is not dict or set(trace) - _TRACE_KEYS:
        raise ValueError(
            f"wire frame carries malformed trace context: {trace!r:.120}"
        )
    flow = trace.get("flow")
    if flow is not None and (
        type(flow) is not int or not 0 < flow < (1 << 63)
    ):
        raise ValueError(
            f"wire frame trace context has non-int flow {flow!r:.80}"
        )
    node = trace.get("node")
    if node is not None and (type(node) is not str or len(node) > 64):
        raise ValueError(
            f"wire frame trace context has bad node id {node!r:.80}"
        )
    t_send = trace.get("t_send")
    if t_send is not None and (
        type(t_send) not in (int, float)
        or not (-1e12 < float(t_send) < 1e12)
    ):
        raise ValueError(
            f"wire frame trace context has bad t_send {t_send!r:.80}"
        )
    return trace


@dataclasses.dataclass
class FilterSpec:
    """A filter application (ref proto/filter.proto FilterConfig)."""

    type: str  # 'key_caching' | 'compressing' | 'fixing_float' | 'add_noise' | 'sparse'
    num_bytes: int = 0  # fixing_float width
    clear_cache_if_done: bool = False
    mean: float = 0.0
    std: float = 0.0
    extra: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class Task:
    """Scheduling metadata for one logical step (ref task.proto Task)."""

    request: bool = True
    time: int = INVALID_TIME
    wait_time: List[int] = dataclasses.field(default_factory=list)
    key_channel: int = 0
    key_range: Range = dataclasses.field(default_factory=Range.all)
    filters: List[FilterSpec] = dataclasses.field(default_factory=list)
    cmd: Optional[Command] = None
    push: bool = False  # push vs pull for parameter tasks
    more: bool = False  # scheduler hint: more blocks coming (ref darlin)
    payload: Any = None  # app-specific (workload descriptors, progress, ...)
    #: wire trace context (telemetry/spans.trace_context): the sending
    #: thread's flow id + origin node + send wall time, stamped by
    #: Van.transfer so one batch/request stays ONE flow across
    #: processes. Plain scalars only — validated on decode
    #: (_validate_trace): a hostile blob here is rejected loudly, and a
    #: legacy header without the field decodes as None (rolling
    #: upgrades).
    trace: Optional[dict] = None

    def fresh_copy(self) -> "Task":
        """Per-send copy. Filter ``extra`` dicts are per-message side
        channels the encode chain mutates (compression meta, key
        signatures); sharing them across concurrent sends or group
        targets races one send's meta into another's frame."""
        return dataclasses.replace(
            self,
            wait_time=list(self.wait_time),
            filters=[
                dataclasses.replace(f, extra=dict(f.extra))
                for f in self.filters
            ],
        )


@dataclasses.dataclass
class Message:
    """One unit of work/communication (ref message.h Message).

    ``key``/``values`` are host numpy arrays (the localized view of device
    buffers); the device arrays themselves flow through the jitted step the
    executor dispatches.
    """

    task: Task = dataclasses.field(default_factory=Task)
    sender: str = ""
    recver: str = ""
    key: Optional[np.ndarray] = None
    values: List[np.ndarray] = dataclasses.field(default_factory=list)
    callback: Optional[Callable[[], None]] = None

    def __repr__(self) -> str:
        nk = 0 if self.key is None else len(self.key)
        return (
            f"Message({'req' if self.task.request else 'res'} t={self.task.time} "
            f"{self.sender}->{self.recver} keys={nk} vals={len(self.values)})"
        )

    def to_bytes(self) -> bytes:
        """Wire serialization (ref van.cc Van::Send: Task proto followed
        by the key/value SArrays as raw buffers). The task — including
        FilterSpec ``extra`` side-channels like compression meta and key
        signatures — rides pickle, our stand-in for the reference's
        protobuf on a trusted intra-cluster control plane; arrays go as
        raw typed buffers. ``callback`` never crosses the wire."""
        import pickle
        import struct

        arrays = ([] if self.key is None else [self.key]) + list(self.values)
        arrays = [np.ascontiguousarray(a) for a in arrays]
        header = {
            "task": self.task,
            "sender": self.sender,
            "recver": self.recver,
            "has_key": self.key is not None,
            "dtypes": [str(a.dtype) for a in arrays],
            "shapes": [a.shape for a in arrays],
        }
        hb = pickle.dumps(header, protocol=pickle.HIGHEST_PROTOCOL)
        parts = [struct.pack("<I", len(hb)), hb]
        for a in arrays:
            b = a.tobytes()
            parts.append(struct.pack("<Q", len(b)))
            parts.append(b)
        return b"".join(parts)

    @staticmethod
    def from_bytes(blob: bytes) -> "Message":
        """Inverse of :meth:`to_bytes` (ref van.cc Van::Recv).

        Malformed or truncated frames raise ``ValueError`` (matching the
        codec layer's contract), and the header unpickler is restricted
        to this package's types + numpy reconstruction — a frame from a
        compromised peer cannot name arbitrary callables the way plain
        ``pickle.loads`` would allow."""
        import struct

        try:
            (hlen,) = struct.unpack_from("<I", blob, 0)
            header = _restricted_loads(bytes(blob[4 : 4 + hlen]))
            task = header["task"]
            # trace-context hardening + rolling-upgrade tolerance: a
            # legacy peer's Task pickle predates the field entirely
            # (dataclass unpickling restores __dict__ verbatim, no
            # __init__ defaults) — normalize to None; a present field
            # is narrowed to the closed trace shape or rejected loudly
            task.trace = _validate_trace(getattr(task, "trace", None))
            off = 4 + hlen
            arrays = []
            for dtype, shape in zip(header["dtypes"], header["shapes"]):
                (n,) = struct.unpack_from("<Q", blob, off)
                off += 8
                dt = np.dtype(dtype)
                if off + n > len(blob):
                    raise ValueError("array payload exceeds frame")
                arrays.append(
                    np.frombuffer(blob, dtype=dt, count=n // dt.itemsize,
                                  offset=off).reshape(shape).copy()
                    if n
                    else np.zeros(shape, dt)
                )
                off += n
            key = arrays.pop(0) if header["has_key"] else None
            return Message(
                task=header["task"],
                sender=header["sender"],
                recver=header["recver"],
                key=key,
                values=arrays,
            )
        except ValueError:
            raise
        except Exception as e:  # struct.error, pickle errors, bad shapes...
            raise ValueError(f"truncated or malformed wire frame: {e}") from e


def slice_message(msg: Message, key_ranges: Sequence[Range]) -> List[Message]:
    """Partition an ordered-key message by server key ranges.

    Counterpart of ``Parameter::SliceKOFVMessage`` (parameter.h): for each
    server range, binary-search the key array and emit a sub-message with the
    matching key/value segments.
    """
    out: List[Message] = []
    keys = msg.key if msg.key is not None else np.zeros(0, dtype=np.int64)
    for r in key_ranges:
        lo = int(np.searchsorted(keys, r.begin, side="left"))
        hi = int(np.searchsorted(keys, r.end, side="left"))
        sub = Message(
            task=dataclasses.replace(msg.task, key_range=r),
            sender=msg.sender,
            recver=msg.recver,
            key=keys[lo:hi],
            values=[v.reshape(len(keys), -1)[lo:hi].reshape(-1) for v in msg.values]
            if len(keys)
            else [],
        )
        out.append(sub)
    return out
