"""Executor: logical clocks and dependency tracking over XLA async dispatch.

Counterpart of ``src/system/executor.{h,cc}`` + ``task_tracker.h``. The
reference runs a per-customer DAG engine thread that picks received messages
whose ``wait_time`` dependencies are finished. On TPU the same pipelining
falls out of XLA's async dispatch: submitting a jitted step returns
immediately with future arrays; ordering *within* a device queue is program
order, and cross-step constraints are enforced by blocking on tracked
futures before dispatch.

``Submit`` assigns a timestamp, runs the step's host closure (which
dispatches device work), and records returned jax arrays as the step's
future. ``Wait(ts)`` blocks until that step's arrays are materialized —
``Customer::Wait`` semantics. Bounded-delay consistency = submit without
waiting, with a sliding window: ``Submit`` itself blocks when more than
``max_in_flight`` steps are unfinished (the reference throttles identically
through its message clocks).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

import jax

from .message import INVALID_TIME, Message, Task


class TaskTracker:
    """Finished/started timestamp bookkeeping (ref task_tracker.h)."""

    def __init__(self) -> None:
        self._finished: set[int] = set()
        self._started: set[int] = set()
        self._lock = threading.Lock()

    def start(self, ts: int) -> None:
        with self._lock:
            self._started.add(ts)

    def finish(self, ts: int) -> None:
        with self._lock:
            self._finished.add(ts)

    def is_finished(self, ts: int) -> bool:
        with self._lock:
            return ts in self._finished

    def was_started(self, ts: int) -> bool:
        with self._lock:
            return ts in self._started


class Executor:
    def __init__(self, name: str = "", max_in_flight: int = 0):
        self.name = name
        self._time = 0
        self._futures: Dict[int, Any] = {}  # ts -> pytree of jax arrays
        self._callbacks: Dict[int, Callable[[], None]] = {}
        self.tracker = TaskTracker()
        self._lock = threading.Lock()
        self.max_in_flight = max_in_flight  # 0 = unbounded (eventual consistency)

    def time(self) -> int:
        with self._lock:
            return self._time

    def _next_time(self) -> int:
        with self._lock:
            ts = self._time
            self._time += 1
            return ts

    def submit(
        self,
        step: Callable[[], Any],
        task: Optional[Task] = None,
        callback: Optional[Callable[[], None]] = None,
    ) -> int:
        """Dispatch ``step`` with dependency waits; returns its timestamp.

        ``task.wait_time`` lists timestamps that must be *finished* before
        this step runs (ref executor.cc PickActiveMsg dependency check).
        Dependencies must reference already-submitted steps — the reference
        allocates timestamps at Submit, so a dep can never be in the future.
        """
        task = task or Task()
        if task.time != INVALID_TIME:
            ts = task.time
            with self._lock:
                if ts in self._futures or (
                    ts < self._time and self.tracker.was_started(ts)
                ):
                    raise ValueError(f"timestamp {ts} already used")
                # keep the auto counter ahead of explicit timestamps so they
                # can never collide with a later auto-assigned one
                self._time = max(self._time, ts + 1)
        else:
            ts = self._next_time()
        for dep in task.wait_time:
            if dep == INVALID_TIME:
                continue
            if dep >= ts:
                raise ValueError(f"dependency {dep} is not before step {ts}")
            self.wait(dep)
        if self.max_in_flight > 0:
            self._throttle(ts)
        self.tracker.start(ts)
        result = step()
        with self._lock:
            self._futures[ts] = result
            if callback is not None:
                self._callbacks[ts] = callback
        return ts

    def _throttle(self, ts: int) -> None:
        """Bounded-delay window: block until step ts - max_in_flight is done.

        Completion only (pop=False): the step's result stays claimable by a
        later wait()/pop_result() — throttling must not consume metrics the
        caller still wants to collect.
        """
        horizon = ts - self.max_in_flight
        if horizon >= 0:
            self.wait(horizon, pop=False)

    def wait(self, ts: int, pop: bool = True) -> Any:
        """Block until step ``ts`` has materialized (Customer::Wait).

        By default evicts the step's future so device buffers are released —
        without this, every intermediate result would stay pinned in HBM.
        ``pop=False`` blocks without consuming (used by the throttle).
        Returns the step's value (None if ts is unknown or already popped).
        """
        with self._lock:
            fut = self._futures.pop(ts, None) if pop else self._futures.get(ts)
            cb = self._callbacks.pop(ts, None)
        if fut is not None:
            jax.block_until_ready(fut)
        if self.tracker.was_started(ts):
            self.tracker.finish(ts)
        if cb is not None:
            cb()
        return fut

    def wait_all(self) -> None:
        with self._lock:
            pending = list(self._futures.keys())
        for ts in pending:
            self.wait(ts)

    def result(self, ts: int) -> Any:
        """The (possibly still-async) value of step ts (None once waited)."""
        with self._lock:
            return self._futures.get(ts)

    def pop_result(self, ts: int) -> Any:
        return self.wait(ts)


class NodeGroups:
    """Symbolic node group ids (ref executor.h kServerGroup et al.).

    On TPU these resolve to mesh axes rather than socket lists; kept for API
    parity so app code reads like the reference.
    """

    SERVER_GROUP = "all_servers"
    WORKER_GROUP = "all_workers"
    COMP_GROUP = "all_comp_nodes"
    REPLICA_GROUP = "all_replicas"
    OWNER_GROUP = "all_owners"
    LIVE_GROUP = "all_lives"
