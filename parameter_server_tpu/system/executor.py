"""Executor: logical clocks and dependency tracking over XLA async dispatch.

Counterpart of ``src/system/executor.{h,cc}`` + ``task_tracker.h``. The
reference runs a per-customer DAG engine thread that picks any received
message whose ``wait_time`` dependencies are finished (executor.cc
PickActiveMsg) — messages behind an unmet dependency do NOT block ready
ones submitted later. This executor reproduces that: ``submit`` enqueues
and returns immediately; a dispatch thread repeatedly runs the
lowest-timestamp *ready* step (all deps finished), skipping over blocked
ones. When nothing is ready it resolves the oldest blocked step's
dependencies by materializing their device futures (XLA async dispatch
means a "run" step may still be computing on device; a dependency counts
as finished only once its results are ready — the reference's handler-ran
== message-finished contract).

``Wait(ts)`` blocks until step ``ts`` has run and its arrays materialized
— ``Customer::Wait`` semantics. Bounded-delay consistency: ``submit``
itself blocks when more than ``max_in_flight`` steps are unfinished (the
reference throttles identically through its message clocks).
"""

from __future__ import annotations

import heapq
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax

from ..telemetry import registry as telemetry_registry
from ..telemetry import spans as telemetry_spans
from ..utils.retry import Deadline, DeadlineExceeded
from . import faults
from .message import INVALID_TIME, Message, Task


class _ExecutorTelemetry:
    """Per-executor bridge into the process registry (telemetry spine).

    The dispatch loop must stay hardware-speed, so the per-step path is
    ONE buffer append under one small lock; the buffered phase records
    flush into the registry instruments lazily — on the registry's
    collector hook (every ``snapshot()``/``render_text()`` read) or when
    the buffer fills. Instrument children are bound once here so the
    flush path does no name/label lookups either.
    """

    __slots__ = (
        "queue_wait", "run", "materialize", "total",
        "steps", "in_flight", "pending", "name",
        "_buf", "_buf_lock", "__weakref__",
    )

    _FLUSH_AT = 4096  # bound buffered memory between registry reads

    def __init__(self, name: str):
        from ..telemetry.instruments import executor_instruments

        reg = telemetry_registry.default_registry()
        insts = executor_instruments(reg)
        self.name = name
        self.queue_wait = insts["queue_wait"].labels(executor=name)
        self.run = insts["run"].labels(executor=name)
        self.materialize = insts["materialize"].labels(executor=name)
        self.total = insts["total"].labels(executor=name)
        self.steps = insts["steps"].labels(executor=name)
        self.in_flight = insts["in_flight"].labels(executor=name)
        self.pending = insts["pending"].labels(executor=name)
        self._buf: list = []  # guarded-by: _buf_lock
        self._buf_lock = threading.Lock()
        reg.add_collector(self.flush)

    def record(
        self,
        queue_wait: float,
        run_s: float,
        mat_s: float,
        total: float,
        in_flight: int,
        pending: int,
    ) -> None:
        """Hot path: one lock, one append (~1µs); flush is amortized."""
        with self._buf_lock:
            self._buf.append(
                (queue_wait, run_s, mat_s, total, in_flight, pending)
            )
            if len(self._buf) < self._FLUSH_AT:
                return
            buf, self._buf = self._buf, []
        self._flush_records(buf)

    def flush(self) -> None:
        """Drain buffered step records into the registry (collector hook)."""
        with self._buf_lock:
            buf, self._buf = self._buf, []
        if buf:
            self._flush_records(buf)

    def _flush_records(self, buf: list) -> None:
        for qw, run_s, mat_s, total, _, _ in buf:
            self.queue_wait.observe(qw)
            self.run.observe(run_s)
            self.materialize.observe(mat_s)
            self.total.observe(total)
        self.steps.inc(len(buf))
        # gauges are point-in-time: the newest record wins
        self.in_flight.set(buf[-1][4])
        self.pending.set(buf[-1][5])


class TaskTracker:
    """Finished/started timestamp bookkeeping (ref task_tracker.h)."""

    def __init__(self) -> None:
        self._finished: set[int] = set()  # guarded-by: _lock
        self._started: set[int] = set()  # guarded-by: _lock
        # in-flight is tracked incrementally: the set difference the
        # old in_flight() computed is O(all steps ever), and it ran
        # once per dispatched step — quadratic across a training run
        self._inflight = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def start(self, ts: int) -> None:
        with self._lock:
            if ts not in self._started and ts not in self._finished:
                self._inflight += 1
            self._started.add(ts)

    def finish(self, ts: int) -> None:
        with self._lock:
            if ts in self._started and ts not in self._finished:
                self._inflight -= 1
            self._finished.add(ts)

    def is_finished(self, ts: int) -> bool:
        with self._lock:
            return ts in self._finished

    def was_started(self, ts: int) -> bool:
        with self._lock:
            return ts in self._started

    def in_flight(self) -> int:
        """Started (dispatched) but not yet finished. O(1)."""
        with self._lock:
            return self._inflight


#: every live executor, weakly held — the diagnostic-bundle capture
#: (telemetry/blackbox.py) walks this to snapshot pending/in-flight
#: state at the moment of an incident; dead executors fall out with GC.
#: WeakSet is not thread-safe: registration (any thread constructing
#: an Executor) and the capture-thread copy both go through
#: _live_lock, or an incident capture racing a construction would die
#: with set-changed-size-during-iteration — replacing the executors
#: section with an error string at exactly the moment it matters.
_live_executors: "weakref.WeakSet" = weakref.WeakSet()
_live_lock = threading.Lock()


def live_executors() -> List["Executor"]:
    """The process's live executors (for diagnostics; order arbitrary)."""
    with _live_lock:
        return list(_live_executors)


class Executor:
    def __init__(
        self,
        name: str = "",
        max_in_flight: int = 0,
        telemetry: Optional[bool] = None,
    ):
        self.name = name
        self._time = 0  # guarded-by: _cv — the logical clock
        # telemetry spine (doc/OBSERVABILITY.md): per-step phase
        # histograms + depth gauges, and one JSONL span event per
        # finished step correlating host time to the logical clock.
        # ``telemetry=None`` follows the process-wide switch; the
        # decision is cached here so the hot path tests one attribute.
        if telemetry is None:
            telemetry = telemetry_registry.enabled()
        self._tel: Optional[_ExecutorTelemetry] = (
            _ExecutorTelemetry(name) if telemetry else None
        )
        # ts -> [t_submit, t_dispatch, run_s, materialize_s] (perf_counter)
        # Deliberately NOT guarded-by _cv: the dispatch thread mutates a
        # record's cells while waiter threads accumulate materialize
        # time into others; cross-thread hand-off rides dict.pop's
        # atomicity ("popped exactly once", _record_finished) so the
        # per-step hot path never takes the cv twice.
        self._step_times: Dict[int, List[float]] = {}
        self._pending: Dict[int, Tuple[Callable[[], Any], List[int]]] = {}  # guarded-by: _cv
        # dependency-counted readiness (round 5): the original picker
        # re-sorted and re-scanned every pending step per dispatch —
        # O(n² log n) across an n-step burst, measured at 2.7k steps/s
        # for a 5000-step burst vs 33k for 500 (benchmarks executor).
        # Now: unmet-dep counts + a dep→dependents map maintained at
        # submit/finish, and a min-heap of ready timestamps — each
        # step is pushed and popped once.
        self._unmet: Dict[int, int] = {}  # guarded-by: _cv — pending ts -> unmet dep count
        self._dependents: Dict[int, List[int]] = {}  # guarded-by: _cv — dep ts -> waiters
        self._ready: List[int] = []  # guarded-by: _cv — heap of dispatchable timestamps
        # ts -> (flow id, origin node) captured on the SUBMITTING
        # thread; the dispatch loop re-activates it around the step
        # body so spans emitted inside (a ps.py RPC's van.transfer, a
        # wire encode) stay on the batch/request's flow — without this
        # the flow dies at submit and the cross-node timeline cannot
        # stitch the step's downstream work. Only populated while a
        # flow is actually active (tracing on).
        self._flows: Dict[int, Tuple[int, Optional[str]]] = {}  # guarded-by: _cv
        self._running: Optional[int] = None  # guarded-by: _cv — picked, step() executing now
        self._ran: set[int] = set()  # guarded-by: _cv — ran, not finished yet (pruned on finish)
        self._futures: Dict[int, Any] = {}  # guarded-by: _cv — ts -> pytree (run, maybe async)
        self._callbacks: Dict[int, Callable[[], None]] = {}  # guarded-by: _cv
        self._errors: Dict[int, BaseException] = {}  # guarded-by: _cv
        self.tracker = TaskTracker()
        self._cv = threading.Condition()
        self._thread: Optional[threading.Thread] = None  # guarded-by: _cv
        self._stopped = False  # guarded-by: _cv
        self.max_in_flight = max_in_flight  # 0 = unbounded (eventual consistency)
        # telemetry: max |started \ finished| ever observed at dispatch time
        # (τ-bounded-delay proof for the darlin scheduler)
        self.max_dispatched_in_flight = 0
        with _live_lock:
            _live_executors.add(self)

    def time(self) -> int:
        with self._cv:
            return self._time

    def debug_state(self, max_pending: int = 16) -> Dict[str, Any]:
        """Point-in-time diagnostic snapshot for incident bundles
        (telemetry/blackbox.py): logical clock, backlog depth and its
        oldest timestamps, the step executing right now, in-flight
        count. One lock acquire; safe from any thread."""
        with self._cv:
            pending = sorted(self._pending)
            return {
                "name": self.name,
                "logical_time": self._time,
                "pending": len(pending),
                "pending_ts": pending[:max_pending],
                "running": self._running,
                "in_flight": self.tracker.in_flight(),
            }

    def pending_count(self) -> int:
        """Submitted steps not yet picked by the dispatch thread — an
        O(1) backlog read an admission controller can gate on per
        request (serving/admission.py ``depth_fn``; the composed
        frontend gates on its own in-flight count instead, but a bare
        store serving direct pulls has only this signal)."""
        with self._cv:
            return len(self._pending)

    # -- submission (ref Customer::Submit) --

    def submit(
        self,
        step: Callable[[], Any],
        task: Optional[Task] = None,
        callback: Optional[Callable[[], None]] = None,
    ) -> int:
        """Enqueue ``step``; returns its timestamp immediately.

        ``task.wait_time`` lists timestamps that must be *finished* before
        this step runs (ref executor.cc PickActiveMsg dependency check).
        Dependencies must reference already-submitted steps — the reference
        allocates timestamps at Submit, so a dep can never be in the future.
        A dep naming a timestamp that was NEVER submitted counts as
        satisfied, evaluated once at submit time: backfilling that
        timestamp later (an explicit ``Task(time=...)``) does not
        retroactively block this step.
        The step runs on the executor's dispatch thread, possibly after
        later-submitted steps whose dependencies cleared earlier.
        """
        task = task or Task()
        with self._cv:
            if task.time != INVALID_TIME:
                ts = task.time
                if ts < self._time and self.tracker.was_started(ts) or (
                    ts in self._pending
                ):
                    raise ValueError(f"timestamp {ts} already used")
                # keep the auto counter ahead of explicit timestamps so they
                # can never collide with a later auto-assigned one
                self._time = max(self._time, ts + 1)
            else:
                ts = self._time
                self._time += 1
            deps = []
            for dep in task.wait_time:
                if dep == INVALID_TIME:
                    continue
                if dep >= ts:
                    raise ValueError(f"dependency {dep} is not before step {ts}")
                deps.append(dep)
            self._pending[ts] = (step, deps)
            flow = telemetry_spans.current_flow()
            if flow is not None:
                self._flows[ts] = (flow, telemetry_spans.current_flow_node())
            if self._tel is not None:
                # [t_submit, t_dispatch (0 = not picked yet),
                #  run_s (-1 = run not completed yet), materialize_s,
                #  flow id active on the SUBMITTING thread (timeline
                #  flow correlation: the batch/request this step
                #  serves) or None]
                self._step_times[ts] = [
                    time.perf_counter(), 0.0, -1.0, 0.0, flow,
                ]
            # readiness accounting: a dep not yet done registers this
            # step as its dependent; _finish(dep) decrements the count
            # and promotes the step to the ready heap at zero. A dep
            # that is done (or was never submitted) never transitions
            # again, so checking it exactly once here is sound.
            unmet = [d for d in deps if not self._dep_done_locked(d)]
            if unmet:
                self._unmet[ts] = len(unmet)
                for d in unmet:
                    self._dependents.setdefault(d, []).append(ts)
            else:
                heapq.heappush(self._ready, ts)
            if callback is not None:
                self._callbacks[ts] = callback
            self._ensure_thread()
            self._cv.notify_all()
        if self.max_in_flight > 0:
            self._throttle(ts)
        return ts

    def _throttle(self, ts: int) -> None:
        """Bounded-delay window: block until step ts - max_in_flight is done.

        Completion only (pop=False): the step's result stays claimable by a
        later wait()/pop_result() — throttling must not consume metrics the
        caller still wants to collect.
        """
        horizon = ts - self.max_in_flight
        if horizon >= 0:
            self.wait(horizon, pop=False)

    # -- the dispatch thread (ref executor.cc thread + PickActiveMsg) --

    def _ensure_thread(self) -> None:  # holds-lock: _cv (submit calls this)
        if self._thread is None or not self._thread.is_alive():
            self._stopped = False
            self._thread = threading.Thread(
                target=self._dispatch_loop, name=f"executor:{self.name}", daemon=True
            )
            self._thread.start()

    def _dispatch_loop(self) -> None:
        while True:
            dep_fut = None
            with self._cv:
                while not self._pending and not self._stopped:
                    self._cv.wait()
                if self._stopped:
                    return
                pick = self._pick_ready_locked()
                if pick is None:
                    # Nothing ready: resolve the oldest blocked step's first
                    # unmet dep. Every unmet dep is an older timestamp, so
                    # by induction it has already run (or is being waited on
                    # by another thread) — never pending.
                    oldest = min(self._pending)
                    dep = next(
                        (
                            d
                            for d in self._pending[oldest][1]
                            if not self._dep_done_locked(d)
                        ),
                        None,
                    )
                    if dep is None:
                        # every dep of the oldest blocked step is in
                        # fact done, yet the step is not in the ready
                        # heap: either a concurrent wait() finished the
                        # dep between the ready-pick and here, or the
                        # dep was finished through an EXTERNAL
                        # tracker.finish (Customer.reply does this) that
                        # bypasses _finish's promotion. Promote it
                        # directly — without this the loop would spin
                        # forever on a step no _finish will ever push
                        # (duplicate heap entries are skipped lazily).
                        self._unmet.pop(oldest, None)
                        heapq.heappush(self._ready, oldest)
                        continue
                    if dep in self._futures:
                        dep_fut = self._futures[dep]  # materialize below
                    else:
                        # running, or popped by a concurrent wait(): that
                        # path will finish it and notify — do NOT finish an
                        # unmaterialized dep here
                        self._cv.wait()
                        continue
                else:
                    ts, step = pick
                    self._running = ts
                    step_flow = self._flows.pop(ts, None)
            if pick is None:
                if dep_fut is not None:
                    self._materialize_fut(dep, dep_fut)
                self._finish(dep)
                continue
            # run the step outside the lock (it may dispatch device work,
            # or block — submitters and waiters must stay free)
            self.tracker.start(ts)
            self.max_dispatched_in_flight = max(
                self.max_dispatched_in_flight, self.tracker.in_flight()
            )
            tel = self._tel
            if tel is not None:
                t_run0 = time.perf_counter()
                times = self._step_times.get(ts)
                if times is not None:
                    times[1] = t_run0  # dispatch pickup: queue wait ends
            try:
                # fault point (doc/ROBUSTNESS.md): kind="raise" makes
                # this step fail exactly like a raising step body (the
                # error propagates to the waiter); a ``delay_s`` stalls
                # the dispatch thread first (kind="stall" stalls
                # without raising). Inside the try so an injected raise
                # rides the organic error path bit-for-bit.
                faults.inject("executor.step", detail=f"{self.name}:{ts}")
                # the submitter's flow rides into the step body so
                # spans it emits (ps.py RPC transfers, nested submits)
                # keep the unit-of-work correlation across the dispatch
                # thread; flow_scope(None) is a free passthrough
                with telemetry_spans.flow_scope(
                    *(step_flow or (None, None))
                ):
                    result = step()
                err = None
            except BaseException as e:  # propagate to the waiter
                result, err = None, e
            if tel is not None and times is not None:
                times[2] = time.perf_counter() - t_run0
            with self._cv:
                self._running = None
                self._ran.add(ts)
                if err is not None:
                    self._errors[ts] = err
                else:
                    self._futures[ts] = result
                self._cv.notify_all()

    def _dep_done_locked(self, d: int) -> bool:  # holds-lock: _cv
        """A dependency is satisfied when finished — or never submitted
        (the reference waits only on timestamps it issued; an unknown ts is
        a no-op there too)."""
        if self.tracker.is_finished(d):
            return True
        return (
            d not in self._pending
            and d != self._running
            and d not in self._ran
            and not self.tracker.was_started(d)
        )

    def _pick_ready_locked(self) -> Optional[Tuple[int, Callable[[], Any]]]:  # holds-lock: _cv
        """Lowest-timestamp READY step (PickActiveMsg: any ready message
        may overtake blocked ones). O(log n) via the ready heap. Lazy
        skips: entries whose step is gone (run or cancelled), and
        entries whose timestamp has an unmet-dep count — a stale heap
        entry must never dispatch a REUSED explicit timestamp past its
        fresh dependencies."""
        while self._ready:
            if self._ready[0] in self._unmet:
                heapq.heappop(self._ready)
                continue
            ts = heapq.heappop(self._ready)
            entry = self._pending.pop(ts, None)
            if entry is not None:
                return ts, entry[0]
        return None

    def _note_materialize(self, ts: int, seconds: float) -> None:
        """Accumulate block_until_ready wall time onto the step's record
        (a step may be forced from several waiters; the phases sum)."""
        if self._tel is None:
            return
        times = self._step_times.get(ts)
        if times is not None:
            times[3] += seconds

    def _materialize_fut(self, ts: int, fut: Any) -> None:
        """block_until_ready tolerant of DONATED futures.

        The zero-copy data plane stores live table handles as step
        results; a LATER step may consume (donate) that buffer in
        place. The dispatch thread is serial, so donation implies the
        producing step already completed — a deleted/donated buffer
        here means 'materialized long ago', not an error. The waiter
        still receives the dead handle; READING it raises jax's
        read-after-donate, which is the documented contract
        (doc/PERFORMANCE.md "Donation rules"). Without this guard a
        fire-and-forget push pipeline crashed (and then wedged — see
        wait()) the moment a snapshot waited on a superseded future.

        Known tradeoff: the message match cannot distinguish a
        legitimately superseded future from an erroneously
        double-donated buffer — the latter is only caught when its
        VALUE is read (which still raises). Narrowing this would need
        the stores to mark superseded timestamps explicitly.
        """
        t0 = time.perf_counter()
        try:
            jax.block_until_ready(fut)
        except RuntimeError as e:
            msg = str(e)
            if "deleted" not in msg and "donated" not in msg:
                raise
        self._note_materialize(ts, time.perf_counter() - t0)

    def _record_finished(self, ts: int, num_pending: int) -> None:
        """Record the finished step's phases into the registry and emit
        the per-step span event (one line per step, popped exactly once).
        ``num_pending`` is sampled by the caller inside its own _cv
        critical section — this path must not re-take the cv per step."""
        tel = self._tel
        if tel is None:
            return
        times = self._step_times.get(ts)
        if times is None or times[1] == 0.0 or times[2] < 0.0:
            # not dispatched here, or the step body is still executing
            # (an external tracker.finish — Customer.reply — can satisfy
            # a waiter mid-run): leave the record in place so the finish
            # that observes the completed run emits it exactly once
            return
        times = self._step_times.pop(ts, None)
        if times is None:
            return  # a concurrent finish won the pop; it emitted
        now = time.perf_counter()
        t_submit, t_dispatch, run_s, mat_s, flow = times
        queue_wait = max(0.0, t_dispatch - t_submit)
        total = max(0.0, now - t_submit)
        tel.record(
            queue_wait,
            run_s,
            mat_s,
            total,
            self.tracker.in_flight(),
            num_pending,
        )
        if telemetry_spans.get_sink() is not None:
            event = {
                "kind": "span",
                "name": "executor.step",
                "executor": tel.name,
                "ts": ts,
                "t_wall": time.time(),
                "queue_wait_s": queue_wait,
                "run_s": run_s,
                "materialize_s": mat_s,
                "total_s": total,
            }
            if flow is not None:
                event["flow"] = flow
            telemetry_spans.emit(event)

    def _finish(self, ts: int) -> None:
        """Mark finished (results materialized), prune, fire callback
        once, and promote dependents whose last unmet dep this was."""
        if self.tracker.was_started(ts):
            self.tracker.finish(ts)
        with self._cv:
            self._ran.discard(ts)
            self._flows.pop(ts, None)  # externally-finished steps
            for t in self._dependents.pop(ts, ()):
                left = self._unmet.get(t)
                if left is None:
                    continue  # cancelled by stop()
                if left <= 1:
                    del self._unmet[t]
                    if t in self._pending:
                        heapq.heappush(self._ready, t)
                else:
                    self._unmet[t] = left - 1
            cb = self._callbacks.pop(ts, None)
            # sampled here so the telemetry record below needs no
            # second cv acquire on the per-step path
            num_pending = len(self._pending)
            self._cv.notify_all()
        self._record_finished(ts, num_pending)
        if cb is not None:
            cb()

    # -- waiting (ref Customer::Wait) --

    def wait(self, ts: int, pop: bool = True,
             timeout: Optional[float] = None) -> Any:
        """Block until step ``ts`` has run and materialized (Customer::Wait).

        By default evicts the step's future so device buffers are released —
        without this, every intermediate result would stay pinned in HBM.
        ``pop=False`` blocks without consuming (used by the throttle).
        Returns the step's value (None if ts is unknown or already popped).
        Re-raises the step's exception, if it raised.

        ``timeout`` bounds the wait (seconds): on expiry a diagnostic
        :class:`~..utils.retry.DeadlineExceeded` (a TimeoutError) names
        the wedged timestamp, its state, and — the case that used to
        hang callers forever — its unsatisfied ``wait_time``
        dependencies. Completion-only; a timed-out step keeps running
        and a later wait() can still claim its result.
        """
        deadline = Deadline(timeout)
        timed_out: Optional[DeadlineExceeded] = None
        with self._cv:
            known = (
                ts in self._pending
                or ts == self._running
                or ts in self._ran
                or self.tracker.was_started(ts)
                or self.tracker.is_finished(ts)
            )
            if not known:
                return None
            while not (
                ts in self._futures
                or ts in self._errors
                or self.tracker.is_finished(ts)
            ):
                left = deadline.remaining()
                if left is None:
                    self._cv.wait()
                elif left <= 0:
                    timed_out = self._wait_timeout_locked(ts, timeout)
                    break
                else:
                    self._cv.wait(left)
            if timed_out is None:
                err = (
                    self._errors.pop(ts, None) if pop
                    else self._errors.get(ts)
                )
                fut = (
                    self._futures.pop(ts, None) if pop
                    else self._futures.get(ts)
                )
        if timed_out is not None:
            # a wedged wait is a flight-recorder trigger (the evidence
            # — recent spans, executor state — is exactly what rots if
            # diagnosis waits). Raised OUTSIDE the cv: the bundle
            # capture reads executor state through the public API and
            # must not deadlock on our own lock. Best-effort,
            # rate-limited, never masks the diagnostic error.
            from ..telemetry import blackbox

            blackbox.trigger_bundle(
                "executor_wait_timeout", detail=str(timed_out)
            )
            raise timed_out
        if err is not None:
            self._finish(ts)
            raise err
        if fut is not None:
            try:
                self._materialize_fut(ts, fut)
            except BaseException:
                # the step DID run; mark it finished even when forcing
                # its value fails, or every later wait()/wait_all() on
                # this ts would spin forever on a future that is gone
                self._finish(ts)
                raise
        self._finish(ts)
        return fut

    def _wait_timeout_locked(self, ts: int, timeout: float) -> DeadlineExceeded:  # holds-lock: _cv
        """Build the diagnostic deadline error for a wedged wait: which
        state the step is stuck in, and — when it is pending — which
        ``wait_time`` dependencies never finished (a lost dependency is
        the classic way a caller hangs forever)."""
        entry = self._pending.get(ts)
        if entry is not None:
            unmet = [d for d in entry[1] if not self._dep_done_locked(d)]
            state = (
                f"pending with unsatisfied wait_time deps {unmet}"
                if unmet
                else "pending (ready but not yet dispatched)"
            )
        elif ts == self._running:
            state = "executing on the dispatch thread right now"
        elif ts in self._ran:
            state = "ran; result not yet materialized/finished"
        else:
            state = (
                "started externally (tracker), never finished — a "
                "Customer.reply that never arrived?"
            )
        return DeadlineExceeded(
            f"executor {self.name!r}: step {ts} unfinished after "
            f"{timeout}s — {state}",
            op=f"executor:{self.name} wait({ts})", deadline_s=timeout,
        )

    def wait_all(self, pop: bool = True,
                 timeout: Optional[float] = None) -> None:
        """Drain every unfinished step, including the one executing right
        now. ``pop=False`` preserves results for later collection.
        ``timeout`` bounds the WHOLE drain (one budget across steps,
        utils/retry.Deadline); expiry raises the per-step diagnostic
        DeadlineExceeded of whichever step was wedged."""
        deadline = Deadline(timeout)
        while True:
            with self._cv:
                todo = set(self._pending) | self._ran
                if self._running is not None:
                    todo.add(self._running)
            if not todo:
                return
            for ts in sorted(todo):
                left = deadline.remaining()
                self.wait(ts, pop=pop, timeout=left)

    def result(self, ts: int) -> Any:
        """The (possibly still-async) value of step ts (None once waited,
        or if the step has not been dispatched yet)."""
        with self._cv:
            return self._futures.get(ts)

    def pop_result(self, ts: int) -> Any:
        return self.wait(ts)

    def stop(self, cancel_pending: bool = True) -> None:
        """Stop the dispatch thread and join it. ``cancel_pending`` drops
        steps that have not started (the executing one always completes —
        its state mutation cannot be torn). Idempotent."""
        with self._cv:
            if cancel_pending:
                cancelled = set(self._pending)
                for ts in cancelled:
                    self._pending.pop(ts)
                    self._callbacks.pop(ts, None)
                    self._unmet.pop(ts, None)
                    self._step_times.pop(ts, None)  # never dispatched
                    self._flows.pop(ts, None)
                # purge, don't lazy-skip: an explicit timestamp may be
                # REUSED after cancellation, and a stale heap entry
                # (or a stale _dependents registration decrementing
                # the reincarnation's fresh unmet count) would let the
                # new step dispatch before its dependencies
                self._ready = [t for t in self._ready if t not in cancelled]
                heapq.heapify(self._ready)
                for d in list(self._dependents):
                    kept = [
                        t for t in self._dependents[d] if t not in cancelled
                    ]
                    if kept:
                        self._dependents[d] = kept
                    else:
                        del self._dependents[d]
            self._stopped = True
            self._cv.notify_all()
            thread = self._thread
        if thread is not None and thread.is_alive() and (
            thread is not threading.current_thread()
        ):
            thread.join(timeout=60)
        if self._tel is not None:
            # push buffered step records out before this executor (and
            # its collector registration) can be garbage-collected
            self._tel.flush()


class NodeGroups:
    """Symbolic node group ids (ref executor.h kServerGroup et al.).

    On TPU these resolve to mesh axes rather than socket lists; kept for API
    parity so app code reads like the reference.
    """

    SERVER_GROUP = "all_servers"
    WORKER_GROUP = "all_workers"
    COMP_GROUP = "all_comp_nodes"
    REPLICA_GROUP = "all_replicas"
    OWNER_GROUP = "all_owners"
    LIVE_GROUP = "all_lives"
