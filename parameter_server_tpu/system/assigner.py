"""Node/data assignment (ref ``src/system/assigner.{h,cc}``).

``NodeAssigner`` hands out ranks and server key ranges; ``DataAssigner``
partitions input files (or byte ranges of a single file) over workers,
matching the reference's file-count vs even-divide logic.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..utils import file as psfile
from ..utils.range import Range
from .manager import Node


class NodeAssigner:
    def __init__(self, num_servers: int, key_range: Optional[Range] = None):
        self.num_servers = num_servers
        self.key_range = key_range if key_range is not None else Range.all()
        self._server_rank = 0
        self._worker_rank = 0

    def assign(self, node: Node) -> Node:
        if node.role == Node.SERVER:
            node.key_range = self.key_range.even_divide(
                self.num_servers, self._server_rank
            )
            node.rank = self._server_rank
            self._server_rank += 1
        elif node.role == Node.WORKER:
            node.rank = self._worker_rank
            self._worker_rank += 1
        return node


@dataclasses.dataclass
class DataPart:
    """One worker's share: a file list, or a (file, example-range) slice."""

    files: List[str]
    range_begin: int = 0
    range_end: int = 0  # 0 = whole files


class DataAssigner:
    """Partition files over ``num`` consumers (ref DataAssigner::set/next).

    With at least ``num`` files, files are dealt round-robin (the reference
    evenly divides the file list); with fewer files, each file is split by
    example ranges.
    """

    def __init__(self, files: Optional[List[str]] = None, num: int = 0, local: bool = False):
        self._parts: List[DataPart] = []
        self._pos = 0
        if files is not None and num > 0:
            self.set(files, num, local)

    def set(self, files: List[str], num: int, local: bool = False) -> None:
        files = psfile.expand_globs(files)
        self._parts = []
        self._pos = 0
        if not files:
            return
        if len(files) >= num:
            full = Range(0, len(files))
            for i in range(num):
                r = full.even_divide(num, i)
                self._parts.append(DataPart(files=files[r.begin : r.end]))
        else:
            # fewer files than consumers: split by example range per file
            per_file = -(-num // len(files))
            for i in range(num):
                f = files[i % len(files)]
                slot = i // len(files)
                self._parts.append(
                    DataPart(files=[f], range_begin=slot, range_end=per_file)
                )
        del local  # reference uses it to pin local shards; mesh handles placement

    def next(self) -> Optional[DataPart]:
        if self._pos >= len(self._parts):
            return None
        part = self._parts[self._pos]
        self._pos += 1
        return part

    @property
    def cur_id(self) -> int:
        return self._pos

    def size(self) -> int:
        return len(self._parts)
