"""Alert-driven autoscaling: the alert→action edge.

Every plane below this one already exists: the history store evaluates
``serve_p99_burn`` over fast AND slow windows (telemetry/history.py —
one latency spike cannot page, a sustained burn must), the elastic
coordinator grows the fleet under live serving traffic
(system/elastic.py + the frontend's pause/quiesce/rebind/resume,
tier-1-tested), and the flight recorder captures diagnosis bundles
(telemetry/blackbox.py). What was missing is the EDGE: a firing alert
reached a human, not an actuator. :class:`AlertDrivenScaler` closes it
— an :meth:`AlertManager.add_listener` hook that, on the watched rule
transitioning to ``firing``, grows the fleet and captures the bundle
arc (overload → resize → resolve) so the page that never happened is
still diagnosable after the fact.

Deliberately conservative, in the doc/ROBUSTNESS.md spirit:

- **one rule, one action**: grow by one worker per firing, under a
  cooldown — an oscillating alert must not saw the fleet;
- **bounded**: ``max_workers`` caps growth; past it the scaler only
  records (capacity exhausted IS the page);
- **never raises into the alert plane**: AlertManager swallows
  listener exceptions by contract, and the scaler additionally fences
  its own action errors into the action log;
- **evidence first**: every action (and the eventual resolve) triggers
  a rate-limit-respecting flight-recorder bundle, so the whole arc
  lands in ``blackbox.bundles()`` — asserted by the overload drill in
  tests/test_autoscale.py.

The default action is ``coordinator.add_worker()`` (a bare resize);
serving deployments pass ``grow=`` wiring the full serve-through-resize
sequence (``fe.pause() → fe.quiesce() → co.add_worker() →
fe.rebind(...) → fe.resume()`` — the drill does exactly this).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional


class AlertDrivenScaler:
    """Listener on one alert rule that grows an elastic fleet.

    ``manager`` is the :class:`~..telemetry.alerts.AlertManager` to
    listen on; ``coordinator`` anything with ``add_worker()`` (the
    :class:`~.elastic.ElasticCoordinator` contract). ``grow`` overrides
    the action (called with no args, returns a descriptive value);
    ``cooldown_s`` spaces actions; ``max_workers`` bounds total grows.
    ``clock`` is injectable for deterministic drills.
    """

    def __init__(
        self,
        manager,
        coordinator,
        rule: str = "serve_p99_burn",
        *,
        grow: Optional[Callable[[], object]] = None,
        cooldown_s: float = 60.0,
        max_workers: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.manager = manager
        self.coordinator = coordinator
        self.rule = str(rule)
        self._grow = grow
        self.cooldown_s = float(cooldown_s)
        self.max_workers = max_workers
        self._clock = clock
        self._lock = threading.Lock()
        self._last_action_t: Optional[float] = None  # guarded-by: _lock
        self._grown = 0  # guarded-by: _lock
        self._actions: List[dict] = []  # guarded-by: _lock
        manager.add_listener(self._on_event)

    # -- the listener (runs inside AlertManager.evaluate) ---------------

    def _on_event(self, ev) -> None:
        if ev.rule != self.rule:
            return
        if ev.to == "firing":
            self._act(ev)
        elif ev.to == "resolved":
            self._resolved(ev)

    def _act(self, ev) -> None:
        now = self._clock()
        with self._lock:
            if (
                self._last_action_t is not None
                and now - self._last_action_t < self.cooldown_s
            ):
                self._record_locked("skipped-cooldown", ev, now)
                return
            if (
                self.max_workers is not None
                and self._grown >= self.max_workers
            ):
                # capacity exhausted: nothing left to actuate — this
                # is the state that still needs the human the alert
                # would otherwise have paged
                self._record_locked("skipped-max-workers", ev, now)
                return
            self._last_action_t = now
            self._grown += 1
        try:
            result = (
                self._grow() if self._grow is not None
                else self.coordinator.add_worker()
            )
            outcome = "grew"
        except Exception as e:  # fence: never raise into evaluate()
            result = f"{type(e).__name__}: {e}"
            outcome = "error"
            with self._lock:
                self._grown -= 1
        with self._lock:
            self._record_locked(outcome, ev, now, result=result)
        # evidence: the last seconds of spans/metrics around the
        # overload AND the action, while they are still in the ring
        from ..telemetry import blackbox

        blackbox.trigger_bundle(
            "alert",
            detail=(
                f"{self.rule} firing -> {outcome} "
                f"(value={ev.value}, workers_grown={self.grown()})"
            ),
        )

    def _resolved(self, ev) -> None:
        now = self._clock()
        with self._lock:
            acted = any(a["outcome"] == "grew" for a in self._actions)
            self._record_locked("resolved", ev, now)
        if acted:
            # close the arc: the bundle pair (firing->grew, resolved)
            # is the drill's assertable evidence that no human was in
            # the loop
            from ..telemetry import blackbox

            blackbox.trigger_bundle(
                "alert",
                detail=(
                    f"{self.rule} resolved after autoscale "
                    f"(workers_grown={self.grown()})"
                ),
            )

    # holds-lock: _lock
    def _record_locked(self, outcome, ev, now, result=None) -> None:
        self._actions.append(
            {
                "outcome": outcome,
                "rule": ev.rule,
                "to": ev.to,
                "value": ev.value,
                "t": now,
                **({"result": result} if result is not None else {}),
            }
        )

    # -- introspection --------------------------------------------------

    def grown(self) -> int:
        with self._lock:
            return self._grown

    def actions(self) -> List[dict]:
        with self._lock:
            return list(self._actions)
