"""Postoffice: the process-wide system singleton.

Counterpart of ``src/system/postoffice.{h,cc}``: owns the manager (node and
customer registry) and the van (transport). ``start`` boots the system —
in the reference that spawns send/recv threads and connects ZMQ; here it
builds the device mesh (and, multi-host, joins the jax.distributed
rendezvous), which *is* the connected network on TPU.
"""

from __future__ import annotations

import threading
from typing import Optional

from jax.sharding import Mesh

from ..parallel import mesh as meshlib
from ..telemetry import registry as telemetry_registry
from ..telemetry import spans as telemetry_spans
from ..utils.range import Range
from .manager import Manager
from .van import Van, init_distributed


class Postoffice:
    _instance: Optional["Postoffice"] = None  # guarded-by: _lock
    _lock = threading.Lock()

    def __init__(self) -> None:
        self.manager = Manager()
        self.mesh: Optional[Mesh] = None
        self.van: Optional[Van] = None
        self.aux = None  # AuxRuntime once start_aux() is called
        # the process telemetry spine: every layer's instruments register
        # here (doc/OBSERVABILITY.md); reset() swaps in a fresh registry
        self.metrics = telemetry_registry.default_registry()
        self._started = False

    @classmethod
    def instance(cls) -> "Postoffice":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """Test helper — tear down the singleton (ref Postoffice::Stop).
        Also resets the telemetry spine (fresh default registry, span
        sink closed) so metrics never leak across hermetic tests."""
        with cls._lock:
            cls._instance = None
        telemetry_registry.reset_default_registry()
        telemetry_spans.close_sink()
        # learning truth planes bind per-worker registries; drop them
        # with the spine so a hermetic test never reads a prior run's
        # staleness/heat through learning.snapshot_all()
        from ..telemetry import learning as telemetry_learning

        telemetry_learning.reset()

    def start(
        self,
        num_data: Optional[int] = None,
        num_server: int = 1,
        key_space: Optional[Range] = None,
    ) -> "Postoffice":
        if self._started:
            return self
        # honor JAX_PLATFORMS even when an accelerator plugin set the
        # platform programmatically — this is what lets ps.sh/main.py
        # run on CPU meshes
        meshlib.honor_jax_platforms()
        # persistent compile cache before the first jit: retries and
        # multi-process runs reuse serialized executables instead of
        # re-exercising the (fragile, slow through the tunnel) compiler
        from parameter_server_tpu.utils.compile_cache import enable

        enable()
        init_distributed()
        self.mesh = meshlib.make_mesh(num_data=num_data, num_server=num_server)
        self.van = Van(self.mesh)
        self.manager.init_nodes(
            num_servers=meshlib.num_servers(self.mesh),
            num_workers=meshlib.num_workers(self.mesh),
            key_space=key_space or Range.all(),
        )
        self._started = True
        return self

    def start_aux(self, heartbeat_timeout: float = 10.0, print_fn=print):
        """Create (once) the heartbeat/dashboard/recovery runtime — the
        reference boots these with every node (postoffice.cc heartbeat
        thread, manager.cc dead-node flow, dashboard.cc)."""
        if self.aux is None:
            from .aux_runtime import AuxRuntime

            self.aux = AuxRuntime(
                heartbeat_timeout=heartbeat_timeout, print_fn=print_fn
            )
        return self.aux

    def beat(self, node_id: str) -> None:
        """Heartbeat passthrough for hot loops; no-op before start_aux."""
        if self.aux is not None:
            self.aux.beat(node_id)

    def stop(self) -> None:
        if self.aux is not None:
            self.aux.stop()
            self.aux = None
        self.manager.stop()
        self._started = False

    @property
    def started(self) -> bool:
        return self._started
