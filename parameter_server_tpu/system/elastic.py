"""Live elasticity: node join/leave with key-range migration, TPU-style.

Counterpart of the reference's live cluster membership
(``src/system/manager.cc``: AddNode assigns the new server a key range and
broadcasts the updated node set; the dead-node flow re-assigns a dead
node's work). On TPU a "node" is a mesh slot inside one SPMD program, so
membership changes are mesh re-factorizations:

- **join/leave (graceful)** — snapshot the sharded table to host memory
  (``AsyncSGDWorker.state_host``, no files), rebuild the Postoffice mesh
  with the new data x server split, install the snapshot under the new
  ``NamedSharding`` (``load_state_host``). Key->slot hashing uses the
  CONFIGURED modulus, so every key keeps its slot while the per-server
  key RANGES move — exactly the reference's fixed key space with moving
  server ranges (``Range::EvenDivide``).
- **server death (crash)** — first try the in-place live replica
  (``recover_server_shard``, ref Parameter::GetReplica); if no replica is
  configured the dead shard's segment is lost (as in the reference) and
  the cluster shrinks around it.

The Manager records every membership change and broadcasts add/remove
events to subscribers (ref manager.cc NodeChange).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..utils.range import Range
from .postoffice import Postoffice


class ElasticCoordinator:
    """Owns the Postoffice lifecycle for one elastic app node.

    ``make_worker(mesh) -> worker`` builds the app on a given mesh; the
    worker must expose ``state_host``/``load_state_host`` (and
    ``recover_server_shard`` for crash recovery) — AsyncSGDWorker does.
    """

    def __init__(
        self,
        make_worker: Callable,
        num_data: int,
        num_server: int,
        key_space: Optional[Range] = None,
    ):
        self.make_worker = make_worker
        self.num_data = num_data
        self.num_server = num_server
        self.key_space = key_space or Range.all()
        self.worker = None
        self._listeners = []
        # measured stop-the-world pauses, newest last: dicts with
        # old/new mesh shape and the pause in seconds (VERDICT r2 #6:
        # the pause is REPORTED, not assumed away)
        self.resize_history = []

    # -- lifecycle --

    def start(self):
        po = Postoffice.instance()
        if not po.started:
            po.start(
                num_data=self.num_data,
                num_server=self.num_server,
                key_space=self.key_space,
            )
        self._resubscribe(po)
        self.worker = self.make_worker(po.mesh)
        return self.worker

    def subscribe_nodes(self, cb) -> None:
        """Node add/remove events survive mesh rebuilds (the Manager is
        recreated with the Postoffice; the coordinator re-subscribes)."""
        self._listeners.append(cb)
        po = Postoffice.instance()
        if po.started:
            po.manager.subscribe_nodes(cb)

    def _resubscribe(self, po) -> None:
        for cb in self._listeners:
            po.manager.subscribe_nodes(cb)

    # -- membership changes (ref manager.cc AddNode / NodeDisconnected) --

    def resize(self, num_data: Optional[int] = None,
               num_server: Optional[int] = None,
               notify: bool = True):
        """Live migration to a new data x server split: no files, no
        training-state loss; key ranges re-divide over the new server
        set while every key keeps its hash slot.

        Node ids here are mesh SLOTS (positional, "S0..Sn-1"), so the
        broadcast diff reports slot-count changes: growing emits adds for
        the new highest ranks, shrinking emits removes for the dropped
        ones. A crash names its dead node explicitly first and passes
        ``notify=False`` — the survivors' renumbering is not a
        membership change."""
        import time as _time

        new_data = self.num_data if num_data is None else num_data
        new_server = self.num_server if num_server is None else num_server
        pause_t0 = _time.perf_counter()  # stop-the-world begins at snapshot
        snap = self.worker.state_host() if self.worker is not None else None

        old_po = Postoffice.instance()
        old_nodes = list(old_po.manager.nodes)
        # the aux runtime (heartbeat poller thread, recovery handlers,
        # per-node samplers) survives the resize as the SAME live object:
        # a cluster that went deaf after its first membership change
        # would never detect the second death. Detach it so old_po.stop()
        # doesn't kill its poller.
        live_aux = old_po.aux
        old_po.aux = None
        # orderly teardown of the rest of the old incarnation: the
        # executor dispatch thread must not outlive the mesh it ran on
        if self.worker is not None:
            self.worker.executor.stop()
        old_po.stop()
        Postoffice.reset()
        po = Postoffice.instance().start(
            num_data=new_data, num_server=new_server, key_space=self.key_space
        )
        if live_aux is not None:
            po.aux = live_aux
            # decommissioned slots must not later be declared dead
            new_ids = {n.id for n in po.manager.nodes}
            for n in old_nodes:
                if n.id not in new_ids:
                    live_aux.forget(n.id)
            # the resize pause itself is not a death: refresh survivors'
            # last-seen so a rebuild longer than the heartbeat timeout
            # can't trigger a spurious death cascade on the next check
            live_aux.collector.touch_all()
        self._resubscribe(po)
        if notify:
            # membership diff through the (fresh) manager — the same
            # add/remove stream the reference broadcasts on NodeChange
            old_ids = {n.id for n in old_nodes}
            new_ids = {n.id for n in po.manager.nodes}
            for n in old_nodes:
                if n.id not in new_ids:
                    po.manager.broadcast("remove", n)
            for n in po.manager.nodes:
                if n.id not in old_ids:
                    po.manager.broadcast("add", n)

        old_shape = (self.num_data, self.num_server)
        self.num_data, self.num_server = new_data, new_server
        self.worker = self.make_worker(po.mesh)
        if snap is not None:
            self.worker.load_state_host(snap)
        pause_s = _time.perf_counter() - pause_t0
        self.resize_history.append(
            {"old": old_shape, "new": (new_data, new_server),
             "pause_s": round(pause_s, 3)}
        )
        if po.aux is not None:
            po.aux.dashboard.add_event(
                f"elastic resize {old_shape[0]}x{old_shape[1]} -> "
                f"{new_data}x{new_server}: stop-the-world {pause_s:.2f}s"
            )
        return self.worker

    def add_server(self):
        return self.resize(num_server=self.num_server + 1)

    def remove_server(self):
        assert self.num_server > 1, "cannot remove the last server"
        return self.resize(num_server=self.num_server - 1)

    def add_worker(self):
        return self.resize(num_data=self.num_data + 1)

    def remove_worker(self):
        assert self.num_data > 1, "cannot remove the last worker"
        return self.resize(num_data=self.num_data - 1)

    def attach_recovery(self, rc) -> None:
        """Drive membership from heartbeat timeouts: a RecoveryCoordinator
        server-death event becomes the manager.cc dead-node flow."""
        rc.on_server_dead(lambda nid: self.handle_server_death(int(nid[1:])))

    def handle_server_death(self, rank: int) -> str:
        """Crash path (ref manager.cc dead-node flow): in-place recovery
        from the live neighbor replica when configured; otherwise the
        segment is lost and the cluster shrinks around the dead server.
        Returns "recovered" or "resharded"."""
        po = Postoffice.instance()
        if self.worker is not None and self.worker.recover_server_shard(rank):
            # restored in place from the live replica: membership is
            # unchanged (same slot, same range) — no event
            return "recovered"
        if self.worker is not None:
            # the shard is gone for real: drop its segment before the
            # survivors re-divide the key space
            self.worker.wipe_server_shard(rank)
        # the DEAD node's identity event; the survivors' positional
        # renumbering inside resize is suppressed (notify=False). Forget
        # it in the aux runtime EXPLICITLY — remove_node runs before
        # resize snapshots the node list, so resize's decommission sweep
        # won't see it — or a replacement reusing the slot id could have
        # its own death masked by the stale dead-handled flag.
        if po.aux is not None:
            po.aux.forget(f"S{rank}")
        po.manager.remove_node(f"S{rank}")
        new_server = max(1, self.num_server - 1)
        rebuilt = new_server == self.num_server  # last server: slot reborn
        self.resize(num_server=new_server, notify=False)
        if rebuilt:
            # a 1-server cluster cannot shrink: the slot is rebuilt
            # (empty) — subscribers must see the replacement join or
            # their membership view ends at zero servers
            po2 = Postoffice.instance()
            for n in po2.manager.nodes:
                if n.id == f"S{rank}":
                    po2.manager.broadcast("add", n)
        return "resharded"
