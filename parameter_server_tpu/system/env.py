"""Environment/flag handling (ref ``src/system/env.{h,cc}``).

The reference reads gflags + env vars (node id, scheduler address, #workers,
#servers). Here: one dataclass resolved from env vars with the same
semantics, used by CLI entry points.
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass
class Env:
    num_servers: int = 1
    num_workers: int = 0  # 0 = all remaining devices
    coordinator_address: str = ""
    process_id: int = 0
    num_processes: int = 1
    verbose: int = 0

    @staticmethod
    def from_env() -> "Env":
        return Env(
            num_servers=int(os.environ.get("PS_NUM_SERVERS", "1")),
            num_workers=int(os.environ.get("PS_NUM_WORKERS", "0")),
            coordinator_address=os.environ.get("PS_COORDINATOR_ADDRESS", ""),
            process_id=int(os.environ.get("PS_PROCESS_ID", "0")),
            num_processes=int(os.environ.get("PS_NUM_PROCESSES", "1")),
            verbose=int(os.environ.get("PS_VERBOSE", "0")),
        )
