"""Failure detection → recovery orchestration.

Counterpart of the reference manager's dead-node handling
(``src/system/manager.cc``: heartbeat timeouts surface dead nodes; the
scheduler then restores the dead worker's workloads —
``WorkloadPool::Restore`` — and has a replacement server ``Recover()``
from its replica). This module is that glue: a coordinator polls the
HeartbeatCollector and dispatches role-specific recovery callbacks
exactly once per dead node.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

from .heartbeat import HeartbeatCollector
from .manager import Node

_LOG = logging.getLogger(__name__)


class RecoveryCoordinator:
    """Watches liveness and fires per-role recovery handlers.

    Typical wiring (see tests/test_recovery.py):

    - worker dead  → ``workload_pool.restore(node_id)`` so its unfinished
      file assignments go back to the pool for live workers;
    - server dead  → ``replica_manager.recover(parameter)`` on the
      replacement shard (or a CheckpointManager restore).
    """

    def __init__(self, collector: HeartbeatCollector):
        self.collector = collector
        self._handlers: Dict[str, List[Callable[[str], None]]] = {
            Node.WORKER: [],
            Node.SERVER: [],
            Node.SCHEDULER: [],
        }
        self._recovered: set = set()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def on_worker_dead(self, cb: Callable[[str], None]) -> None:
        self._handlers[Node.WORKER].append(cb)

    def on_server_dead(self, cb: Callable[[str], None]) -> None:
        self._handlers[Node.SERVER].append(cb)

    def on_scheduler_dead(self, cb: Callable[[str], None]) -> None:
        self._handlers[Node.SCHEDULER].append(cb)

    @staticmethod
    def _role_of(node_id: str) -> str:
        return {"W": Node.WORKER, "S": Node.SERVER, "H": Node.SCHEDULER}.get(
            node_id[:1], Node.WORKER
        )

    def check(self, now: Optional[float] = None) -> List[str]:
        """One detection pass; returns nodes newly handled this call."""
        handled = []
        for nid in self.collector.dead_nodes(now):
            with self._lock:
                if nid in self._recovered:
                    continue
                self._recovered.add(nid)
            _LOG.warning("node %s declared dead; running recovery", nid)
            for cb in self._handlers[self._role_of(nid)]:
                try:
                    cb(nid)
                except Exception:  # noqa: BLE001 — keep recovering others
                    _LOG.exception("recovery handler failed for %s", nid)
            handled.append(nid)
        return handled

    def revive(self, node_id: str) -> None:
        """A node reported again after recovery — allow future detection."""
        with self._lock:
            self._recovered.discard(node_id)

    # -- background polling (the scheduler's heartbeat thread) --

    def start(self, interval: float = 1.0) -> None:
        assert self._thread is None, "already started"
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                self.check()

        self._thread = threading.Thread(target=loop, daemon=True, name="recovery")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
