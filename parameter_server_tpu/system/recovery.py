"""Failure detection → recovery orchestration.

Counterpart of the reference manager's dead-node handling
(``src/system/manager.cc``: heartbeat timeouts surface dead nodes; the
scheduler then restores the dead worker's workloads —
``WorkloadPool::Restore`` — and has a replacement server ``Recover()``
from its replica). This module is that glue: a coordinator polls the
HeartbeatCollector and dispatches role-specific recovery callbacks
exactly once per dead node.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from ..telemetry import registry as telemetry_registry
from ..utils.retry import RetryPolicy, call_with_retry
from .heartbeat import HeartbeatCollector
from .manager import Node

_LOG = logging.getLogger(__name__)

#: the recommended handler retry for IDEMPOTENT handlers: a recovery
#: callback that fails transiently (the replacement shard mid-rebuild,
#: an executor briefly wedged) gets three attempts with jittered
#: exponential backoff before the failure is counted — a dead cluster
#: must never get deader because one handler hiccuped once. NOT the
#: default: retrying a partially-completed NON-idempotent handler
#: double-applies it (elastic.handle_server_death shrinks the cluster
#: twice for one death; a replay-based recover double-pushes), so a
#: handler must opt in by being safe to re-run from the top.
DEFAULT_HANDLER_RETRY = RetryPolicy(
    max_attempts=3, base_delay_s=0.05, max_delay_s=0.5
)


class RecoveryCoordinator:
    """Watches liveness and fires per-role recovery handlers.

    Typical wiring (see tests/test_recovery.py):

    - worker dead  → ``workload_pool.restore(node_id)`` so its unfinished
      file assignments go back to the pool for live workers;
    - server dead  → ``replica_manager.recover(parameter)`` on the
      replacement shard (or a CheckpointManager restore).
    """

    def __init__(
        self,
        collector: HeartbeatCollector,
        handler_retry: Optional[RetryPolicy] = None,
    ):
        self.collector = collector
        self._handlers: Dict[str, List[Callable[[str], None]]] = {
            Node.WORKER: [],
            Node.SERVER: [],
            Node.SCHEDULER: [],
        }
        #: retry policy for handler callbacks. None (the default) =
        #: single attempt, the safe choice for non-idempotent handlers
        #: (the pre-existing elastic/workload-pool wiring); pass
        #: DEFAULT_HANDLER_RETRY (or your own policy) for handlers
        #: that are safe to re-run from the top.
        self.handler_retry = handler_retry
        #: cluster context for the node-death diagnostic bundle: the
        #: owning AuxRuntime sets this to itself so the capture gets
        #: Van-fetched rings with staleness, the merged metrics
        #: snapshot, alert states and clock offsets — a standalone
        #: coordinator (drills, tests) captures process-local.
        self.bundle_context = None
        self._recovered: set = set()  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # recovery telemetry (doc/OBSERVABILITY.md "Recovery"): deaths
        # by role, handler failures (post-retry), and the wall time of
        # each node's full recovery handling — RecoveryCoordinator.check
        # used to only LOG, leaving MTTR invisible to every snapshot
        self._tel = None
        if telemetry_registry.enabled():
            from ..telemetry.instruments import recovery_instruments

            self._tel = recovery_instruments(
                telemetry_registry.default_registry()
            )

    def on_worker_dead(self, cb: Callable[[str], None]) -> None:
        self._handlers[Node.WORKER].append(cb)

    def on_server_dead(self, cb: Callable[[str], None]) -> None:
        self._handlers[Node.SERVER].append(cb)

    def on_scheduler_dead(self, cb: Callable[[str], None]) -> None:
        self._handlers[Node.SCHEDULER].append(cb)

    @staticmethod
    def _role_of(node_id: str) -> str:
        return {"W": Node.WORKER, "S": Node.SERVER, "H": Node.SCHEDULER}.get(
            node_id[:1], Node.WORKER
        )

    def check(self, now: Optional[float] = None) -> List[str]:
        """One detection pass; returns nodes newly handled this call.

        When :attr:`handler_retry` is set (opt-in — the handler must
        be idempotent), each handler runs under that policy's jittered
        exponential backoff (utils/retry.py) and only a callback that
        exhausts its attempts counts as a failure; either way a
        failing callback never blocks the others (or other dead
        nodes)."""
        handled = []
        for nid in self.collector.dead_nodes(now):
            with self._lock:
                if nid in self._recovered:
                    continue
                self._recovered.add(nid)
            role = self._role_of(nid)
            _LOG.warning("node %s declared dead; running recovery", nid)
            if self._tel is not None:
                self._tel["deaths"].labels(role=role).inc()
            t0 = time.perf_counter()
            for cb in self._handlers[role]:
                try:
                    if self.handler_retry is None:
                        cb(nid)
                    else:
                        call_with_retry(
                            lambda: cb(nid),
                            self.handler_retry,
                            op=f"recovery handler for {nid}",
                            on_retry=lambda a, e, d: _LOG.warning(
                                "recovery handler for %s failed "
                                "(attempt %d, %s: %s); retrying in %.3fs",
                                nid, a + 1, type(e).__name__, e, d,
                            ),
                        )
                except Exception:  # noqa: BLE001 — keep recovering others
                    _LOG.exception("recovery handler failed for %s", nid)
                    if self._tel is not None:
                        self._tel["handler_failures"].inc()
            if self._tel is not None:
                self._tel["seconds"].observe(time.perf_counter() - t0)
            # a node death is a flight-recorder trigger: capture the
            # diagnostic bundle while the pre-death spans are still in
            # every survivor's ring. The dead node is marked STALE by
            # the caller-visible staleness contract — the coordinator
            # knows who died before any aggregator notices the silence.
            # Best-effort + rate-limited (telemetry/blackbox.py).
            from ..telemetry import blackbox

            blackbox.trigger_bundle(
                "node_death",
                detail=nid,
                aux=self.bundle_context,
                stale={nid: "declared dead (heartbeat timeout)"},
            )
            handled.append(nid)
        return handled

    def revive(self, node_id: str) -> None:
        """A node reported again after recovery — allow future detection."""
        with self._lock:
            self._recovered.discard(node_id)

    # -- background polling (the scheduler's heartbeat thread) --

    def start(self, interval: float = 1.0) -> None:
        assert self._thread is None, "already started"
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                self.check()

        self._thread = threading.Thread(target=loop, daemon=True, name="recovery")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
