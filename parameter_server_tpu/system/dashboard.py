"""Dashboard: node metrics table (ref ``src/system/dashboard.{h,cc}``).

Renders a fixed-width table of per-node heartbeat reports, ordered
scheduler → workers → servers by rank (ref NodeIDCmp), same column spirit
as the reference's dashboard output.
"""

from __future__ import annotations

import threading
from typing import Dict

from ..telemetry import registry as telemetry_registry
from .heartbeat import HeartbeatReport

_COLUMNS = [
    ("node", 8),
    ("total(s)", 9),
    ("busy(s)", 8),
    ("in(MB)", 8),
    ("out(MB)", 8),
    ("rss(MB)", 8),
    ("cpu%", 6),
    ("host", 10),
]


def _node_sort_key(node_id: str):
    # H (scheduler) first, then W workers, then S servers, by numeric rank
    order = {"H": 0, "W": 1, "S": 2}
    return (order.get(node_id[:1], 3), int(node_id[1:]) if node_id[1:].isdigit() else 0)


class Dashboard:
    def __init__(self, registry=None) -> None:
        # Lock: AuxRuntime.beat() feeds reports from every node's
        # reporter/hot-loop thread while the aux poller thread renders
        # report() — an unlocked dict iteration there raised
        # "dictionary changed size during iteration" under load
        # (pslint guarded-access; regression test in
        # tests/test_system_aux.py).
        self._data: Dict[str, HeartbeatReport] = {}  # guarded-by: _lock
        self._tasks: Dict[str, int] = {}  # guarded-by: _lock
        self._events: list = []  # guarded-by: _lock — cluster events (resizes, recoveries)
        self._lock = threading.Lock()
        # telemetry source for the report's metrics section: None keeps
        # the bare node table (unit-test dashboards), a MetricsRegistry
        # pins one, and "default" resolves the process default registry
        # at RENDER time so a Postoffice.reset between construction and
        # report never shows a stale spine. AuxRuntime passes "default".
        self._registry = registry
        # optional AlertManager (telemetry/alerts.py): report() renders
        # its non-inactive rules under an "alerts:" heading, and its
        # transitions already land in the event log via add_event —
        # the scheduler-side console view of an SLO breach
        self._alerts = None

    def set_alerts(self, manager) -> None:
        self._alerts = manager

    def add_report(self, node_id: str, report: HeartbeatReport) -> None:
        with self._lock:
            self._data[node_id] = report

    def add_task(self, node_id: str, task_id: int) -> None:
        with self._lock:
            self._tasks[node_id] = task_id

    def add_event(self, line: str, keep: int = 8) -> None:
        """Record a cluster event (elastic resize with its measured
        stop-the-world pause, recovery, ...) shown under the node table
        — the reference's dashboard prints NodeChange notes the same
        way (ref dashboard.cc)."""
        with self._lock:
            self._events.append(line)
            del self._events[:-keep]

    def title(self) -> str:
        return "  ".join(name.ljust(width) for name, width in _COLUMNS)

    def report(self) -> str:
        # snapshot under the lock, render outside it (rendering calls
        # into the telemetry registry, which has locks of its own —
        # keep the dashboard leaf-level in the lock order)
        with self._lock:
            data = dict(self._data)
            events = list(self._events)
        lines = [self.title()]
        for nid in sorted(data, key=_node_sort_key):
            r = data[nid]
            cells = [
                nid,
                f"{r.total_time_milli / 1e3:.1f}",
                f"{r.busy_time_milli / 1e3:.1f}",
                f"{r.net_in_mb:.1f}",
                f"{r.net_out_mb:.1f}",
                f"{r.process_rss_mb:.0f}",
                f"{100 * r.process_cpu_usage:.0f}",
                r.hostname[:10],
            ]
            lines.append(
                "  ".join(c.ljust(w) for c, (_, w) in zip(cells, _COLUMNS))
            )
        lines.extend(f"event: {e}" for e in events)
        lines.extend(self._alert_lines())
        lines.extend(self._telemetry_lines())
        return "\n".join(lines)

    def _alert_lines(self) -> list:
        if self._alerts is None:
            return []
        active = [
            f"  {name} {st.state_name}"
            + (f" value={st.value:.6g}" if st.value is not None else "")
            for name, st in sorted(self._alerts.states().items())
            if st.state_name != "inactive"
        ]
        return ["alerts:"] + active if active else []

    def _telemetry_lines(self) -> list:
        """Registry snapshot rendered for humans: one line per series,
        histograms compressed to count/avg/p50/p99. Empty when no
        registry is wired or nothing has been recorded."""
        if self._registry is None:
            return []
        reg = (
            telemetry_registry.default_registry()
            if self._registry == "default"
            else self._registry
        )
        snap = reg.snapshot()
        lines = []
        for name, entry in snap.items():  # snapshot() is name-sorted
            for labelstr, val in entry["values"].items():
                series = f"{name}{{{labelstr}}}" if labelstr else name
                if entry["type"] == "histogram":
                    if not val["count"]:
                        continue
                    lines.append(
                        f"  {series} count={val['count']} "
                        f"avg={val['avg']:.6g} p50={val['p50']:.6g} "
                        f"p99={val['p99']:.6g}"
                    )
                else:
                    lines.append(f"  {series} {val:.6g}")
        if lines:
            lines.insert(0, "telemetry:")
        return lines
