"""Deterministic fault injection: named points, seeded triggers, no-op off.

The reference parameter server's defining capability is continuous
operation through node failure (OSDI'14 §4.3) — but failure machinery
that is only ever exercised by polite unit tests is machinery that has
never been *proven*. This module is the chaos plane's core: every layer
that claims robustness declares **named fault points** at the exact
places real faults land (the wire, the dispatch loop, the heartbeat
path, the checkpoint writer, the ingest workers, the serving store
path — catalog in doc/ROBUSTNESS.md), and drills arm them with
deterministic trigger specs to inject drops, delays, duplicates,
stalls, raises, silences and mid-write deaths **under live load**.

Design rules:

- **Zero overhead disarmed.** A disarmed point costs one function call,
  one module-int truth test and a return — no lock, no dict lookup, no
  allocation. The recovery drill's paired-rep A/B
  (``benchmarks/components.recovery_drill`` → ``disarmed_overhead``)
  keeps this honest.
- **Deterministic under a fixed seed.** Triggers are evaluated against
  a per-point call counter and a per-point ``random.Random`` seeded
  from ``(registry seed, point name)`` — the n-th *call* of a point
  fires (or not) identically across runs, independent of which thread
  happens to make it.
- **The call site owns the semantics.** The registry decides *whether*
  a spec fires; the point's code interprets the spec's ``kind`` (a Van
  "drop" is not an Executor "stall"). :func:`inject` covers the common
  raise/delay interpretation so simple sites stay one line.

Usage (tests and drills; production never arms anything)::

    from parameter_server_tpu.system import faults

    faults.arm("heartbeat.report", kind="silence", match="S0")
    faults.arm("van.transfer", kind="delay", delay_s=0.01,
               after_n_calls=3, probability=0.5)
    with faults.scoped("executor.step", kind="raise", once=True):
        ...
    faults.reset()  # hermetic teardown
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import threading
import time
import zlib
from typing import Callable, Dict, Iterator, Optional

#: the canonical point names (doc/ROBUSTNESS.md keeps the prose
#: catalog; arming an unknown name raises so a typo'd drill can't
#: silently test nothing)
POINTS = (
    "van.transfer",        # host wire frames: drop / delay / duplicate
    "executor.step",       # step execution: raise / stall
    "heartbeat.report",    # collector ingress: silence a node
    "checkpoint.write",    # CheckpointManager._write: die mid-write
    "ingest.prep",         # ingest pool workers: raise mid-batch
    "serve.pull",          # serving live-pull store path: raise / stall
    "serve.refresh",       # read-replica refresh store path: raise
    "rebalance.migrate",   # live migration, post-snapshot host phase:
                           # stall (widen the journal window) / raise
    "consistency.rollback",  # divergence reaction, before LR backoff +
                             # snapshot rollback: raise / stall (drill
                             # the recovery path itself failing)
)


class FaultError(RuntimeError):
    """An *injected* failure — distinguishable from organic errors so
    tests can assert the failure they caused is the failure they saw."""

    def __init__(self, point: str, detail: str = ""):
        super().__init__(
            f"injected fault at {point!r}" + (f" ({detail})" if detail else "")
        )
        self.point = point
        self.detail = detail


@dataclasses.dataclass
class FaultSpec:
    """One armed point's trigger + payload. Mutable counters (``calls``,
    ``fired``) are only touched under the owning registry's lock."""

    point: str
    kind: str = "raise"
    after_n_calls: int = 0      # skip the first N matching calls
    probability: float = 1.0    # per-call fire chance (seeded, per point)
    once: bool = False          # disarm after the first firing
    delay_s: float = 0.0        # sleep payload (delay/stall kinds)
    match: Optional[str] = None  # only calls whose detail contains this
    error: Optional[Callable[[], BaseException]] = None  # raise payload
    calls: int = 0
    fired: int = 0

    def make_error(self, detail: str = "") -> BaseException:
        return self.error() if self.error is not None else FaultError(
            self.point, detail
        )


class FaultRegistry:
    """Armed specs + deterministic trigger evaluation.

    Most code uses the process-default registry through the module
    functions below; a private registry is for tests that must not
    share counters.
    """

    def __init__(self, seed: int = 0):
        self.seed = int(seed)
        self._specs: Dict[str, FaultSpec] = {}  # guarded-by: _lock
        self._rngs: Dict[str, random.Random] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        # lock-free fast-path mirror of len(_specs): the disarmed hot
        # path reads this int and returns. Python int read/write is
        # atomic; a racing arm() is visible by the next call, which is
        # all a fault injector needs.
        self.n_armed = 0

    # -- arming --

    def arm(
        self,
        point: str,
        kind: str = "raise",
        *,
        after_n_calls: int = 0,
        probability: float = 1.0,
        once: bool = False,
        delay_s: float = 0.0,
        match: Optional[str] = None,
        error: Optional[Callable[[], BaseException]] = None,
    ) -> FaultSpec:
        if point not in POINTS:
            raise ValueError(
                f"unknown fault point {point!r}; known: {', '.join(POINTS)}"
            )
        if not 0.0 <= probability <= 1.0:
            raise ValueError(f"probability must be in [0, 1], got {probability}")
        spec = FaultSpec(
            point=point, kind=kind, after_n_calls=int(after_n_calls),
            probability=float(probability), once=once,
            delay_s=float(delay_s), match=match, error=error,
        )
        with self._lock:
            self._specs[point] = spec
            # per-point stream seeded from (seed, name): arming order
            # and cross-point interleaving cannot shift the draws
            self._rngs[point] = random.Random(
                (self.seed << 32) ^ zlib.crc32(point.encode())
            )
            self.n_armed = len(self._specs)
        return spec

    def disarm(self, point: str) -> None:
        with self._lock:
            self._specs.pop(point, None)
            self._rngs.pop(point, None)
            self.n_armed = len(self._specs)

    def reset(self) -> None:
        """Disarm everything (hermetic test teardown)."""
        with self._lock:
            self._specs.clear()
            self._rngs.clear()
            self.n_armed = 0

    def spec(self, point: str) -> Optional[FaultSpec]:
        """The armed spec (with its live counters), or None."""
        with self._lock:
            return self._specs.get(point)

    # -- the hot path --

    def check(self, point: str, detail: Optional[str] = None) -> Optional[FaultSpec]:
        """Evaluate one call of ``point``; returns the spec iff it fires.

        Non-matching calls (``match`` miss) are not counted — a spec
        targeting node S0 fires on S0's n-th report no matter how many
        other nodes reported in between.
        """
        if not self.n_armed:
            return None
        with self._lock:
            spec = self._specs.get(point)
            if spec is None:
                return None
            if spec.match is not None and (
                detail is None or spec.match not in str(detail)
            ):
                return None
            spec.calls += 1
            if spec.calls <= spec.after_n_calls:
                return None
            if spec.probability < 1.0:
                if self._rngs[point].random() >= spec.probability:
                    return None
            spec.fired += 1
            if spec.once:
                del self._specs[point]
                self._rngs.pop(point, None)
                self.n_armed = len(self._specs)
        return spec


#: the process-default registry (drills re-seed via :func:`seed`)
_default = FaultRegistry()


def default_registry() -> FaultRegistry:
    return _default


def seed(value: int) -> None:
    """Re-seed the default registry (only affects specs armed after)."""
    _default.seed = int(value)


def arm(point: str, kind: str = "raise", **kw) -> FaultSpec:
    return _default.arm(point, kind, **kw)


def disarm(point: str) -> None:
    _default.disarm(point)


def reset() -> None:
    _default.reset()


def spec(point: str) -> Optional[FaultSpec]:
    return _default.spec(point)


def check(point: str, detail: Optional[str] = None) -> Optional[FaultSpec]:
    """The fault-point hot path: None (the overwhelmingly common case,
    one int test) or the firing spec for the call site to interpret."""
    if not _default.n_armed:
        return None
    return _default.check(point, detail)


def inject(point: str, detail: str = "") -> Optional[FaultSpec]:
    """check() + the common interpretation: sleep ``delay_s`` if set,
    raise on kind ``raise``/``die``; other kinds return the spec for
    the call site. One line for simple sites."""
    sp = check(point, detail)
    if sp is None:
        return None
    if sp.delay_s:
        time.sleep(sp.delay_s)
    if sp.kind in ("raise", "die"):
        raise sp.make_error(detail)
    return sp


@contextlib.contextmanager
def scoped(point: str, kind: str = "raise", **kw) -> Iterator[FaultSpec]:
    """Arm for the duration of a with-block, disarm on exit (even when
    the injected fault propagates out of the block)."""
    sp = arm(point, kind, **kw)
    try:
        yield sp
    finally:
        disarm(point)
