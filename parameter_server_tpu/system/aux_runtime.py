"""Production wiring of heartbeat → dashboard → recovery.

The reference starts these as part of every run: nodes send
HeartbeatReports on a timer (``src/system/postoffice.cc`` heartbeat
thread), the scheduler renders the dashboard (``dashboard.cc``) and its
manager reacts to dead nodes (``manager.cc`` dead-node flow). Round 1
built the pieces but never started them from a production loop; this
module is the glue the apps actually call.

Usage (see apps/linear/main.py and tests/test_aux_integration.py):

    aux = Postoffice.instance().start_aux(heartbeat_timeout=10.0)
    aux.coordinator.on_worker_dead(pool.restore)
    aux.start(check_interval=1.0, dashboard_interval=30.0)
    ...   # hot loops call po.beat(node_id) / aux.beat(node_id)
    aux.stop()
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from .dashboard import Dashboard
from .heartbeat import HeartbeatCollector, HeartbeatInfo
from .recovery import RecoveryCoordinator


class AuxRuntime:
    """Heartbeat sampling + liveness + dashboard, one per process."""

    def __init__(
        self,
        heartbeat_timeout: float = 10.0,
        print_fn: Callable[[str], None] = print,
    ):
        self.collector = HeartbeatCollector(timeout=heartbeat_timeout)
        # "default": the dashboard's telemetry section renders whatever
        # the process default registry holds at report time (the spine
        # every layer records into — doc/OBSERVABILITY.md)
        self.dashboard = Dashboard(registry="default")
        self.coordinator = RecoveryCoordinator(self.collector)
        self.print_fn = print_fn
        self._tel = None
        from ..telemetry import registry as telemetry_registry

        if telemetry_registry.enabled():
            from ..telemetry.instruments import heartbeat_instruments

            self._tel = heartbeat_instruments(
                telemetry_registry.default_registry()
            )
        self._infos: Dict[str, HeartbeatInfo] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- node-side (each logical node beats from its hot loop) --

    def register(self, node_id: str, hostname: str = "") -> HeartbeatInfo:
        """Create (or return) the node's metrics sampler and report an
        initial heartbeat so liveness tracking starts immediately."""
        with self._lock:
            info = self._infos.get(node_id)
            if info is None:
                import socket

                info = HeartbeatInfo(hostname=hostname or socket.gethostname())
                self._infos[node_id] = info
        self.beat(node_id)
        return info

    def beat(self, node_id: str) -> None:
        """Sample and report one heartbeat (ref postoffice.cc heartbeat
        thread body). Safe no-op for unregistered nodes."""
        with self._lock:
            info = self._infos.get(node_id)
        if info is None:
            return
        report = info.get()
        self.collector.report(node_id, report)
        self.dashboard.add_report(node_id, report)
        if self._tel is not None:
            self._tel["reports"].labels(node=node_id).inc()
            self._tel["net_in_mb"].labels(node=node_id).set(report.net_in_mb)
            self._tel["net_out_mb"].labels(node=node_id).set(report.net_out_mb)
        # a node beating again after being declared dead is back — allow
        # future re-detection (ref manager re-adding a returned node)
        self.coordinator.revive(node_id)

    def info(self, node_id: str) -> Optional[HeartbeatInfo]:
        with self._lock:
            return self._infos.get(node_id)

    def forget(self, node_id: str) -> None:
        """Drop a decommissioned node everywhere (elastic shrink): its
        sampler, its liveness record, and its dead-handled flag — so it
        neither false-alarms a 'death' nor blocks re-detection if the
        same slot id joins again later."""
        with self._lock:
            self._infos.pop(node_id, None)
        self.collector.forget(node_id)
        self.coordinator.revive(node_id)

    # -- scheduler-side background services --

    def start(
        self, check_interval: float = 1.0, dashboard_interval: float = 0.0
    ) -> None:
        """Start the liveness/recovery poller; ``dashboard_interval > 0``
        also prints the dashboard table on that period (ref dashboard.cc
        scheduler thread)."""
        if self._thread is not None:
            return
        self._stop.clear()
        last_dash = [time.monotonic()]

        def loop() -> None:
            while not self._stop.wait(check_interval):
                self.coordinator.check()
                if (
                    dashboard_interval > 0
                    and time.monotonic() - last_dash[0] >= dashboard_interval
                ):
                    last_dash[0] = time.monotonic()
                    self.print_fn(self.dashboard.report())

        self._thread = threading.Thread(target=loop, daemon=True, name="aux-runtime")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
        self.coordinator.stop()

    @property
    def running(self) -> bool:
        return self._thread is not None
