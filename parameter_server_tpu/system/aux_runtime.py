"""Production wiring of heartbeat → metrics plane → dashboard → recovery.

The reference starts these as part of every run: nodes send
HeartbeatReports on a timer (``src/system/postoffice.cc`` heartbeat
thread), the scheduler renders the dashboard (``dashboard.cc``) and its
manager reacts to dead nodes (``manager.cc`` dead-node flow). Round 1
built the pieces but never started them from a production loop; this
module is the glue the apps actually call — and, since the cluster
metrics plane (PR 10), the place per-node METRIC reports are produced:
each registered node owns a private registry of ps_node_* instruments
refreshed from its HeartbeatReport, shipped over the Van's real
transfer path (serialization, filter chains, byte accounting, the
``van.transfer`` fault point) to the scheduler-side
:class:`~parameter_server_tpu.telemetry.aggregate.ClusterAggregator`,
which merges everything under a ``node`` label for the exposition
endpoint (telemetry/exposition.py). The direct-call path is kept for
single-process tests (``wire=False``).

Usage (see apps/linear/main.py and tests/test_aux_integration.py):

    aux = Postoffice.instance().start_aux(heartbeat_timeout=10.0)
    aux.coordinator.on_worker_dead(pool.restore)
    aux.start(check_interval=1.0, dashboard_interval=30.0,
              metrics_interval=1.0)
    ...   # hot loops call po.beat(node_id) / aux.beat(node_id)
    aux.stop()
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, Optional, Tuple

from ..telemetry import registry as telemetry_registry
from ..telemetry.aggregate import ClusterAggregator
from .dashboard import Dashboard
from .heartbeat import (
    ClockSync,
    HeartbeatCollector,
    HeartbeatInfo,
    HeartbeatReport,
)
from .message import Command, Message, Task
from .recovery import RecoveryCoordinator

_LOG = logging.getLogger(__name__)


class AuxRuntime:
    """Heartbeat sampling + liveness + metrics plane + dashboard."""

    def __init__(
        self,
        heartbeat_timeout: float = 10.0,
        print_fn: Callable[[str], None] = print,
        node_id: Optional[str] = None,
        stale_after_s: Optional[float] = None,
    ):
        self.collector = HeartbeatCollector(timeout=heartbeat_timeout)
        # "default": the dashboard's telemetry section renders whatever
        # the process default registry holds at report time (the spine
        # every layer records into — doc/OBSERVABILITY.md)
        self.dashboard = Dashboard(registry="default")
        self.coordinator = RecoveryCoordinator(self.collector)
        # node-death bundles captured through this coordinator get the
        # full cluster context (Van-fetched rings, merged metrics,
        # alert states, clock offsets) instead of a process-local view
        self.coordinator.bundle_context = self
        self.print_fn = print_fn
        #: this PROCESS's identity on the cluster metrics plane — the
        #: node the default registry's export is reported under. One
        #: process per node in the multi-process future; "H0" (the
        #: scheduler) in today's single-process runs.
        self.node_id = node_id or os.environ.get("PS_NODE_ID", "H0")
        #: scheduler-side merge of every node's metric reports
        self.cluster = ClusterAggregator(
            stale_after_s=(
                heartbeat_timeout if stale_after_s is None else stale_after_s
            )
        )
        #: optional AlertManager (telemetry/alerts.py) — set_alerts()
        self.alerts = None
        #: per-peer clock-offset estimates from metric-report round
        #: trips (heartbeat.ClockSync) — the alignment input of the
        #: merged multi-node timeline and of every diagnostic bundle
        self.clock = ClockSync()
        #: when True (default), an alert's pending→firing transition
        #: auto-captures a diagnostic bundle (telemetry/blackbox.py —
        #: rate-limited there); the evidence is gone by the time a
        #: human reads the page, so capture rides the transition
        self.bundle_on_alerts = True
        self._last_bundle: Optional[dict] = None  # guarded-by: _bundle_lock
        self._last_bundle_t = 0.0  # guarded-by: _bundle_lock
        self._bundle_lock = threading.Lock()
        self._tel = None
        if telemetry_registry.enabled():
            from ..telemetry.instruments import heartbeat_instruments

            self._tel = heartbeat_instruments(
                telemetry_registry.default_registry()
            )
        #: scrape-time refresh floor: a /metrics GET younger than this
        #: since the last sweep serves the merged view as-is instead of
        #: re-sweeping — a tight scrape loop must not multiply message-
        #: plane traffic or tick per-node report counters (and the
        #: heartbeat.report fault point's call counter) at scrape rate
        self.scrape_refresh_min_s = 0.2
        #: window of down-sampled history each metric report carries
        #: (telemetry/history.py export_ring) — the retention the
        #: scheduler-side range queries serve for remote nodes
        self.history_ship_window_s = 600.0
        self._last_sweep = 0.0  # monotonic; single float, atomic in CPython
        # serializes the scrape-time floor check-and-sweep: N handler
        # threads scraping concurrently must collapse to ONE sweep per
        # floor window, not each pass the age check before any sweep
        # lands (the MonitorMaster.maybe_print race, PR 10, same shape)
        self._sweep_lock = threading.Lock()
        self._infos: Dict[str, HeartbeatInfo] = {}  # guarded-by: _lock
        # per-node PRIVATE registries for the metrics plane:
        # node id -> (registry, instruments, last-lifetime-totals)
        self._node_regs: Dict[str, Tuple] = {}  # guarded-by: _lock
        # per-(node -> scheduler) RemoteNode endpoint pairs for the
        # metric-report wire (stateful filter chains stay per peer)
        self._wire_eps: Dict[str, Tuple] = {}  # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- node-side (each logical node beats from its hot loop) --

    def register(self, node_id: str, hostname: str = "") -> HeartbeatInfo:
        """Create (or return) the node's metrics sampler and report an
        initial heartbeat AND metric report, so liveness tracking and
        cluster staleness marking both start immediately — a node that
        goes silent right after joining shows up STALE in the merged
        view, not absent from it."""
        with self._lock:
            info = self._infos.get(node_id)
            if info is None:
                import socket

                info = HeartbeatInfo(hostname=hostname or socket.gethostname())
                self._infos[node_id] = info
        # direct-path seed (wire=False): registration is local
        # bootstrap, not remote traffic — it must not tick the Van's
        # byte accounting the way timer-driven reports deliberately do
        self.report_node(node_id, wire=False)
        return info

    def beat(self, node_id: str) -> None:
        """Sample and report one heartbeat (ref postoffice.cc heartbeat
        thread body). Safe no-op for unregistered nodes."""
        with self._lock:
            info = self._infos.get(node_id)
        if info is None:
            return
        self._deliver(node_id, info.get())

    def _deliver(self, node_id: str, report: HeartbeatReport) -> bool:
        """Feed one sampled report into collector + dashboard +
        registry mirrors; returns False when the collector's armed
        ``heartbeat.report`` silence swallowed it (the node is 'dead'
        to the scheduler — nothing downstream may report on its
        behalf)."""
        before = self.collector.last_seen(node_id)
        self.collector.report(node_id, report)
        if self.collector.last_seen(node_id) == before:
            return False
        self.dashboard.add_report(node_id, report)
        if self._tel is not None:
            self._tel["reports"].labels(node=node_id).inc()
            self._tel["net_in_mb"].labels(node=node_id).set(report.net_in_mb)
            self._tel["net_out_mb"].labels(node=node_id).set(report.net_out_mb)
        # a node beating again after being declared dead is back — allow
        # future re-detection (ref manager re-adding a returned node)
        self.coordinator.revive(node_id)
        return True

    def info(self, node_id: str) -> Optional[HeartbeatInfo]:
        with self._lock:
            return self._infos.get(node_id)

    def forget(self, node_id: str) -> None:
        """Drop a decommissioned node everywhere (elastic shrink): its
        sampler, its liveness record, its metrics-plane state, and its
        dead-handled flag — so it neither false-alarms a 'death' nor
        blocks re-detection if the same slot id joins again later."""
        with self._lock:
            self._infos.pop(node_id, None)
            self._node_regs.pop(node_id, None)
            self._wire_eps.pop(node_id, None)
        self.collector.forget(node_id)
        self.cluster.forget(node_id)
        self.coordinator.revive(node_id)

    # -- the cluster metrics plane (PR 10) --

    def report_node(self, node_id: str, wire: Optional[bool] = None) -> bool:
        """One node's metric report: heartbeat-deliver, refresh its
        private ps_node_* registry from the sampled report, and ship
        the registry export to the aggregator — over the Van message
        plane when the system is started (``wire=None`` auto-detects;
        ``False`` forces the direct call for single-process tests).
        Returns False when the report was silenced or lost."""
        with self._lock:
            info = self._infos.get(node_id)
        if info is None:
            return False
        report = info.get()
        if not self._deliver(node_id, report):
            return False  # silenced: a crashed node reports NOTHING
        export = self._node_export(node_id, info, report)
        return self._ship(
            node_id, export, report, wire,
            history_ring=self._node_history_ring(node_id),
        )

    def report_all(self, wire: Optional[bool] = None) -> int:
        """One metrics-plane sweep: every registered node reports, plus
        the process default registry under this process's
        :attr:`node_id` (when that id is not itself a registered
        sampler). Returns how many reports landed."""
        self._last_sweep = time.monotonic()
        with self._lock:
            node_ids = list(self._infos)
        landed = sum(1 for nid in node_ids if self.report_node(nid, wire))
        if self.node_id not in node_ids:
            from .faults import check as faults_check

            if faults_check("heartbeat.report", detail=self.node_id) is None:
                if self._ship(
                    self.node_id,
                    telemetry_registry.default_registry().export_state(),
                    None,
                    wire,
                    history_ring=self._default_history_ring(),
                ):
                    landed += 1
        return landed

    def _default_history_ring(self) -> Optional[dict]:
        """The process default store's shipped ring (fold first so the
        ring covers this sweep's registry state); None on any failure —
        history must never break the metric report that carries it."""
        try:
            from ..telemetry import history as history_mod

            store = history_mod.default_store()
            store.fold()
            return store.export_ring(window_s=self.history_ship_window_s)
        except Exception:
            return None

    def _node_history_ring(self, node_id: str) -> Optional[dict]:
        """One node's shipped history ring: its private registry's
        store, merged (for THIS process's node) with the default
        store's ring the same way :meth:`_node_export` merges the
        registries themselves."""
        try:
            with self._lock:
                entry = self._node_regs.get(node_id)
            if entry is None:
                return None
            store = entry[3]
            store.fold()
            ring = store.export_ring(window_s=self.history_ship_window_s)
            if node_id == self.node_id:
                spine = self._default_history_ring()
                if spine is not None:
                    merged = dict(spine["metrics"])
                    merged.update(ring["metrics"])
                    ring = dict(spine)
                    ring["metrics"] = merged
                    ring["series"] = sum(
                        len(m["series"]) for m in merged.values()
                    )
            return ring
        except Exception:
            return None

    def _node_export(
        self, node_id: str, info: HeartbeatInfo, report: HeartbeatReport
    ) -> dict:
        """Refresh the node's private registry from its sampler and
        return the export. Counters advance by LIFETIME-total deltas so
        they stay monotone no matter how report windows interleave with
        hot-loop beats (which drain the per-report deltas)."""
        from ..telemetry.history import HistoryStore
        from ..telemetry.instruments import node_instruments
        from ..telemetry.registry import MetricsRegistry

        with self._lock:
            entry = self._node_regs.get(node_id)
            if entry is None:
                reg = MetricsRegistry()
                # each node's metrics plane gets its own ring cascade —
                # the per-node history the scheduler's fleet-wide range
                # queries serve (shipped by _node_history_ring)
                entry = self._node_regs[node_id] = (
                    reg, node_instruments(reg), {"t": None},
                    HistoryStore(reg),
                )
            reg, tel, state = entry[:3]
            now = time.monotonic()
            for key, total in (
                ("busy", info.total_busy_ms / 1e3),
                ("net_in", float(info.total_in_bytes)),
                ("net_out", float(info.total_out_bytes)),
            ):
                prev = state.get(key, 0.0)
                if total > prev:
                    tel[key].inc(total - prev)
                state[key] = max(prev, total)
            tel["heartbeats"].inc()
            tel["rss_mb"].set(report.process_rss_mb)
            tel["cpu"].set(report.process_cpu_usage)
            tel["host_cpu"].set(report.host_cpu_usage)
            tel["uptime"].set(info.uptime_s)
            if state["t"] is not None:
                tel["report_interval"].observe(now - state["t"])
            state["t"] = now
        export = reg.export_state()
        if node_id == self.node_id:
            # this process's node also carries the process-wide
            # registry (the spine every layer records into)
            merged = dict(telemetry_registry.default_registry().export_state())
            merged.update(export)
            export = merged
        return export

    def _wire_pair(self, node_id: str):
        from .remote_node import RemoteNode

        with self._lock:
            pair = self._wire_eps.get(node_id)
            if pair is None:
                pair = self._wire_eps[node_id] = (
                    RemoteNode(self.node_id),  # node's endpoint → scheduler
                    RemoteNode(node_id),       # scheduler's endpoint ← node
                )
            return pair

    def _ship(
        self,
        node_id: str,
        export: dict,
        report: Optional[HeartbeatReport],
        wire: Optional[bool],
        history_ring: Optional[dict] = None,
    ) -> bool:
        """Move one report to the aggregator — through ``van.transfer``
        (real serialization + byte accounting + the ``van.transfer``
        fault point) when the system is started, directly otherwise.
        The node's down-sampled history ring piggybacks on the same
        frame: a dropped frame loses the shipment (staleness shows it),
        never half of it."""
        payload = {"node": node_id, "metrics": export}
        if report is not None:
            payload["heartbeat"] = report
        if history_ring is not None:
            payload["history"] = history_ring
        van = None
        if wire is not False:
            from .postoffice import Postoffice

            po = Postoffice._instance  # never create the singleton here
            van = po.van if po is not None else None
        if van is not None:
            msg = Message(
                task=Task(cmd=Command.HEARTBEAT, payload=payload),
                sender=node_id,
                recver=self.node_id,
            )
            tx, rx = self._wire_pair(node_id)
            try:
                t0 = time.perf_counter()
                out = van.transfer(tx, rx, msg)
                delivery_s = time.perf_counter() - t0
            except Exception as e:  # injected drop / torn frame: the
                # report is LOST — staleness tracking is how it shows
                _LOG.debug("metric report from %s lost: %s", node_id, e)
                return False
            payload = out.task.payload
            # clock sync: the frame's trace context carries the send
            # wall time on the NODE's clock; paired with our receive
            # time + the measured delivery duration it yields one
            # offset sample (heartbeat.ClockSync — merged timelines
            # align on these). The whole measured window IS the
            # one-way delivery on this loopback leg (transfer returns
            # when the receiver has decoded), so it is passed as
            # delay_s whole — halving it would bias every offset by
            # +delay/2, which an injected van delay fault would turn
            # into a real misalignment of bundle timelines
            trace = getattr(out.task, "trace", None)
            if isinstance(trace, dict) and trace.get("t_send") is not None:
                self.clock.observe(
                    node_id, float(trace["t_send"]), time.time(),
                    delivery_s,
                )
        self.handle_metrics_message(payload)
        return True

    def handle_metrics_message(self, payload: dict) -> None:
        """Receiver side of a metric report (scheduler): merge the
        node's export; a piggybacked HeartbeatReport from a REMOTE
        process also lands in the collector/dashboard (in-process
        reports already delivered through :meth:`_deliver`)."""
        node = payload["node"]
        self.cluster.update(node, payload["metrics"])
        # history rides the same frame but folds separately: a torn /
        # partial payload without a well-formed ring drops THIS
        # shipment only — the aggregator's stored ring for the node is
        # never replaced with garbage (it goes stale by age instead)
        hist = payload.get("history")
        if isinstance(hist, dict) and isinstance(hist.get("metrics"), dict):
            self.cluster.update_history(node, hist)
        hb = payload.get("heartbeat")
        if hb is not None and self.info(node) is None:
            self.collector.report(node, hb)
            self.dashboard.add_report(node, hb)

    # -- flight-recorder rings + diagnostic bundles (PR 14) --

    def fetch_rings(self, wire: Optional[bool] = None) -> Dict[str, dict]:
        """One ring dump per known node, fetched over the Van message
        plane (real serialization through the restricted unpickler,
        byte accounting, the ``van.transfer`` fault point) — the PR 10
        report path, reused for incident evidence. Staleness semantics
        for silent nodes: a node whose metric reports are already stale
        is NOT fetched (a crashed node answers nothing — pretending to
        dump its ring would fabricate evidence), and a fetch lost on
        the wire records the loss instead of the ring. This process's
        own node dumps locally (there is no wire to itself)."""
        from ..telemetry import blackbox

        rings: Dict[str, dict] = {}
        ages = self.cluster.node_ages()
        stale = set(self.cluster.stale_nodes())
        with self._lock:
            node_ids = set(self._infos)
        node_ids.add(self.node_id)
        van = None
        if wire is not False:
            from .postoffice import Postoffice

            po = Postoffice._instance  # never create the singleton here
            van = po.van if po is not None else None
        for nid in sorted(node_ids):
            # this process's OWN node dumps locally FIRST, before any
            # staleness verdict: a stalled aux loop marks self stale —
            # exactly the wedged-process incident a bundle diagnoses —
            # but the in-memory ring needs no wire and is provably
            # alive (this code is executing); skipping it would drop
            # the prime evidence from its own capture
            if nid == self.node_id:
                rec = blackbox.recorder(nid, create=False)
                if rec is None:
                    rec = blackbox.installed_recorder()
                rings[nid] = (
                    rec.dump() if rec is not None
                    else {"absent": True,
                          "reason": "no flight recorder registered"}
                )
                continue
            if nid in stale:
                rings[nid] = {
                    "stale": True,
                    "reason": "metric reports stale — node silent",
                    "report_age_s": round(ages.get(nid, -1.0), 3),
                }
                continue
            rec = blackbox.recorder(nid, create=False)
            if rec is None:
                rings[nid] = {
                    "absent": True,
                    "reason": "no flight recorder registered",
                }
                continue
            dump = rec.dump()
            if van is None:
                rings[nid] = dump
                continue
            msg = Message(
                task=Task(
                    cmd=Command.DUMP_BLACKBOX,
                    payload={"node": nid, "dump": dump},
                ),
                sender=nid,
                recver=self.node_id,
            )
            tx, rx = self._wire_pair(nid)
            try:
                rings[nid] = van.transfer(tx, rx, msg).task.payload["dump"]
            except Exception as e:  # injected drop / torn frame
                rings[nid] = {
                    "stale": True,
                    "reason": f"ring fetch lost on the wire: {e}",
                    "report_age_s": round(ages.get(nid, -1.0), 3),
                }
        return rings

    def bundle(self, trigger: str = "scrape", force: bool = False) -> dict:
        """The /debug/bundle body: a full diagnostic bundle
        (telemetry/blackbox.capture_bundle) with this runtime's cluster
        context. ``scrape`` captures are floored at
        :attr:`scrape_refresh_min_s` like /metrics — a tight scrape
        loop (or N concurrent handler threads) serves the cached bundle
        instead of re-driving the message plane and ticking fault-point
        call counters per GET. A non-``scrape`` trigger always captures
        fresh: serving a cached bundle stamped with a different trigger
        kind would misreport why the artifact exists."""
        from ..telemetry import blackbox

        with self._bundle_lock:
            now = time.monotonic()
            if (
                not force
                and trigger == "scrape"
                and self._last_bundle is not None
                and now - self._last_bundle_t < self.scrape_refresh_min_s
            ):
                return self._last_bundle
            b = blackbox.capture_bundle(trigger=trigger, aux=self)
            self._last_bundle, self._last_bundle_t = b, now
            return b

    def metrics_text(self, refresh: bool = True) -> str:
        """The /metrics scrape body: refresh local nodes' reports (each
        passing the heartbeat fault gate — a silenced node goes stale,
        it does not freeze) and render the node-labeled merged view.
        Refreshes are floored at :attr:`scrape_refresh_min_s` so a
        tight scrape loop reads the merged view instead of re-driving
        the message plane per GET — and the floor check-and-sweep is
        ONE critical section, so N concurrent scrapers (the exposition
        server is threaded) collapse to one sweep per window instead of
        each passing the age check before any sweep lands."""
        if refresh:
            with self._sweep_lock:
                if (
                    time.monotonic() - self._last_sweep
                    >= self.scrape_refresh_min_s
                ):
                    self.report_all()
        return self.cluster.render_text()

    def health(self, now: Optional[float] = None) -> Tuple[bool, dict]:
        """The /healthz verdict: non-OK while any tracked shard is dead
        (heartbeat timeout) or its metric reports are stale. Firing
        alerts are DISCLOSED but do not flip health — an SLO breach is
        the workload's problem, not the process's."""
        dead = sorted(self.collector.dead_nodes(now))
        stale = self.cluster.stale_nodes()
        firing = sorted(self.alerts.firing()) if self.alerts is not None else []
        detail = {
            "ok": not dead and not stale,
            "node_id": self.node_id,
            "dead_nodes": dead,
            "stale_nodes": stale,
            "node_report_age_s": {
                n: round(a, 3) for n, a in sorted(self.cluster.node_ages().items())
            },
            "heartbeat_timeout_s": self.collector.timeout,
            "stale_after_s": self.cluster.stale_after_s,
            "recovery_running": self.running,
            "alerts_firing": firing,
        }
        return detail["ok"], detail

    def set_alerts(self, manager) -> None:
        """Attach an AlertManager: the aux loop evaluates it each pass,
        its transitions land in the dashboard event log, its firing
        rules show in /healthz + the dashboard's alerts section, and —
        when :attr:`bundle_on_alerts` — a pending→firing transition
        auto-captures a diagnostic bundle (the flight-recorder evidence
        of the breach, taken while it is still in the ring)."""
        self.alerts = manager
        manager.add_listener(
            lambda ev: self.dashboard.add_event(str(ev))
        )
        manager.add_listener(self._maybe_bundle_on_alert)
        self.dashboard.set_alerts(manager)

    def _maybe_bundle_on_alert(self, ev) -> None:
        """Alert-transition listener: firing → capture (rate-limited in
        blackbox; never raises — a broken capture must not stop the
        alert from delivering to other listeners)."""
        if not self.bundle_on_alerts or getattr(ev, "to", None) != "firing":
            return
        from ..telemetry import blackbox

        blackbox.trigger_bundle(
            "alert", detail=getattr(ev, "rule", ""), aux=self
        )

    # -- scheduler-side background services --

    def start(
        self,
        check_interval: float = 1.0,
        dashboard_interval: float = 0.0,
        metrics_interval: float = 0.0,
    ) -> None:
        """Start the liveness/recovery poller; ``dashboard_interval > 0``
        also prints the dashboard table on that period (ref dashboard.cc
        scheduler thread), and ``metrics_interval > 0`` runs the
        metrics-plane report sweep (ref postoffice.cc heartbeat thread:
        per-node reports over messages on a timer)."""
        if self._thread is not None:
            return
        self._stop.clear()
        last_dash = [time.monotonic()]
        last_metrics = [0.0]

        def loop() -> None:
            while not self._stop.wait(check_interval):
                self.coordinator.check()
                now = time.monotonic()
                if (
                    metrics_interval > 0
                    and now - last_metrics[0] >= metrics_interval
                ):
                    last_metrics[0] = now
                    try:
                        self.report_all()
                    except Exception:
                        _LOG.exception("metrics-plane sweep failed")
                if self.alerts is not None:
                    try:
                        # the loop IS the evaluator's schedule: its lag
                        # meta-gauge must be judged against this period
                        if self.alerts.period_s != check_interval:
                            self.alerts.period_s = check_interval
                        self.alerts.evaluate()
                    except Exception:
                        _LOG.exception("alert evaluation failed")
                if (
                    dashboard_interval > 0
                    and time.monotonic() - last_dash[0] >= dashboard_interval
                ):
                    last_dash[0] = time.monotonic()
                    self.print_fn(self.dashboard.report())

        self._thread = threading.Thread(target=loop, daemon=True, name="aux-runtime")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=5)
            self._thread = None
        self.coordinator.stop()

    @property
    def running(self) -> bool:
        return self._thread is not None
