"""Payload compression (ref ``src/filter/compressing.h``).

The reference snappy-compresses each value SArray on the wire
(``shared_array_inl.h:245`` CompressTo). Here each value array goes
through ``utils/codec.py``: the native LZ block codec in
``cpp/psnative.cc`` (snappy-class; zlib-1 fallback without the native
lib; frames are self-describing so mixed deployments interop, and
incompressible payloads ride raw). Arrays are restored to their original
dtype/shape on decode. The device-path analog is dtype narrowing (bf16
pulls / int8 pushes) which the learners apply directly — compression of
ICI traffic is a precision choice, not a byte codec.

The UPLOAD path's realization of this filter is
``learner/wire.compress_batch``/``decompress_batch`` (the
``wire_compress`` staging leg): same codec, same incompressible-rides-
raw rule, same chain position (quantize/encode first, byte codec
last), applied per batch-tree leaf between the prep pool and the
uploader thread — see doc/PERFORMANCE.md "What LZ does and does not
shrink" for which legs it actually compresses.
"""

from __future__ import annotations

import numpy as np

from ..system.message import FilterSpec, Message
from ..utils import codec
from .base import Filter, register


@register
class CompressingFilter(Filter):
    TYPE = "compressing"

    def encode(self, msg: Message, spec: FilterSpec) -> Message:
        meta = []
        out = []
        for v in msg.values:
            raw = np.ascontiguousarray(v)
            blob = codec.compress(raw.tobytes())
            meta.append((str(raw.dtype), raw.shape))
            out.append(np.frombuffer(blob, dtype=np.uint8))
        spec.extra["meta"] = meta
        msg.values = out
        return msg

    def decode(self, msg: Message, spec: FilterSpec) -> Message:
        meta = spec.extra.get("meta")
        if meta is None:
            return msg
        out = []
        for v, (dtype, shape) in zip(msg.values, meta):
            dt = np.dtype(dtype)
            expected = dt.itemsize * int(np.prod(shape, dtype=np.int64))
            raw = codec.decompress(v.tobytes(), expected_size=expected)
            out.append(np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape).copy())
        msg.values = out
        return msg
