"""Payload compression (ref ``src/filter/compressing.h``).

The reference LZ4-compresses each value array on the wire. LZ4 isn't in
this environment, so the host codec is zlib (level 1 — closest speed
profile); arrays are restored to their original dtype/shape on decode. The
device-path analog is dtype narrowing (bf16 pulls / int8 pushes) which the
learners apply directly — compression of ICI traffic is a precision choice,
not a byte codec.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..system.message import FilterSpec, Message
from .base import Filter, register


@register
class CompressingFilter(Filter):
    TYPE = "compressing"

    def encode(self, msg: Message, spec: FilterSpec) -> Message:
        meta = []
        out = []
        for v in msg.values:
            raw = np.ascontiguousarray(v)
            blob = zlib.compress(raw.tobytes(), level=1)
            meta.append((str(raw.dtype), raw.shape))
            out.append(np.frombuffer(blob, dtype=np.uint8))
        spec.extra["meta"] = meta
        msg.values = out
        return msg

    def decode(self, msg: Message, spec: FilterSpec) -> Message:
        meta = spec.extra.get("meta")
        if meta is None:
            return msg
        out = []
        for v, (dtype, shape) in zip(msg.values, meta):
            raw = zlib.decompress(v.tobytes())
            out.append(np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape).copy())
        msg.values = out
        return msg
