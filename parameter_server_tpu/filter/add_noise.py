"""Gaussian noise injection (ref ``src/filter/add_noise.h``).

Adds N(mean, std) noise to float value arrays on encode (used for
differential-privacy-flavoured experiments in the reference). Decode is a
no-op — noise is not removed.
"""

from __future__ import annotations

import numpy as np

from ..system.message import FilterSpec, Message
from .base import Filter, register


@register
class AddNoiseFilter(Filter):
    TYPE = "add_noise"

    def __init__(self) -> None:
        self._rng = np.random.default_rng(0)

    def encode(self, msg: Message, spec: FilterSpec) -> Message:
        if spec.std <= 0:
            return msg
        msg.values = [
            (v + self._rng.normal(spec.mean, spec.std, v.shape).astype(v.dtype))
            if v.dtype.kind == "f"
            else v
            for v in msg.values
        ]
        return msg
