"""Key caching (ref ``src/filter/key_caching.h``).

Repeated pushes/pulls over the same key set needn't resend keys: the sender
attaches a crc32c signature of the key array; if the receiver's cache for
(channel, key_range) holds the same signature, keys are omitted and restored
from cache. Device analog: the learner caches gather *slot* arrays on device
keyed by the same signature (no host→device index upload when the key set
repeats — see apps/linear/async_sgd).
"""

from __future__ import annotations

from typing import Dict, Tuple

from ..system.message import FilterSpec, Message
from ..utils import crc32c
from .base import Filter, register


@register
class KeyCachingFilter(Filter):
    TYPE = "key_caching"
    MAX_SIG_LEN = 2048

    def __init__(self) -> None:
        # (channel, key_range) -> (signature, cached keys)
        self._cache: Dict[Tuple[int, Tuple[int, int]], Tuple[int, object]] = {}

    def _cache_key(self, msg: Message):
        kr = msg.task.key_range
        return (msg.task.key_channel, (kr.begin, kr.end))

    def encode(self, msg: Message, spec: FilterSpec) -> Message:
        if msg.key is None:
            spec.extra.pop("signature", None)
            return msg
        sig = crc32c.array_signature(msg.key, self.MAX_SIG_LEN)
        spec.extra["signature"] = sig
        ck = self._cache_key(msg)
        cached = self._cache.get(ck)
        if cached is not None and cached[0] == sig and len(cached[1]) == len(msg.key):
            msg.key = None  # hit: drop keys from the wire
        else:
            self._cache[ck] = (sig, msg.key)
        if spec.clear_cache_if_done and not msg.task.more:
            self._cache.pop(ck, None)
        return msg

    def decode(self, msg: Message, spec: FilterSpec) -> Message:
        sig = spec.extra.get("signature")
        if sig is None:
            return msg
        ck = self._cache_key(msg)
        if msg.key is not None:
            self._cache[ck] = (sig, msg.key)
            return msg
        cached = self._cache.get(ck)
        if cached is None or cached[0] != sig:
            raise KeyError(f"key cache miss for {ck} (signature {sig})")
        msg.key = cached[1]
        if spec.clear_cache_if_done and not msg.task.more:
            self._cache.pop(ck, None)
        return msg
