"""Sparse value filter (ref ``src/filter/sparse_filter.h``).

The reference marks entries to skip with a NaN bitpattern (kkt filter marks)
and drops zero runs from the wire. Here: encode replaces each float array
with (nonzero positions, nonzero values); decode restores the dense array.
Marked (NaN) entries survive the roundtrip — they encode "skip this
coordinate", which darlin's KKT filter relies on.
"""

from __future__ import annotations

import numpy as np

from ..system.message import FilterSpec, Message
from .base import Filter, register

# the reference uses a fixed NaN payload as the mark (sparse_filter.h kMark)
MARK = np.float32(np.nan)


def mark(arr: np.ndarray, idx) -> None:
    arr[idx] = MARK


def marked(arr: np.ndarray) -> np.ndarray:
    return np.isnan(arr)


@register
class SparseFilter(Filter):
    TYPE = "sparse"

    def encode(self, msg: Message, spec: FilterSpec) -> Message:
        meta = []
        out = []
        for v in msg.values:
            if v.dtype.kind != "f":
                out.append(v)
                meta.append(None)
                continue
            nz = np.flatnonzero((v != 0) | np.isnan(v))
            meta.append((len(v), nz.astype(np.int32)))
            out.append(v[nz])
        spec.extra["meta"] = meta
        msg.values = out
        return msg

    def decode(self, msg: Message, spec: FilterSpec) -> Message:
        meta = spec.extra.get("meta")
        if meta is None:
            return msg
        out = []
        for v, m in zip(msg.values, meta):
            if m is None:
                out.append(v)
                continue
            size, nz = m
            dense = np.zeros(size, dtype=v.dtype)
            dense[nz] = v
            out.append(dense)
        msg.values = out
        return msg
