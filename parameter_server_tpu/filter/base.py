"""Filter framework: per-message encode/decode plugins.

Counterpart of ``src/filter/filter.{h,cc}``: the reference applies an
ordered filter chain to every message in Van::Send (encode) and Van::Recv
(decode, reverse order) — compression, quantization, key caching, noise.
Here the chain transforms host-side ``Message`` objects (control plane and
host↔device staging); the device-side analogs (quantized collectives,
cached gather indices) are provided by the jit-able helpers each filter
exposes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Type

from ..system.message import FilterSpec, Message


class Filter:
    """One filter; stateful per peer pair like ref RemoteNode's filter
    cache (remote_node.cc FindFilterOrCreate)."""

    TYPE = "base"

    def encode(self, msg: Message, spec: FilterSpec) -> Message:
        return msg

    def decode(self, msg: Message, spec: FilterSpec) -> Message:
        return msg


_REGISTRY: Dict[str, Type[Filter]] = {}


def register(cls: Type[Filter]) -> Type[Filter]:
    _REGISTRY[cls.TYPE] = cls
    return cls


def create(type_: str) -> Filter:
    """Factory (ref filter.cc Filter::create switch)."""
    if type_ not in _REGISTRY:
        raise ValueError(f"unknown filter type: {type_}")
    return _REGISTRY[type_]()


class FilterChain:
    """Ordered, stateful chain bound to one peer (ref RemoteNode)."""

    def __init__(self) -> None:
        self._filters: Dict[str, Filter] = {}

    def _get(self, type_: str) -> Filter:
        if type_ not in self._filters:
            self._filters[type_] = create(type_)
        return self._filters[type_]

    def encode(self, msg: Message, specs: Optional[Sequence[FilterSpec]] = None) -> Message:
        for spec in specs if specs is not None else msg.task.filters:
            msg = self._get(spec.type).encode(msg, spec)
        return msg

    def decode(self, msg: Message, specs: Optional[Sequence[FilterSpec]] = None) -> Message:
        chain: List[FilterSpec] = list(specs if specs is not None else msg.task.filters)
        for spec in reversed(chain):  # decode applies in reverse (ref van.cc)
            msg = self._get(spec.type).decode(msg, spec)
        return msg


_default_chain = FilterChain()


def encode_chain(msg: Message, specs: Optional[Sequence[FilterSpec]] = None) -> Message:
    return _default_chain.encode(msg, specs)


def decode_chain(msg: Message, specs: Optional[Sequence[FilterSpec]] = None) -> Message:
    return _default_chain.decode(msg, specs)


def _register_builtin() -> None:
    from . import add_noise, compressing, fixing_float, key_caching, sparse  # noqa: F401


_register_builtin()
