"""Tail-feature frequency filter (ref ``src/filter/frequency_filter.h``).

``FreqencyFilter`` [sic] in the reference wraps a count-min sketch:
``InsertKeys(keys, counts)`` accumulates, ``QueryKeys(keys, freq)`` returns
the subset with estimated count ≥ freq. Used by MinibatchReader to drop
ultra-rare features before pulling weights.
"""

from __future__ import annotations

import numpy as np

from ..utils.sketch import CountMin


class FrequencyFilter:
    def __init__(self, n: int = 1 << 20, k: int = 2):
        self._sketch = CountMin(n, k)

    def resize(self, n: int, k: int) -> None:
        self._sketch = CountMin(n, k)

    def insert_keys(self, keys: np.ndarray, counts: np.ndarray | int = 1) -> None:
        self._sketch.insert(keys, counts)

    def query_keys(self, keys: np.ndarray, freq: int) -> np.ndarray:
        """Keys whose estimated frequency ≥ freq (kept sorted if input is)."""
        if freq <= 0:
            return np.asarray(keys)
        est = self._sketch.query(keys)
        return np.asarray(keys)[est >= freq]

    def clear(self) -> None:
        self._sketch.clear()

    @property
    def empty(self) -> bool:
        return not self._sketch.data.any()
