"""Randomized fixed-point quantization (ref ``src/filter/fixing_float.h``).

The reference packs each float into ``num_bytes`` as
``round((v - min) / (max - min) * 2^(8b) + bernoulli)`` with a shared
[min,max] per array — lossy, unbiased via stochastic rounding. Same scheme
here, host (NumPy) for messages and a jit variant (``quantize_jax`` /
``dequantize_jax``) for compressing device pushes before cross-chip
reduction — the TPU analog of shrinking wire bytes.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..system.message import FilterSpec, Message
from .base import Filter, register


def quantize(
    arr: np.ndarray, num_bytes: int, rng: np.random.Generator
) -> Tuple[np.ndarray, float, float]:
    assert num_bytes in (1, 2), "fixed-point width must be 1 or 2 bytes"
    lo, hi = float(arr.min()), float(arr.max())
    if hi <= lo:
        hi = lo + 1.0
    levels = float((1 << (8 * num_bytes)) - 1)
    scaled = (arr.astype(np.float64) - lo) / (hi - lo) * levels
    noise = rng.random(arr.shape)
    q = np.floor(scaled + noise)  # stochastic rounding (ref boolrand)
    dt = np.uint8 if num_bytes == 1 else np.uint16
    return np.clip(q, 0, levels).astype(dt), lo, hi


def dequantize(q: np.ndarray, lo: float, hi: float, num_bytes: int) -> np.ndarray:
    levels = float((1 << (8 * num_bytes)) - 1)
    return (q.astype(np.float64) / levels * (hi - lo) + lo).astype(np.float32)


def quantize_jax(arr: jax.Array, num_bytes: int, key: jax.Array):
    """Device-side stochastic quantization for push compression."""
    levels = float((1 << (8 * num_bytes)) - 1)
    lo = jnp.min(arr)
    hi = jnp.maximum(jnp.max(arr), lo + 1e-12)
    scaled = (arr - lo) / (hi - lo) * levels
    noise = jax.random.uniform(key, arr.shape)
    q = jnp.clip(jnp.floor(scaled + noise), 0, levels)
    dt = jnp.uint8 if num_bytes == 1 else jnp.uint16
    return q.astype(dt), lo, hi


def dequantize_jax(q: jax.Array, lo, hi, num_bytes: int) -> jax.Array:
    levels = float((1 << (8 * num_bytes)) - 1)
    return (q.astype(jnp.float32) / levels * (hi - lo) + lo).astype(jnp.float32)


@register
class FixingFloatFilter(Filter):
    TYPE = "fixing_float"

    def __init__(self) -> None:
        self._rng = np.random.default_rng(0)

    def encode(self, msg: Message, spec: FilterSpec) -> Message:
        if spec.num_bytes == 0:
            return msg
        ranges = []
        out = []
        for v in msg.values:
            if v.dtype.kind != "f" or v.size == 0:
                out.append(v)
                ranges.append(None)
                continue
            q, lo, hi = quantize(v, spec.num_bytes, self._rng)
            out.append(q)
            ranges.append((lo, hi))
        msg.values = out
        spec.extra["ranges"] = ranges
        return msg

    def decode(self, msg: Message, spec: FilterSpec) -> Message:
        if spec.num_bytes == 0 or "ranges" not in spec.extra:
            return msg
        out = []
        for v, r in zip(msg.values, spec.extra["ranges"]):
            if r is None:
                out.append(v)
            else:
                out.append(dequantize(v, r[0], r[1], spec.num_bytes))
        msg.values = out
        return msg
