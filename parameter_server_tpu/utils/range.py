"""Key/index ranges.

TPU-native counterpart of the reference's ``src/util/range.h`` (Range<T>,
SizeR): half-open integer ranges used to describe server key segments and
feature blocks, with ``even_divide`` mirroring ``Range::EvenDivide``.
"""

from __future__ import annotations

import dataclasses

UINT64_MAX = (1 << 64) - 1


@dataclasses.dataclass(frozen=True, order=True)
class Range:
    """Half-open range ``[begin, end)`` over integer keys or indices."""

    begin: int = 0
    end: int = 0

    @staticmethod
    def all() -> "Range":
        return Range(0, UINT64_MAX)

    def size(self) -> int:
        return max(0, self.end - self.begin)

    # NOTE: deliberately no __len__ — bool(Range.all()) would overflow
    # CPython's index-sized __len__ with a 2^64 key space.

    def empty(self) -> bool:
        return self.end <= self.begin

    def valid(self) -> bool:
        return self.end >= self.begin

    def __contains__(self, key: int) -> bool:
        return self.begin <= key < self.end

    def contains_range(self, other: "Range") -> bool:
        return self.begin <= other.begin and other.end <= self.end

    def intersection(self, other: "Range") -> "Range":
        b = max(self.begin, other.begin)
        e = min(self.end, other.end)
        return Range(b, max(b, e))

    def union(self, other: "Range") -> "Range":
        return Range(min(self.begin, other.begin), max(self.end, other.end))

    def shift(self, offset: int) -> "Range":
        return Range(self.begin + offset, self.end + offset)

    def even_divide(self, n: int, i: int) -> "Range":
        """The i-th of n near-equal consecutive partitions (ref range.h:EvenDivide)."""
        if not (0 <= i < n):
            raise ValueError(f"partition {i} out of {n}")
        total = self.size()
        b = self.begin + (total * i) // n
        e = self.begin + (total * (i + 1)) // n
        return Range(b, e)

    def divide(self, n: int) -> list["Range"]:
        return [self.even_divide(n, i) for i in range(n)]

    def __str__(self) -> str:  # matches reference's "[b, e)" logging style
        return f"[{self.begin}, {self.end})"
