"""Bit-packed wire format for slot-id / label streams.

The host→device link is the async-SGD pipeline's scarce resource (the
device step is ~100x faster than the transfer), so integers bound by the
table size travel as a little-endian bitstream: ``bits = ceil(log2 S)``
bits per value instead of 32 (or 24 for the u24 format). Same byte-economy
instinct as the reference's fixing_float filter
(``src/filter/fixing_float.h``) applied to the key stream.

Host side packs (fused C++ hash→slot→pack when available, NumPy
otherwise); the jitted step unpacks with two word-gathers plus shifts —
cheap on an otherwise idle VPU.

Stream layout: value ``i`` occupies stream bits ``[i*bits, (i+1)*bits)``;
stream bit ``k`` lives in byte ``k>>3`` at in-byte position ``k&7``
(little-endian). Words are the same bytes viewed ``<u4``, so stream bit
``k`` is word ``k>>5`` bit ``k&31``.
"""

from __future__ import annotations

import ctypes
from typing import Optional

import numpy as np


def slot_bits(num_slots: int, sentinel: bool = False) -> int:
    """Bits needed for ids in [0, num_slots), +1 value when a padding
    sentinel (== num_slots) must be representable."""
    top = num_slots if sentinel else num_slots - 1
    return max(1, int(top).bit_length())


def packed_nbytes(n: int, bits: int) -> int:
    return (n * bits + 7) // 8


def packed_nwords(n: int, bits: int) -> int:
    """uint32 words holding n b-bit values PLUS one slack word — the
    device-side ``unpack_bits`` gathers ``words[lo+1]`` unconditionally,
    so every packer and the unpacker must agree on this layout."""
    return (n * bits + 31) // 32 + 1


def pack_bits_np(vals: np.ndarray, bits: int) -> np.ndarray:
    """Pure-NumPy bitstream pack (correctness reference / C++ fallback)."""
    v = np.ascontiguousarray(vals, dtype=np.uint32).ravel()
    bitmat = (
        (v[:, None] >> np.arange(bits, dtype=np.uint32)) & np.uint32(1)
    ).astype(np.uint8)
    return np.packbits(bitmat.reshape(-1), bitorder="little")


def pack_bits(vals: np.ndarray, bits: int) -> np.ndarray:
    """int32 values → little-endian uint8 bitstream (C++ fast path)."""
    from ..cpp import native

    v = np.ascontiguousarray(vals, dtype=np.int32).ravel()
    lib = native()
    if lib is None or v.size < 4096:
        return pack_bits_np(v, bits)
    out = np.zeros(packed_nbytes(v.size, bits), np.uint8)
    lib.ps_pack_bits(
        v.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        v.size,
        ctypes.c_uint32(bits),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def hash_slots_packed(
    keys: np.ndarray,
    num_slots: int,
    bits: int,
    seed: int = 0,
    out: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Fused hash → slot → bitstream over a raw key array: the localization
    hot path (one C++ pass, no int32 temporary). Bit-exact with
    ``hash_slots`` + ``pack_bits_np``. ``out``, when given, must be a
    C-contiguous uint8 buffer of exactly ``packed_nbytes(n, bits)`` — the
    stream is written in place (skips an allocation + copy per batch)."""
    from ..cpp import native
    from .murmur import hash_slots

    k = np.asarray(keys)
    if k.dtype == np.int64 and k.flags.c_contiguous:
        k = k.view(np.uint64)
    else:
        k = np.ascontiguousarray(k, dtype=np.uint64)
    k = k.ravel()
    nbytes = packed_nbytes(k.size, bits)
    if out is not None:
        assert out.dtype == np.uint8 and out.flags.c_contiguous
        assert out.size == nbytes, (out.size, nbytes)
    lib = native()
    if lib is None or k.size < 4096:
        stream = pack_bits_np(hash_slots(k, num_slots, seed), bits)
        if out is None:
            return stream
        out[:] = stream
        return out
    if out is None:
        out = np.empty(nbytes, np.uint8)
    lib.ps_hash_slots_packbits(
        k.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
        k.size,
        ctypes.c_uint64(seed),
        ctypes.c_uint64(num_slots),
        ctypes.c_uint32(bits),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out


def stream_to_words(stream: np.ndarray, n: int, bits: int) -> np.ndarray:
    """Pad a byte stream and view it as the uint32 word array the device
    unpacker expects (one extra word so the ``w1`` gather stays in
    bounds)."""
    buf = np.zeros(packed_nwords(n, bits) * 4, np.uint8)
    buf[: stream.size] = stream
    return buf.view("<u4")


def unpack_bits(words, n: int, bits: int):
    """Jit-side inverse: uint32 word array → int32 [n]; ``bits`` <= 31.

    Dispatches to the gather-free tiled unpack whenever ``n`` is a
    multiple of the stream's value period (every production wire is:
    rows_pad*lanes is 2^14*39, divisible by both 16 and 32); the
    two-gather form remains as the general fallback."""
    per_vals = _bit_period(bits)[0]
    if n and n % per_vals == 0:
        return _unpack_bits_tiled(words, n, bits)
    return _unpack_bits_gather(words, n, bits)


def _unpack_bits_gather(words, n: int, bits: int):
    """General-n unpack: two GATHERS + shifts per value. Shift amounts
    stay in [0, 31] (the ``sh == 0`` lane is masked by the where)."""
    import jax.numpy as jnp

    i = jnp.arange(n, dtype=jnp.int32)
    bitpos = i * bits
    lo = bitpos >> 5
    sh = (bitpos & 31).astype(jnp.uint32)
    w0 = words[lo]
    w1 = words[lo + 1]
    hi = w1 << ((jnp.uint32(32) - sh) & jnp.uint32(31))
    v = (w0 >> sh) | jnp.where(sh == jnp.uint32(0), jnp.uint32(0), hi)
    return (v & jnp.uint32((1 << bits) - 1)).astype(jnp.int32)


def _bit_period(bits: int):
    """(values, words) in one period of the bitstream: bit offsets
    repeat every lcm(bits, 32) bits — 16 values for even ``bits``, 32
    for odd (and trivially 32/bits for powers of two)."""
    import math

    lcm = bits * 32 // math.gcd(bits, 32)
    return lcm // bits, lcm // 32


def _unpack_bits_tiled(words, n: int, bits: int):
    """Gather-free unpack for ``n`` a multiple of the value period.

    The decode phase of the fused step spent its time on the fallback's
    1.28M random word-gathers per batch (step_phase_decode ~74 ms at
    the headline shapes, tying gather/scatter — BENCH_ONCHIP 08-02
    04:22). But (lo, sh) as a function of value index is periodic:
    viewing the stream as [n/V, W] word tiles (V values per W words per
    lcm(bits,32)-bit period), every value is a STATIC column pair +
    static shift — V strided loads, no gather, which is exactly what
    the TPU's vector unit wants. No cross-tile carry exists: a period
    ends exactly on a word boundary (lcm is a multiple of 32), so the
    last value's high bits live in column w_per-1, never the next
    tile."""
    import jax.numpy as jnp

    v_per, w_per = _bit_period(bits)
    nper = n // v_per
    cols = words[: nper * w_per].reshape(nper, w_per)
    mask = jnp.uint32((1 << bits) - 1)
    lanes = []
    for j in range(v_per):
        off = j * bits
        lo, sh = off >> 5, off & 31
        w0 = cols[:, lo]
        if sh == 0:
            v = w0
        elif sh + bits <= 32:  # value lives entirely in w0
            v = w0 >> jnp.uint32(sh)
        else:
            v = (w0 >> jnp.uint32(sh)) | (
                cols[:, lo + 1] << jnp.uint32(32 - sh)
            )
        lanes.append(v & mask)
    return jnp.stack(lanes, axis=1).reshape(-1).astype(jnp.int32)


def unpack_sign_bits(bits_u8, n: int):
    """Jit-side label unpack: uint8 bit array → float32 ±1 [n]."""
    import jax.numpy as jnp

    r = jnp.arange(n, dtype=jnp.int32)
    byte = bits_u8[r >> 3]
    bit = (byte >> (r & 7).astype(jnp.uint8)) & jnp.uint8(1)
    return bit.astype(jnp.float32) * 2.0 - 1.0
