"""Advisory inter-process lock for the (single) tunneled TPU device.

Two processes opening the tunneled backend concurrently wedge or fail
each other (one chip, one client at a time) — the realistic collision
is the evidence watcher (``script/onchip.py --watch``) holding the
device when an interactive ``bench.py`` run (or the round driver's)
starts. Both sides take this flock around device use: flock is
released by the kernel when the holder dies, so a crashed holder can
never leave a stale lock — a held lock always means a LIVE holder.

Watcher-side holds are bounded (each task child is killed by its
subprocess timeout, max 5400s), so a bench waiting
``WAIT_ABOVE_LONGEST_HOLD_S`` always outlasts the watcher. The
converse is NOT bounded — an interactive ``bench.py --real`` on a slow
link can legitimately hold for hours under a healthy progress
watchdog — so the two sides use different timeout policies: bench
proceeds after its bound (with a stderr disclosure; only another
bench can outlive it), while the watcher treats a timeout as "device
busy, defer" and never collides (see the callers).

The yielded :class:`LockResult` is truthy when the lock (or a
parent's) is held and carries a ``reason`` so callers can tell
"busy" (a live holder) from "unsupported" (flock impossible here —
exclusion cannot exist, proceed).

Children spawned BY a lock holder must not re-acquire — holders export
``PS_DEVICE_LOCK_HELD=1`` (via :func:`held_env`) and ``device_lock``
becomes a no-op under it.

**Priority protocol** (round 4): the round driver's ``bench.py`` run is
the artifact of record, so the watcher must never make it wait. A
process that needs the device *now* calls :func:`request_priority`
before waiting on the flock; cooperative background holders (the
watcher) poll :func:`foreign_priority` and (a) stop probing/starting
tasks while a fresh foreign request exists, and (b) preempt a running
task child to release the lock within seconds. The requester clears
its marker via :func:`clear_priority` on exit; a crashed requester's
marker simply ages out (``PRIORITY_FRESH_S``). The marker is advisory
— it changes who *waits*, never who may run.
"""

from __future__ import annotations

import contextlib
import errno
import os
import sys
import time
from typing import Iterator

LOCK_ENV = "PS_DEVICE_LOCK"
HELD_ENV = "PS_DEVICE_LOCK_HELD"

#: above the longest WATCHER-side hold (bench_real task timeout: 5400s)
WAIT_ABOVE_LONGEST_HOLD_S = 5700.0

#: a priority request younger than this keeps cooperative holders away
#: (covers the requester's probe retries and inter-phase gaps; a crashed
#: requester's stale marker costs at most this much watcher idle time)
PRIORITY_FRESH_S = 1800.0


class LockResult:
    """Truthy iff the device is exclusively ours (or a parent's).

    ``reason``: "acquired" | "held-by-parent" | "busy" (live holder
    outlasted the wait) | "unsupported" (flock impossible on this
    filesystem — no exclusion exists to wait for)."""

    def __init__(self, acquired: bool, reason: str):
        self.acquired = acquired
        self.reason = reason

    def __bool__(self) -> bool:
        return self.acquired

    def __repr__(self) -> str:
        return f"LockResult({self.acquired}, {self.reason!r})"


def _lock_path() -> str:
    return os.environ.get(LOCK_ENV, "/tmp/ps_tpu_device.lock")


def _open_lock_file() -> "int | None":
    """Open (creating if needed) the lock file. The shared /tmp path is
    chmod'd world-writable so a second user can take the same lock; if
    another user's umask already made it unwritable for us, fall back
    to a per-uid path (loses cross-user exclusion). Returns None when
    no lock file can be opened at all (e.g. /tmp unwritable) — the
    caller reports "unsupported", never crashes the JSON contract."""
    path = _lock_path()
    try:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
        with contextlib.suppress(OSError):
            os.chmod(path, 0o666)  # defeat the creator's umask
        return fd
    except OSError:
        try:
            fallback = f"{path}.{os.getuid()}"
            return os.open(fallback, os.O_CREAT | os.O_RDWR, 0o666)
        except OSError:
            return None


# -- priority requests ------------------------------------------------------

def _request_path() -> str:
    return _lock_path() + ".request"


def request_priority(note: str = "bench") -> None:
    """Mark that THIS process needs the device now. Cooperative
    background holders (the watcher) yield while the marker is fresh.
    Atomic write; never raises (a priority marker is best-effort).

    No-op when a parent already holds the flock for us (HELD_ENV): a
    holder's child asking for priority is self-sabotage — the watcher
    spawning ``bench.py`` saw its own child's probe marker as foreign
    and preempted it (observed 2026-08-01, task bench killed at 6s)."""
    if os.environ.get(HELD_ENV):
        return
    path = _request_path()
    try:
        tmp = f"{path}.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(f"{os.getpid()} {time.time():.0f} {note}\n")
        os.replace(tmp, path)
        with contextlib.suppress(OSError):
            os.chmod(path, 0o666)
    except OSError:
        pass


def clear_priority() -> None:
    """Remove OUR priority marker (a foreign one is left alone)."""
    path = _request_path()
    try:
        with open(path) as f:
            pid = int(f.read().split()[0])
        if pid == os.getpid():
            os.unlink(path)
    except (OSError, ValueError, IndexError):
        pass


def foreign_priority(
    fresh_s: float = PRIORITY_FRESH_S, ignore_pid: "int | None" = None
) -> "str | None":
    """A fresh priority request from ANOTHER process, or None.

    Returns a short human-readable description ("pid 123 note, 45s
    ago") for the yielding side's log. A marker from a dead pid is
    still honored while fresh — the requester may be a shell whose
    python child does the device work. ``ignore_pid`` lets a holder
    running a known child disregard that child's own marker (belt to
    request_priority's HELD_ENV suspenders — an older child binary
    without the no-op would otherwise still self-preempt)."""
    path = _request_path()
    try:
        with open(path) as f:
            parts = f.read().split(None, 2)
        pid = int(parts[0])
        stamp = float(parts[1])
        note = parts[2].strip() if len(parts) > 2 else "?"
    except (OSError, ValueError, IndexError):
        return None
    if pid == os.getpid() or pid == ignore_pid or os.environ.get(HELD_ENV):
        return None  # our own request (or our holder parent's/child's)
    age = time.time() - stamp
    # the marker stamp is written at whole-second precision, so a
    # just-written marker can read up to 0.5s "in the future"; allow a
    # small negative age, reject real clock skew
    if not (-60 <= age < fresh_s):
        return None  # stale (or clock-skewed far into the future)
    return f"pid {pid} ({note}), {age:.0f}s ago"


@contextlib.contextmanager
def device_lock(
    timeout_s: float = WAIT_ABOVE_LONGEST_HOLD_S,
    poll_s: float = 5.0,
    block_after_timeout: bool = False,
    priority_note: "str | None" = None,
) -> Iterator[LockResult]:
    """Hold the device flock for the enclosed block.

    Yields a truthy :class:`LockResult` when the lock was acquired (or
    a parent holds it); falsy with ``reason`` "busy"/"unsupported"
    otherwise — the block still runs either way, callers choose their
    policy from the reason (see module docstring).

    ``block_after_timeout=True`` (the bench's policy): when the wait
    bound expires, KEEP polling until the holder releases and take the
    lock then, instead of running unlocked — a lockless bench would
    let the watcher's next task collide with it the moment the
    original holder exits. The overrun is disclosed on stderr each
    extra minute so a wedged holder is visible in the driver's log.

    ``priority_note`` makes the wait a PRIORITY wait: the request
    marker is written on entry and re-written while polling (every
    ``PRIORITY_FRESH_S/3``), so it cannot age out under a wait longer
    than the freshness window — a stale marker would let the watcher
    win the flock race against the very caller the protocol
    prioritizes. The caller still owns clearing it (clear_priority)
    when its device need ends."""
    if os.environ.get(HELD_ENV):
        yield LockResult(True, "held-by-parent")
        return
    import fcntl

    fd = _open_lock_file()
    if fd is None:
        print(
            "device_lock: no lock file could be opened; "
            "no exclusion possible",
            file=sys.stderr,
        )
        yield LockResult(False, "unsupported")
        return
    res = LockResult(False, "busy")
    t0 = time.monotonic()
    warned_wait = False
    overrun_said = 0.0
    refreshed = time.monotonic()
    if priority_note is not None:
        request_priority(priority_note)
    try:
        while True:
            if (priority_note is not None
                    and time.monotonic() - refreshed > PRIORITY_FRESH_S / 3):
                request_priority(priority_note)
                refreshed = time.monotonic()
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                res = LockResult(True, "acquired")
                break
            except OSError as e:
                if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN,
                                   errno.EACCES):
                    # flock unsupported here (e.g. ENOLCK on NFS):
                    # exclusion is impossible — say so once, don't spin
                    print(
                        f"device_lock: flock unavailable ({e}); "
                        "no exclusion possible",
                        file=sys.stderr,
                    )
                    res = LockResult(False, "unsupported")
                    break
                waited = time.monotonic() - t0
                if waited >= timeout_s:
                    if not block_after_timeout:
                        if timeout_s > 0:
                            print(
                                f"device_lock: holder outlived the "
                                f"{timeout_s:.0f}s wait",
                                file=sys.stderr,
                            )
                        break
                    if waited - overrun_said >= 60.0:
                        print(
                            f"device_lock: holder past the "
                            f"{timeout_s:.0f}s bound ({waited:.0f}s); "
                            "still waiting to acquire (will not run "
                            "unlocked)",
                            file=sys.stderr,
                        )
                        overrun_said = waited
                elif not warned_wait:
                    # a silent multi-minute block is indistinguishable
                    # from a wedge — say what we're doing, once
                    print(
                        "device_lock: device held by another process; "
                        f"waiting up to {timeout_s:.0f}s",
                        file=sys.stderr,
                    )
                    warned_wait = True
                time.sleep(poll_s)
        yield res
    finally:
        try:
            if res.acquired:
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)


def held_env() -> dict:
    """Environment for children of a lock holder (no re-acquire)."""
    env = dict(os.environ)
    env[HELD_ENV] = "1"
    return env
