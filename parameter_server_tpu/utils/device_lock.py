"""Advisory inter-process lock for the (single) tunneled TPU device.

Two processes opening the tunneled backend concurrently wedge or fail
each other (one chip, one client at a time) — the realistic collision
is the evidence watcher (``script/onchip.py --watch``) holding the
device when an interactive ``bench.py`` run (or the round driver's)
starts. Both sides take this flock around device use: flock is
released by the kernel when the holder dies, so a crashed holder can
never leave a stale lock — a held lock always means a LIVE holder.

Every legitimate holder has a bounded lifetime (watcher tasks are
killed by their subprocess timeout, max 5400s; bench runs have their
own watchdog), so waiters use a timeout ABOVE the longest legitimate
hold: waiting that long guarantees progress without ever proceeding
into a collision. A wait that still times out means something outside
the framework holds the lock; the waiter then proceeds with a stderr
warning (a possible collision beats never running at all).

Children spawned BY a lock holder must not re-acquire — holders export
``PS_DEVICE_LOCK_HELD=1`` (via :func:`held_env`) and ``device_lock``
becomes a no-op under it.
"""

from __future__ import annotations

import contextlib
import errno
import os
import sys
import time
from typing import Iterator

LOCK_ENV = "PS_DEVICE_LOCK"
HELD_ENV = "PS_DEVICE_LOCK_HELD"

#: above the longest legitimate hold (watcher bench_real task: 5400s)
WAIT_ABOVE_LONGEST_HOLD_S = 5700.0


def _open_lock_file() -> int:
    """Open (creating if needed) the lock file. The shared /tmp path is
    chmod'd world-writable so a second user can take the same lock; if
    another user's umask already made it unwritable for us, fall back
    to a per-uid path (loses cross-user exclusion, never crashes the
    caller's JSON contract)."""
    path = os.environ.get(LOCK_ENV, "/tmp/ps_tpu_device.lock")
    try:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
        with contextlib.suppress(OSError):
            os.chmod(path, 0o666)  # defeat the creator's umask
        return fd
    except OSError:
        fallback = f"{path}.{os.getuid()}"
        return os.open(fallback, os.O_CREAT | os.O_RDWR, 0o666)


@contextlib.contextmanager
def device_lock(
    timeout_s: float = WAIT_ABOVE_LONGEST_HOLD_S, poll_s: float = 5.0
) -> Iterator[bool]:
    """Hold the device flock for the enclosed block.

    Yields True when the lock was acquired, False when the wait timed
    out (the block still runs — see module docstring) or when the
    parent already holds it (``PS_DEVICE_LOCK_HELD``)."""
    if os.environ.get(HELD_ENV):
        yield True
        return
    import fcntl

    fd = _open_lock_file()
    got = False
    t0 = time.monotonic()
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                got = True
                break
            except OSError as e:
                if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN,
                                   errno.EACCES):
                    # flock unsupported here (e.g. ENOLCK on NFS):
                    # exclusion is impossible — say so once, don't spin
                    print(
                        f"device_lock: flock unavailable ({e}); "
                        "proceeding without exclusion",
                        file=sys.stderr,
                    )
                    break
                if time.monotonic() - t0 >= timeout_s:
                    if timeout_s > 0:
                        print(
                            f"device_lock: holder outlived the "
                            f"{timeout_s:.0f}s wait (not a framework "
                            "process?); proceeding without exclusion",
                            file=sys.stderr,
                        )
                    break
                time.sleep(poll_s)
        yield got
    finally:
        try:
            if got:
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)


def held_env() -> dict:
    """Environment for children of a lock holder (no re-acquire)."""
    env = dict(os.environ)
    env[HELD_ENV] = "1"
    return env
