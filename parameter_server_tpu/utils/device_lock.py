"""Advisory inter-process lock for the (single) tunneled TPU device.

Two processes opening the tunneled backend concurrently wedge or fail
each other (one chip, one client at a time) — the realistic collision
is the evidence watcher (``script/onchip.py --watch``) holding the
device when an interactive ``bench.py`` run (or the round driver's)
starts. Both sides take this flock around device use: flock is
released by the kernel when the holder dies, so a crashed holder can
never leave a stale lock — a held lock always means a LIVE holder.

Watcher-side holds are bounded (each task child is killed by its
subprocess timeout, max 5400s), so a bench waiting
``WAIT_ABOVE_LONGEST_HOLD_S`` always outlasts the watcher. The
converse is NOT bounded — an interactive ``bench.py --real`` on a slow
link can legitimately hold for hours under a healthy progress
watchdog — so the two sides use different timeout policies: bench
proceeds after its bound (with a stderr disclosure; only another
bench can outlive it), while the watcher treats a timeout as "device
busy, defer" and never collides (see the callers).

The yielded :class:`LockResult` is truthy when the lock (or a
parent's) is held and carries a ``reason`` so callers can tell
"busy" (a live holder) from "unsupported" (flock impossible here —
exclusion cannot exist, proceed).

Children spawned BY a lock holder must not re-acquire — holders export
``PS_DEVICE_LOCK_HELD=1`` (via :func:`held_env`) and ``device_lock``
becomes a no-op under it.
"""

from __future__ import annotations

import contextlib
import errno
import os
import sys
import time
from typing import Iterator

LOCK_ENV = "PS_DEVICE_LOCK"
HELD_ENV = "PS_DEVICE_LOCK_HELD"

#: above the longest WATCHER-side hold (bench_real task timeout: 5400s)
WAIT_ABOVE_LONGEST_HOLD_S = 5700.0


class LockResult:
    """Truthy iff the device is exclusively ours (or a parent's).

    ``reason``: "acquired" | "held-by-parent" | "busy" (live holder
    outlasted the wait) | "unsupported" (flock impossible on this
    filesystem — no exclusion exists to wait for)."""

    def __init__(self, acquired: bool, reason: str):
        self.acquired = acquired
        self.reason = reason

    def __bool__(self) -> bool:
        return self.acquired

    def __repr__(self) -> str:
        return f"LockResult({self.acquired}, {self.reason!r})"


def _open_lock_file() -> int:
    """Open (creating if needed) the lock file. The shared /tmp path is
    chmod'd world-writable so a second user can take the same lock; if
    another user's umask already made it unwritable for us, fall back
    to a per-uid path (loses cross-user exclusion, never crashes the
    caller's JSON contract)."""
    path = os.environ.get(LOCK_ENV, "/tmp/ps_tpu_device.lock")
    try:
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o666)
        with contextlib.suppress(OSError):
            os.chmod(path, 0o666)  # defeat the creator's umask
        return fd
    except OSError:
        fallback = f"{path}.{os.getuid()}"
        return os.open(fallback, os.O_CREAT | os.O_RDWR, 0o666)


@contextlib.contextmanager
def device_lock(
    timeout_s: float = WAIT_ABOVE_LONGEST_HOLD_S, poll_s: float = 5.0
) -> Iterator[LockResult]:
    """Hold the device flock for the enclosed block.

    Yields a truthy :class:`LockResult` when the lock was acquired (or
    a parent holds it); falsy with ``reason`` "busy"/"unsupported"
    otherwise — the block still runs either way, callers choose their
    policy from the reason (see module docstring)."""
    if os.environ.get(HELD_ENV):
        yield LockResult(True, "held-by-parent")
        return
    import fcntl

    fd = _open_lock_file()
    res = LockResult(False, "busy")
    t0 = time.monotonic()
    warned_wait = False
    try:
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                res = LockResult(True, "acquired")
                break
            except OSError as e:
                if e.errno not in (errno.EWOULDBLOCK, errno.EAGAIN,
                                   errno.EACCES):
                    # flock unsupported here (e.g. ENOLCK on NFS):
                    # exclusion is impossible — say so once, don't spin
                    print(
                        f"device_lock: flock unavailable ({e}); "
                        "no exclusion possible",
                        file=sys.stderr,
                    )
                    res = LockResult(False, "unsupported")
                    break
                if time.monotonic() - t0 >= timeout_s:
                    if timeout_s > 0:
                        print(
                            f"device_lock: holder outlived the "
                            f"{timeout_s:.0f}s wait",
                            file=sys.stderr,
                        )
                    break
                if not warned_wait:
                    # a silent multi-minute block is indistinguishable
                    # from a wedge — say what we're doing, once
                    print(
                        "device_lock: device held by another process; "
                        f"waiting up to {timeout_s:.0f}s",
                        file=sys.stderr,
                    )
                    warned_wait = True
                time.sleep(poll_s)
        yield res
    finally:
        try:
            if res.acquired:
                fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)


def held_env() -> dict:
    """Environment for children of a lock holder (no re-acquire)."""
    env = dict(os.environ)
    env[HELD_ENV] = "1"
    return env
