"""Device-level tracing (§5 tracing/monitoring aux subsystem).

The reference's tracing story is host-side counters
(``src/util/resource_usage.h``, heartbeat/dashboard tables); on TPU the
equivalent visibility tool is an XLA device trace — per-op device
timelines, HBM traffic, and fusion boundaries — captured with
``jax.profiler`` and viewed in TensorBoard's profile plugin or
Perfetto. This module wraps it behind a no-op-on-failure surface so
profiling can be wired into production CLIs (LM ``--profile``) without
making the profiler a hard dependency of training.
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def device_trace(log_dir: str | None) -> Iterator[None]:
    """Capture a device trace of the enclosed block into ``log_dir``.

    Output is TensorBoard-profile/Perfetto format. ``None`` is a no-op,
    so callers can pass an optional CLI flag straight through. A
    profiler that fails to start (unsupported backend, double-start)
    degrades to a warning, never a crashed training run."""
    if not log_dir:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception as e:  # pragma: no cover - backend-dependent
        import warnings

        warnings.warn(f"device trace not started: {e!r}")
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover
            import warnings

            warnings.warn(f"device trace not stopped cleanly: {e!r}")


def annotate(name: str):
    """Named region inside a capture (shows as a track annotation).
    Usable as a context manager: ``with annotate("push"): ...``"""
    import jax

    return jax.profiler.TraceAnnotation(name)


# -- trace post-processing ---------------------------------------------------
#
# jax.profiler writes TensorBoard-profile artifacts; the
# ``*.trace.json.gz`` file inside is Chrome-trace JSON whose complete
# events carry the HLO op name (and, through jax.named_scope, our
# phase prefix) either in the event name or in args.name/args.tf_op.
# Summarizing it here turns a --profile capture into a self-contained
# breakdown table in the bench record — no TensorBoard needed on the
# capture host (the r3 verdict's "where does the step time go").

_PHASE_PREFIXES = (
    "ps_decode", "ps_pull", "ps_compute", "ps_push", "ps_update",
    "ps_metrics",
)


def _trace_files(log_dir: str) -> "list[str]":
    """Trace files of the NEWEST profiler run only: jax.profiler writes
    each capture under ``<dir>/plugins/profile/<timestamp>/``, and a
    reused dir (the watcher's fixed /tmp path) accumulates runs — mixing
    them would sum device time across captures."""
    import glob
    import os

    paths = {
        # dedup a side-by-side gunzipped copy of the same trace (key
        # without .gz); prefer the .gz original deterministically
        (p[:-3] if p.endswith(".gz") else p): p
        for pat in ("*.trace.json", "*.trace.json.gz")
        for p in glob.glob(
            os.path.join(log_dir, "**", pat), recursive=True
        )
    }
    if not paths:
        return []
    runs: dict = {}
    for p in paths.values():
        runs.setdefault(os.path.dirname(p), []).append(p)
    newest = max(runs, key=lambda d: os.path.getmtime(d))
    return sorted(runs[newest])


def _iter_trace_events(log_dir: str):
    """Yield (pid->process-name, (pid,tid)->thread-name, events) per
    trace file of the newest run. Chrome-trace JSON, maybe gzipped."""
    import gzip
    import json as _json

    for path in _trace_files(log_dir):
        try:
            if path.endswith(".gz"):
                with gzip.open(path, "rt", errors="replace") as f:
                    doc = _json.load(f)
            else:
                with open(path, errors="replace") as f:
                    doc = _json.load(f)
        except (OSError, ValueError):
            continue
        # both legal Chrome-trace top levels: object with traceEvents,
        # or the bare event array
        events = (
            doc if isinstance(doc, list) else doc.get("traceEvents")
        ) or []
        pnames: dict = {}
        tnames: dict = {}
        for ev in events:
            if not isinstance(ev, dict) or ev.get("ph") != "M":
                continue
            nm = (ev.get("args") or {}).get("name") or ""
            if ev.get("name") == "process_name":
                pnames[ev.get("pid")] = nm
            elif ev.get("name") == "thread_name":
                tnames[(ev.get("pid"), ev.get("tid"))] = nm
        yield pnames, tnames, events


def _device_op_keys(pnames: dict, tnames: dict):
    """(device_pids, keep(pid, tid)) — the device-op track filter shared
    by :func:`summarize_trace` and :func:`device_track_events`: pids
    whose process name looks like a device, and within them only
    op-level tids (prefer threads named "XLA Ops"; a device pid without
    one keeps its tids minus Module/Step aggregates, which cover the
    sum of their ops and would double everything)."""
    device_pids = {
        pid
        for pid, nm in pnames.items()
        if any(k in nm for k in ("XLA Ops", "TPU", "/device:", "Device"))
        and "host" not in nm.lower()
    }
    op_tids = {
        key
        for key, nm in tnames.items()
        if key[0] in device_pids and "XLA Ops" in nm
    }
    named_pids = {p for p, _ in op_tids}
    excluded = {
        key
        for key, nm in tnames.items()
        if key[0] in device_pids
        and any(k in nm for k in ("Module", "Step", "module"))
    }

    def keep(pid, tid) -> bool:
        if pid not in device_pids:
            return False
        key = (pid, tid)
        if pid in named_pids:
            return key in op_tids
        return key not in excluded

    return device_pids, keep


def device_track_events(
    log_dir: str,
    host_anchor: "float | None" = None,
    max_events: int = 4000,
) -> "list[dict]":
    """The newest capture's device-op complete events as span-sink-shaped
    dicts — the device track of a merged timeline.

    Each op becomes ``{"kind": "span", "name": "device.<op>", "thread":
    "device:<pid>", "t_wall": ..., "dur_s": ...}``, consumable by the
    same readers as host spans (telemetry/timeline.py export,
    telemetry/attribution.device_breakdown). Durations are exact trace
    truth; ABSOLUTE placement is best-effort — the profiler clock has
    no wall reference, so the track is shifted as a block to start at
    ``host_anchor`` (the host wall time of the profiled launch,
    bench.py phase_breakdown); nothing is clipped at the far end. Ops
    beyond ``max_events`` are dropped longest-kept (sorted by
    duration) and the truncation is visible as ``len() ==
    max_events``; never raises (result-path code)."""
    try:
        collected: "list[tuple[int, float, float, str]]" = []
        for pnames, tnames, events in _iter_trace_events(log_dir):
            _, keep = _device_op_keys(pnames, tnames)
            for ev in events:
                if not isinstance(ev, dict) or ev.get("ph") != "X":
                    continue
                if not keep(ev.get("pid"), ev.get("tid")):
                    continue
                dur = ev.get("dur")
                if not dur:
                    continue
                name = str(ev.get("name") or "?")[:80]
                collected.append(
                    (ev.get("pid"), float(ev.get("ts", 0.0)), float(dur), name)
                )
        if not collected:
            return []
        if len(collected) > max_events:
            collected = sorted(collected, key=lambda c: -c[2])[:max_events]
        t0_us = min(c[1] for c in collected)
        base = host_anchor if host_anchor is not None else 0.0
        out = [
            {
                "kind": "span",
                "name": f"device.{name}",
                "thread": f"device:{pid}",
                "t_wall": base + (ts - t0_us) / 1e6,
                "dur_s": dur / 1e6,
            }
            for pid, ts, dur, name in collected
        ]
        out.sort(key=lambda e: e["t_wall"])
        return out
    except Exception:  # pragma: no cover - defensive: result-path code
        return []


def _self_times(track_events: "list[dict]"):
    """Yield ``(event, self_us)`` for complete events of ONE trace
    track, where self_us is the event's duration minus the duration of
    child events nested inside it on the same track (Chrome-trace
    nesting: a child starts at/after the parent and ends at/before it).
    Sorting by (start, -duration) makes parents precede their children;
    a span stack then attributes each event's time to the innermost
    enclosing span, which is exactly per-op self time."""
    evs = sorted(
        track_events,
        key=lambda e: (e.get("ts", 0), -(e.get("dur") or 0)),
    )
    stack: list = []  # [event, end_ts, child_us]
    for ev in evs:
        ts = ev.get("ts", 0)
        dur = ev.get("dur") or 0
        while stack and ts >= stack[-1][1]:
            top_ev, _, child_us = stack.pop()
            yield top_ev, (top_ev.get("dur") or 0) - child_us
        if stack:
            stack[-1][2] += dur
        stack.append([ev, ts + dur, 0.0])
    while stack:
        top_ev, _, child_us = stack.pop()
        yield top_ev, (top_ev.get("dur") or 0) - child_us


def summarize_trace(
    log_dir: str, top: int = 12
) -> "dict | None":
    """Bucket device time in a captured trace by named-scope phase and
    by op, from the device ("XLA Ops"-style) tracks only.

    Returns ``{"device_ms": total, "phases": {phase: ms}, "top_ops":
    [{"name", "ms", "calls"}...]}`` or None when no parseable trace
    exists or no device-op track can be identified (counting host
    tracks would report wall-clock as device time). Only op-level
    tracks are summed — a device pid also carries "XLA Modules"/
    "Steps" spans that cover the sum of their ops, and including them
    would double device_ms. Within the op track, control-flow spans
    (``while``/``fusion`` parents) NEST their body ops as child events
    on the same track; each event is therefore credited only its SELF
    time (duration minus time covered by its children), so a scan
    wrapper no longer double-counts its body into a phantom "other"
    bucket. Never raises: result-path code."""
    try:
        phases: dict = {}
        ops: dict = {}
        total_us = 0.0
        seen = False
        all_device_pids: set = set()
        for pnames, tnames, events in _iter_trace_events(log_dir):
            # op-level device tracks only (the shared filter: prefer
            # "XLA Ops"-named threads, exclude Module/Step aggregates)
            device_pids, keep = _device_op_keys(pnames, tnames)
            if not device_pids:
                continue  # no device track in this file
            all_device_pids.update(device_pids)
            tracks: dict = {}
            for ev in events:
                if not isinstance(ev, dict) or ev.get("ph") != "X":
                    continue
                pid = ev.get("pid")
                if not keep(pid, ev.get("tid")):
                    continue
                dur = ev.get("dur")
                if not dur:
                    continue
                tracks.setdefault((pid, ev.get("tid")), []).append(ev)
            for track_events in tracks.values():
                for ev, self_us in _self_times(track_events):
                    if self_us <= 0:
                        continue
                    args = ev.get("args") or {}
                    label = (
                        args.get("name")
                        or args.get("tf_op")
                        or args.get("long_name")
                        or ev.get("name")
                        or "?"
                    )
                    label = str(label)
                    seen = True
                    total_us += self_us
                    phase = next(
                        (p for p in _PHASE_PREFIXES if p in label),
                        "other",
                    )
                    phases[phase] = phases.get(phase, 0.0) + self_us
                    short = str(ev.get("name") or label)[:80]
                    rec = ops.setdefault(short, [0.0, 0])
                    rec[0] += self_us
                    rec[1] += 1
        if not seen:
            return None
        out = {
            # aggregate op-time summed over ALL device tracks (one per
            # core on a multi-core capture) — core-time, not step
            # wall-clock; device_tracks discloses the multiplier
            "device_ms": round(total_us / 1e3, 3),
            "device_tracks": len(all_device_pids),
            "phases": {
                k: round(v / 1e3, 3)
                for k, v in sorted(
                    phases.items(), key=lambda kv: -kv[1]
                )
            },
            "top_ops": [
                {"name": k, "ms": round(v[0] / 1e3, 3), "calls": v[1]}
                for k, v in sorted(
                    ops.items(), key=lambda kv: -kv[1][0]
                )[:top]
            ],
        }
        return out
    except Exception:  # pragma: no cover - defensive: result-path code
        return None
