"""Device-level tracing (§5 tracing/monitoring aux subsystem).

The reference's tracing story is host-side counters
(``src/util/resource_usage.h``, heartbeat/dashboard tables); on TPU the
equivalent visibility tool is an XLA device trace — per-op device
timelines, HBM traffic, and fusion boundaries — captured with
``jax.profiler`` and viewed in TensorBoard's profile plugin or
Perfetto. This module wraps it behind a no-op-on-failure surface so
profiling can be wired into production CLIs (LM ``--profile``) without
making the profiler a hard dependency of training.
"""

from __future__ import annotations

import contextlib
from typing import Iterator


@contextlib.contextmanager
def device_trace(log_dir: str | None) -> Iterator[None]:
    """Capture a device trace of the enclosed block into ``log_dir``.

    Output is TensorBoard-profile/Perfetto format. ``None`` is a no-op,
    so callers can pass an optional CLI flag straight through. A
    profiler that fails to start (unsupported backend, double-start)
    degrades to a warning, never a crashed training run."""
    if not log_dir:
        yield
        return
    import jax

    try:
        jax.profiler.start_trace(log_dir)
    except Exception as e:  # pragma: no cover - backend-dependent
        import warnings

        warnings.warn(f"device trace not started: {e!r}")
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # pragma: no cover
            import warnings

            warnings.warn(f"device trace not stopped cleanly: {e!r}")


def annotate(name: str):
    """Named region inside a capture (shows as a track annotation).
    Usable as a context manager: ``with annotate("push"): ...``"""
    import jax

    return jax.profiler.TraceAnnotation(name)
