"""Assignment operators for push/merge (ref ``src/util/assign_op.h``).

The reference enumerates ASSIGN/PLUS/MINUS/TIMES/DIVIDE/AND/OR/XOR as
``AssignOpType`` and applies them in ``AssignFunc``; pushes default to PLUS
(gradient aggregation) and pulls to ASSIGN.
"""

from __future__ import annotations

import enum

import numpy as np


class AssignOp(enum.Enum):
    ASSIGN = "assign"
    PLUS = "plus"
    MINUS = "minus"
    TIMES = "times"
    DIVIDE = "divide"
    AND = "and"
    OR = "or"
    XOR = "xor"


def apply_op(op: AssignOp, dst, src):
    if op is AssignOp.ASSIGN:
        return src
    if op is AssignOp.PLUS:
        return dst + src
    if op is AssignOp.MINUS:
        return dst - src
    if op is AssignOp.TIMES:
        return dst * src
    if op is AssignOp.DIVIDE:
        return dst / src
    if op is AssignOp.AND:
        return np.logical_and(dst, src)
    if op is AssignOp.OR:
        return np.logical_or(dst, src)
    if op is AssignOp.XOR:
        return np.logical_xor(dst, src)
    raise ValueError(f"unknown op {op}")
