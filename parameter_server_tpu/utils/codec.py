"""Wire payload codec — the reference's snappy message compression
(``src/util/shared_array_inl.h:245`` CompressTo/UncompressFrom, applied
per-SArray by ``src/filter/compressing.h``).

The hot path is the native LZ codec in ``cpp/psnative.cc`` (LZ4-style:
greedy matcher, 16-bit offsets, skip acceleration — snappy-class
design; measured 3-40x zlib-1 compress and 4-250x decompress across
representative payloads on this host). When
the native library is unavailable the fallback is zlib level 1. Frames
are self-describing (one header byte): a zlib/raw sender always decodes
on a native receiver, but an _LZ frame needs the native lib on the
receiving side too — deployments mixing native and native-less hosts
must ship the lib everywhere (it builds from cpp/ with g++ alone) or
the native-less receiver raises ValueError on LZ frames. Incompressible
payloads are stored raw rather than expanded.
"""

from __future__ import annotations

import ctypes
import zlib

import numpy as np

from ..cpp import native

_RAW = 0x00  # header byte: stored uncompressed
_LZ = 0x01   # native LZ block
_ZLIB = 0x02  # zlib (fallback path)


def compress(data: bytes) -> bytes:
    """Compress ``data`` into a self-describing frame."""
    lib = native()
    n = len(data)
    if n == 0:
        return bytes([_RAW])
    if lib is not None:
        src = np.frombuffer(data, np.uint8)
        cap = int(lib.ps_lz_max_compressed(n))
        dst = np.empty(cap, np.uint8)
        got = lib.ps_lz_compress(
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), n,
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
        )
        if 0 <= got < n:
            return bytes([_LZ]) + dst[:got].tobytes()
        return bytes([_RAW]) + data
    blob = zlib.compress(data, level=1)
    if len(blob) < n:
        return bytes([_ZLIB]) + blob
    return bytes([_RAW]) + data


def decompress(
    frame: bytes, max_size: int = 1 << 31, expected_size: int = None
) -> bytes:
    """Decode a frame from :func:`compress`. Raises ``ValueError`` on a
    malformed frame (wire payloads are untrusted). ``expected_size`` (the
    decoded byte count, when the caller's metadata implies it — the
    compressing filter's dtype/shape) sizes the output buffer exactly so
    the native decode is single-pass; without it the buffer grows
    geometrically."""
    if len(frame) < 1:
        raise ValueError("empty codec frame")
    tag, body = frame[0], frame[1:]
    if tag == _RAW:
        return bytes(body)
    if tag == _ZLIB:
        # decompressobj + max_length, not zlib.decompress: a hostile
        # frame header can claim a multi-GB expansion (the >4GB-frame
        # edge) and the one-shot API would allocate it before failing —
        # the bound must hold BEFORE the bytes exist
        try:
            d = zlib.decompressobj()
            out = d.decompress(body, max_size)
            if d.unconsumed_tail:
                raise ValueError("zlib frame output exceeds max_size")
            if not d.eof:
                # decompressobj (unlike the one-shot API) returns
                # partial output on a truncated stream — the untrusted-
                # frame contract requires a raise, never silent bytes
                raise ValueError("truncated zlib frame")
            if d.unused_data:
                raise ValueError("trailing garbage after zlib frame")
            return out
        except zlib.error as e:
            raise ValueError(f"bad zlib frame: {e}") from e
    if tag == _LZ:
        lib = native()
        if lib is None:
            raise ValueError("native LZ frame but libpsnative unavailable")
        src = np.frombuffer(body, np.uint8)
        # the frame doesn't carry the decoded size (decode must stand
        # alone); callers that know it pass expected_size for a
        # single-pass decode, else grow geometrically
        if expected_size is not None:
            cap = min(max(64, int(expected_size)), max_size)
        else:
            cap = max(64, 4 * len(body))
        while True:
            dst = np.empty(cap, np.uint8)
            got = lib.ps_lz_decompress(
                src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                len(body),
                dst.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
            )
            if got >= 0:
                return dst[:got].tobytes()
            if got == -1:
                raise ValueError("malformed LZ frame")
            if cap >= max_size:  # got == -2: needs more output space
                raise ValueError("LZ frame output exceeds max_size")
            cap = min(cap * 4, max_size)
    raise ValueError(f"unknown codec tag {tag}")
