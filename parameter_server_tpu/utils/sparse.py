"""Sparse example batches.

TPU-native counterpart of the reference's matrix stack
(``src/util/matrix.h``, ``sparse_matrix.h``, ``dense_matrix.h``): row-major
CSR batches of examples on the host, plus fixed-shape device encodings.

Where the reference hands Eigen a CSR and loops, TPU kernels need *static
shapes*. The device format here is a padded COO/"row-block CSR": a batch is
``(row_ids[nnz_pad], col_ids[nnz_pad], values[nnz_pad])`` padded to a fixed
nnz budget, with padding rows pointed at a sentinel column whose weight is
pinned to zero. Gathers/segment-sums over this layout tile cleanly onto the
VPU/MXU, and every minibatch compiles once.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass
class SparseBatch:
    """A minibatch of sparse examples (host side, CSR).

    Mirrors the role of ``PS::SparseMatrix<I,V>`` (ref sparse_matrix.h) plus
    the label vector: ``y`` is ``[n]``, CSR arrays describe an ``n x p``
    feature matrix. ``binary`` marks 0/1 features stored without values
    (ref sparse_matrix.h ``binary()`` fast path).
    """

    y: np.ndarray  # [n] float32, labels in {-1, +1} (or regression targets)
    indptr: np.ndarray  # [n+1] int64
    indices: np.ndarray  # [nnz] int64 — feature keys (global or localized)
    values: Optional[np.ndarray] = None  # [nnz] float32, None if binary
    num_cols: Optional[int] = None  # p; None = max(indices)+1
    # per-entry feature-group ids (ref Example proto Slot.id,
    # data/proto/example.proto) — load-bearing for formats whose keys don't
    # encode the group (criteo's global hash keys); SlotReader groups by
    # these when present
    slot_ids: Optional[np.ndarray] = None  # [nnz] int32 or None

    @property
    def n(self) -> int:
        return len(self.y)

    @property
    def nnz(self) -> int:
        return len(self.indices)

    @property
    def binary(self) -> bool:
        return self.values is None

    @property
    def cols(self) -> int:
        if self.num_cols is not None:
            return self.num_cols
        return int(self.indices.max()) + 1 if self.nnz else 0

    def row_ids(self) -> np.ndarray:
        """Expand indptr to per-nnz row ids (COO rows)."""
        return np.repeat(
            np.arange(self.n, dtype=np.int32), np.diff(self.indptr).astype(np.int64)
        )

    def value_array(self) -> np.ndarray:
        if self.values is not None:
            return self.values
        return np.ones(self.nnz, dtype=np.float32)

    def to_dense(self) -> np.ndarray:
        out = np.zeros((self.n, self.cols), dtype=np.float32)
        rows = self.row_ids()
        np.add.at(out, (rows, self.indices.astype(np.int64)), self.value_array())
        return out

    def to_csc(self) -> "SparseCols":
        """Column-major view for block coordinate descent (ref bcd/darlin use
        colMajor CSC; sparse_matrix.h toColMajor)."""
        order = np.argsort(self.indices, kind="stable")
        cols = self.indices[order]
        rows = self.row_ids()[order]
        vals = None if self.binary else self.values[order]
        p = self.cols
        colptr = np.zeros(p + 1, dtype=np.int64)
        np.add.at(colptr, cols.astype(np.int64) + 1, 1)
        np.cumsum(colptr, out=colptr)
        return SparseCols(
            colptr=colptr, row_ids=rows.astype(np.int32), values=vals, num_rows=self.n
        )

    def slice_rows(self, begin: int, end: int) -> "SparseBatch":
        lo, hi = self.indptr[begin], self.indptr[end]
        return SparseBatch(
            y=self.y[begin:end],
            indptr=(self.indptr[begin : end + 1] - lo),
            indices=self.indices[lo:hi],
            values=None if self.binary else self.values[lo:hi],
            num_cols=self.num_cols,
            slot_ids=None if self.slot_ids is None else self.slot_ids[lo:hi],
        )

    def pad_device(
        self, nnz_pad: int, rows_pad: Optional[int] = None, pad_col: Optional[int] = None
    ) -> "PaddedBatch":
        """Encode for device: COO padded to ``nnz_pad`` entries / ``rows_pad`` rows.

        Padding entries get ``row=rows_pad-1`` is wrong (would pollute that
        example) — instead they point at ``pad_col`` (default: ``cols``, one
        extra sentinel column) with value 0, and a valid-row mask is emitted.
        """
        if rows_pad is None:
            rows_pad = self.n
        if self.nnz > nnz_pad:
            raise ValueError(f"nnz {self.nnz} exceeds budget {nnz_pad}")
        if self.n > rows_pad:
            raise ValueError(f"rows {self.n} exceed budget {rows_pad}")
        if pad_col is None:
            pad_col = self.cols
        rows = np.zeros(nnz_pad, dtype=np.int32)
        cols = np.full(nnz_pad, pad_col, dtype=np.int32)
        vals = np.zeros(nnz_pad, dtype=np.float32)
        rows[: self.nnz] = self.row_ids()
        cols[: self.nnz] = self.indices
        vals[: self.nnz] = self.value_array()
        y = np.zeros(rows_pad, dtype=np.float32)
        y[: self.n] = self.y
        mask = np.zeros(rows_pad, dtype=np.float32)
        mask[: self.n] = 1.0
        return PaddedBatch(y=y, rows=rows, cols=cols, vals=vals, row_mask=mask)


@dataclasses.dataclass
class SparseCols:
    """CSC view: per-column row lists (ref sparse_matrix.h colMajor)."""

    colptr: np.ndarray  # [p+1]
    row_ids: np.ndarray  # [nnz] int32
    values: Optional[np.ndarray]  # [nnz] or None if binary
    num_rows: int

    @property
    def cols(self) -> int:
        return len(self.colptr) - 1

    def col(self, j: int):
        lo, hi = self.colptr[j], self.colptr[j + 1]
        v = None if self.values is None else self.values[lo:hi]
        return self.row_ids[lo:hi], v


@dataclasses.dataclass
class PaddedBatch:
    """Static-shape device encoding of a SparseBatch (COO + sentinel padding)."""

    y: np.ndarray  # [rows_pad]
    rows: np.ndarray  # [nnz_pad] int32
    cols: np.ndarray  # [nnz_pad] int32 — padding points at sentinel column
    vals: np.ndarray  # [nnz_pad] float32 — padding is 0
    row_mask: np.ndarray  # [rows_pad] float32 1=real example

    @property
    def rows_pad(self) -> int:
        return len(self.y)

    @property
    def nnz_pad(self) -> int:
        return len(self.rows)


def from_dense(x: np.ndarray, y: np.ndarray) -> SparseBatch:
    n, p = x.shape
    indptr = [0]
    indices = []
    values = []
    for i in range(n):
        (nz,) = np.nonzero(x[i])
        indices.append(nz)
        values.append(x[i, nz])
        indptr.append(indptr[-1] + len(nz))
    return SparseBatch(
        y=y.astype(np.float32),
        indptr=np.asarray(indptr, dtype=np.int64),
        indices=np.concatenate(indices).astype(np.int64) if indices else np.zeros(0, np.int64),
        values=np.concatenate(values).astype(np.float32) if values else np.zeros(0, np.float32),
        num_cols=p,
    )


def random_sparse(
    n: int,
    p: int,
    nnz_per_row: int,
    seed: int = 0,
    binary: bool = False,
    w_true: Optional[np.ndarray] = None,
) -> SparseBatch:
    """Synthetic sparse logistic data (test/bench helper).

    Plays the role of the reference's generated test matrices in
    ``src/test/sparse_matrix_test.cc`` and gives learners a ground-truth
    weight vector to recover.
    """
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, p, size=(n, nnz_per_row), dtype=np.int64)
    # rows may contain duplicate column ids (they merge additively downstream);
    # consumers must not assume unique columns per row
    vals = (
        np.ones((n, nnz_per_row), dtype=np.float32)
        if binary
        else rng.normal(size=(n, nnz_per_row)).astype(np.float32)
    )
    if w_true is None:
        w_true = (rng.normal(size=p) * (rng.random(p) < 0.1)).astype(np.float32)
    logits = (vals * w_true[idx]).sum(axis=1)
    yprob = 1.0 / (1.0 + np.exp(-logits))
    y = np.where(rng.random(n) < yprob, 1.0, -1.0).astype(np.float32)
    indptr = np.arange(0, (n + 1) * nnz_per_row, nnz_per_row, dtype=np.int64)
    return SparseBatch(
        y=y,
        indptr=indptr,
        indices=idx.reshape(-1),
        values=None if binary else vals.reshape(-1),
        num_cols=p,
    )
