"""Deadlines, retries, jittered exponential backoff.

The chaos plane's second half (doc/ROBUSTNESS.md): fault injection
proves failures HAPPEN; deadline/retry policy decides what the caller
does about them. The reference's bounded-delay machinery assumes every
dependency eventually finishes — under real faults "eventually" needs a
number, and a blocked caller needs a diagnosis, not a hang. This module
is the one home of that policy:

- :class:`DeadlineExceeded` — the explicit deadline miss. Subclasses
  ``TimeoutError`` so existing ``except TimeoutError`` callers keep
  working, but carries the operation name and budget for diagnostics.
- :class:`RetryPolicy` — immutable retry/backoff parameters (attempts,
  exponential backoff with a seeded jitter, optional overall deadline).
- :func:`call_with_retry` — run a callable under a policy.
- :class:`Deadline` — a countdown budget to thread through multi-step
  waits (``Executor.wait_all(timeout=...)`` uses it).

Applied at: the executor wait path (``Executor.wait(timeout=)`` raises
a diagnostic :class:`DeadlineExceeded` naming the wedged timestamp and
its unsatisfied dependencies), serving ticket resolution
(``Ticket.result`` / ``PullTicket.result``), and recovery handlers
(``RecoveryCoordinator`` retries each handler under a policy before
counting ``ps_recovery_handler_failures_total``).
"""

from __future__ import annotations

import dataclasses
import random
import time
from typing import Callable, Optional, Tuple, Type


class DeadlineExceeded(TimeoutError):
    """An operation missed its deadline.

    ``op`` names the operation (e.g. ``"executor:store wait(42)"``),
    ``deadline_s`` the budget that was exceeded. A TimeoutError
    subclass: callers that only care that time ran out need no code
    change; callers that diagnose get the message and fields.
    """

    def __init__(self, message: str, *, op: str = "",
                 deadline_s: Optional[float] = None):
        super().__init__(message)
        self.op = op
        self.deadline_s = deadline_s


class Deadline:
    """A countdown budget: construct once, ask ``remaining()`` at each
    blocking step. ``None`` budget = infinite (every query says so)."""

    __slots__ = ("_t_end", "_clock", "budget_s")

    def __init__(self, budget_s: Optional[float],
                 clock: Callable[[], float] = time.monotonic):
        self.budget_s = budget_s
        self._clock = clock
        self._t_end = None if budget_s is None else clock() + budget_s

    def remaining(self) -> Optional[float]:
        """Seconds left (may be <= 0), or None for no deadline."""
        if self._t_end is None:
            return None
        return self._t_end - self._clock()

    def expired(self) -> bool:
        r = self.remaining()
        return r is not None and r <= 0


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Immutable retry/backoff parameters.

    Backoff for attempt ``a`` (0-based) is
    ``min(max_delay_s, base_delay_s * multiplier**a)`` scaled by a
    uniform jitter in ``[1 - jitter, 1 + jitter]`` — jitter is drawn
    from a SEEDED generator per :func:`call_with_retry` call, so two
    runs of the same drill back off identically (the determinism
    contract every chaos-plane component keeps). ``deadline_s`` bounds
    the whole attempt sequence: a retry whose backoff would overrun it
    raises :class:`DeadlineExceeded` immediately instead of sleeping
    into a guaranteed miss.
    """

    max_attempts: int = 3
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    multiplier: float = 2.0
    jitter: float = 0.1
    deadline_s: Optional[float] = None
    retry_on: Tuple[Type[BaseException], ...] = (Exception,)

    def backoff_s(self, attempt: int, rng: random.Random) -> float:
        d = min(self.max_delay_s, self.base_delay_s * self.multiplier ** attempt)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, d)


#: no-retry policy (one attempt, fail fast) for callers that want the
#: deadline bookkeeping without the retries
NO_RETRY = RetryPolicy(max_attempts=1)


def call_with_retry(
    fn: Callable,
    policy: RetryPolicy = RetryPolicy(),
    *,
    op: str = "operation",
    seed: int = 0,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    on_retry: Optional[Callable[[int, BaseException, float], None]] = None,
):
    """Run ``fn()`` under ``policy``; returns its value.

    Exceptions outside ``policy.retry_on`` propagate immediately. The
    final attempt's exception propagates unwrapped (callers see the
    real failure, with the retry history only in ``on_retry``).
    ``on_retry(attempt, error, backoff_s)`` fires before each sleep —
    telemetry/log hook, must not raise.
    """
    rng = random.Random(seed)
    deadline = Deadline(policy.deadline_s, clock)
    for attempt in range(max(1, policy.max_attempts)):
        try:
            return fn()
        except policy.retry_on as e:
            if attempt + 1 >= max(1, policy.max_attempts):
                raise
            delay = policy.backoff_s(attempt, rng)
            remaining = deadline.remaining()
            if remaining is not None and delay >= remaining:
                raise DeadlineExceeded(
                    f"{op}: attempt {attempt + 1} failed "
                    f"({type(e).__name__}: {e}) and the {delay:.3f}s "
                    f"backoff would overrun the {policy.deadline_s}s "
                    "retry deadline",
                    op=op, deadline_s=policy.deadline_s,
                ) from e
            if on_retry is not None:
                on_retry(attempt, e, delay)
            sleep(delay)
    raise AssertionError("unreachable")  # pragma: no cover
