"""Probabilistic sketches: bloom filter and count-min.

TPU-native counterparts of ``src/util/bloom_filter.h``,
``block_bloom_filter.h``, ``countmin.h`` and ``sketch.h``. Vectorized NumPy:
these run on host in the data pipeline (tail-feature filtering), exactly
where the reference runs them (MinibatchReader, FreqencyFilter).
"""

from __future__ import annotations

import numpy as np

from .murmur import murmur64_np


def _hashes(keys: np.ndarray, num_hash: int, mod: int, seed0: int = 0x9E3779B9) -> np.ndarray:
    """[num_hash, n] hashed positions via double hashing (Kirsch–Mitzenmacher)."""
    keys = np.asarray(keys, dtype=np.uint64)
    h1 = murmur64_np(keys, np.uint64(seed0))
    h2 = murmur64_np(keys, np.uint64(0xC2B2AE3D27D4EB4F)) | np.uint64(1)
    i = np.arange(num_hash, dtype=np.uint64)[:, None]
    return ((h1[None, :] + i * h2[None, :]) % np.uint64(mod)).astype(np.int64)


class BloomFilter:
    """Standard bloom filter (ref bloom_filter.h: insert/query by key)."""

    def __init__(self, num_bits: int = 1 << 20, num_hash: int = 2):
        self.num_bits = int(num_bits)
        self.num_hash = int(num_hash)
        self.bits = np.zeros(self.num_bits, dtype=bool)

    def insert(self, keys: np.ndarray) -> None:
        pos = _hashes(keys, self.num_hash, self.num_bits)
        self.bits[pos.reshape(-1)] = True

    def query(self, keys: np.ndarray) -> np.ndarray:
        pos = _hashes(keys, self.num_hash, self.num_bits)
        return self.bits[pos].all(axis=0)

    def __contains__(self, key: int) -> bool:
        return bool(self.query(np.asarray([key]))[0])


class CountMin:
    """Count-min sketch with saturating uint32 counters (ref countmin.h).

    ``insert(keys, counts)`` adds capped counts; ``query`` returns the
    min over hash rows — an upper-biased frequency estimate used by the
    tail-feature ``FreqencyFilter``.
    """

    def __init__(self, n: int = 1 << 20, k: int = 2, cap: int = 255):
        self.n = int(n)
        self.k = int(k)
        self.cap = int(cap)
        self.data = np.zeros((self.k, self.n), dtype=np.uint32)

    def insert(self, keys: np.ndarray, counts: np.ndarray | int = 1) -> None:
        keys = np.asarray(keys, dtype=np.uint64)
        counts = np.broadcast_to(np.asarray(counts, dtype=np.uint32), keys.shape)
        pos = _hashes(keys, self.k, self.n)
        for r in range(self.k):
            # scatter-add with saturation; np.add.at handles duplicate pos.
            # Clamp only the touched buckets, not the whole 2^20-entry row.
            row = self.data[r]
            np.add.at(row, pos[r], counts)
            touched = pos[r]
            row[touched] = np.minimum(row[touched], self.cap)

    def query(self, keys: np.ndarray) -> np.ndarray:
        pos = _hashes(np.asarray(keys, dtype=np.uint64), self.k, self.n)
        est = self.data[0][pos[0]]
        for r in range(1, self.k):
            est = np.minimum(est, self.data[r][pos[r]])
        return est

    def clear(self) -> None:
        self.data.fill(0)


class DecayCountMin(CountMin):
    """Count-min with windowed exponential decay — the key-heat sketch
    of the learning truth plane (telemetry/learning.py).

    Same CM machinery the ingest tail filter rides, but the counters
    track the RECENT stream instead of lifetime totals: ``decay()``
    halves every counter, so calling it once per window gives every
    observation a half-life of one window. Heat ranking only needs
    relative magnitudes, so the integer floor-halving bias (a stuck 1
    decays to 0) is immaterial — and exactly what lets a cold key fall
    out of the top-k. The cap is raised from the tail filter's 255
    (there the question is "below freq?"; here hot keys must keep
    separating long past 255).
    """

    def __init__(self, n: int = 1 << 16, k: int = 2, cap: int = 1 << 30):
        super().__init__(n=n, k=k, cap=cap)

    def decay(self) -> None:
        """Advance one window: halve every counter in place."""
        self.data >>= 1
