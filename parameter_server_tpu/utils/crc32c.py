"""CRC32C (Castagnoli) checksums (ref ``src/util/crc32c.{h,cc}``).

Used for recordio framing and key-caching signatures. Table-driven Python
with optional C++ fast path (``cpp/libpsnative``); identical polynomial
(0x82F63B78) and masking scheme to the reference so signatures are stable.
"""

from __future__ import annotations

import numpy as np

_POLY = 0x82F63B78


def _make_table() -> np.ndarray:
    tbl = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (_POLY if (c & 1) else 0)
        tbl[i] = c
    return tbl


_TABLE = _make_table()
_MASK_DELTA = 0xA282EAD8


def value(data: bytes | np.ndarray) -> int:
    """CRC32C of a byte string (ref crc32c::Value).

    Uses the C++ slicing-by-8 implementation in ``cpp/libpsnative`` when
    available; the pure-Python loop is the portability fallback.
    """
    raw = data.tobytes() if isinstance(data, np.ndarray) else bytes(data)
    from ..cpp import native

    lib = native()
    if lib is not None:
        import ctypes

        buf = (ctypes.c_uint8 * len(raw)).from_buffer_copy(raw) if raw else (ctypes.c_uint8 * 1)()
        return int(lib.ps_crc32c(buf, len(raw)))
    tbl = _TABLE
    c = 0xFFFFFFFF
    for b in raw:
        c = (c >> 8) ^ int(tbl[(c ^ b) & 0xFF])
    return (c ^ 0xFFFFFFFF) & 0xFFFFFFFF


def masked(crc: int) -> int:
    """Rotate+offset masking for storing CRCs of CRCs (ref crc32c::Mask)."""
    return (((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF


def unmask(m: int) -> int:
    rot = (m - _MASK_DELTA) & 0xFFFFFFFF
    return ((rot >> 17) | (rot << 15)) & 0xFFFFFFFF


def array_signature(arr: np.ndarray, max_len: int = 2048) -> int:
    """Signature of a (prefix of a) key array — role of the key-caching
    filter's ``crc32c::Value(key.data(), min(size, max_sig_len))``."""
    view = np.ascontiguousarray(arr).view(np.uint8)
    return value(view[: max_len].tobytes())
