"""64-bit mixing hash (role of ``src/util/murmurhash3.{h,cc}``).

The reference uses MurmurHash3 to hash feature keys into sketches. We use a
splitmix64-style finalizer — same statistical quality, fully vectorizable in
NumPy, and trivially portable to the C++ fast path in ``cpp/``.
"""

from __future__ import annotations

import numpy as np

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def murmur64_np(keys: np.ndarray, seed: np.uint64 = np.uint64(0)) -> np.ndarray:
    """Vectorized 64-bit finalizer hash over a uint64 array.

    Large arrays route through the C++ ``ps_mix64_array`` (same function,
    ~6x faster than the numpy temporaries); results are identical.
    """
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    if keys.size >= 4096:
        from ..cpp import native

        lib = native()
        if lib is not None:
            import ctypes

            out = np.empty_like(keys)
            lib.ps_mix64_array(
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                keys.size,
                ctypes.c_uint64(int(seed)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            )
            return out
    with np.errstate(over="ignore"):
        z = keys + seed + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * _M1
        z = (z ^ (z >> np.uint64(27))) * _M2
        return z ^ (z >> np.uint64(31))


def murmur64(key: int, seed: int = 0) -> int:
    return int(murmur64_np(np.asarray([key], dtype=np.uint64), np.uint64(seed))[0])


def hash_slots(keys: np.ndarray, num_slots: int, seed: int = 0) -> np.ndarray:
    """Hash keys into ``[0, num_slots)`` as int32 — the hashed-directory hot
    path (KeyDirectory.slots). One fused C++ pass when available; bit-exact
    NumPy fallback otherwise, so slot assignment never depends on batch size
    or library availability."""
    keys = np.asarray(keys)
    if keys.dtype == np.int64 and keys.flags.c_contiguous:
        keys = keys.view(np.uint64)  # same bits, no copy
    else:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
    if keys.size >= 4096:
        from ..cpp import native

        lib = native()
        if lib is not None:
            import ctypes

            out = np.empty(keys.size, np.int32)
            lib.ps_hash_slots(
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                keys.size,
                ctypes.c_uint64(seed),
                ctypes.c_uint64(num_slots),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            return out.reshape(keys.shape)
    h = murmur64_np(keys, np.uint64(seed))
    if num_slots & (num_slots - 1) == 0:
        # pow2 table: bitmask beats uint64 modulo by ~5x on host
        return (h & np.uint64(num_slots - 1)).astype(np.int32)
    return (h % np.uint64(num_slots)).astype(np.int32)


_M64 = (1 << 64) - 1


def _rotl64(x: int, r: int) -> int:
    return ((x << r) | (x >> (64 - r))) & _M64


def _fmix64(k: int) -> int:
    k ^= k >> 33
    k = (k * 0xFF51AFD7ED558CCD) & _M64
    k ^= k >> 33
    k = (k * 0xC4CEB9FE1A85EC53) & _M64
    k ^= k >> 33
    return k


def murmur3_x64_128(data: bytes, seed: int = 0) -> tuple:
    """Real MurmurHash3 x64 128-bit (the reference's util/murmurhash3.cc;
    criteo categorical keys are ``h[0] ^ h[1]`` with seed 512927377).
    Routes through the C++ library when available; the pure-Python path is
    bit-exact (cross-validated against the reference implementation)."""
    from ..cpp import native

    lib = native()
    if lib is not None:
        import ctypes

        out = (ctypes.c_uint64 * 2)()
        lib.ps_murmur3_x64_128(data, len(data), ctypes.c_uint32(seed), out)
        return int(out[0]), int(out[1])
    c1, c2 = 0x87C37B91114253D5, 0x4CF5AD432745937F
    h1 = h2 = seed & _M64
    n = len(data)
    nblocks = n // 16
    for i in range(nblocks):
        k1 = int.from_bytes(data[i * 16 : i * 16 + 8], "little")
        k2 = int.from_bytes(data[i * 16 + 8 : i * 16 + 16], "little")
        k1 = (k1 * c1) & _M64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * c2) & _M64
        h1 ^= k1
        h1 = _rotl64(h1, 27)
        h1 = (h1 + h2) & _M64
        h1 = (h1 * 5 + 0x52DCE729) & _M64
        k2 = (k2 * c2) & _M64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * c1) & _M64
        h2 ^= k2
        h2 = _rotl64(h2, 31)
        h2 = (h2 + h1) & _M64
        h2 = (h2 * 5 + 0x38495AB5) & _M64
    tail = data[nblocks * 16 :]
    k1 = k2 = 0
    if len(tail) > 8:
        k2 = int.from_bytes(tail[8:], "little")
        k2 = (k2 * c2) & _M64
        k2 = _rotl64(k2, 33)
        k2 = (k2 * c1) & _M64
        h2 ^= k2
    if tail:
        k1 = int.from_bytes(tail[:8], "little")
        k1 = (k1 * c1) & _M64
        k1 = _rotl64(k1, 31)
        k1 = (k1 * c2) & _M64
        h1 ^= k1
    h1 ^= n
    h2 ^= n
    h1 = (h1 + h2) & _M64
    h2 = (h2 + h1) & _M64
    h1 = _fmix64(h1)
    h2 = _fmix64(h2)
    h1 = (h1 + h2) & _M64
    h2 = (h2 + h1) & _M64
    return h1, h2
