"""64-bit mixing hash (role of ``src/util/murmurhash3.{h,cc}``).

The reference uses MurmurHash3 to hash feature keys into sketches. We use a
splitmix64-style finalizer — same statistical quality, fully vectorizable in
NumPy, and trivially portable to the C++ fast path in ``cpp/``.
"""

from __future__ import annotations

import numpy as np

_M1 = np.uint64(0xBF58476D1CE4E5B9)
_M2 = np.uint64(0x94D049BB133111EB)


def murmur64_np(keys: np.ndarray, seed: np.uint64 = np.uint64(0)) -> np.ndarray:
    """Vectorized 64-bit finalizer hash over a uint64 array.

    Large arrays route through the C++ ``ps_mix64_array`` (same function,
    ~6x faster than the numpy temporaries); results are identical.
    """
    keys = np.ascontiguousarray(keys, dtype=np.uint64)
    if keys.size >= 4096:
        from ..cpp import native

        lib = native()
        if lib is not None:
            import ctypes

            out = np.empty_like(keys)
            lib.ps_mix64_array(
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                keys.size,
                ctypes.c_uint64(int(seed)),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            )
            return out
    with np.errstate(over="ignore"):
        z = keys + seed + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * _M1
        z = (z ^ (z >> np.uint64(27))) * _M2
        return z ^ (z >> np.uint64(31))


def murmur64(key: int, seed: int = 0) -> int:
    return int(murmur64_np(np.asarray([key], dtype=np.uint64), np.uint64(seed))[0])


def hash_slots(keys: np.ndarray, num_slots: int, seed: int = 0) -> np.ndarray:
    """Hash keys into ``[0, num_slots)`` as int32 — the hashed-directory hot
    path (KeyDirectory.slots). One fused C++ pass when available; bit-exact
    NumPy fallback otherwise, so slot assignment never depends on batch size
    or library availability."""
    keys = np.asarray(keys)
    if keys.dtype == np.int64 and keys.flags.c_contiguous:
        keys = keys.view(np.uint64)  # same bits, no copy
    else:
        keys = np.ascontiguousarray(keys, dtype=np.uint64)
    if keys.size >= 4096:
        from ..cpp import native

        lib = native()
        if lib is not None:
            import ctypes

            out = np.empty(keys.size, np.int32)
            lib.ps_hash_slots(
                keys.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
                keys.size,
                ctypes.c_uint64(seed),
                ctypes.c_uint64(num_slots),
                out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            return out.reshape(keys.shape)
    h = murmur64_np(keys, np.uint64(seed))
    if num_slots & (num_slots - 1) == 0:
        # pow2 table: bitmask beats uint64 modulo by ~5x on host
        return (h & np.uint64(num_slots - 1)).astype(np.int32)
    return (h % np.uint64(num_slots)).astype(np.int32)
