"""Host-side concurrency helpers.

Counterparts of ``src/util/threadpool.h``, ``producer_consumer.h``,
``threadsafe_queue.h`` and ``threadsafe_limited_queue.h``. On TPU the device
does the math; these keep the *host* busy — prefetching/parsing minibatches
while the chip runs — which is exactly the role the reference's
ProducerConsumer plays for MinibatchReader.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Generic, Iterator, Optional, TypeVar

T = TypeVar("T")


class ThreadsafeQueue(Generic[T]):
    """Unbounded thread-safe FIFO (ref threadsafe_queue.h)."""

    def __init__(self) -> None:
        self._q: "queue.Queue[T]" = queue.Queue()

    def push(self, item: T) -> None:
        self._q.put(item)

    def wait_and_pop(self, timeout: Optional[float] = None) -> T:
        return self._q.get(timeout=timeout)

    def try_pop(self) -> Optional[T]:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def empty(self) -> bool:
        return self._q.empty()


class ProducerConsumer(Generic[T]):
    """Bounded producer/consumer with a capacity budget (ref
    producer_consumer.h: startProducer(fn) where fn fills an item and reports
    its size; pop() blocks until data or producer end)."""

    _END = object()

    def __init__(self, capacity: int = 16):
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._threads: list[threading.Thread] = []
        self._live = 0
        self._live_lock = threading.Lock()

    def start_producer(
        self, produce: Callable[[], Optional[T]], num_threads: int = 1
    ) -> None:
        """``produce`` returns the next item or None at end of stream.

        With num_threads > 1, several producers drain the same source
        concurrently (``produce`` must be thread-safe); order of items is
        then unspecified — fine for SGD minibatches, which the reference
        shuffles anyway.
        """
        self._live = num_threads

        def run():
            while True:
                item = produce()
                if item is None:
                    with self._live_lock:
                        self._live -= 1
                        if self._live == 0:
                            self._q.put(self._END)
                    return
                self._q.put(item)

        for _ in range(num_threads):
            t = threading.Thread(target=run, daemon=True)
            self._threads.append(t)
            t.start()

    def pop(self) -> Optional[T]:
        item = self._q.get()
        if item is self._END:
            # re-queue the sentinel so every later pop() (another consumer,
            # a second iteration) also sees end-of-stream instead of hanging —
            # matches the reference pop() returning false repeatedly at end.
            self._q.put(self._END)
            return None
        return item

    def __iter__(self) -> Iterator[T]:
        while True:
            item = self.pop()
            if item is None:
                return
            yield item


class ThreadPool:
    """Fixed-size pool mirroring ref threadpool.h's add()/startWorkers()."""

    def __init__(self, num_workers: int):
        self._num = max(1, num_workers)
        self._tasks: list[Callable[[], None]] = []

    def add(self, fn: Callable[[], None]) -> None:
        self._tasks.append(fn)

    def start_workers(self) -> None:
        """Run all queued tasks across the pool and join (the reference
        blocks in the destructor; we block here)."""
        it = iter(self._tasks)
        lock = threading.Lock()
        errors: list[BaseException] = []

        def worker():
            while True:
                with lock:
                    task = next(it, None)
                if task is None:
                    return
                try:
                    task()
                except BaseException as e:  # surface to caller, don't die silently
                    with lock:
                        errors.append(e)
                    return

        threads = [threading.Thread(target=worker) for _ in range(self._num)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._tasks.clear()
        if errors:
            raise errors[0]
