"""Host-side concurrency helpers.

Counterparts of ``src/util/threadpool.h``, ``producer_consumer.h``,
``threadsafe_queue.h`` and ``threadsafe_limited_queue.h``. On TPU the device
does the math; these keep the *host* busy — prefetching/parsing minibatches
while the chip runs — which is exactly the role the reference's
ProducerConsumer plays for MinibatchReader.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Generic, Iterator, Optional, TypeVar

from ..telemetry import spans as telemetry_spans

T = TypeVar("T")


class ThreadsafeQueue(Generic[T]):
    """Unbounded thread-safe FIFO (ref threadsafe_queue.h)."""

    def __init__(self) -> None:
        self._q: "queue.Queue[T]" = queue.Queue()

    def push(self, item: T) -> None:
        self._q.put(item)

    def wait_and_pop(self, timeout: Optional[float] = None) -> T:
        return self._q.get(timeout=timeout)

    def try_pop(self) -> Optional[T]:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def empty(self) -> bool:
        return self._q.empty()


class _ProducerError:
    """Wrapper carrying a producer exception through the queue."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class ProducerConsumer(Generic[T]):
    """Bounded producer/consumer with a capacity budget (ref
    producer_consumer.h: startProducer(fn) where fn fills an item and reports
    its size; pop() blocks until data or producer end).

    Contracts the ingest pipelines rely on (tested in
    tests/test_ingest.py): an exception raised by ``produce`` is
    forwarded to the consumer — ``pop()`` re-raises it instead of
    hanging or silently truncating the stream — and :meth:`close` stops
    and joins the producer threads, so a consumer that exits early
    leaks no threads blocked in ``q.put`` (interpreter teardown would
    kill such a thread mid-call)."""

    _END = object()

    def __init__(self, capacity: int = 16):
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._threads: list[threading.Thread] = []
        self._live = 0  # guarded-by: _live_lock — producers still running
        self._live_lock = threading.Lock()
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None

    def _put(self, item) -> bool:
        """Stop-aware put: returns False when close() was requested."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def start_producer(
        self, produce: Callable[[], Optional[T]], num_threads: int = 1
    ) -> None:
        """``produce`` returns the next item or None at end of stream.

        With num_threads > 1, several producers drain the same source
        concurrently (``produce`` must be thread-safe); order of items is
        then unspecified — fine for SGD minibatches, which the reference
        shuffles anyway.
        """
        with self._live_lock:
            self._live = num_threads

        def run():
            try:
                while not self._stop.is_set():
                    item = produce()
                    if item is None:
                        break
                    if not self._put(item):
                        return
            except BaseException as e:  # forward to the consumer
                self._put(_ProducerError(e))
                return
            with self._live_lock:
                self._live -= 1
                if self._live == 0:
                    self._put(self._END)

        for _ in range(num_threads):
            t = threading.Thread(target=run, daemon=True)
            self._threads.append(t)
            t.start()

    def pop(self) -> Optional[T]:
        # a poisoned stream stays poisoned: once an error surfaced,
        # every later pop() re-raises immediately (held in an attribute
        # rather than re-queued — a blocking re-put could deadlock
        # against still-live producers on a full queue)
        if self._error is not None:
            raise self._error
        item = self._q.get()
        if item is self._END:
            # re-queue the sentinel so every later pop() (another consumer,
            # a second iteration) also sees end-of-stream instead of hanging —
            # matches the reference pop() returning false repeatedly at end.
            # Safe: END is only put once ALL producers finished, so no
            # producer can race this slot.
            self._q.put(self._END)
            return None
        if isinstance(item, _ProducerError):
            self._error = item.exc
            raise item.exc
        return item

    def __iter__(self) -> Iterator[T]:
        while True:
            item = self.pop()
            if item is None:
                return
            yield item

    def close(self, join_s: float = 2.5) -> None:
        """Stop producers and join their threads (bounded): the early-
        consumer-exit path. A producer wedged inside ``produce`` itself
        cannot be interrupted and is left to daemon teardown."""
        self._stop.set()
        deadline = time.monotonic() + max(0.0, join_s)
        while time.monotonic() < deadline and any(
            t.is_alive() for t in self._threads
        ):
            # drain so a producer mid-put unblocks at its next timeout tick
            try:
                self._q.get_nowait()
            except queue.Empty:
                pass
            for t in self._threads:
                t.join(timeout=0.05)


class _Slot:
    """One in-flight item of an OrderedStagePool: the ordering token the
    consumer waits on."""

    __slots__ = ("event", "value", "error")

    def __init__(self):
        self.event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None


class OrderedStagePool(Generic[T]):
    """Ordered parallel stage: N workers apply ``fn`` to items pulled
    from ``source``, and results are emitted IN SOURCE ORDER through a
    bounded window — the pipeline building block the staged host-ingest
    path needs (parallel localize/pack with a deterministic batch
    stream; ref threadpool.h applied to the MinibatchReader role).

    Structure: a feeder thread iterates ``source`` (so a slow source —
    parsing, filtering — runs OFF the consumer thread too), assigning
    each item a slot that enters the bounded output queue in source
    order; workers fill slots as they finish. ``capacity`` bounds the
    in-flight window (completed-but-unconsumed + in-progress items), so
    the feeder backpressures instead of racing ahead.

    Exception contract (tested): an exception raised by ``source``
    ends the stream and re-raises at the consumer; an exception raised
    by ``fn`` on item k re-raises when the consumer reaches position k
    — deterministic either way. ``close()`` (also called when the
    consumer's iteration ends or breaks early) stops and joins the
    feeder and workers, so early exit leaks no threads.
    """

    _END = object()
    _WSTOP = object()

    def __init__(
        self,
        fn: Callable[[T], object],
        source,
        num_workers: int = 2,
        capacity: Optional[int] = None,
        name: str = "stage",
        close_join_s: float = 2.5,
    ):
        self._fn = fn
        self._source = iter(source)
        self._num = max(1, int(num_workers))
        cap = capacity if capacity is not None else 2 * self._num
        self._capacity = max(1, int(cap))
        self._name = name
        self._close_join_s = close_join_s
        self._out_q: "queue.Queue" = queue.Queue(maxsize=self._capacity)
        self._work_q: "queue.Queue" = queue.Queue(maxsize=self._capacity)
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._started = False

    # -- internals ----------------------------------------------------

    def _put(self, q: "queue.Queue", item) -> bool:
        while not self._stop.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _feed(self) -> None:
        try:
            for item in self._source:
                slot = _Slot()
                # out_q first: the slot takes its ordinal position in
                # the emission order before any worker can touch it
                if not self._put(self._out_q, slot):
                    return
                if not self._put(self._work_q, (item, slot)):
                    return
            self._put(self._out_q, self._END)
        except BaseException as e:  # source exception -> ordered re-raise
            # timeline terminator: the stream dies HERE — without this
            # tombstone the trace just stops and a reader cannot tell a
            # wedge from a crash (doc/OBSERVABILITY.md, abandoned spans)
            telemetry_spans.abandoned(
                f"{self._name}.source", reason=type(e).__name__
            )
            slot = _Slot()
            slot.error = e
            slot.event.set()
            self._put(self._out_q, slot)

    def _work(self) -> None:
        while True:
            task = self._work_q.get()
            if task is self._WSTOP:
                return
            item, slot = task
            if self._stop.is_set():
                # consumer is gone: don't burn CPU on abandoned items,
                # but mark the slot so no one can block on it
                slot.event.set()
                continue
            try:
                slot.value = self._fn(item)
            except BaseException as e:
                # exception-forwarding path: the item's span (opened by
                # the stage fn) closed with an error attr when the
                # exception unwound it; this explicit terminator marks
                # the POOL abandoning the item, so the timeline shows
                # where the ordered stream was poisoned even when the
                # stage fn opened no span of its own
                telemetry_spans.abandoned(
                    f"{self._name}.worker", reason=type(e).__name__
                )
                slot.error = e
            slot.event.set()

    # -- public surface ----------------------------------------------

    def start(self) -> "OrderedStagePool[T]":
        """Idempotent: spin up the feeder + worker threads once."""
        if self._started:
            return self
        self._started = True
        feeder = threading.Thread(
            target=self._feed, daemon=True, name=f"{self._name}-feed"
        )
        self._threads.append(feeder)
        for i in range(self._num):
            w = threading.Thread(
                target=self._work, daemon=True, name=f"{self._name}-w{i}"
            )
            self._threads.append(w)
        for t in self._threads:
            t.start()
        return self

    def qsize(self) -> int:
        """Completed-or-in-progress items staged ahead of the consumer."""
        return self._out_q.qsize()

    def __iter__(self) -> Iterator:
        self.start()
        try:
            while True:
                slot = self._out_q.get()
                if slot is self._END:
                    return
                slot.event.wait()
                if slot.error is not None:
                    raise slot.error
                yield slot.value
        finally:
            self.close()

    def close(self) -> None:
        """Stop feeder + workers and join them (bounded). Safe to call
        more than once; a worker wedged inside ``fn`` stays alive
        (daemon) and is disclosed to teardown as-is."""
        self._stop.set()
        deadline = time.monotonic() + max(0.0, self._close_join_s)
        # wake idle workers immediately: one stop sentinel each. A full
        # queue drains fast once stop is set (workers skip fn and just
        # mark slots), so a short blocking put suffices — draining here
        # instead could swallow a sentinel another worker never saw.
        workers = self._threads[1:]
        for _ in range(self._num):
            while time.monotonic() < deadline and any(
                t.is_alive() for t in workers
            ):
                try:
                    self._work_q.put(self._WSTOP, timeout=0.05)
                    break
                except queue.Full:
                    continue
        while time.monotonic() < deadline and any(
            t.is_alive() for t in self._threads
        ):
            # drain the output so a feeder mid-put unblocks at its next
            # timeout tick...
            try:
                self._out_q.get_nowait()
            except queue.Empty:
                pass
            # ...and re-seed an END sentinel so a CONSUMER on another
            # thread blocked in out_q.get() (the DeviceUploader nesting)
            # wakes and terminates instead of waiting forever on a slot
            # this drain may have stolen
            try:
                self._out_q.put_nowait(self._END)
            except queue.Full:
                pass
            for t in self._threads:
                t.join(timeout=0.05)


class ThreadPool:
    """Fixed-size pool mirroring ref threadpool.h's add()/startWorkers()."""

    def __init__(self, num_workers: int):
        self._num = max(1, num_workers)
        self._tasks: list[Callable[[], None]] = []

    def add(self, fn: Callable[[], None]) -> None:
        self._tasks.append(fn)

    def start_workers(self) -> None:
        """Run all queued tasks across the pool and join (the reference
        blocks in the destructor; we block here)."""
        it = iter(self._tasks)
        lock = threading.Lock()
        errors: list[BaseException] = []

        def worker():
            while True:
                with lock:
                    task = next(it, None)
                if task is None:
                    return
                try:
                    task()
                except BaseException as e:  # surface to caller, don't die silently
                    with lock:
                        errors.append(e)
                    return

        threads = [threading.Thread(target=worker) for _ in range(self._num)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._tasks.clear()
        if errors:
            raise errors[0]


def iter_on_thread(it, maxsize: int, close_join_s: float = 2.5):
    """Run iterator ``it`` on a daemon thread, yielding its items
    through a bounded queue (backpressure: the producer blocks once
    ``maxsize`` items are staged ahead). Exceptions raised by the
    producer propagate to the consumer at the point of iteration.

    The generator-returning sibling of :class:`ProducerConsumer` (ref
    producer_consumer.h), adding the two contracts the training/bench
    pipelines need: producer exceptions forwarded to the consumer, and
    abandonment handling — when the consumer stops iterating early (an
    exception in its loop body, a break, an explicit ``close()``), the
    producer is signalled to stop and briefly joined, because a thread
    left blocked in ``q.put`` forever would be killed mid-call by
    interpreter teardown (observed as 'terminate called / FATAL:
    exception not rethrown' from inside a jax device call). The join
    is bounded by ``close_join_s``: a producer wedged inside the
    SOURCE iterator itself (a stuck read, a wedged tunnel transfer)
    cannot be interrupted from here, and close() must not hold up the
    consumer's own error propagation waiting for it."""
    q: "queue.Queue" = queue.Queue(maxsize=maxsize)
    done = object()
    stop = threading.Event()

    def _put(x) -> bool:
        while not stop.is_set():
            try:
                q.put(x, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def run():
        try:
            for x in it:
                if not _put(x):
                    return
            _put(done)
        except BaseException as e:
            _put(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        while True:
            x = q.get()
            if x is done:
                return
            if isinstance(x, BaseException):
                raise x
            yield x
    finally:
        stop.set()
        # drain so a producer mid-put unblocks at its next timeout
        # tick, then give it a bounded window to finish its current
        # item; a producer stuck in the source iterator stays alive
        # (nothing can stop it) and is disclosed to teardown as-is
        deadline = time.monotonic() + max(0.0, close_join_s)
        while t.is_alive() and time.monotonic() < deadline:
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.1)
