"""Host-side concurrency helpers.

Counterparts of ``src/util/threadpool.h``, ``producer_consumer.h``,
``threadsafe_queue.h`` and ``threadsafe_limited_queue.h``. On TPU the device
does the math; these keep the *host* busy — prefetching/parsing minibatches
while the chip runs — which is exactly the role the reference's
ProducerConsumer plays for MinibatchReader.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Generic, Iterator, Optional, TypeVar

T = TypeVar("T")


class ThreadsafeQueue(Generic[T]):
    """Unbounded thread-safe FIFO (ref threadsafe_queue.h)."""

    def __init__(self) -> None:
        self._q: "queue.Queue[T]" = queue.Queue()

    def push(self, item: T) -> None:
        self._q.put(item)

    def wait_and_pop(self, timeout: Optional[float] = None) -> T:
        return self._q.get(timeout=timeout)

    def try_pop(self) -> Optional[T]:
        try:
            return self._q.get_nowait()
        except queue.Empty:
            return None

    def empty(self) -> bool:
        return self._q.empty()


class ProducerConsumer(Generic[T]):
    """Bounded producer/consumer with a capacity budget (ref
    producer_consumer.h: startProducer(fn) where fn fills an item and reports
    its size; pop() blocks until data or producer end)."""

    _END = object()

    def __init__(self, capacity: int = 16):
        self._q: "queue.Queue" = queue.Queue(maxsize=capacity)
        self._threads: list[threading.Thread] = []
        self._live = 0
        self._live_lock = threading.Lock()

    def start_producer(
        self, produce: Callable[[], Optional[T]], num_threads: int = 1
    ) -> None:
        """``produce`` returns the next item or None at end of stream.

        With num_threads > 1, several producers drain the same source
        concurrently (``produce`` must be thread-safe); order of items is
        then unspecified — fine for SGD minibatches, which the reference
        shuffles anyway.
        """
        self._live = num_threads

        def run():
            while True:
                item = produce()
                if item is None:
                    with self._live_lock:
                        self._live -= 1
                        if self._live == 0:
                            self._q.put(self._END)
                    return
                self._q.put(item)

        for _ in range(num_threads):
            t = threading.Thread(target=run, daemon=True)
            self._threads.append(t)
            t.start()

    def pop(self) -> Optional[T]:
        item = self._q.get()
        if item is self._END:
            # re-queue the sentinel so every later pop() (another consumer,
            # a second iteration) also sees end-of-stream instead of hanging —
            # matches the reference pop() returning false repeatedly at end.
            self._q.put(self._END)
            return None
        return item

    def __iter__(self) -> Iterator[T]:
        while True:
            item = self.pop()
            if item is None:
                return
            yield item


class ThreadPool:
    """Fixed-size pool mirroring ref threadpool.h's add()/startWorkers()."""

    def __init__(self, num_workers: int):
        self._num = max(1, num_workers)
        self._tasks: list[Callable[[], None]] = []

    def add(self, fn: Callable[[], None]) -> None:
        self._tasks.append(fn)

    def start_workers(self) -> None:
        """Run all queued tasks across the pool and join (the reference
        blocks in the destructor; we block here)."""
        it = iter(self._tasks)
        lock = threading.Lock()
        errors: list[BaseException] = []

        def worker():
            while True:
                with lock:
                    task = next(it, None)
                if task is None:
                    return
                try:
                    task()
                except BaseException as e:  # surface to caller, don't die silently
                    with lock:
                        errors.append(e)
                    return

        threads = [threading.Thread(target=worker) for _ in range(self._num)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        self._tasks.clear()
        if errors:
            raise errors[0]


def iter_on_thread(it, maxsize: int, close_join_s: float = 2.5):
    """Run iterator ``it`` on a daemon thread, yielding its items
    through a bounded queue (backpressure: the producer blocks once
    ``maxsize`` items are staged ahead). Exceptions raised by the
    producer propagate to the consumer at the point of iteration.

    The generator-returning sibling of :class:`ProducerConsumer` (ref
    producer_consumer.h), adding the two contracts the training/bench
    pipelines need: producer exceptions forwarded to the consumer, and
    abandonment handling — when the consumer stops iterating early (an
    exception in its loop body, a break, an explicit ``close()``), the
    producer is signalled to stop and briefly joined, because a thread
    left blocked in ``q.put`` forever would be killed mid-call by
    interpreter teardown (observed as 'terminate called / FATAL:
    exception not rethrown' from inside a jax device call). The join
    is bounded by ``close_join_s``: a producer wedged inside the
    SOURCE iterator itself (a stuck read, a wedged tunnel transfer)
    cannot be interrupted from here, and close() must not hold up the
    consumer's own error propagation waiting for it."""
    q: "queue.Queue" = queue.Queue(maxsize=maxsize)
    done = object()
    stop = threading.Event()

    def _put(x) -> bool:
        while not stop.is_set():
            try:
                q.put(x, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def run():
        try:
            for x in it:
                if not _put(x):
                    return
            _put(done)
        except BaseException as e:
            _put(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        while True:
            x = q.get()
            if x is done:
                return
            if isinstance(x, BaseException):
                raise x
            yield x
    finally:
        stop.set()
        # drain so a producer mid-put unblocks at its next timeout
        # tick, then give it a bounded window to finish its current
        # item; a producer stuck in the source iterator stays alive
        # (nothing can stop it) and is disclosed to teardown as-is
        deadline = time.monotonic() + max(0.0, close_join_s)
        while t.is_alive() and time.monotonic() < deadline:
            try:
                q.get_nowait()
            except queue.Empty:
                pass
            t.join(timeout=0.1)
