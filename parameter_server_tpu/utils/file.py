"""File IO helpers (ref ``src/util/file.{h,cc}``, ``filelinereader.{h,cc}``,
``hdfs.h``).

Local + gzip reading, glob expansion of DataConfig-style file patterns, a
line reader, and a pluggable remote-filesystem registry. Remote URLs
(``scheme://...``) route to a registered filesystem adapter; the bundled
``HadoopCliFS`` shells out to ``hadoop fs`` exactly like the reference
(``util/file.cc hadoopFS()``: ``<home>/bin/hadoop fs -D
fs.default.name=<namenode> -D hadoop.job.ugi=<ugi> -cat/-ls/-put``).
Environments without a hadoop client keep the clear gated error.
"""

from __future__ import annotations

import glob as _glob
import gzip
import io
import os
import re
import subprocess
from typing import IO, Dict, Iterable, Iterator, List, Optional

# -- pluggable remote filesystems ------------------------------------------

_REMOTE_FS: Dict[str, "RemoteFS"] = {}


class RemoteFS:
    """Adapter interface for a remote filesystem scheme.

    Counterpart of the reference's HDFS hooks in ``util/file.cc``
    (hadoopFS -cat / -ls). Implementations provide streaming reads,
    writes, and pattern listing; gzip decoding is layered on top by
    :func:`open_read`, mirroring the reference's gzFile path.
    """

    def open_read(self, path: str) -> IO[bytes]:
        raise NotImplementedError

    def open_write(self, path: str) -> IO[bytes]:
        raise NotImplementedError

    def list(self, pattern: str) -> List[str]:
        raise NotImplementedError


def register_filesystem(scheme: str, fs: Optional[RemoteFS]) -> None:
    """Register (or, with None, remove) the adapter for ``scheme://``."""
    if fs is None:
        _REMOTE_FS.pop(scheme, None)
    else:
        _REMOTE_FS[scheme] = fs


def get_filesystem(path_or_scheme: str) -> Optional[RemoteFS]:
    scheme = path_or_scheme.split("://", 1)[0] if "://" in path_or_scheme else path_or_scheme
    return _REMOTE_FS.get(scheme)


class HadoopCliFS(RemoteFS):
    """``hadoop fs`` CLI adapter (ref util/file.cc hadoopFS + hdfs.h).

    Streams bytes through the hadoop client subprocess: ``-cat`` for
    reads, ``-put -`` for writes, ``-ls`` for listing. ``home``/
    ``namenode``/``ugi`` mirror the reference's HDFSConfig proto fields;
    ``home`` falls back to $HADOOP_HOME.
    """

    def __init__(
        self,
        home: str = "",
        namenode: str = "",
        ugi: str = "",
        binary: Optional[str] = None,
    ):
        self.home = home or os.environ.get("HADOOP_HOME", "")
        self.namenode = namenode
        self.ugi = ugi
        self._binary = binary  # test hook: explicit executable

    def _cmd(self) -> List[str]:
        if self._binary:
            cmd = [self._binary, "fs"]
        elif self.home:
            cmd = [os.path.join(self.home, "bin", "hadoop"), "fs"]
        else:
            cmd = ["hadoop", "fs"]
        if self.namenode:
            cmd += ["-D", f"fs.default.name={self.namenode}"]
        if self.ugi:
            cmd += ["-D", f"hadoop.job.ugi={self.ugi}"]
        return cmd

    def open_read(self, path: str) -> IO[bytes]:
        proc = subprocess.Popen(
            self._cmd() + ["-cat", path],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        return _ProcReader(proc, path)

    def open_write(self, path: str) -> IO[bytes]:
        proc = subprocess.Popen(
            self._cmd() + ["-put", "-", path],
            stdin=subprocess.PIPE,
            stderr=subprocess.PIPE,
        )
        return _ProcWriter(proc, path)

    def list(self, pattern: str) -> List[str]:
        out = subprocess.run(
            self._cmd() + ["-ls", pattern],
            capture_output=True,
            text=True,
        )
        if out.returncode != 0:
            return []
        files = []
        for line in out.stdout.splitlines():
            # `hadoop fs -ls` lines end with the path (ref file.cc
            # readFilenamesInDirectory: token after the last space)
            parts = line.split()
            if parts and "://" in parts[-1] or (parts and parts[-1].startswith("/")):
                files.append(parts[-1])
        return sorted(files)


class _ProcReader(io.RawIOBase):
    """File-like over a subprocess stdout; surfaces the exit code."""

    def __init__(self, proc: subprocess.Popen, path: str):
        self._proc = proc
        self._path = path

    def readable(self) -> bool:
        return True

    def readinto(self, b) -> int:
        chunk = self._proc.stdout.read(len(b))
        if not chunk:
            return 0
        b[: len(chunk)] = chunk
        return len(chunk)

    def close(self) -> None:
        if self.closed:
            return
        self._proc.stdout.close()
        code = self._proc.wait()
        err = self._proc.stderr.read().decode(errors="replace")
        self._proc.stderr.close()
        super().close()
        if code != 0:
            raise IOError(f"remote read failed ({code}) for {self._path}: {err.strip()}")


class _ProcWriter(io.RawIOBase):
    def __init__(self, proc: subprocess.Popen, path: str):
        self._proc = proc
        self._path = path

    def writable(self) -> bool:
        return True

    def write(self, b) -> int:
        self._proc.stdin.write(b)
        return len(b)

    def close(self) -> None:
        if self.closed:
            return
        self._proc.stdin.close()
        code = self._proc.wait()
        err = self._proc.stderr.read().decode(errors="replace")
        self._proc.stderr.close()
        super().close()
        if code != 0:
            raise IOError(f"remote write failed ({code}) for {self._path}: {err.strip()}")


# -- scheme-aware open/list -------------------------------------------------


def is_remote(path: str) -> bool:
    return "://" in path


def open_read(path: str, mode: str = "rt") -> IO:
    if is_remote(path):
        fs = get_filesystem(path)
        if fs is None:
            raise NotImplementedError(
                f"no filesystem registered for {path!r} — register one with "
                "utils.file.register_filesystem (e.g. HadoopCliFS for "
                "hdfs://; the reference shells out to `hadoop fs` the "
                "same way)"
            )
        raw = fs.open_read(path)
        if path.endswith(".gz"):
            raw = gzip.open(raw, "rb")
        if "b" not in mode:
            return io.TextIOWrapper(io.BufferedReader(raw) if isinstance(raw, io.RawIOBase) else raw)
        return raw
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def open_write(path: str, mode: str = "w") -> IO:
    """Open for writing, creating parent directories (the reference's
    SaveModel does createDir(getPath(file)) first, bcd.h:225)."""
    if is_remote(path):
        fs = get_filesystem(path)
        if fs is None:
            raise NotImplementedError(
                f"no filesystem registered for {path!r} — register one with "
                "utils.file.register_filesystem"
            )
        raw = fs.open_write(path)
        if path.endswith(".gz"):
            return gzip.open(raw, "wb")
        if "b" not in mode:
            return io.TextIOWrapper(io.BufferedWriter(raw) if isinstance(raw, io.RawIOBase) else raw)
        return raw
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def expand_globs(patterns: Iterable[str]) -> List[str]:
    """Expand data-file patterns.

    The reference matches the basename as a REGEX against the files in the
    pattern's directory (data/common.cc:113-134 searchFiles), which is why
    its example configs say ``part.*``. We accept both: shell glob first
    (the pythonic convenience), then reference-style anchored basename
    regex when the glob finds nothing. Remote patterns list through the
    registered filesystem (ref file.cc readFilenamesInDirectory hdfs -ls),
    passing through untouched when none is registered.
    """
    out: List[str] = []
    for p in patterns:
        if is_remote(p):
            fs = get_filesystem(p)
            hits = fs.list(p) if fs is not None else []
            out.extend(hits if hits else [p])
            continue
        hits = sorted(_glob.glob(p))
        if not hits and os.path.exists(p):
            hits = [p]
        if not hits:
            dirname, base = os.path.split(p)
            try:
                rx = re.compile(base)
                d = dirname or "."
                if os.path.isdir(d):
                    hits = sorted(
                        os.path.join(dirname, f) if dirname else f
                        for f in os.listdir(d)
                        if rx.fullmatch(f)
                    )
            except re.error:
                pass
        out.extend(hits)
    return out


def read_lines(path: str) -> Iterator[str]:
    """Line reader (ref FileLineReader::Reload loop)."""
    with open_read(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line:
                yield line
