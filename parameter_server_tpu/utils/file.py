"""File IO helpers (ref ``src/util/file.{h,cc}``, ``filelinereader.{h,cc}``,
``hdfs.h``).

Local + gzip reading, glob expansion of DataConfig-style file patterns, and
a line reader. HDFS/S3 URLs are recognized and rejected with a clear error
(gated, no hadoop client in this environment — ref hdfs.h shells out to
``hadoop fs``).
"""

from __future__ import annotations

import glob as _glob
import re
import gzip
import os
from typing import IO, Iterable, Iterator, List


def is_remote(path: str) -> bool:
    return path.startswith("hdfs://") or path.startswith("s3://")


def open_read(path: str, mode: str = "rt") -> IO:
    if is_remote(path):
        raise NotImplementedError(
            f"remote filesystem not available in this environment: {path} "
            "(reference shells out to `hadoop fs`; gate your DataConfig to local files)"
        )
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def open_write(path: str, mode: str = "w") -> IO:
    """Open for writing, creating parent directories (the reference's
    SaveModel does createDir(getPath(file)) first, bcd.h:225)."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    if path.endswith(".gz"):
        return gzip.open(path, mode)
    return open(path, mode)


def expand_globs(patterns: Iterable[str]) -> List[str]:
    """Expand data-file patterns.

    The reference matches the basename as a REGEX against the files in the
    pattern's directory (data/common.cc:113-134 searchFiles), which is why
    its example configs say ``part.*``. We accept both: shell glob first
    (the pythonic convenience), then reference-style anchored basename
    regex when the glob finds nothing.
    """
    out: List[str] = []
    for p in patterns:
        if is_remote(p):
            out.append(p)
            continue
        hits = sorted(_glob.glob(p))
        if not hits and os.path.exists(p):
            hits = [p]
        if not hits:
            dirname, base = os.path.split(p)
            try:
                rx = re.compile(base)
                d = dirname or "."
                if os.path.isdir(d):
                    hits = sorted(
                        os.path.join(dirname, f) if dirname else f
                        for f in os.listdir(d)
                        if rx.fullmatch(f)
                    )
            except re.error:
                pass
        out.extend(hits)
    return out


def read_lines(path: str) -> Iterator[str]:
    """Line reader (ref FileLineReader::Reload loop)."""
    with open_read(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if line:
                yield line
