"""Classification metrics (ref ``src/util/evaluation.h``, ``auc.h``).

``auc``/``accuracy``/``logloss`` match the reference's semantics: labels in
{-1,+1}, predictions are raw margins Xw. Vectorized NumPy on host; jnp
variants used inside jitted evaluation steps live in apps/linear/loss.py.
"""

from __future__ import annotations

import numpy as np


def auc(y: np.ndarray, xw: np.ndarray) -> float:
    """Area under ROC via rank statistic (ref Evaluation<V>::auc)."""
    y = np.asarray(y)
    xw = np.asarray(xw)
    pos = y > 0
    npos = int(pos.sum())
    nneg = len(y) - npos
    if npos == 0 or nneg == 0:
        return 1.0
    order = np.argsort(xw, kind="stable")
    ranks = np.empty(len(xw), dtype=np.float64)
    ranks[order] = np.arange(1, len(xw) + 1)
    # average ties for exactness
    sxw = xw[order]
    i = 0
    while i < len(sxw):
        j = i
        while j + 1 < len(sxw) and sxw[j + 1] == sxw[i]:
            j += 1
        if j > i:
            ranks[order[i : j + 1]] = 0.5 * (i + 1 + j + 1)
        i = j + 1
    return float((ranks[pos].sum() - npos * (npos + 1) / 2) / (npos * nneg))


def accuracy(y: np.ndarray, xw: np.ndarray, threshold: float = 0.0) -> float:
    """Fraction with sign(Xw-threshold) == sign(y) (ref Evaluation<V>::accuracy)."""
    y = np.asarray(y)
    xw = np.asarray(xw)
    correct = ((xw > threshold) & (y > 0)) | ((xw <= threshold) & (y <= 0))
    return float(correct.mean()) if len(y) else 0.0


def logloss(y: np.ndarray, xw: np.ndarray) -> float:
    """Mean log(1+exp(-y*Xw)) — the logit objective per example."""
    y = np.asarray(y, dtype=np.float64)
    xw = np.asarray(xw, dtype=np.float64)
    return float(np.mean(np.logaddexp(0.0, -y * xw))) if len(y) else 0.0


def rmse(y: np.ndarray, xw: np.ndarray) -> float:
    y = np.asarray(y, dtype=np.float64)
    xw = np.asarray(xw, dtype=np.float64)
    return float(np.sqrt(np.mean((y - xw) ** 2))) if len(y) else 0.0
