"""Host resource sampling (ref ``src/util/resource_usage.h``).

Reads /proc to report cpu%, rss, and io counters for heartbeat/dashboard —
same data the reference's ResUsage pulls for HeartbeatInfo.
"""

from __future__ import annotations

import dataclasses
import os
import time


@dataclasses.dataclass
class Usage:
    timestamp: float
    rss_mb: float
    vm_mb: float
    cpu_seconds: float
    host_total_cpu_seconds: float
    load1: float


def _read_status() -> tuple[float, float]:
    rss = vm = 0.0
    try:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    rss = float(line.split()[1]) / 1024.0
                elif line.startswith("VmSize:"):
                    vm = float(line.split()[1]) / 1024.0
    except OSError:
        pass
    return rss, vm


def sample() -> Usage:
    rss, vm = _read_status()
    cpu = time.process_time()
    try:
        with open("/proc/stat") as f:
            parts = f.readline().split()[1:8]
        host_cpu = sum(int(x) for x in parts) / os.sysconf("SC_CLK_TCK")
    except (OSError, ValueError):
        host_cpu = 0.0
    try:
        load1 = os.getloadavg()[0]
    except OSError:
        load1 = 0.0
    return Usage(
        timestamp=time.time(),
        rss_mb=rss,
        vm_mb=vm,
        cpu_seconds=cpu,
        host_total_cpu_seconds=host_cpu,
        load1=load1,
    )
