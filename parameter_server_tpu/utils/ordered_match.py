"""Ordered key matching/merging.

TPU-native counterpart of ``src/util/parallel_ordered_match.h``: given two
sorted unique key arrays and values attached to the source keys, merge the
source values into the destination positions whose keys match. The reference
recurses and multithreads; NumPy ``searchsorted`` vectorizes the same thing.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from .assign_op import AssignOp, apply_op


def match_positions(dst_keys: np.ndarray, src_keys: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """For each src key present in dst, its position in dst.

    Returns ``(src_hit_mask, dst_pos_of_hits)``. Both key arrays must be
    sorted ascending and unique.
    """
    pos = np.searchsorted(dst_keys, src_keys)
    posc = np.minimum(pos, max(len(dst_keys) - 1, 0))
    hit = (
        (pos < len(dst_keys)) & (dst_keys[posc] == src_keys)
        if len(dst_keys)
        else np.zeros(len(src_keys), dtype=bool)
    )
    return hit, pos[hit]


def ordered_match(
    dst_keys: np.ndarray,
    dst_vals: np.ndarray,
    src_keys: np.ndarray,
    src_vals: np.ndarray,
    op: AssignOp = AssignOp.ASSIGN,
    k: int = 1,
) -> int:
    """Merge ``src_vals`` into ``dst_vals`` where keys match; returns #matched.

    ``k`` is the per-key value width (ref: ``ParallelOrderedMatch`` template
    param ``k``); values are laid out row-major ``[nkeys, k]`` or flat.
    """
    hit, pos = match_positions(dst_keys, src_keys)
    dv = dst_vals.reshape(len(dst_keys), k)
    sv = src_vals.reshape(len(src_keys), k)
    dv[pos] = apply_op(op, dv[pos], sv[hit])
    return int(hit.sum())
