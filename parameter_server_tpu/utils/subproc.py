"""Graceful subprocess timeout for tunnel-client children.

``subprocess.run(timeout=...)`` SIGKILLs on timeout — and a hard-killed
tunnel client mid-device-claim is the documented relay-wedge trigger
(bench.py probe_device note): a probing watcher could PROLONG the very
wedge it measures, one killed client per probe interval for hours.
``run_graceful`` SIGTERMs first and grants a grace period so a
responsive child can run its finalizers and release its claim.

Shared by bench.py's probe_device and script/onchip.py's watcher probe
(one definition — the interrupt-reaping subtleties below were wrong in
two inline copies once).
"""

from __future__ import annotations

import subprocess


def run_graceful(
    argv,
    timeout_s: float,
    term_grace_s: float = 10.0,
    capture_stdout: bool = False,
    **popen_kw,
) -> "tuple[int | None, bytes, bytes]":
    """Run ``argv`` to completion with a graceful timeout.

    Returns ``(returncode, stderr_bytes, stdout_bytes)`` —
    ``stdout_bytes`` is ``b""`` unless ``capture_stdout=True`` (the
    default discards stdout so a chatty child can't deadlock an
    unread pipe). Raises ``subprocess.TimeoutExpired`` after the
    graceful shutdown completes; the exception's ``.output`` carries
    any captured stdout so callers can forward records the child
    emitted before wedging. On ANY exception (including
    KeyboardInterrupt while blocked in communicate) the child is
    killed and reaped before the exception propagates —
    subprocess.run's guarantee, which a naive Popen/communicate port
    silently drops: an orphaned live tunnel client outliving its
    parent's device-lock scope is exactly the two-concurrent-clients
    collision the lock exists to prevent."""
    p = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE if capture_stdout else subprocess.DEVNULL,
        stderr=subprocess.PIPE,
        **popen_kw,
    )
    try:
        out, err = p.communicate(timeout=timeout_s)
        return p.returncode, err, out if capture_stdout else b""
    except subprocess.TimeoutExpired as te:
        # the terminate/grace sequence needs its own interrupt guard:
        # a KeyboardInterrupt raised while blocked in the grace-window
        # communicate would escape BOTH handlers (the outer
        # except BaseException cannot catch exceptions raised inside a
        # SIBLING except block), leaving a SIGTERM'd-but-possibly-alive
        # unreaped child — the exact orphan this module exists to
        # prevent
        try:
            p.terminate()
            try:
                out, err = p.communicate(timeout=term_grace_s)
            except subprocess.TimeoutExpired:
                p.kill()
                out, err = p.communicate()
        except BaseException:
            p.kill()
            p.communicate()
            raise
        # hand the pre-wedge stdout/stderr to the caller: records the
        # child emitted before timing out are evidence, not garbage
        te.output = out if capture_stdout else b""
        te.stderr = err
        raise
    except BaseException:
        p.kill()
        p.communicate()
        raise


# The probe child's device init runs in a DAEMON THREAD: CPython only
# delivers signal handlers between bytecodes of the MAIN thread, and a
# main thread blocked inside the PJRT backend-init C call (the wedge
# scenario) can never run its SIGTERM handler — the graceful shutdown
# would silently degrade to the SIGKILL it exists to avoid. With init
# on a side thread, the main thread sleeps in short slices, stays
# signal-deliverable, and sys.exit(143) runs finalizers/atexit so the
# tunnel client can release its claim.
PROBE_CHILD_SRC = (
    "import signal, sys, threading, time\n"
    "signal.signal(signal.SIGTERM, lambda *a: sys.exit(143))\n"
    "done = []\n"
    "def _init():\n"
    "    import os, jax\n"
    "    p = os.environ.get('JAX_PLATFORMS')\n"
    "    if p:\n"
    "        jax.config.update('jax_platforms', p)\n"
    "    try:\n"
    "        jax.devices()\n"
    "        done.append(0)\n"
    "    except BaseException as e:\n"
    "        sys.stderr.write(repr(e) + '\\n')\n"
    "        done.append(1)\n"
    "t = threading.Thread(target=_init, daemon=True)\n"
    "t.start()\n"
    "while t.is_alive():\n"
    "    time.sleep(0.2)\n"
    "sys.exit(done[0] if done else 1)\n"
)
