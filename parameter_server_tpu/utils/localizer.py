"""Key localization: global feature keys → contiguous local indices.

TPU-native counterpart of ``src/util/localizer.h`` (Localizer<K,V>):
``count_uniq_keys`` ≙ ``CountUniqIndex`` (sorted unique keys + appearance
counts) and ``remap`` ≙ ``RemapIndex`` (rewrite a batch's feature keys to
positions within a chosen key set, dropping filtered keys).

This is load-bearing for the TPU design: device code must see dense int32
ids with static shapes, so all uint64-key bookkeeping happens here on host
(NumPy vectorized; the C++ fast path in ``cpp/`` accelerates the sort for
large blocks).
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from .sparse import SparseBatch


def count_uniq_keys(batch: SparseBatch, cap: int = 255) -> Tuple[np.ndarray, np.ndarray]:
    """Sorted unique feature keys and their (capped) appearance counts.

    Counts are capped at ``cap`` to mirror the reference's uint8 counters
    (localizer.h stores counts as uint8 for the countmin filter).
    """
    keys, counts = np.unique(batch.indices, return_counts=True)
    return keys, np.minimum(counts, cap).astype(np.uint32)


def remap(batch: SparseBatch, keep_keys: np.ndarray) -> SparseBatch:
    """Rewrite ``batch.indices`` to positions in sorted ``keep_keys``.

    Entries whose key is not in ``keep_keys`` are dropped (tail-feature
    filtering, ref localizer.h RemapIndex with filtered key set). Returns a
    new CSR batch with ``num_cols == len(keep_keys)``.
    """
    from .ordered_match import match_positions

    hit, new_idx = match_positions(keep_keys, batch.indices)
    # new per-row counts after dropping misses
    rows = batch.row_ids()
    new_counts = np.zeros(batch.n, dtype=np.int64)
    np.add.at(new_counts, rows[hit], 1)
    indptr = np.zeros(batch.n + 1, dtype=np.int64)
    np.cumsum(new_counts, out=indptr[1:])
    return SparseBatch(
        y=batch.y,
        indptr=indptr,
        indices=new_idx.astype(np.int64),
        values=None if batch.binary else batch.values[hit],
        num_cols=len(keep_keys),
        slot_ids=None if batch.slot_ids is None else batch.slot_ids[hit],
    )


class Localizer:
    """Stateful convenience wrapper mirroring the reference class's two-call
    protocol (CountUniqIndex then RemapIndex).

    The two-call protocol enables the hot-path shortcut the standalone
    :func:`remap` cannot take: ``np.unique`` already yields each
    entry's position in the unique key array (``return_inverse``), so
    ``remap_index`` never needs the per-entry ``searchsorted`` over
    UNSORTED needles that dominated prep_batch (~82 ms vs ~15 ms per
    320k-nnz shard on the bench host — binary search over random
    needles is cache-hostile). With a filtered ``keep_keys`` the
    per-entry match reduces to a match over the (sorted, much smaller)
    unique key set plus an inverse-take. Bit-identical to
    :func:`remap` either way (tested)."""

    def __init__(self) -> None:
        self._keys: Optional[np.ndarray] = None
        self._inverse: Optional[np.ndarray] = None
        self._batch: Optional[SparseBatch] = None

    def count_uniq_index(self, batch: SparseBatch, cap: int = 255):
        self._batch = batch
        keys, inverse, counts = np.unique(
            batch.indices, return_inverse=True, return_counts=True
        )
        self._keys = keys
        self._inverse = inverse
        return keys, np.minimum(counts, cap).astype(np.uint32)

    def remap_index(self, keep_keys: np.ndarray) -> SparseBatch:
        assert self._batch is not None, "call count_uniq_index first"
        batch = self._batch
        keep = np.asarray(keep_keys, dtype=np.int64)
        if keep is keep_keys and keep_keys is self._keys:
            # full-key remap (prep_batch): the inverse IS the localized
            # index array — every entry hits
            indptr = batch.indptr.copy()
            return SparseBatch(
                y=batch.y,
                indptr=indptr,
                indices=self._inverse.astype(np.int64, copy=False),
                values=None if batch.binary else batch.values,
                num_cols=len(keep),
                slot_ids=batch.slot_ids,
            )
        # filtered remap: match the UNIQUE keys (sorted needles — cheap)
        # and push hits through the inverse
        from .ordered_match import match_positions

        hit_u, pos_u = match_positions(keep, self._keys)
        # per-unique-key destination (sentinel -1 for dropped keys)
        dest = np.full(len(self._keys), -1, np.int64)
        dest[hit_u] = pos_u
        per_entry = dest[self._inverse]
        hit = per_entry >= 0
        rows = batch.row_ids()
        new_counts = np.bincount(
            rows[hit], minlength=batch.n
        ).astype(np.int64)
        indptr = np.zeros(batch.n + 1, dtype=np.int64)
        np.cumsum(new_counts, out=indptr[1:])
        return SparseBatch(
            y=batch.y,
            indptr=indptr,
            indices=per_entry[hit],
            values=None if batch.binary else batch.values[hit],
            num_cols=len(keep),
            slot_ids=None if batch.slot_ids is None else batch.slot_ids[hit],
        )
