"""Persistent XLA compilation cache (best-effort, on by default).

Compiling through the tunneled TPU backend is the fragile step: the
relay's remote-compile helper has returned HTTP 500s on big programs
(BENCH_ONCHIP.md 2026-07-31 04:14/04:59 captures) and tunnel wedges
correlate with long compiles. Reference analogue: the reference keeps
no compiler in the loop at all — its runtime is precompiled C++
(src/ps_main.cc) — so amortizing our JIT cost across processes is part
of matching its startup/retry economics.

With a disk cache, a bench retry after a wedge — and the driver's
end-of-round ``bench.py`` run after the watcher already compiled the
same programs — reuses serialized executables instead of re-exercising
the compile helper. Safe everywhere: if the backend cannot serialize
executables the cache simply stays empty.

This JAX build does not bind the ``JAX_COMPILATION_CACHE_DIR`` env var
(verified: config stays None with it set), so the knob must be set via
``jax.config.update`` — which is why this helper exists instead of an
env line in a launcher script. ``PS_NO_COMPILE_CACHE=1`` opts out.
"""

from __future__ import annotations

import os
import stat as _stat

# uid-scoped: the cache holds serialized executables that jax will
# happily deserialize and run — a world-shared fixed path would let
# another local user pre-plant entries (and a foreign-owned dir breaks
# every write). Same reasoning as device_lock's per-uid fallback.
DEFAULT_DIR = f"/tmp/ps_jax_cache_{os.getuid()}"
_ENABLED_DIR: "str | None" = None


def _accelerator_plugin_detectable() -> bool:
    """True when a PJRT accelerator plugin is plausibly installed,
    checked without initializing any backend (early backend init is
    fatal before the jax.distributed rendezvous — see enable())."""
    try:
        import importlib.util as ilu

        if (ilu.find_spec("libtpu") is not None
                or ilu.find_spec("jax_plugins") is not None):
            return True
        from importlib.metadata import entry_points

        return bool(entry_points(group="jax_plugins"))
    except Exception:
        return False


def enable(cache_dir: "str | None" = None) -> "str | None":
    """Point jax at a persistent compilation cache directory.

    Returns the directory in effect, or None when disabled (opt-out
    env set, or jax missing/too old). Idempotent; never raises —
    callers treat the cache as a pure optimization."""
    global _ENABLED_DIR
    if os.environ.get("PS_NO_COMPILE_CACHE"):
        return None
    cache_dir = cache_dir or os.environ.get(
        "PS_COMPILE_CACHE_DIR", DEFAULT_DIR
    )
    if _ENABLED_DIR == cache_dir:
        return _ENABLED_DIR
    # CPU: compiles are fast AND the XLA:CPU AOT loader warns about
    # machine-feature mismatches on reload ("could lead to ... SIGILL")
    # — observed 2026-08-01 reloading an entry written minutes earlier
    # on the SAME host. The win is the tunneled TPU backend's remote
    # compiler, so CPU stays off unless explicitly requested
    # (PS_COMPILE_CACHE_CPU=1). The platform is read from the REQUEST
    # (env/jax_platforms config), never jax.default_backend(): that
    # call initializes the backend, and Postoffice.start() runs this
    # BEFORE the jax.distributed rendezvous, where early backend init
    # is fatal for multi-process runs.
    if not os.environ.get("PS_COMPILE_CACHE_CPU"):
        requested = os.environ.get("JAX_PLATFORMS", "")
        if not requested:
            try:
                import jax

                requested = jax.config.jax_platforms or ""
            except Exception:
                requested = ""
        req = requested.split(",")[0].strip().lower()
        if req == "cpu":
            return None
        if not req:
            # No explicit platform request: jax may silently default to
            # XLA:CPU, which must not get the cache either (the SIGILL
            # reload risk above). Enable only when an accelerator
            # plugin is detectable WITHOUT initializing a backend —
            # jax discovers PJRT plugins via the jax_plugins namespace
            # package AND via importlib.metadata entry points, so both
            # registration styles are checked.
            if not _accelerator_plugin_detectable():
                return None
    # the cache holds executables jax will deserialize and RUN, and a
    # predictable /tmp name is world-creatable: make the dir 0700 and
    # refuse one we don't own (another user pre-planting entries would
    # be arbitrary code execution in our process) — the XDG runtime-dir
    # check pattern
    try:
        # a pre-created SYMLINK at the predictable name would make
        # makedirs/stat/chmod all operate on the attacker's chosen
        # target (e.g. chmod 0700 on a dir the victim owns): reject
        # links outright, and lstat (not stat) afterwards so a swap
        # between makedirs and the check is also caught
        if os.path.islink(cache_dir):
            return None
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        st = os.lstat(cache_dir)
        if st.st_uid != os.getuid() or not _stat.S_ISDIR(st.st_mode):
            return None
        os.chmod(cache_dir, 0o700)
    except OSError:
        return None
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # the dir update is what turns the cache on — record success
        # now so a failure of the optional threshold tweak below can't
        # leave an active cache reported as disabled (and re-entered
        # on every Postoffice.start())
        _ENABLED_DIR = cache_dir
    except Exception:
        return None
    try:
        # the big fused programs are the ones that matter, but small
        # sub-second helpers recompile on every retry too — cache
        # anything that took a meaningful compile. Best-effort: not
        # every jax build has this knob
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass
    return cache_dir
