"""Version compatibility shims for the jax API surface we depend on.

``jax.shard_map`` graduated from ``jax.experimental.shard_map`` in newer
jax releases, and its replication-check kwarg was renamed along the way
(``check_rep`` → ``check_vma``). Older environments (e.g. jax 0.4.x)
only ship the experimental path with the old kwarg. Import from here so
the whole package runs on both: call sites use the NEW spelling
(``check_vma``) and the shim translates for old jax.
"""

from __future__ import annotations

import functools
import inspect

try:  # jax >= 0.6: top-level export, check_vma kwarg
    from jax import shard_map
except ImportError:  # jax 0.4.x/0.5.x: experimental namespace, check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    if "check_vma" in inspect.signature(_shard_map).parameters:
        shard_map = _shard_map
    else:

        @functools.wraps(_shard_map)
        def shard_map(*args, **kwargs):
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map(*args, **kwargs)


__all__ = ["shard_map"]
