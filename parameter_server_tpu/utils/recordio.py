"""CRC-framed record files (ref ``src/util/recordio.{h,cc}``).

Frame layout mirrors the reference's RecordWriter/RecordReader: per record a
fixed header ``[masked_crc32c(payload):4][length:4]`` then the payload. The
reference stores protobuf ``Example``s; we store any bytes (the data layer
serializes SparseBatch rows with np.save-style packing in data/text2record).
"""

from __future__ import annotations

import struct
from typing import BinaryIO, Iterator, Optional

from . import crc32c

_HEADER = struct.Struct("<II")  # masked crc, length


class RecordWriter:
    def __init__(self, f: BinaryIO):
        self._f = f

    def write_record(self, payload: bytes) -> None:
        crc = crc32c.masked(crc32c.value(payload))
        self._f.write(_HEADER.pack(crc, len(payload)))
        self._f.write(payload)

    def close(self) -> None:
        self._f.close()


class RecordReader:
    def __init__(self, f: BinaryIO):
        self._f = f

    def read_record(self) -> Optional[bytes]:
        hdr = self._f.read(_HEADER.size)
        if len(hdr) < _HEADER.size:
            return None
        crc, length = _HEADER.unpack(hdr)
        payload = self._f.read(length)
        if len(payload) < length:
            raise IOError("truncated record")
        if crc32c.unmask(crc) != crc32c.value(payload):
            raise IOError("record crc mismatch")
        return payload

    def __iter__(self) -> Iterator[bytes]:
        while True:
            rec = self.read_record()
            if rec is None:
                return
            yield rec
