"""Dense bitmap (ref ``src/util/bitmap.h``): set/clear/test/nnz/fill.

Used by darlin's active set. Host side only — on device the active set is a
float/bool mask array (static shapes); this class backs host bookkeeping and
tests.
"""

from __future__ import annotations

import numpy as np


class Bitmap:
    def __init__(self, size: int = 0, value: bool = False):
        self._bits = np.full(size, bool(value), dtype=bool)

    def resize(self, size: int, value: bool = False) -> None:
        self._bits = np.full(size, bool(value), dtype=bool)

    def set(self, i: int) -> None:
        self._bits[i] = True

    def clear(self, i: int) -> None:
        self._bits[i] = False

    def test(self, i: int) -> bool:
        return bool(self._bits[i])

    def fill(self, value: bool) -> None:
        self._bits.fill(bool(value))

    def nnz(self) -> int:
        return int(self._bits.sum())

    @property
    def size(self) -> int:
        return len(self._bits)

    def array(self) -> np.ndarray:
        return self._bits
