"""Example-info statistics (ref ``src/data/info_parser.{h,cc}``): per-slot
min/max key, nnz element/example counts, total example count — computed
from parsed batches instead of per-proto accumulation."""

from __future__ import annotations

import numpy as np

from ..utils.sparse import SparseBatch
from .example import ExampleInfo, SlotInfo
from .text_parser import SLOT_SPACE


def info_from_batch(batch: SparseBatch, split_slots: bool = True) -> ExampleInfo:
    info = ExampleInfo(num_ex=batch.n)
    if batch.nnz == 0:
        return info
    if split_slots and batch.slot_ids is not None:
        slot_of = batch.slot_ids.astype(np.int64)
    elif split_slots:
        slot_of = (batch.indices // SLOT_SPACE).astype(np.int64)
    else:
        slot_of = np.zeros(batch.nnz, np.int64)
    rows = batch.row_ids()
    for sid in np.unique(slot_of):
        sel = slot_of == sid
        keys = batch.indices[sel]
        ex = np.unique(rows[sel])
        info.slot.append(
            SlotInfo(
                id=int(sid),
                format="sparse_binary" if batch.binary else "sparse",
                min_key=int(keys.min()),
                max_key=int(keys.max()) + 1,
                nnz_ele=int(sel.sum()),
                nnz_ex=int(len(ex)),
            )
        )
    return info
