"""Text format parsers (ref ``src/data/text_parser.{h,cc}`` ExampleParser).

Formats, as in the reference: libsvm ("label idx:val ..."), criteo
(label \\t 13 numeric \\t 26 hex categorical), adfea ("line_id key:groupid ..."
with label first), terafea, and ps_sparse/ps_dense. Output is a SparseBatch
(CSR over uint64 feature keys) carrying per-entry feature-group slot ids,
matching the reference's Example proto slots (``src/data/proto/example.proto``,
``text_parser.cc`` Slot.set_id). The C++ fast path (cpp/psnative.cc
ps_parse_*) handles the two hot formats; NumPy/Python fallbacks cover all.
"""

from __future__ import annotations

import re
import ctypes
from typing import List, Optional

import numpy as np

from ..cpp import native
from ..utils.sparse import SparseBatch

# per-slot key striping for multi-slot formats (matches cpp/psnative.cc)
SLOT_SPACE = 1 << 52


def _batch_from_rows(
    labels: List[float],
    row_keys: List[np.ndarray],
    row_vals: Optional[List[np.ndarray]],
    row_slots: Optional[List[np.ndarray]] = None,
) -> SparseBatch:
    n = len(labels)
    counts = np.array([len(k) for k in row_keys], dtype=np.int64)
    indptr = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(counts, out=indptr[1:])
    indices = (
        np.concatenate(row_keys).astype(np.int64) if n and indptr[-1] else np.zeros(0, np.int64)
    )
    values = None
    if row_vals is not None:
        values = (
            np.concatenate(row_vals).astype(np.float32)
            if n and indptr[-1]
            else np.zeros(0, np.float32)
        )
    slot_ids = None
    if row_slots is not None:
        slot_ids = (
            np.concatenate(row_slots).astype(np.int32)
            if n and indptr[-1]
            else np.zeros(0, np.int32)
        )
    return SparseBatch(
        y=np.asarray(labels, dtype=np.float32),
        indptr=indptr,
        indices=indices,
        values=values,
        slot_ids=slot_ids,
    )


_DECFLOAT_RE = re.compile(r"[+-]?(?:\d+(?:\.\d*)?|\.\d+)(?:[eE][+-]?\d+)?\Z")
# the C++ fast path parses numeric tokens via a 64-byte scratch buffer:
# longer tokens are malformed THERE, so they must be malformed HERE too
_MAX_NUM_TOK = 63
# C++ tokenization splits on space/tab/\r only (NOT \x0b/\x0c like
# str.split) — same separator set on both paths
_WS_SPLIT = re.compile(r"[ \t\r]+")


def _decfloat_ok(tok: str) -> bool:
    return len(tok) <= _MAX_NUM_TOK and _DECFLOAT_RE.match(tok) is not None
_DECINT_RE = re.compile(r"([+-]?)(\d+)\Z")
_U64_MASK = (1 << 64) - 1


def _parse_u64(tok: str):
    """Reference strtou64 semantics: optional sign (negation wraps modulo
    2^64), clamp to ULLONG_MAX before negating, whole token must consume.
    Returns the uint64 value or None. An EMPTY token is 0: strtoull("")
    performs no conversion, leaves end at the terminator, and strtonum.h
    treats that as success — so ":val" is feature id 0 in the reference."""
    if tok == "":
        return 0
    m = _DECINT_RE.match(tok)
    if not m:
        return None
    # leading zeros don't contribute magnitude: strip them BEFORE the
    # digit-count overflow guard, or '00…07' would clamp to ULLONG_MAX
    # where strtoull accumulates to 7 (C++/reference parity)
    digits = m.group(2).lstrip("0") or "0"
    # CPython 3.11+ caps int() at 4300 digits with a ValueError; any run
    # past 20 digits clamps at ULLONG_MAX anyway (like the C++ path)
    mag = _U64_MASK if len(digits) > 20 else min(int(digits), _U64_MASK)
    return (_U64_MASK + 1 - mag) & _U64_MASK if m.group(1) == "-" else mag


def _wrap_i64(x: int) -> int:
    """Fold an unbounded Python int into int64 two's-complement range so
    np.int64 array construction can never raise OverflowError (corrupt
    lines can carry arbitrarily long digit runs)."""
    x &= _U64_MASK
    return x - (1 << 64) if x > (1 << 63) - 1 else x


def _wrap_i32(x: int) -> int:
    x &= 0xFFFFFFFF
    return x - (1 << 32) if x > (1 << 31) - 1 else x


def parse_libsvm(lines: List[str]) -> SparseBatch:
    """All libsvm features live in feature-group slot 1 (ref ParseLibsvm,
    text_parser.cc: ``fea_slot->set_id(1)``; slot 0 holds the label).

    Reference-strict line validation (ParseLibsvm + strtonum.h): the
    label and every value must be a FULL decimal-float token, every
    feature token must contain ':', indices parse with strtou64
    semantics, feature ids must be non-decreasing in uint64 order, and
    ANY malformed token drops the WHOLE line (no partial rows). Empty
    sub-tokens are 0 like the reference: ":val" is feature id 0 and
    "idx:" is value 0.0 (strtoull("")/strtof("") are successful
    no-conversions under strtonum.h's end-of-string check).
    Deliberate narrowing vs strtof: hex floats / inf / nan tokens are
    rejected (real libsvm data never contains them, and the C++ fast
    path must stay bit-exact with this grammar)."""
    labels, keys, vals, slots = [], [], [], []
    for line in lines:
        parts = [t for t in _WS_SPLIT.split(line.rstrip("\n")) if t]
        if not parts:
            continue
        if not _decfloat_ok(parts[0]):
            continue  # ref: strtofloat(label) false -> drop line
        label = float(parts[0])
        k, v = [], []
        last_idx = 0
        ok = True
        for tok in parts[1:]:
            i, colon, x = tok.partition(":")
            if not colon:
                ok = False  # ref: token without ':' -> drop line
                break
            idx = _parse_u64(i)
            if idx is None or last_idx > idx:
                ok = False  # bad index / unordered -> drop line
                break
            last_idx = idx
            if x == "":
                val = 0.0  # ref: strtofloat("") succeeds with 0
            elif _decfloat_ok(x):
                val = float(x)
            else:
                ok = False
                break
            k.append(_wrap_i64(idx))
            v.append(val)
        if not ok:
            continue
        labels.append(1.0 if label > 0 else -1.0)
        keys.append(np.asarray(k, dtype=np.int64))
        vals.append(np.asarray(v, dtype=np.float32))
        slots.append(np.ones(len(k), dtype=np.int32))
    return _batch_from_rows(labels, keys, vals, slots)


_CRITEO_STRIPE = ((1 << 64) - 1) // 13  # ref: kMaxKey / 13
_CRITEO_SEED = 512927377
_CRITEO_INT_RE = re.compile(r" *([+-]?)(\d+)\Z")


def parse_criteo(lines: List[str]) -> SparseBatch:
    """label\\t13 ints\\t26 categorical tokens — reference semantics
    (ParseCriteo, text_parser.cc): ALL features are BINARY keys. Integer
    slot i with count c → key ``kMaxKey/13*i + c`` (one-hot by count);
    categorical tokens longer than 4 chars → ``h0 ^ h1`` of
    MurmurHash3_x64_128(token, seed 512927377). Lines missing any tab
    before the last categorical field are dropped (the reference returns
    false for a missing int tab, and for a missing cat tab when i != 25).
    Feature-group slots match the reference Example proto: int feature i
    → slot i+1, categorical i → slot i+14."""
    from ..utils.murmur import murmur3_x64_128

    labels, keys, slots = [], [], []
    for line in lines:
        f = line.rstrip("\n").split("\t")
        if len(f) < 40:  # label + 13 ints + 26 cats; ref drops short lines
            continue
        lbl_tok = f[0].lstrip(" ")
        if f[0] == "":
            label = 0.0  # ref strtofloat(""): no conversion = success, 0
        elif _decfloat_ok(lbl_tok):
            label = float(lbl_tok)
        else:
            continue  # ref strtofloat: strict full-field decimal float
        k, s = [], []
        for i, tok in enumerate(f[1:14]):
            # ref strtoi32: leading spaces + sign + digits consuming the
            # WHOLE field (partial parses skip the field), long clamp on
            # overflow, then int32 truncation. An EMPTY field is count 0
            # (strtol("") is a successful no-conversion) — real criteo
            # data marks missing ints with empty fields, so the
            # reference emits key stripe*i+0 for them, not a skip
            if tok == "":
                k.append((_CRITEO_STRIPE * i) & ((1 << 64) - 1))
                s.append(i + 1)
                continue
            m = _CRITEO_INT_RE.match(tok)
            if not m:
                continue
            # strip leading zeros before the digit-count guard (strtol
            # accumulates magnitude; '00…05' is 5, not ERANGE)
            digits = m.group(2).lstrip("0") or "0"
            # len guard first: CPython caps int() at 4300 digits
            raw = (1 << 63) if len(digits) > 19 else int(digits)
            if raw > (1 << 63) - 1:  # strtol ERANGE clamp
                cnt64 = -(1 << 63) if m.group(1) == "-" else (1 << 63) - 1
            else:
                cnt64 = -raw if m.group(1) == "-" else raw
            cnt = _wrap_i32(cnt64)
            k.append((_CRITEO_STRIPE * i + cnt) & ((1 << 64) - 1))
            s.append(i + 1)
        for i, tok in enumerate(f[14:40]):
            if len(tok) > 4:
                h0, h1 = murmur3_x64_128(tok.encode(), _CRITEO_SEED)
                k.append(h0 ^ h1)
                s.append(i + 14)
        labels.append(1.0 if label > 0 else -1.0)
        keys.append(np.asarray(k, dtype=np.uint64).view(np.int64))
        slots.append(np.asarray(s, dtype=np.int32))
    return _batch_from_rows(labels, keys, None, slots)


def parse_adfea(lines: List[str]) -> SparseBatch:
    """ref ParseAdfea (text_parser.cc:90-121): tokens split on space/colon
    are ``line_id 1 label key:slot_id key:slot_id ...`` — the LABEL is the
    third token (the second is the constant example count "1"). Binary
    features; keys striped by their slot (group) id, which is also emitted
    as the entry's feature-group slot (ref: ``slot->set_id(slot_id)``)."""
    labels, keys, slots = [], [], []
    for line in lines:
        toks = line.replace(":", " ").split()
        if len(toks) < 3:
            continue
        try:
            label = float(toks[2])
        except ValueError:
            continue
        labels.append(1.0 if label > 0 else -1.0)
        k, s = [], []
        pairs = toks[3:]
        for j in range(0, len(pairs) - 1, 2):
            try:
                key = int(pairs[j])
                g = int(pairs[j + 1])
            except ValueError:
                continue
            k.append(_wrap_i64(g * SLOT_SPACE + key % (SLOT_SPACE - 1)))
            s.append(_wrap_i32(g))
        keys.append(np.asarray(k, dtype=np.int64))
        slots.append(np.asarray(s, dtype=np.int32))
    return _batch_from_rows(labels, keys, None, slots)


def parse_terafea(lines: List[str]) -> SparseBatch:
    """ref ParseTerafea (text_parser.cc:128-160): space-separated
    ``label line_id separator key key ...``; the group id lives in the top
    bits of each key (``key >> 54``) and the WHOLE key is the feature id,
    so keys pass through unchanged (masked into the non-negative int64
    range, keeping the reference's low-collision intent). The top-10-bit
    group id is emitted as the feature-group slot (ref ParseTerafea:
    ``slot_id = key >> 54``)."""
    labels, keys, slots = [], [], []
    for line in lines:
        toks = line.split()
        if len(toks) < 3:
            continue
        try:
            label = float(toks[0])
        except ValueError:
            continue
        labels.append(1.0 if label > 0 else -1.0)
        k, s = [], []
        for tok in toks[3:]:
            try:
                key = int(tok)
            except ValueError:
                continue
            k.append(key & 0x7FFFFFFFFFFFFFFF)
            s.append((key >> 54) & 0x3FF)
        keys.append(np.asarray(k, dtype=np.int64))
        slots.append(np.asarray(s, dtype=np.int32))
    return _batch_from_rows(labels, keys, None, slots)


def parse_ps_sparse(lines: List[str]) -> SparseBatch:
    """ref ParsePS sparse: "label;grp_id idx:val ...;grp_id ...;" — we fold
    groups into key stripes like criteo; the group id is the slot id."""
    labels, keys, vals, slots = [], [], [], []
    for line in lines:
        groups = [g for g in line.strip().split(";") if g]
        if not groups:
            continue
        try:
            label = float(groups[0])
        except ValueError:
            continue
        labels.append(1.0 if label > 0 else -1.0)
        k, v, s = [], [], []
        for grp in groups[1:]:
            toks = grp.split()
            if not toks:
                continue
            try:
                gid = int(toks[0])
            except ValueError:
                continue
            for tok in toks[1:]:
                i, _, x = tok.partition(":")
                # parse BOTH halves before appending either — a bad
                # value after a good key must not desync the arrays
                try:
                    key = _wrap_i64(gid * SLOT_SPACE + int(i))
                    val = float(x) if x else 1.0
                except ValueError:
                    continue
                k.append(key)
                v.append(val)
                s.append(_wrap_i32(gid))
        keys.append(np.asarray(k, dtype=np.int64))
        vals.append(np.asarray(v, dtype=np.float32))
        slots.append(np.asarray(s, dtype=np.int32))
    return _batch_from_rows(labels, keys, vals, slots)


def parse_ps_sparse_binary(lines: List[str]) -> SparseBatch:
    """ref ParsePS SPARSE_BINARY: "label;grp_id key key ...;" — every token
    after the group id is a bare uint64 key, values implicitly 1."""
    labels, keys, slots = [], [], []
    for line in lines:
        groups = [g for g in line.strip().split(";") if g]
        if not groups:
            continue
        try:
            label = float(groups[0])
        except ValueError:
            continue
        labels.append(1.0 if label > 0 else -1.0)
        k, s = [], []
        for grp in groups[1:]:
            toks = grp.split()
            if not toks:
                continue
            try:
                gid = int(toks[0])
            except ValueError:
                continue
            for tok in toks[1:]:
                try:
                    k.append(_wrap_i64(gid * SLOT_SPACE + int(tok)))
                    s.append(_wrap_i32(gid))
                except ValueError:
                    continue
        keys.append(np.asarray(k, dtype=np.int64))
        slots.append(np.asarray(s, dtype=np.int32))
    return _batch_from_rows(labels, keys, None, slots)


def parse_ps_dense(lines: List[str]) -> SparseBatch:
    """ref ParsePS DENSE: "label;grp_id val val ...;" — float values at
    implicit positional indices within each group."""
    labels, keys, vals, slots = [], [], [], []
    for line in lines:
        groups = [g for g in line.strip().split(";") if g]
        if not groups:
            continue
        try:
            label = float(groups[0])
        except ValueError:
            continue
        labels.append(1.0 if label > 0 else -1.0)
        k, v, s = [], [], []
        for grp in groups[1:]:
            toks = grp.split()
            if not toks:
                continue
            try:
                gid = int(toks[0])
            except ValueError:
                continue
            for pos, tok in enumerate(toks[1:]):
                try:
                    x = float(tok)
                except ValueError:
                    continue
                k.append(_wrap_i64(gid * SLOT_SPACE + pos))
                v.append(x)
                s.append(_wrap_i32(gid))
        keys.append(np.asarray(k, dtype=np.int64))
        vals.append(np.asarray(v, dtype=np.float32))
        slots.append(np.asarray(s, dtype=np.int32))
    return _batch_from_rows(labels, keys, vals, slots)


def _parse_native(text: bytes, fn_name: str, max_rows: int) -> Optional[SparseBatch]:
    lib = native()
    if lib is None:
        return None
    fn = getattr(lib, fn_name)
    max_nnz = max(1024, len(text) // 2)
    while True:
        y = np.zeros(max_rows, np.float32)
        indptr = np.zeros(max_rows + 1, np.int64)
        indices = np.zeros(max_nnz, np.uint64)
        values = np.zeros(max_nnz, np.float32)
        slots = np.zeros(max_nnz, np.int32)
        out_nnz = ctypes.c_int64(0)
        rows = fn(
            text,
            len(text),
            y.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            indptr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            slots.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            max_rows,
            max_nnz,
            ctypes.byref(out_nnz),
        )
        nnz = out_nnz.value
        if rows < 0:
            # explicit truncation signal (-(rows+1), psnative.cc contract):
            # the value budget was hit mid-stream — retry with a bigger buffer
            max_nnz *= 2
            continue
        # .view keeps the raw 64 bits for keys >= 2^63 (criteo murmur keys)
        return SparseBatch(
            y=y[:rows].copy(),
            indptr=indptr[: rows + 1].copy(),
            indices=indices[:nnz].view(np.int64).copy(),
            # criteo is a binary format in the reference (all keys, no
            # values); the C ABI still fills 1.0s, dropped here
            values=None if fn_name == "ps_parse_criteo" else values[:nnz].copy(),
            slot_ids=slots[:nnz].copy(),
        )


_PY_PARSERS = {
    "libsvm": parse_libsvm,
    "criteo": parse_criteo,
    "adfea": parse_adfea,
    "terafea": parse_terafea,
    "ps": parse_ps_sparse,
    "ps_sparse": parse_ps_sparse,
    "ps_sparse_binary": parse_ps_sparse_binary,
    "ps_dense": parse_ps_dense,
}
_NATIVE = {"libsvm": "ps_parse_libsvm", "criteo": "ps_parse_criteo"}


class ExampleParser:
    """Format-dispatching parser (ref ExampleParser::Init/ToProto)."""

    def __init__(self, format_: str = "libsvm", use_native: bool = True):
        f = format_.lower()
        if f not in _PY_PARSERS:
            raise ValueError(f"unknown text format: {format_}")
        self.format = f
        self.use_native = use_native and f in _NATIVE

    def parse_lines(self, lines: List[str]) -> SparseBatch:
        if self.use_native and lines:
            blob = ("\n".join(lines) + "\n").encode()
            out = _parse_native(blob, _NATIVE[self.format], len(lines) + 1)
            if out is not None:
                return out
        return _PY_PARSERS[self.format](lines)

    def parse_text(self, text: bytes) -> SparseBatch:
        """Parse a raw byte chunk (must end at a line boundary) without the
        line-split/join round trip — the streaming hot path: file chunks go
        straight into the C++ parser (ref text_parser.cc consumes the
        mmap'd file the same way)."""
        if self.use_native and text:
            out = _parse_native(text, _NATIVE[self.format], text.count(b"\n") + 1)
            if out is not None:
                return out
        return _PY_PARSERS[self.format](text.decode().splitlines())
