"""Example/slot containers + binary serialization.

Counterpart of ``src/data/proto/example.proto`` (Example/Slot/SlotInfo/
ExampleInfo) and ``src/data/common.h`` conversions — without protobuf: a
compact numpy framing (`batch_to_bytes`/`batch_from_bytes`) stored inside
recordio files, and slot/statistics dataclasses used by info_parser and the
slot reader.
"""

from __future__ import annotations

import dataclasses
import io
import struct
from typing import Dict, List, Optional

import numpy as np

from ..utils.sparse import SparseBatch

_MAGIC = b"PSB1"


@dataclasses.dataclass
class SlotInfo:
    """ref example.proto SlotInfo."""

    id: int = 0
    format: str = "sparse"  # dense | sparse | sparse_binary
    min_key: int = (1 << 64) - 1
    max_key: int = 0
    nnz_ele: int = 0
    nnz_ex: int = 0


@dataclasses.dataclass
class ExampleInfo:
    """ref example.proto ExampleInfo."""

    slot: List[SlotInfo] = dataclasses.field(default_factory=list)
    num_ex: int = 0

    def merge(self, other: "ExampleInfo") -> None:
        self.num_ex += other.num_ex
        by_id: Dict[int, SlotInfo] = {s.id: s for s in self.slot}
        for s in other.slot:
            if s.id in by_id:
                d = by_id[s.id]
                d.min_key = min(d.min_key, s.min_key)
                d.max_key = max(d.max_key, s.max_key)
                d.nnz_ele += s.nnz_ele
                d.nnz_ex += s.nnz_ex
            else:
                self.slot.append(dataclasses.replace(s))
        self.slot.sort(key=lambda s: s.id)


def batch_to_bytes(batch: SparseBatch) -> bytes:
    """Serialize a SparseBatch (the Example-records payload).

    The third header word is a flags field: bit0 = binary (no values), bit1 =
    slot ids present (ref example.proto Slot.id, appended after values).
    Pre-slot files wrote 0/1 here, so they decode unchanged.
    """
    buf = io.BytesIO()
    buf.write(_MAGIC)
    flags = (1 if batch.binary else 0) | (2 if batch.slot_ids is not None else 0)
    buf.write(struct.pack("<qqq", batch.n, batch.nnz, flags))
    buf.write(batch.y.astype(np.float32).tobytes())
    buf.write(batch.indptr.astype(np.int64).tobytes())
    buf.write(batch.indices.astype(np.int64).tobytes())
    if not batch.binary:
        buf.write(batch.values.astype(np.float32).tobytes())
    if batch.slot_ids is not None:
        buf.write(batch.slot_ids.astype(np.int32).tobytes())
    return buf.getvalue()


def batch_from_bytes(data: bytes) -> SparseBatch:
    if data[:4] != _MAGIC:
        raise IOError("bad batch magic")
    n, nnz, flags = struct.unpack_from("<qqq", data, 4)
    off = 4 + 24
    y = np.frombuffer(data, np.float32, n, off).copy()
    off += 4 * n
    indptr = np.frombuffer(data, np.int64, n + 1, off).copy()
    off += 8 * (n + 1)
    indices = np.frombuffer(data, np.int64, nnz, off).copy()
    off += 8 * nnz
    values = None
    if not (flags & 1):
        values = np.frombuffer(data, np.float32, nnz, off).copy()
        off += 4 * nnz
    slot_ids = None
    if flags & 2:
        slot_ids = np.frombuffer(data, np.int32, nnz, off).copy()
    return SparseBatch(y=y, indptr=indptr, indices=indices, values=values, slot_ids=slot_ids)
