"""Slot reader: grouped-feature column cache for BCD preprocessing.

Counterpart of ``src/data/slot_reader.{h,cc}``: the reference reads all
files once, splits features into their slots (feature groups, Example
proto Slot.id), and caches each slot's CSC arrays (offset/index/value)
compressed on disk so darlin can load one feature group at a time. Here:
slots come from the per-entry slot ids the parsers emit
(``SparseBatch.slot_ids``, matching ``text_parser.cc`` Slot.set_id); for
batches without that side channel (e.g. synthetic data) they fall back to
the key striping (key // SLOT_SPACE). Per-slot CSR partitions are cached
as .npz under a cache dir.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

import numpy as np

from ..utils.sparse import SparseBatch
from .example import ExampleInfo, SlotInfo
from .stream_reader import StreamReader
from .text_parser import SLOT_SPACE


class SlotReader:
    def __init__(
        self,
        files: Optional[List[str]] = None,
        data_format: str = "libsvm",
        cache_dir: Optional[str] = None,
    ):
        self.files = files or []
        self.format = data_format
        self.cache_dir = cache_dir
        self.info = ExampleInfo()
        self._slots: Dict[int, SparseBatch] = {}
        self._labels: Optional[np.ndarray] = None

    def _cache_path(self, slot_id: int) -> Optional[str]:
        if not self.cache_dir:
            return None
        import hashlib

        os.makedirs(self.cache_dir, exist_ok=True)
        # stable digest (Python's hash() is salted per process — it would
        # defeat the cross-run cache)
        key = "|".join(self.files) + f"|{self.format}|{slot_id}"
        tag = hashlib.sha1(key.encode()).hexdigest()[:8]
        return os.path.join(self.cache_dir, f"slot_{slot_id}_{tag}.npz")

    def read(self) -> ExampleInfo:
        """Read all files, split by slot, fill ExampleInfo (ref Read())."""
        batch = StreamReader(self.files, self.format).read_all()
        if batch is None:
            return self.info
        self._labels = batch.y
        if batch.slot_ids is not None:
            slot_of = batch.slot_ids.astype(np.int64)
        else:
            slot_of = (batch.indices // SLOT_SPACE).astype(np.int64)
        self.info = ExampleInfo(num_ex=batch.n)
        rows = batch.row_ids()
        vals = batch.value_array()
        for sid in np.unique(slot_of):
            sel = slot_of == sid
            keys = batch.indices[sel]
            sub_rows = rows[sel]
            counts = np.zeros(batch.n, np.int64)
            np.add.at(counts, sub_rows, 1)
            indptr = np.zeros(batch.n + 1, np.int64)
            np.cumsum(counts, out=indptr[1:])
            order = np.argsort(sub_rows, kind="stable")
            sub = SparseBatch(
                y=batch.y,
                indptr=indptr,
                indices=keys[order],
                values=vals[sel][order],
            )
            self._slots[int(sid)] = sub
            self.info.slot.append(
                SlotInfo(
                    id=int(sid),
                    format="sparse",
                    min_key=int(keys.min()),
                    max_key=int(keys.max()) + 1,
                    nnz_ele=int(sel.sum()),
                    nnz_ex=int((counts > 0).sum()),
                )
            )
            path = self._cache_path(int(sid))
            if path:
                np.savez_compressed(
                    path, y=sub.y, indptr=sub.indptr, indices=sub.indices, values=sub.values
                )
        self.info.slot.sort(key=lambda s: s.id)
        return self.info

    def slot(self, slot_id: int) -> Optional[SparseBatch]:
        """The CSR batch restricted to one slot (ref offset/index/value)."""
        if slot_id in self._slots:
            return self._slots[slot_id]
        path = self._cache_path(slot_id)
        if path and os.path.exists(path):
            z = np.load(path)
            return SparseBatch(
                y=z["y"], indptr=z["indptr"], indices=z["indices"], values=z["values"]
            )
        return None

    def clear(self, slot_id: int) -> None:
        self._slots.pop(slot_id, None)

    @property
    def labels(self) -> Optional[np.ndarray]:
        return self._labels
