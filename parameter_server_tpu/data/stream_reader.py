"""Streaming minibatch reader (ref ``src/data/stream_reader.h``).

``StreamReader<V>::readMatrices(size, &out)`` pulls the next N examples
from a list of (possibly gzipped) text/record files. Here:
``StreamReader.minibatches(n)`` yields SparseBatch chunks of n examples,
crossing file boundaries, using ExampleParser for the configured format.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import numpy as np

from ..utils import file as psfile
from ..utils import recordio
from ..utils.sparse import SparseBatch
from .text_parser import ExampleParser


def _concat_batches(parts: List[SparseBatch]) -> SparseBatch:
    if len(parts) == 1:
        return parts[0]
    y = np.concatenate([p.y for p in parts])
    indptr = [np.zeros(1, np.int64)]
    offset = 0
    for p in parts:
        indptr.append(p.indptr[1:] + offset)
        offset += p.indptr[-1]
    binary = all(p.binary for p in parts)
    has_slots = all(p.slot_ids is not None for p in parts)
    return SparseBatch(
        y=y,
        indptr=np.concatenate(indptr),
        indices=np.concatenate([p.indices for p in parts]),
        values=None
        if binary
        else np.concatenate([p.value_array() for p in parts]),
        slot_ids=np.concatenate([p.slot_ids for p in parts]) if has_slots else None,
    )


def rebatch(parts_iter: Iterator[SparseBatch], size: int) -> Iterator[SparseBatch]:
    """Re-slice a stream of arbitrarily-sized batches into ``size``-row
    minibatches (the last may be smaller). Shared by the record and byte
    paths — the accumulate/merge/slice bookkeeping lives once."""
    pending: List[SparseBatch] = []
    count = 0
    for b in parts_iter:
        pending.append(b)
        count += b.n
        if count < size:
            continue
        merged = _concat_batches(pending)
        lo = 0
        while merged.n - lo >= size:
            yield merged.slice_rows(lo, lo + size)
            lo += size
        rest = merged.slice_rows(lo, merged.n)
        pending = [rest] if rest.n else []
        count = rest.n
    if count:
        yield _concat_batches(pending)


class StreamReader:
    def __init__(self, files: List[str], data_format: str = "libsvm"):
        self.files = psfile.expand_globs(files)
        self.format = data_format
        self.parser = (
            ExampleParser(data_format)
            if data_format not in ("record", "ref_record") else None
        )

    def _lines(self) -> Iterator[str]:
        for path in self.files:
            yield from psfile.read_lines(path)

    def _record_batches(self) -> Iterator[SparseBatch]:
        from .example import batch_from_bytes

        for path in self.files:
            with psfile.open_read(path, "rb") as f:
                for payload in recordio.RecordReader(f):
                    yield batch_from_bytes(payload)

    def _ref_record_batches(self, size: int) -> Iterator[SparseBatch]:
        """Reference-produced protobuf Example recordio files
        (data/ref_interop.py; ref src/util/recordio.h + example.proto):
        one Example per record, grouped here into SparseBatches."""
        from .ref_interop import (
            decode_example,
            example_slots_to_row,
            iter_ref_records,
            rows_to_batch,
        )

        rows: List = []
        for path in self.files:
            for payload in iter_ref_records(path):
                rows.append(example_slots_to_row(decode_example(payload)))
                if len(rows) >= size:
                    yield rows_to_batch(rows)
                    rows = []
        if rows:
            yield rows_to_batch(rows)

    def minibatches(self, size: int) -> Iterator[SparseBatch]:
        """Yield batches of ``size`` examples (last may be smaller)."""
        if self.format == "record":
            yield from rebatch(self._record_batches(), size)
            return
        if self.format == "ref_record":
            yield from self._ref_record_batches(size)
            return
        lines: List[str] = []
        for line in self._lines():
            lines.append(line)
            if len(lines) >= size:
                yield self.parser.parse_lines(lines)
                lines = []
        if lines:
            yield self.parser.parse_lines(lines)

    def _byte_chunks(self, chunk_bytes: int) -> Iterator[bytes]:
        """Line-aligned raw byte chunks across all files."""
        for path in self.files:
            tail = b""
            with psfile.open_read(path, "rb") as f:
                while True:
                    buf = f.read(chunk_bytes)
                    if not buf:
                        break
                    buf = tail + buf
                    cut = buf.rfind(b"\n")
                    if cut < 0:
                        tail = buf
                        continue
                    tail = buf[cut + 1 :]
                    yield buf[: cut + 1]
            # a file with no trailing newline still ends its own line — the
            # tail must never glue onto the next file's first line
            if tail:
                yield tail + b"\n"

    def minibatches_bytes(
        self, size: int, chunk_bytes: int = 16 << 20, threads: int = 4
    ) -> Iterator[SparseBatch]:
        """Streaming minibatches on the chunked byte path: line-aligned
        chunks go straight into the C++ parser on a small thread pool (the
        native call releases the GIL, so chunks parse in true parallel —
        the TPU-side analogue of the reference's multi-threaded
        stream_reader.h producer). Submission is windowed so only
        ~``threads`` chunks are in memory at once. Falls back to the
        line-by-line path for formats without a native parser."""
        if self.parser is None or not self.parser.use_native:
            yield from self.minibatches(size)
            return
        import collections
        from concurrent.futures import ThreadPoolExecutor

        def parsed_chunks() -> Iterator[SparseBatch]:
            chunks = self._byte_chunks(chunk_bytes)
            futs: collections.deque = collections.deque()
            with ThreadPoolExecutor(threads) as pool:

                def fill() -> None:
                    while len(futs) < threads + 2:
                        try:
                            c = next(chunks)
                        except StopIteration:
                            return
                        futs.append(pool.submit(self.parser.parse_text, c))

                fill()
                while futs:
                    b = futs.popleft().result()
                    fill()
                    yield b

        yield from rebatch(parsed_chunks(), size)

    def read_all(self) -> Optional[SparseBatch]:
        """Whole-dataset read (BCD preprocessing path)."""
        parts = list(self.minibatches(1 << 16))
        if not parts:
            return None
        return _concat_batches(parts)
