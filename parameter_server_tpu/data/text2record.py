"""Text → record conversion tool (ref ``src/data/text2proto.h`` +
``util/recordio``): parse any supported text format and write CRC-framed
binary record files, which StreamReader reads back with format="record".

    python -m parameter_server_tpu.data.text2record \\
        --input data/part-* --format criteo --output data/part.rec \\
        [--batch 65536] [--ref-format]

``--ref-format`` writes the REFERENCE's binary format instead —
protobuf ``Example`` records in magic-framed recordio
(data/ref_interop.py; ref src/util/recordio.h + example.proto) — so a
converted dataset is consumable by a reference process, and reads back
here with format="ref_record".
"""

from __future__ import annotations

import argparse
import sys

from ..utils import file as psfile
from ..utils.recordio import RecordWriter
from .example import batch_to_bytes
from .stream_reader import StreamReader


def convert(inputs, data_format: str, output: str, batch_size: int = 65536) -> int:
    reader = StreamReader(list(inputs), data_format)
    n = 0
    with open(output, "wb") as f:
        writer = RecordWriter(f)
        for batch in reader.minibatches(batch_size):
            writer.write_record(batch_to_bytes(batch))
            n += batch.n
    return n


def convert_ref(inputs, data_format: str, output: str, batch_size: int = 65536) -> int:
    """Text -> reference protobuf Example recordio (one record per
    example, ref recordio.h framing owned by ref_interop)."""
    from .ref_interop import batch_to_ref_payloads, write_ref_records

    reader = StreamReader(list(inputs), data_format)
    return write_ref_records(
        output,
        (
            payload
            for batch in reader.minibatches(batch_size)
            for payload in batch_to_ref_payloads(batch)
        ),
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--input", nargs="+", required=True)
    ap.add_argument("--format", default="libsvm")
    ap.add_argument("--output", required=True)
    ap.add_argument("--batch", type=int, default=65536)
    ap.add_argument(
        "--ref-format", action="store_true",
        help="write the reference's protobuf Example recordio format",
    )
    args = ap.parse_args(argv)
    files = psfile.expand_globs(args.input)
    if not files:
        print(f"no input files match {args.input}", file=sys.stderr)
        return 2
    fn = convert_ref if args.ref_format else convert
    n = fn(files, args.format, args.output, args.batch)
    print(f"wrote {n} examples to {args.output}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
