"""Binary matrix files + data-science helpers.

Counterpart of the reference's MATLAB toolbox (``src/data/matlab/*.m``) and
the binary matrix container it reads (``Matrix::writeToBinFile`` in
``src/util/sparse_matrix.h`` / ``dense_matrix.h``): a ``<name>.info``
protobuf-text descriptor (MatrixInfo, ``src/util/proto/matrix.proto``)
next to raw little-endian arrays ``<name>.offset`` (uint64 CSR row
offsets), ``<name>.index`` (uint32 column indices), ``<name>.value``
(float64), and optionally ``<name>.key`` (uint64 global keys after
localization). Functions keep the MATLAB names so reference users can map
their workflow one to one:

=================  =====================================================
reference .m       here
=================  =====================================================
load_bin.m         :func:`load_bin`
save_bin.m         :func:`save_bin`
bin2mat.m          :func:`bin2mat` (returns dense ndarray or SparseBatch)
mat2bin (implied   :func:`mat2bin` (the writer bin2mat expects,
by recordio2bin)    writeToBinFile layout)
saveas_pserver.m   :func:`saveas_pserver` (ps text format round-trips
                    through data/text_parser.parse_ps_*)
filter_fea.m       :func:`filter_fea` (drop features seen <= pv times)
=================  =====================================================
"""

from __future__ import annotations

import os
from typing import Optional, Tuple, Union

import numpy as np

from ..utils.sparse import SparseBatch


def save_bin(name: str, arr: np.ndarray, dtype=None) -> None:
    """Write a vector as raw little-endian binary (ref save_bin.m)."""
    a = np.asarray(arr)
    if dtype is not None:
        a = a.astype(dtype)
    a.ravel().tofile(name)


def load_bin(
    name: str, dtype="float64", offset: int = 0, count: int = -1
) -> np.ndarray:
    """Read a raw binary vector (ref load_bin.m: format/offset/length)."""
    dt = np.dtype(dtype)
    with open(name, "rb") as f:
        f.seek(dt.itemsize * offset)
        return np.fromfile(f, dtype=dt, count=count)


def _write_info(name: str, fields: list) -> None:
    lines = []
    for key, val in fields:
        if isinstance(val, tuple):  # range message {begin end}
            lines.append(f"{key} {{ begin: {val[0]} end: {val[1]} }}")
        elif isinstance(val, bool):
            lines.append(f"{key}: {'true' if val else 'false'}")
        else:
            lines.append(f"{key}: {val}")
    with open(name + ".info", "w") as f:
        f.write("\n".join(lines) + "\n")


def _read_info(name: str) -> dict:
    """Parse the MatrixInfo proto-text descriptor. Deliberately minimal
    (flat fields + one-level ``{ begin end }`` ranges) and enum-preserving
    — the config parser's enum coercion would rewrite DENSE/SPARSE."""
    import re

    out: dict = {}
    with open(name + ".info") as f:
        text = f.read()
    for key, body in re.findall(r"(\w+)\s*\{([^}]*)\}", text):
        rng = {}
        for k2, v2 in re.findall(r"(\w+)\s*:\s*(\S+)", body):
            rng[k2] = int(v2)
        out[key] = rng
    flat = re.sub(r"\w+\s*\{[^}]*\}", "", text)
    for key, val in re.findall(r"(\w+)\s*:\s*(\S+)", flat):
        if val in ("true", "false"):
            out[key] = val == "true"
        else:
            try:
                out[key] = int(val)
            except ValueError:
                out[key] = val
    return out


def mat2bin(
    name: str,
    mat: Union[np.ndarray, SparseBatch],
    keys: Optional[np.ndarray] = None,
) -> None:
    """Write the reference's binary matrix container (writeToBinFile
    layout, readable by bin2mat.m / :func:`bin2mat`)."""
    if isinstance(mat, np.ndarray):
        assert mat.ndim == 2
        _write_info(
            name,
            [
                ("type", "DENSE"),
                ("row_major", True),
                ("row", (0, mat.shape[0])),
                ("col", (0, mat.shape[1])),
                ("nnz", mat.size),
                ("sizeof_value", 8),
            ],
        )
        save_bin(name + ".value", mat, np.float64)
        return
    b: SparseBatch = mat
    # a non-localized batch can carry global 64-bit hash keys (criteo);
    # casting those to uint32 would silently corrupt the .index file, so
    # widen sizeof_index to 8 when the indices don't fit
    fits32 = b.nnz == 0 or (
        int(b.indices.min()) >= 0 and int(b.indices.max()) < 2**32
    )
    _write_info(
        name,
        [
            ("type", "SPARSE_BINARY" if b.binary else "SPARSE"),
            ("row_major", True),
            ("row", (0, b.n)),
            ("col", (0, b.cols)),
            ("nnz", b.nnz),
            ("sizeof_index", 4 if fits32 else 8),
            ("sizeof_value", 8),
        ],
    )
    save_bin(name + ".offset", b.indptr, np.uint64)
    if fits32:
        save_bin(name + ".index", b.indices, np.uint32)
    else:
        # .view keeps the raw 64 bits for keys >= 2^63 stored as negative int64
        save_bin(name + ".index", b.indices.astype(np.int64).view(np.uint64), np.uint64)
    if not b.binary:
        save_bin(name + ".value", b.values, np.float64)
    if keys is not None:
        save_bin(name + ".key", keys, np.uint64)


def bin2mat(
    name: str,
) -> Union[np.ndarray, Tuple[SparseBatch, Optional[np.ndarray]]]:
    """Load a binary matrix container (ref bin2mat.m). DENSE → float64
    ndarray; SPARSE/SPARSE_BINARY → (SparseBatch-without-labels, keys)."""
    info = _read_info(name)
    mtype = str(info.get("type", "SPARSE"))
    rows = int(info["row"]["end"]) - int(info["row"].get("begin", 0))
    cols = int(info["col"]["end"]) - int(info["col"].get("begin", 0))
    if "DENSE" in mtype:
        vals = load_bin(name + ".value", np.float64)
        return vals.reshape(rows, cols)
    indptr = load_bin(name + ".offset", np.uint64).astype(np.int64)
    if int(info.get("sizeof_index", 4)) == 8:
        indices = load_bin(name + ".index", np.uint64).view(np.int64)
    else:
        indices = load_bin(name + ".index", np.uint32).astype(np.int64)
    values = (
        None
        if "BINARY" in mtype
        else load_bin(name + ".value", np.float64).astype(np.float32)
    )
    keys = (
        load_bin(name + ".key", np.uint64)
        if os.path.exists(name + ".key")
        else None
    )
    batch = SparseBatch(
        y=np.zeros(rows, np.float32),
        indptr=indptr,
        indices=indices,
        values=values,
        num_cols=cols,
    )
    return batch, keys


def saveas_pserver(
    file_name: str,
    y: np.ndarray,
    batch: SparseBatch,
    group_id: Optional[np.ndarray] = None,
    binary: Optional[bool] = None,
) -> None:
    """Write examples in the ps text format (ref saveas_pserver.m):
    ``label;grp idx[:val] ...;grp ...;`` — parse_ps_sparse /
    parse_ps_sparse_binary read it back."""
    binary = batch.binary if binary is None else binary
    group_id = (
        np.zeros(batch.cols, np.int64)
        if group_id is None
        else np.asarray(group_id)
    )
    if not np.all(np.diff(group_id) >= 0):
        raise ValueError("group_id must be sorted (ref assert(issorted))")
    with open(file_name, "w") as f:
        for i in range(batch.n):
            f.write(f"{int(y[i])}")
            lo, hi = batch.indptr[i], batch.indptr[i + 1]
            pre_gid = None
            for e in range(lo, hi):
                col = int(batch.indices[e])
                gid = int(group_id[col])
                if gid != pre_gid:
                    f.write(f"; {gid}")
                    pre_gid = gid
                if binary:
                    f.write(f" {col}")
                else:
                    f.write(f" {col}:{batch.values[e]:g}")
            f.write(";\n")


def filter_fea(batch: SparseBatch, pv: int) -> Tuple[SparseBatch, np.ndarray]:
    """Drop features appearing <= pv times (ref filter_fea.m's
    ``sum(X) > pv`` pruning). Returns (filtered batch remapped to the kept
    columns, kept original column ids)."""
    from ..utils.localizer import remap

    keys, counts = np.unique(batch.indices, return_counts=True)
    keep = keys[counts > pv]
    return remap(batch, keep), keep
