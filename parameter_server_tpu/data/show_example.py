"""Inspect parsed examples from any supported input (ref show_example.h).

The reference ships a tiny debugging binary (``src/data/show_example.h``:
read the first ``-n`` Example protos from a recordio file and print their
``ShortDebugString()``). Slot/parser bugs — like round 1's criteo
slot-grouping regression — are exactly the kind of thing it exists to
catch, so ours goes further: it reads either a recordio file written by
``text2record`` OR raw text in any of the five reference formats, and
prints each example as a proto-debug-style line grouped by slot.

Usage::

    python -m parameter_server_tpu.data.show_example -input part-0 \
        -format criteo -n 3
"""

from __future__ import annotations

import argparse
import sys
from typing import Iterator, List

import numpy as np

from ..utils import file as psfile
from ..utils.recordio import RecordReader
from ..utils.sparse import SparseBatch
from .example import batch_from_bytes
from .text_parser import _PY_PARSERS, ExampleParser

_FORMATS = sorted(_PY_PARSERS) + ["recordio"]


def format_example(batch: SparseBatch, i: int) -> str:
    """One example as a proto-ShortDebugString-style line.

    Mirrors what ``Example::ShortDebugString()`` shows in the reference:
    the label slot (id 0) then each feature slot with its keys (and
    values unless the batch is binary).
    """
    lo, hi = int(batch.indptr[i]), int(batch.indptr[i + 1])
    keys = batch.indices[lo:hi]
    vals = None if batch.values is None else batch.values[lo:hi]
    # parsers emit 1-based slot ids (0 is the label slot, ref example.proto)
    slots = (
        batch.slot_ids[lo:hi]
        if batch.slot_ids is not None
        else np.ones(hi - lo, dtype=np.int32)
    )
    parts: List[str] = ["slot { id: 0 val: %g }" % float(batch.y[i])]
    for sid in np.unique(slots):
        sel = np.flatnonzero(slots == sid)
        fields = [f"id: {int(sid)}"]
        # keys are uint64 in the reference proto; indices may arrive as a
        # signed int64 view of hashed keys — display unsigned
        fields += [f"key: {int(k) & 0xFFFFFFFFFFFFFFFF}" for k in keys[sel]]
        if vals is not None:
            fields += ["val: %g" % float(v) for v in vals[sel]]
        parts.append("slot { %s }" % " ".join(fields))
    return " ".join(parts)


def _batches(path: str, fmt: str, limit: int) -> Iterator[SparseBatch]:
    if fmt == "recordio":
        with psfile.open_read(path, "rb") as f:
            for payload in RecordReader(f):
                yield batch_from_bytes(payload)
    else:
        parser = ExampleParser(fmt)
        lines: List[str] = []
        with psfile.open_read(path, "rt") as f:
            for line in f:
                if line.strip():
                    lines.append(line)
                if len(lines) >= limit:
                    break
        if lines:
            yield parser.parse_lines(lines)


def show_example(path: str, fmt: str, n: int, out=None) -> int:
    """Print the first ``n`` examples; returns how many were printed."""
    out = out if out is not None else sys.stdout
    shown = 0
    for batch in _batches(path, fmt, n):
        for i in range(batch.n):
            if shown >= n:
                return shown
            print(format_example(batch, i), file=out)
            shown += 1
    return shown


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="show_example",
        description="print the first n parsed examples (ref show_example.h)",
    )
    # single-dash flags accepted for reference CLI parity (-input/-format/-n)
    ap.add_argument("-input", "--input", required=True, help="input file")
    ap.add_argument(
        "-format", "--format", default="recordio", choices=_FORMATS,
        help="input format (default: recordio)",
    )
    ap.add_argument(
        "-n", "--n", type=int, default=3,
        help="show the first n instances in text format",
    )
    args = ap.parse_args(argv)
    if args.n <= 0:
        ap.error("-n must be positive")
    try:
        shown = show_example(args.input, args.format, args.n)
    except FileNotFoundError as e:
        ap.error(str(e))
    except BrokenPipeError:  # e.g. `... | head`
        return 0
    if shown == 0:
        print("(no examples)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
