"""Reference-format interop: the reference's protobuf ``Example``
recordio files, decoded/encoded WITHOUT a protobuf dependency.

The reference's binary dataset format (the one its stream/slot readers
consume as ``DataConfig.PROTO``) is:

* framing (ref ``src/util/recordio.h``): each record is
  ``[magic int32 LE = 0x3ed7230a][payload_size uint32 LE][payload]``;
* payload: a serialized ``PS.Example``
  (ref ``src/data/proto/example.proto``)::

      message Slot    { optional int32 id = 1;
                        repeated uint64 key = 2 [packed=true];
                        repeated float  val = 3 [packed=true]; }
      message Example { repeated Slot slot = 1; }

* convention (ref ``src/data/text_parser.cc`` ParseLibsvm/ParseCriteo):
  slot 0 carries the label as ``val[0]`` and no keys; feature slots
  (id >= 1) carry sorted ``key`` arrays, with ``val`` absent for binary
  features (criteo/adfea) and parallel to ``key`` otherwise (libsvm);
* the optional ``<name>.info`` sidecar is an ``ExampleInfo`` in
  protobuf ASCII text format (ref ``src/data/text2proto.h``
  writeProtoToASCIIFile).

This module hand-decodes that fixed schema from the proto wire format
(varints, length-delimited fields, packed scalars) — a ~150-line
decoder beats dragging in a protobuf runtime for one frozen message
family, and the encoder lets tests and ``text2record --ref-format``
produce byte-streams a reference process would accept. Both accept the
packed AND unpacked encodings of the repeated fields, as any compliant
proto parser must.
"""

from __future__ import annotations

import struct
from typing import Iterable, Iterator, List, Optional, Tuple

import numpy as np

from ..utils.sparse import SparseBatch
from .example import ExampleInfo, SlotInfo

#: ref src/util/recordio.h kMagicNumber
REF_MAGIC = 0x3ED7230A
_MAGIC_BYTES = struct.pack("<i", REF_MAGIC)

# SlotInfo.Format enum values (ref example.proto)
_FORMAT_FROM_ENUM = {1: "dense", 2: "sparse", 3: "sparse_binary"}
_FORMAT_TO_ENUM = {v: k for k, v in _FORMAT_FROM_ENUM.items()}


# ---------------------------------------------------------------------------
# proto wire primitives
# ---------------------------------------------------------------------------

def _read_uvarint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            # mask to 64 bits: a maximal 10-byte varint carries up to
            # 70 payload bits, and compliant proto parsers TRUNCATE
            # (fuzz-found: the unmasked value overflowed numpy uint64)
            return result & 0xFFFFFFFFFFFFFFFF, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint longer than 64 bits")


def _write_uvarint(out: bytearray, value: int) -> None:
    value &= (1 << 64) - 1
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(buf: bytes) -> Iterator[Tuple[int, int, object]]:
    """Yield (field_number, wire_type, value) for a message's bytes.

    value is an int for varint/fixed wire types and a memoryview for
    length-delimited fields. Unknown wire types raise (the schema is
    frozen; anything else means the input is not a PS proto)."""
    view = memoryview(buf)
    pos = 0
    n = len(buf)
    while pos < n:
        tag, pos = _read_uvarint(buf, pos)
        field, wt = tag >> 3, tag & 7
        if wt == 0:  # varint
            val, pos = _read_uvarint(buf, pos)
            yield field, wt, val
        elif wt == 2:  # length-delimited
            ln, pos = _read_uvarint(buf, pos)
            if pos + ln > n:
                raise ValueError("truncated length-delimited field")
            yield field, wt, view[pos:pos + ln]
            pos += ln
        elif wt == 5:  # fixed32
            if pos + 4 > n:
                raise ValueError("truncated fixed32")
            yield field, wt, struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        elif wt == 1:  # fixed64
            if pos + 8 > n:
                raise ValueError("truncated fixed64")
            yield field, wt, struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt} (field {field})")


def _decode_packed_uvarints(view) -> List[int]:
    buf = bytes(view)
    out: List[int] = []
    pos = 0
    while pos < len(buf):
        v, pos = _read_uvarint(buf, pos)
        out.append(v)
    return out


# ---------------------------------------------------------------------------
# Slot / Example
# ---------------------------------------------------------------------------

def decode_slot(buf) -> Tuple[int, np.ndarray, Optional[np.ndarray]]:
    """``PS.Slot`` bytes -> (id, keys uint64[], vals float32[] | None)."""
    slot_id = 0
    keys: List[int] = []
    vals: Optional[List[float]] = None
    for field, wt, val in _iter_fields(bytes(buf)):
        if field == 1 and wt == 0:
            slot_id = int(np.int32(np.uint32(val & 0xFFFFFFFF)))
        elif field == 2 and wt == 2:  # packed keys
            keys.extend(_decode_packed_uvarints(val))
        elif field == 2 and wt == 0:  # unpacked key
            keys.append(val)
        elif field == 3 and wt == 2:  # packed vals
            arr = np.frombuffer(bytes(val), dtype="<f4")
            vals = (vals or []) + arr.tolist()
        elif field == 3 and wt == 5:  # unpacked val
            vals = (vals or [])
            vals.append(struct.unpack("<f", struct.pack("<I", val))[0])
        # unknown fields are skipped by _iter_fields' framing
    return (
        slot_id,
        np.asarray(keys, dtype=np.uint64),
        None if vals is None else np.asarray(vals, dtype=np.float32),
    )


def encode_slot(slot_id: int, keys, vals=None) -> bytes:
    out = bytearray()
    _write_uvarint(out, (1 << 3) | 0)  # id: field 1, varint
    _write_uvarint(out, int(slot_id) & 0xFFFFFFFF)
    keys = np.asarray(keys, dtype=np.uint64)
    if keys.size:
        packed = bytearray()
        for k in keys.tolist():
            _write_uvarint(packed, k)
        _write_uvarint(out, (2 << 3) | 2)  # key: field 2, packed
        _write_uvarint(out, len(packed))
        out += packed
    if vals is not None:
        v = np.asarray(vals, dtype="<f4").tobytes()
        _write_uvarint(out, (3 << 3) | 2)  # val: field 3, packed
        _write_uvarint(out, len(v))
        out += v
    return bytes(out)


def decode_example(buf) -> List[Tuple[int, np.ndarray, Optional[np.ndarray]]]:
    """``PS.Example`` bytes -> list of decoded slots (see decode_slot)."""
    slots = []
    for field, wt, val in _iter_fields(bytes(buf)):
        if field == 1 and wt == 2:
            slots.append(decode_slot(val))
    return slots


def encode_example(slots) -> bytes:
    """Inverse of :func:`decode_example`: slots is an iterable of
    (id, keys, vals-or-None)."""
    out = bytearray()
    for slot_id, keys, vals in slots:
        body = encode_slot(slot_id, keys, vals)
        _write_uvarint(out, (1 << 3) | 2)  # slot: field 1
        _write_uvarint(out, len(body))
        out += body
    return bytes(out)


# ---------------------------------------------------------------------------
# recordio framing (ref src/util/recordio.h)
# ---------------------------------------------------------------------------

def iter_ref_records(path: str) -> Iterator[bytes]:
    """Yield raw record payloads from a reference recordio file.

    Opened through utils.file so .gz and registered remote schemes
    (hdfs://) work exactly as they do for every other reader path."""
    from ..utils import file as psfile

    with psfile.open_read(path, "rb") as f:
        while True:
            head = f.read(8)
            if not head:
                return
            if len(head) < 8:
                raise ValueError(f"{path}: truncated record header")
            magic, size = struct.unpack("<iI", head)
            if magic != REF_MAGIC:
                raise ValueError(
                    f"{path}: bad magic 0x{magic & 0xFFFFFFFF:08x} "
                    f"(want 0x{REF_MAGIC:08x}) — not a reference recordio"
                )
            payload = f.read(size)
            if len(payload) < size:
                raise ValueError(f"{path}: truncated record payload")
            yield payload


def write_ref_records(path: str, payloads: Iterable[bytes]) -> int:
    """Write payloads with the reference framing; returns record count."""
    n = 0
    with open(path, "wb") as f:
        for p in payloads:
            f.write(_MAGIC_BYTES)
            f.write(struct.pack("<I", len(p)))
            f.write(p)
            n += 1
    return n


# ---------------------------------------------------------------------------
# Example stream <-> SparseBatch
# ---------------------------------------------------------------------------

#: one decoded Example as a row: (label, slot-key chunks, slot-val
#: chunks (None where the slot was binary), slot-id chunks)
Row = Tuple[float, List[np.ndarray], List[Optional[np.ndarray]], List[np.ndarray]]


def example_slots_to_row(slots) -> Row:
    """Decoded Example slots -> a row tuple for :func:`rows_to_batch`.

    Label = slot 0's ``val[0]`` (0.0 if absent); feature slots keep
    their global uint64 keys and per-entry slot ids."""
    label = 0.0
    key_chunks: List[np.ndarray] = []
    val_chunks: List[Optional[np.ndarray]] = []
    slot_chunks: List[np.ndarray] = []
    for slot_id, keys, vals in slots:
        if slot_id == 0:
            if vals is not None and vals.size:
                label = float(vals[0])
            continue
        key_chunks.append(keys)
        val_chunks.append(vals)
        slot_chunks.append(np.full(keys.size, slot_id, dtype=np.int32))
    return label, key_chunks, val_chunks, slot_chunks


def rows_to_batch(rows: List[Row]) -> SparseBatch:
    """Assemble decoded rows into one SparseBatch. ``values`` is None
    (binary) when NO slot in the batch carries vals, else missing vals
    default to 1.0 (the reference's binary()/values duality,
    sparse_matrix.h)."""
    ys = [r[0] for r in rows]
    indptr = np.zeros(len(rows) + 1, np.int64)
    key_chunks: List[np.ndarray] = []
    val_chunks: List[Optional[np.ndarray]] = []
    slot_chunks: List[np.ndarray] = []
    for i, (_, kc, vc, sc) in enumerate(rows):
        indptr[i + 1] = indptr[i] + sum(k.size for k in kc)
        key_chunks += kc
        val_chunks += vc
        slot_chunks += sc
    any_vals = any(v is not None for v in val_chunks)
    if any_vals:
        values = np.concatenate(
            [
                v if v is not None else np.ones(k.size, np.float32)
                for k, v in zip(key_chunks, val_chunks)
            ]
        ) if key_chunks else np.zeros(0, np.float32)
    else:
        values = None
    indices = (
        np.concatenate(key_chunks).view(np.int64)
        if key_chunks else np.zeros(0, np.int64)
    )
    return SparseBatch(
        y=np.asarray(ys, dtype=np.float32),
        indptr=indptr,
        indices=indices,
        values=values,
        slot_ids=(
            np.concatenate(slot_chunks)
            if slot_chunks else np.zeros(0, np.int32)
        ),
    )


def read_ref_batch(
    path: str, max_examples: Optional[int] = None
) -> SparseBatch:
    """Read a reference ``Example`` recordio file into one SparseBatch
    (see :func:`example_slots_to_row` for the slot conventions)."""
    rows: List[Row] = []
    for payload in iter_ref_records(path):
        if max_examples is not None and len(rows) >= max_examples:
            break
        rows.append(example_slots_to_row(decode_example(payload)))
    return rows_to_batch(rows)


def batch_to_ref_payloads(batch: SparseBatch) -> Iterator[bytes]:
    """SparseBatch -> one ``Example`` payload per row (slot 0 = label,
    features grouped by slot id; binary batches emit keys only)."""
    slot_ids = batch.slot_ids
    idx = batch.indices.view(np.uint64)
    for r in range(batch.n):
        lo, hi = int(batch.indptr[r]), int(batch.indptr[r + 1])
        slots = [(0, np.zeros(0, np.uint64),
                  np.asarray([batch.y[r]], np.float32))]
        row_slots = (
            slot_ids[lo:hi] if slot_ids is not None
            else np.ones(hi - lo, np.int32)
        )
        for sid in np.unique(row_slots):
            sel = np.flatnonzero(row_slots == sid) + lo
            vals = None if batch.values is None else batch.values[sel]
            slots.append((int(sid), idx[sel], vals))
        yield encode_example(slots)


def write_ref_batch(path: str, batch: SparseBatch) -> int:
    """Write a SparseBatch as reference ``Example`` records. Returns
    the record count — one per example."""
    return write_ref_records(path, batch_to_ref_payloads(batch))


# ---------------------------------------------------------------------------
# ExampleInfo ASCII sidecar (ref text2proto.h writeProtoToASCIIFile)
# ---------------------------------------------------------------------------

def parse_info_ascii(text: str) -> ExampleInfo:
    """Parse an ``ExampleInfo`` written in protobuf ASCII text format::

        slot {
          format: SPARSE_BINARY
          id: 1
          min_key: 5
          ...
        }
        num_ex: 100

    Only this frozen grammar (nested ``slot`` blocks + scalar fields)
    is accepted — it is what the reference emits for ``.info`` files."""
    info = ExampleInfo()
    cur: Optional[SlotInfo] = None
    for raw in text.splitlines():
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        if line.startswith("slot") and line.endswith("{"):
            cur = SlotInfo()
            continue
        if line == "}":
            if cur is not None:
                info.slot.append(cur)
            cur = None
            continue
        if ":" not in line:
            raise ValueError(f"unparseable .info line: {raw!r}")
        key, val = (t.strip() for t in line.split(":", 1))
        if cur is None:
            if key == "num_ex":
                info.num_ex = int(val)
            continue  # unknown top-level scalars are ignorable
        if key == "format":
            cur.format = (
                _FORMAT_FROM_ENUM[int(val)] if val.isdigit()
                else val.lower()
            )
        elif key == "id":
            cur.id = int(val)
        elif key in ("min_key", "max_key", "nnz_ele", "nnz_ex"):
            setattr(cur, key, int(val))
    info.slot.sort(key=lambda s: s.id)
    return info


def format_info_ascii(info: ExampleInfo) -> str:
    """Inverse of :func:`parse_info_ascii` (reference-compatible)."""
    lines = []
    for s in info.slot:
        lines += [
            "slot {",
            f"  format: {s.format.upper()}",
            f"  id: {s.id}",
            f"  min_key: {s.min_key}",
            f"  max_key: {s.max_key}",
            f"  nnz_ele: {s.nnz_ele}",
            f"  nnz_ex: {s.nnz_ex}",
            "}",
        ]
    lines.append(f"num_ex: {info.num_ex}")
    return "\n".join(lines) + "\n"
