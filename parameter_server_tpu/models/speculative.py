"""Speculative decoding (Leviathan et al. 2023, greedy variant).

A small DRAFT model proposes ``gamma`` tokens autoregressively; the
TARGET model scores the whole proposal in ONE multi-token cache pass
(a (gamma+1)-wide chunk instead of gamma+1 sequential decode steps —
the MXU sees a batched matmul and the weights are read once per
round). Greedy acceptance keeps the longest prefix where the draft's
token equals the target's argmax, then appends the target's
correction — so the output is GUARANTEED token-for-token equal to
plain greedy decoding of the target model; the only thing speculation
changes is how many target passes it takes. The reference has no
serving path at all (extension, alongside lm_generate).

Cache invariant (both models): at round start every position
``< committed-1`` is cached; the slot at ``committed-1`` (the last
committed token, round input x0) is written DURING the round — the
draft writes it decoding proposal 1, the target writes it verifying
the chunk. The draft runs one EXTRA step so the last proposal's own
slot is written too (a fully-accepted round advances past it; an
unwritten slot would sit as silent zeros inside every later mask).
Rejected proposals leave stale slots past the committed point; each
stale slot is overwritten by a later round's write BEFORE the first
query whose mask includes it.

Batch rows accept different prefix lengths, so positions are
PER-ROW (``committed [B]``) — unlike lm_generate's scalar scan
position. Rows that finish early keep re-processing their last slot
(capped commit) until the slowest row completes; compute per round is
static.
"""

from __future__ import annotations

import functools
from typing import Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from .transformer import (
    LMConfig,
    _alloc_kv_caches,
    _chunk_decode,
    _prefill,
)


def _accept_and_correct(key, d, p_d, p_t):
    """The Leviathan accept/reject core, pure so its distribution
    guarantee is statistically testable in isolation.

    ``d`` [B, g] sampled draft proposals, ``p_d`` [B, g, V] the draft
    probabilities they were sampled from, ``p_t`` [B, g+1, V] target
    probabilities at the same positions (row g is the bonus position
    after all proposals). Position j's proposal is accepted with
    probability ``min(1, p_t[j][d_j] / p_d[j][d_j])``; ``n`` is the
    count of leading accepts, and the correction token at position n
    is sampled from the normalized residual ``max(p_t[n] - p_d[n], 0)``
    (plain ``p_t[g]`` at the bonus position, where there is no draft).
    The marginal of the emitted token at every position is EXACTLY the
    target distribution (Leviathan et al. 2023, Thm 1).

    Returns (n [B], commit_row [B, g+1]): commit_row[j] = d[j] for
    j < n, the correction sample at j = n, undefined beyond."""
    b, g = d.shape
    rows = jnp.arange(b)
    k_u, k_c = jax.random.split(key)
    u = jax.random.uniform(k_u, (b, g))
    pd_at = jnp.take_along_axis(p_d, d[..., None], axis=-1)[..., 0]
    pt_at = jnp.take_along_axis(p_t[:, :g], d[..., None], axis=-1)[..., 0]
    accept = u * jnp.maximum(pd_at, 1e-30) < pt_at  # u < pt/pd
    n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    # residual at the rejection position; at the bonus position (n=g)
    # there is no draft, so the "residual" is the target row itself
    # (p_d extended with zeros)
    p_d_ext = jnp.concatenate([p_d, jnp.zeros_like(p_t[:, :1])], axis=1)
    resid = jnp.maximum(p_t[rows, n] - p_d_ext[rows, n], 0.0)  # [B, V]
    mass = resid.sum(-1, keepdims=True)
    # mass == 0 only when p_t <= p_d everywhere, i.e. p_t == p_d — then
    # the rejection probability was 0; fall back to p_t for safety
    resid = jnp.where(mass > 1e-12, resid, p_t[rows, n])
    correction = jax.random.categorical(
        k_c, jnp.log(jnp.maximum(resid, 1e-30)), axis=-1
    ).astype(jnp.int32)
    j_idx = jnp.arange(g + 1)[None, :]
    commit_row = jnp.where(
        j_idx < n[:, None],
        jnp.pad(d, ((0, 0), (0, 1))),
        correction[:, None],
    )
    return n, commit_row


def speculative_generate(
    target_params: Dict[str, jax.Array],
    target_cfg: LMConfig,
    draft_params: Dict[str, jax.Array],
    draft_cfg: LMConfig,
    prompt: jax.Array,  # [B, P] int32
    steps: int,
    *,
    gamma: int = 4,
    prompt_lengths: "jax.Array | None" = None,
    eos_id: "int | None" = None,
    temperature: "float | None" = None,
    key: "jax.Array | None" = None,
    return_stats: bool = False,
) -> "jax.Array | Tuple[jax.Array, Dict[str, jax.Array]]":
    """Speculative decoding that provably matches decoding the target
    model directly.

    ``temperature=None`` (or 0) is the GREEDY variant: token-for-token
    equal to ``lm_generate(target_params, ..., temperature=None)`` —
    verified by tests — in ~``steps / (1 + mean_accepted)`` target
    passes instead of ``steps``. ``temperature > 0`` is the SAMPLED
    variant (Leviathan et al. 2023): the draft samples its proposals,
    each is accepted with probability ``min(1, p_t/p_d)``, rejections
    sample the normalized residual ``max(p_t - p_d, 0)`` — the emitted
    distribution at every position is exactly the target's
    softmax(logits/temperature) (the acceptance core is the pure
    ``_accept_and_correct``, statistically pinned by tests); sampling
    needs ``key``.

    ``gamma``: draft proposals per round. Both configs must share the
    vocab; windows/rope/GQA/bf16/int8-cache compose per model
    independently (each model runs its OWN config against its own
    cache), and MoE targets/drafts are served with dropless routing
    (transformer._moe_ffn_dropless; exactness pinned in
    tests/test_moe_serving.py).

    ``prompt_lengths`` [B] enables RAGGED batches (same contract as
    ``lm_generate``): right-padded prompts, each row speculating from
    its own length, output row b's continuation at
    ``[len_b, len_b + steps)`` with zeros beyond — and, greedy, every
    row EXACTLY equal to plain greedy decode of its unpadded prompt.
    The per-row ``committed`` clocks the core already keeps make this
    a parametrization, not a new path: pad-garbage cache slots obey
    the same overwrite-before-admissible invariant as stale rejected
    proposals (rounds write contiguous chunks from the row's front, so
    no hole is ever attended).

    ``eos_id``: a row that COMMITS the stop token finishes — the
    commit is clamped at the eos and the rest of the row's budget
    stays pad 0; greedy output exactly matches
    ``lm_generate(eos_id=)``'s "eos then pads" (tested). Works in the
    sampled variant too (tokens before the stop keep the target
    distribution).

    ``return_stats=True`` additionally returns
    ``{"rounds": r, "target_passes": r, "accepted_frac": f}`` —
    ``accepted_frac`` is the fraction of draft proposals that were
    accepted AND committed, counted only while a row was still live
    (finished rows keep spinning until the slowest row completes, and
    their idle work must not skew the number that decides whether a
    draft model pays for itself)."""
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"vocab mismatch: target {target_cfg.vocab} vs draft "
            f"{draft_cfg.vocab} — the models must share a tokenizer"
        )
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if eos_id is not None and not 0 <= eos_id < target_cfg.vocab:
        raise ValueError(
            f"eos_id must be in [0, vocab={target_cfg.vocab}), got {eos_id}"
        )
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    # mirror lm_generate's contract: greedy detection needs a CONCRETE
    # Python number (a jax Array would make `greedy` — a static
    # argument — non-hashable); a traced/Array temperature is treated
    # as sampling, so sweeping it never recompiles
    concrete = isinstance(temperature, (int, float))
    greedy = temperature is None or (concrete and temperature == 0)
    if not greedy:
        if concrete and temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if key is None:
            raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.PRNGKey(0)  # unused by the greedy path
    if prompt_lengths is None:
        lengths = jnp.full(prompt.shape[0], prompt.shape[1], jnp.int32)
    else:
        from .transformer import _validate_prompt_lengths

        lengths = _validate_prompt_lengths(prompt_lengths, prompt)
    return _spec_jit(
        target_params, draft_params, prompt, lengths,
        jnp.asarray(1.0 if greedy else temperature, jnp.float32),
        jnp.asarray(0 if eos_id is None else eos_id, jnp.int32), key,
        tcfg=target_cfg, dcfg=draft_cfg, steps=steps, gamma=gamma,
        greedy=greedy, has_eos=eos_id is not None,
        return_stats=return_stats,
    )


@functools.partial(
    jax.jit, static_argnames=("tcfg", "dcfg", "steps", "gamma", "greedy",
                              "has_eos", "return_stats")
)
def _spec_jit(tparams, dparams, prompt, lengths, temperature, eos, key, *,
              tcfg, dcfg, steps, gamma, greedy, has_eos, return_stats):
    b, p_len = prompt.shape
    # per-row budget: row b decodes until lengths[b] + steps (for dense
    # batches lengths == p_len everywhere and this is the old scalar)
    limit = lengths + steps  # [B]
    # slack: a round can overshoot by gamma tokens + 1 trash slot
    total = p_len + steps + gamma + 1
    trash = total - 1  # masked-commit writes land here, never read
    tk, tv = _alloc_kv_caches(tcfg, b, total)
    dk, dv = _alloc_kv_caches(dcfg, b, total)
    prompt = prompt.astype(jnp.int32)
    # prefill BOTH models on the prompt (slots [0, p_len); for ragged
    # rows the pad slots' garbage obeys the overwrite-before-admissible
    # invariant — see speculative_generate docstring)
    t_logits, tk, tv = _prefill(tparams, tcfg, prompt, tk, tv)
    _, dk, dv = _prefill(dparams, dcfg, prompt, dk, dv)
    col = jnp.arange(p_len)
    toks = jnp.zeros((b, total), jnp.int32).at[:, :p_len].set(
        jnp.where(col[None, :] < lengths[:, None], prompt, 0)
    )
    rows = jnp.arange(b)
    # first committed token: each row's target-prefill logits at ITS
    # last real position
    last = t_logits[rows, lengths - 1]
    key, k0 = jax.random.split(key)
    if greedy:
        first = jnp.argmax(last, axis=-1)
    else:
        first = jax.random.categorical(k0, last / temperature, axis=-1)
    toks = toks.at[rows, lengths].set(first.astype(jnp.int32))
    committed = lengths + 1
    if has_eos:
        # a first token that IS the stop token finishes the row now
        committed = jnp.where(first.astype(jnp.int32) == eos, limit,
                              committed)

    def round_body(carry):
        toks, committed, tk, tv, dk, dv, key, rounds, acc, prop = carry
        live = committed < limit  # rows still decoding at round start
        x0 = toks[rows, committed - 1]  # [B] last committed token
        # -- draft: gamma sequential proposals (C=1 chunk steps) --
        key, k_acc, *k_draft = jax.random.split(key, 2 + gamma)
        d_toks = []
        d_probs = []
        cur = x0
        for j in range(gamma):
            dl, dk, dv = _chunk_decode(
                dparams, dcfg, cur[:, None], dk, dv, committed - 1 + j
            )
            if greedy:
                cur = jnp.argmax(dl[:, 0], axis=-1).astype(jnp.int32)
            else:
                z = dl[:, 0] / temperature
                cur = jax.random.categorical(
                    k_draft[j], z, axis=-1
                ).astype(jnp.int32)
                d_probs.append(jax.nn.softmax(z, axis=-1))
            d_toks.append(cur)
        # one extra draft step processes d_gamma itself: its K/V slot
        # (committed-1+gamma) would otherwise NEVER be written, and on a
        # fully-accepted round the next round starts past it — the hole
        # would sit inside every later query's mask as silent zeros,
        # eroding draft quality (and so acceptance) forever. For
        # partially-accepted rows this write is stale, but every stale
        # slot is overwritten by a later round's draft step BEFORE the
        # first query whose mask includes it (write-then-attend within a
        # step). The produced logits are deliberately unused.
        _, dk, dv = _chunk_decode(
            dparams, dcfg, cur[:, None], dk, dv, committed - 1 + gamma
        )
        d = jnp.stack(d_toks, axis=1)  # [B, gamma]
        # -- target: ONE (gamma+1)-chunk verify over [x0, d1..dg] --
        chunk = jnp.concatenate([x0[:, None], d], axis=1)
        tl, tk, tv = _chunk_decode(
            tparams, tcfg, chunk, tk, tv, committed - 1
        )
        j_idx = jnp.arange(gamma + 1)[None, :]
        if greedy:
            tpred = jnp.argmax(tl, axis=-1).astype(jnp.int32)  # [B, g+1]
            # greedy acceptance: longest prefix where d[j] == tpred[j]
            agree = d == tpred[:, :gamma]  # [B, gamma]
            n = jnp.sum(
                jnp.cumprod(agree.astype(jnp.int32), axis=1), axis=1
            )
            correction = tpred[rows, n]  # [B]
            commit_row = jnp.where(
                j_idx < n[:, None],
                jnp.pad(d, ((0, 0), (0, 1))),  # d[j] for j < n
                correction[:, None],  # at j == n; masked out beyond
            )
        else:
            p_t = jax.nn.softmax(tl / temperature, axis=-1)  # [B, g+1, V]
            n, commit_row = _accept_and_correct(
                k_acc, d, jnp.stack(d_probs, axis=1), p_t
            )
        # capped commit: a finished row re-processes its last slot
        # instead of overflowing the buffer
        n_eff = jnp.minimum(n + 1, limit - committed)
        if has_eos:
            # clamp at the first stop token inside the commit: tokens
            # past it never land ("eos then pads" — toks stays 0
            # there), and the row freezes below
            is_eos = (commit_row == eos) & (j_idx < n_eff[:, None])
            first_eos = jnp.min(
                jnp.where(is_eos, j_idx, gamma + 1), axis=1
            )  # [B]; gamma+1 = none
            n_eff = jnp.minimum(n_eff, first_eos + 1)
        dest = jnp.where(
            j_idx < n_eff[:, None], committed[:, None] + j_idx, trash
        )
        toks = toks.at[rows[:, None], dest].set(commit_row)
        committed = committed + n_eff
        if has_eos:
            committed = jnp.where(first_eos <= gamma, limit, committed)
        # stats count only LIVE rows and only accepted-AND-committed
        # proposals (a capped commit may truncate the accepted run)
        acc = acc + jnp.sum(jnp.where(live, jnp.minimum(n, n_eff), 0))
        prop = prop + jnp.sum(jnp.where(live, gamma, 0))
        return (toks, committed, tk, tv, dk, dv, key, rounds + 1, acc,
                prop)

    def cond(carry):
        return jnp.any(carry[1] < limit)

    toks, committed, *_, rounds, acc, prop = jax.lax.while_loop(
        cond,
        round_body,
        (toks, committed, tk, tv, dk, dv, key, jnp.int32(0), jnp.int32(0),
         jnp.int32(0)),
    )
    out = toks[:, : p_len + steps]
    if not return_stats:
        return out
    stats = {
        "rounds": rounds,
        "target_passes": rounds,
        "accepted_frac": acc / jnp.maximum(prop, 1),
    }
    return out, stats


# -- continuous batching: the round-stepped API ---------------------------
#
# serving/batcher.py runs ONE speculative-decode state machine for many
# concurrent sessions (Orca-style iteration-level scheduling): the body
# of _spec_jit's while_loop is lifted out so the HOST decides, between
# rounds, when to step, who joins a free slot and who retires.
# ``spec_batch_alloc`` builds the shared fixed-capacity state,
# ``_spec_join_jit`` prefills one session into a slot at a round
# boundary, ``_spec_round_jit`` advances every slot by one speculative
# round. GREEDY only: the batcher's correctness contract is
# token-for-token parity with each session's own sequential
# ``speculative_generate(temperature=None)`` run, and greedy is the
# variant with a deterministic stream to pin.
#
# Slot lifecycle is encoded entirely in (committed, limit): a FREE or
# retired slot has ``committed == limit``, so its per-round commit is
# capped at zero tokens (everything lands in the trash slot) and its
# toks row never changes after retirement — the host can read it out
# at leisure. The slot's cache rows keep receiving garbage writes while
# idle; they obey the same overwrite-before-admissible invariant as
# rejected proposals (a join's prefill rewrites [0, max_prompt) and the
# contiguous round windows rewrite every later position before the
# first query whose mask includes it), so a rejoin is exact.
#
# Per-row ``eos`` uses -1 as the "no stop token" sentinel: vocab ids
# are >= 0, so -1 never matches a commit and the eos math degenerates
# to the has_eos=False path row-wise — one compiled round serves mixed
# eos/no-eos sessions.


class SpecBatchState(NamedTuple):
    """Device-resident state of one continuous decode batch (a pytree:
    passes through jit whole). ``toks [S, cap]`` the committed token
    rows, ``committed/limit/eos [S]`` per-slot clocks (free slot ==
    ``committed == limit``), plus both models' KV caches at batch
    capacity. ``cap`` must be ``max_prompt + max_new + gamma + 1``
    (speculation overshoot + trash slot — same slack as _spec_jit)."""

    toks: jax.Array
    committed: jax.Array
    limit: jax.Array
    eos: jax.Array
    tk: Tuple
    tv: Tuple
    dk: Tuple
    dv: Tuple


def spec_batch_alloc(
    tcfg: LMConfig, dcfg: LMConfig, slots: int, capacity: int
) -> SpecBatchState:
    """A fresh all-slots-free batch state. ``committed = limit = 1`` (not
    0) so an idle slot's round input ``toks[s, committed-1]`` indexes a
    valid position; idle rows decode garbage whose commits are capped to
    the trash slot."""
    if tcfg.vocab != dcfg.vocab:
        raise ValueError(
            f"vocab mismatch: target {tcfg.vocab} vs draft {dcfg.vocab} "
            "— the models must share a tokenizer"
        )
    if slots < 1:
        raise ValueError(f"slots must be >= 1, got {slots}")
    tk, tv = _alloc_kv_caches(tcfg, slots, capacity)
    dk, dv = _alloc_kv_caches(dcfg, slots, capacity)
    return SpecBatchState(
        toks=jnp.zeros((slots, capacity), jnp.int32),
        committed=jnp.ones((slots,), jnp.int32),
        limit=jnp.ones((slots,), jnp.int32),
        eos=jnp.full((slots,), -1, jnp.int32),
        tk=tk, tv=tv, dk=dk, dv=dv,
    )


@functools.partial(
    jax.jit, static_argnames=("tcfg", "dcfg"), donate_argnums=(2,)
)
def _spec_join_jit(tparams, dparams, state, prompt, length, steps, eos_id,
                   slot, *, tcfg, dcfg):
    """Admit one session into ``slot`` at a round boundary: prefill both
    models on the (padded, fixed-width) ``prompt [1, P]``, scatter the
    single-row caches into the batch caches, commit the first target
    token — exactly _spec_jit's pre-loop phase, per slot. ``length``,
    ``steps``, ``eos_id`` (-1 = none) and ``slot`` are traced scalars,
    so joins at any slot share ONE compilation per prompt width."""
    cap = state.toks.shape[1]
    p_len = prompt.shape[1]
    prompt = prompt.astype(jnp.int32)
    rtk, rtv = _alloc_kv_caches(tcfg, 1, cap)
    rdk, rdv = _alloc_kv_caches(dcfg, 1, cap)
    t_logits, rtk, rtv = _prefill(tparams, tcfg, prompt, rtk, rtv)
    _, rdk, rdv = _prefill(dparams, dcfg, prompt, rdk, rdv)

    def scatter(full, row):
        # full [L, S, kvh, T, ...], row [L, 1, kvh, T, ...] — works for
        # both cache data and (optional) int8 scale leaves
        return full.at[:, slot].set(row[:, 0])

    tk = jax.tree.map(scatter, state.tk, rtk)
    tv = jax.tree.map(scatter, state.tv, rtv)
    dk = jax.tree.map(scatter, state.dk, rdk)
    dv = jax.tree.map(scatter, state.dv, rdv)
    col = jnp.arange(p_len)
    row_toks = jnp.zeros((cap,), jnp.int32).at[:p_len].set(
        jnp.where(col < length, prompt[0], 0)
    )
    # first committed token: the target prefill's logits at the row's
    # last real position (greedy — the batcher contract)
    first = jnp.argmax(t_logits[0, length - 1], axis=-1).astype(jnp.int32)
    row_toks = row_toks.at[length].set(first)
    limit_new = length + steps
    # a first token that IS the stop token finishes the session now
    committed_new = jnp.where(first == eos_id, limit_new, length + 1)
    return SpecBatchState(
        toks=state.toks.at[slot].set(row_toks),
        committed=state.committed.at[slot].set(committed_new),
        limit=state.limit.at[slot].set(limit_new),
        eos=state.eos.at[slot].set(eos_id),
        tk=tk, tv=tv, dk=dk, dv=dv,
    )


@functools.partial(
    jax.jit, static_argnames=("tcfg", "dcfg"), donate_argnums=(2,)
)
def _spec_join_many_jit(tparams, dparams, state, prompts, lengths, steps,
                        eos_ids, slots, *, tcfg, dcfg):
    """Admit R sessions in ONE call: the vectorized `_spec_join_jit`.

    Per-row join cost is dominated by fixed per-call dispatch (the
    prefill itself is a handful of matmuls), so a wave of joiners pays
    it R times when admitted one by one. Here both prefills run over
    ``prompts [R, P]`` at once and all R rows scatter into the batch in
    one update. Callers pad R to a power of two BY REPEATING THE LAST
    ROW (same slot, same values — duplicate scatter indices then write
    identical data, so XLA's pick-any-duplicate semantics is harmless),
    which bounds compilations to log2(slots)+1 per prompt width.
    ``lengths``/``steps``/``eos_ids``/``slots`` are traced ``[R]``
    vectors (per-row eos lets one wave mix requests)."""
    cap = state.toks.shape[1]
    r, p_len = prompts.shape
    prompts = prompts.astype(jnp.int32)
    rtk, rtv = _alloc_kv_caches(tcfg, r, cap)
    rdk, rdv = _alloc_kv_caches(dcfg, r, cap)
    t_logits, rtk, rtv = _prefill(tparams, tcfg, prompts, rtk, rtv)
    _, rdk, rdv = _prefill(dparams, dcfg, prompts, rdk, rdv)

    def scatter(full, rows):
        # full [L, S, kvh, T, ...], rows [L, R, kvh, T, ...]
        return full.at[:, slots].set(rows)

    tk = jax.tree.map(scatter, state.tk, rtk)
    tv = jax.tree.map(scatter, state.tv, rtv)
    dk = jax.tree.map(scatter, state.dk, rdk)
    dv = jax.tree.map(scatter, state.dv, rdv)
    col = jnp.arange(p_len)[None, :]
    row_toks = jnp.zeros((r, cap), jnp.int32).at[:, :p_len].set(
        jnp.where(col < lengths[:, None], prompts, 0)
    )
    # first committed token per row: target prefill logits at each
    # row's last real position (greedy — the batcher contract)
    last = jnp.take_along_axis(
        t_logits, (lengths - 1)[:, None, None], axis=1
    )[:, 0]
    first = jnp.argmax(last, axis=-1).astype(jnp.int32)
    row_toks = row_toks.at[jnp.arange(r), lengths].set(first)
    limit_new = lengths + steps
    committed_new = jnp.where(first == eos_ids, limit_new, lengths + 1)
    return SpecBatchState(
        toks=state.toks.at[slots].set(row_toks),
        committed=state.committed.at[slots].set(committed_new),
        limit=state.limit.at[slots].set(limit_new),
        eos=state.eos.at[slots].set(eos_ids),
        tk=tk, tv=tv, dk=dk, dv=dv,
    )


def _round_core(tparams, dparams, state, tcfg, dcfg, gamma):
    """One speculative round over the whole batch — _spec_jit's
    ``round_body``, greedy branch, with per-row eos. Returns
    ``(state, accepted, proposed)``; the stats count only live slots so
    idle-slot spin never skews the acceptance rate. Traced helper
    shared by :func:`_spec_round_jit` (one round per dispatch) and
    :func:`_spec_round_block_jit` (K rounds fused in one dispatch)."""
    toks, committed, limit, eos = (
        state.toks, state.committed, state.limit, state.eos,
    )
    tk, tv, dk, dv = state.tk, state.tv, state.dk, state.dv
    b, total = toks.shape
    trash = total - 1
    rows = jnp.arange(b)
    live = committed < limit
    x0 = toks[rows, committed - 1]
    d_toks = []
    cur = x0
    for j in range(gamma):
        dl, dk, dv = _chunk_decode(
            dparams, dcfg, cur[:, None], dk, dv, committed - 1 + j
        )
        cur = jnp.argmax(dl[:, 0], axis=-1).astype(jnp.int32)
        d_toks.append(cur)
    # the extra draft step (see round_body): writes d_gamma's own slot
    _, dk, dv = _chunk_decode(
        dparams, dcfg, cur[:, None], dk, dv, committed - 1 + gamma
    )
    d = jnp.stack(d_toks, axis=1)
    chunk = jnp.concatenate([x0[:, None], d], axis=1)
    tl, tk, tv = _chunk_decode(tparams, tcfg, chunk, tk, tv, committed - 1)
    j_idx = jnp.arange(gamma + 1)[None, :]
    tpred = jnp.argmax(tl, axis=-1).astype(jnp.int32)
    agree = d == tpred[:, :gamma]
    n = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1), axis=1)
    correction = tpred[rows, n]
    commit_row = jnp.where(
        j_idx < n[:, None],
        jnp.pad(d, ((0, 0), (0, 1))),
        correction[:, None],
    )
    n_eff = jnp.minimum(n + 1, limit - committed)
    is_eos = (commit_row == eos[:, None]) & (j_idx < n_eff[:, None])
    first_eos = jnp.min(jnp.where(is_eos, j_idx, gamma + 1), axis=1)
    n_eff = jnp.minimum(n_eff, first_eos + 1)
    dest = jnp.where(
        j_idx < n_eff[:, None], committed[:, None] + j_idx, trash
    )
    toks = toks.at[rows[:, None], dest].set(commit_row)
    committed = committed + n_eff
    committed = jnp.where(first_eos <= gamma, limit, committed)
    acc = jnp.sum(jnp.where(live, jnp.minimum(n, n_eff), 0))
    prop = jnp.sum(jnp.where(live, gamma, 0))
    return (
        SpecBatchState(
            toks=toks, committed=committed, limit=limit, eos=eos,
            tk=tk, tv=tv, dk=dk, dv=dv,
        ),
        acc,
        prop,
    )


@functools.partial(
    jax.jit, static_argnames=("tcfg", "dcfg", "gamma"), donate_argnums=(2,)
)
def _spec_round_jit(tparams, dparams, state, *, tcfg, dcfg, gamma):
    """One round, one dispatch — see :func:`_round_core`."""
    return _round_core(tparams, dparams, state, tcfg, dcfg, gamma)


@functools.partial(
    jax.jit, static_argnames=("tcfg", "dcfg", "gamma"), donate_argnums=(2,)
)
def _spec_round_block_jit(tparams, dparams, state, k, *, tcfg, dcfg,
                          gamma):
    """``k`` rounds FUSED into one dispatch (``k`` is a traced scalar,
    so every block size shares one compilation).

    The host-stepped loop pays a fixed per-dispatch cost every round —
    argument marshalling, donation bookkeeping, per-op launch — that a
    round executed inside a compiled loop does not (the same ops run
    ~10x cheaper per round inside ``speculative_generate``'s fused
    while_loop; that gap is most of the batched lane's overhead at
    small occupancy). Fusing K rounds amortizes it K-fold. The batcher
    picks K so that NO row can reach its limit inside the block
    (``ceil(min_remaining / (gamma+1))`` — a round commits at most
    gamma+1 tokens), so fusion never delays a retirement and never
    spins a finished row; rows CAN finish early via per-row eos, which
    is why the batcher drops to single-round stepping while any
    eos-armed session is resident."""
    def body(_, carry):
        st, a, p = carry
        st, acc, prop = _round_core(tparams, dparams, st, tcfg, dcfg,
                                    gamma)
        return st, a + acc, p + prop

    return jax.lax.fori_loop(
        0, k, body, (state, jnp.int32(0), jnp.int32(0))
    )
