"""Speculative decoding (Leviathan et al. 2023, greedy variant).

A small DRAFT model proposes ``gamma`` tokens autoregressively; the
TARGET model scores the whole proposal in ONE multi-token cache pass
(a (gamma+1)-wide chunk instead of gamma+1 sequential decode steps —
the MXU sees a batched matmul and the weights are read once per
round). Greedy acceptance keeps the longest prefix where the draft's
token equals the target's argmax, then appends the target's
correction — so the output is GUARANTEED token-for-token equal to
plain greedy decoding of the target model; the only thing speculation
changes is how many target passes it takes. The reference has no
serving path at all (extension, alongside lm_generate).

Cache invariant (both models): at round start every position
``< committed-1`` is cached; the slot at ``committed-1`` (the last
committed token, round input x0) is written DURING the round — the
draft writes it decoding proposal 1, the target writes it verifying
the chunk. The draft runs one EXTRA step so the last proposal's own
slot is written too (a fully-accepted round advances past it; an
unwritten slot would sit as silent zeros inside every later mask).
Rejected proposals leave stale slots past the committed point; each
stale slot is overwritten by a later round's write BEFORE the first
query whose mask includes it.

Batch rows accept different prefix lengths, so positions are
PER-ROW (``committed [B]``) — unlike lm_generate's scalar scan
position. Rows that finish early keep re-processing their last slot
(capped commit) until the slowest row completes; compute per round is
static.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .transformer import (
    LMConfig,
    _alloc_kv_caches,
    _chunk_decode,
    _prefill,
)


def _accept_and_correct(key, d, p_d, p_t):
    """The Leviathan accept/reject core, pure so its distribution
    guarantee is statistically testable in isolation.

    ``d`` [B, g] sampled draft proposals, ``p_d`` [B, g, V] the draft
    probabilities they were sampled from, ``p_t`` [B, g+1, V] target
    probabilities at the same positions (row g is the bonus position
    after all proposals). Position j's proposal is accepted with
    probability ``min(1, p_t[j][d_j] / p_d[j][d_j])``; ``n`` is the
    count of leading accepts, and the correction token at position n
    is sampled from the normalized residual ``max(p_t[n] - p_d[n], 0)``
    (plain ``p_t[g]`` at the bonus position, where there is no draft).
    The marginal of the emitted token at every position is EXACTLY the
    target distribution (Leviathan et al. 2023, Thm 1).

    Returns (n [B], commit_row [B, g+1]): commit_row[j] = d[j] for
    j < n, the correction sample at j = n, undefined beyond."""
    b, g = d.shape
    rows = jnp.arange(b)
    k_u, k_c = jax.random.split(key)
    u = jax.random.uniform(k_u, (b, g))
    pd_at = jnp.take_along_axis(p_d, d[..., None], axis=-1)[..., 0]
    pt_at = jnp.take_along_axis(p_t[:, :g], d[..., None], axis=-1)[..., 0]
    accept = u * jnp.maximum(pd_at, 1e-30) < pt_at  # u < pt/pd
    n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    # residual at the rejection position; at the bonus position (n=g)
    # there is no draft, so the "residual" is the target row itself
    # (p_d extended with zeros)
    p_d_ext = jnp.concatenate([p_d, jnp.zeros_like(p_t[:, :1])], axis=1)
    resid = jnp.maximum(p_t[rows, n] - p_d_ext[rows, n], 0.0)  # [B, V]
    mass = resid.sum(-1, keepdims=True)
    # mass == 0 only when p_t <= p_d everywhere, i.e. p_t == p_d — then
    # the rejection probability was 0; fall back to p_t for safety
    resid = jnp.where(mass > 1e-12, resid, p_t[rows, n])
    correction = jax.random.categorical(
        k_c, jnp.log(jnp.maximum(resid, 1e-30)), axis=-1
    ).astype(jnp.int32)
    j_idx = jnp.arange(g + 1)[None, :]
    commit_row = jnp.where(
        j_idx < n[:, None],
        jnp.pad(d, ((0, 0), (0, 1))),
        correction[:, None],
    )
    return n, commit_row


def speculative_generate(
    target_params: Dict[str, jax.Array],
    target_cfg: LMConfig,
    draft_params: Dict[str, jax.Array],
    draft_cfg: LMConfig,
    prompt: jax.Array,  # [B, P] int32
    steps: int,
    *,
    gamma: int = 4,
    prompt_lengths: "jax.Array | None" = None,
    eos_id: "int | None" = None,
    temperature: "float | None" = None,
    key: "jax.Array | None" = None,
    return_stats: bool = False,
) -> "jax.Array | Tuple[jax.Array, Dict[str, jax.Array]]":
    """Speculative decoding that provably matches decoding the target
    model directly.

    ``temperature=None`` (or 0) is the GREEDY variant: token-for-token
    equal to ``lm_generate(target_params, ..., temperature=None)`` —
    verified by tests — in ~``steps / (1 + mean_accepted)`` target
    passes instead of ``steps``. ``temperature > 0`` is the SAMPLED
    variant (Leviathan et al. 2023): the draft samples its proposals,
    each is accepted with probability ``min(1, p_t/p_d)``, rejections
    sample the normalized residual ``max(p_t - p_d, 0)`` — the emitted
    distribution at every position is exactly the target's
    softmax(logits/temperature) (the acceptance core is the pure
    ``_accept_and_correct``, statistically pinned by tests); sampling
    needs ``key``.

    ``gamma``: draft proposals per round. Both configs must share the
    vocab; windows/rope/GQA/bf16/int8-cache compose per model
    independently (each model runs its OWN config against its own
    cache), and MoE targets/drafts are served with dropless routing
    (transformer._moe_ffn_dropless; exactness pinned in
    tests/test_moe_serving.py).

    ``prompt_lengths`` [B] enables RAGGED batches (same contract as
    ``lm_generate``): right-padded prompts, each row speculating from
    its own length, output row b's continuation at
    ``[len_b, len_b + steps)`` with zeros beyond — and, greedy, every
    row EXACTLY equal to plain greedy decode of its unpadded prompt.
    The per-row ``committed`` clocks the core already keeps make this
    a parametrization, not a new path: pad-garbage cache slots obey
    the same overwrite-before-admissible invariant as stale rejected
    proposals (rounds write contiguous chunks from the row's front, so
    no hole is ever attended).

    ``eos_id``: a row that COMMITS the stop token finishes — the
    commit is clamped at the eos and the rest of the row's budget
    stays pad 0; greedy output exactly matches
    ``lm_generate(eos_id=)``'s "eos then pads" (tested). Works in the
    sampled variant too (tokens before the stop keep the target
    distribution).

    ``return_stats=True`` additionally returns
    ``{"rounds": r, "target_passes": r, "accepted_frac": f}`` —
    ``accepted_frac`` is the fraction of draft proposals that were
    accepted AND committed, counted only while a row was still live
    (finished rows keep spinning until the slowest row completes, and
    their idle work must not skew the number that decides whether a
    draft model pays for itself)."""
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"vocab mismatch: target {target_cfg.vocab} vs draft "
            f"{draft_cfg.vocab} — the models must share a tokenizer"
        )
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if eos_id is not None and not 0 <= eos_id < target_cfg.vocab:
        raise ValueError(
            f"eos_id must be in [0, vocab={target_cfg.vocab}), got {eos_id}"
        )
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    # mirror lm_generate's contract: greedy detection needs a CONCRETE
    # Python number (a jax Array would make `greedy` — a static
    # argument — non-hashable); a traced/Array temperature is treated
    # as sampling, so sweeping it never recompiles
    concrete = isinstance(temperature, (int, float))
    greedy = temperature is None or (concrete and temperature == 0)
    if not greedy:
        if concrete and temperature < 0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if key is None:
            raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if key is None:
        key = jax.random.PRNGKey(0)  # unused by the greedy path
    if prompt_lengths is None:
        lengths = jnp.full(prompt.shape[0], prompt.shape[1], jnp.int32)
    else:
        from .transformer import _validate_prompt_lengths

        lengths = _validate_prompt_lengths(prompt_lengths, prompt)
    return _spec_jit(
        target_params, draft_params, prompt, lengths,
        jnp.asarray(1.0 if greedy else temperature, jnp.float32),
        jnp.asarray(0 if eos_id is None else eos_id, jnp.int32), key,
        tcfg=target_cfg, dcfg=draft_cfg, steps=steps, gamma=gamma,
        greedy=greedy, has_eos=eos_id is not None,
        return_stats=return_stats,
    )


@functools.partial(
    jax.jit, static_argnames=("tcfg", "dcfg", "steps", "gamma", "greedy",
                              "has_eos", "return_stats")
)
def _spec_jit(tparams, dparams, prompt, lengths, temperature, eos, key, *,
              tcfg, dcfg, steps, gamma, greedy, has_eos, return_stats):
    b, p_len = prompt.shape
    # per-row budget: row b decodes until lengths[b] + steps (for dense
    # batches lengths == p_len everywhere and this is the old scalar)
    limit = lengths + steps  # [B]
    # slack: a round can overshoot by gamma tokens + 1 trash slot
    total = p_len + steps + gamma + 1
    trash = total - 1  # masked-commit writes land here, never read
    tk, tv = _alloc_kv_caches(tcfg, b, total)
    dk, dv = _alloc_kv_caches(dcfg, b, total)
    prompt = prompt.astype(jnp.int32)
    # prefill BOTH models on the prompt (slots [0, p_len); for ragged
    # rows the pad slots' garbage obeys the overwrite-before-admissible
    # invariant — see speculative_generate docstring)
    t_logits, tk, tv = _prefill(tparams, tcfg, prompt, tk, tv)
    _, dk, dv = _prefill(dparams, dcfg, prompt, dk, dv)
    col = jnp.arange(p_len)
    toks = jnp.zeros((b, total), jnp.int32).at[:, :p_len].set(
        jnp.where(col[None, :] < lengths[:, None], prompt, 0)
    )
    rows = jnp.arange(b)
    # first committed token: each row's target-prefill logits at ITS
    # last real position
    last = t_logits[rows, lengths - 1]
    key, k0 = jax.random.split(key)
    if greedy:
        first = jnp.argmax(last, axis=-1)
    else:
        first = jax.random.categorical(k0, last / temperature, axis=-1)
    toks = toks.at[rows, lengths].set(first.astype(jnp.int32))
    committed = lengths + 1
    if has_eos:
        # a first token that IS the stop token finishes the row now
        committed = jnp.where(first.astype(jnp.int32) == eos, limit,
                              committed)

    def round_body(carry):
        toks, committed, tk, tv, dk, dv, key, rounds, acc, prop = carry
        live = committed < limit  # rows still decoding at round start
        x0 = toks[rows, committed - 1]  # [B] last committed token
        # -- draft: gamma sequential proposals (C=1 chunk steps) --
        key, k_acc, *k_draft = jax.random.split(key, 2 + gamma)
        d_toks = []
        d_probs = []
        cur = x0
        for j in range(gamma):
            dl, dk, dv = _chunk_decode(
                dparams, dcfg, cur[:, None], dk, dv, committed - 1 + j
            )
            if greedy:
                cur = jnp.argmax(dl[:, 0], axis=-1).astype(jnp.int32)
            else:
                z = dl[:, 0] / temperature
                cur = jax.random.categorical(
                    k_draft[j], z, axis=-1
                ).astype(jnp.int32)
                d_probs.append(jax.nn.softmax(z, axis=-1))
            d_toks.append(cur)
        # one extra draft step processes d_gamma itself: its K/V slot
        # (committed-1+gamma) would otherwise NEVER be written, and on a
        # fully-accepted round the next round starts past it — the hole
        # would sit inside every later query's mask as silent zeros,
        # eroding draft quality (and so acceptance) forever. For
        # partially-accepted rows this write is stale, but every stale
        # slot is overwritten by a later round's draft step BEFORE the
        # first query whose mask includes it (write-then-attend within a
        # step). The produced logits are deliberately unused.
        _, dk, dv = _chunk_decode(
            dparams, dcfg, cur[:, None], dk, dv, committed - 1 + gamma
        )
        d = jnp.stack(d_toks, axis=1)  # [B, gamma]
        # -- target: ONE (gamma+1)-chunk verify over [x0, d1..dg] --
        chunk = jnp.concatenate([x0[:, None], d], axis=1)
        tl, tk, tv = _chunk_decode(
            tparams, tcfg, chunk, tk, tv, committed - 1
        )
        j_idx = jnp.arange(gamma + 1)[None, :]
        if greedy:
            tpred = jnp.argmax(tl, axis=-1).astype(jnp.int32)  # [B, g+1]
            # greedy acceptance: longest prefix where d[j] == tpred[j]
            agree = d == tpred[:, :gamma]  # [B, gamma]
            n = jnp.sum(
                jnp.cumprod(agree.astype(jnp.int32), axis=1), axis=1
            )
            correction = tpred[rows, n]  # [B]
            commit_row = jnp.where(
                j_idx < n[:, None],
                jnp.pad(d, ((0, 0), (0, 1))),  # d[j] for j < n
                correction[:, None],  # at j == n; masked out beyond
            )
        else:
            p_t = jax.nn.softmax(tl / temperature, axis=-1)  # [B, g+1, V]
            n, commit_row = _accept_and_correct(
                k_acc, d, jnp.stack(d_probs, axis=1), p_t
            )
        # capped commit: a finished row re-processes its last slot
        # instead of overflowing the buffer
        n_eff = jnp.minimum(n + 1, limit - committed)
        if has_eos:
            # clamp at the first stop token inside the commit: tokens
            # past it never land ("eos then pads" — toks stays 0
            # there), and the row freezes below
            is_eos = (commit_row == eos) & (j_idx < n_eff[:, None])
            first_eos = jnp.min(
                jnp.where(is_eos, j_idx, gamma + 1), axis=1
            )  # [B]; gamma+1 = none
            n_eff = jnp.minimum(n_eff, first_eos + 1)
        dest = jnp.where(
            j_idx < n_eff[:, None], committed[:, None] + j_idx, trash
        )
        toks = toks.at[rows[:, None], dest].set(commit_row)
        committed = committed + n_eff
        if has_eos:
            committed = jnp.where(first_eos <= gamma, limit, committed)
        # stats count only LIVE rows and only accepted-AND-committed
        # proposals (a capped commit may truncate the accepted run)
        acc = acc + jnp.sum(jnp.where(live, jnp.minimum(n, n_eff), 0))
        prop = prop + jnp.sum(jnp.where(live, gamma, 0))
        return (toks, committed, tk, tv, dk, dv, key, rounds + 1, acc,
                prop)

    def cond(carry):
        return jnp.any(carry[1] < limit)

    toks, committed, *_, rounds, acc, prop = jax.lax.while_loop(
        cond,
        round_body,
        (toks, committed, tk, tv, dk, dv, key, jnp.int32(0), jnp.int32(0),
         jnp.int32(0)),
    )
    out = toks[:, : p_len + steps]
    if not return_stats:
        return out
    stats = {
        "rounds": rounds,
        "target_passes": rounds,
        "accepted_frac": acc / jnp.maximum(prop, 1),
    }
    return out, stats
