"""Speculative decoding (Leviathan et al. 2023, greedy variant).

A small DRAFT model proposes ``gamma`` tokens autoregressively; the
TARGET model scores the whole proposal in ONE multi-token cache pass
(a (gamma+1)-wide chunk instead of gamma+1 sequential decode steps —
the MXU sees a batched matmul and the weights are read once per
round). Greedy acceptance keeps the longest prefix where the draft's
token equals the target's argmax, then appends the target's
correction — so the output is GUARANTEED token-for-token equal to
plain greedy decoding of the target model; the only thing speculation
changes is how many target passes it takes. The reference has no
serving path at all (extension, alongside lm_generate).

Cache invariant (both models): at round start every position
``< committed-1`` is cached; the slot at ``committed-1`` (the last
committed token, round input x0) is written DURING the round — the
draft writes it decoding proposal 1, the target writes it verifying
the chunk. The draft runs one EXTRA step so the last proposal's own
slot is written too (a fully-accepted round advances past it; an
unwritten slot would sit as silent zeros inside every later mask).
Rejected proposals leave stale slots past the committed point; each
stale slot is overwritten by a later round's write BEFORE the first
query whose mask includes it.

Batch rows accept different prefix lengths, so positions are
PER-ROW (``committed [B]``) — unlike lm_generate's scalar scan
position. Rows that finish early keep re-processing their last slot
(capped commit) until the slowest row completes; compute per round is
static.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .transformer import (
    LMConfig,
    _alloc_kv_caches,
    _chunk_decode,
    _prefill,
)


def speculative_generate(
    target_params: Dict[str, jax.Array],
    target_cfg: LMConfig,
    draft_params: Dict[str, jax.Array],
    draft_cfg: LMConfig,
    prompt: jax.Array,  # [B, P] int32
    steps: int,
    *,
    gamma: int = 4,
    return_stats: bool = False,
) -> "jax.Array | Tuple[jax.Array, Dict[str, jax.Array]]":
    """Greedy speculative decoding whose output exactly matches plain
    greedy decoding of the target model.

    Token-for-token equal to ``lm_generate(target_params, ...,
    temperature=None)`` — verified by tests — in
    ~``steps / (1 + mean_accepted)`` target passes instead of
    ``steps``. ``gamma``: draft proposals per round. Both configs must
    share the vocab; windows/rope/GQA/bf16/int8-cache compose per
    model independently (each model runs its OWN config against its
    own cache). Dense FFN only (same restriction as lm_generate).

    ``return_stats=True`` additionally returns
    ``{"rounds": r, "target_passes": r, "accepted_frac": f}`` —
    ``accepted_frac`` is the fraction of draft proposals that were
    accepted AND committed, counted only while a row was still live
    (finished rows keep spinning until the slowest row completes, and
    their idle work must not skew the number that decides whether a
    draft model pays for itself)."""
    for name, cfg in (("target", target_cfg), ("draft", draft_cfg)):
        if cfg.moe_every > 0:
            raise ValueError(
                f"speculative_generate: {name} model must be dense-FFN "
                "(same restriction as lm_generate)"
            )
    if target_cfg.vocab != draft_cfg.vocab:
        raise ValueError(
            f"vocab mismatch: target {target_cfg.vocab} vs draft "
            f"{draft_cfg.vocab} — the models must share a tokenizer"
        )
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    return _spec_jit(
        target_params, draft_params, prompt,
        tcfg=target_cfg, dcfg=draft_cfg, steps=steps, gamma=gamma,
        return_stats=return_stats,
    )


@functools.partial(
    jax.jit, static_argnames=("tcfg", "dcfg", "steps", "gamma",
                              "return_stats")
)
def _spec_jit(tparams, dparams, prompt, *, tcfg, dcfg, steps, gamma,
              return_stats):
    b, p_len = prompt.shape
    limit = p_len + steps
    # slack: a round can overshoot by gamma tokens + 1 trash slot
    total = limit + gamma + 1
    trash = total - 1  # masked-commit writes land here, never read
    tk, tv = _alloc_kv_caches(tcfg, b, total)
    dk, dv = _alloc_kv_caches(dcfg, b, total)
    prompt = prompt.astype(jnp.int32)
    # prefill BOTH models on the prompt (slots [0, p_len))
    t_logits, tk, tv = _prefill(tparams, tcfg, prompt, tk, tv)
    _, dk, dv = _prefill(dparams, dcfg, prompt, dk, dv)
    toks = jnp.zeros((b, total), jnp.int32).at[:, :p_len].set(prompt)
    # first committed token comes straight from the target prefill
    toks = toks.at[:, p_len].set(
        jnp.argmax(t_logits[:, -1], axis=-1).astype(jnp.int32)
    )
    committed = jnp.full((b,), p_len + 1, jnp.int32)
    rows = jnp.arange(b)

    def round_body(carry):
        toks, committed, tk, tv, dk, dv, rounds, acc, prop = carry
        live = committed < limit  # rows still decoding at round start
        x0 = toks[rows, committed - 1]  # [B] last committed token
        # -- draft: gamma sequential proposals (C=1 chunk steps) --
        d_toks = []
        cur = x0
        for j in range(gamma):
            dl, dk, dv = _chunk_decode(
                dparams, dcfg, cur[:, None], dk, dv, committed - 1 + j
            )
            cur = jnp.argmax(dl[:, 0], axis=-1).astype(jnp.int32)
            d_toks.append(cur)
        # one extra draft step processes d_gamma itself: its K/V slot
        # (committed-1+gamma) would otherwise NEVER be written, and on a
        # fully-accepted round the next round starts past it — the hole
        # would sit inside every later query's mask as silent zeros,
        # eroding draft quality (and so acceptance) forever. For
        # partially-accepted rows this write is stale, but every stale
        # slot is overwritten by a later round's draft step BEFORE the
        # first query whose mask includes it (write-then-attend within a
        # step). The produced logits are deliberately unused.
        _, dk, dv = _chunk_decode(
            dparams, dcfg, cur[:, None], dk, dv, committed - 1 + gamma
        )
        d = jnp.stack(d_toks, axis=1)  # [B, gamma]
        # -- target: ONE (gamma+1)-chunk verify over [x0, d1..dg] --
        chunk = jnp.concatenate([x0[:, None], d], axis=1)
        tl, tk, tv = _chunk_decode(
            tparams, tcfg, chunk, tk, tv, committed - 1
        )
        tpred = jnp.argmax(tl, axis=-1).astype(jnp.int32)  # [B, gamma+1]
        # greedy acceptance: longest prefix where d[j] == tpred[j]
        agree = d == tpred[:, :gamma]  # [B, gamma]
        n = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1), axis=1)
        # committed tokens this round: d[0..n-1] then the correction
        # tpred[n]; lay them out as a [B, gamma+1] row and mask-commit
        j_idx = jnp.arange(gamma + 1)[None, :]
        correction = tpred[rows, n]  # [B]
        commit_row = jnp.where(
            j_idx < n[:, None],
            jnp.pad(d, ((0, 0), (0, 1))),  # d[j] for j < n
            correction[:, None],  # at j == n; masked out beyond
        )
        # capped commit: a finished row re-processes its last slot
        # instead of overflowing the buffer
        n_eff = jnp.minimum(n + 1, limit - committed)
        dest = jnp.where(
            j_idx < n_eff[:, None], committed[:, None] + j_idx, trash
        )
        toks = toks.at[rows[:, None], dest].set(commit_row)
        committed = committed + n_eff
        # stats count only LIVE rows and only accepted-AND-committed
        # proposals (a capped commit may truncate the accepted run)
        acc = acc + jnp.sum(jnp.where(live, jnp.minimum(n, n_eff), 0))
        prop = prop + jnp.sum(jnp.where(live, gamma, 0))
        return toks, committed, tk, tv, dk, dv, rounds + 1, acc, prop

    def cond(carry):
        return jnp.min(carry[1]) < limit

    toks, committed, *_, rounds, acc, prop = jax.lax.while_loop(
        cond,
        round_body,
        (toks, committed, tk, tv, dk, dv, jnp.int32(0), jnp.int32(0),
         jnp.int32(0)),
    )
    out = toks[:, :limit]
    if not return_stats:
        return out
    stats = {
        "rounds": rounds,
        "target_passes": rounds,
        "accepted_frac": acc / jnp.maximum(prop, 1),
    }
    return out, stats
