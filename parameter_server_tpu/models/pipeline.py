"""Pipeline parallelism: stage-sharded layers, microbatched fill-drain.

The remaining parallelism mode (pp) beside dp / table-model / sp / ep:
a deep stack of identical blocks is sharded over a mesh axis — device d
holds a contiguous BLOCK of k = n_stages/n stages (k = 1 being one
stage per device) — and microbatches stream through the pipeline with
activations hopping device-to-device over ``ppermute`` (GPipe
fill-drain schedule: M microbatches finish in M + n - 1 ticks, every
tick running all DEVICES in parallel on different microbatches, each
chaining its local stage block).

Everything is a single jitted program: the schedule is a ``lax.scan``
over ticks, stage selection is mask arithmetic (no data-dependent
control flow), and autodiff through the scan + ppermute gives exact
pipeline-parallel gradients.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


@functools.partial(jax.jit, static_argnames=("stage_fn", "mesh", "axis"))
def pipeline_apply(
    stage_fn,
    stage_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "data",
):
    """Run ``x`` through the pipeline stages sharded over ``axis``.

    ``stage_params``: pytree whose leaves have leading dim n_stages (one
    slice per stage), sharded over ``axis``; n_stages may be any MULTIPLE
    of the axis size — device d holds the contiguous block of k =
    n_stages/n stages starting at d*k and chains it per tick. ``x``:
    [M, mb, ...] microbatches, replicated. ``stage_fn(params_slice,
    x_mb) -> y_mb`` applies one stage. Returns [M, mb, ...] outputs,
    replicated.
    """
    n = mesh.shape[axis]
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]
    if n_stages == 0 or n_stages % n:
        raise ValueError(
            f"stage count {n_stages} must be a MULTIPLE of mesh axis "
            f"{axis}={n} (each device holds one contiguous stage block)"
        )
    k = n_stages // n  # stages chained locally per device per tick

    def local(params, x):
        # params leaves arrive as [k, ...] (this device's stage block);
        # a tick runs the whole block in sequence — same fill-drain
        # bubble as one-stage-per-device (the (n-1)-tick ramp just costs
        # k stage-times per tick), so deep stacks need no extra devices
        m = x.shape[0]
        stage = jax.lax.axis_index(axis)
        is_first = stage == 0
        is_last = stage == n - 1
        ticks = m + n - 1

        def tick(carry, t):
            held, out = carry
            # stage 0 ingests microbatch t (while valid); others use the
            # activation handed over from the previous tick's ppermute
            feed = x[jnp.minimum(t, m - 1)]
            y = jnp.where(is_first, feed, held)
            for j in range(k):
                y = stage_fn(jax.tree.map(lambda l: l[j], params), y)
            # the last stage completed microbatch t - (n-1) this tick
            done_idx = jnp.maximum(t - (n - 1), 0)
            valid = is_last & (t - (n - 1) >= 0)
            prev = jax.lax.dynamic_index_in_dim(out, done_idx, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, y, prev), done_idx, axis=0
            )
            # hand activations forward around the ring (stage s -> s+1);
            # the wrap-around into stage 0 is ignored (it re-feeds from x)
            held = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n) for i in range(n)]
            )
            return (held, out), None

        held0 = jnp.zeros_like(x[0])
        out0 = jnp.zeros_like(x)
        (_, out), _ = jax.lax.scan(
            tick, (held0, out0), jnp.arange(ticks), length=ticks
        )
        # only the last stage holds real outputs: share them with all
        return jax.lax.psum(out, axis) / 1.0  # replicate via sum (others 0)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, x)


def sequential_apply(stage_fn, stage_params, x: jax.Array):
    """Dense reference: apply the n stages in order to every microbatch."""
    n = jax.tree.leaves(stage_params)[0].shape[0]

    def one(mb):
        y = mb
        for s in range(n):
            p = jax.tree.map(lambda l: l[s], stage_params)
            y = stage_fn(p, y)
        return y

    return jax.vmap(one)(x)
