"""Pipeline parallelism: stage-sharded layers, microbatched fill-drain.

The remaining parallelism mode (pp) beside dp / table-model / sp / ep:
a deep stack of identical blocks is sharded over a mesh axis — device s
holds stage s's parameters — and microbatches stream through the
pipeline with activations hopping stage-to-stage over ``ppermute``
(GPipe fill-drain schedule: M microbatches finish in M + n - 1 ticks,
every tick running ALL stages in parallel on different microbatches).

Everything is a single jitted program: the schedule is a ``lax.scan``
over ticks, stage selection is mask arithmetic (no data-dependent
control flow), and autodiff through the scan + ppermute gives exact
pipeline-parallel gradients.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P


@functools.partial(jax.jit, static_argnames=("stage_fn", "mesh", "axis"))
def pipeline_apply(
    stage_fn,
    stage_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "data",
):
    """Run ``x`` through n pipeline stages sharded over ``axis``.

    ``stage_params``: pytree whose leaves have leading dim n (one slice
    per stage), sharded over ``axis``. ``x``: [M, mb, ...] microbatches,
    replicated. ``stage_fn(params_slice, x_mb) -> y_mb`` applies one
    stage. Returns [M, mb, ...] outputs, replicated.
    """
    n = mesh.shape[axis]
    n_stages = jax.tree.leaves(stage_params)[0].shape[0]
    assert n_stages == n, (
        f"stage count {n_stages} must equal mesh axis {axis}={n} — a "
        "multiple would silently shard several stages onto one device "
        "and apply only the first"
    )

    def local(params, x):
        # params leaves arrive as [1, ...] (this stage's slice)
        p_local = jax.tree.map(lambda l: l[0], params)
        m = x.shape[0]
        stage = jax.lax.axis_index(axis)
        is_first = stage == 0
        is_last = stage == n - 1
        ticks = m + n - 1

        def tick(carry, t):
            held, out = carry
            # stage 0 ingests microbatch t (while valid); others use the
            # activation handed over from the previous tick's ppermute
            feed = x[jnp.minimum(t, m - 1)]
            inp = jnp.where(is_first, feed, held)
            y = stage_fn(p_local, inp)
            # the last stage completed microbatch t - (n-1) this tick
            done_idx = jnp.maximum(t - (n - 1), 0)
            valid = is_last & (t - (n - 1) >= 0)
            prev = jax.lax.dynamic_index_in_dim(out, done_idx, keepdims=False)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(valid, y, prev), done_idx, axis=0
            )
            # hand activations forward around the ring (stage s -> s+1);
            # the wrap-around into stage 0 is ignored (it re-feeds from x)
            held = jax.lax.ppermute(
                y, axis, [(i, (i + 1) % n) for i in range(n)]
            )
            return (held, out), None

        held0 = jnp.zeros_like(x[0])
        out0 = jnp.zeros_like(x)
        (_, out), _ = jax.lax.scan(
            tick, (held0, out0), jnp.arange(ticks), length=ticks
        )
        # only the last stage holds real outputs: share them with all
        return jax.lax.psum(out, axis) / 1.0  # replicate via sum (others 0)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(),
        check_vma=False,
    )(stage_params, x)


def sequential_apply(stage_fn, stage_params, x: jax.Array):
    """Dense reference: apply the n stages in order to every microbatch."""
    n = jax.tree.leaves(stage_params)[0].shape[0]

    def one(mb):
        y = mb
        for s in range(n):
            p = jax.tree.map(lambda l: l[s], stage_params)
            y = stage_fn(p, y)
        return y

    return jax.vmap(one)(x)
