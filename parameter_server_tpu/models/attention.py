"""Ring attention: exact attention over sequence-sharded inputs.

Long-context support is first-class in this framework: sequences longer
than one chip's memory are sharded over a mesh axis and attention runs
blockwise, streaming K/V shards around the ICI ring (ppermute) while each
device keeps a numerically-stable online-softmax accumulator (the
flash/ring-attention recurrence). Exact — matches dense attention to float
tolerance — with O(seq/n) memory per device.

``ring_attention(q, k, v, mesh, axis)`` expects [B, S, H] arrays sharded on
S over ``axis``; causal masking accounts for the global block offsets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.ring import ring_next


def _block_attn(q, k, v, mask):
    """Scores for one (q-block, kv-block) pair: returns (scores, values)."""
    s = jnp.einsum("bqh,bkh->bqk", q, k) / jnp.sqrt(q.shape[-1])
    s = jnp.where(mask, s, -jnp.inf)
    return s


@functools.partial(jax.jit, static_argnames=("mesh", "axis", "causal"))
def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "data",
    causal: bool = False,
) -> jax.Array:
    """Exact attention with S sharded over ``axis``. q,k,v: [B, S, H]."""
    n = mesh.shape[axis]

    def local(q, k, v):
        b, s_loc, h = q.shape
        my = jax.lax.axis_index(axis)
        # online softmax accumulators
        acc = jnp.zeros((b, s_loc, h), jnp.float32)
        row_max = jnp.full((b, s_loc), -jnp.inf, jnp.float32)
        row_sum = jnp.zeros((b, s_loc), jnp.float32)
        kb, vb = k, v
        src = my  # which device's K/V block we currently hold
        q_pos = my * s_loc + jnp.arange(s_loc)
        for step in range(n):
            k_pos = src * s_loc + jnp.arange(s_loc)
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
            else:
                mask = jnp.ones((s_loc, s_loc), bool)
            scores = _block_attn(q, kb, vb, mask[None, :, :])
            blk_max = jnp.max(scores, axis=-1)
            new_max = jnp.maximum(row_max, blk_max)
            # guard fully-masked rows (all -inf)
            safe_max = jnp.where(jnp.isinf(new_max), 0.0, new_max)
            p = jnp.exp(scores - safe_max[..., None])
            p = jnp.where(jnp.isinf(scores), 0.0, p)
            correction = jnp.where(
                jnp.isinf(row_max), 0.0, jnp.exp(row_max - safe_max)
            )
            acc = acc * correction[..., None] + jnp.einsum("bqk,bkh->bqh", p, vb)
            row_sum = row_sum * correction + jnp.sum(p, axis=-1)
            row_max = new_max
            if step + 1 < n:
                kb = ring_next(kb, axis)
                vb = ring_next(vb, axis)
                src = (src - 1) % n  # ppermute shifts blocks forward
        out = acc / jnp.maximum(row_sum, 1e-30)[..., None]
        return out.astype(q.dtype)

    spec = P(None, axis, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def dense_attention(q, k, v, causal: bool = False):
    """Reference implementation for tests."""
    s = jnp.einsum("bqh,bkh->bqk", q, k) / jnp.sqrt(q.shape[-1])
    if causal:
        n = q.shape[1]
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkh->bqh", p, v)
