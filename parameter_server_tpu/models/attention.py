"""Sequence-parallel attention schedules over sharded inputs.

Long-context support is first-class in this framework: sequences longer
than one chip's memory are sharded over a mesh axis and attention runs
blockwise. Two schedules, each exact (matches dense attention to float
tolerance):

- :func:`ring_attention` — K/V shards stream around the ICI ring
  (ppermute) while each device keeps a numerically-stable online-softmax
  accumulator; O(seq/n) memory per device. Chunk computes: ``impl="xla"``
  (materialized score block), ``impl="flash"`` (Pallas kernel, O(block)
  VMEM), ``impl="zigzag"`` (flash over the zigzag-permuted layout for
  balanced causal work per hop — see :func:`zigzag_permutation`).
- :func:`ulysses_attention` — all_to_all seq<->head reshard, dense (or
  flash) per-head attention, two collectives total.

Both accept ``window=`` (with the flash computes) for sliding-window
attention. ``ring_attention(q, k, v, mesh, axis)`` expects [B, S, H]
arrays sharded on S over ``axis``; causal masking accounts for the
global block offsets.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.ring import ring_next


def _block_attn(q, k, v, mask):
    """Scores for one (q-block, kv-block) pair: returns (scores, values)."""
    s = jnp.einsum("bqh,bkh->bqk", q, k) / jnp.sqrt(q.shape[-1])
    s = jnp.where(mask, s, -jnp.inf)
    return s


def _ring_hops(k, v, axis: str, n: int):
    """Yield ``(kb, vb, src)`` for each of the n ring hops: the K/V chunk
    currently held and WHICH device's shard it is. The single home of the
    schedule invariant — ``ring_next``'s ppermute shifts blocks forward,
    so the held chunk's source index DEcrements — shared by both
    ring-attention impls so their causal offsets cannot desynchronize."""
    src = jax.lax.axis_index(axis)
    kb, vb = k, v
    for step in range(n):
        yield kb, vb, src
        if step + 1 < n:
            kb = ring_next(kb, axis)
            vb = ring_next(vb, axis)
            src = (src - 1) % n


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "axis", "causal", "impl", "use_pallas", "interpret", "window",
    ),
)
def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "data",
    causal: bool = False,
    impl: str = "xla",
    use_pallas=None,
    interpret=None,
    window=None,
) -> jax.Array:
    """Exact attention with S sharded over ``axis``. q,k,v: [B, S, H].

    ``impl="xla"`` materializes each visiting chunk's [s_loc, s_loc]
    score block (fine for moderate chunks); ``impl="flash"`` computes
    each chunk with the Pallas flash kernel (ops/flash_attention.py) —
    O(block) VMEM per chunk — and merges chunks by logsumexp, so BOTH
    levels of the blocking (across devices and within a chunk) stream.
    ``impl="zigzag"`` is flash over the zigzag-permuted layout
    (:func:`zigzag_permutation`) — balanced causal work per ring hop;
    inputs and outputs must already be in that layout.
    """
    if impl == "flash":
        return _ring_attention_flash(
            q, k, v, mesh=mesh, axis=axis, causal=causal,
            use_pallas=use_pallas, interpret=interpret, window=window,
        )
    if impl == "zigzag":
        return _ring_attention_zigzag(
            q, k, v, mesh=mesh, axis=axis, causal=causal,
            use_pallas=use_pallas, interpret=interpret, window=window,
        )
    if impl != "xla":
        raise ValueError(
            f"ring_attention impl must be 'xla', 'flash' or 'zigzag', got "
            f"{impl!r} — all are exact, so a silent fallback would hide "
            "the memory profile choice"
        )
    if window is not None:
        raise ValueError(
            "window (sliding-window attention) is implemented by the "
            "flash kernels — use impl='flash' or 'zigzag'"
        )
    if use_pallas is not None or interpret is not None:
        raise ValueError(
            "use_pallas/interpret only apply to impl='flash'/'zigzag'; "
            "the xla impl would silently ignore them (and you would "
            "believe you benchmarked the Pallas kernel)"
        )
    n = mesh.shape[axis]

    def local(q, k, v):
        b, s_loc, h = q.shape
        my = jax.lax.axis_index(axis)
        # online softmax accumulators
        acc = jnp.zeros((b, s_loc, h), jnp.float32)
        row_max = jnp.full((b, s_loc), -jnp.inf, jnp.float32)
        row_sum = jnp.zeros((b, s_loc), jnp.float32)
        q_pos = my * s_loc + jnp.arange(s_loc)
        for kb, vb, src in _ring_hops(k, v, axis, n):
            k_pos = src * s_loc + jnp.arange(s_loc)
            if causal:
                mask = q_pos[:, None] >= k_pos[None, :]
            else:
                mask = jnp.ones((s_loc, s_loc), bool)
            scores = _block_attn(q, kb, vb, mask[None, :, :])
            blk_max = jnp.max(scores, axis=-1)
            new_max = jnp.maximum(row_max, blk_max)
            # guard fully-masked rows (all -inf)
            safe_max = jnp.where(jnp.isinf(new_max), 0.0, new_max)
            p = jnp.exp(scores - safe_max[..., None])
            p = jnp.where(jnp.isinf(scores), 0.0, p)
            correction = jnp.where(
                jnp.isinf(row_max), 0.0, jnp.exp(row_max - safe_max)
            )
            acc = acc * correction[..., None] + jnp.einsum("bqk,bkh->bqh", p, vb)
            row_sum = row_sum * correction + jnp.sum(p, axis=-1)
            row_max = new_max
        out = acc / jnp.maximum(row_sum, 1e-30)[..., None]
        return out.astype(q.dtype)

    spec = P(None, axis, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec, check_vma=False
    )(q, k, v)


def _merge_chunk(out, lse, out_i, lse_i):
    """Exact combination of two normalized partial-attention results via
    their logsumexps (the FlashAttention-2 chunk merge): order-invariant,
    and a fully-masked chunk (lse_i ~ -1e30) contributes weight 0."""
    new_lse = jnp.logaddexp(lse, lse_i)
    w_old = jnp.exp(lse - new_lse)
    w_new = jnp.exp(lse_i - new_lse)
    out = out * w_old[..., None] + out_i.astype(jnp.float32) * w_new[..., None]
    return out, new_lse


def _ring_attention_flash(q, k, v, *, mesh, axis, causal, use_pallas,
                          interpret, window=None):
    """Ring schedule with the Pallas flash kernel as the chunk compute.

    Each hop produces a NORMALIZED chunk output plus its logsumexp; two
    chunks merge exactly via softmax-of-lse weights (the FlashAttention-2
    chunk combination), so the result matches dense attention to float
    tolerance regardless of hop order."""
    from ..ops.flash_attention import flash_attention

    n = mesh.shape[axis]

    def local(q, k, v):
        b, s_loc, h = q.shape
        my = jax.lax.axis_index(axis)
        out = jnp.zeros((b, s_loc, h), jnp.float32)
        lse = jnp.full((b, s_loc), -1e30, jnp.float32)
        for kb, vb, src in _ring_hops(k, v, axis, n):
            out_i, lse_i = flash_attention(
                q, kb, vb, causal=causal,
                q_offset=my * s_loc, k_offset=src * s_loc,
                use_pallas=use_pallas, interpret=interpret, with_lse=True,
                window=window,
            )
            out, lse = _merge_chunk(out, lse, out_i, lse_i)
        return out.astype(q.dtype)

    spec = P(None, axis, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def zigzag_permutation(seq_len: int, n: int) -> np.ndarray:
    """Token permutation for the zigzag causal layout: the sequence is
    split into 2n half-blocks and device i holds half-blocks
    ``(i, 2n-1-i)``. ``x[:, perm]`` re-orders a natural-layout sequence
    so a plain ``P(axis)`` sharding lands those pairs on device i;
    ``argsort(perm)`` inverts. Why: under the contiguous causal layout
    device 0's queries precede almost every visiting K/V chunk, so it
    skips most hops while the last device computes on all of them — the
    hop wall-clock is set by the busiest device. Pairing the i-th and
    (2n-1-i)-th half-blocks gives every device the same causal workload
    per hop (the zigzag schedule of Brandon et al.'s striped-attention
    line of work), while K/V still streams over the same ICI ring."""
    if seq_len % (2 * n):
        raise ValueError(f"seq_len {seq_len} must divide by 2n={2 * n}")
    h = seq_len // (2 * n)
    blocks = []
    for i in range(n):
        blocks.append(np.arange(i * h, (i + 1) * h))
        blocks.append(np.arange((2 * n - 1 - i) * h, (2 * n - i) * h))
    return np.concatenate(blocks)


def _ring_attention_zigzag(q, k, v, *, mesh, axis, causal, use_pallas,
                           interpret, window=None):
    """Ring attention over ZIGZAG-sharded inputs (see
    :func:`zigzag_permutation` — inputs/outputs are in the permuted
    layout). Each device holds two half-blocks with different global
    offsets, so every hop runs four half×half flash calls (q half × kv
    half) with the right offset pairs and merges by logsumexp; the
    kernel's causal block-skip makes the fully-masked combinations
    cheap. Exact for causal and non-causal alike."""
    from ..ops.flash_attention import flash_attention

    n = mesh.shape[axis]

    def local(q, k, v):
        b, s_loc, h_feat = q.shape
        if s_loc % 2:
            raise ValueError(
                f"zigzag needs an even per-device sequence length, got "
                f"{s_loc} — shard a seq divisible by 2*{n} (see "
                "zigzag_permutation)"
            )
        half = s_loc // 2
        my = jax.lax.axis_index(axis)
        q_halves = (q[:, :half], q[:, half:])
        q_offs = (my * half, (2 * n - 1 - my) * half)
        outs = [jnp.zeros((b, half, h_feat), jnp.float32) for _ in range(2)]
        lses = [jnp.full((b, half), -1e30, jnp.float32) for _ in range(2)]
        for kb, vb, src in _ring_hops(k, v, axis, n):
            kv_halves = ((kb[:, :half], vb[:, :half]), (kb[:, half:], vb[:, half:]))
            kv_offs = (src * half, (2 * n - 1 - src) * half)
            for qi in range(2):
                for ki in range(2):
                    out_i, lse_i = flash_attention(
                        q_halves[qi], kv_halves[ki][0], kv_halves[ki][1],
                        causal=causal,
                        q_offset=q_offs[qi], k_offset=kv_offs[ki],
                        use_pallas=use_pallas, interpret=interpret,
                        with_lse=True, window=window,
                    )
                    outs[qi], lses[qi] = _merge_chunk(
                        outs[qi], lses[qi], out_i, lse_i
                    )
        return jnp.concatenate(outs, axis=1).astype(q.dtype)

    spec = P(None, axis, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def dense_attention(q, k, v, causal: bool = False):
    """Reference implementation for tests."""
    s = jnp.einsum("bqh,bkh->bqk", q, k) / jnp.sqrt(q.shape[-1])
    if causal:
        n = q.shape[1]
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask[None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkh->bqh", p, v)


def dense_mha(q, k, v, n_heads: int, causal: bool = False):
    """Multi-head reference: [B, S, H] with H = n_heads * dh."""
    b, s, h = q.shape
    dh = h // n_heads

    def split(x):
        return x.reshape(b, s, n_heads, dh).transpose(0, 2, 1, 3)

    qh, kh, vh = split(q), split(k), split(v)
    scores = jnp.einsum("bnqd,bnkd->bnqk", qh, kh) / jnp.sqrt(dh)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bnqk,bnkd->bnqd", p, vh)
    return out.transpose(0, 2, 1, 3).reshape(b, s, h)


@functools.partial(
    jax.jit,
    static_argnames=(
        "mesh", "axis", "n_heads", "causal", "impl", "use_pallas",
        "interpret", "window",
    ),
)
def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "data",
    n_heads: int,
    causal: bool = False,
    impl: str = "xla",
    use_pallas=None,
    interpret=None,
    window=None,
) -> jax.Array:
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism: the
    complement of :func:`ring_attention` for long sequences.

    Inputs arrive sequence-sharded ([B, S, H] with S over ``axis``); one
    all_to_all re-shards to HEAD-sharded (each device owns n_heads/n full
    -sequence heads), attention runs densely per local head — a single
    big MXU matmul instead of a ring of n block steps — and a second
    all_to_all restores sequence sharding. Two collectives total (vs n-1
    ppermutes): cheaper when heads divide evenly and the full sequence's
    scores fit on-chip; ring wins when S^2 memory must stay blocked —
    unless ``impl="flash"``, which runs the per-head full-sequence
    attention through the Pallas flash kernel (O(block) VMEM), removing
    exactly that S^2 limit while keeping the two-collective schedule.
    """
    n = mesh.shape[axis]
    assert n_heads % n == 0, f"n_heads={n_heads} must divide by mesh axis {n}"
    if impl not in ("xla", "flash"):
        raise ValueError(
            f"ulysses_attention impl must be 'xla' or 'flash', got {impl!r}"
        )
    if impl == "xla" and (use_pallas is not None or interpret is not None):
        raise ValueError(
            "use_pallas/interpret only apply to impl='flash'; the xla "
            "impl would silently ignore them"
        )
    if window is not None and impl != "flash":
        raise ValueError(
            "window (sliding-window attention) is implemented by the "
            "flash kernel — use impl='flash'"
        )

    def local(q, k, v):
        b, s_loc, h = q.shape
        dh = h // n_heads

        def to_heads(x):
            # [B, s_loc, H] -> [B, s_loc, nh, dh] -> a2a: scatter heads,
            # gather sequence -> [B, S, nh/n, dh]
            x = x.reshape(b, s_loc, n_heads, dh)
            return jax.lax.all_to_all(
                x, axis, split_axis=2, concat_axis=1, tiled=True
            )

        qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)  # [B, S, nh/n, dh]
        s_full = qh.shape[1]
        nh_loc = qh.shape[2]
        if impl == "flash":
            from ..ops.flash_attention import flash_attention

            def to_bh(x):  # [B, S, nh/n, dh] -> [B*nh/n, S, dh]
                return x.transpose(0, 2, 1, 3).reshape(b * nh_loc, s_full, dh)

            out = flash_attention(
                to_bh(qh), to_bh(kh), to_bh(vh), causal=causal,
                use_pallas=use_pallas, interpret=interpret, window=window,
            )
            out = out.reshape(b, nh_loc, s_full, dh).transpose(0, 2, 1, 3)
        else:
            scores = jnp.einsum("bqnd,bknd->bnqk", qh, kh) / jnp.sqrt(dh)
            if causal:
                mask = jnp.tril(jnp.ones((s_full, s_full), bool))
                scores = jnp.where(mask[None, None], scores, -jnp.inf)
            p = jax.nn.softmax(scores, axis=-1)
            out = jnp.einsum("bnqk,bknd->bqnd", p, vh)  # [B, S, nh/n, dh]
        # inverse a2a: scatter sequence, gather heads
        out = jax.lax.all_to_all(
            out, axis, split_axis=1, concat_axis=2, tiled=True
        )
        return out.reshape(b, s_loc, h)

    spec = P(None, axis, None)
    return shard_map(
        local, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)
