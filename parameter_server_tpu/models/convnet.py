"""Convolutional network (role of the CXXNET CIFAR-10 worker the reference
trains through KVLayer dense push/pull — README.md points NN training at
CXXNET/Minerva with the parameter server as the KVLayer backend).

A compact flax CNN sized for CIFAR-shaped inputs; trained by
``apps/nn/trainer.py`` with parameters stored in a KVLayer.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp


class ConvNet(nn.Module):
    num_classes: int = 10
    width: int = 32

    @nn.compact
    def __call__(self, x):  # x: [B, H, W, C]
        x = nn.Conv(self.width, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(self.width * 2, (3, 3))(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


class MLP(nn.Module):
    """Small dense net for quick KVLayer tests."""

    num_classes: int = 10
    hidden: int = 64

    @nn.compact
    def __call__(self, x):
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden)(x)
        x = nn.relu(x)
        return nn.Dense(self.num_classes)(x)


def cross_entropy(logits, labels):
    import jax

    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jnp.eye(logits.shape[-1])[labels]
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))
