"""Mixture-of-experts FFN with expert parallelism (TPU-native).

Beyond-parity extension rounding out the parallelism modes: dp (data
axis), table/model parallel (server axis), sp (ring + all-to-all
attention) — and here ep: experts sharded over a mesh axis, tokens
routed to them with two ``all_to_all`` collectives (the standard
Switch/GShard dispatch, jax-native).

Top-1 (switch) routing with a per-token-shard capacity: each shard of
tokens computes router gates locally, builds a [tokens, E, C] dispatch
one-hot (C = capacity per expert per shard), and einsum-dispatches its
tokens to expert buffers; an all_to_all re-shards the EXPERT axis so
every device holds the full token buffers of its E/n local experts, the
2-layer FFN runs as dense [E/n, n*C, d] batched matmuls (MXU-shaped),
and the inverse all_to_all + combine einsum route outputs back. Dropped
tokens (over capacity) pass through on the residual path, as in Switch.
"""

from __future__ import annotations

import functools
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def init_moe(key, d_model: int, d_ff: int, n_experts: int) -> Dict[str, jax.Array]:
    k1, k2, k3 = jax.random.split(key, 3)
    scale = 1.0 / np.sqrt(d_model)
    return {
        "router": jax.random.normal(k1, (d_model, n_experts)) * scale,
        "w_in": jax.random.normal(k2, (n_experts, d_model, d_ff)) * scale,
        "w_out": jax.random.normal(k3, (n_experts, d_ff, d_model))
        * (1.0 / np.sqrt(d_ff)),
    }


def _route(x, router, n_experts: int, capacity: int):
    """Shard-local switch routing: returns (dispatch [T,E,C] one-hot,
    combine [T,E,C] gate-weighted) for this shard's T tokens."""
    logits = x @ router  # [T, E]
    gates = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(gates, axis=-1)  # [T]
    gate = jnp.take_along_axis(gates, expert[:, None], axis=1)[:, 0]  # [T]
    onehot = jax.nn.one_hot(expert, n_experts, dtype=jnp.float32)  # [T, E]
    # position of each token within its expert's buffer (arrival order)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
    keep = (pos < capacity) * onehot  # over-capacity tokens drop
    pos_clipped = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
    dispatch = keep[:, :, None] * jax.nn.one_hot(
        pos_clipped, capacity, dtype=jnp.float32
    )  # [T, E, C]
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def _expert_ffn(w_in, w_out, h):
    return jnp.einsum(
        "ecf,efo->eco", jax.nn.relu(jnp.einsum("ecd,edf->ecf", h, w_in)), w_out
    )


def moe_ffn_dense(params, x, n_shards: int, capacity_factor: float = 1.25):
    """Single-device reference: identical math to the sharded layer —
    tokens processed in ``n_shards`` chunks with per-chunk routing and
    capacity, experts all local. For tests."""
    b, s, d = x.shape
    n_experts = params["router"].shape[1]
    s_loc = s // n_shards
    t_loc = b * s_loc
    capacity = max(1, int(capacity_factor * t_loc / n_experts))
    outs = []
    for i in range(n_shards):
        # mirror the sharded layer exactly: a shard owns a SEQUENCE slice
        # (all batch rows), flattened in the same [B, s_loc] order
        xt = x[:, i * s_loc : (i + 1) * s_loc, :].reshape(-1, d)
        dispatch, combine = _route(xt, params["router"], n_experts, capacity)
        h = jnp.einsum("tec,td->ecd", dispatch, xt)
        out_e = _expert_ffn(params["w_in"], params["w_out"], h)
        outs.append(
            jnp.einsum("tec,ecd->td", combine, out_e).reshape(b, s_loc, d)
        )
    return jnp.concatenate(outs, axis=1)


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "capacity_factor")
)
def moe_ffn(
    params,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "data",
    capacity_factor: float = 1.25,
) -> jax.Array:
    """Expert-parallel MoE FFN. ``x``: [B, S, d] sequence-sharded over
    ``axis``; expert tables sharded over the same axis (E % n == 0).
    Output keeps x's sharding."""
    n = mesh.shape[axis]
    n_experts = params["router"].shape[1]
    assert n_experts % n == 0, f"experts {n_experts} must divide mesh axis {n}"

    def local(router, w_in, w_out, x):
        b, s_loc, d = x.shape
        xt = x.reshape(-1, d)  # [T_loc, d]
        t_loc = xt.shape[0]
        capacity = max(1, int(capacity_factor * t_loc / n_experts))
        dispatch, combine = _route(xt, router, n_experts, capacity)
        h = jnp.einsum("tec,td->ecd", dispatch, xt)  # [E, C, d]
        # a2a: scatter experts, gather token-shards -> local experts see
        # every shard's buffer: [E/n, n*C, d]
        h = jax.lax.all_to_all(h, axis, split_axis=0, concat_axis=1, tiled=True)
        out_e = _expert_ffn(w_in, w_out, h)  # [E/n, n*C, d]
        out_e = jax.lax.all_to_all(
            out_e, axis, split_axis=1, concat_axis=0, tiled=True
        )  # [E, C, d]
        out = jnp.einsum("tec,ecd->td", combine, out_e)
        return out.reshape(b, s_loc, d)

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(None, axis, None)),
        out_specs=P(None, axis, None),
        check_vma=False,
    )(params["router"], params["w_in"], params["w_out"], x)
