"""Sequence-parallel decoder-only transformer LM.

The long-context story end to end: activations are sharded along the
SEQUENCE axis of the mesh, attention runs as the exact ring schedule
(models/attention.py — ppermute streams K/V blocks over ICI), and every
other op (layernorm, MLP, embedding lookup, the shifted next-token loss)
auto-partitions under jit, XLA inserting the halo/collective traffic.
Parameters are replicated (small-model regime); gradient psums across
shards come out of auto-SPMD.

The reference has no transformer — this extends the framework beyond
parity to show the sequence-parallel design carries a real model: train
sequences n× longer than one chip's memory by adding chips to the seq
axis, at exact-attention quality.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .attention import ring_attention, ulysses_attention
from .moe import init_moe, moe_ffn


@dataclasses.dataclass(frozen=True)
class LMConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 128
    # sequence-parallel attention schedule: "ring" (ppermute K/V ring,
    # O(S/n) memory), "ring_flash" (same ring, but each visiting chunk
    # runs the Pallas flash kernel — O(block) VMEM, scores never hit
    # HBM), "ring_zigzag" (flash over the zigzag-permuted layout for
    # balanced causal work per hop; train via zigzag_lm_arrays +
    # lm_loss_with_targets), or "a2a" (Ulysses: all_to_all seq<->head
    # reshard, dense per-head matmuls; needs n_heads % mesh-axis == 0).
    # "ring_flash" is the MEASURED training default on one v5e chip
    # (BENCH_ONCHIP.md 2026-07-31 04:27/04:30): with the swept 512x512
    # kernel blocking, flash trains the s=8192/bf16 LM at 30.6k tok/s
    # vs the XLA chunk path's 21.1k (1.45x; kernel-level fwd+bwd 19.8k
    # vs 8.8k GFLOP/s, 2.2x) AND keeps O(block) memory where XLA saves
    # per-chunk P matrices. With the original 128x128 blocking this
    # comparison went the OTHER way (14.6k vs 19.4k) — the default
    # follows the measurement, not the architecture diagram.
    attention: str = "ring_flash"
    # >0: every moe_every-th layer's FFN is an expert-parallel MoE
    # (models/moe.py) with n_experts switch-routed experts
    moe_every: int = 0
    n_experts: int = 8
    capacity_factor: float = 2.0
    # rematerialize each decoder layer in the backward pass
    # (jax.checkpoint): activations are recomputed instead of stored, so
    # training memory drops from O(layers * S) activations to O(S) +
    # per-layer recompute — THE long-context memory lever alongside
    # sequence parallelism. Gradients are numerically identical up to
    # compiler reassociation of the recomputed ops.
    remat: bool = False
    # "bfloat16" runs decoder activations in bf16 (MXU-native): params
    # and the softmax/logits stay float32, attention accumulates f32
    compute_dtype: str = "float32"
    # sliding-window (local) attention span: each position attends to
    # the `window` most recent tokens only. Implemented by the flash
    # kernels (out-of-window blocks are skipped — O(window)/query), so
    # it requires a flash attention mode; None = full causal attention
    window: "int | None" = None
    # grouped-query attention: K/V carry only this many heads, each
    # serving n_heads/n_kv_heads query heads (1 = MQA). Shrinks wk/wv
    # params AND the decode KV cache by the group factor — the cache is
    # the dominant serving HBM traffic. None = n_heads (standard MHA)
    n_kv_heads: "int | None" = None
    # rotary position embedding (RoFormer, Su et al. 2021): q/k head
    # vectors are rotated by position-dependent angles before attention,
    # so scores depend only on RELATIVE offsets — parameter-free and
    # length-extrapolating, vs the default NoPE (causal masking alone
    # carries order). Composes with every schedule here: the training
    # forward rotates on the GLOBAL [B, S] view (GSPMD partitions the
    # position iota with the sequence; zigzag uses its permutation as
    # the position ids), the decode path rotates at the absolute cache
    # slot, and window/GQA are unaffected (rotation acts per head-dim
    # pair before any masking/grouping)
    rope: bool = False
    rope_theta: float = 10000.0
    # decode KV-cache storage: None = the compute dtype (bf16 under
    # bfloat16 — the existing behavior); "int8" = per-token-per-head
    # symmetric int8 quantization (one f32 scale per [layer, batch,
    # kv-head, position] row: 4/head_dim = 6.25% over the int8 payload
    # at head_dim 64, i.e. ~0.53x of the bf16 cache it replaces). Decode is
    # cache-bandwidth-bound once GQA narrows the weights (measured:
    # BENCH_ONCHIP.md kv2 decode), so int8 halves the remaining bf16
    # cache traffic; dequantization fuses into the attention einsum.
    # Scores/softmax still accumulate f32. Training is unaffected.
    kv_cache_dtype: "str | None" = None

    def __post_init__(self):
        if self.kv_cache_dtype not in (None, "int8"):
            raise ValueError(
                f"LMConfig.kv_cache_dtype must be None or 'int8', got "
                f"{self.kv_cache_dtype!r}"
            )
        if self.attention not in ("ring", "ring_flash", "ring_zigzag", "a2a"):
            raise ValueError(
                f"LMConfig.attention must be 'ring', 'ring_flash', "
                f"'ring_zigzag' or 'a2a', got {self.attention!r} — all "
                "are exact, so a silent fallback would hide the "
                "memory/collective profile choice"
            )
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"LMConfig.compute_dtype must be 'float32' or 'bfloat16', "
                f"got {self.compute_dtype!r}"
            )
        if self.window is not None:
            if self.attention not in ("ring_flash", "ring_zigzag"):
                raise ValueError(
                    "LMConfig.window (sliding-window attention) needs a "
                    "flash attention mode ('ring_flash' or 'ring_zigzag')"
                )
            if self.window < 1:
                raise ValueError(
                    f"LMConfig.window must be >= 1, got {self.window}"
                )
        if self.n_kv_heads is not None:
            if not 1 <= self.n_kv_heads <= self.n_heads:
                raise ValueError(
                    f"LMConfig.n_kv_heads must be in [1, n_heads="
                    f"{self.n_heads}], got {self.n_kv_heads}"
                )
            if self.n_heads % self.n_kv_heads:
                raise ValueError(
                    f"n_heads={self.n_heads} must be a multiple of "
                    f"n_kv_heads={self.n_kv_heads} (each K/V head serves "
                    "an equal group of query heads)"
                )
        if self.rope and (self.d_model // self.n_heads) % 2:
            raise ValueError(
                f"LMConfig.rope pairs head dimensions: head_dim="
                f"{self.d_model // self.n_heads} must be even"
            )

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads


def init_lm(key: jax.Array, cfg: LMConfig) -> Dict[str, jax.Array]:
    ks = jax.random.split(key, 2 + 4 * cfg.n_layers)
    s = 0.02
    p = {
        "emb": s * jax.random.normal(ks[0], (cfg.vocab, cfg.d_model)),
        "ln_f": jnp.ones((cfg.d_model,)),
    }
    for i in range(cfg.n_layers):
        k1, k2, k3, k4 = ks[2 + 4 * i : 6 + 4 * i]
        p[f"l{i}/ln1"] = jnp.ones((cfg.d_model,))
        p[f"l{i}/ln2"] = jnp.ones((cfg.d_model,))
        # separate q/k/v projections (not a fused [d, 3d]): under tensor
        # parallelism each projection column-shards on its own, so the
        # qkv split boundaries stay shard-local (the fused-QKV TP pitfall
        # puts K across two shards and forces per-layer reshards)
        wqkv = s * jax.random.normal(k1, (cfg.d_model, 3 * cfg.d_model))
        p[f"l{i}/wq"], p[f"l{i}/wk"], p[f"l{i}/wv"] = jnp.split(wqkv, 3, axis=1)
        if cfg.kv_heads != cfg.n_heads:  # GQA: narrow K/V projections
            kv_w = cfg.kv_heads * (cfg.d_model // cfg.n_heads)
            p[f"l{i}/wk"] = p[f"l{i}/wk"][:, :kv_w]
            p[f"l{i}/wv"] = p[f"l{i}/wv"][:, :kv_w]
        p[f"l{i}/wo"] = s * jax.random.normal(k2, (cfg.d_model, cfg.d_model))
        if _is_moe_layer(cfg, i):
            moe = init_moe(k3, cfg.d_model, cfg.d_ff, cfg.n_experts)
            p[f"l{i}/moe_router"] = moe["router"]
            p[f"l{i}/moe_w_in"] = moe["w_in"]
            p[f"l{i}/moe_w_out"] = moe["w_out"]
        else:
            p[f"l{i}/w1"] = s * jax.random.normal(k3, (cfg.d_model, cfg.d_ff))
            p[f"l{i}/w2"] = s * jax.random.normal(k4, (cfg.d_ff, cfg.d_model))
    return jax.tree.map(lambda x: x.astype(jnp.float32), p)


def _is_moe_layer(cfg: LMConfig, i: int) -> bool:
    return cfg.moe_every > 0 and (i + 1) % cfg.moe_every == 0


def _moe_layer_params(params, i: int):
    """The MoE leaves of layer ``i`` for the serving path (one place —
    three forwards consume this slice)."""
    return {
        "moe_router": params[f"l{i}/moe_router"],
        "moe_w_in": params[f"l{i}/moe_w_in"],
        "moe_w_out": params[f"l{i}/moe_w_out"],
    }


def _moe_ffn_dropless(lp, h2, n_experts: int):
    """Serving-side MoE FFN: DROPLESS per-token top-1 routing.

    The training layer (models/moe.py) drops over-capacity tokens, and
    which tokens drop depends on every other token in the shard — a
    decision incremental decoding cannot reproduce (the cache sees
    tokens one at a time). Serving therefore routes every token
    independently with no capacity: self-consistent across prefill /
    chunk-ingest / one-token decode (the generate-family exactness
    contracts hold), and equal to the training forward whenever the
    training capacity did not bind (capacity_factor >= n_experts
    guarantees that; tests pin it). Math mirrors moe_ffn: routing and
    experts in f32, relu activation, gate-weighted output.

    Implementation is a static per-expert loop with masking — every
    expert's weights are read once regardless of batch (decode is
    weights-bound anyway) and no [T, E, C] dispatch tensor or per-token
    weight gather is materialized. COST NOTE: this computes every
    expert's FFN over all T tokens (n_experts x the dense-FFN FLOPs),
    which is the right trade for the one-token decode step but makes
    MoE PREFILL compute-heavy on long prompts; a sort/gather-by-expert
    prefill variant is the known optimization if MoE serving becomes a
    measured bottleneck."""
    shape = h2.shape
    x = h2.reshape(-1, shape[-1]).astype(jnp.float32)  # [T, d]
    router = lp["moe_router"].astype(jnp.float32)
    gates = jax.nn.softmax(x @ router, axis=-1)  # [T, E]
    expert = jnp.argmax(gates, axis=-1)  # [T]
    gate = jnp.take_along_axis(gates, expert[:, None], axis=1)[:, 0]
    out = jnp.zeros_like(x)
    for e in range(n_experts):
        y = jax.nn.relu(
            x @ lp["moe_w_in"][e].astype(jnp.float32)
        ) @ lp["moe_w_out"][e].astype(jnp.float32)
        out = out + jnp.where((expert == e)[:, None], y, 0.0)
    return (out * gate[:, None]).reshape(shape)


def _ln(x, scale):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-6) * scale


def _rope_tables(positions, head_dim: int, theta: float):
    """cos/sin rotation tables (f32) for ``apply_rope``: angles are
    pos * theta^(-i/half). Computed in f32 regardless of the activation
    dtype — bf16 positions lose integer precision past 256. Hoist these
    out of per-layer code: they depend only on positions and theta, and
    inside a ``jax.checkpoint`` region they would be recomputed in every
    layer's backward pass."""
    half = head_dim // 2
    inv = theta ** (jnp.arange(half, dtype=jnp.float32) / -half)
    ang = jnp.asarray(positions, jnp.float32)[..., None] * inv
    return jnp.cos(ang), jnp.sin(ang)


def _rotate(x: jax.Array, cos, sin) -> jax.Array:
    """Apply precomputed rotation tables in ``x.dtype``."""
    x1, x2 = jnp.split(x, 2, axis=-1)
    c = cos.astype(x.dtype)
    s = sin.astype(x.dtype)
    return jnp.concatenate([x1 * c - x2 * s, x1 * s + x2 * c], -1)


def apply_rope(x: jax.Array, positions, theta: float = 10000.0) -> jax.Array:
    """Rotary position embedding (RoFormer, Su et al. 2021), GPT-NeoX
    half-split pairing: dimension i of the first half pairs with
    dimension i of the second, each pair rotated by pos * theta^(-i/half).

    ``x`` is [..., head_dim] (head_dim even); ``positions`` is an int
    array broadcastable to ``x.shape[:-1]`` (a scalar for single-slot
    decode, ``[1, S, 1]`` for a [B, S, heads, hd] batch)."""
    cos, sin = _rope_tables(positions, x.shape[-1], theta)
    return _rotate(x, cos, sin)


def _rope_position_ids(cfg: LMConfig, s: int, mesh: Mesh, axis: str):
    """Global position ids for the training forward: natural order, or
    the zigzag permutation when the sequence is laid out zigzag (token
    at layout index j sits at global position perm[j])."""
    if cfg.attention == "ring_zigzag":
        from .attention import zigzag_permutation

        return jnp.asarray(
            zigzag_permutation(s, mesh.shape[axis]), jnp.int32
        )
    return jnp.arange(s, dtype=jnp.int32)


def _layer_params(params: Dict[str, jax.Array], i: int) -> Dict[str, jax.Array]:
    """The i-th decoder layer's parameter sub-dict (explicit argument so
    jax.checkpoint sees them as inputs and differentiates through)."""
    pre = f"l{i}/"
    return {k[len(pre):]: v for k, v in params.items() if k.startswith(pre)}


def lm_forward(
    params: Dict[str, jax.Array],
    tokens: jax.Array,  # [B, S] int32, S sharded over `axis`
    cfg: LMConfig,
    mesh: Mesh,
    axis: str = "data",
) -> jax.Array:
    """Logits [B, S, vocab] (always float32; decoder activations run in
    ``cfg.compute_dtype``, rematerialized per layer when ``cfg.remat``)."""
    b, s = tokens.shape
    hd = cfg.d_model // cfg.n_heads
    dtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32

    # RoPE tables, computed ONCE on the GLOBAL sequence view (GSPMD
    # shards them with the tokens; zigzag's position ids are its
    # permutation) and closed over by every layer — under remat they
    # enter jax.checkpoint as inputs, not per-layer recomputation
    rope_cs = (
        _rope_tables(
            _rope_position_ids(cfg, s, mesh, axis)[None, :, None],
            hd, cfg.rope_theta,
        )
        if cfg.rope
        else None
    )

    def layer(x, lp, is_moe):
        cast = lambda k: lp[k].astype(dtype)  # noqa: E731
        h = _ln(x, cast("ln1"))
        q = h @ cast("wq")
        k = h @ cast("wk")
        v = h @ cast("wv")
        if cfg.rope:  # rotate BEFORE the GQA broadcast: k is still narrow
            q = _rotate(
                q.reshape(b, s, cfg.n_heads, hd), *rope_cs
            ).reshape(b, s, cfg.d_model)
            k = _rotate(
                k.reshape(b, s, cfg.kv_heads, hd), *rope_cs
            ).reshape(b, s, cfg.kv_heads * hd)
        if cfg.kv_heads != cfg.n_heads:
            # GQA: broadcast each K/V head over its query-head group up
            # front; every attention schedule below then sees full-width
            # [B, S, d] (training keeps the PARAM saving; the cache
            # saving is the decode path's, which stays grouped)
            def expand(t):
                t = t.reshape(b, s, cfg.kv_heads, 1, hd)
                t = jnp.broadcast_to(
                    t, (b, s, cfg.kv_heads, cfg.n_heads // cfg.kv_heads, hd)
                )
                return t.reshape(b, s, cfg.d_model)

            k = expand(k)
            v = expand(v)

        def heads(t):  # [B, S, d] -> [B*nh, S, hd]
            t = t.reshape(b, s, cfg.n_heads, hd)
            return t.transpose(0, 2, 1, 3).reshape(b * cfg.n_heads, s, hd)

        if cfg.attention == "a2a":
            # Ulysses: q/k/v stay [B, S, d]; the layer splits heads itself
            att = ulysses_attention(
                q, k, v, mesh=mesh, axis=axis, n_heads=cfg.n_heads,
                causal=True,
            )
        else:
            impl = {
                "ring": "xla", "ring_flash": "flash", "ring_zigzag": "zigzag"
            }[cfg.attention]
            att = ring_attention(
                heads(q), heads(k), heads(v), mesh=mesh, axis=axis,
                causal=True, impl=impl, window=cfg.window,
            )
            att = (
                att.reshape(b, cfg.n_heads, s, hd)
                .transpose(0, 2, 1, 3)
                .reshape(b, s, cfg.d_model)
            )
        x = x + att.astype(dtype) @ cast("wo")
        h2 = _ln(x, cast("ln2"))
        if is_moe:
            moe_p = {
                "router": lp["moe_router"],
                "w_in": lp["moe_w_in"],
                "w_out": lp["moe_w_out"],
            }
            # MoE routing (top-1 argmax + capacity bookkeeping) stays in
            # the params' dtype — f32 — for stable expert selection
            x = x + moe_ffn(
                moe_p, h2.astype(jnp.float32), mesh=mesh, axis=axis,
                capacity_factor=cfg.capacity_factor,
            ).astype(dtype)
        else:
            x = x + jax.nn.gelu(h2 @ cast("w1")) @ cast("w2")
        return x

    if cfg.remat:
        layer = jax.checkpoint(layer, static_argnums=(2,))

    x = (params["emb"][tokens] * np.sqrt(cfg.d_model)).astype(dtype)
    for i in range(cfg.n_layers):
        x = layer(x, _layer_params(params, i), _is_moe_layer(cfg, i))
    x32 = x.astype(jnp.float32)
    return _ln(x32, params["ln_f"]) @ params["emb"].T


def _quant_kv_i8(x):
    """Symmetric per-row int8: x [..., hd] -> (int8 rows, f32 scale per
    row). scale = max|x|/127 so the row's peak maps to ±127; an all-zero
    row gets scale 0 and quantizes to zeros (dequant is exact there)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = amax / 127.0
    q = jnp.round(
        x.astype(jnp.float32) / jnp.maximum(scale, 1e-30)[..., None]
    ).astype(jnp.int8)
    return q, scale


def _cache_write(cache, idx, val):
    """Write ``val`` [..., hd] into a cache pytree at slice ``idx``.
    Cache is ``(data, scale)``: scale None = plain dtype cast (the
    existing path); scale array = int8 data + per-row scales. ``idx``
    indexes [layer, :, :, position(s)] on both arrays."""
    data, scale = cache
    if scale is None:
        return (data.at[idx].set(val.astype(data.dtype)), None)
    q, s = _quant_kv_i8(val)
    return (data.at[idx].set(q), scale.at[idx].set(s))


def _cache_layer(cache, i):
    """Layer ``i`` of a cache pytree as f32 [B, kvh, T, hd] — for int8
    the per-row dequant multiply fuses into the consuming einsum (the
    HBM read stays 1 byte/element + scales)."""
    data, scale = cache
    full = data[i].astype(jnp.float32)
    if scale is not None:
        full = full * scale[i][..., None]
    return full


def _alloc_kv_caches(cfg: LMConfig, b: int, total: int):
    """(kcache, vcache) pytrees for ``total`` slots — the ONE home of
    the cache layout/dtype policy (lm_generate and speculative decoding
    both allocate here). Caches live in the compute dtype (bf16 halves
    per-token cache streaming) or, under ``kv_cache_dtype="int8"``, as
    (int8 data, f32 per-row scale); ``cfg.kv_heads`` not n_heads —
    under GQA the cache carries only the K/V heads."""
    hd = cfg.d_model // cfg.n_heads
    shape = (cfg.n_layers, b, cfg.kv_heads, total, hd)
    dtype = (
        jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32
    )
    if cfg.kv_cache_dtype == "int8":
        k = (jnp.zeros(shape, jnp.int8), jnp.zeros(shape[:-1], jnp.float32))
    else:
        k = (jnp.zeros(shape, dtype), None)
    return k, jax.tree.map(jnp.zeros_like, k)


def _cache_write_rows(cache, i, qpos, val):
    """Write ``val`` [B, C, kvh, hd] into layer ``i`` at PER-ROW
    absolute positions ``qpos`` [B, C]. Advanced-index layout: indexing
    data[i] with (rows [B,1], :, qpos [B,C]) puts the broadcast [B, C]
    dims first -> slot shape [B, C, kvh, hd], matching val."""
    data, scale = cache
    rows = jnp.arange(val.shape[0])[:, None]
    if scale is None:
        return (data.at[i, rows, :, qpos].set(val.astype(data.dtype)), None)
    q, s = _quant_kv_i8(val)
    return (
        data.at[i, rows, :, qpos].set(q),
        scale.at[i, rows, :, qpos].set(s),
    )


def _chunk_decode(params, cfg: LMConfig, toks, kcache, vcache, pos):
    """The ONE home of cached decoding: ``toks`` [B, C] live at
    absolute positions ``pos[:, None] + arange(C)`` (per-row ``pos``
    [B]). Writes both caches at those slots — each chunk position
    attends everything cached up to itself, including earlier chunk
    positions — and returns (logits [B, C, vocab], caches). C=1 is the
    lm_generate scan step (see :func:`_decode_step`); C=gamma+1 is
    speculative decoding's target verify pass. Runs in
    ``cfg.compute_dtype`` like the training forward (softmax and
    logits in f32), so decode matches training numerics dtype for
    dtype."""
    b, c = toks.shape
    nh = cfg.n_heads
    kvh = cfg.kv_heads
    g = nh // kvh  # query heads per K/V head (1 = MHA)
    hd = cfg.d_model // nh
    t_max = kcache[0].shape[3]
    dtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32
    x = (params["emb"][toks] * np.sqrt(cfg.d_model)).astype(dtype)  # [B,C,d]
    qpos = pos[:, None] + jnp.arange(c)  # [B, C]
    t_range = jnp.arange(t_max)
    keep = t_range[None, None, :] <= qpos[..., None]  # [B, C, T]
    if cfg.window is not None:  # sliding window, mirroring lm_forward
        keep &= (qpos[..., None] - t_range[None, None, :]) < cfg.window
    rope_cs = (
        _rope_tables(qpos, hd, cfg.rope_theta) if cfg.rope else None
    )
    for i in range(cfg.n_layers):
        cast = lambda k: params[f"l{i}/{k}"].astype(dtype)  # noqa: E731,B023
        h = _ln(x, cast("ln1"))
        q = (h @ cast("wq")).reshape(b, c, kvh, g, hd)
        k = (h @ cast("wk")).reshape(b, c, kvh, hd)
        v = (h @ cast("wv")).reshape(b, c, kvh, hd)
        if cfg.rope:  # rotate at the absolute slot; the cache stores
            # ROTATED k, matching the prefill/training convention
            cos, sin = rope_cs
            q = _rotate(q, cos[:, :, None, None, :], sin[:, :, None, None, :])
            k = _rotate(k, cos[:, :, None, :], sin[:, :, None, :])
        kcache = _cache_write_rows(kcache, i, qpos, k)
        vcache = _cache_write_rows(vcache, i, qpos, v)
        s = jnp.einsum(
            "bckgd,bktd->bckgt",
            q.astype(jnp.float32),
            _cache_layer(kcache, i),
        ) / np.sqrt(hd)
        s = jnp.where(keep[:, :, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        att = (
            jnp.einsum("bckgt,bktd->bckgd", p, _cache_layer(vcache, i))
            .reshape(b, c, cfg.d_model)
            .astype(dtype)
        )
        x = x + att @ cast("wo")
        h2 = _ln(x, cast("ln2"))
        if _is_moe_layer(cfg, i):
            x = x + _moe_ffn_dropless(
                _moe_layer_params(params, i), h2, cfg.n_experts
            ).astype(dtype)
        else:
            x = x + jax.nn.gelu(h2 @ cast("w1")) @ cast("w2")
    x32 = x.astype(jnp.float32)
    return _ln(x32, params["ln_f"]) @ params["emb"].T, kcache, vcache


def _decode_step(params, cfg: LMConfig, tok, kcache, vcache, pos):
    """One KV-cached decoder step (lm_generate's scan body): tok [B],
    SCALAR pos. This is the specialized fast path of
    :func:`_chunk_decode` (C=1, uniform position): the scalar position
    lets cache writes lower to dynamic-update-slice instead of the
    per-row scatter and keeps the mask/rope tables scalar — measured
    ~2x per-token over routing through the generic chunk path. The two
    must stay semantically identical; tests/test_transformer.py pins
    ``_decode_step == _chunk_decode`` output across rope/GQA/window/
    int8 variants so they cannot drift."""
    b = tok.shape[0]
    nh = cfg.n_heads
    kvh = cfg.kv_heads
    g = nh // kvh  # query heads per K/V head (1 = MHA)
    hd = cfg.d_model // nh
    t_max = kcache[0].shape[3]
    dtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32
    x = (params["emb"][tok] * np.sqrt(cfg.d_model)).astype(dtype)  # [B, d]
    t_range = jnp.arange(t_max)
    keep = t_range <= pos
    if cfg.window is not None:  # sliding window, mirroring lm_forward
        keep &= (pos - t_range) < cfg.window
    mask = keep[None, None, None, :]  # [1, 1, 1, T]
    rope_cs = (
        _rope_tables(pos, hd, cfg.rope_theta) if cfg.rope else None
    )
    for i in range(cfg.n_layers):
        cast = lambda k: params[f"l{i}/{k}"].astype(dtype)  # noqa: E731,B023
        h = _ln(x, cast("ln1"))
        q = (h @ cast("wq")).reshape(b, kvh, g, hd)
        k = (h @ cast("wk")).reshape(b, kvh, hd)
        v = (h @ cast("wv")).reshape(b, kvh, hd)
        if cfg.rope:  # rotate at the absolute slot; the cache stores
            # ROTATED k, matching the prefill/training convention
            q = _rotate(q, *rope_cs)
            k = _rotate(k, *rope_cs)
        kcache = _cache_write(kcache, (i, slice(None), slice(None), pos), k)
        vcache = _cache_write(vcache, (i, slice(None), slice(None), pos), v)
        s = jnp.einsum(
            "bkgd,bktd->bkgt", q.astype(jnp.float32), _cache_layer(kcache, i)
        ) / np.sqrt(hd)
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        att = (
            jnp.einsum("bkgt,bktd->bkgd", p, _cache_layer(vcache, i))
            .reshape(b, cfg.d_model)
            .astype(dtype)
        )
        x = x + att @ cast("wo")
        h2 = _ln(x, cast("ln2"))
        if _is_moe_layer(cfg, i):
            x = x + _moe_ffn_dropless(
                _moe_layer_params(params, i), h2, cfg.n_experts
            ).astype(dtype)
        else:
            x = x + jax.nn.gelu(h2 @ cast("w1")) @ cast("w2")
    x32 = x.astype(jnp.float32)
    return _ln(x32, params["ln_f"]) @ params["emb"].T, kcache, vcache


def _chunked_causal_attn(q, k, v, window, chunk: int = 256):
    """Causal attention, q [B, P, nh, hd] x k/v [B, P, kvh, hd] ->
    [B, P, nh*hd], scanned over query blocks: transient memory is ONE
    [B, kvh, g, chunk, P] score block instead of the full [B, nh, P, P]
    tensor (which at batch 8, 8 heads, P=2048 would be >1 GB f32 per
    layer). K/V stay at their NARROW head count (kvh <= nh, GQA) — the
    grouped einsums never materialize the broadcast."""
    b, p_len, nh, hd = q.shape
    kvh = k.shape[2]
    g = nh // kvh  # query heads per K/V head (1 = MHA)
    c = min(chunk, p_len)
    pad = (-p_len) % c
    qp = jnp.pad(q, ((0, 0), (0, pad), (0, 0), (0, 0)))
    nc = (p_len + pad) // c
    k32 = k.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    kpos = jnp.arange(p_len)

    def body(_, inp):
        ci, qblk = inp  # qblk [B, c, nh, hd] -> grouped [B, c, kvh, g, hd]
        qg = qblk.astype(jnp.float32).reshape(b, c, kvh, g, hd)
        qpos = ci * c + jnp.arange(c)
        keep = qpos[:, None] >= kpos[None, :]
        if window is not None:  # sliding window, mirroring _decode_step
            keep &= (qpos[:, None] - kpos[None, :]) < window
        s = jnp.einsum("bqhgd,bthd->bhgqt", qg, k32) / np.sqrt(hd)
        s = jnp.where(keep[None, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        att = jnp.einsum("bhgqt,bthd->bqhgd", p, v32)
        return None, att.reshape(b, c, nh * hd)

    _, out = jax.lax.scan(
        body, None,
        (jnp.arange(nc), jnp.moveaxis(qp.reshape(b, nc, c, nh, hd), 1, 0)),
    )
    out = jnp.moveaxis(out, 0, 1).reshape(b, nc * c, nh * hd)
    return out[:, :p_len]


def _prefill_attention(q, k, v, window, use_flash=None, interpret=None):
    """Prefill attention dispatch: q [B, P, nh, hd], k/v [B, P, kvh, hd]
    -> [B, P, nh*hd]. On TPU backends the Pallas flash kernel does the
    O(P^2) work (MXU-shaped matmuls, O(block) VMEM, window blocks
    skipped); elsewhere the chunked XLA path bounds transient memory.
    ``use_flash=None`` auto-selects by backend; tests force the flash
    path in interpret mode and compare against the chunked path."""
    from ..ops.flash_attention import _use_pallas, flash_mha

    if use_flash is None:
        use_flash = _use_pallas()
    if not use_flash:
        return _chunked_causal_attn(q, k, v, window)
    b, p_len, nh, hd = q.shape
    kvh = k.shape[2]
    # flash_mha owns the head fold + GQA group-broadcast (one home for
    # the kv-major head-order convention, shared with training)
    return flash_mha(
        q.reshape(b, p_len, nh * hd),
        k.reshape(b, p_len, kvh * hd),
        v.reshape(b, p_len, kvh * hd),
        nh, n_kv_heads=kvh, causal=True, window=window,
        use_pallas=True, interpret=interpret,
    )


def _prefill(params, cfg: LMConfig, prompt, kcache, vcache):
    """Batched prompt ingestion: ONE causal forward over [B, P] writes
    cache slots [0, P) for every layer and returns all prompt logits
    [B, P, vocab] — O(1) forward passes instead of P sequential decode
    iterations (for a 2048-token prompt that is the serving-latency
    difference between one batched pass and 2048 scan steps). Numerics
    mirror ``_decode_step`` op for op: compute in ``cfg.compute_dtype``,
    scores/softmax/logits in f32, caches stored in the caller's cache
    dtype (the compute dtype — bf16 under bfloat16); attention runs in
    query chunks so transient memory stays bounded."""
    b, p_len = prompt.shape
    nh = cfg.n_heads
    kvh = cfg.kv_heads
    hd = cfg.d_model // nh
    dtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else jnp.float32
    x = (params["emb"][prompt] * np.sqrt(cfg.d_model)).astype(dtype)
    rope_cs = (
        _rope_tables(
            jnp.arange(p_len)[None, :, None], hd, cfg.rope_theta
        )
        if cfg.rope
        else None
    )
    for i in range(cfg.n_layers):
        cast = lambda k: params[f"l{i}/{k}"].astype(dtype)  # noqa: E731,B023
        h = _ln(x, cast("ln1"))
        q = (h @ cast("wq")).reshape(b, p_len, nh, hd)
        k = (h @ cast("wk")).reshape(b, p_len, kvh, hd)
        v = (h @ cast("wv")).reshape(b, p_len, kvh, hd)
        if cfg.rope:
            q = _rotate(q, *rope_cs)
            k = _rotate(k, *rope_cs)
        idx = (i, slice(None), slice(None), slice(None, p_len))
        kcache = _cache_write(kcache, idx, jnp.swapaxes(k, 1, 2))
        vcache = _cache_write(vcache, idx, jnp.swapaxes(v, 1, 2))
        att = _prefill_attention(q, k, v, cfg.window).astype(dtype)
        x = x + att @ cast("wo")
        h2 = _ln(x, cast("ln2"))
        if _is_moe_layer(cfg, i):
            x = x + _moe_ffn_dropless(
                _moe_layer_params(params, i), h2, cfg.n_experts
            ).astype(dtype)
        else:
            x = x + jax.nn.gelu(h2 @ cast("w1")) @ cast("w2")
    x32 = x.astype(jnp.float32)
    logits = _ln(x32, params["ln_f"]) @ params["emb"].T
    return logits, kcache, vcache


def _pick_token(logits, k_step, temperature, top_p, *, greedy, top_k,
                has_top_p):
    """Greedy argmax or temperature/top-k/top-p sampling of one token
    per row — shared by lm_generate and lm_generate_continue."""
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    z = logits / temperature
    if top_k is not None:
        kth = jnp.sort(z, axis=-1)[:, -top_k][:, None]
        z = jnp.where(z >= kth, z, -jnp.inf)
    if has_top_p:
        # nucleus: keep the smallest sorted prefix with cumulative
        # probability >= top_p. A token stays iff the cumulative mass
        # STRICTLY BEFORE it (descending order) is < top_p — the
        # argmax token always survives (cum-before = 0 < top_p)
        zs = jnp.sort(z, axis=-1)[:, ::-1]  # descending
        ps = jax.nn.softmax(zs, axis=-1)
        before = jnp.cumsum(ps, axis=-1) - ps
        zs_masked = jnp.where(before < top_p, zs, jnp.inf)
        cutoff = jnp.min(zs_masked, axis=-1, keepdims=True)
        z = jnp.where(z >= cutoff, z, -jnp.inf)
    return jax.random.categorical(k_step, z, axis=-1).astype(jnp.int32)


@dataclasses.dataclass(frozen=True)
class GenState:
    """Resumable generation state (multi-turn serving). Opaque to
    callers. ``capacity`` (cache slots) bounds how far
    :func:`lm_generate_continue` can extend.

    Two boundary shapes exist, and the state records which:
    ``boundary_cached=False`` — the last token's cache slot is NOT yet
    written (a generation scan ended; the same invariant speculative
    decoding uses) and the continuation processes it first.
    ``boundary_cached=True`` — every token's slot IS written (prefill-
    only or ingest-only states) and ``last_logits`` carries the next-
    token logits so the continuation never recomputes (and never
    re-writes) an already-cached slot: every path stays EXACTLY equal
    to single-shot generation."""

    kcache: tuple
    vcache: tuple
    last_tok: jax.Array  # [B] int32
    length: int  # tokens emitted so far (prompt + generated)
    boundary_cached: bool = False
    last_logits: "jax.Array | None" = None  # [B, vocab], f32

    @property
    def capacity(self) -> int:
        return self.kcache[0].shape[3]


def _validate_prompt_lengths(prompt_lengths, prompt) -> jax.Array:
    """Shared ragged-batch validation (lm_generate + speculative):
    out-of-range lengths would SILENTLY produce garbage under jit
    (clamped gathers, dropped scatters) — fail here where the values
    are concrete."""
    lens_np = np.asarray(prompt_lengths)
    if lens_np.ndim != 1 or lens_np.shape[0] != prompt.shape[0]:
        raise ValueError(
            f"prompt_lengths must be [B={prompt.shape[0]}], got "
            f"shape {lens_np.shape}"
        )
    if lens_np.min() < 1 or lens_np.max() > prompt.shape[1]:
        raise ValueError(
            "prompt_lengths must lie in [1, padded width="
            f"{prompt.shape[1]}], got range "
            f"[{lens_np.min()}, {lens_np.max()}]"
        )
    return jnp.asarray(lens_np, jnp.int32)


def _sampling_args(cfg, temperature, top_k, top_p, key):
    """Shared wrapper-side validation for the generate family; returns
    (greedy, temperature-array, top_p-array, key)."""
    concrete = isinstance(temperature, (int, float))
    greedy = temperature is None or (concrete and temperature == 0)
    if concrete and temperature < 0:
        raise ValueError(f"temperature must be >= 0, got {temperature}")
    if not greedy and key is None:
        raise ValueError("sampling (temperature > 0) needs a PRNG key")
    if top_k is not None:
        if greedy:
            raise ValueError(
                "top_k requires sampling — pass temperature > 0 (greedy "
                "argmax would silently ignore the truncation)"
            )
        if not 1 <= top_k <= cfg.vocab:
            raise ValueError(
                f"top_k must be in [1, vocab={cfg.vocab}], got {top_k}"
            )
    if top_p is not None:
        if greedy:
            raise ValueError(
                "top_p requires sampling — pass temperature > 0 (greedy "
                "argmax would silently ignore the truncation)"
            )
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if key is None:
        key = jax.random.PRNGKey(0)  # unused by the greedy path
    if greedy:
        temperature = 1.0  # dead operand on the greedy trace
    return (
        greedy,
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(1.0 if top_p is None else top_p, jnp.float32),
        key,
    )


def lm_generate(
    params: Dict[str, jax.Array],
    prompt: jax.Array,  # [B, P] int32
    cfg: LMConfig,
    steps: int,
    *,  # options are keyword-only: inserting new ones can never silently
    # rebind a positional caller's arguments
    return_logits: bool = False,
    return_state: bool = False,
    max_len: "int | None" = None,
    prompt_lengths: "jax.Array | None" = None,
    eos_id: "int | None" = None,
    temperature=None,
    top_k: "int | None" = None,
    top_p: "float | None" = None,
    key: "jax.Array | None" = None,
) -> jax.Array:
    """KV-cached decoding (the serving path — single device; the
    sharded-mesh schedules are the TRAINING story): ingests the prompt
    with ONE batched causal forward that fills the KV caches
    (``_prefill``), then a lax.scan extends it ``steps`` tokens one at a
    time. Sampling consumes one PRNG split for the first generated token
    plus one per scan step (NOT one per prompt position — the per-token
    prompt walk is gone).

    ``eos_id`` freezes a row after it EMITS that token: the rest of
    its fixed-length budget fills with the pad token 0 ("eos then
    pads" — lax.scan cannot end early, so all rows still run
    ``steps`` iterations; frozen rows keep caching their pad tokens,
    which nothing meaningful attends). Works in dense and ragged
    modes.

    ``prompt_lengths`` [B] enables RAGGED batches: ``prompt`` is
    right-padded to a common width and each row decodes from its own
    length — row b's continuation lands at positions
    ``[len_b, len_b + steps)``, and under GREEDY decoding every row's
    output equals what a single-row call on its unpadded prompt would
    produce (pad slots are progressively OVERWRITTEN by generated
    tokens, and the per-row position masks in the chunked decode path
    never attend a slot that still holds pad garbage). Sampled rows
    see the same DISTRIBUTION but not the same draws as a single-row
    call — the per-step Gumbel noise is shaped by the batch. Positions past ``len_b + steps`` in the
    returned array are zeros. Ragged mode returns tokens only
    (``return_logits``/``return_state`` are dense-batch features).
    ``temperature=None`` (or 0) is greedy argmax; otherwise samples from
    softmax(logits/temperature), optionally truncated to the ``top_k``
    most likely tokens and/or the nucleus holding ``top_p`` probability
    mass (smallest prefix of the sorted distribution with cumulative
    probability >= top_p; both filters compose — k-truncate, then
    nucleus). Sampling needs ``key``. A non-zero temperature is a
    TRACED operand of the jitted core — sweeping it does not recompile
    the decode scan. Returns [B, P+steps]. MoE layers are served with DROPLESS
    per-token routing (see :func:`_moe_ffn_dropless` — capacity drops
    are a whole-batch decision incremental decoding cannot reproduce;
    outputs match the training forward exactly whenever its capacity
    did not bind).

    ``return_state=True`` appends a :class:`GenState` to the return —
    resumable by :func:`lm_generate_continue` for multi-turn serving
    without re-prefilling the history; pass ``max_len`` to pre-size
    the caches for the expected conversation length (default: exactly
    prompt+steps, leaving no continuation headroom).

    This wrapper is EAGER on purpose: argument validation (greedy
    detection, sign/range checks) needs concrete Python values, which a
    jitted body never sees — the heavy lifting lives in the jitted core
    below."""
    greedy, temperature, top_p_arr, key = _sampling_args(
        cfg, temperature, top_k, top_p, key
    )
    total = prompt.shape[1] + steps
    capacity = max_len if max_len is not None else total
    if capacity < total:
        raise ValueError(
            f"max_len={max_len} < prompt+steps={total}: the caches "
            "cannot hold the generation being requested"
        )
    if eos_id is not None and not 0 <= eos_id < cfg.vocab:
        raise ValueError(
            f"eos_id must be in [0, vocab={cfg.vocab}), got {eos_id}"
        )
    if eos_id is not None and (return_state or return_logits):
        # a frozen row's GenState is poisoned (pad tokens fill its
        # cache, last_tok is the pad) and its gen_logits tail no longer
        # satisfies "row t predicts token t+1" — reject rather than
        # hand back silently-wrong continuations/parity hooks
        raise ValueError(
            "eos_id does not compose with return_state/return_logits: "
            "frozen rows cache pad tokens, which breaks the multi-turn "
            "and logits-parity contracts"
        )
    # eos rides as a TRACED operand (same contract as temperature/
    # top_p: serving different stop tokens must not recompile); only
    # its PRESENCE is static
    eos_arr = jnp.asarray(
        0 if eos_id is None else eos_id, jnp.int32
    )
    if prompt_lengths is not None:
        if return_logits or return_state:
            raise ValueError(
                "prompt_lengths (ragged batches) does not compose with "
                "return_logits/return_state — pad-split the batch or "
                "use the dense path for those"
            )
        if steps == 0:
            raise ValueError("ragged generation needs steps >= 1")
        return _lm_generate_ragged_jit(
            params, prompt, _validate_prompt_lengths(prompt_lengths, prompt),
            temperature, top_p_arr, key,
            cfg=cfg, steps=steps, top_k=top_k,
            has_top_p=top_p is not None, greedy=greedy, capacity=capacity,
            eos=eos_arr, has_eos=eos_id is not None,
        )
    # top_p rides as a TRACED operand (sweeping it must not recompile,
    # same contract as temperature); only its PRESENCE is static, so the
    # disabled path pays no sort/cumsum
    out = _lm_generate_jit(
        params, prompt, temperature, top_p_arr, key,
        cfg=cfg, steps=steps, return_logits=return_logits, top_k=top_k,
        has_top_p=top_p is not None, greedy=greedy, capacity=capacity,
        return_state=return_state, eos=eos_arr, has_eos=eos_id is not None,
    )
    if not return_state:
        return out
    *rest, last_logits, kcache, vcache = out
    toks = rest[0]
    state = GenState(
        kcache=kcache, vcache=vcache, last_tok=toks[:, total - 1],
        length=total,
        # steps=0: prefill wrote EVERY slot; the prompt's next-token
        # logits ride along so a continuation never re-touches slots
        boundary_cached=steps == 0,
        last_logits=last_logits,
    )
    return (*rest, state) if len(rest) > 1 else (toks, state)


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "steps", "return_logits", "top_k", "has_top_p", "greedy",
        "capacity", "return_state", "has_eos",
    ),
)
def _lm_generate_jit(
    params, prompt, temperature, top_p, key, *, cfg, steps, return_logits,
    top_k, has_top_p, greedy, capacity=None, return_state=False,
    eos=None, has_eos=False,
):
    b, p_len = prompt.shape
    total = p_len + steps
    kcache, vcache = _alloc_kv_caches(
        cfg, b, total if capacity is None else capacity
    )
    toks = jnp.concatenate(
        [prompt.astype(jnp.int32), jnp.zeros((b, steps), jnp.int32)], axis=1
    )

    def pick(logits, k_step):
        return _pick_token(
            logits, k_step, temperature, top_p, greedy=greedy,
            top_k=top_k, has_top_p=has_top_p,
        )

    def ret(*main, last_logits=None):
        return (*main, last_logits, kcache, vcache) if return_state else (
            main if len(main) > 1 else main[0]
        )

    # batched prefill: one causal forward ingests the whole prompt; the
    # sequential scan below covers only the GENERATED tokens
    prefill_logits, kcache, vcache = _prefill(
        params, cfg, prompt.astype(jnp.int32), kcache, vcache
    )
    if steps == 0:
        # contract: total-1 logit rows (row t predicts token t+1); the
        # last prompt position's prediction has no output slot here —
        # it rides into the GenState instead (boundary_cached)
        last = prefill_logits[:, -1]
        if return_logits:
            return ret(toks, prefill_logits[:, :-1], last_logits=last)
        return ret(toks, last_logits=last)
    key, k0 = jax.random.split(key)
    first = pick(prefill_logits[:, -1], k0)
    toks = toks.at[:, p_len].set(first)
    # eos freeze mask: a row that has EMITTED the (traced) eos token
    # keeps emitting the pad token 0 for the rest of the fixed-length
    # scan (lax.scan cannot end early; the contract is "eos then
    # pads"). Only carried when the feature is on (has_eos is static).
    done = first == eos if has_eos else jnp.zeros(b, bool)

    def body(carry, pos):
        toks, kcache, vcache, key, done = carry
        key, k_step = jax.random.split(key)
        tok = jax.lax.dynamic_index_in_dim(toks, pos, axis=1, keepdims=False)
        logits, kcache, vcache = _decode_step(
            params, cfg, tok, kcache, vcache, pos
        )
        nxt = pick(logits, k_step)
        if has_eos:
            nxt = jnp.where(done, 0, nxt)
            done = done | (nxt == eos)
        toks = jax.lax.dynamic_update_index_in_dim(toks, nxt, pos + 1, axis=1)
        return (toks, kcache, vcache, key, done), logits

    # positions p_len .. total-2: each processes an already-written token
    # and writes the next one (the final position total-1 is written by
    # the last iteration and needs no processing)
    (toks, kcache, vcache, _, _), gen_logits = jax.lax.scan(
        body, (toks, kcache, vcache, key, done), jnp.arange(p_len, total - 1)
    )
    if return_logits:
        # [B, T-1, vocab]: row t predicts token t+1 — the decode-vs-full-
        # forward parity hook for tests (prefill rows + generated rows)
        return ret(toks, jnp.concatenate(
            [prefill_logits, jnp.swapaxes(gen_logits, 0, 1)], axis=1
        ))
    return ret(toks)


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg", "steps", "top_k", "has_top_p", "greedy", "capacity",
        "has_eos",
    ),
)
def _lm_generate_ragged_jit(
    params, prompt, lengths, temperature, top_p, key, *, cfg, steps,
    top_k, has_top_p, greedy, capacity, eos=None, has_eos=False,
):
    """Ragged-batch core: right-padded prompt [B, P] + per-row lengths.

    One padded prefill fills cache slots [0, len_b) correctly per row
    (pad rows' garbage K/V lands at [len_b, P) — never attended: the
    chunked decode's ``keep`` mask admits only slots <= the row's
    CURRENT position, and every slot up to there has been overwritten
    by a real generated token by the time it becomes admissible). The
    decode loop runs :func:`_chunk_decode` with C=1 and per-row
    positions ``lengths + t`` — cache writes, rope tables and window
    masks all follow the row's own clock."""
    b, p_len = prompt.shape
    kcache, vcache = _alloc_kv_caches(cfg, b, capacity)
    prompt = prompt.astype(jnp.int32)
    rows = jnp.arange(b)
    # output: prompt with pad slots zeroed (so rows are comparable
    # regardless of what padding value the caller used), widened to
    # hold each row's continuation at [len_b, len_b + steps)
    col = jnp.arange(p_len)
    out = jnp.zeros((b, p_len + steps), jnp.int32)
    out = out.at[:, :p_len].set(
        jnp.where(col[None, :] < lengths[:, None], prompt, 0)
    )

    def pick(logits, k_step):
        return _pick_token(
            logits, k_step, temperature, top_p, greedy=greedy,
            top_k=top_k, has_top_p=has_top_p,
        )

    prefill_logits, kcache, vcache = _prefill(
        params, cfg, prompt, kcache, vcache
    )
    # each row's next-token logits live at ITS last real position
    last = jnp.take_along_axis(
        prefill_logits, (lengths - 1)[:, None, None], axis=1
    )[:, 0]
    key, k0 = jax.random.split(key)
    cur = pick(last, k0)
    out = out.at[rows, lengths].set(cur)
    done = cur == eos if has_eos else jnp.zeros(b, bool)

    def body(carry, t):
        out, kcache, vcache, cur, key, done = carry
        key, k_step = jax.random.split(key)
        pos = lengths + t  # [B]: absolute slot of `cur`, per row
        logits, kcache, vcache = _chunk_decode(
            params, cfg, cur[:, None], kcache, vcache, pos
        )
        nxt = pick(logits[:, 0], k_step)
        if has_eos:
            nxt = jnp.where(done, 0, nxt)
            done = done | (nxt == eos)
        out = out.at[rows, pos + 1].set(nxt)
        return (out, kcache, vcache, nxt, key, done), None

    (out, kcache, vcache, _, _, _), _ = jax.lax.scan(
        body, (out, kcache, vcache, cur, key, done), jnp.arange(steps - 1)
    )
    return out


def lm_beam_search(
    params: Dict[str, jax.Array],
    prompt: jax.Array,  # [B, P] int32
    cfg: LMConfig,
    steps: int,
    *,
    beam_width: int = 4,
    eos_id: "int | None" = None,
    length_penalty: float = 0.0,
    prompt_lengths: "jax.Array | None" = None,
) -> "Tuple[jax.Array, jax.Array]":
    """Beam search over the KV-cached decode path: maintains the
    ``beam_width`` highest-logprob continuations per prompt and returns
    ``(tokens [B, W, P+steps], scores [B, W])`` best-first.

    One prefill on [B, P] fills the caches, which are then tiled W×
    (beam-major rows ``b*W + w``); every step scores all ``W * vocab``
    candidates, keeps the global top W, and REORDERS the caches by each
    survivor's parent beam (the gather is the classic beam cost).
    ``scores`` are exact sums of next-token log-probabilities under the
    model — tests pin them against teacher-forcing the returned
    sequences through the training forward.

    ``eos_id``: a beam that emits it is FINISHED — its score freezes
    and it pads (it competes as a single candidate; an unfinished beam
    can still overtake it). ``length_penalty`` alpha applies the GNMT
    normalization ``score / ((5 + len) / 6)^alpha`` at the FINAL
    ranking only (len = generated tokens incl. eos; without eos all
    beams share one length and the ranking is unaffected).

    ``prompt_lengths`` [B] enables RAGGED batches (same contract as
    lm_generate): right-padded prompts, each prompt's beams expanding
    from its own length — row b's beams carry their continuations at
    ``[len_b, len_b + steps)`` (zeros beyond), and every prompt's beam
    set equals what a single-prompt call on the unpadded prompt
    produces. The ragged path steps through the per-row-position chunk
    decode; dense batches keep the scalar-position fast path.

    Deterministic (no sampling)."""
    if beam_width < 1:
        raise ValueError(f"beam_width must be >= 1, got {beam_width}")
    if steps < 1:
        raise ValueError(f"steps must be >= 1, got {steps}")
    if eos_id is not None and not 0 <= eos_id < cfg.vocab:
        raise ValueError(
            f"eos_id must be in [0, vocab={cfg.vocab}), got {eos_id}"
        )
    if beam_width > cfg.vocab:
        raise ValueError(
            f"beam_width {beam_width} > vocab {cfg.vocab}: the first "
            "expansion cannot fill the beams"
        )
    if prompt_lengths is not None:
        lengths = _validate_prompt_lengths(prompt_lengths, prompt)
    else:
        lengths = jnp.full(prompt.shape[0], prompt.shape[1], jnp.int32)
    toks, scores, gen_len = _beam_jit(
        params, prompt, lengths,
        jnp.asarray(0 if eos_id is None else eos_id, jnp.int32),
        cfg=cfg, steps=steps, beam_width=beam_width,
        has_eos=eos_id is not None, ragged=prompt_lengths is not None,
    )
    # final ranking on the host: length_penalty only scales the [B, W]
    # ranking, so sweeping alpha must never recompile the decode program
    if length_penalty:
        norm = ((5.0 + gen_len.astype(jnp.float32)) / 6.0) ** float(
            length_penalty
        )
        ranked = scores / norm
    else:
        ranked = scores
    order = jnp.argsort(-ranked, axis=1)
    return (
        jnp.take_along_axis(toks, order[:, :, None], axis=1),
        jnp.take_along_axis(scores, order, axis=1),
    )


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "steps", "beam_width", "has_eos", "ragged"),
)
def _beam_jit(params, prompt, lengths, eos, *, cfg, steps, beam_width,
              has_eos, ragged):
    b, p_len = prompt.shape
    w = beam_width
    total = p_len + steps
    prompt = prompt.astype(jnp.int32)
    kc, vc = _alloc_kv_caches(cfg, b, total)
    prefill_logits, kc, vc = _prefill(params, cfg, prompt, kc, vc)
    # each prompt's first-expansion logits live at ITS last real
    # position (== column -1 for dense batches)
    last = (
        jnp.take_along_axis(
            prefill_logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0]
        if ragged else prefill_logits[:, -1]  # static slice, no gather
    )
    logp0 = jax.nn.log_softmax(last.astype(jnp.float32), axis=-1)  # [B, V]
    scores, tok0 = jax.lax.top_k(logp0, w)  # [B, W] each
    # beam-major tiling: row r = b*W + w_idx shares prompt history
    tile = lambda a: jnp.repeat(a, w, axis=1)  # noqa: E731  [L,B,...] -> [L,B*W,...]
    kc, vc = (
        jax.tree.map(lambda x: tile(x) if x is not None else None, c,
                     is_leaf=lambda x: x is None)
        for c in (kc, vc)
    )
    col = jnp.arange(p_len)
    base_prompt = (
        jnp.where(col[None, :] < lengths[:, None], prompt, 0)
        if ragged else prompt
    )
    toks = jnp.broadcast_to(base_prompt[:, None, :], (b, w, p_len))
    toks = jnp.concatenate(
        [toks, jnp.zeros((b, w, steps), jnp.int32)], axis=2
    )
    rows_b = jnp.arange(b)[:, None]
    if ragged:
        toks = toks.at[
            rows_b, jnp.arange(w)[None, :], lengths[:, None]
        ].set(tok0)
    else:
        toks = toks.at[:, :, p_len].set(tok0)
    done = (tok0 == eos) if has_eos else jnp.zeros((b, w), bool)
    gen_len = jnp.ones((b, w), jnp.int32)  # tokens emitted (incl. eos)
    batch_base = (jnp.arange(b) * w)[:, None]  # [B, 1]
    lengths_rows = jnp.repeat(lengths, w)  # [B*W], beam-major

    def body(carry, t):
        toks, kc, vc, scores, done, gen_len, cur = carry
        if ragged:
            # per-row clocks through the chunk path (cache writes,
            # rope, masks all follow each prompt's own position)
            pos_rows = lengths_rows + t
            logits, kc, vc = _chunk_decode(
                params, cfg, cur.reshape(b * w)[:, None], kc, vc,
                pos_rows,
            )
            logits = logits[:, 0]
        else:
            # dense: scalar-position fast path (~2x per token)
            logits, kc, vc = _decode_step(
                params, cfg, cur.reshape(b * w), kc, vc, lengths[0] + t
            )
        logp = jax.nn.log_softmax(
            logits.astype(jnp.float32), axis=-1
        ).reshape(b, w, cfg.vocab)
        if has_eos:
            # a finished beam competes as ONE candidate: pad (token 0)
            # at unchanged score; every other continuation is -inf
            frozen = jnp.full_like(logp, -jnp.inf).at[:, :, 0].set(0.0)
            logp = jnp.where(done[:, :, None], frozen, logp)
        cand = scores[:, :, None] + logp  # [B, W, V]
        scores, idx = jax.lax.top_k(cand.reshape(b, w * cfg.vocab), w)
        parent = idx // cfg.vocab  # [B, W]
        tok = (idx % cfg.vocab).astype(jnp.int32)
        # reorder beam state by parent
        toks = jnp.take_along_axis(toks, parent[:, :, None], axis=1)
        done = jnp.take_along_axis(done, parent, axis=1)
        gen_len = jnp.take_along_axis(gen_len, parent, axis=1)
        flat_parent = (batch_base + parent).reshape(-1)  # [B*W]

        def reorder(x):
            return None if x is None else x[:, flat_parent]

        kc = jax.tree.map(reorder, kc, is_leaf=lambda x: x is None)
        vc = jax.tree.map(reorder, vc, is_leaf=lambda x: x is None)
        if ragged:
            toks = toks.at[
                rows_b, jnp.arange(w)[None, :], (lengths + t + 1)[:, None]
            ].set(tok)
        else:
            # dense: one dynamic-update-slice, not a general scatter
            toks = jax.lax.dynamic_update_slice_in_dim(
                toks, tok[:, :, None], p_len + 1 + t, axis=2
            )
        if has_eos:
            gen_len = gen_len + (~done).astype(jnp.int32)
            done = done | (tok == eos)
        else:
            gen_len = gen_len + 1
        return (toks, kc, vc, scores, done, gen_len, tok), None

    (toks, kc, vc, scores, done, gen_len, _), _ = jax.lax.scan(
        body, (toks, kc, vc, scores, done, gen_len, tok0),
        jnp.arange(steps - 1),
    )
    return toks, scores, gen_len


def lm_generate_continue(
    params: Dict[str, jax.Array],
    state: GenState,
    cfg: LMConfig,
    steps: int,
    *,
    new_tokens: "jax.Array | None" = None,
    temperature=None,
    top_k: "int | None" = None,
    top_p: "float | None" = None,
    key: "jax.Array | None" = None,
) -> "Tuple[jax.Array, GenState]":
    """Extend a :class:`GenState` by ``steps`` tokens — multi-turn
    serving without re-prefilling the history.

    ``new_tokens`` [B, M] (e.g. the next user turn) is ingested first
    in ONE multi-token cache pass (:func:`_chunk_decode` — weights read
    once for the whole turn), then the usual one-token decode scan
    generates. Returns ``(generated [B, steps], new_state)``. The
    state's cache capacity (``lm_generate(..., max_len=)``) must hold
    ``state.length + M + steps`` slots. The same sampling options as
    lm_generate apply. The window/rope/GQA/int8-cache config must be
    the one the state was created with (the caches carry its layout).

    ``steps=0`` with ``new_tokens`` is the ingest-only call ("absorb
    the user's turn now, generate later"): the returned state carries
    ``boundary_cached=True`` plus the turn's next-token logits, so the
    follow-up continuation starts from those logits and never touches
    an already-written cache slot — every path stays exactly equal to
    single-shot generation.

    ``state.length`` rides as a TRACED operand: turns of the same
    (new-turn width, steps) shape reuse one compiled program no matter
    how long the conversation has grown."""
    greedy, temperature, top_p_arr, key = _sampling_args(
        cfg, temperature, top_k, top_p, key
    )
    m = 0 if new_tokens is None else new_tokens.shape[1]
    if steps == 0 and m == 0:
        return (
            jnp.zeros((state.last_tok.shape[0], 0), jnp.int32), state
        )
    need = state.length + m + steps
    if need > state.capacity:
        raise ValueError(
            f"continuation needs {need} cache slots but the state was "
            f"allocated {state.capacity} — create it with "
            f"lm_generate(..., max_len={need}) or more"
        )
    if new_tokens is None:
        new_tokens = jnp.zeros((state.last_tok.shape[0], 0), jnp.int32)
    gen, kcache, vcache, last, last_logits = _lm_continue_jit(
        params, state.kcache, state.vcache, state.last_tok,
        state.last_logits, new_tokens.astype(jnp.int32),
        jnp.int32(state.length), temperature, top_p_arr, key,
        cfg=cfg, steps=steps, top_k=top_k,
        has_top_p=top_p is not None, greedy=greedy,
        boundary_cached=state.boundary_cached,
    )
    return gen, GenState(
        kcache=kcache, vcache=vcache, last_tok=last, length=need,
        boundary_cached=steps == 0, last_logits=last_logits,
    )


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "steps", "top_k", "has_top_p", "greedy",
                     "boundary_cached"),
)
def _lm_continue_jit(
    params, kcache, vcache, last_tok, last_logits, new_tokens, length,
    temperature, top_p, key, *, cfg, steps, top_k, has_top_p, greedy,
    boundary_cached,
):
    b, m = new_tokens.shape

    def pick(logits, k_step):
        return _pick_token(
            logits, k_step, temperature, top_p, greedy=greedy,
            top_k=top_k, has_top_p=has_top_p,
        )

    if boundary_cached:
        # every existing slot is written (prefill-/ingest-only state):
        # ingest ONLY the new turn at positions length..length+m-1; with
        # no new turn the carried last_logits already predict the next
        # token (m=0 AND steps=0 was dispatched in the wrapper)
        if m > 0:
            logits_c, kcache, vcache = _chunk_decode(
                params, cfg, new_tokens, kcache, vcache,
                jnp.full((b,), length, jnp.int32),
            )
            src_logits = logits_c[:, -1]
        else:
            src_logits = last_logits
    else:
        # ingest [last_tok, new turn] as one chunk: writes the boundary
        # token's pending cache slot (length-1) plus the turn's slots;
        # the final row's logits predict the first generated token
        chunk = jnp.concatenate([last_tok[:, None], new_tokens], axis=1)
        logits_c, kcache, vcache = _chunk_decode(
            params, cfg, chunk, kcache, vcache,
            jnp.full((b,), length - 1, jnp.int32),
        )
        src_logits = logits_c[:, -1]
    if steps == 0:  # ingest-only: hand the logits to the next turn
        return (
            jnp.zeros((b, 0), jnp.int32), kcache, vcache,
            new_tokens[:, -1], src_logits,
        )
    key, k0 = jax.random.split(key)
    first = pick(src_logits, k0)
    start = length + m  # absolute position of the first generated token
    gen = jnp.zeros((b, steps), jnp.int32).at[:, 0].set(first)

    def body(carry, i):
        gen, kcache, vcache, key = carry
        key, k_step = jax.random.split(key)
        tok = jax.lax.dynamic_index_in_dim(gen, i, axis=1, keepdims=False)
        logits, kcache, vcache = _decode_step(
            params, cfg, tok, kcache, vcache, start + i
        )
        nxt = pick(logits, k_step)
        gen = jax.lax.dynamic_update_index_in_dim(gen, nxt, i + 1, axis=1)
        return (gen, kcache, vcache, key), None

    if steps > 1:
        (gen, kcache, vcache, _), _ = jax.lax.scan(
            body, (gen, kcache, vcache, key), jnp.arange(steps - 1)
        )
    return gen, kcache, vcache, gen[:, -1], None


def lm_loss(params, tokens, cfg, mesh, axis="data"):
    """Mean next-token cross entropy; the [:, 1:] shift crosses shard
    boundaries — GSPMD emits the halo exchange."""
    if cfg.attention == "ring_zigzag":
        raise ValueError(
            "lm_loss's [:, 1:] shift assumes NATURAL token order; the "
            "zigzag layout breaks that adjacency — use "
            "zigzag_lm_arrays + lm_loss_with_targets instead"
        )
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1
    )
    weights = jnp.ones(tokens.shape, jnp.float32).at[:, -1].set(0.0)
    return lm_loss_with_targets(
        params, tokens, targets, weights, cfg, mesh, axis
    )


def lm_loss_with_targets(params, tokens, targets, weights, cfg, mesh, axis="data"):
    """Weighted next-token cross entropy with EXPLICIT per-position
    targets — the layout-agnostic loss: under a permuted token layout
    (zigzag) "next token" is not position+1 locally, so the caller maps
    labels (see :func:`zigzag_lm_arrays`) instead of the loss shifting."""
    logits = lm_forward(params, tokens, cfg, mesh, axis)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    w = weights.astype(jnp.float32)
    # eps only guards all-zero weights (loss 0); fractional weight sums
    # must divide through unscaled
    return (nll * w).sum() / jnp.maximum(w.sum(), 1e-9)


def zigzag_lm_arrays(tokens: np.ndarray, n: int):
    """Host-side prep for the zigzag LM layout: permute NATURAL-order
    tokens into the zigzag sharding and carry each position's next-token
    target along (the last natural position gets weight 0). Feed the
    results to :func:`lm_loss_with_targets` with
    ``LMConfig(attention="ring_zigzag")``."""
    from .attention import zigzag_permutation

    b, s = tokens.shape
    perm = zigzag_permutation(s, n)
    tgt = np.concatenate(
        [tokens[:, 1:], np.zeros((b, 1), tokens.dtype)], axis=1
    )
    weights = np.ones((b, s), np.float32)
    weights[:, -1] = 0.0
    return tokens[:, perm], tgt[:, perm], weights[:, perm]


def make_lm_train_step(cfg: LMConfig, mesh: Mesh, axis: str = "data",
                       lr: float = 0.3, donate: bool = False,
                       steps_per_launch: int = 1):
    """SGD train step; tokens must be placed sharded P(..., axis).

    ``donate=True`` donates the incoming params (input/output aliasing —
    halves param HBM footprint). Opt-in: a donated call consumes the
    caller's buffers, which breaks patterns like stepping two configs
    from the SAME initial params; enable it in owned training loops that
    always rebind (``params, loss = step(params, toks)``).

    ``steps_per_launch > 1`` fuses that many sequential SGD steps into
    ONE compiled program via ``lax.scan`` (the LM analogue of the linear
    app's ELL supersteps): ``step(params, tokens)`` then takes a stacked
    ``[T, B, S]`` batch, consumes one ``[B, S]`` slice per scan step with
    the params carried through, and returns ``(params, losses[T])`` —
    bit-identical training semantics to T separate calls, minus T-1
    dispatch round trips (dominant on high-latency links). Activations
    live one step at a time, so peak memory matches a single step."""
    if cfg.attention == "ring_zigzag":
        raise ValueError(
            "the zigzag layout needs explicit targets — use "
            "make_lm_train_step_with_targets (+ zigzag_lm_arrays)"
        )
    if steps_per_launch < 1:
        raise ValueError(f"steps_per_launch must be >= 1, got {steps_per_launch}")

    def one(params, tokens):
        loss, grads = jax.value_and_grad(lm_loss)(params, tokens, cfg, mesh, axis)
        new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new, loss

    if steps_per_launch == 1:
        return jax.jit(one, donate_argnums=(0,) if donate else ())

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(params, tokens_stack):
        return jax.lax.scan(one, params, tokens_stack)

    return step


def make_lm_train_step_with_targets(
    cfg: LMConfig, mesh: Mesh, axis: str = "data", lr: float = 0.3,
    donate: bool = False,
):
    """SGD train step on (tokens, targets, weights) — the layout-agnostic
    factory: works for any attention mode, and is the sanctioned one for
    ``ring_zigzag`` (feed it ``zigzag_lm_arrays`` outputs). ``donate``:
    see make_lm_train_step."""

    @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
    def step(params, tokens, targets, weights):
        loss, grads = jax.value_and_grad(lm_loss_with_targets)(
            params, tokens, targets, weights, cfg, mesh, axis
        )
        new = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return new, loss

    return step


def shard_tokens(tokens: np.ndarray, mesh: Mesh, axis: str = "data") -> jax.Array:
    """Place ``[B, S]`` (or a stacked ``[T, B, S]`` superbatch) with the
    sequence dimension sharded over ``axis``."""
    spec = P(*([None] * (tokens.ndim - 1)), axis)
    return jax.device_put(tokens, NamedSharding(mesh, spec))


def shard_lm_params(
    params: Dict[str, jax.Array], mesh: Mesh, axis: str = "server"
) -> Dict[str, jax.Array]:
    """Tensor parallelism by placement (Megatron-style): project-in
    weights (wq/wk/wv, w1) column-sharded over ``axis``, project-out weights
    (wo, w2) row-sharded; GSPMD inserts the partial-sum psums under jit.
    Composes with sequence parallelism on the other mesh axis — on the
    framework's data x server mesh the same 2-D mesh carries sp x tp.
    Embedding/layernorm/MoE tables stay replicated (MoE experts shard
    over the sp axis inside moe_ffn itself)."""

    def place(k, v):
        if k.endswith(("/wq", "/wk", "/wv", "/w1")):
            spec = P(None, axis)
        elif k.endswith("/wo") or k.endswith("/w2"):
            spec = P(axis, None)
        else:
            spec = P()
        return jax.device_put(v, NamedSharding(mesh, spec))

    return {k: place(k, v) for k, v in params.items()}


def _shard_tree_over_axis(tree, mesh: Mesh, axis: str):
    """Split every array leaf over ``axis`` on its largest free
    dimension divisible by the axis size; keep existing ``axis``
    placements; pin scalars and indivisible leaves replicated so the
    whole tree stays mesh-committed. Shared placement engine behind
    :func:`zero1_shard_opt_state` and :func:`fsdp_shard_lm_params`."""
    n = mesh.shape[axis]

    def place(x):
        if (
            not hasattr(x, "shape") or x.ndim == 0 or n == 1
        ):
            # nothing to split: keep an existing mesh placement (a
            # tensor-parallel moment must NOT be gathered back to
            # replicated just because the data axis is trivial), pin
            # anything unplaced replicated so the tree stays committed
            if isinstance(getattr(x, "sharding", None), NamedSharding):
                return x
            return jax.device_put(x, NamedSharding(mesh, P()))
        cur = getattr(x, "sharding", None)
        spec = (
            list(cur.spec) + [None] * (x.ndim - len(cur.spec))
            if isinstance(cur, NamedSharding)
            else [None] * x.ndim
        )
        if axis in spec:  # already data-sharded; keep as is
            return x
        for d in sorted(range(x.ndim), key=lambda d: -x.shape[d]):
            if spec[d] is None and x.shape[d] % n == 0:
                spec[d] = axis
                return jax.device_put(x, NamedSharding(mesh, P(*spec)))
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree.map(place, tree)


def zero1_shard_opt_state(opt_state, mesh: Mesh, axis: str = "data"):
    """ZeRO-1 optimizer-state sharding (Rajbhandari et al. 2020) by
    placement: every state leaf is split over the ``axis`` mesh axis on
    its largest free dimension divisible by the axis size. Params stay
    however the caller placed them (replicated, or Megatron-split via
    :func:`shard_lm_params`) — under jit, GSPMD partitions the
    elementwise moment update to match the state sharding and
    all-gathers only the final parameter delta, so the per-device
    optimizer footprint drops by the data-axis size at the cost of one
    gather of the update. Composes with tensor parallelism: a leaf
    already sharded over the server axis keeps that placement and gains
    the data axis on another dimension. Scalar leaves (adam's step
    count) and leaves with no divisible free dimension are pinned
    replicated, so the whole tree is mesh-committed (the checkpoint
    restore template relies on that)."""
    return _shard_tree_over_axis(opt_state, mesh, axis)


def fsdp_shard_lm_params(
    params: Dict[str, jax.Array], mesh: Mesh, axis: str = "data"
) -> Dict[str, jax.Array]:
    """FSDP / ZeRO-3 parameter sharding (Rajbhandari et al. 2020; the
    reference's analogue is its server-sharded KVLayer partitioning,
    kv_layer.h partition threshold) by placement: every parameter leaf
    is split over ``axis`` on its largest free dimension divisible by
    the axis size. Under jit GSPMD all-gathers each weight just before
    use and reduce-scatters its gradient — per-device parameter AND
    gradient memory divided by the axis size, at the cost of one
    gather per weight per materialization (twice under remat: forward
    and recompute). Semantics are placement-only, but NOT bit-exact
    (unlike ZeRO-1): the gradient reduction becomes a reduce-scatter,
    whose summation order differs from the all-reduce, so trajectories
    track the replicated run to float reduction-order tolerance
    (~1e-4 over a few adam steps — tests/test_fsdp.py).

    Composes with Megatron tensor parallelism (a leaf already sharded
    over the server axis keeps that dim and gains the data axis on
    another) and with :func:`zero1_shard_opt_state` — optax moments
    initialized from FSDP params inherit the sharding, which together
    is the full ZeRO-3 stack: params, grads, and optimizer state all
    sharded over the data axis."""
    return _shard_tree_over_axis(params, mesh, axis)
